"""Device-mesh sharding of the simulation state (multi-chip scale-out).

The reference scales by running ONE process over all N simulated nodes
(OMNeT++ kernel, single-threaded; SURVEY.md §2.5).  The TPU rebuild's
scale axis is the node-slot dimension: every [N, ...] state array (and the
[P, ...] message pool, P = pool_factor*N) is sharded over a 1-D
`jax.sharding.Mesh` along its leading axis, and the whole tick step runs
under `jit` with GSPMD partitioning — XLA inserts the collectives:

  * the global key-table gathers (`ctx.keys[slot]`) become all-gathers of
    the [N, KL] key table (small: 20 B/node) over ICI;
  * the pool's scatter-min inbox selection (engine/pool.py, default
    ``inbox_impl="scatter"``) partitions into a LOCAL per-shard
    select + an all-reduce-min of the [N] per-destination minima —
    O(N) reduction traffic per round instead of the legacy sort path's
    all-to-all merge exchange (XLA's partitioned `lax.sort` moves the
    whole [P] pool's keys across chips; still taken under
    ``inbox_impl="sort"``);
  * per-node vmapped logic stays fully local to each shard (the dominant
    FLOPs — finger scans, key arithmetic — never cross chips);
  * scalar stats/counters are replicated and all-reduced.

Multi-host (DCN) fits the same program: initialize jax.distributed and
build the mesh over all processes' devices — jit/GSPMD handles the rest.
No NCCL/MPI translation (reference has none anyway): ICI/DCN collectives
are the communication backend.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
REPLICA_AXIS = "replicas"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices).reshape(-1), (NODE_AXIS,))


def make_replica_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the REPLICA axis (oversim_tpu/campaign/).

    Campaign state leaves are [S, ...]; sharding the leading replica
    axis is pure data parallelism — replicas never exchange data inside
    the tick, so the partitioned step compiles with ZERO cross-replica
    collectives (pinned by scripts/hlo_breakdown.py --campaign and
    tests/test_campaign.py): 4 chips run 4× replicas at solo speed.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices).reshape(-1), (REPLICA_AXIS,))


def make_mesh_2d(replica_devices: int = 1, node_devices: int | None = None,
                 devices=None) -> Mesh:
    """2-D ``(REPLICA_AXIS, NODE_AXIS)`` mesh: one program runs
    ``replica_devices`` replica groups, each over ``node_devices``
    node shards.  ``node_devices=None`` takes every remaining device.
    ``make_mesh_2d(1, k)`` is the solo node-sharded layout; composed
    with the campaign's stacked [S, ...] axis it is S replicas ×
    K-way-sharded nodes in one compiled tick."""
    if devices is None:
        devices = jax.devices()
    if node_devices is None:
        node_devices = len(devices) // replica_devices
    need = replica_devices * node_devices
    if need < 1 or need > len(devices):
        raise ValueError(
            f"mesh {replica_devices}x{node_devices} needs {need} devices, "
            f"have {len(devices)}")
    return Mesh(np.array(devices[:need]).reshape(replica_devices,
                                                 node_devices),
                (REPLICA_AXIS, NODE_AXIS))


def _shape(leaf):
    return tuple(getattr(leaf, "shape", None) or np.shape(leaf))


def _node_spec(leaf, lead: int):
    """P sharding dim ``lead`` on NODE_AXIS (replica dims prepended by
    the campaign builders)."""
    nd = len(_shape(leaf))
    return P(*([None] * lead), NODE_AXIS, *([None] * (nd - lead - 1)))


def state_pspecs_2d(state):
    """PartitionSpec pytree for a solo SimState on a (replica, node)
    mesh: pool leaves ([P]/[P, W]) and logic leaves with leading dim N
    shard along NODE_AXIS; EVERYTHING else is replicated.

    The replication ledger (why not "every [N, ...] leaf"):

      * ``alive``/``node_keys``/``malicious`` [N] — cross-indexed by
        every handler through the full-width Ctx (``ctx.keys[slot]``);
        at 20 B/node replicating is cheaper than an all-gather per use;
      * churn/underlay/stats/counters/telemetry + scalars — the churn
        step, ``logic.reset`` and ``send_batch`` draw FULL-WIDTH rng
        planes; running them replicated is what keeps the sharded tick
        bit-identical to the solo oracle (parallel/shard_tick.py);
      * the dominant bytes — the [P, W] pool block (O(N·pool_factor·W))
        and the per-node logic rows (O(N·F)) — do shard.
    """
    n = _shape(state.alive)[0]

    def logic_spec(leaf):
        shp = _shape(leaf)
        return _node_spec(leaf, 0) if shp and shp[0] == n else P()

    import dataclasses
    sp = jax.tree.map(lambda _: P(), state)
    return dataclasses.replace(
        sp,
        pool=jax.tree.map(lambda l: _node_spec(l, 0), state.pool),
        logic=jax.tree.map(logic_spec, state.logic))


def state_shardings_2d(state, mesh: Mesh):
    """NamedSharding pytree for a solo SimState on a 2-D mesh (node
    leaves sharded on NODE_AXIS, replicated across REPLICA_AXIS)."""
    k = int(mesh.shape[NODE_AXIS])
    n = _shape(state.alive)[0]
    p = _shape(state.pool.valid)[0]
    if n % k or p % k:
        raise ValueError(
            f"n={n} / pool={p} not divisible by node shards k={k}")
    return jax.tree.map(lambda _, sp: NamedSharding(mesh, sp), state,
                        state_pspecs_2d(state))


def shard_state_2d(state, mesh: Mesh):
    """Place a solo SimState onto a 2-D (replica, node) mesh."""
    return jax.device_put(state, state_shardings_2d(state, mesh))


def campaign_state_pspecs_2d(cs):
    """PartitionSpec pytree for a stacked [S, ...] campaign state on the
    2-D mesh: every leaf shards its leading replica axis; pool and
    logic-node leaves additionally shard dim 1 along NODE_AXIS (same
    replication ledger as :func:`state_pspecs_2d`, shifted one dim)."""
    n = _shape(cs.alive)[1]

    import dataclasses
    sp = jax.tree.map(lambda l: P(REPLICA_AXIS), cs)

    def logic_spec(leaf):
        shp = _shape(leaf)
        return (P(REPLICA_AXIS, NODE_AXIS)
                if len(shp) >= 2 and shp[1] == n else P(REPLICA_AXIS))

    sp = dataclasses.replace(
        sp,
        pool=jax.tree.map(lambda l: P(REPLICA_AXIS, NODE_AXIS), cs.pool),
        logic=jax.tree.map(logic_spec, cs.logic))
    return sp


def campaign_state_shardings_2d(cs, mesh: Mesh):
    """NamedSharding pytree for a stacked campaign state on a 2-D
    (replica, node) mesh."""
    r = int(mesh.shape[REPLICA_AXIS])
    k = int(mesh.shape[NODE_AXIS])
    s = _shape(cs.alive)[0]
    n = _shape(cs.alive)[1]
    p = _shape(cs.pool.valid)[1]
    if s % r:
        raise ValueError(f"S={s} replicas not divisible by replica "
                         f"mesh extent r={r}")
    if n % k or p % k:
        raise ValueError(
            f"n={n} / pool={p} not divisible by node shards k={k}")
    return jax.tree.map(lambda _, sp: NamedSharding(mesh, sp), cs,
                        campaign_state_pspecs_2d(cs))


def shard_campaign_state_2d(cs, mesh: Mesh):
    """Place a stacked campaign state onto a 2-D (replica, node) mesh."""
    return jax.device_put(cs, campaign_state_shardings_2d(cs, mesh))


def jit_sharded_step(sim, mesh: Mesh, donate: bool = True):
    """jit the genuinely node-sharded one-tick step (shard_map plane,
    parallel/shard_tick.py) with matching in/out shardings."""
    from oversim_tpu.parallel.shard_tick import ShardedSim
    ssim = ShardedSim(sim, mesh)
    return jax.jit(ssim.step, in_shardings=(ssim.shardings,),
                   out_shardings=ssim.shardings,
                   donate_argnums=(0,) if donate else ())


def jit_sharded_run(sim, mesh: Mesh, n_ticks: int, donate: bool = True):
    """jit a ``lax.scan`` of n_ticks node-sharded steps."""
    from oversim_tpu.parallel.shard_tick import ShardedSim
    ssim = ShardedSim(sim, mesh)

    def run(s):
        def body(carry, _):
            return ssim.step(carry), None
        s, _ = jax.lax.scan(body, s, None, length=n_ticks)
        return s

    return jax.jit(run, in_shardings=(ssim.shardings,),
                   out_shardings=ssim.shardings,
                   donate_argnums=(0,) if donate else ())


def jit_sharded_campaign_step(camp, mesh: Mesh, donate: bool = True):
    """jit the S-replica × K-node-shard campaign step on the 2-D mesh
    (zero cross-replica collectives: every pmin names NODE_AXIS only,
    so replica groups span node subgroups — pinned by the shard gate)."""
    from oversim_tpu.parallel.shard_tick import ShardedCampaign
    scamp = ShardedCampaign(camp, mesh)
    return jax.jit(scamp.vstep, in_shardings=(scamp.shardings,),
                   out_shardings=scamp.shardings,
                   donate_argnums=(0,) if donate else ())


def state_shardings(state, mesh: Mesh):
    """NamedSharding pytree for a SimState: leading axis of every array
    whose first dim divides evenly over the mesh is sharded; scalars and
    ragged leaves are replicated.  Telemetry ring buffers (leading axis
    = the sample window W, not a node dimension) are always replicated —
    a W that happens to divide the device count must not turn the gated
    ring scatter into a cross-shard update."""
    n_dev = mesh.devices.size
    replicated = NamedSharding(mesh, P())

    def spec(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] % n_dev == 0 and leaf.shape[0] > 0:
            return NamedSharding(mesh, P(NODE_AXIS, *([None] * (leaf.ndim - 1))))
        return replicated

    sh = jax.tree.map(spec, state)
    if getattr(state, "telemetry", None) is not None:
        import dataclasses
        sh = dataclasses.replace(
            sh, telemetry=jax.tree.map(lambda _: replicated, state.telemetry))
    return sh


def shard_state(state, mesh: Mesh):
    """Place a SimState onto the mesh with node-axis sharding."""
    return jax.device_put(state, state_shardings(state, mesh))


def campaign_state_shardings(cs, mesh: Mesh):
    """NamedSharding pytree for a stacked [S, ...] campaign state:
    shard the leading REPLICA axis of every leaf whose first dim divides
    evenly over the mesh; replicate the rest (per-replica scalars like
    t_now are [S] and shard too — they are one element per replica)."""
    n_dev = mesh.devices.size

    def spec(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] % n_dev == 0 and leaf.shape[0] > 0:
            return NamedSharding(
                mesh, P(REPLICA_AXIS, *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, cs)


def shard_campaign_state(cs, mesh: Mesh):
    """Place a stacked campaign state onto the mesh, replica-sharded."""
    return jax.device_put(cs, campaign_state_shardings(cs, mesh))


def jit_campaign_run_until(camp, mesh: Mesh, chunk: int = 64,
                           donate: bool = True):
    """jit a replica-sharded ``(cs, target_ns) -> cs`` campaign runner.

    The campaign analogue of ``jit_run_until``: a donated
    ``lax.while_loop`` of ``chunk``-tick vmapped scans with cond
    ``any(t_now < target_ns)`` (all replicas run until the slowest
    passes).  The only cross-device op the cond needs is a reduce over
    the [S] t_now vector — outside the tick body; the tick itself has
    zero cross-replica collectives.
    """
    example = camp.init()
    shardings = campaign_state_shardings(example, mesh)

    def run(cs, target_ns):
        def cond(carry):
            return jnp.any(carry.t_now < target_ns)

        def body(carry):
            def sbody(c, _):
                return camp._vstep(c), None
            c, _ = jax.lax.scan(sbody, carry, None, length=chunk)
            return c

        return jax.lax.while_loop(cond, body, cs)

    return jax.jit(run,
                   in_shardings=(shardings, NamedSharding(mesh, P())),
                   out_shardings=shardings,
                   donate_argnums=(0,) if donate else ())


def jit_step(sim, mesh: Mesh, donate: bool = True):
    """jit the one-tick step with sharded in/out state.

    Returns a compiled callable state -> state.  The sharding constraint is
    placed on the argument/result; everything inside is GSPMD-partitioned.
    """
    example = sim.init()
    shardings = state_shardings(example, mesh)
    return jax.jit(sim.step, in_shardings=(shardings,),
                   out_shardings=shardings,
                   donate_argnums=(0,) if donate else ())


def jit_run(sim, mesh: Mesh, n_ticks: int, donate: bool = True):
    """jit a ``lax.scan`` of n_ticks sharded steps (one dispatch for the
    whole run — the multi-chip equivalent of Simulation.run_chunk)."""
    example = sim.init()
    shardings = state_shardings(example, mesh)

    def run(s):
        def body(carry, _):
            return sim.step(carry), None
        s, _ = jax.lax.scan(body, s, None, length=n_ticks)
        return s

    return jax.jit(run, in_shardings=(shardings,), out_shardings=shardings,
                   donate_argnums=(0,) if donate else ())


def jit_run_until(sim, mesh: Mesh, chunk: int = 64, donate: bool = True):
    """jit a device-resident ``(state, target_ns) -> state`` runner.

    The multi-chip equivalent of ``Simulation.run_until_device``: a
    ``lax.while_loop`` re-runs ``chunk``-tick scans until
    ``t_now >= target_ns``, so the whole run to a simulation-time target
    is ONE dispatch — no per-chunk host round-trip (the per-chunk sync
    in the host loop costs a full ICI/DCN drain at scale).  ``target_ns``
    is an i64 scalar in engine ns (``t_sim * sim_mod.NS``), replicated.
    """
    example = sim.init()
    shardings = state_shardings(example, mesh)

    def run(s, target_ns):
        def cond(carry):
            return carry.t_now < target_ns

        def body(carry):
            def sbody(c, _):
                return sim.step(c), None
            c, _ = jax.lax.scan(sbody, carry, None, length=chunk)
            return c

        return jax.lax.while_loop(cond, body, s)

    return jax.jit(run,
                   in_shardings=(shardings, NamedSharding(mesh, P())),
                   out_shardings=shardings,
                   donate_argnums=(0,) if donate else ())
