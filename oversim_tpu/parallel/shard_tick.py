"""The genuinely node-sharded tick: shard_map over the (replica, node) mesh.

`parallel/mesh.py` shards state PLACEMENT and lets GSPMD partition the
solo tick; this module is the explicit plane: the tick body runs under
``shard_map`` with every cross-shard exchange written out by hand as a
minimum-reduction, so the collective census of the compiled step is
``all-reduce:min`` and NOTHING else — no all-to-all, no all-gather of
pool payloads, zero cross-replica collectives (contract entries
``sharded_tick`` / ``sharded_campaign_tick`` in analysis/contracts.py).

The one collective primitive — the min-gather
--------------------------------------------
Every exchange here is "each shard owns a disjoint slice; everyone needs
the union".  That is an all-gather, but an all-gather is expressible as
an all-reduce with the MIN combiner over a buffer where each shard
writes its slice and leaves the identity (dtype max) elsewhere:

    min(x, MAX, MAX, ...) == x   for every bit pattern
    (and when x == MAX the result is MAX — still bit-identical).

Bools ride as i32, floats as bitcast unsigned ints (ordering among real
values is irrelevant — only owner-vs-identity matters), ints as
themselves.  This is EXACT, not approximate, so the sharded tick is
bit-identical to the solo oracle while lowering to a single collective
kind.  Per-destination inbox minima and scalar horizon minima are
additionally TRUE mins, where `lax.pmin` is the natural op anyway.

What runs sharded vs replicated (the bit-identity split)
--------------------------------------------------------
Sharded (the dominant bytes and FLOPs):
  * the [P]/[P, W] message pool — inbox scatter-min select, payload
    gather, free/alloc writes all touch only the local tile;
  * the per-node logic rows ([N, F] leaves) — the vmapped `_node_step`
    runs over the local N/K rows only, with rng streams folded on the
    TRUE global node index (bit-identical to the dense sweep).

Replicated (full-width rng draws and cross-indexed small vectors):
  * churn step, `logic.reset`, `underlay.send_batch`, stats/telemetry
    fold — each draws full-width [N]/[N, M] rng planes; re-running them
    identically on every shard is what keeps the trace bit-identical
    to the solo tick (sharding the draw would change the stream);
  * `alive`/`node_keys`/`malicious` [N] — cross-indexed by every
    handler through the full-width Ctx (`ctx.keys[slot]`).

The sparse active-set plane (tick_impl="sparse") compacts across the
whole node axis and is NOT supported here — `ShardedSim` refuses it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu import telemetry as telemetry_mod
from oversim_tpu.engine import pool as pool_mod
from oversim_tpu.engine import sim as sim_mod
from oversim_tpu.parallel import mesh as mesh_mod

try:  # jax >= 0.6: public API, replication checked via varying-manual-axes
    from jax import shard_map as _shard_map_impl
    _SMAP_KW = {"check_vma": False}
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SMAP_KW = {"check_rep": False}

I32 = jnp.int32
I64 = jnp.int64
T_INF = pool_mod.T_INF


def _smap(f, mesh, in_specs, out_specs):
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **_SMAP_KW)


def _carrier(x):
    """(integer carrier, restore fn) for the min-gather: a dtype whose
    ``iinfo.max`` is a min-identity for every payload bit pattern."""
    dt = x.dtype
    if dt == jnp.bool_:
        return x.astype(I32), lambda y: y != 0
    if jnp.issubdtype(dt, jnp.floating):
        u = jnp.dtype(f"uint{dt.itemsize * 8}")
        return (jax.lax.bitcast_convert_type(x, u),
                lambda y: jax.lax.bitcast_convert_type(y, dt))
    return x, lambda y: y


class ShardedSim:
    """One Simulation's tick, hand-sharded K ways along the node axis.

    ``mesh`` must carry ``mesh_mod.NODE_AXIS``; a REPLICA_AXIS may be
    present (and is simply not named by any collective — replica groups
    span node subgroups only, so cross-replica traffic is structurally
    zero).  ``step`` is the global entry; `_local_step` is the
    shard_map body (also vmapped by :class:`ShardedCampaign`).
    """

    def __init__(self, sim, mesh):
        if mesh_mod.NODE_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh {mesh.axis_names} has no "
                             f"{mesh_mod.NODE_AXIS!r} axis")
        if sim.ep.tick_impl != "dense":
            raise ValueError(
                "sharded tick requires tick_impl='dense': the sparse "
                "active-set plane compacts across the whole node axis")
        if sim.ep.inbox_impl not in ("scatter", "pallas"):
            raise ValueError(
                f"sharded tick supports inbox_impl 'scatter' or 'pallas', "
                f"got {sim.ep.inbox_impl!r} (the sort path is a full-pool "
                "lexicographic sort — all-to-all under sharding)")
        self.sim = sim
        self.mesh = mesh
        self.axis = mesh_mod.NODE_AXIS
        self.k = int(mesh.shape[self.axis])
        n = sim.n
        p = sim.ep.pool_factor * n
        if n % self.k or p % self.k:
            raise ValueError(f"n={n} / pool={p} not divisible by node "
                             f"shards k={self.k}")
        self.nl = n // self.k
        self.pl = p // self.k
        example = jax.eval_shape(sim.init_from_rng, jax.random.PRNGKey(0))
        self.pspecs = mesh_mod.state_pspecs_2d(example)
        self.shardings = jax.tree.map(
            lambda _, sp: jax.sharding.NamedSharding(mesh, sp),
            example, self.pspecs)
        logic_leaves, self._logic_def = jax.tree.flatten(example.logic)
        self._logic_node = [len(l.shape) >= 1 and l.shape[0] == n
                            for l in logic_leaves]

    # -- collective primitives (everything lowers to all-reduce:min) -----

    def _gmin(self, x, ax):
        """Min-gather: per-shard contiguous tiles [T, ...] -> the full
        [K*T, ...] array on every shard, via ONE all-reduce:min."""
        car, back = _carrier(x)
        buf = jnp.full((self.k,) + car.shape, jnp.iinfo(car.dtype).max,
                       car.dtype).at[ax].set(car)
        g = jax.lax.pmin(buf, self.axis)
        return back(g.reshape((self.k * x.shape[0],) + x.shape[1:]))

    def _pervec(self, v, ax):
        """[K] vector of one per-shard scalar (int sums ride this: local
        partial -> [K] min-gather -> local sum, exact for ints)."""
        buf = jnp.full((self.k,), jnp.iinfo(v.dtype).max,
                       v.dtype).at[ax].set(v)
        return jax.lax.pmin(buf, self.axis)

    def _owned(self, vals_l, idx, base_p):
        """Gather rows of a pool-sharded array by GLOBAL index: the
        owning shard contributes the row, everyone else the identity."""
        loc = idx - base_p
        mine = (loc >= 0) & (loc < self.pl)
        rows = vals_l[jnp.clip(loc, 0, self.pl - 1)]
        car, back = _carrier(rows)
        m = mine.reshape(mine.shape + (1,) * (car.ndim - mine.ndim))
        contrib = jnp.where(m, car, jnp.iinfo(car.dtype).max)
        return back(jax.lax.pmin(contrib, self.axis))

    def _gather_logic(self, logic_l, ax):
        """Local logic rows -> the full-width logic state (node leaves
        min-gathered; glob leaves are replicated and pass through)."""
        leaves = self._logic_def.flatten_up_to(logic_l)
        out = [self._gmin(x, ax) if is_node else x
               for x, is_node in zip(leaves, self._logic_node)]
        return jax.tree.unflatten(self._logic_def, out)

    def _slice_logic(self, logic_full, rows_l):
        leaves = self._logic_def.flatten_up_to(logic_full)
        out = [rows_l(x) if is_node else x
               for x, is_node in zip(leaves, self._logic_node)]
        return jax.tree.unflatten(self._logic_def, out)

    # -- the sharded tick body (runs under shard_map) --------------------

    def _local_step(self, s):
        sim = self.sim
        n, k, nl, pl = sim.n, self.k, self.nl, self.pl
        p = pl * k
        ax = jax.lax.axis_index(self.axis).astype(I32)
        base_n = ax * nl
        base_p = ax * pl

        def rows_l(x):  # full-width -> my contiguous node-tile rows
            return jax.lax.dynamic_slice_in_dim(x, base_n, nl, axis=0)

        def csum(v):  # global int sum: [K] min-gather of partials
            return jnp.sum(self._pervec(v, ax))

        # ---- phase 1: horizon.  The pool term is the only cross-shard
        # min; logic/churn next-events run replicated on the gathered
        # full logic state (also needed by the replicated reset below).
        logic_full = self._gather_logic(s.logic, ax)
        pool_next = jax.lax.pmin(
            jnp.min(jnp.where(s.pool.valid, s.pool.t_deliver, T_INF)),
            self.axis)
        window_ns = jnp.int64(int(sim.ep.window * sim_mod.NS))
        t_next = jnp.minimum(
            pool_next,
            jnp.minimum(
                jnp.min(jnp.where(s.alive, sim.logic.next_event(logic_full),
                                  T_INF)),
                sim_mod.churn_mod.next_event(s.churn)))
        t_next = jnp.maximum(t_next, s.t_now)
        t_end = jnp.where(t_next >= T_INF, t_next, t_next + window_ns)
        rngs = jax.random.split(s.rng, 7)
        (rng, r_churn, r_keys, r_reset, r_nodes, r_mig, r_send) = rngs

        # ---- phase 2: churn — REPLICATED (full-width rng draws; see
        # module docstring), reusing the solo phase verbatim on a state
        # view whose logic is the gathered full-width state.
        (churn_state, alive, pre_killed, node_keys, ul_state,
         logic_res) = sim._phase_churn(
            dataclasses.replace(s, logic=logic_full), t_next, t_end,
            r_churn, r_keys, r_reset, r_mig)

        # ---- phase 3: inbox — local select over the pool tile + the
        # cross-shard all-reduce:min merge (engine/pool.py scatter form
        # or the shard-aware fused kernel, kernels/inbox.py).
        hold = sim._hold_mask(s)  # local: pool columns only
        if sim.ep.inbox_impl == "pallas":
            from oversim_tpu import kernels
            inbox, delivered, to_dead = kernels.inbox.fused_select_sharded(
                s.pool, n, sim.ep.inbox_slots, t_end, alive, hold=hold,
                axis_name=self.axis, base=base_p, p_total=p)
        else:
            inbox, delivered, to_dead = pool_mod.build_inbox_scatter(
                s.pool, n, sim.ep.inbox_slots, t_end, alive, hold,
                axis_name=self.axis, base=base_p, p_total=p)

        # payload gather: owner-contributed rows of the packed block +
        # the two i64 fields (empty slots read global row 0 — owned by
        # shard 0, matching the solo safe-index gather).
        safe = jnp.maximum(inbox, 0)
        gblk = self._owned(s.pool.blk, safe, base_p)
        g_tdel = self._owned(s.pool.t_deliver, safe, base_p)
        g_stamp = self._owned(s.pool.stamp, safe, base_p)
        msgs = sim._msgs_from_block(s, t_next, inbox, gblk,
                                    t_deliver=g_tdel, stamp=g_stamp)
        msgs_l = jax.tree.map(rows_l, msgs)

        # ---- phase 4: node step over MY rows only (rng folded on the
        # TRUE global node index -> bit-identical streams), then
        # min-gather the per-node outputs back to full width for the
        # replicated merge/post_step/send path.
        ctx, node_part_full, glob, measuring = sim._make_ctx(
            s, t_next, t_end, alive, pre_killed, churn_state, node_keys,
            ul_state, logic_res)
        part_l = jax.tree.map(rows_l, node_part_full)
        idx64 = base_n.astype(I64) + jnp.arange(nl, dtype=I64)
        node_rngs = sim._node_rngs(r_nodes, s.tick, idx64)
        node_idx = base_n + jnp.arange(nl, dtype=I32)
        part_l, out_f_l, out_v_l, out_o_l, ev_l = jax.vmap(
            sim._node_step, in_axes=(None, 0, 0, 0, 0))(
                ctx, part_l, msgs_l, node_rngs, node_idx)
        gm = lambda t: jax.tree.map(lambda x: self._gmin(x, ax), t)  # noqa: E731
        node_part = gm(part_l)
        out_fields = gm(out_f_l)
        out_valid = self._gmin(out_v_l, ax)
        out_overflow = self._gmin(out_o_l, ax)
        events = gm(ev_l)
        logic_state = (sim.logic.merge(node_part, glob)
                       if hasattr(sim.logic, "merge") else node_part)
        if hasattr(sim.logic, "post_step"):
            logic_state = sim.logic.post_step(ctx, logic_state, events)

        # ---- phase 5: free + underlay send (replicated) + SHARDED
        # sort-free alloc: the free-slot ranking becomes a [K] per-shard
        # free-count vector (exclusive prefix -> global ranks) and the
        # compacted fslot table one contribution-scatter + pmin; each
        # shard then writes only destinations inside its tile.
        new_pool = pool_mod.free(s.pool, delivered | to_dead)
        node_idx_full = jnp.arange(n, dtype=I32)
        t_del, ok, ul_state, drops = sim.ul.send_batch(
            ul_state, sim.up, r_send,
            jnp.broadcast_to(node_idx_full[:, None], out_fields["dst"].shape),
            out_fields["dst"], out_fields["size_b"], out_fields["t_send"],
            out_valid, alive, kind=out_fields["kind"])
        flat = {k2: v.reshape((-1,) + v.shape[2:])
                for k2, v in out_fields.items() if k2 != "t_send"}
        flat["t_deliver"] = t_del.reshape(-1)
        flat["src"] = jnp.broadcast_to(node_idx_full[:, None],
                                       out_valid.shape).reshape(-1)
        want = (out_valid & ok).reshape(-1)

        free_l = ~new_pool.valid
        free_vec = self._pervec(jnp.sum(free_l.astype(I32)), ax)
        n_free = jnp.sum(free_vec)
        rank0 = (jnp.cumsum(free_vec) - free_vec)[ax]
        free_i = free_l.astype(I32)
        grank = jnp.cumsum(free_i) - free_i + rank0
        fslot = jax.lax.pmin(
            jnp.full((p,), p, I32).at[jnp.where(free_l, grank, p)].set(
                base_p + jnp.arange(pl, dtype=I32), mode="drop"),
            self.axis)
        n_want = jnp.sum(want.astype(I32))
        want_i = want.astype(I32)
        want_rank = jnp.cumsum(want_i) - want_i
        dest = jnp.where(want & (want_rank < n_free),
                         fslot[jnp.minimum(want_rank, p - 1)], p)
        pool_overflow = jnp.maximum(n_want - n_free, 0)
        dl = dest - base_p
        dloc = jnp.where((dl >= 0) & (dl < pl), dl, pl)  # pl drops
        out_blk = pool_mod.pack_block(flat, s.pool.kl, s.pool.rmax)
        new_pool = dataclasses.replace(
            new_pool,
            blk=new_pool.blk.at[dloc].set(out_blk, mode="drop"),
            t_deliver=new_pool.t_deliver.at[dloc].set(
                jnp.asarray(flat["t_deliver"], I64), mode="drop"),
            stamp=new_pool.stamp.at[dloc].set(
                jnp.asarray(flat["stamp"], I64), mode="drop"),
            valid=new_pool.valid.at[dloc].set(True, mode="drop"))

        # stats + counters (global sums of pool-local masks ride [K]
        # count-vector min-gathers — integer-exact, census-clean)
        new_stats = stats_mod.record(s.stats, events, measuring)
        counters = dict(s.counters)
        counters["queue_lost"] += drops["queue_lost"]
        counters["bit_error_lost"] += drops["bit_error_lost"]
        counters["partition_lost"] += drops["partition_lost"]
        counters["dest_unavailable_lost"] += (
            drops["dest_unavailable_lost"] + csum(jnp.sum(to_dead)))
        counters["pool_overflow"] += pool_overflow
        counters["outbox_overflow"] += jnp.sum(out_overflow)
        counters["inbox_deferred"] = jnp.maximum(
            counters["inbox_deferred"],
            (csum(jnp.sum(s.pool.valid & (s.pool.t_deliver < t_end))) -
             csum(jnp.sum(delivered | to_dead))).astype(I64))
        tel = telemetry_mod.fold(
            s.telemetry, sim.ep.telemetry, t_end=t_end, tick=s.tick + 1,
            alive=alive, stats=new_stats, counters=counters)

        return sim_mod.SimState(
            t_now=t_end, tick=s.tick + 1, rng=rng, alive=alive,
            node_keys=node_keys, underlay=ul_state, pool=new_pool,
            churn=churn_state, malicious=s.malicious,
            logic=self._slice_logic(logic_state, rows_l),
            stats=new_stats, counters=counters, telemetry=tel)

    # -- global entries ---------------------------------------------------

    def step(self, s):
        """One node-sharded tick on the full (replicated+sharded) state."""
        return _smap(self._local_step, self.mesh,
                     (self.pspecs,), self.pspecs)(s)

    def place(self, s):
        """Put a solo SimState onto this mesh with the 2-D layout."""
        return jax.device_put(s, self.shardings)


class ShardedCampaign:
    """S stacked replicas × K node shards on one 2-D mesh: shard_map
    over BOTH axes, vmapping the sharded tick body over each device's
    local replica rows.  No collective names REPLICA_AXIS, so the
    cross-replica traffic is structurally zero — same pin as the 1-D
    replica mesh, now composed with node sharding."""

    def __init__(self, camp, mesh):
        if camp.sweep_stack:
            raise NotImplementedError(
                "sharded campaign tick supports pure seed replicas only "
                "(sweep overrides change the per-replica trace; run grid "
                "sweeps on the 1-D replica mesh)")
        if mesh_mod.REPLICA_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh {mesh.axis_names} has no "
                             f"{mesh_mod.REPLICA_AXIS!r} axis")
        self.camp = camp
        self.mesh = mesh
        self.ssim = ShardedSim(camp.sim, mesh)
        self.r = int(mesh.shape[mesh_mod.REPLICA_AXIS])
        if camp.s % self.r:
            raise ValueError(f"S={camp.s} replicas not divisible by "
                             f"replica mesh extent r={self.r}")
        example = jax.eval_shape(
            lambda ids: jax.vmap(camp.sim.init_from_rng)(
                jax.vmap(camp.replica_rng)(ids)),
            jnp.asarray(camp.ids))
        self.pspecs = mesh_mod.campaign_state_pspecs_2d(example)
        self.shardings = jax.tree.map(
            lambda _, sp: jax.sharding.NamedSharding(mesh, sp),
            example, self.pspecs)

    def vstep(self, cs):
        """One tick of every replica, node-sharded K ways."""
        f = jax.vmap(self.ssim._local_step)
        return _smap(f, self.mesh, (self.pspecs,), self.pspecs)(cs)

    def place(self, cs):
        """Put a stacked campaign state onto the 2-D mesh."""
        return jax.device_put(cs, self.shardings)
