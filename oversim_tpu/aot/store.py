"""Versioned on-disk store for ``jax.export`` entry-point artifacts.

One artifact per registry entry: a StableHLO blob (``<entry>.bin``)
plus a JSON meta sidecar (``<entry>.json``) carrying the FULL key it
was exported under.  The key is

    (entry name, config sha256 of the EntryContext
     [telemetry.config_hash], jax version, device signature
     [hostcache.device_signature], host CPU hash, format version)

Refusal semantics mirror checkpoint v2: a load whose stored key differs
from the caller's key in ANY field is REFUSED with a reason naming the
differing fields — the caller recompiles fresh and ``save`` overwrites
the stale artifact.  Corrupt meta or a missing blob refuse the same
way.  Nothing in this module ever raises on a bad artifact: stale or
torn state degrades to a recompile, never a crash or a silent stale
execution.

Writes are atomic (tmp + ``os.replace``, meta last) so a kill mid-save
leaves either the previous consistent pair or a blob whose meta still
describes the previous blob — which the size check then refuses.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path

# bump when the artifact layout or the export wrapper convention
# changes — old artifacts are then refused and rewritten
FORMAT_VERSION = 1

# the meta fields compared on load, in refusal-message order
KEY_FIELDS = ("entry", "config_hash", "jax_version", "device_signature",
              "host", "format")


def artifact_key(entry_name: str, config) -> dict:
    """The full versioned key for one entry under the CURRENT runtime.
    ``config`` is any JSON-serializable mapping (the warm-up plane
    passes the EntryContext fields)."""
    import jax

    from oversim_tpu import hostcache
    from oversim_tpu.telemetry import config_hash
    host = hashlib.sha1(
        hostcache.host_signature().encode()).hexdigest()[:10]
    return {
        "entry": entry_name,
        "config_hash": config_hash(config),
        "jax_version": str(jax.__version__),
        "device_signature": hostcache.device_signature(),
        "host": host,
        "format": FORMAT_VERSION,
    }


def default_root() -> str:
    """$OVERSIM_AOT_DIR, else a host-keyed sibling of the XLA persistent
    cache (same machine-feature keying, same rationale)."""
    env = os.environ.get("OVERSIM_AOT_DIR")
    if env:
        return env
    from oversim_tpu import hostcache
    return hostcache.cache_dir() + "_aot"


class ArtifactStore:
    """Load/save exported entry artifacts under one root directory."""

    def __init__(self, root=None):
        self.root = Path(root if root is not None else default_root())
        self.root.mkdir(parents=True, exist_ok=True)

    def blob_path(self, entry_name: str) -> Path:
        return self.root / f"{entry_name}.bin"

    def meta_path(self, entry_name: str) -> Path:
        return self.root / f"{entry_name}.json"

    def load(self, entry_name: str, key: dict):
        """``(blob, None)`` on a clean hit; ``(None, None)`` on a plain
        miss (nothing stored); ``(None, reason)`` on a REFUSAL (stale
        key / corrupt meta / torn blob).  Never raises."""
        meta_p = self.meta_path(entry_name)
        if not meta_p.exists():
            return None, None
        try:
            meta = json.loads(meta_p.read_text())
        except (OSError, ValueError) as e:
            return None, f"corrupt meta sidecar ({e})"
        stored = meta.get("key", {})
        diffs = [f for f in KEY_FIELDS if stored.get(f) != key.get(f)]
        if diffs:
            detail = ", ".join(
                f"{f}: stored={stored.get(f)!r} != current={key.get(f)!r}"
                for f in diffs)
            return None, f"stale key ({detail})"
        blob_p = self.blob_path(entry_name)
        try:
            blob = blob_p.read_bytes()
        except OSError as e:
            return None, f"blob unreadable ({e})"
        if len(blob) != meta.get("size"):
            return None, (f"blob size {len(blob)} != recorded "
                          f"{meta.get('size')} (torn write)")
        return blob, None

    def save(self, entry_name: str, key: dict, blob: bytes) -> str:
        """Atomic overwrite: blob first, meta (the commit point) last."""
        blob_p = self.blob_path(entry_name)
        tmp = str(blob_p) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, blob_p)
        meta_p = self.meta_path(entry_name)
        tmp = str(meta_p) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"key": dict(key), "size": len(blob)}, f, indent=1)
        os.replace(tmp, meta_p)
        return str(blob_p)

    def entries(self) -> list:
        return sorted(p.stem for p in self.root.glob("*.json"))
