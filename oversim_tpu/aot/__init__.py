"""AOT compile plane: exported entry-point artifacts + process pre-warm.

Every on-chip measurement round since r03 has been eaten by compile +
warm-up rather than run time (PERFORMANCE.md; the reference OverSim's
C++ event loop starts instantly).  This package attacks that tax
structurally, over the SAME entry-point registry the graph-contract
analyzer walks (oversim_tpu/analysis/contracts.py):

* :mod:`oversim_tpu.aot.store` — versioned on-disk ``jax.export``
  artifacts keyed by (entry, EntryContext config hash, jax version,
  device signature, host, format), with loud refusal-on-mismatch and
  recompile+rewrite fallback — never a crash, never silent stale
  execution.
* :mod:`oversim_tpu.aot.warmup` — ``aot.warmup()``: the one pre-warm
  call at the top of bench.py and the runner scripts; deserializes or
  exports each entry, reports per-entry compile-vs-load seconds for
  ``run_manifest`` and Perfetto.

CI enforcement (compile-seconds budgets per entry) lives in the
analysis plane: ``scripts/analyze.py --compile-budget`` +
``GraphContract.max_compile_seconds``.  See README "AOT compile plane".
"""

from oversim_tpu.aot.store import (  # noqa: F401
    FORMAT_VERSION,
    ArtifactStore,
    artifact_key,
    default_root,
)
from oversim_tpu.aot.warmup import (  # noqa: F401
    call_exported,
    enabled_by_env,
    entry_config,
    export_entry,
    load_entry,
    trace_spans,
    warmup,
)
