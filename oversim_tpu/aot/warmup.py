"""Process pre-warm over the analysis registry's compiled entry points.

:func:`warmup` walks the registry entries a run will need and, per
entry, either DESERIALIZES the stored ``jax.export`` artifact
(StableHLO bytes — skips trace+lower, the dominant cold-start cost on
these graphs) or BUILDS + exports it fresh and writes the artifact for
the next process.  The returned report carries per-entry
``compile_seconds`` vs ``load_seconds`` plus hit/refusal counters; the
runner scripts attach it to every ``run_manifest``
(``extra={"aot": report}``) and lay it out as Perfetto spans
(:func:`trace_spans`).

Export wrapper
--------------
The sim-state pytrees (``register_dataclass`` types) carry no
``jax.export`` serialization registrations, so entries are exported as
a FLATTENED-LEAF wrapper: the jitted wrapper takes only the
``jax.Array`` leaves of the entry's example args, closes over the
static leaves (Simulation instances, python ints), reassembles via
``tree_unflatten``, and returns ``tree_leaves`` of the result.  Calling
an exported entry therefore needs only fresh dynamic leaves in the same
flatten order (:func:`call_exported`).

Sharded entries (campaign_tick / resharded_resume) export with their
mesh extent baked in (``Exported.nr_devices > 1``); deserialization is
device-independent but a ``.call`` requires a matching device context —
:func:`call_exported` refuses (returns None) rather than crash when the
visible device count differs.

Warm-up never throws: any per-entry failure (export bug, refused
artifact that then fails to rebuild) is recorded in the report and the
run proceeds cold for that entry.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

from oversim_tpu.aot.store import ArtifactStore, artifact_key


def _log(msg: str) -> None:
    sys.stderr.write(f"aot: {msg}\n")


def enabled_by_env(environ=None) -> bool:
    """$OVERSIM_AOT truthy → warm-up active.  Default OFF: tests and
    fleet-smoke subprocesses must not pay export cost implicitly."""
    environ = os.environ if environ is None else environ
    return str(environ.get("OVERSIM_AOT", "")).lower() in (
        "1", "true", "on", "yes")


def entry_config(name: str, ctx) -> dict:
    """The JSON config hashed into the artifact key: entry name + every
    EntryContext field — any build-shape change rolls the key."""
    return {"entry": name, **dataclasses.asdict(ctx)}


def _dyn_leaves(args):
    """(flat leaves, treedef, dynamic indices) of an args tuple — the
    dynamic leaves are exactly the jax.Arrays; everything else (sim
    objects, python scalars) is closed over statically."""
    import jax
    flat, tree = jax.tree_util.tree_flatten(args)
    idx = [i for i, x in enumerate(flat) if isinstance(x, jax.Array)]
    return flat, tree, idx


def export_entry(built):
    """Export one EntryBuild as a flattened-leaf jax.export artifact."""
    import jax
    from jax import export as jexport

    flat, tree, idx = _dyn_leaves(built.make_args())

    def flat_fn(*leaves):
        full = list(flat)
        for i, v in zip(idx, leaves):
            full[i] = v
        out = built.fn(*jax.tree_util.tree_unflatten(tree, full))
        return jax.tree_util.tree_leaves(out)

    return jexport.export(jax.jit(flat_fn))(*[flat[i] for i in idx])


def deserialize(blob: bytes):
    from jax import export as jexport
    return jexport.deserialize(blob)


def load_entry(name: str, *, ctx, store=None):
    """Deserialize one stored artifact, or None (miss/refusal/corrupt).
    The cheap path the smoke and serving loops use after :func:`warmup`
    has populated the store."""
    store = store if store is not None else ArtifactStore()
    key = artifact_key(name, entry_config(name, ctx))
    blob, refusal = store.load(name, key)
    if blob is None:
        if refusal:
            _log(f"load_entry({name}): refused — {refusal}")
        return None
    try:
        return deserialize(blob)
    except Exception as e:  # noqa: BLE001 — a bad blob must not kill a run
        _log(f"load_entry({name}): deserialize failed — {e}")
        return None


def call_exported(exp, built):
    """Run an exported entry on FRESH dynamic leaves from its
    EntryBuild's args factory.  Returns the flat output leaves, or None
    when the export's device extent doesn't match the current context
    (multi-device exports demand an equal device count)."""
    import jax
    flat, _, idx = _dyn_leaves(built.make_args())
    if exp.nr_devices > 1 and exp.nr_devices != len(jax.devices()):
        _log(f"call refused: exported for {exp.nr_devices} devices, "
             f"{len(jax.devices())} visible")
        return None
    return exp.call(*[flat[i] for i in idx])


def warmup(names=None, *, ctx=None, store=None, enabled=None,
           environ=None) -> dict:
    """Pre-warm the named registry entries (default: all of them).

    Per entry: try the artifact store (load = deserialize StableHLO,
    recorded as ``load_seconds`` with ``compile_seconds`` 0.0); on a
    miss or a LOUD refusal, build + export + serialize fresh
    (``compile_seconds`` = build + trace/lower/export wall) and rewrite
    the artifact.  Returns the report dict for
    ``run_manifest(extra={"aot": report})``; with warm-up disabled
    (``enabled=False`` / $OVERSIM_AOT unset) returns immediately with
    ``{"enabled": False}`` so callers can attach it unconditionally.
    """
    from oversim_tpu.analysis import contracts as contracts_mod

    if enabled is None:
        enabled = enabled_by_env(environ)
    report = {"kind": "aot_warmup", "enabled": bool(enabled),
              "entries": {}, "fresh_compiles": 0, "artifact_hits": 0,
              "refusals": 0, "errors": 0}
    if not enabled:
        return report
    if ctx is None:
        ctx = contracts_mod.EntryContext.make(fast=True)
    store = store if store is not None else ArtifactStore()
    report["store"] = str(store.root)
    names = list(names) if names is not None else list(contracts_mod.REGISTRY)
    t_warm0 = time.perf_counter()
    for name in names:
        rec = {"started_s": round(time.perf_counter() - t_warm0, 3)}
        report["entries"][name] = rec
        try:
            key = artifact_key(name, entry_config(name, ctx))
            blob, refusal = store.load(name, key)
            if blob is not None:
                t0 = time.perf_counter()
                try:
                    deserialize(blob)
                    rec.update(source="artifact",
                               load_seconds=round(
                                   time.perf_counter() - t0, 3),
                               compile_seconds=0.0,
                               blob_bytes=len(blob))
                    report["artifact_hits"] += 1
                    _log(f"{name}: artifact hit "
                         f"({rec['load_seconds']}s load)")
                    continue
                except Exception as e:  # noqa: BLE001 — degrade to fresh
                    refusal = f"deserialize failed ({e})"
                    blob = None
            if refusal:
                report["refusals"] += 1
                rec["refused"] = refusal
                _log(f"{name}: REFUSING stored artifact — {refusal}; "
                     f"recompiling fresh and rewriting")
            t0 = time.perf_counter()
            built = contracts_mod.REGISTRY[name].build(ctx)
            exp = export_entry(built)
            new_blob = exp.serialize()
            rec.update(source="fresh",
                       compile_seconds=round(time.perf_counter() - t0, 3),
                       load_seconds=0.0, blob_bytes=len(new_blob),
                       nr_devices=int(exp.nr_devices))
            store.save(name, key, new_blob)
            report["fresh_compiles"] += 1
            _log(f"{name}: fresh export ({rec['compile_seconds']}s) "
                 f"-> {store.blob_path(name)}")
        except Exception as e:  # noqa: BLE001 — warm-up must never kill a run
            rec.update(source="error", error=f"{type(e).__name__}: {e}")
            report["errors"] += 1
            _log(f"{name}: warm-up FAILED ({e}) — run proceeds cold")
    report["wall_seconds"] = round(time.perf_counter() - t_warm0, 3)
    return report


def trace_spans(trace, report: dict, *, t0_s: float = 0.0,
                tid: int = 3) -> None:
    """Lay a warm-up report out as Perfetto spans (one per entry, named
    ``aot.load:`` / ``aot.export:`` by source) on a telemetry
    PerfettoTrace."""
    for name, rec in (report.get("entries") or {}).items():
        src = rec.get("source")
        dur = rec.get("load_seconds" if src == "artifact"
                      else "compile_seconds", 0.0) or 0.0
        trace.span(f"aot.{'load' if src == 'artifact' else 'export'}:{name}",
                   t0_s + rec.get("started_s", 0.0), dur, tid=tid,
                   args={k: v for k, v in rec.items()
                         if isinstance(v, (int, float, str, bool))})
