"""Reshard-on-resume: a checkpoint restores at a DIFFERENT topology.

Checkpoint v2 (oversim_tpu/checkpoint.py) resumes bit-identically on
the same topology only: ``load`` demands an example with the exact
checkpointed shapes, and placement is whatever the caller re-applies.
This module makes both axes free variables at restore time:

  * **replica axis** — :func:`reshard_stacked` grows/shrinks the
    leading [S] axis of campaign-stacked state by slicing/padding.
    Surviving rows are the checkpointed arrays UNCHANGED (bit-identical
    across any 1×1 → 8-way → 1×1 round trip, pinned by
    tests/test_zz_elastic.py); grown rows come from the target
    campaign's own ``init()`` — and since ``Campaign.init`` derives row
    r from ``fold_in(PRNGKey(base_seed), ids[r])``, a grown slot is
    re-seeded deterministically, exactly the replica the full campaign
    would have started with.
  * **node/device placement** — :func:`place_campaign` /
    :func:`place_solo` re-establish ``NamedSharding`` over whatever
    mesh `parallel/mesh.py` can build from the devices available NOW:
    the largest device count that divides the leading axis (1 chip, 8
    chips, anything between).  Placement is layout-only; values are
    untouched.

:func:`reshard_load` is the end-to-end path: raw checkpoint leaves →
campaign-identity refusals (base seed / sweep grid / replica-id prefix,
recorded by ``Campaign.describe()`` in the checkpoint meta) →
per-replica structure fingerprint refusal (a shape-mismatched reshard
fails LOUDLY, never silently corrupts) → grown/shrunk stacked state.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from oversim_tpu import checkpoint as ckpt_mod
from oversim_tpu.parallel import mesh as mesh_mod


def _leading_extent(leaves, what: str) -> int:
    """The common leading-axis extent of stacked leaves."""
    extents = set()
    for x in leaves:
        shape = tuple(np.shape(x))
        if not shape:
            raise ValueError(
                f"reshard fingerprint mismatch: {what} has a scalar "
                "leaf — not campaign-stacked state (every stacked leaf "
                "carries a leading replica axis)")
        extents.add(shape[0])
    if len(extents) != 1:
        raise ValueError(
            f"reshard fingerprint mismatch: {what} leaves disagree on "
            f"the leading replica extent ({sorted(extents)})")
    return extents.pop()


def replica_fingerprint(state_or_leaves) -> str:
    """sha1 over the PER-REPLICA structure (trailing dims + dtype of
    every leaf, flatten order) — the replica-count-independent analogue
    of checkpoint._fingerprint.  Two stacked states of the same scenario
    at different S share it; different scenarios (or solo state mistaken
    for stacked) do not."""
    leaves = jax.tree.leaves(state_or_leaves)
    sig = ";".join(
        f"{tuple(np.shape(x))[1:]}:{np.asarray(x).dtype}" for x in leaves)
    return hashlib.sha1(sig.encode()).hexdigest()


def reshard_stacked(old, fresh):
    """Graft checkpointed stacked state ``old`` ([S_old, ...] leaves)
    onto the replica extent of ``fresh`` ([S_new, ...], typically
    ``camp.init()`` of the target campaign).

    Row policy: rows ``0..min(S_old,S_new)-1`` are ``old``'s arrays
    unchanged (surviving replicas stay bit-identical); rows past S_old
    are taken from ``fresh`` (deterministically re-seeded grown slots).
    Pure function of its inputs — no checkpoint, no devices — so the
    grow/shrink identity is unit-testable on synthetic pytrees.

    Raises ``ValueError`` mentioning the fingerprints when the
    per-replica structures differ (shape-mismatched reshard requests
    fail loudly instead of silently corrupting)."""
    old_leaves, old_def = jax.tree.flatten(old)
    new_leaves, new_def = jax.tree.flatten(fresh)
    if old_def != new_def:
        raise ValueError(
            "reshard fingerprint mismatch: checkpoint and target "
            f"campaign disagree on pytree structure ({len(old_leaves)} "
            f"vs {len(new_leaves)} leaves)")
    fp_old = replica_fingerprint(old)
    fp_new = replica_fingerprint(fresh)
    if fp_old != fp_new:
        raise ValueError(
            "reshard fingerprint mismatch (different Simulation "
            f"configuration per replica): checkpoint {fp_old[:12]} vs "
            f"target {fp_new[:12]}")
    s_old = _leading_extent(old_leaves, "checkpoint")
    s_new = _leading_extent(new_leaves, "target")
    keep = min(s_old, s_new)
    out = []
    for o, n in zip(old_leaves, new_leaves):
        n = jnp.asarray(n)
        o = jnp.asarray(o, dtype=n.dtype)
        if s_new <= s_old:
            out.append(o[:s_new])
        else:
            out.append(jnp.concatenate([o[:keep], n[keep:]], axis=0))
    from oversim_tpu.engine.sim import _dedupe_buffers
    # run_chunk donates the result downstream; slicing two deduped-alias
    # source leaves could re-alias the outputs
    return _dedupe_buffers(jax.tree.unflatten(new_def, out))


def _check_campaign_meta(meta: dict, camp) -> None:
    """Refuse to graft a checkpoint onto the WRONG campaign: same array
    layout does not mean same ensemble.  Compares the checkpointed
    ``Campaign.describe()`` record (when present — plain checkpoints
    skip this) against the target's: base seed and sweep grid must be
    equal, and the replica-id sequences must agree on their common
    prefix so surviving row k is the same replica on both sides."""
    rec = meta.get("campaign")
    if not rec:
        return
    want = camp.describe()
    if (rec.get("base_seed") is not None
            and rec["base_seed"] != want["base_seed"]):
        raise ValueError(
            f"reshard campaign mismatch: checkpoint has "
            f"base_seed={rec['base_seed']} but target campaign has "
            f"{want['base_seed']} — grown slots would be mis-seeded")
    if rec.get("sweep") is not None:
        have = [[n, list(v)] for n, v in rec["sweep"]]
        if have != want["sweep"]:
            raise ValueError(
                "reshard campaign mismatch: checkpoint sweep grid "
                f"{have} differs from target {want['sweep']}")
    # with a sweep grid, global id i maps to grid point i // replicas —
    # changing `replicas` renumbers every replica's parameter point, so
    # only pure seed sweeps may grow/shrink along the replicas axis
    if (rec.get("replicas") is not None and len(camp.grid) > 1
            and rec["replicas"] != want["replicas"]):
        raise ValueError(
            f"reshard campaign mismatch: checkpoint has "
            f"replicas={rec['replicas']} per grid point but target has "
            f"{want['replicas']} — the id→grid-point mapping would "
            "shift under the sweep")
    old_ids = rec.get("replica_ids")
    if old_ids is not None:
        k = min(len(old_ids), len(want["replica_ids"]))
        if list(old_ids[:k]) != list(want["replica_ids"][:k]):
            raise ValueError(
                "reshard campaign mismatch: replica-id prefix differs "
                f"(checkpoint {list(old_ids[:k])} vs target "
                f"{want['replica_ids'][:k]}) — row k would change "
                "identity across the reshape")


def reshard_load(path: str, camp, *, expect_config: str | None = None,
                 fresh=None):
    """Restore checkpoint ``path`` into campaign ``camp`` at WHATEVER
    replica extent ``camp`` has — grow, shrink, or same-size.

    ``fresh`` — pre-built ``camp.init()`` (built on demand when omitted;
    pass it when the caller already initialized, to avoid a second
    compile).  ``expect_config`` refuses foreign scenarios exactly like
    ``checkpoint.load``.  Returns ``(state, meta)`` — ``meta`` is the
    checkpoint manifest, so callers recover service/fleet bookkeeping
    without a second read."""
    raw, meta = ckpt_mod.load_raw(path)
    if expect_config is not None:
        got = meta.get("config_hash")
        if got is not None and got != expect_config:
            raise ValueError(
                "checkpoint scenario mismatch: checkpoint was written "
                f"by config {got} but this run is config "
                f"{expect_config} ({path})")
    _check_campaign_meta(meta, camp)
    if fresh is None:
        fresh = camp.init()
    new_leaves, new_def = jax.tree.flatten(fresh)
    if len(raw) != len(new_leaves):
        raise ValueError(
            "reshard fingerprint mismatch: checkpoint holds "
            f"{len(raw)} leaves but the target campaign state has "
            f"{len(new_leaves)}")
    old = jax.tree.unflatten(new_def, raw)
    return reshard_stacked(old, fresh), meta


def _best_divisor(extent: int, n_devices: int) -> int:
    """Largest device count ≤ n_devices dividing ``extent`` — the widest
    mesh the leading axis shards onto evenly."""
    for d in range(min(extent, n_devices), 0, -1):
        if extent % d == 0:
            return d
    return 1


def _check_node_shards(n: int, p: int, node_shards: int, avail: int):
    """Refuse LOUDLY when a requested 2-D restore cannot hold: the node
    extents must divide evenly and the devices must exist — silently
    degrading a requested K-way mesh to 1-way would hide a capacity
    regression from the fleet controller."""
    if node_shards < 1:
        raise ValueError(f"node_shards={node_shards} must be >= 1")
    if n % node_shards or p % node_shards:
        raise ValueError(
            f"reshard placement mismatch: n={n} / pool={p} not "
            f"divisible by the requested node_shards={node_shards}")
    if node_shards > avail:
        raise ValueError(
            f"reshard placement mismatch: node_shards={node_shards} "
            f"exceeds the {avail} available devices")


def place_campaign(cs, n_devices: int | None = None,
                   node_shards: int | None = None):
    """Re-establish replica-axis placement over the mesh available NOW.

    Builds a REPLICA_AXIS mesh over the largest available device count
    that divides the stacked extent (all of them when S % n_dev == 0,
    degenerating to 1 — fully replicated placement — for prime
    mismatches) and ``device_put``s the state onto it.  Layout only:
    values are bit-identical before and after.  Returns
    ``(state, mesh)`` so the caller can jit with matching shardings.

    ``node_shards`` — restore onto the 2-D ``(replica, node)`` mesh
    instead, K-way node-sharded (parallel/mesh.py 2-D layout, the
    shard_tick plane's placement).  Requested explicitly, it REFUSES
    rather than degrades: N (and the pool) must divide evenly by K and
    replica_extent × K devices must exist."""
    leaves = jax.tree.leaves(cs)
    s = _leading_extent(leaves, "state")
    avail = len(jax.devices()) if n_devices is None else n_devices
    if node_shards is not None:
        # np.shape yields static python ints — no device sync
        n = np.shape(cs.alive)[1]
        p = np.shape(cs.pool.valid)[1]
        _check_node_shards(n, p, node_shards, avail)
        r = _best_divisor(s, avail // node_shards)
        mesh = mesh_mod.make_mesh_2d(r, node_shards)
        return mesh_mod.shard_campaign_state_2d(cs, mesh), mesh
    mesh = mesh_mod.make_replica_mesh(_best_divisor(s, avail))
    return mesh_mod.shard_campaign_state(cs, mesh), mesh


def place_solo(state, n_devices: int | None = None,
               node_shards: int | None = None):  # analysis: allow(device-sync)
    """Node-axis analogue of :func:`place_campaign` for solo SimState:
    NODE_AXIS mesh over the largest device count dividing N, state
    placed with ``parallel/mesh.py`` ``state_shardings`` (telemetry
    rings replicated as usual).  Returns ``(state, mesh)``.  The int()
    here reads a static SHAPE, not a device value — no sync.

    ``node_shards`` — restore onto the 2-D ``(1, K)`` mesh with the
    shard_tick plane's explicit layout (pool + logic node leaves
    sharded, full-width rng planes replicated) instead of the 1-D
    GSPMD placement; refuses loudly on indivisible extents."""
    n = int(np.shape(state.alive)[0])
    avail = len(jax.devices()) if n_devices is None else n_devices
    if node_shards is not None:
        p = int(np.shape(state.pool.valid)[0])
        _check_node_shards(n, p, node_shards, avail)
        mesh = mesh_mod.make_mesh_2d(1, node_shards)
        return mesh_mod.shard_state_2d(state, mesh), mesh
    mesh = mesh_mod.make_mesh(_best_divisor(n, avail))
    return mesh_mod.shard_state(state, mesh), mesh
