"""Host-side machinery of the fleet supervisor (scripts/fleet_run.py).

A fleet run splits a campaign's replica grid into contiguous shards,
runs each shard in its own worker process (its own jax runtime — a
worker dying takes out only its shard), and merges the per-shard
artifacts back into ONE ensemble identical to an uninterrupted
single-process campaign.  Everything here is pure host code (json,
numpy, no jax) so the supervisor never initializes a backend and the
pieces unit-test without compiles:

  * :func:`shard_replicas` — contiguous near-even split of global
    replica ids; together with ``CampaignParams.replica_ids`` a shard
    worker advances exactly its rows of the full campaign,
    bit-identically (run_chunk is replica-independent).
  * heartbeat files — one atomic JSON per worker, rewritten after every
    chunk; the supervisor SIGKILLs-and-reschedules workers whose
    heartbeat goes stale (hang detection, not just death detection).
  * :func:`chaos_schedule` — the seeded chaos mode: (delay, worker)
    kill events from ``random.Random(seed)``, reproducible end to end.
  * :func:`encode_leaves` / :func:`decode_leaves` — dtype-preserving
    JSON codec for the counter-leaf pytree (dtype fidelity matters: the
    ensemble-identity check is EXACT equality, so a float32 leaf must
    not come back float64).
  * :func:`merge_shard_leaves` — row-merge of per-shard counter leaves
    by global replica id, refusing overlaps/holes; feed the result to
    ``service.loop.campaign_summarize_leaves`` for the ensemble summary.

Determinism contract: workers and any reference run MUST advance by the
same fixed-tick ``run_chunk`` cadence.  ``run_until_device`` is NOT
stack-invariant (its ``any(t_now < target)`` cond lets fast replicas
keep ticking until the slowest passes, so the stop tick depends on who
shares the stack) — fixed tick counts are what make shard == rows.
"""

from __future__ import annotations

import json
import os
import random
import time

import numpy as np


# ------------------------------------------------------------- shards --


def shard_replicas(total: int, workers: int) -> list:
    """Contiguous near-even split of global replica ids ``0..total-1``
    into at most ``workers`` non-empty shards (fewer when
    workers > total).  Deterministic: earlier shards take the remainder."""
    if total < 1 or workers < 1:
        raise ValueError("need total >= 1 and workers >= 1")
    workers = min(workers, total)
    base, rem = divmod(total, workers)
    out, start = [], 0
    for w in range(workers):
        n = base + (1 if w < rem else 0)
        out.append(tuple(range(start, start + n)))
        start += n
    return out


# ------------------------------------------------ atomic json + hearts --


def write_json_atomic(path: str, doc: dict) -> None:
    """tmp+fsync+rename — a SIGKILL mid-write never leaves a torn file
    (the checkpoint.py discipline, for heartbeats and shard artifacts)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json(path: str):
    """The parsed file, or None when missing/torn (a worker killed
    before its first heartbeat is a normal fleet condition)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def write_heartbeat(path: str, **fields) -> None:  # analysis: allow(wall-clock)
    """Worker liveness: atomic JSON stamped with the wall clock, plus
    caller fields (ticks_done, retries, ...)."""
    write_json_atomic(path, {"wall": time.time(), **fields})


def heartbeat_age(path: str, now: float | None = None):  # analysis: allow(wall-clock)
    """Seconds since the worker last heartbeat, or None when it never
    wrote one."""
    doc = read_json(path)
    if not doc or "wall" not in doc:
        return None
    return (time.time() if now is None else now) - float(doc["wall"])


def aggregate_heartbeats(docs: dict, now: float | None = None) -> dict:  # analysis: allow(wall-clock)
    """Fleet-level rollup of per-worker heartbeat docs.

    ``docs`` maps worker index → parsed heartbeat JSON (or None for a
    worker that never wrote / whose file is torn).  Pure host-side
    arithmetic: the supervisor polls this into fleet-level metric
    series (obs plane), ``fleet_report.json``, and the watcher."""
    t = time.time() if now is None else now
    out = {"workers_total": len(docs), "workers_reporting": 0,
           "ticks_done": 0, "ticks_target": 0, "retries": 0,
           "degraded_to_cpu": 0, "heartbeat_age_max_s": None,
           "per_worker": {}}
    ages = []
    for widx, doc in sorted(docs.items()):
        if not doc:
            out["per_worker"][str(widx)] = None
            continue
        out["workers_reporting"] += 1
        age = (t - float(doc["wall"])) if "wall" in doc else None
        if age is not None:
            ages.append(age)
        out["ticks_done"] += int(doc.get("ticks_done", 0))
        out["ticks_target"] += int(doc.get("ticks", 0))
        out["retries"] += int(doc.get("retries", 0))
        out["degraded_to_cpu"] += 1 if doc.get("degraded_to_cpu") else 0
        out["per_worker"][str(widx)] = {
            "age_s": round(age, 3) if age is not None else None,
            "ticks_done": int(doc.get("ticks_done", 0)),
            "ticks": int(doc.get("ticks", 0)),
            "retries": int(doc.get("retries", 0)),
            "chunk_wall_s": doc.get("chunk_wall_s"),
            "degraded_to_cpu": bool(doc.get("degraded_to_cpu", False)),
        }
    if ages:
        out["heartbeat_age_max_s"] = round(max(ages), 3)
    return out


# -------------------------------------------------------------- chaos --


def chaos_schedule(kills: int, workers: int, seed: int,
                   span_s: float = 10.0, min_delay_s: float = 0.5) -> list:
    """The seeded kill plan: ``kills`` events of ``(delay_s, worker)``,
    delays uniform over [min_delay_s, min_delay_s + span_s), sorted by
    delay.  Same seed → same plan, so a chaos failure reproduces."""
    rnd = random.Random(seed)
    events = [(min_delay_s + rnd.random() * span_s, rnd.randrange(workers))
              for _ in range(kills)]
    return sorted(events)


# ----------------------------------------------------- leaves json i/o --


def encode_leaves(tree):
    """Counter-leaf pytree (nested dicts of arrays) → JSON-able doc,
    dtype-preserving."""
    if isinstance(tree, dict):
        return {k: encode_leaves(v) for k, v in tree.items()}
    arr = np.asarray(tree)
    return {"__nd__": arr.tolist(), "dtype": str(arr.dtype)}


def decode_leaves(doc):
    """Inverse of :func:`encode_leaves` — numpy arrays with their
    original dtypes."""
    if isinstance(doc, dict) and "__nd__" in doc:
        return np.asarray(doc["__nd__"], dtype=np.dtype(doc["dtype"]))
    return {k: decode_leaves(v) for k, v in doc.items()}


# ------------------------------------------------------------- resize --


def plan_resize(row_ticks: dict, new_workers: int) -> list:
    """Re-split live replica rows across a CHANGED worker count.

    ``row_ticks`` maps global replica id → that row's checkpointed
    ``ticks_done`` (0 for a row never checkpointed).  A shard worker
    resumes from ONE ``ticks_done``, so rows are first grouped into
    tick classes (rows sharing a resume point) and each class is then
    split contiguously; shards are allocated to classes proportionally
    to class size (largest remainder), every class keeping at least
    one.  Returns ``[(replica_ids, ticks_done), ...]`` — at least
    ``len(classes)`` shards even when ``new_workers`` is smaller (rows
    at different resume points can never share a worker), never more
    shards than rows.

    This is what makes the autoscaler's resize safe WITHOUT a global
    barrier: ``run_chunk`` is replica-independent (the fleet
    determinism contract), so a row's future depends only on
    (base_seed, id, ticks_done) — not on which worker advances it."""
    if not row_ticks:
        raise ValueError("plan_resize needs at least one replica row")
    if new_workers < 1:
        raise ValueError("need new_workers >= 1")
    classes: dict = {}
    for gid, td in sorted(row_ticks.items()):
        classes.setdefault(int(td), []).append(int(gid))
    new_workers = min(new_workers, len(row_ticks))
    n_classes = len(classes)
    total = len(row_ticks)
    # proportional shard allocation, >= 1 per class, largest remainder
    counts = {td: 1 for td in classes}
    extra = max(new_workers - n_classes, 0)
    if extra:
        quotas = sorted(
            ((len(ids) * extra / total, td) for td, ids in classes.items()),
            reverse=True)
        whole = {td: int(q) for q, td in quotas}
        left = extra - sum(whole.values())
        for q, td in quotas:
            add = 1 if left > 0 and q - whole[td] > 0 else 0
            counts[td] += whole[td] + add
            left -= add
    out = []
    for td in sorted(classes):
        ids = classes[td]
        k = min(counts[td], len(ids))
        base, rem = divmod(len(ids), k)
        start = 0
        for w in range(k):
            n = base + (1 if w < rem else 0)
            out.append((tuple(ids[start:start + n]), td))
            start += n
    return out


def regroup_shard_leaves(old_shards, new_ids) -> list:
    """Rows for ONE new shard, drawn from the old shards' checkpoint
    leaves.

    ``old_shards`` — list of ``(replica_ids, leaves_list)`` where
    ``leaves_list`` holds the shard checkpoint's arrays in flatten
    order, each with the shard rows on axis 0.  Returns the new shard's
    leaves (same flatten order, rows in ``new_ids`` order).  Refuses a
    duplicated or missing global id loudly — a resize bug must not
    silently mint or lose a replica row."""
    loc: dict = {}
    for si, (ids, _) in enumerate(old_shards):
        for ri, gid in enumerate(ids):
            if int(gid) in loc:
                raise ValueError(
                    f"replica id {gid} appears in more than one shard")
            loc[int(gid)] = (si, ri)
    missing = [int(g) for g in new_ids if int(g) not in loc]
    if missing:
        raise ValueError(
            f"replica ids {missing} missing from the old shards")
    nleaf = {len(lv) for _, lv in old_shards}
    if len(nleaf) != 1:
        raise ValueError(
            f"old shards disagree on leaf count ({sorted(nleaf)})")
    out = []
    for j in range(nleaf.pop()):
        rows = []
        for gid in new_ids:
            si, ri = loc[int(gid)]
            rows.append(np.asarray(old_shards[si][1][j])[ri])
        out.append(np.stack(rows, axis=0))
    return out


# -------------------------------------------------------------- merge --


def merge_shard_leaves(shards, total: int | None = None):
    """Row-merge per-shard counter leaves into full-campaign leaves.

    ``shards`` — list of ``(replica_ids, leaves)`` where every leaf
    array's leading axis indexes the shard's rows in ``replica_ids``
    order.  The global ids must tile ``0..total-1`` exactly (no holes,
    no overlaps — a supervisor bug here must not silently produce a
    plausible ensemble).  Output rows are in global id order, so the
    merged leaves are positionally identical to an uninterrupted
    full-campaign run's."""
    ids = [int(i) for rid, _ in shards for i in rid]
    if total is None:
        total = max(ids) + 1 if ids else 0
    if sorted(ids) != list(range(total)):
        raise ValueError(
            f"shard replica ids do not tile 0..{total - 1}: got "
            f"{sorted(ids)}")
    order = np.argsort(np.asarray(ids, dtype=np.int64), kind="stable")

    def rec(parts):
        if isinstance(parts[0], dict):
            keys = list(parts[0].keys())
            for p in parts[1:]:
                if list(p.keys()) != keys:
                    raise ValueError("shard leaves disagree on keys")
            return {k: rec([p[k] for p in parts]) for k in keys}
        cat = np.concatenate([np.asarray(p) for p in parts], axis=0)
        return cat[order]

    return rec([leaves for _, leaves in shards])
