"""Closed-loop autoscaling: the fleet reads its own gauges and reacts.

PR 9 built a fleet that *survives* failure (reshard-on-resume, chaos
SIGKILL, seeded backoff) and PR 15 built a fleet that *sees itself*
(gauges, latency histograms, heartbeat rollups); this module connects
observation to action.  "Comparing Maintenance Strategies for Overlays"
(arxiv 0710.0386) makes the same point at the protocol layer — reactive
strategies that adapt to observed conditions dominate fixed-rate ones
under dynamic load — and that is what "elastic" must mean for this
serving stack: reacting to traffic, not just surviving SIGKILL.

The pieces, all host-only (no jax import — the supervisor runs this
before/without a backend; no obs import — the AST ``obs-import`` rule
confines the plane to host runners, so gauge publication stays with the
caller):

  * :class:`AutoscalePolicy` — the hysteresis knobs: scale-up /
    scale-down backlog thresholds (a DEAD BAND between them, so the
    loop cannot flap), an optional p99-latency trigger, a cooldown
    between decisions, and hard min/max worker bounds.
  * :class:`Signals` — one observation: backlog (outstanding work
    units — row-ticks for a fleet, queued requests for a service),
    provisioned workers, optional p99 latency, an ``aligned`` flag the
    caller clears while a resize would be unsafe, and the caller's
    monotonic clock reading (injected, so policy math is unit-testable
    without sleeping).
  * :class:`Autoscaler` — ``decide(signals)`` returns a
    :class:`Decision` (or None) and keeps the decision history the
    supervisor writes to its flight recorder and fleet report.
  * :func:`scrape_exposition` — minimal OpenMetrics text → {family:
    value} scraper (hand-rolled: elastic may not import obs), so the
    supervisor can close the loop on its OWN ``/metrics`` endpoint —
    the same bytes an external scraper sees — rather than on private
    supervisor state.

The actual resize (kill → regroup checkpoints → respawn) is the
supervisor's job: ``fleet.plan_resize`` + ``fleet.regroup_shard_leaves``
compute the new shard layout and ``scripts/fleet_run.py`` executes it.
"""

from __future__ import annotations

import dataclasses
import time
import urllib.request

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Hysteresis knobs for the scaling loop.

    ``up_backlog_per_worker`` and ``down_backlog_per_worker`` bound a
    dead band: above the first the fleet is under-provisioned (scale
    up), below the second it is over-provisioned (scale down), between
    them nothing happens — the band is what keeps a decision from
    immediately un-deciding itself after the backlog-per-worker ratio
    jumps across a single threshold."""

    min_workers: int = 1
    max_workers: int = 4
    up_backlog_per_worker: float = 256.0
    down_backlog_per_worker: float = 64.0
    p99_up_s: float | None = None     # optional latency trigger (scale up)
    cooldown_s: float = 5.0           # quiet period after any decision
    step: int = 1                     # workers added/removed per decision

    def __post_init__(self):
        if self.min_workers < 1 or self.max_workers < self.min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"[{self.min_workers}, {self.max_workers}]")
        if self.down_backlog_per_worker >= self.up_backlog_per_worker:
            raise ValueError(
                "hysteresis band inverted: down threshold "
                f"{self.down_backlog_per_worker} must be < up threshold "
                f"{self.up_backlog_per_worker}")
        if self.step < 1:
            raise ValueError("step must be >= 1")


@dataclasses.dataclass(frozen=True)
class Signals:
    """One scrape of the fleet's own gauges, ready for ``decide``.

    ``backlog`` is in whatever work unit the caller scales on —
    outstanding row-ticks for a fleet supervisor, queued requests for a
    serving tier.  ``now_s`` is the caller's monotonic clock (injected
    so cooldown math is deterministic in tests).  ``aligned`` is the
    caller's it-is-safe-to-resize-now flag; while False, decisions are
    deferred (counted, never silently dropped)."""

    backlog: float
    workers: int
    now_s: float
    p99_s: float | None = None
    workers_alive: int | None = None
    aligned: bool = True


@dataclasses.dataclass(frozen=True)
class Decision:
    action: str            # SCALE_UP | SCALE_DOWN
    from_workers: int
    to_workers: int
    reason: str
    at_s: float            # caller clock (Signals.now_s)
    wall: float            # wall stamp for cross-process correlation

    def describe(self) -> dict:
        return dataclasses.asdict(self)


class Autoscaler:
    """The decision loop: feed ``decide`` one :class:`Signals` per
    scrape cadence; it returns a :class:`Decision` when the policy
    wants a different worker count (and cooldown/alignment permit),
    else None.  Every decision lands in ``self.history``; deferrals
    (alignment) and cooldown skips are counted so the supervisor's
    gauges can show WHY the fleet is not reacting."""

    def __init__(self, policy: AutoscalePolicy | None = None):
        self.policy = policy or AutoscalePolicy()
        self.history: list = []
        self.scale_ups = 0
        self.scale_downs = 0
        self.deferred = 0          # wanted to act, but not aligned
        self.cooldown_skips = 0    # wanted to act, but inside cooldown
        self._last_at: float | None = None

    # ------------------------------------------------------- policy ----
    def target_for(self, sig: Signals) -> tuple:
        """Pure threshold logic: ``(target_workers, reason)`` with no
        cooldown/alignment gating — what the policy WANTS right now."""
        p = self.policy
        workers = max(1, sig.workers)
        per = sig.backlog / workers
        if sig.p99_s is not None and p.p99_up_s is not None \
                and sig.p99_s > p.p99_up_s:
            return (min(sig.workers + p.step, p.max_workers),
                    f"p99 {sig.p99_s:.3f}s > {p.p99_up_s:.3f}s")
        if per > p.up_backlog_per_worker:
            return (min(sig.workers + p.step, p.max_workers),
                    f"backlog/worker {per:.1f} > "
                    f"{p.up_backlog_per_worker:.1f}")
        if per < p.down_backlog_per_worker:
            return (max(sig.workers - p.step, p.min_workers),
                    f"backlog/worker {per:.1f} < "
                    f"{p.down_backlog_per_worker:.1f}")
        return sig.workers, "in band"

    def decide(self, sig: Signals):  # analysis: allow(wall-clock)
        """One scrape → at most one :class:`Decision`.

        The wall stamp on the decision is intentional wall-clock (the
        allow marker): decisions are correlated across processes with
        heartbeat files and flight events, which are wall-stamped."""
        target, reason = self.target_for(sig)
        if target == sig.workers:
            return None
        if (self._last_at is not None
                and sig.now_s - self._last_at < self.policy.cooldown_s):
            self.cooldown_skips += 1
            return None
        if not sig.aligned:
            self.deferred += 1
            return None
        action = SCALE_UP if target > sig.workers else SCALE_DOWN
        d = Decision(action=action, from_workers=sig.workers,
                     to_workers=target, reason=reason, at_s=sig.now_s,
                     wall=time.time())
        self._last_at = sig.now_s
        if action == SCALE_UP:
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        self.history.append(d)
        return d

    def describe(self) -> dict:
        """Report-ready summary (fleet_report.json ``autoscale``)."""
        return {"policy": dataclasses.asdict(self.policy),
                "decisions": [d.describe() for d in self.history],
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "deferred": self.deferred,
                "cooldown_skips": self.cooldown_skips}


def parse_exposition_text(text: str) -> dict:
    """Minimal OpenMetrics text parser: ``{family_or_series: value}``.

    A hand-rolled twin of ``obs.metrics.parse_exposition`` — this
    module may NOT import the obs plane (AST ``obs-import`` rule), and
    the closed loop should read the same bytes an external scraper
    reads.  Histogram series keep their suffixed names; plain counter/
    gauge samples land under the family name."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        name, val = parts
        name = name.split("{", 1)[0]
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


def scrape_exposition(url: str, timeout: float = 2.0) -> dict | None:
    """Scrape ``url`` (an obs ``/metrics`` endpoint) into {family:
    value}; None on any network error — the autoscaler must keep
    deciding off its fallback signal source when a scrape fails, not
    unwind the supervisor."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return parse_exposition_text(
                resp.read().decode("utf-8", "replace"))
    except Exception:  # noqa: BLE001 — scrape failure is a soft miss
        return None
