"""Elastic fleets: checkpoint-portable resharding + preemption tolerance.

The production-operations counterpart to raw scale (ROADMAP item 5):

  * :mod:`oversim_tpu.elastic.reshard` — a checkpoint written at one
    topology restores at another: the replica axis of campaign-stacked
    state grows/shrinks by padding/slicing (grown slots re-seeded
    deterministically from the campaign's base seed), and placement is
    re-established via ``NamedSharding`` over whatever mesh is available
    at restore time.  Surviving replicas are bit-identical across the
    reshape.
  * :mod:`oversim_tpu.elastic.retry` — the failure taxonomy: device /
    tunnel errors classified transient vs fatal, jittered exponential
    backoff around device dispatch and backend acquisition, and a
    graceful, loudly-annotated degradation to ``JAX_PLATFORMS=cpu``
    when chip acquisition keeps failing.
  * :mod:`oversim_tpu.elastic.fleet` — the host-side pieces of the
    fleet supervisor (``scripts/fleet_run.py``): replica-shard
    assignment, heartbeat files, seeded chaos schedules, and the
    per-shard artifact merge that reproduces the uninterrupted
    single-process ensemble exactly.

  * :mod:`oversim_tpu.elastic.autoscaler` — the closed loop: a
    hysteresis policy over the fleet's own gauges (backlog, p99
    latency, liveness) deciding when to grow/shrink the worker set;
    ``fleet.plan_resize`` + ``fleet.regroup_shard_leaves`` compute the
    resulting re-split of live replica rows.

See README.md "Elastic fleets" for the user guide.
"""

from oversim_tpu.elastic.autoscaler import (  # noqa: F401
    SCALE_DOWN,
    SCALE_UP,
    AutoscalePolicy,
    Autoscaler,
    Decision,
    Signals,
    parse_exposition_text,
    scrape_exposition,
)
from oversim_tpu.elastic.fleet import (  # noqa: F401
    chaos_schedule,
    decode_leaves,
    encode_leaves,
    heartbeat_age,
    merge_shard_leaves,
    plan_resize,
    read_json,
    regroup_shard_leaves,
    shard_replicas,
    write_heartbeat,
    write_json_atomic,
)
from oversim_tpu.elastic.reshard import (  # noqa: F401
    place_campaign,
    place_solo,
    replica_fingerprint,
    reshard_load,
    reshard_stacked,
)
from oversim_tpu.elastic.retry import (  # noqa: F401
    FATAL,
    TRANSIENT,
    RetryBudgetExceeded,
    RetryPolicy,
    acquire_backend,
    backoff_delays,
    classify,
    with_retry,
)
