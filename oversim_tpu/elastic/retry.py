"""Failure taxonomy + retry/backoff for preemptible device capacity.

Every on-chip measurement round since r03 has been lost to tunnel
flakiness, preemptions, or deadline SIGKILLs rather than to simulation
bugs (PERFORMANCE.md).  This module turns that class of failure from a
run-killer into a tolerated condition:

  * :func:`classify` — the taxonomy.  An exception raised by device
    dispatch or backend bring-up is either TRANSIENT (tunnel stall,
    connection reset, preempted/unavailable device, deadline, resource
    exhaustion — retry with backoff) or FATAL (shape/type/value errors,
    invalid arguments — a retry would fail identically; raise now).
    Classification is by exception type first, then by status markers in
    the message (XLA runtime errors surface as a generic RuntimeError
    whose text carries the gRPC-style status).
  * :func:`with_retry` — wrap any thunk in jittered exponential backoff
    over transient failures.  The jitter is SEEDED
    (``random.Random(policy.seed)``) so fleet workers retrying in lockstep
    de-synchronize deterministically instead of thundering back onto the
    tunnel together.
  * :func:`acquire_backend` — bring-up with degradation: probe the
    ambient jax backend under the retry policy; when chip acquisition
    keeps failing transiently, pin ``JAX_PLATFORMS=cpu``, warn LOUDLY on
    stderr, and return a manifest annotation (``degraded_to_cpu: True``
    plus the attempt log) that rides into every artifact via
    ``telemetry.run_manifest(extra={"elastic": ...})`` — a degraded run
    is always distinguishable from a healthy one.

No jax import at module scope: the whole point is to run BEFORE a
backend exists.
"""

from __future__ import annotations

import dataclasses
import os
import random
import sys
import time

TRANSIENT = "transient"
FATAL = "fatal"

# exception TYPES that are transient wherever they appear: every flavor
# of I/O, socket, and timeout failure the tunnel transport can surface
_TRANSIENT_TYPES = (
    ConnectionError,        # incl. BrokenPipeError / ConnectionResetError
    TimeoutError,
    InterruptedError,
    OSError,                # tunnel fds, sockets, NFS checkpoints
)

# message markers of transient device/tunnel failures.  XLA runtime
# errors reach Python as RuntimeError/XlaRuntimeError with a gRPC-style
# status prefix in the text — match the text so we need no jaxlib import.
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline exceeded",
    "deadline_exceeded",
    "resource exhausted",
    "resource_exhausted",
    "aborted",
    "cancelled",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "tunnel",
    "preempt",
    "timed out",
    "timeout",
    "temporarily",
    "try again",
    "too many open files",
    "failed to connect",
    "transport",
)

# message markers that are FATAL even on an otherwise-transient type:
# retrying an invalid program never helps
_FATAL_MARKERS = (
    "invalid_argument",
    "invalid argument",
    "failed_precondition",
    "failed precondition",
    "unimplemented",
    "not_found",
    "out_of_range",
)

# exception types where a retry would fail identically
_FATAL_TYPES = (ValueError, TypeError, KeyError, IndexError,
                AttributeError, AssertionError, NotImplementedError)


class RetryBudgetExceeded(RuntimeError):
    """The total-wall-clock retry budget ran out mid-storm.

    Raised by :func:`with_retry` when ``policy.max_total_seconds`` would
    be exceeded by the next backoff sleep — a transient-error storm
    fails LOUD at a bounded time instead of backing off through the
    whole attempt schedule.  Carries the full retry ``history``
    (``[(attempt, delay_s, error), ...]``) and the ``last_error`` so
    the operator sees every failure that burned the budget, not just
    the final one."""

    def __init__(self, label: str, elapsed_s: float, budget_s: float,
                 history: list, last_error: BaseException):
        self.label = label
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s
        self.history = list(history)
        self.last_error = last_error
        lines = "; ".join(f"attempt {a + 1}: {err}"
                          for a, _d, err in self.history) or "none"
        super().__init__(
            f"{label or 'retry'}: total retry budget exceeded "
            f"({elapsed_s:.1f}s elapsed of {budget_s:.1f}s) — retry "
            f"history: {lines}; last error: {last_error}")


def classify(exc: BaseException) -> str:
    """The failure taxonomy: ``"transient"`` (retry with backoff) or
    ``"fatal"`` (raise immediately).  Unknown errors default to FATAL —
    silently retrying a bug would hide it."""
    # a blown retry budget only ever wraps a transient storm (fatal
    # errors raise before any budget check) — callers with their own
    # degradation path (acquire_backend) treat it like the storm itself
    if isinstance(exc, RetryBudgetExceeded):
        return TRANSIENT
    text = f"{type(exc).__name__}: {exc}".lower()
    for marker in _FATAL_MARKERS:
        if marker in text:
            return FATAL
    if isinstance(exc, _FATAL_TYPES):
        return FATAL
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    for marker in _TRANSIENT_MARKERS:
        if marker in text:
            return TRANSIENT
    return FATAL


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff knobs.

    ``seed`` makes the jitter DETERMINISTIC: two policies with the same
    seed produce the same delay sequence (testable), and fleet workers
    seeded by worker index de-synchronize reproducibly."""

    attempts: int = 5           # total tries (first call included)
    base_s: float = 0.5         # first backoff delay
    factor: float = 2.0         # exponential growth per attempt
    max_s: float = 30.0         # delay ceiling (pre-jitter)
    jitter: float = 0.5         # delay *= 1 + uniform(0, jitter)
    seed: int = 0
    # total-wall-clock deadline across ALL attempts and sleeps; None =
    # unbounded (the attempt count alone bounds the loop).  When the
    # next backoff sleep would cross it, with_retry raises
    # RetryBudgetExceeded with the full retry history attached.
    max_total_seconds: float | None = None


def backoff_delays(policy: RetryPolicy) -> list:
    """The policy's full delay schedule (``attempts - 1`` sleeps),
    jittered by the seeded rng — pure, deterministic, unit-testable."""
    rnd = random.Random(policy.seed)
    out = []
    for i in range(max(0, policy.attempts - 1)):
        base = min(policy.max_s, policy.base_s * policy.factor ** i)
        out.append(base * (1.0 + policy.jitter * rnd.random()))
    return out


def with_retry(fn, *, policy: RetryPolicy | None = None,
               classify_fn=classify, on_retry=None, sleep=time.sleep,
               clock=time.monotonic, label: str = ""):
    """Call ``fn()`` under the retry policy.

    Transient failures sleep the next backoff delay and retry; fatal
    failures (and transient ones past the attempt budget) re-raise.
    ``policy.max_total_seconds`` additionally bounds the TOTAL wall
    clock: when the elapsed time plus the next sleep would cross it,
    :class:`RetryBudgetExceeded` is raised with the retry history
    attached — a transient storm fails loud at a bounded time.
    ``on_retry(attempt, delay_s, exc)`` observes every retry (the fleet
    worker logs them into its heartbeat); ``sleep`` and ``clock`` are
    injectable for tests."""
    policy = policy or RetryPolicy()
    delays = backoff_delays(policy)
    budget = policy.max_total_seconds
    t0 = clock()
    history: list = []
    for attempt in range(policy.attempts):
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — classified below
            if classify_fn(exc) != TRANSIENT or attempt >= len(delays):
                raise
            delay = delays[attempt]
            history.append((attempt, delay, repr(exc)))
            if budget is not None:
                elapsed = clock() - t0
                if elapsed + delay > budget:
                    raise RetryBudgetExceeded(
                        label, elapsed, budget, history, exc) from exc
            if on_retry is not None:
                on_retry(attempt, delay, exc)
            else:
                sys.stderr.write(
                    "elastic.retry: %stransient failure (attempt %d/%d, "
                    "retry in %.1fs): %s\n"
                    % (f"{label}: " if label else "", attempt + 1,
                       policy.attempts, delay, exc))
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


def _default_probe():
    """Touch the backend for real: device list + one tiny computation
    through the whole dispatch path."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    jnp.zeros(()).block_until_ready()
    return dev.platform


def acquire_backend(policy: RetryPolicy | None = None, *, probe=None,
                    sleep=time.sleep, environ=None,
                    clock=time.monotonic) -> dict:
    """Acquire a usable jax backend, degrading to CPU when the chip
    keeps failing.

    Runs ``probe`` (default: ``jax.devices()`` + a tiny dispatch) under
    the retry policy.  Success returns
    ``{"platform": ..., "degraded_to_cpu": False, "attempts": n}``.
    When every attempt fails TRANSIENTLY (tunnel down, device
    preempted), pins ``JAX_PLATFORMS=cpu`` in ``environ``, warns loudly,
    and returns ``degraded_to_cpu: True`` with the final error — the
    caller merges this dict into its run manifest
    (``run_manifest(extra={"elastic": ann})``) so the degradation is
    recorded on every artifact the run emits.  Fatal probe errors raise:
    degradation is for capacity problems, not for bugs."""
    policy = policy or RetryPolicy()
    environ = os.environ if environ is None else environ
    probe = probe or _default_probe
    attempts = 0
    last = None

    def counted():
        nonlocal attempts
        attempts += 1
        return probe()

    try:
        platform = with_retry(counted, policy=policy, sleep=sleep,
                              clock=clock, label="backend acquisition")
        return {"platform": str(platform), "degraded_to_cpu": False,
                "attempts": attempts}
    except BaseException as exc:  # noqa: BLE001 — classified below
        if classify(exc) != TRANSIENT:
            raise
        last = exc
    environ["JAX_PLATFORMS"] = "cpu"
    sys.stderr.write(
        "=" * 70 + "\n"
        "elastic.retry: CHIP ACQUISITION FAILED after %d attempts — "
        "DEGRADING to JAX_PLATFORMS=cpu.\n"
        "elastic.retry: last error: %s\n"
        "elastic.retry: every artifact of this run will carry "
        "degraded_to_cpu=true in its manifest.\n" % (attempts, last)
        + "=" * 70 + "\n")
    ann = {"platform": "cpu", "degraded_to_cpu": True,
           "attempts": attempts, "last_error": str(last)}
    if isinstance(last, RetryBudgetExceeded):
        # the storm log rides into the manifest: every error that burned
        # the budget, not just the final one
        ann["retry_budget_s"] = last.budget_s
        ann["retry_elapsed_s"] = round(last.elapsed_s, 3)
        ann["retry_history"] = [
            {"attempt": a, "delay_s": round(d, 3), "error": e}
            for a, d, e in last.history]
    return ann
