"""Stdlib-only HTTP endpoint for the live observability plane.

One daemon thread serving three read-only endpoints off the process's
metrics registry (obs/metrics.py):

  /metrics   Prometheus/OpenMetrics text exposition
  /healthz   {"status": "ready"|"overloaded"|"draining", ...} — HTTP
             200 while ready, 503 otherwise.  ``draining`` means the
             process is on its way OUT (a SIGTERM handler flips it so
             load balancers stop routing before exit); ``overloaded``
             means it is alive but SHEDDING load (admission control)
             and will return to ready when the backlog clears
  /statusz   JSON operational snapshot: server info merged with the
             runner-provided ``statusz`` callable (tick, window,
             replica shards, inbox_impl, degraded_to_cpu, checkpoint
             age — see obs/runtime.py RunObserver.statusz)

The ``statusz`` callable MUST be cheap and sync-free: it is invoked
from the serving thread on every scrape, so it may only read host-side
snapshots that the runner updated at its last window boundary — never
a device leaf.

``port=0`` binds an ephemeral port (the CI smoke's mode); the bound
port is available as ``server.port`` after ``start()`` and is printed/
recorded by the runners so scrapers can find it.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"

READY = "ready"
DRAINING = "draining"
OVERLOADED = "overloaded"


class ObsServer:
    def __init__(self, registry=None, *, port: int = 0,
                 host: str = "127.0.0.1", statusz=None):
        if registry is None:
            from oversim_tpu.obs.metrics import REGISTRY as registry
        self.registry = registry
        self.host = host
        self.port = port
        self.statusz_fn = statusz
        self.health = READY
        self._httpd = None
        self._thread = None
        self._t0 = time.monotonic()

    # ------------------------------------------------------ lifecycle --
    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        obs = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # no per-scrape stderr spam
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = obs.registry.render().encode()
                        self._send(200, body, CONTENT_TYPE_METRICS)
                    elif path == "/healthz":
                        doc = {"status": obs.health,
                               "uptime_s": round(obs.uptime_s(), 3)}
                        code = 200 if obs.health == READY else 503
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif path == "/statusz":
                        self._send(200, json.dumps(obs.status()).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must never kill the serving thread
                    try:
                        self._send(500, f"error: {e}\n".encode(),
                                   "text/plain")
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="obs-server", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # --------------------------------------------------------- status --
    def uptime_s(self) -> float:
        return time.monotonic() - self._t0

    def set_health(self, state: str) -> None:
        if state not in (READY, DRAINING, OVERLOADED):
            raise ValueError(f"unknown health state {state!r}")
        self.health = state

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def status(self) -> dict:
        doc = {"health": self.health, "port": self.port,
               "uptime_s": round(self.uptime_s(), 3)}
        if self.statusz_fn is not None:
            try:
                doc.update(self.statusz_fn() or {})
            except Exception as e:  # noqa: BLE001 — scrape must not die
                doc["statusz_error"] = str(e)
        return doc
