"""Request-level tracing: EXT_IN mint → EXT_OUT settle latency.

Every external request already carries a process-unique session id
(``sid``): ``InProcessIngest.submit`` and the RealtimeGateway's socket
pollers mint one per EXT_IN frame, and the EXT_OUT drain hands it back.
The :class:`RequestTracer` piggybacks on that id as the trace id —
``mint(sid)`` at ingest, ``settle(sid)`` at the drain — and feeds two
request-to-response latency histograms:

  * ``oversim_request_latency_seconds``  — wall clock, and
  * ``oversim_request_window_latency``   — WINDOWS between injection
    and drain (the serving tier's native latency unit: a request
    injected before window k and drained after window k took 1).

Both ingest paths take the tracer as a plain parameter (duck-typed), so
``gateway.py``/``service/ingest.py`` never import ``obs`` — the AST
``obs-import`` rule keeps the plane confined to host-side runners.

``keep_samples=True`` additionally retains raw per-request samples so
``scripts/loadgen.py`` can report EXACT p50/p99 instead of the
histogram's bucket-interpolated estimate.  Stdlib-only, host-side.
"""

from __future__ import annotations

import threading
import time

from oversim_tpu.obs import metrics as metrics_mod


def percentile(sorted_vals: list, q: float) -> float | None:
    """Exact linear-interpolated percentile over a SORTED list."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo]) * (1 - frac) + float(sorted_vals[hi]) * frac


class RequestTracer:
    """Mint/settle matcher with latency histograms.

    ``mint(sid, window=k)`` records the ingest instant; ``settle(sid,
    window=k')`` observes ``k' - k + 1`` window latency plus the wall
    latency and returns ``(wall_s, windows)``.  An unknown/duplicate
    sid settles to None (and counts as ``unmatched``) — the drain
    offers every parked EXT_OUT, not only traced ones."""

    def __init__(self, registry=None, *, keep_samples: bool = False,
                 max_samples: int = 65536, clock=time.monotonic,
                 prefix: str = "oversim", labels: dict | None = None):
        self.registry = registry or metrics_mod.get_registry()
        self.clock = clock
        self.keep_samples = keep_samples
        self.max_samples = max_samples
        self.samples_wall_s: list = []
        self.samples_windows: list = []
        self._open: dict = {}             # sid -> (t_mono, window)
        self._lock = threading.Lock()
        r = self.registry
        # the default prefix/labels reproduce the historical flat
        # oversim_* series exactly; per-tenant tracers use
        # prefix="oversim_tenant", labels={"tenant": "<t>"} so every
        # tenant gets its own labelled series on one shared registry
        self.minted = r.counter(
            f"{prefix}_requests_minted_total",
            "EXT_IN frames assigned a trace id at ingest",
            labels=labels)
        self.settled = r.counter(
            f"{prefix}_requests_settled_total",
            "EXT_OUT responses matched back to a minted trace id",
            labels=labels)
        self.unmatched = r.counter(
            f"{prefix}_requests_unmatched_total",
            "EXT_OUT drains with no (or an already-settled) trace id",
            labels=labels)
        self.nacked = r.counter(
            f"{prefix}_requests_nacked_total",
            "minted requests explicitly refused by admission control",
            labels=labels)
        self.latency_s = r.histogram(
            f"{prefix}_request_latency_seconds",
            "request-to-response wall latency",
            buckets=metrics_mod.LATENCY_BUCKETS_S, labels=labels)
        self.latency_windows = r.histogram(
            f"{prefix}_request_window_latency",
            "request-to-response latency in serving windows",
            buckets=metrics_mod.WINDOW_BUCKETS, labels=labels)

    def mint(self, sid, *, window: int | None = None) -> None:
        with self._lock:
            self._open[sid] = (self.clock(), window)
        self.minted.inc()

    def settle(self, sid, *, window: int | None = None):
        with self._lock:
            rec = self._open.pop(sid, None)
        if rec is None:
            self.unmatched.inc()
            return None
        t0, w0 = rec
        wall_s = self.clock() - t0
        windows = None
        if window is not None and w0 is not None:
            windows = int(window) - int(w0) + 1
            self.latency_windows.observe(windows)
        self.latency_s.observe(wall_s)
        self.settled.inc()
        if self.keep_samples and len(self.samples_wall_s) < self.max_samples:
            self.samples_wall_s.append(wall_s)
            if windows is not None:
                self.samples_windows.append(windows)
        return wall_s, windows

    def nack(self, sid, *, window: int | None = None) -> bool:
        """Close a minted trace as REFUSED (admission control shed).

        A NACKed request counts in ``nacked``, never in the latency
        histograms — shedding exists precisely so tail latency is not
        polluted by requests that were never served.  Unknown sid →
        ``unmatched`` (same contract as :meth:`settle`).  Together the
        counters satisfy minted == settled + nacked + outstanding."""
        del window  # symmetry with settle; a refusal has no latency
        with self._lock:
            rec = self._open.pop(sid, None)
        if rec is None:
            self.unmatched.inc()
            return False
        self.nacked.inc()
        return True

    def outstanding(self) -> int:
        with self._lock:
            return len(self._open)

    def percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """Exact percentiles over the kept samples (keep_samples=True);
        falls back to histogram bucket estimates otherwise."""
        if self.samples_wall_s:
            wall = sorted(self.samples_wall_s)
            wins = sorted(self.samples_windows)
            return {"exact": True, "count": len(wall),
                    "wall_s": {f"p{round(q * 100)}": percentile(wall, q)
                               for q in qs},
                    "windows": {f"p{round(q * 100)}": percentile(wins, q)
                                for q in qs}}
        return {"exact": False, "count": self.latency_s.count,
                "wall_s": {f"p{round(q * 100)}": self.latency_s.quantile(q)
                           for q in qs},
                "windows": {f"p{round(q * 100)}":
                            self.latency_windows.quantile(q) for q in qs}}

    def table(self, qs=(0.5, 0.9, 0.99)) -> str:
        """The human p50/p99 request-to-response latency table
        (ROADMAP item 4's deliverable; printed by scripts/loadgen.py)."""
        p = self.percentiles(qs)
        cols = [f"p{round(q * 100)}" for q in qs]
        head = "metric      " + "".join(f"{c:>12}" for c in cols)
        wall = "wall_ms     " + "".join(
            f"{(p['wall_s'][c] or 0.0) * 1e3:>12.2f}" for c in cols)
        wins = "windows     " + "".join(
            f"{(p['windows'][c] if p['windows'][c] is not None else 0):>12.2f}"
            for c in cols)
        tag = "exact" if p["exact"] else "histogram-estimated"
        return "\n".join(
            [f"request-to-response latency ({p['count']} settled, {tag})",
             head, wall, wins])


class SyntheticLoad:
    """N synthetic clients driving an InProcessIngest-shaped source.

    An ingest-protocol wrapper: before every window boundary it submits
    ``per_window`` fresh requests round-robin across ``clients``
    synthetic client ids (``b`` = client id, ``c`` = request serial —
    the echo app answers ``c + transform``, so payloads are checkable),
    then delegates to the wrapped source.  Attach the tracer to the
    INNER ingest; this wrapper only generates load."""

    def __init__(self, inner, *, clients: int = 4, per_window: int = 8,
                 max_requests: int | None = None):
        if clients < 1 or per_window < 0:
            raise ValueError("need clients >= 1 and per_window >= 0")
        self.inner = inner
        self.clients = clients
        self.per_window = per_window
        self.max_requests = max_requests
        self.submitted = 0
        self.sids: list = []

    @property
    def responses(self):
        return self.inner.responses

    def before_window(self, state, target_ns: int):
        for _ in range(self.per_window):
            if (self.max_requests is not None
                    and self.submitted >= self.max_requests):
                break
            client = self.submitted % self.clients
            self.sids.append(
                self.inner.submit(b=client, c=self.submitted))
            self.submitted += 1
        return self.inner.before_window(state, target_ns)

    def after_window(self, state):
        return self.inner.after_window(state)


def ramp_profile(clients: int, windows: int) -> list:
    """Triangular 0→``clients``→0 active-client schedule over
    ``windows`` boundaries: ramp up over the first half (peaking at
    ``clients``), back down to exactly 0 by the last window.  Pure and
    unit-testable — the overload proof in scripts/loadgen.py and the
    autoscale_smoke gate both ride on this shape."""
    if clients < 1 or windows < 1:
        raise ValueError("need clients >= 1 and windows >= 1")
    up = (windows + 1) // 2
    down = windows - up
    out = []
    for w in range(windows):
        if w < up:
            active = round(clients * (w + 1) / up)
        else:
            active = round(clients * (windows - 1 - w) / down)
        out.append(max(0, min(clients, active)))
    return out


class RampLoad:
    """Ramped synthetic load: 0→N clients→0 over a fixed window count.

    Same ingest-protocol wrapper shape as :class:`SyntheticLoad`, but
    the number of active clients follows :func:`ramp_profile` — the
    rising edge drives the backlog across the autoscaler's scale-up
    threshold (and past the admission bound, forcing sheds), the
    falling edge brings it back down across scale-down.  Each active
    client submits ``per_client`` requests per window (``b`` = client
    id, ``c`` = global serial); every submission is remembered in
    ``self.sent`` as ``(sid, b, c)`` so the driver can check each
    answer exactly (the echo app replies ``(b, c + 1)``).  Windows past
    the profile submit nothing — the drain tail."""

    def __init__(self, inner, *, clients: int = 8, windows: int = 32,
                 per_client: int = 1):
        if per_client < 1:
            raise ValueError("need per_client >= 1")
        self.inner = inner
        self.clients = clients
        self.windows = windows
        self.per_client = per_client
        self.profile = ramp_profile(clients, windows)
        self.window = 0
        self.submitted = 0
        self.sent: list = []          # (sid, b, c) in submit order

    @property
    def responses(self):
        return self.inner.responses

    def before_window(self, state, target_ns: int):
        if self.window < len(self.profile):
            for client in range(self.profile[self.window]):
                for _ in range(self.per_client):
                    sid = self.inner.submit(b=client, c=self.submitted)
                    self.sent.append((sid, client, self.submitted))
                    self.submitted += 1
        self.window += 1
        return self.inner.before_window(state, target_ns)

    def after_window(self, state):
        return self.inner.after_window(state)
