"""Process-wide live metrics registry with OpenMetrics exposition.

The GlobalStatistics equivalent for LONG-LIVED processes: the batch
tiers already emit post-hoc artifacts (telemetry rings, .vec files,
manifests), but the service loop, fleet supervisor and bench drivers
run for minutes-to-days and need metrics while they run.  This module
is the host-side half of that: three metric kinds —

  * :class:`Counter`   — monotonic; ``inc()`` refuses negative deltas,
  * :class:`Gauge`     — last-write-wins scalar,
  * :class:`Histogram` — fixed upper-bound buckets with cumulative
                         counts, ``_sum`` and ``_count`` samples,

— registered in a :class:`Registry` and rendered as Prometheus/
OpenMetrics text (``render()``), ready for ``/metrics`` scrapes
(obs/server.py).

Strictly host-side and stdlib-only: no jax, no numpy, no third-party
client library.  Updates happen ONLY at existing host-sync points
(window drains, measurement windows, heartbeat polls) — the registry
must never introduce a device sync of its own, which is why it takes
plain Python numbers, never array leaves.

Label support is deliberately minimal: a metric instance carries one
frozen label dict (e.g. ``labels={"worker": "0"}``); each distinct
``(name, labels)`` pair is its own series, grouped under a single
``# HELP``/``# TYPE`` header per family at exposition time.
"""

from __future__ import annotations

import math
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# request-to-response window latency: serving answers within a handful
# of windows; the +Inf bucket catches pathologically parked responses
WINDOW_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
# wall-clock request latency in seconds (sub-ms to a minute)
LATENCY_BUCKETS_S = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def escape_help(text: str) -> str:
    """HELP-line escaping per the Prometheus text format."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def escape_label_value(text: str) -> str:
    """Label-value escaping: backslash, double quote, newline."""
    return (text.replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def format_value(v: float) -> str:
    """Sample-value formatting: integers render bare, +Inf as ``+Inf``."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    """Common identity/labels machinery of the three metric kinds."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for k in (labels or {}):
            if not _LABEL_RE.match(k):
                raise ValueError(f"bad label name {k!r}")
        self.name = name
        self.help = help
        self.labels = dict(sorted((labels or {}).items()))
        self._lock = threading.Lock()

    def label_suffix(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(
            f'{k}="{escape_label_value(str(v))}"'
            for k, v in self.labels.items())
        return "{" + inner + "}"

    def samples(self) -> list:
        """``[(sample_name, label_suffix, value), ...]`` for exposition."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter.  Name it ``*_total`` (OpenMetrics idiom)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc({v}))")
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        return [(self.name, self.label_suffix(), self._value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        return [(self.name, self.label_suffix(), self._value)]


class Histogram(_Metric):
    """Fixed-bucket histogram: ``buckets`` are ascending finite upper
    bounds; an implicit ``+Inf`` bucket tops them off.  Exposed as
    cumulative ``_bucket{le=...}`` samples plus ``_sum``/``_count``."""

    kind = "histogram"

    def __init__(self, name, help="", labels=None, buckets=LATENCY_BUCKETS_S):
        super().__init__(name, help, labels)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(set(bs)) or bs[-1] == math.inf:
            raise ValueError(f"buckets must be ascending finite: {bs}")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)     # per-bucket, +Inf last
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = len(self.buckets)
            for j, le in enumerate(self.buckets):
                if v <= le:
                    i = j
                    break
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self) -> list:
        """NON-cumulative per-bucket counts (``+Inf`` last) — the shape
        ``vis.histogram_svg`` draws."""
        return list(self._counts)

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile estimate in [0, 1]; None when
        empty.  Values beyond the last finite bound clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        if self._count == 0:
            return None
        rank = q * self._count
        cum = 0
        lo = 0.0
        for j, le in enumerate(self.buckets):
            prev = cum
            cum += self._counts[j]
            if cum >= rank:
                frac = ((rank - prev) / self._counts[j]
                        if self._counts[j] else 0.0)
                return lo + (le - lo) * frac
        return self.buckets[-1]

    def samples(self):
        out = []
        base = dict(self.labels)
        cum = 0
        for j, le in enumerate(list(self.buckets) + [math.inf]):
            cum += self._counts[j]
            labels = dict(base)
            labels["le"] = format_value(le)
            inner = ",".join(f'{k}="{escape_label_value(str(v))}"'
                             for k, v in labels.items())
            out.append((self.name + "_bucket", "{" + inner + "}", cum))
        suffix = self.label_suffix()
        out.append((self.name + "_sum", suffix, self._sum))
        out.append((self.name + "_count", suffix, self._count))
        return out


class Registry:
    """Get-or-create registry of metric families.

    ``counter``/``gauge``/``histogram`` return the EXISTING instance for
    an already-registered ``(name, labels)`` pair — call sites stay
    idempotent — and raise when the same name is re-registered as a
    different kind (a family must have one type)."""

    def __init__(self):
        self._metrics: dict = {}      # (name, labels-tuple) -> metric
        self._kinds: dict = {}        # name -> kind
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if existing.kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                return existing
            if self._kinds.get(name, cls.kind) != cls.kind:
                raise ValueError(
                    f"metric family {name!r} already registered as "
                    f"{self._kinds[name]}, not {cls.kind}")
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
            self._kinds[name] = cls.kind
            return m

    def counter(self, name, help="", labels=None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=None,
                  buckets=LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def collect(self) -> list:
        """Metric instances grouped by family name, registration-stable."""
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus/OpenMetrics text exposition of every registered
        series, one ``# HELP``/``# TYPE`` header per family, terminated
        with ``# EOF``."""
        families: dict = {}
        for m in self.collect():
            families.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(families):
            members = families[name]
            help_text = next((m.help for m in members if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {escape_help(help_text)}")
            lines.append(f"# TYPE {name} {members[0].kind}")
            for m in members:
                for sname, suffix, value in m.samples():
                    lines.append(f"{sname}{suffix} {format_value(value)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


# the process-wide default registry every runner publishes into
REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY


def parse_exposition(text: str) -> dict:
    """Parse exposition text back into ``{sample_key: float}`` —
    ``sample_key`` is the sample name plus its literal label suffix.
    The scrape-side half used by scripts/obs_watch.py and the
    monotonicity assertions in scripts/obs_smoke.py."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        if not key:
            continue
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out
