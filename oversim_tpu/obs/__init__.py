"""Live observability plane — host-side only, stdlib-only.

The post-hoc artifacts (telemetry rings, .vec files, Perfetto traces,
manifests) materialize after a run ends; this package is the LIVE half:
a process-wide metrics registry with OpenMetrics exposition
(``metrics``), an HTTP endpoint thread serving ``/metrics`` /
``/healthz`` / ``/statusz`` (``server``), a JSONL flight recorder with
a crash-tail dump (``flight``), EXT_IN→EXT_OUT request tracing
(``requests``), the :class:`RunObserver` glue runners publish into
(``runtime``), and the ``OVERSIM_XPROF`` on-chip capture hatch
(``xprof``).

Contract: everything here updates strictly at EXISTING host-sync
points and never enters a compiled graph.  The analysis plane enforces
it — the ``obs-import`` AST rule (analysis/ast_pass.py) fails any
``oversim_tpu`` module outside this package that imports it; runners
under ``scripts/`` and ``bench.py`` are the intended consumers, and
in-package code (gateway, ingest) takes tracer/observer objects as
plain duck-typed parameters instead of importing the plane.
"""

from oversim_tpu.obs.flight import FlightRecorder
from oversim_tpu.obs.metrics import (LATENCY_BUCKETS_S, REGISTRY,
                                     WINDOW_BUCKETS, Counter, Gauge,
                                     Histogram, Registry, get_registry,
                                     parse_exposition)
from oversim_tpu.obs.requests import (RampLoad, RequestTracer,
                                      SyntheticLoad, ramp_profile)
from oversim_tpu.obs.runtime import RunObserver
from oversim_tpu.obs.server import DRAINING, OVERLOADED, READY, ObsServer
from oversim_tpu.obs.xprof import capture as xprof_capture
from oversim_tpu.obs.xprof import xprof_dir

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "get_registry", "parse_exposition", "LATENCY_BUCKETS_S",
    "WINDOW_BUCKETS", "ObsServer", "READY", "DRAINING", "OVERLOADED",
    "FlightRecorder", "RequestTracer", "SyntheticLoad", "RampLoad",
    "ramp_profile", "RunObserver", "xprof_capture", "xprof_dir",
]
