"""JSONL flight recorder: bounded ring + streamed event log + crash tail.

Long-lived runners emit structured events at their host-sync points —
window dispatched/fetched, checkpoint written, retry/backoff, chaos
kill, AOT hit/miss, contract verdict — and the recorder does two things
with each:

  1. streams it to disk as one JSON line (append + flush, so a SIGKILL
     loses at most the in-flight line), and
  2. keeps the last ``capacity`` events in an in-memory ring, dumped as
     ``<path>.tail.json`` on SIGTERM/fatal error (``install()``) or on
     demand (``dump_tail``) — the "what were the last 512 things this
     process did" artifact the post-mortem starts from.

Event volume is window-cadence (a handful per second at most), so the
per-event flush is noise; the recorder must never be put on a per-tick
path.  Stdlib-only, thread-safe, and deliberately non-throwing: a
recorder error must never take down the run it is observing.
"""

from __future__ import annotations

import collections
import json
import os
import signal as signal_mod
import sys
import threading
import time


class FlightRecorder:
    def __init__(self, path: str | None = None, *, capacity: int = 512):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.path = str(path) if path else None
        self.capacity = capacity
        self.events_total = 0
        self._ring = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._file = None
        self._prev_handlers = {}
        self._prev_excepthook = None

    # --------------------------------------------------------- record --
    def event(self, kind: str, **fields) -> dict:  # analysis: allow(wall-clock)
        """Record one structured event (wall + monotonic stamped).  The
        wall clock is deliberate here: flight logs are correlated with
        external logs/scrapes, not used for intervals."""
        ev = {"kind": kind, "wall": time.time(),
              "mono": time.monotonic(), **fields}
        with self._lock:
            self.events_total += 1
            self._ring.append(ev)
            if self.path is not None:
                try:
                    if self._file is None:
                        self._file = open(self.path, "a", buffering=1)
                    self._file.write(json.dumps(ev, default=str) + "\n")
                    self._file.flush()
                except OSError:
                    self._file = None       # keep the ring; retry later
        return ev

    @property
    def dropped(self) -> int:
        """Events no longer in the ring (streamed to disk, if a path
        was configured)."""
        return max(0, self.events_total - len(self._ring))

    def tail(self) -> list:
        with self._lock:
            return list(self._ring)

    def summary(self) -> dict:
        return {"path": self.path, "events_total": self.events_total,
                "ring": len(self._ring), "capacity": self.capacity}

    # ----------------------------------------------------------- dump --
    def dump_tail(self, path: str | None = None) -> str | None:
        """Write the ring tail as ONE JSON array.  Default target is
        ``<path>.tail.json`` next to the stream; with neither, the tail
        goes to stderr.  Returns the written path (None for stderr)."""
        doc = {"kind": "flight_tail", "events_total": self.events_total,
               "tail": self.tail()}
        target = path or (self.path + ".tail.json" if self.path else None)
        blob = json.dumps(doc, indent=1, default=str)
        if target is None:
            sys.stderr.write(blob + "\n")
            return None
        try:
            tmp = target + ".tmp"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, target)
            return target
        except OSError:
            sys.stderr.write(blob + "\n")
            return None

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    # -------------------------------------------- signal / fatal hooks --
    def install(self, signals=(signal_mod.SIGTERM,),
                excepthook: bool = True) -> None:
        """Dump the tail on fatal paths, CHAINING whatever was installed
        before: the previous signal handler / excepthook still runs, so
        a runner's own SIGTERM graceful-stop logic is preserved.  Use
        only from the main thread (CPython signal rule); runners that
        own their SIGTERM handler should instead call ``event`` +
        ``dump_tail`` from it directly."""
        for sig in signals:
            prev = signal_mod.getsignal(sig)
            self._prev_handlers[sig] = prev

            def _handler(signum, frame, _prev=prev):
                self.event("signal", signum=signum)
                self.dump_tail()
                if callable(_prev):
                    _prev(signum, frame)
                elif _prev == signal_mod.SIG_DFL:
                    signal_mod.signal(signum, signal_mod.SIG_DFL)
                    signal_mod.raise_signal(signum)

            signal_mod.signal(sig, _handler)
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def _hook(tp, value, tb):
                self.event("fatal", error=f"{tp.__name__}: {value}")
                self.dump_tail()
                (self._prev_excepthook or sys.__excepthook__)(tp, value, tb)

            sys.excepthook = _hook

    def uninstall(self) -> None:
        for sig, prev in self._prev_handlers.items():
            signal_mod.signal(sig, prev)
        self._prev_handlers = {}
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
