"""RunObserver: the one-per-process glue every runner publishes into.

Bundles the plane's pieces — metrics registry, HTTP endpoint, flight
recorder, optional request tracer — behind the two callbacks the
runners already have at their host-sync points:

  * ``on_window(window, summary, wall_s)``   ← service loop / bench
    measurement-window ``on_window`` callbacks (the per-window host
    sync that fetched ``summary`` is the loop's own; the observer only
    reads the already-fetched dict), and
  * ``loop_event(kind, **fields)``           ← ``ServiceLoop(events=)``
    (window dispatched/fetched, checkpoint written) and ad-hoc runner
    events (retry/backoff, chaos kill, AOT hit/miss, contract verdict).

``statusz()`` assembles the ``/statusz`` snapshot — tick, window,
replica shards, inbox_impl, degraded_to_cpu, checkpoint age — purely
from those host-side updates, so a scrape never touches the device.

Typical runner wiring (scripts/service_run.py)::

    obs = RunObserver(role="service", port=args.metrics_port,
                      flight_path=args.flight)
    obs.set_static(inbox_impl=sim.ep.inbox_impl, replicas=args.replicas)
    obs.start()                       # → bound port (0 = ephemeral)
    loop = ServiceLoop(..., on_window=..., events=obs.loop_event)
    ...
    obs.draining()                    # SIGTERM: healthz → 503
    obs.close()
"""

from __future__ import annotations

import time

from oversim_tpu.obs import metrics as metrics_mod
from oversim_tpu.obs.flight import FlightRecorder
from oversim_tpu.obs.server import DRAINING, OVERLOADED, READY, ObsServer

# per-window wall cost (dispatch-to-drain), seconds
WINDOW_WALL_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                       5.0, 10.0, 30.0, 60.0, 120.0)


class RunObserver:
    def __init__(self, *, role: str = "service", registry=None,
                 port: int | None = None, host: str = "127.0.0.1",
                 flight_path: str | None = None,
                 flight_capacity: int = 512, tracer=None):
        self.role = role
        self.registry = registry or metrics_mod.get_registry()
        self._port_req = port
        self.host = host
        self.port: int | None = None
        self.server: ObsServer | None = None
        self.flight = FlightRecorder(flight_path, capacity=flight_capacity)
        self.tracer = tracer
        self._static: dict = {"role": role}
        self._last: dict = {}
        self._last_wall_s: float | None = None
        self._last_checkpoint_mono: float | None = None
        r = self.registry
        self.up = r.gauge("oversim_up", "1 while the process serves",
                          labels={"role": role})
        self.up.set(1)
        self.windows = r.counter("oversim_windows_total",
                                 "serving/measurement windows drained")
        self.ticks = r.gauge("oversim_ticks",
                             "simulation ticks at the last drain")
        self.sim_seconds = r.gauge("oversim_sim_seconds",
                                   "simulated seconds at the last drain")
        self.alive = r.gauge("oversim_alive_nodes",
                             "alive overlay nodes at the last drain")
        # sparse active-set plane (EngineParams.tick_impl="sparse"):
        # cumulative per-tick active-set sizes — the live view of "tick
        # cost scales with traffic, not N".  Only set when the engine
        # carries the sparse counters (dense runs never touch them).
        self.awake_nodes = r.gauge(
            "oversim_sparse_awake_nodes",
            "cumulative awake nodes summed over ticks (sparse tick)")
        self.active_dst = r.gauge(
            "oversim_sparse_active_dst",
            "cumulative due-message destinations summed over ticks "
            "(sparse tick)")
        self.active_deferred = r.gauge(
            "oversim_sparse_active_deferred",
            "cumulative awake nodes deferred past the active_cap "
            "(sparse tick; nonzero means the cap clipped a window)")
        self.window_wall = r.histogram(
            "oversim_window_wall_seconds",
            "wall seconds per drained window",
            buckets=WINDOW_WALL_BUCKETS)
        self.checkpoints = r.counter("oversim_checkpoints_total",
                                     "checkpoints written")
        self.events = r.counter("oversim_flight_events_total",
                                "flight-recorder events recorded")
        # gateway/ingest RX export (attach_rx_source): the host-side
        # rx_* counters mirrored into the registry as monotone counters
        self._rx_src = None
        self._rx_counters: dict = {}
        self._rx_last: dict = {}

    # ------------------------------------------------------ lifecycle --
    def start(self) -> int | None:
        """Start the HTTP endpoint when a port was requested (0 =
        ephemeral); returns the bound port (None = endpoint off)."""
        if self._port_req is None:
            return None
        self.server = ObsServer(self.registry, port=self._port_req,
                                host=self.host, statusz=self.statusz)
        self.port = self.server.start()
        self.flight.event("obs_start", port=self.port, role=self.role)
        return self.port

    def draining(self) -> None:
        """Flip /healthz ready → draining (503) and log it — call from
        the SIGTERM handler BEFORE the graceful stop begins."""
        if self.server is not None:
            self.server.set_health(DRAINING)
        self.record("draining")

    def overloaded(self, **fields) -> None:
        """Flip /healthz ready → overloaded (503): admission control is
        SHEDDING.  Distinct from draining (the process is staying, load
        balancers should back off, not deregister); a process already
        draining keeps that terminal state."""
        if self.server is None or self.server.health != READY:
            return
        self.server.set_health(OVERLOADED)
        self.record("overloaded", **fields)

    def ready(self, **fields) -> None:
        """Clear an overload: overloaded → ready.  Draining is terminal
        and never cleared from here."""
        if self.server is None or self.server.health != OVERLOADED:
            return
        self.server.set_health(READY)
        self.record("overload_cleared", **fields)

    def close(self, *, dump_tail: bool = False) -> None:
        if dump_tail:
            self.flight.dump_tail()
        self.flight.close()
        if self.server is not None:
            self.server.stop()
            self.server = None

    def describe(self) -> dict:
        """Manifest-ready endpoint description."""
        return {"metrics_port": self.port, "flight": self.flight.path}

    # -------------------------------------------------------- updates --
    def set_static(self, **fields) -> None:
        """Scrape-visible run facts that don't change per window:
        inbox_impl, replicas, shards, degraded_to_cpu, ..."""
        self._static.update(fields)

    def record(self, kind: str, **fields) -> None:
        """A flight event + the event counter (ad-hoc runner events:
        retry, chaos_kill, aot hit/miss, contract verdict...)."""
        self.events.inc()
        self.flight.event(kind, **fields)

    def loop_event(self, kind: str, **fields) -> None:
        """ServiceLoop ``events=`` hook: every loop lifecycle event into
        the flight ring; checkpoint writes also feed the counter/age."""
        if kind == "checkpoint_written":
            self.checkpoints.inc()
            self._last_checkpoint_mono = time.monotonic()
        self.record(kind, **fields)

    def attach_rx_source(self, src) -> None:
        """Mirror a gateway/ingest's host-side ``rx_*`` counters into
        the registry so they reach ``/metrics`` (ISSUE 17: today they
        are counted host-side but invisible to scrapers).  ``src`` is
        duck-typed — any object carrying integer ``rx_frames`` /
        ``rx_batches`` / ``rx_dropped`` / ``rx_socket_errors`` /
        ``rx_shed`` attributes (missing ones are skipped).  Deltas are
        synced at every ``on_window`` / ``statusz`` scrape."""
        self._rx_src = src
        specs = (
            ("rx_frames", "oversim_gateway_rx_frames_total",
             "external frames injected into the pool (post-parse)"),
            ("rx_batches", "oversim_gateway_rx_batches_total",
             "batched EXT_IN pool writes performed"),
            ("rx_dropped", "oversim_gateway_rx_dropped_total",
             "malformed/unauthenticated frames dropped"),
            ("rx_socket_errors", "oversim_gateway_rx_socket_errors_total",
             "transient socket-level receive errors"),
            ("rx_shed", "oversim_gateway_rx_shed_total",
             "well-formed frames refused by admission control (NACKed)"),
        )
        for attr, name, help_ in specs:
            if hasattr(src, attr):
                self._rx_counters[attr] = self.registry.counter(name, help_)
                self._rx_last.setdefault(attr, 0)
        self.sync_rx()

    def sync_rx(self) -> None:
        """Push the rx source's counter deltas into the registry
        (counters are monotone: only positive deltas are applied)."""
        if self._rx_src is None:
            return
        for attr, counter in self._rx_counters.items():
            val = getattr(self._rx_src, attr, None)
            if val is None:
                continue
            delta = int(val) - self._rx_last[attr]
            if delta > 0:
                counter.inc(delta)
                self._rx_last[attr] = int(val)

    def on_window(self, window: int, summary: dict, wall_s: float) -> None:
        """Per-drained-window update off the ALREADY-FETCHED summary —
        chain it from the runner's own on_window callback."""
        self.windows.inc()
        self.sync_rx()
        if "_ticks" in summary:
            self.ticks.set(summary["_ticks"])
        if "_t_sim" in summary:
            self.sim_seconds.set(summary["_t_sim"])
        if "_alive" in summary:
            self.alive.set(summary["_alive"])
        eng = summary.get("_engine") or {}
        if "awake_nodes" in eng:
            self.awake_nodes.set(eng["awake_nodes"])
            self.active_dst.set(eng.get("active_dst", 0))
            self.active_deferred.set(eng.get("active_deferred", 0))
        if self._last_wall_s is not None and wall_s >= self._last_wall_s:
            self.window_wall.observe(wall_s - self._last_wall_s)
        self._last_wall_s = wall_s
        self._last = {"window": window,
                      "tick": summary.get("_ticks"),
                      "t_sim": summary.get("_t_sim"),
                      "alive": summary.get("_alive")}

    # --------------------------------------------------------- status --
    def checkpoint_age_s(self) -> float | None:
        if self._last_checkpoint_mono is None:
            return None
        return time.monotonic() - self._last_checkpoint_mono

    def statusz(self) -> dict:
        age = self.checkpoint_age_s()
        self.sync_rx()
        doc = dict(self._static)
        doc.update(self._last)
        doc["windows_done"] = int(self.windows.value)
        doc["checkpoints_written"] = int(self.checkpoints.value)
        doc["checkpoint_age_s"] = (round(age, 3)
                                   if age is not None else None)
        doc["flight"] = self.flight.summary()
        if self.tracer is not None:
            doc["requests"] = {
                "minted": int(self.tracer.minted.value),
                "settled": int(self.tracer.settled.value),
                "nacked": int(getattr(self.tracer, "nacked").value)
                if hasattr(self.tracer, "nacked") else 0,
                "outstanding": self.tracer.outstanding()}
        if self._rx_src is not None:
            doc["rx"] = {attr: self._rx_last.get(attr, 0)
                         for attr in self._rx_counters}
        return doc
