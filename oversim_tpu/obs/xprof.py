"""On-chip profiler escape hatch: ``OVERSIM_XPROF=dir``.

The host-side metrics plane sees window walls, not what the chip did
inside them; ``OVERSIM_XPROF=<dir>`` wraps the measurement windows in
``jax.profiler.trace`` so a real XLA capture (HLO timelines, on-device
annotations) lands in ``dir``, and the capture path is attached to the
run artifact — the measurement-debt bridge for ROADMAP items 2-3
(on-chip window-wall breakdown / device-timeline pipelining proof).

The capture is strictly best-effort: a missing/broken profiler backend
degrades to a disabled capture with the error recorded, never a dead
run.  jax is imported lazily INSIDE the capture so the rest of ``obs``
stays importable without a backend.
"""

from __future__ import annotations

import contextlib
import os

ENV = "OVERSIM_XPROF"


def xprof_dir(environ=None) -> str | None:
    """The capture directory, or None when the hatch is closed."""
    return (environ or os.environ).get(ENV) or None


@contextlib.contextmanager
def capture(label: str = "measure", *, out_dir: str | None = None):
    """Wrap a measurement region in ``jax.profiler.trace`` when armed.

    Yields an info dict: ``{"enabled", "dir", "label", "error"}`` —
    check ``enabled`` after the block; ``dir`` is what the artifact
    records.  With no $OVERSIM_XPROF (and no explicit ``out_dir``) the
    body runs untouched."""
    d = out_dir or xprof_dir()
    info = {"enabled": False, "dir": d, "label": label, "error": None}
    if not d:
        yield info
        return
    started = False
    try:
        import jax
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
        started = True
        info["enabled"] = True
    except Exception as e:  # noqa: BLE001 — profiling must never kill a run
        info["error"] = f"{type(e).__name__}: {e}"
    try:
        yield info
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                info["error"] = f"{type(e).__name__}: {e}"
