"""Per-node logic scaffolding: message views, outbox builder, tick context.

A "logic" object plays the role of the whole per-node module stack of the
reference (overlay + tier apps + RPC glue, reference SimpleOverlayHost.ned)
— but as pure functions over structure-of-arrays state, written against a
*single* node's slice and vmapped over all N nodes by the engine.

Logic interface (duck-typed; see engine/sim.py):

  key_spec              -> core.keys.KeySpec
  stat_spec()           -> StatSpec
  init(rng, n)          -> state pytree of [N, ...] arrays
  reset(state, clear, join, t_now, rng) -> state
      # churn transitions: ``clear`` [N] marks slots to wipe (created AND
      # killed), ``join`` [N] the subset that goes live and must schedule
      # its join; t_now is the window start (i64 scalar)
  ready_mask(state)     -> [N] bool           # overlay READY (bootstrappable)
  next_event(state)     -> [N] i64            # earliest local timer/timeout
  step(ctx, state_n, inbox, rng, node_idx, *, outbox_slots, rmax)
      -> (state_n, Outbox, events)            # per-node; vmapped over N

``events`` is a dict stat-name -> (values, mask) pairs consumed by
engine/stats.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NO_NODE = jnp.int32(-1)
T_INF = jnp.int64(2**62)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Msg:
    """View of one (or a batch of) pool message(s); see engine/pool.py."""

    valid: jnp.ndarray
    t_deliver: jnp.ndarray
    src: jnp.ndarray
    dst: jnp.ndarray
    kind: jnp.ndarray
    key: jnp.ndarray
    nonce: jnp.ndarray
    hops: jnp.ndarray
    a: jnp.ndarray
    b: jnp.ndarray
    c: jnp.ndarray
    d: jnp.ndarray
    nodes: jnp.ndarray
    size_b: jnp.ndarray
    stamp: jnp.ndarray

    def slot(self, r: int) -> "Msg":
        """Select inbox slot r (fields lose their leading R axis)."""
        return jax.tree.map(lambda x: x[r], self)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Ctx:
    """Broadcast tick context available to every node's handlers."""

    t_start: jnp.ndarray      # i64 scalar — window start
    t_end: jnp.ndarray        # i64 scalar — window end (exclusive)
    keys: jnp.ndarray         # [N, KL] u32 — global node-key table (oracle)
    alive: jnp.ndarray        # [N] bool
    ready: jnp.ndarray        # [N] bool — overlay READY at window start
    ready_cumsum: jnp.ndarray  # [N] i32 inclusive cumsum of ready mask
    n_ready: jnp.ndarray      # i32 scalar
    measuring: jnp.ndarray    # bool scalar — inside measurement phase
    glob: object = None       # logic-global read-only state (see LogicBase)
    # graceful-leave grace windows (engine/sim.py step; reference
    # NF_OVERLAY_NODE_LEAVE / NF_OVERLAY_NODE_GRACEFUL_LEAVE):
    leaving: object = None      # [N] bool — pre-killed, still running
    graceful: object = None     # [N] bool — subset doing data handover
    malicious: object = None    # [N] bool — byzantine attacker flags
    # partition support (set only when the underlay defines >1 node type):
    node_type: object = None    # [N] i32
    conn: object = None         # [T, T] bool connectivity matrix
    ready_cum_t: object = None  # [T, N] i32 per-type ready cumsums
    # campaign sweep overrides: {dotted-name: traced scalar} or None.
    # Handlers opt in via ov_get(); absent keys keep the static-param
    # code path so a no-sweep trace stays bit-identical.
    ov: object = None

    def ov_get(self, name, default=None):
        """Traced sweep-override lookup (trace-time dict access)."""
        if self.ov is None:
            return default
        return self.ov.get(name, default)

    def sample_ready(self, rng, me=None):
        """Draw a uniformly random READY node slot (-1 if none).

        Oracle bootstrap draw, reference GlobalNodeList::getBootstrapNode
        (GlobalNodeList.h:115) / getRandomNode — O(1) via the per-type
        bootstrapped-peer vectors; here a searchsorted over the cumsum.

        With partitions active and ``me`` given (the drawing node's slot),
        the draw is restricted to node types connected to ``me``'s type
        (the reference's per-type bootstrap vectors + connectionMatrix,
        GlobalNodeList.h:232-235) so a partitioned node never bootstraps
        across the cut.
        """
        if self.conn is not None and me is not None:
            my_type = self.node_type[me]
            allowed = self.conn[my_type]                  # [T]
            counts = self.ready_cum_t[:, -1]              # [T]
            eff = jnp.where(allowed, counts, 0)
            total = jnp.sum(eff)
            k = jax.random.randint(rng, (), 0, jnp.maximum(total, 1),
                                   dtype=I32)
            cum_t = jnp.cumsum(eff)
            tpick = jnp.searchsorted(cum_t, k + 1, side="left").astype(I32)
            tpick = jnp.clip(tpick, 0, counts.shape[0] - 1)
            within = k - jnp.where(tpick > 0, cum_t[jnp.maximum(tpick - 1, 0)],
                                   0)
            idx = jnp.searchsorted(self.ready_cum_t[tpick], within + 1,
                                   side="left").astype(I32)
            return jnp.where(total > 0, idx, NO_NODE)
        k = jax.random.randint(rng, (), 0, jnp.maximum(self.n_ready, 1),
                               dtype=I32)
        idx = jnp.searchsorted(self.ready_cumsum, k + 1, side="left").astype(I32)
        return jnp.where(self.n_ready > 0, idx, NO_NODE)


class LogicBase:
    """Optional base for logic objects: splits state into a vmapped
    per-node part and a simulation-global part.

    The reference has true singletons next to the per-node module stacks
    (GlobalNodeList, GlobalStatistics, GlobalDhtTestMap — SURVEY.md §1).
    Per-node handlers run vmapped and cannot write shared arrays, so
    global state follows a gather/scatter discipline:

      * ``split(state) -> (node_part, glob)``: ``node_part`` is the
        [N, ...] pytree the engine vmaps over; ``glob`` is broadcast
        read-only into every handler as ``ctx.glob``;
      * handlers emit ``"g:name"`` entries in their events dict
        (per-node update requests; ignored by the stats sink);
      * ``post_step(ctx, state, events) -> state`` runs un-vmapped after
        the node sweep and folds those events into the global part.
    """

    def split(self, state):
        return state, None

    def merge(self, node_part, glob):
        return node_part

    def post_step(self, ctx, state, events):
        del ctx, events
        return state


class Outbox:
    """Append-only per-node message emitter used inside vmapped handlers.

    Every ``send`` records the message lazily; ``finish`` materializes
    the whole batch with ONE stack + ONE compacting gather per field.
    A naive implementation scatters ~14 fields per send — with tens of
    send sites unrolled in a handler chain that dominates the tick
    graph's op count (the engine is op-issue-bound, not FLOP-bound).
    Deferring to finish() collapses S sends × 14 scatters into 14
    stack+gather pairs.

    ``en`` picks whether a send occupies a slot; disabled sends cost a
    lane in the stacked batch but no slot.  Slots beyond capacity are
    dropped (the engine counts the overflow).  The reference equivalent
    is the unbounded sendMessageToUDP path (BaseOverlay.cc:1147).

    A send site may be VECTOR-VALUED: pass ``en`` with shape [B] and the
    other fields with shape [B] (or scalar, broadcast) to emit B
    candidate messages from ONE trace-time call.  This is the op-count
    lever: an unrolled loop of B scalar sends costs B×14 graph nodes,
    a single vector send costs 14.
    """

    def __init__(self, m: int, key_lanes: int, rmax: int):
        self.m = m
        self.key_lanes = key_lanes
        self.rmax = rmax
        self._en = []      # list of [B_i] bool
        self._rows = []    # list of per-send field dicts ([B_i, ...] leaves)

    def send(self, en, t_send, dst, kind, *, key=None, nonce=0, hops=0,
             a=0, b=0, c=0, d=0, nodes=None, size_b=40, stamp=0):
        en = jnp.atleast_1d(jnp.asarray(en))
        bdim = en.shape[0]

        def f(v, dt):
            v = jnp.asarray(v, dt)
            if v.ndim == 0:
                v = jnp.broadcast_to(v, (bdim,))
            return v

        if key is not None:
            key = jnp.asarray(key)
            if key.ndim == 1:
                key = jnp.broadcast_to(key, (bdim,) + key.shape)
        if nodes is not None:
            nodes = jnp.asarray(nodes, I32)
            if nodes.ndim == 1:
                nodes = jnp.broadcast_to(nodes, (bdim,) + nodes.shape)
            if nodes.shape[-1] > self.rmax:
                raise ValueError("node-list payload exceeds RMAX")
        self._en.append(en)
        self._rows.append(dict(
            t_send=f(t_send, I64),
            dst=f(dst, I32),
            kind=f(kind, I32),
            key=key, nonce=f(nonce, I32),
            hops=f(hops, I32),
            a=f(a, I32), b=f(b, I32),
            c=f(c, I32), d=f(d, I32),
            nodes=nodes, size_b=f(size_b, I32),
            stamp=f(stamp, I64)))

    @property
    def cursor(self):
        """Number of enabled sends so far (inspection/debug only)."""
        if not self._en:
            return jnp.int32(0)
        return jnp.sum(jnp.concatenate(self._en).astype(I32))

    def finish(self):
        """Returns (fields dict, valid [M], overflow count)."""
        m = self.m
        zero_key = jnp.zeros((self.key_lanes,), U32)
        no_nodes = jnp.full((self.rmax,), NO_NODE, I32)
        s = sum(int(e.shape[0]) for e in self._en)
        if s == 0:
            fields = dict(
                t_send=jnp.zeros((m,), I64), dst=jnp.zeros((m,), I32),
                kind=jnp.zeros((m,), I32),
                key=jnp.zeros((m, self.key_lanes), U32),
                nonce=jnp.zeros((m,), I32), hops=jnp.zeros((m,), I32),
                a=jnp.zeros((m,), I32), b=jnp.zeros((m,), I32),
                c=jnp.zeros((m,), I32), d=jnp.zeros((m,), I32),
                nodes=jnp.full((m, self.rmax), NO_NODE, I32),
                size_b=jnp.zeros((m,), I32), stamp=jnp.zeros((m,), I64))
            return fields, jnp.zeros((m,), bool), jnp.int32(0)

        en = jnp.concatenate([e.astype(I32) for e in self._en])  # [S]
        # slot of send j = number of enabled sends before it
        slots = jnp.cumsum(en) - en                              # [S]
        # compaction: out[i] = the send occupying slot i.  gather form
        # (argsort of disabled-last order) keeps everything one fused
        # sort instead of S scatters
        order_key = jnp.where(en > 0, slots, s)                  # [S]
        # [S] send-slot argsort, NOT a pool-sized sort ([:m] is a no-op
        # when s <= m)
        src = jnp.argsort(order_key)[:m]  # analysis: allow(sort-call)
        n_sent = jnp.sum(en)

        def pick(name, fill, width=None):
            rows = []
            for e, r in zip(self._en, self._rows):
                v = r[name]
                b = int(e.shape[0])
                if name == "key":
                    v = (jnp.broadcast_to(zero_key, (b, self.key_lanes))
                         if v is None else v)
                elif name == "nodes":
                    if v is None:
                        v = jnp.broadcast_to(no_nodes, (b, self.rmax))
                    elif v.shape[-1] < self.rmax:
                        v = jnp.concatenate([
                            v, jnp.full(v.shape[:-1]
                                        + (self.rmax - v.shape[-1],),
                                        NO_NODE, I32)], axis=-1)
                rows.append(v)
            stacked = jnp.concatenate(rows)                      # [S, ...]
            out = stacked[src]                                   # [S'≤M]
            pad = m - out.shape[0]
            if pad > 0:
                fill_row = jnp.broadcast_to(
                    fill, out.shape[1:]) if out.ndim > 1 else fill
                out = jnp.concatenate([
                    out, jnp.broadcast_to(
                        fill_row, (pad,) + out.shape[1:])])
            return out

        fields = dict(
            t_send=pick("t_send", jnp.int64(0)),
            dst=pick("dst", jnp.int32(0)),
            kind=pick("kind", jnp.int32(0)),
            key=pick("key", jnp.uint32(0)),
            nonce=pick("nonce", jnp.int32(0)),
            hops=pick("hops", jnp.int32(0)),
            a=pick("a", jnp.int32(0)), b=pick("b", jnp.int32(0)),
            c=pick("c", jnp.int32(0)), d=pick("d", jnp.int32(0)),
            nodes=pick("nodes", NO_NODE),
            size_b=pick("size_b", jnp.int32(0)),
            stamp=pick("stamp", jnp.int64(0)))
        valid = jnp.arange(m, dtype=I32) < n_sent
        overflow = jnp.maximum(n_sent - m, 0)
        return fields, valid, overflow


def select_tree(pred, a, b):
    """Predicated pytree merge: where(pred, a, b) with pred broadcast up to
    each leaf's rank (the state-merge step after a conditional handler)."""
    def sel(x, y):
        p = pred
        while p.ndim < x.ndim:
            p = p[..., None]
        return jnp.where(p, x, y)
    return jax.tree.map(sel, a, b)
