"""The simulation engine: event-horizon tick loop over sharded node state.

This replaces the reference's single-threaded OMNeT++ discrete-event kernel
(one `handleMessage` per event) with a batched synchronous design:

  every tick
    1. advance simulated time to the earliest pending event (message
       deliveries, per-node timers, churn) and open a window of
       ``window_ns`` nanoseconds;
    2. group all messages due in the window by destination (R rounds of
       scatter-min selection — zero full-pool sorts; engine/pool.py) and
       run the vmapped per-node logic step — each node consumes up to R
       messages plus its due timers and appends to a bounded outbox;
    3. push the outbox through the analytic underlay delay model and write
       it into free message-pool slots (sort-free cumsum allocation);
    4. apply churn create/kill events as alive-mask flips + state resets;
    5. fold the tick's stat events into global accumulators.

Everything is jit-compiled; `run` wraps the tick in `lax.scan`.  The node
axis of all state arrays can be sharded over a jax Mesh — gathers/scatters
across the pool then ride XLA collectives (see parallel/mesh.py).

Causality: a handler runs at the logical time of the event that triggered
it (the message's deliver time), and everything it emits is timestamped
from that moment — so event chains carry exact per-hop latencies even
though unrelated events inside one window commute.  Within-window ordering
is the one semantic relaxation vs the reference's total event order; shrink
``window_ns`` to tighten it.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial

import jax
import jax.numpy as jnp

from oversim_tpu import churn as churn_mod
from oversim_tpu import stats as stats_mod
from oversim_tpu import telemetry as telemetry_mod
from oversim_tpu.common.malicious import MaliciousParams
from oversim_tpu.core import keys as keys_mod
from oversim_tpu.engine import pool as pool_mod
from oversim_tpu.engine.logic import Ctx, Msg
from oversim_tpu.underlay import simple as underlay_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = pool_mod.T_INF
# gateway.EXT_OUT mirrored here — the engine must not import the gateway
# (layering: gateway builds on engine); consistency pinned by a test
EXT_OUT_KIND = 151


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Engine-level knobs (sizes are static; times in seconds)."""

    window: float = 0.010          # tick window (s)
    inbox_slots: int = 8           # R — msgs consumed per node per tick
    inbox_impl: str = "scatter"    # inbox grouping: "scatter" (zero-sort
                                   # scatter-min rounds, default) |
                                   # "pallas" (fused kernel plane,
                                   # oversim_tpu/kernels/ — also arms the
                                   # fused outbox allocator) | "sort"
                                   # (legacy full-pool sort, ORACLE-ONLY)
    tick_impl: str = "dense"       # node-step execution: "dense" (vmapped
                                   # full-N sweep, the bit-identity
                                   # ORACLE) | "sparse" (active-set plane:
                                   # compact the awake nodes into A dense
                                   # lanes, step only those, scatter the
                                   # results back — tick cost scales with
                                   # traffic, not N)
    active_cap: int = 0            # A — sparse active-set lane count;
                                   # 0 = auto (min(n, max(64, n // 8))).
                                   # Awake nodes past the cap DEFER to
                                   # the next tick (never dropped; see
                                   # _phase_active_compact)
    outbox_slots: int = 16         # MOUT — msgs emitted per node per tick
    pool_factor: int = 8           # P = pool_factor * N message slots
    rmax: int = 16                 # node-list payload width
    transition_time: float = 0.0   # default.ini:491
    measurement_time: float = -1.0  # default.ini:492 (-1 = unbounded)
    # byzantine fault injection (common/malicious.py; default.ini:529-536)
    malicious: MaliciousParams = MaliciousParams()
    # device-resident KPI time-series rings (oversim_tpu/telemetry.py;
    # **.telemetry.* ini keys).  sample_ticks=0 (default) disables them:
    # SimState.telemetry stays None and the tick graph is unchanged.
    telemetry: telemetry_mod.TelemetryParams = telemetry_mod.TelemetryParams()
    # service/gateway plane: EXT_OUT messages addressed to this node slot
    # are HELD in the pool (never inbox-selected) until a host drain
    # frees them — required for window-granular response draining, where
    # the device runs many ticks between drains (oversim_tpu/service/).
    # -1 (default) disables the hold: tick graph unchanged.
    ext_hold_slot: int = -1


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimState:
    t_now: jnp.ndarray        # i64 scalar ns
    tick: jnp.ndarray         # i64 scalar
    rng: jax.Array
    alive: jnp.ndarray        # [N] bool
    node_keys: jnp.ndarray    # [N, KL] u32 — the GlobalNodeList key oracle
    underlay: underlay_mod.UnderlayState
    pool: pool_mod.MsgPool
    churn: churn_mod.ChurnState
    malicious: jnp.ndarray    # [N] bool — attacker flags (GlobalNodeList
                              # malicious-node marks, default.ini:529-536)
    logic: object             # per-node logic state pytree
    stats: dict
    counters: dict            # engine drop/overflow counters
    # telemetry ring buffers (telemetry.TelemetryState) or None when
    # telemetry.sample_ticks == 0 — None is an empty pytree, so the
    # disabled layout is leaf-identical to the pre-telemetry engine
    telemetry: object = None


ENGINE_COUNTERS = ("queue_lost", "bit_error_lost", "dest_unavailable_lost",
                   "partition_lost", "pool_overflow", "outbox_overflow",
                   "inbox_deferred")
# sparse-plane accounting, carried in SimState.counters ONLY when
# tick_impl == "sparse" (the dense SimState layout stays bit-identical
# to the pre-sparse engine): cumulative awake-node and active-inbox-
# destination lane counts per run, plus the count of awake nodes
# deferred past ``active_cap`` (deferral, never loss)
SPARSE_COUNTERS = ("awake_nodes", "active_dst", "active_deferred")


def _dedupe_buffers(state):
    """Copy any state leaf that shares a device buffer with an earlier
    leaf.  ``run_chunk``/``run_until_device`` DONATE the state; XLA
    refuses to donate the same buffer twice, so a logic/churn init that
    assigns one array object to two fields would poison every later
    chunk.  One-time cost at init; no-op for alias-free states."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    seen, out = set(), []
    for leaf in leaves:
        try:
            ptr = leaf.unsafe_buffer_pointer()
        except (AttributeError, ValueError):
            out.append(leaf)
            continue
        if ptr in seen:
            leaf = jnp.array(leaf, copy=True)
        else:
            seen.add(ptr)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


class Simulation:
    """Host-side driver binding logic + underlay + churn params."""

    def __init__(self, logic, churn_params: churn_mod.ChurnParams,
                 underlay_params=None,
                 engine_params: EngineParams | None = None,
                 underlay_module=None):
        # the underlay is a strategy module (init/migrate/send_batch/
        # connection_matrix): underlay.simple (SimpleUnderlay, default)
        # or underlay.inet (InetUnderlay/ReaSEUnderlay router topology)
        self.ul = underlay_module or underlay_mod
        self.logic = logic
        self.cp = churn_params
        self.up = (self.ul.UnderlayParams()
                   if underlay_params is None else underlay_params)
        self.ep = engine_params or EngineParams()
        self.n = churn_params.num_slots
        self.spec = logic.key_spec

    @property
    def counter_names(self) -> tuple:
        """Counter keys carried in SimState.counters for this engine
        config (the sparse plane rides its active-set accounting along;
        the dense layout is untouched)."""
        if self.ep.tick_impl == "sparse":
            return ENGINE_COUNTERS + SPARSE_COUNTERS
        return ENGINE_COUNTERS

    @property
    def acap(self) -> int:
        """A — static sparse active-set capacity (lanes per tick).
        ``active_cap=0`` auto-sizes: full-N at small n (bit-identity is
        then unconditional), N/8 once n outgrows 8*64."""
        if self.ep.active_cap > 0:
            return min(self.ep.active_cap, self.n)
        return min(self.n, max(64, self.n // 8))

    # -- init ---------------------------------------------------------------

    def init(self, seed: int = 1, ov=None) -> SimState:
        return _dedupe_buffers(
            self.init_from_rng(jax.random.PRNGKey(seed), ov=ov))

    def init_from_rng(self, rng: jax.Array, ov=None) -> SimState:
        """Pure-JAX init from an explicit PRNG key (vmappable — the
        campaign runner vmaps this over per-replica folded keys).  ``ov``
        is an optional {dotted-name: scalar} sweep-override dict (values
        may be traced); ``None`` reproduces ``init(seed)`` bit-exactly.
        NOTE: no ``_dedupe_buffers`` here — under a trace there are no
        device buffers to compare; callers holding concrete outputs
        (``init``, campaign stacked init) apply it host-side."""
        (r_keys, r_ul, r_churn, r_logic, r_run,
         r_mal) = jax.random.split(rng, 6)
        n = self.n
        life_mean = None if ov is None else ov.get("churn.lifetimeMean")
        node_keys = keys_mod.random_keys(r_keys, (n,), self.spec)
        stats = stats_mod.init_stats(self.logic.stat_spec())
        return SimState(
            t_now=jnp.int64(0),
            tick=jnp.int64(0),
            rng=r_run,
            alive=jnp.zeros((n,), bool),
            node_keys=node_keys,
            underlay=self.ul.init(r_ul, n, self.up),
            pool=pool_mod.empty(self.ep.pool_factor * n, self.spec.lanes,
                                self.ep.rmax),
            churn=churn_mod.init(r_churn, self.cp, life_mean=life_mean),
            malicious=(jax.random.uniform(r_mal, (n,))
                       < self.ep.malicious.probability),
            logic=self.logic.init(r_logic, n),
            stats=stats,
            counters={name: jnp.zeros((), I64)
                      for name in self.counter_names},
            telemetry=telemetry_mod.init(
                stats, self.counter_names, self.ep.telemetry,
                app=getattr(self.logic, "app", None)),
        )

    # -- one tick -----------------------------------------------------------
    #
    # The tick is split into PHASE methods (horizon / churn /
    # inbox_select / inbox_gather / node_step / alloc_stats) so
    # oversim_tpu/profiling.py can jit and time each phase separately
    # under OVERSIM_PROFILE=1.  ``step`` composes them; under one jit the
    # split is invisible to XLA (same fused graph as the old monolithic
    # step).

    def _phase_horizon(self, s: SimState, *, ov=None):
        """Phase 1/5: advance to the event horizon + per-tick rng split."""
        w = None if ov is None else ov.get("engine.window")
        if w is None:
            window_ns = jnp.int64(int(self.ep.window * NS))
        else:
            # traced sweep value (campaign grid over the tick window)
            window_ns = (jnp.asarray(w) * NS).astype(I64)
        t_next = jnp.minimum(
            pool_mod.next_deliver_time(s.pool),
            jnp.minimum(
                jnp.min(jnp.where(s.alive, self.logic.next_event(s.logic),
                                  T_INF)),
                churn_mod.next_event(s.churn)))
        t_next = jnp.maximum(t_next, s.t_now)
        # with no pending events anywhere t_next is T_INF; keep t_end there
        # too so T_INF-parked timers/churn sentinels never satisfy `< t_end`
        t_end = jnp.where(t_next >= T_INF, t_next, t_next + window_ns)
        rngs = jax.random.split(s.rng, 7)
        return t_next, t_end, rngs

    def _phase_churn(self, s: SimState, t_next, t_end, r_churn, r_keys,
                     r_reset, r_mig, *, ov=None):
        """Phase 2/5: churn events (incl. graceful-leave grace windows)."""
        n, cp, up = self.n, self.cp, self.up
        logic = self.logic
        life_mean = None if ov is None else ov.get("churn.lifetimeMean")
        churn_state, created, killed, _leaving = churn_mod.step(
            s.churn, cp, s.alive, t_next, t_end, r_churn,
            life_mean=life_mean)
        alive = (s.alive | created) & ~killed
        # pre-killed nodes run until their final kill but leave the
        # bootstrap oracle immediately (preKillNode removePeer,
        # SimpleUnderlayConfigurator.cc:350)
        pre_killed = churn_state.t_dead < T_INF
        # created slots get fresh nodeIds (BaseOverlay::join draws a random
        # nodeId, BaseOverlay.cc:597-608) and fresh coordinates — unless
        # rejoin_context keeps the slot's previous identity
        # (GlobalNodeList::getContext/restoreContext, BaseOverlay.cc:
        # 823-831: the rejoining peer reclaims its nodeId + flags)
        if cp.rejoin_context:
            node_keys = s.node_keys
        else:
            node_keys = jnp.where(
                created[:, None],
                keys_mod.random_keys(r_keys, (n,), self.spec),
                s.node_keys)
        ul_state = self.ul.migrate(s.underlay, created, r_mig, up)
        # clear both created and killed slots; created ones schedule a join
        logic_state = logic.reset(s.logic, created | killed, created, t_next,
                                  r_reset)
        return churn_state, alive, pre_killed, node_keys, ul_state, logic_state

    def _hold_mask(self, s: SimState):
        """[P] hold mask for the service plane's parked EXT_OUT
        responses, or None when ``ext_hold_slot`` is disarmed."""
        if self.ep.ext_hold_slot < 0:
            return None
        return ((s.pool.kind == EXT_OUT_KIND)
                & (s.pool.dst == self.ep.ext_hold_slot))

    def _phase_inbox_select(self, s: SimState, t_end, alive):
        """Phase 3a: pick each destination's R earliest due messages
        (scatter-min rounds by default — zero full-pool sorts; see
        engine/pool.py and ``EngineParams.inbox_impl``)."""
        return pool_mod.build_inbox(
            s.pool, self.n, self.ep.inbox_slots, t_end, alive,
            impl=self.ep.inbox_impl, hold=self._hold_mask(s))

    def _msgs_from_block(self, s: SimState, t_next, inbox, blk,
                         t_deliver=None, stamp=None):
        """[N, R] index table + gathered [N, R, W] payload block → the
        Msg view (shared by the lax gather and the fused kernel path;
        the two i64 fields are gathered here off the index table — the
        Pallas core has no 64-bit lanes — unless the caller already
        holds them: the sharded tick (parallel/shard_tick.py) passes
        its owner-gathered [N, R] ``t_deliver``/``stamp``, since the
        local pool tile cannot be indexed by global inbox entries)."""
        safe = jnp.maximum(inbox, 0)
        if t_deliver is None:
            t_deliver = s.pool.t_deliver[safe]
        if stamp is None:
            stamp = s.pool.stamp[safe]
        ncol = len(pool_mod.SCAL_COLS)
        col = lambda name: blk[..., pool_mod._COL[name]]  # noqa: E731
        return Msg(
            valid=inbox >= 0,
            t_deliver=jnp.maximum(t_deliver, t_next),
            src=col("src"), dst=col("dst"),
            kind=col("kind"),
            key=jax.lax.bitcast_convert_type(
                blk[..., ncol:ncol + s.pool.kl], jnp.uint32),
            nonce=col("nonce"), hops=col("hops"),
            a=col("a"), b=col("b"),
            c=col("c"), d=col("d"),
            nodes=blk[..., ncol + s.pool.kl:], size_b=col("size_b"),
            stamp=stamp)

    def _phase_inbox_gather(self, s: SimState, t_next, inbox):
        """Phase 3b: ONE gather of the packed [P, W] block for all the
        32-bit fields of the selected messages (pool.py packed layout,
        PERFORMANCE.md lever #3) into the [N, R] Msg view."""
        blk = s.pool.blk[jnp.maximum(inbox, 0)]       # [N, R, W]
        return self._msgs_from_block(s, t_next, inbox, blk)

    def _phase_inbox_fused(self, s: SimState, t_next, t_end, alive):
        """Phase 3 (kernel plane): selection AND the [P, W] payload
        gather in one Pallas kernel (oversim_tpu/kernels/inbox.py) —
        bit-identical to select+gather, pinned in tests/test_kernels.py
        under interpret mode."""
        from oversim_tpu import kernels
        inbox, delivered, to_dead, gblk = kernels.inbox.fused_inbox(
            s.pool, self.n, self.ep.inbox_slots, t_end, alive,
            hold=self._hold_mask(s))
        return (self._msgs_from_block(s, t_next, inbox, gblk),
                delivered, to_dead)

    def _phase_inbox(self, s: SimState, t_next, t_end, alive):
        """Phase 3: inbox select + gather composed (profiling.py times
        the two halves separately; ``inbox_impl="pallas"`` fuses them
        into one kernel and is timed as a single ``inbox_fused``
        phase)."""
        if self.ep.inbox_impl == "pallas":
            return self._phase_inbox_fused(s, t_next, t_end, alive)
        inbox, delivered, to_dead = self._phase_inbox_select(s, t_end, alive)
        msgs = self._phase_inbox_gather(s, t_next, inbox)
        return msgs, delivered, to_dead

    def _make_ctx(self, s: SimState, t_next, t_end, alive, pre_killed,
                  churn_state, node_keys, ul_state, logic_state, *, ov=None):
        """Tick context shared by the dense and sparse node-step phases.

        The Ctx is always FULL-WIDTH — node handlers index the ready/
        bootstrap vectors by true node id, so the sparse path can
        broadcast the same ctx over its compacted lanes.  Returns
        ``(ctx, node_part, glob, measuring)``."""
        n, ep, up, cp = self.n, self.ep, self.up, self.cp
        logic = self.logic
        ready = logic.ready_mask(logic_state) & alive & ~pre_killed
        ready_cumsum = jnp.cumsum(ready.astype(I32))
        measure_start = jnp.int64(
            int((cp.init_finished_time + ep.transition_time) * NS))
        # measurement window: [start, start + measurementTime), unbounded
        # when measurement_time < 0 (default.ini:492)
        measuring = t_next >= measure_start
        if ep.measurement_time >= 0:
            measuring &= t_next < measure_start + jnp.int64(
                int(ep.measurement_time * NS))
        node_part, glob = (logic.split(logic_state)
                           if hasattr(logic, "split") else (logic_state, None))
        # partition support: per-type ready cumsums + live conn matrix
        # (GlobalNodeList per-type bootstrap vectors + connectionMatrix)
        if up.num_node_types > 1:
            conn = self.ul.connection_matrix(up, t_next)
            tmask = (ul_state.node_type[None, :]
                     == jnp.arange(up.num_node_types)[:, None])
            ready_cum_t = jnp.cumsum(
                (ready[None, :] & tmask).astype(I32), axis=1)
            part_kw = dict(node_type=ul_state.node_type, conn=conn,
                           ready_cum_t=ready_cum_t)
        else:
            part_kw = {}
        ctx = Ctx(t_start=t_next, t_end=t_end, keys=node_keys, alive=alive,
                  ready=ready, ready_cumsum=ready_cumsum,
                  n_ready=ready_cumsum[-1], measuring=measuring, glob=glob,
                  leaving=pre_killed & alive,
                  graceful=pre_killed & alive & churn_state.graceful,
                  malicious=s.malicious, ov=ov,
                  **part_kw)
        return ctx, node_part, glob, measuring

    def _node_rngs(self, r_nodes, tick, idx):
        """Per-node rng streams: fold tick, then node index.  The sparse
        path folds the TRUE node index of each compacted lane (same
        dtype as the dense ``jnp.arange``), so the streams are
        bit-identical between tick impls."""
        return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            jax.random.fold_in(r_nodes, tick), idx)

    def _phase_node_step(self, s: SimState, t_next, t_end, alive, pre_killed,
                         churn_state, node_keys, ul_state, logic_state, msgs,
                         r_nodes, *, ov=None):
        """Phase 4/5: tick context + the vmapped per-node logic step."""
        n = self.n
        logic = self.logic
        ctx, node_part, glob, measuring = self._make_ctx(
            s, t_next, t_end, alive, pre_killed, churn_state, node_keys,
            ul_state, logic_state, ov=ov)
        node_rngs = self._node_rngs(r_nodes, s.tick, jnp.arange(n))
        node_idx = jnp.arange(n, dtype=I32)

        node_part, out_fields, out_valid, out_overflow, events = jax.vmap(
            self._node_step, in_axes=(None, 0, 0, 0, 0))(
                ctx, node_part, msgs, node_rngs, node_idx)
        logic_state = (logic.merge(node_part, glob)
                       if hasattr(logic, "merge") else node_part)
        if hasattr(logic, "post_step"):
            logic_state = logic.post_step(ctx, logic_state, events)
        return (logic_state, out_fields, out_valid, out_overflow, events,
                measuring)

    # -- sparse active-set plane (tick_impl="sparse") -----------------------

    def _phase_inbox_select_sparse(self, s: SimState, t_end, alive):
        """Sparse phase 3: selection WITHOUT the full [N, R, W] payload
        gather — the sparse step gathers only the A compacted rows.
        Under ``inbox_impl="pallas"`` the fused kernel runs in
        select-only mode (occupancy-bounded walk, no gather pass)."""
        if self.ep.inbox_impl == "pallas":
            from oversim_tpu import kernels
            return kernels.inbox.fused_select(
                s.pool, self.n, self.ep.inbox_slots, t_end, alive,
                hold=self._hold_mask(s))
        return self._phase_inbox_select(s, t_end, alive)

    def _phase_active_compact(self, s: SimState, t_end, alive, pre_killed,
                              logic_state, inbox, delivered):
        """Sparse phase 4a: compact the awake node set into A dense
        lanes (the ``pool.alloc`` cumsum-compaction idiom; the kernel
        plane uses the serial-counting compaction in
        kernels/outbox.py).

        A node is awake when it has inbox traffic this window
        (``inbox[:, 0] >= 0`` — the selectors fill slot 0 first), a due
        local timer (``logic.next_event < t_end`` — the same oracle the
        event horizon trusts), or churn touched its slot this tick
        (created, killed, or pre-killed).  Every other node is an exact
        fixed point of ``_node_step``, pinned bit-for-bit against the
        dense oracle by tests/test_zz_sparse.py and
        scripts/sparse_gate.py.

        Awake nodes past the cap DEFER, never drop: their timers stay
        due, their selected messages revert to "not delivered" (the
        R-overflow retention mechanism), and the compaction walk starts
        at a per-tick rotating offset so persistent overload
        round-robins the active set instead of starving the tail.
        Returns ``(act [A] i32 lane->node map (sentinel n), delivered
        [P] bool trimmed to stepped destinations, active
        (awake, active_dst, deferred) i64 tallies)``."""
        n, cap = self.n, self.acap
        has_msg = inbox[:, 0] >= 0
        timer_due = alive & (self.logic.next_event(logic_state) < t_end)
        churned = (alive ^ s.alive) | (pre_killed & alive)
        awake = has_msg | timer_due | churned
        n_awake = jnp.sum(awake.astype(I32))
        off = (s.tick % n).astype(I32)
        perm = (jnp.arange(n, dtype=I32) + off) % n
        aw_r = awake[perm]
        if self.ep.inbox_impl == "pallas":
            from oversim_tpu import kernels
            act, _cnt = kernels.outbox.compact_indices(aw_r, perm, cap,
                                                       sentinel=n)
        else:
            aw_i = aw_r.astype(I32)
            rank = jnp.cumsum(aw_i) - aw_i
            act = jnp.full((cap,), n, I32).at[
                jnp.where(aw_r & (rank < cap), rank, cap)].set(
                    perm, mode="drop")
        taken = jnp.zeros((n,), bool).at[act].set(True, mode="drop")
        # messages selected for a deferred destination stay pooled with
        # their original timestamps and are re-offered next tick
        delivered = delivered & taken[jnp.clip(s.pool.dst, 0, n - 1)]
        active = (n_awake.astype(I64),
                  jnp.sum(has_msg.astype(I32)).astype(I64),
                  (n_awake - jnp.minimum(n_awake, cap)).astype(I64))
        return act, delivered, active

    def _phase_sparse_step(self, s: SimState, t_next, t_end, alive,
                           pre_killed, churn_state, node_keys, ul_state,
                           logic_state, inbox, act, r_nodes, *, ov=None):
        """Sparse phase 4b: the vmapped logic step over the COMPACTED
        [A] lane set only, scattered back into full-width state.

        Sentinel lanes (``act == n``) clamp to node n-1 for the compute
        and drop at every scatter-back; the outbox/event bases are
        zeros, which is write-equivalent to the dense path's idle-lane
        junk because every downstream consumer (send_batch, alloc,
        stats.record) is mask-gated."""
        n = self.n
        logic = self.logic
        ctx, node_part, glob, measuring = self._make_ctx(
            s, t_next, t_end, alive, pre_killed, churn_state, node_keys,
            ul_state, logic_state, ov=ov)
        act_c = jnp.minimum(act, n - 1)
        lane_ok = act < n
        inbox_act = jnp.where(lane_ok[:, None], inbox[act_c], -1)
        gblk = s.pool.blk[jnp.maximum(inbox_act, 0)]       # [A, R, W]
        msgs = self._msgs_from_block(s, t_next, inbox_act, gblk)
        part_act = jax.tree_util.tree_map(lambda x: x[act_c], node_part)
        node_rngs = self._node_rngs(r_nodes, s.tick, act_c.astype(jnp.int_))

        part_act, out_f, out_v, out_o, ev = jax.vmap(
            self._node_step, in_axes=(None, 0, 0, 0, 0))(
                ctx, part_act, msgs, node_rngs, act_c)

        scat = lambda base, upd: base.at[act].set(upd, mode="drop")  # noqa: E731
        node_part = jax.tree_util.tree_map(scat, node_part, part_act)
        full = lambda x: jnp.zeros((n,) + x.shape[1:], x.dtype)  # noqa: E731
        out_fields = jax.tree_util.tree_map(
            lambda x: scat(full(x), x), out_f)
        out_valid = scat(full(out_v), out_v)
        out_overflow = scat(full(out_o), out_o)
        events = jax.tree_util.tree_map(lambda x: scat(full(x), x), ev)

        logic_state = (logic.merge(node_part, glob)
                       if hasattr(logic, "merge") else node_part)
        if hasattr(logic, "post_step"):
            logic_state = logic.post_step(ctx, logic_state, events)
        return (logic_state, out_fields, out_valid, out_overflow, events,
                measuring)

    def _phase_alloc_stats(self, s: SimState, t_end, rng, r_send, alive,
                           pre_killed, node_keys, ul_state, churn_state,
                           logic_state, delivered, to_dead, out_fields,
                           out_valid, out_overflow, events, measuring, *,
                           active=None):
        """Phase 5/5: free delivered slots, send the outbox through the
        underlay into free pool slots (sort-free alloc), fold stats."""
        ep, up = self.ep, self.up
        node_idx = jnp.arange(self.n, dtype=I32)
        new_pool = pool_mod.free(s.pool, delivered | to_dead)
        t_del, ok, ul_state, drops = self.ul.send_batch(
            ul_state, up, r_send, jnp.broadcast_to(node_idx[:, None],
                                                 out_fields["dst"].shape),
            out_fields["dst"], out_fields["size_b"], out_fields["t_send"],
            out_valid, alive, kind=out_fields["kind"])
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in out_fields.items()
                if k != "t_send"}
        flat["t_deliver"] = t_del.reshape(-1)
        flat["src"] = jnp.broadcast_to(node_idx[:, None],
                                       out_valid.shape).reshape(-1)
        new_pool, pool_overflow = pool_mod.alloc(
            new_pool, flat, (out_valid & ok).reshape(-1),
            impl=("pallas" if self.ep.inbox_impl == "pallas"
                  else "scatter"))

        # stats
        new_stats = stats_mod.record(s.stats, events, measuring)
        counters = dict(s.counters)
        counters["queue_lost"] += drops["queue_lost"]
        counters["bit_error_lost"] += drops["bit_error_lost"]
        counters["partition_lost"] += drops["partition_lost"]
        counters["dest_unavailable_lost"] += (
            drops["dest_unavailable_lost"] + jnp.sum(to_dead))
        counters["pool_overflow"] += pool_overflow
        counters["outbox_overflow"] += jnp.sum(out_overflow)
        # high-water mark, not a sum: peak count of messages backpressured
        # behind full inboxes in any one tick (a per-tick sum would count
        # each waiting message once per tick it waits; a point-in-time
        # gauge is noise at readout — the peak is stable and still proves
        # whether the deferral path ever engaged)
        counters["inbox_deferred"] = jnp.maximum(
            counters["inbox_deferred"],
            (jnp.sum(s.pool.valid & (s.pool.t_deliver < t_end)) -
             jnp.sum(delivered | to_dead)).astype(jnp.int64))
        if active is not None:
            # sparse-plane accounting (tick_impl="sparse" only): lane
            # tallies from _phase_active_compact — cumulative like the
            # loss counters, so the telemetry rings carry the series
            n_awake, active_dst, n_deferred = active
            counters["awake_nodes"] += n_awake
            counters["active_dst"] += active_dst
            counters["active_deferred"] += n_deferred

        # telemetry sample point (telemetry.py): END-of-tick snapshot of
        # the accumulators into the ring buffers, gated on the sampling
        # cadence via an out-of-bounds-dropped scatter index — no rng,
        # no sorts, and every non-telemetry leaf above is untouched
        # (the tests/test_zz_telemetry_identity.py bit-identity pin)
        tel = telemetry_mod.fold(
            s.telemetry, self.ep.telemetry, t_end=t_end, tick=s.tick + 1,
            alive=alive, stats=new_stats, counters=counters)

        # advance to the window END: anything generated during this tick
        # with a due time inside the window is delivered next tick with
        # its original timestamp (build_inbox consumes `t_deliver <
        # t_end` regardless of the past), so no event is lost and no
        # latency is distorted — but the engine is guaranteed ≥ one full
        # window of progress per tick.  Returning t_next instead lets
        # sub-window message delays drag the horizon back and collapses
        # the batching (observed: 6-7x more ticks than windows).
        return SimState(t_now=t_end, tick=s.tick + 1, rng=rng, alive=alive,
                        node_keys=node_keys, underlay=ul_state, pool=new_pool,
                        churn=churn_state, malicious=s.malicious,
                        logic=logic_state, stats=new_stats,
                        counters=counters, telemetry=tel)

    def step(self, s: SimState, *, ov=None) -> SimState:
        """One tick: the five phases composed (see the phase methods).

        ``ov`` — optional {dotted-name: scalar} sweep-override dict
        (values may be traced; see oversim_tpu/campaign/).  Recognised
        keys: ``engine.window``, ``churn.lifetimeMean``, plus any
        ``app.*`` key a handler reads via ``Ctx.ov_get``.  ``None``
        (the default everywhere) keeps the trace bit-identical to the
        pre-campaign engine."""
        if self.ep.tick_impl == "sparse":
            return self._step_sparse(s, ov=ov)
        t_next, t_end, rngs = self._phase_horizon(s, ov=ov)
        (rng, r_churn, r_keys, r_reset, r_nodes, r_mig, r_send) = rngs
        (churn_state, alive, pre_killed, node_keys, ul_state,
         logic_state) = self._phase_churn(s, t_next, t_end, r_churn, r_keys,
                                          r_reset, r_mig, ov=ov)
        msgs, delivered, to_dead = self._phase_inbox(s, t_next, t_end, alive)
        (logic_state, out_fields, out_valid, out_overflow, events,
         measuring) = self._phase_node_step(
            s, t_next, t_end, alive, pre_killed, churn_state, node_keys,
            ul_state, logic_state, msgs, r_nodes, ov=ov)
        return self._phase_alloc_stats(
            s, t_end, rng, r_send, alive, pre_killed, node_keys, ul_state,
            churn_state, logic_state, delivered, to_dead, out_fields,
            out_valid, out_overflow, events, measuring)

    def _step_sparse(self, s: SimState, *, ov=None) -> SimState:
        """One sparse tick: horizon/churn/alloc phases are shared with
        the dense oracle; the inbox skips the full-width gather, the
        awake set compacts into A lanes, and only those lanes run
        ``_node_step``.  Bit-identical to ``step`` whenever the awake
        count fits ``active_cap`` (unconditional at the auto cap for
        n <= 64); beyond the cap, deterministic rotation-fair
        deferral."""
        t_next, t_end, rngs = self._phase_horizon(s, ov=ov)
        (rng, r_churn, r_keys, r_reset, r_nodes, r_mig, r_send) = rngs
        (churn_state, alive, pre_killed, node_keys, ul_state,
         logic_state) = self._phase_churn(s, t_next, t_end, r_churn, r_keys,
                                          r_reset, r_mig, ov=ov)
        inbox, delivered, to_dead = self._phase_inbox_select_sparse(
            s, t_end, alive)
        act, delivered, active = self._phase_active_compact(
            s, t_end, alive, pre_killed, logic_state, inbox, delivered)
        (logic_state, out_fields, out_valid, out_overflow, events,
         measuring) = self._phase_sparse_step(
            s, t_next, t_end, alive, pre_killed, churn_state, node_keys,
            ul_state, logic_state, inbox, act, r_nodes, ov=ov)
        return self._phase_alloc_stats(
            s, t_end, rng, r_send, alive, pre_killed, node_keys, ul_state,
            churn_state, logic_state, delivered, to_dead, out_fields,
            out_valid, out_overflow, events, measuring, active=active)

    def _node_step(self, ctx, state_n, msgs_n, rng_n, node_idx):
        """Single-node step (vmapped): logic consumes inbox + timers."""
        state_n, outbox, events = self.logic.step(
            ctx, state_n, msgs_n, rng_n, node_idx,
            outbox_slots=self.ep.outbox_slots, rmax=self.ep.rmax)
        fields, valid, overflow = outbox.finish()
        return state_n, fields, valid, overflow, events

    # -- run ----------------------------------------------------------------

    @partial(jax.jit, static_argnames=("self", "n_ticks"),
             donate_argnums=(1,))
    def run_chunk(self, s: SimState, n_ticks: int) -> SimState:
        """One fused dispatch of ``n_ticks`` ticks.

        The incoming SimState is DONATED: XLA writes the output state
        into the input's buffers instead of round-tripping the whole
        state through fresh HBM allocations every chunk
        (parallel/mesh.py already donated; this is the default
        single-chip path).  Callers must rebind
        (``s = sim.run_chunk(s, k)``) and never touch the old reference
        afterwards.
        """
        def body(carry, _):
            return self.step(carry), None
        s, _ = jax.lax.scan(body, s, None, length=n_ticks)
        return s

    def run_until(self, s: SimState, t_sim: float, chunk: int = 256,
                  check_invariants: bool | None = None) -> SimState:
        """Host loop: run chunks until simulated time passes t_sim seconds.

        One device→host sync (``t_now``) per chunk; use
        ``run_until_device`` for the sync-free single-dispatch loop.
        ``check_invariants`` (or OVERSIM_DEBUG_INVARIANTS=1) runs the
        host-side structural validator between chunks — the reference's
        debug-build assert tier (SURVEY §5; oversim_tpu/invariants.py).
        """
        if check_invariants is None:
            check_invariants = bool(os.environ.get(
                "OVERSIM_DEBUG_INVARIANTS"))
        target = int(t_sim * NS)
        while int(s.t_now) < target:  # analysis: allow(device-sync)
            s = self.run_chunk(s, chunk)
            if check_invariants:
                from oversim_tpu import invariants as inv_mod
                inv_mod.check_state(s)
        return s

    @partial(jax.jit, static_argnames=("self", "chunk"), donate_argnums=(1,))
    def _run_until_device(self, s: SimState, target, chunk: int) -> SimState:
        def cond(carry):
            return carry.t_now < target

        def body(carry):
            def sbody(c, _):
                return self.step(c), None
            c, _ = jax.lax.scan(sbody, carry, None, length=chunk)
            return c

        return jax.lax.while_loop(cond, body, s)

    def run_until_device(self, s: SimState, t_sim: float,
                         chunk: int = 256) -> SimState:
        """Device-resident run loop: the whole run is ONE dispatch.

        Wraps the ``chunk``-tick scan in a ``lax.while_loop`` guarded by
        ``t_now < target`` so the host never reads ``t_now`` back
        between chunks (``run_until`` pays one device→host sync per
        chunk).  Both advance in whole chunks until ``t_now >= target``,
        so results are bit-identical to ``run_until`` at equal ``chunk``.
        The state is donated, like ``run_chunk``.  Keep ``run_until``
        for invariant-checking or per-chunk host work.
        """
        target = jnp.int64(int(t_sim * NS))
        return self._run_until_device(s, target, chunk)

    # host-side end-of-run report — syncs by design
    def summary(self, s: SimState) -> dict:  # analysis: allow(host-float, device-sync)
        out = stats_mod.summarize(s.stats)
        out["_engine"] = {k: int(v) for k, v in s.counters.items()}
        out["_t_sim"] = float(s.t_now) / NS
        out["_ticks"] = int(s.tick)
        out["_alive"] = int(jnp.sum(s.alive))
        return out
