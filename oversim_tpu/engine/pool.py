"""Global bounded message pool — the TPU replacement for the future-event set.

The reference delivers packets by inserting them into OMNeT++'s
future-event set one at a time (`sendDirect`, SimpleUDP.cc:418).  Here all
in-flight packets live in one structure-of-arrays pool of P slots; each
simulation tick:

  * the due messages (deliver time inside the tick window) are grouped by
    destination into a fixed-width inbox index table.  The default
    ``scatter`` implementation runs R rounds of deterministic scatter-min
    selection: each round one [P]→[N] scatter-min on t_deliver picks every
    destination's earliest remaining due message (a second scatter-min on
    the pool index breaks t_deliver ties exactly like the old stable
    sort), the winners are masked out, and R rounds fill the [N, R] table
    in O(R·P) work — ZERO full-pool sorts in the tick graph
    (tests/test_engine.py pins sort and scatter counts on the HLO).  The
    legacy ``sort`` implementation (one lexicographic (dst, t_deliver)
    ``lax.sort``, O(P log P)) stays selectable via
    ``EngineParams.inbox_impl`` / the ``**.inboxImpl`` ini key; both
    produce bit-identical inboxes (identity tests in tests/test_engine.py);
  * delivered slots are freed, and the tick's outbox is written into free
    slots with a sort-free cumsum allocation (prefix sum over the free
    mask + one scatter).

Messages that overflow a node's R inbox slots in one window simply stay in
the pool and deliver next tick (receive-queue backpressure).  Pool
exhaustion is counted, never silent (SURVEY.md §7.2 "no silent truncation").

A message carries: src/dst slot, kind, a key, a nonce, hop count, four i32
payload scalars, and a node-list payload of RMAX slot indices (the
FindNodeResponse closest-node set, CommonMessages.msg:246-262, travels as
slot indices — node keys are recoverable from the global key table).

Packed layout (PERFORMANCE.md lever #3): every 32-bit field — the ten
i32 scalars, the key lanes (bitcast u32↔i32) and the RMAX node list —
lives in ONE [P, W] i32 block, so the per-tick inbox build is one gather
and the outbox allocation one scatter, instead of 12+ of each
field-by-field.  Only the two i64 fields (t_deliver, stamp) and the
valid mask stay separate; per-field access is provided by zero-copy
column-slice properties, keeping the old field API for host-side readers
(gateway drain, xmlrpcif) and the Msg view builder.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

# column layout of the packed block: scalars first, then key lanes, then
# the node list
SCAL_COLS = ("src", "dst", "kind", "nonce", "hops", "a", "b", "c", "d",
             "size_b")
_COL = {name: i for i, name in enumerate(SCAL_COLS)}

FIELDS = ("t_deliver", "src", "dst", "kind", "key", "nonce", "hops",
          "a", "b", "c", "d", "nodes", "size_b", "stamp")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MsgPool:
    """Packed pool: [P] masks/times + one [P, W] i32 payload block."""

    valid: jnp.ndarray      # [P] bool
    t_deliver: jnp.ndarray  # [P] i64 ns
    stamp: jnp.ndarray      # [P] i64 ns timestamp payload (send time for
                            # app-latency stats; reference keeps simTime()
                            # in message fields, KBRTestApp.cc)
    blk: jnp.ndarray        # [P, W] i32 — SCAL_COLS + key lanes + nodes
    kl: int = dataclasses.field(metadata=dict(static=True), default=5)
    rmax: int = dataclasses.field(metadata=dict(static=True), default=8)

    @property
    def capacity(self):
        return self.valid.shape[0]

    # -- zero-copy column views (old field API) --------------------------
    @property
    def src(self):
        return self.blk[:, _COL["src"]]

    @property
    def dst(self):
        return self.blk[:, _COL["dst"]]

    @property
    def kind(self):
        return self.blk[:, _COL["kind"]]

    @property
    def nonce(self):
        return self.blk[:, _COL["nonce"]]

    @property
    def hops(self):
        return self.blk[:, _COL["hops"]]

    @property
    def a(self):
        return self.blk[:, _COL["a"]]

    @property
    def b(self):
        return self.blk[:, _COL["b"]]

    @property
    def c(self):
        return self.blk[:, _COL["c"]]

    @property
    def d(self):
        return self.blk[:, _COL["d"]]

    @property
    def size_b(self):
        return self.blk[:, _COL["size_b"]]

    @property
    def key(self):
        s = len(SCAL_COLS)
        return jax.lax.bitcast_convert_type(
            self.blk[..., s:s + self.kl], U32)

    @property
    def nodes(self):
        return self.blk[..., len(SCAL_COLS) + self.kl:]


def pack_block(out: dict, kl: int, rmax: int):
    """Pack a field dict ([Q]-leading arrays, the Outbox.finish() /
    gateway-inject format) into the [Q, W] i32 block."""
    cols = [jnp.asarray(out[name], I32)[:, None] for name in SCAL_COLS]
    cols.append(jax.lax.bitcast_convert_type(
        jnp.asarray(out["key"], U32), I32).reshape(-1, kl))
    cols.append(jnp.asarray(out["nodes"], I32).reshape(-1, rmax))
    return jnp.concatenate(cols, axis=1)


def empty(p: int, key_lanes: int, rmax: int) -> MsgPool:
    w = len(SCAL_COLS) + key_lanes + rmax
    blk = jnp.zeros((p, w), I32)
    blk = blk.at[:, _COL["src"]].set(NO_NODE)
    blk = blk.at[:, _COL["dst"]].set(NO_NODE)
    blk = blk.at[:, len(SCAL_COLS) + key_lanes:].set(NO_NODE)
    return MsgPool(
        valid=jnp.zeros((p,), bool),
        t_deliver=jnp.full((p,), T_INF, I64),
        stamp=jnp.zeros((p,), I64),
        blk=blk,
        kl=key_lanes,
        rmax=rmax,
    )


def next_deliver_time(pool: MsgPool):
    """Earliest pending deliver time (i64; T_INF when pool empty)."""
    return jnp.min(jnp.where(pool.valid, pool.t_deliver, T_INF))


def _due_masks(pool: MsgPool, n: int, t_end, alive, hold=None):
    """(due, to_dead) masks shared by both inbox implementations.

    ``hold`` ([P] bool or None) marks messages that are NEVER due: the
    service/gateway plane parks ``EXT_OUT`` responses in the pool until
    a host drain frees them, instead of having the engine re-deliver
    (and thereby consume) them on the next tick."""
    due = pool.valid & (pool.t_deliver < t_end)
    if hold is not None:
        due = due & ~hold
    to_dead = due & ~alive[jnp.clip(pool.dst, 0, n - 1)]
    return due & ~to_dead, to_dead


def build_inbox_sort(pool: MsgPool, n: int, r: int, t_end, alive,
                     hold=None):  # analysis: allow(sort-call)
    """Legacy inbox grouping: one lexicographic (dst, t_deliver) full-pool
    stable sort, O(P log P).  Kept selectable (``inbox_impl="sort"``) so
    the scatter path stays identity-testable against it."""
    p = pool.capacity
    due, to_dead = _due_masks(pool, n, t_end, alive, hold)

    dst_k = jnp.where(due, pool.dst, n).astype(I32)
    t_k = jnp.where(due, pool.t_deliver, T_INF)
    idx = jnp.arange(p, dtype=I32)
    dst_s, _, idx_s = jax.lax.sort((dst_k, t_k, idx), dimension=0, num_keys=2)

    # rank of each message within its destination group
    first = jnp.searchsorted(dst_s, dst_s, side="left").astype(I32)
    rank = jnp.arange(p, dtype=I32) - first
    take = (dst_s < n) & (rank < r)

    rows = jnp.where(take, dst_s, n)  # row n is out-of-bounds -> dropped
    inbox = jnp.full((n, r), NO_NODE, I32).at[rows, jnp.minimum(rank, r - 1)].set(
        idx_s, mode="drop")
    delivered = jnp.zeros((p,), bool).at[idx_s].set(take)
    return inbox, delivered, to_dead


def build_inbox_scatter(pool: MsgPool, n: int, r: int, t_end, alive,
                        hold=None, *, axis_name=None, base=0, p_total=None):
    """Zero-sort inbox grouping: R rounds of deterministic scatter-min.

    Round k scatter-mins t_deliver over the destination axis to find each
    row's earliest remaining due message, then scatter-mins the POOL INDEX
    over the messages matching that minimum — reproducing the stable
    sort's exact (t_deliver, idx) tie-break — and masks the winners out.
    O(R·P) work, 2R small [P]→[N] scatters, no full-pool sort.
    Bit-identical to :func:`build_inbox_sort` (pinned by the identity
    tests in tests/test_engine.py).

    Under explicit node sharding (parallel/shard_tick.py) ``pool`` is
    one shard's contiguous tile: pass the shard_map ``axis_name``, the
    tile's ``base`` pool offset and the global ``p_total``.  Each round's
    two scatter-mins then run on the LOCAL tile and merge across shards
    with ``lax.pmin`` — the local-select + all-reduce:min form this
    selection was designed for.  The per-round global minimum over
    (t_deliver, pool index) is the min of the per-shard minima, so the
    sharded table is bit-identical to the solo one; ``delivered`` /
    ``to_dead`` come back tile-local.  Defaults leave the solo path
    byte-for-byte unchanged.
    """
    p = pool.capacity
    pt = p if p_total is None else p_total
    due, to_dead = _due_masks(pool, n, t_end, alive, hold)

    idx = base + jnp.arange(p, dtype=I32)  # GLOBAL pool indices
    dstc = jnp.clip(pool.dst, 0, n - 1)
    # remaining-candidate key; winners flip to T_INF between rounds
    tkey = jnp.where(due, pool.t_deliver, T_INF)
    cols, delivered = [], jnp.zeros((p,), bool)
    for _ in range(r):
        min_t = jnp.full((n,), T_INF, I64).at[dstc].min(tkey)
        if axis_name is not None:
            min_t = jax.lax.pmin(min_t, axis_name)
        cand = (tkey < T_INF) & (tkey == min_t[dstc])
        win = jnp.full((n,), pt, I32).at[dstc].min(jnp.where(cand, idx, pt))
        if axis_name is not None:
            win = jax.lax.pmin(win, axis_name)
        cols.append(jnp.where(win < pt, win, NO_NODE))
        is_win = cand & (idx == win[dstc])
        delivered |= is_win
        tkey = jnp.where(is_win, T_INF, tkey)
    return jnp.stack(cols, axis=1), delivered, to_dead


def build_inbox(pool: MsgPool, n: int, r: int, t_end, alive,
                impl: str = "scatter", hold=None):
    """Group due messages by destination into an index table.

    ``impl`` selects the grouping algorithm: ``"scatter"`` (default,
    zero-sort scatter-min rounds), ``"pallas"`` (the fused kernel-plane
    selection, oversim_tpu/kernels/inbox.py — the fused payload gather
    is dropped here; the engine's fused phase consumes it directly) or
    ``"sort"`` (legacy full-pool lexicographic sort, ORACLE-ONLY).  All
    three return bit-identical results.
    ``hold`` ([P] bool) excludes messages from delivery entirely — see
    :func:`_due_masks`.

    Returns:
      inbox: [N, R] i32 pool indices, -1 for empty slots, ordered by
             (deliver time, pool index) within each row.
      delivered: [P] bool — messages placed into the inbox this tick.
      dropped_dead: [P] bool — messages due for a dead node (freed, counted;
             reference drops these as "dest unavailable", SimpleUDP.cc:307).
    """
    if impl == "sort":
        return build_inbox_sort(pool, n, r, t_end, alive, hold)
    if impl == "scatter":
        return build_inbox_scatter(pool, n, r, t_end, alive, hold)
    if impl == "pallas":
        from oversim_tpu import kernels
        inbox, delivered, to_dead, _gblk = kernels.inbox.fused_inbox(
            pool, n, r, t_end, alive, hold)
        return inbox, delivered, to_dead
    raise ValueError(f"unknown inbox_impl: {impl!r} "
                     "(expected 'scatter', 'pallas' or 'sort')")


def free(pool: MsgPool, mask) -> MsgPool:
    return dataclasses.replace(
        pool,
        valid=pool.valid & ~mask,
        t_deliver=jnp.where(mask, T_INF, pool.t_deliver))


def alloc(pool: MsgPool, out: dict, want, impl: str = "scatter"):
    """Write the tick's outbox into free pool slots — SORT-FREE.

    ``out`` maps field name -> [Q, ...] flattened outbox arrays;
    ``want`` is [Q] bool.  Returns (pool', overflow_count).

    The j-th wanted message goes to the j-th free slot (both in index
    order), exactly as the old two-`lax.sort` allocator did, but the
    mapping is built from two prefix sums plus ONE tiny [P] i32 scatter
    (the compacted free-slot list) — O(P) work instead of two
    O(P log P) full-pool sorts, the dominant per-tick cost at P = 8N.
    The payload write stays one gather + one scatter of the packed
    [·, W] block plus the two i64 fields and the valid mask.

    ``impl="pallas"`` computes the destination mapping with the fused
    compaction kernel (oversim_tpu/kernels/outbox.py) instead of the
    cumsum/fslot-scatter trio — bit-identical destinations and
    overflow count; the payload write is shared.
    """
    p = pool.capacity
    if impl == "pallas":
        from oversim_tpu import kernels
        dest, overflow = kernels.outbox.alloc_dest(pool.valid, want)
    else:
        n_want = jnp.sum(want.astype(I32))
        free = ~pool.valid
        n_free = jnp.sum(free.astype(I32))

        # rank of each free slot among free slots / of each wanted
        # message among wanted messages (exclusive prefix sums)
        free_i = free.astype(I32)
        free_rank = jnp.cumsum(free_i) - free_i            # [P]
        want_i = want.astype(I32)
        want_rank = jnp.cumsum(want_i) - want_i            # [Q]

        # compact free-slot list: fslot[j] = index of the j-th free slot
        # (p elsewhere, which scatters/reads as "dropped")
        fslot = jnp.full((p,), p, I32).at[
            jnp.where(free, free_rank, p)].set(
            jnp.arange(p, dtype=I32), mode="drop")
        # destination slot per outbox message; p (out of bounds,
        # dropped) for unwanted messages and for wanted ones past the
        # free supply
        dest = jnp.where(want & (want_rank < n_free),
                         fslot[jnp.minimum(want_rank, p - 1)], p)
        overflow = jnp.maximum(n_want - n_free, 0)

    out_blk = pack_block(out, pool.kl, pool.rmax)
    new_pool = dataclasses.replace(
        pool,
        blk=pool.blk.at[dest].set(out_blk, mode="drop"),
        t_deliver=pool.t_deliver.at[dest].set(
            jnp.asarray(out["t_deliver"], I64), mode="drop"),
        stamp=pool.stamp.at[dest].set(
            jnp.asarray(out["stamp"], I64), mode="drop"),
        valid=pool.valid.at[dest].set(True, mode="drop"))
    return new_pool, overflow
