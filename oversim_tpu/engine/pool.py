"""Global bounded message pool — the TPU replacement for the future-event set.

The reference delivers packets by inserting them into OMNeT++'s
future-event set one at a time (`sendDirect`, SimpleUDP.cc:418).  Here all
in-flight packets live in one structure-of-arrays pool of P slots; each
simulation tick:

  * the due messages (deliver time inside the tick window) are grouped by
    destination into a fixed-width inbox index table via one lexicographic
    sort (dst, t_deliver) — O(P log P) on the whole batch instead of a heap
    pop per message;
  * delivered slots are freed, and the tick's outbox is written into free
    slots with a second sort-based allocation.

Messages that overflow a node's R inbox slots in one window simply stay in
the pool and deliver next tick (receive-queue backpressure).  Pool
exhaustion is counted, never silent (SURVEY.md §7.2 "no silent truncation").

A message carries: src/dst slot, kind, a key, a nonce, hop count, four i32
payload scalars, and a node-list payload of RMAX slot indices (the
FindNodeResponse closest-node set, CommonMessages.msg:246-262, travels as
slot indices — node keys are recoverable from the global key table).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MsgPool:
    """All arrays [P, ...]."""

    valid: jnp.ndarray      # [P] bool
    t_deliver: jnp.ndarray  # [P] i64 ns
    src: jnp.ndarray        # [P] i32
    dst: jnp.ndarray        # [P] i32
    kind: jnp.ndarray       # [P] i32
    key: jnp.ndarray        # [P, KL] u32
    nonce: jnp.ndarray      # [P] i32
    hops: jnp.ndarray       # [P] i32
    a: jnp.ndarray          # [P] i32
    b: jnp.ndarray          # [P] i32
    c: jnp.ndarray          # [P] i32
    d: jnp.ndarray          # [P] i32
    nodes: jnp.ndarray      # [P, RMAX] i32 (NO_NODE padded)
    size_b: jnp.ndarray     # [P] i32 payload bytes (for delay model + stats)
    stamp: jnp.ndarray      # [P] i64 ns timestamp payload (e.g. send time for
                            # app-latency stats; reference keeps simTime() in
                            # message fields, KBRTestApp.cc measurement path)

    @property
    def capacity(self):
        return self.valid.shape[0]


FIELDS = ("t_deliver", "src", "dst", "kind", "key", "nonce", "hops",
          "a", "b", "c", "d", "nodes", "size_b", "stamp")


def empty(p: int, key_lanes: int, rmax: int) -> MsgPool:
    return MsgPool(
        valid=jnp.zeros((p,), bool),
        t_deliver=jnp.full((p,), T_INF, I64),
        src=jnp.full((p,), NO_NODE, I32),
        dst=jnp.full((p,), NO_NODE, I32),
        kind=jnp.zeros((p,), I32),
        key=jnp.zeros((p, key_lanes), U32),
        nonce=jnp.zeros((p,), I32),
        hops=jnp.zeros((p,), I32),
        a=jnp.zeros((p,), I32), b=jnp.zeros((p,), I32),
        c=jnp.zeros((p,), I32), d=jnp.zeros((p,), I32),
        nodes=jnp.full((p, rmax), NO_NODE, I32),
        size_b=jnp.zeros((p,), I32),
        stamp=jnp.zeros((p,), I64),
    )


def next_deliver_time(pool: MsgPool):
    """Earliest pending deliver time (i64; T_INF when pool empty)."""
    return jnp.min(jnp.where(pool.valid, pool.t_deliver, T_INF))


def build_inbox(pool: MsgPool, n: int, r: int, t_end, alive):
    """Group due messages by destination into an index table.

    Returns:
      inbox: [N, R] i32 pool indices, -1 for empty slots, ordered by
             deliver time within each row.
      delivered: [P] bool — messages placed into the inbox this tick.
      dropped_dead: [P] bool — messages due for a dead node (freed, counted;
             reference drops these as "dest unavailable", SimpleUDP.cc:307).
    """
    p = pool.capacity
    due = pool.valid & (pool.t_deliver < t_end)
    to_dead = due & ~alive[jnp.clip(pool.dst, 0, n - 1)]
    due = due & ~to_dead

    dst_k = jnp.where(due, pool.dst, n).astype(I32)
    t_k = jnp.where(due, pool.t_deliver, T_INF)
    idx = jnp.arange(p, dtype=I32)
    dst_s, _, idx_s = jax.lax.sort((dst_k, t_k, idx), dimension=0, num_keys=2)

    # rank of each message within its destination group
    first = jnp.searchsorted(dst_s, dst_s, side="left").astype(I32)
    rank = jnp.arange(p, dtype=I32) - first
    take = (dst_s < n) & (rank < r)

    rows = jnp.where(take, dst_s, n)  # row n is out-of-bounds -> dropped
    inbox = jnp.full((n, r), NO_NODE, I32).at[rows, jnp.minimum(rank, r - 1)].set(
        idx_s, mode="drop")
    delivered = jnp.zeros((p,), bool).at[idx_s].set(take)
    return inbox, delivered, to_dead


def free(pool: MsgPool, mask) -> MsgPool:
    return dataclasses.replace(
        pool,
        valid=pool.valid & ~mask,
        t_deliver=jnp.where(mask, T_INF, pool.t_deliver))


def alloc(pool: MsgPool, out: dict, want):
    """Write the tick's outbox into free pool slots.

    ``out`` maps field name -> [Q, ...] flattened outbox arrays;
    ``want`` is [Q] bool.  Returns (pool', overflow_count).
    """
    p = pool.capacity
    q = want.shape[0]
    n_want = jnp.sum(want.astype(I32))
    n_free = jnp.sum((~pool.valid).astype(I32))

    # j-th wanted message  <-  j-th free slot
    _, wsrc = jax.lax.sort(
        (jnp.where(want, 0, 1).astype(I32), jnp.arange(q, dtype=I32)), num_keys=1)
    _, fslot = jax.lax.sort(
        (jnp.where(pool.valid, 1, 0).astype(I32), jnp.arange(p, dtype=I32)),
        num_keys=1)

    k = min(p, q)
    j = jnp.arange(k, dtype=I32)
    ok = (j < n_want) & (j < n_free)
    slots = jnp.where(ok, fslot[:k], p)  # p = out-of-bounds, dropped
    srcs = wsrc[:k]

    new = {}
    for name in FIELDS:
        cur = getattr(pool, name)
        new[name] = cur.at[slots].set(out[name][srcs], mode="drop")
    valid = pool.valid.at[slots].set(True, mode="drop")
    overflow = jnp.maximum(n_want - n_free, 0)
    return MsgPool(valid=valid, **new), overflow
