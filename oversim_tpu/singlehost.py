"""SingleHost interop surface: TUN raw-packet path + Zeroconf bootstrap.

Completes the singlehostunderlay depth the gateway's socket bridge
(gateway.py) leaves out (reference src/underlay/singlehostunderlay/):

  * **TUN packet parsers** (tunoutscheduler.{h,cc} + the
    *messageparser* family): the reference attaches a TUN device and
    converts raw IPv4/UDP packets to overlay messages and back.  Here
    :func:`parse_ipv4_udp` / :func:`build_ipv4_udp` implement the
    header codec (with real checksums), :class:`TunBridge` couples it
    to a RealtimeGateway (raw packet in → EXT_IN, EXT_OUT → raw packet
    out), and :func:`open_tun` attaches a real ``/dev/net/tun`` device
    when the host allows it (gracefully absent in sandboxes);
  * **Zeroconf bootstrap** (ZeroconfConnector.h:38-44: the reference
    publishes the overlay via Avahi mDNS/DNS-SD and browses for
    bootstrap peers): :class:`ZeroconfDiscovery` speaks actual
    mDNS-framed DNS-SD — a PTR answer for ``_oversim._udp.local`` with
    an SRV additional carrying host:port — over the 224.0.0.251:5353
    multicast group (falling back to loopback when multicast is
    unavailable), interoperable with standard mDNS browsers for the
    announce direction.

All host-side Python: this is the real-network interop layer, not the
TPU compute path (SURVEY.md §2.2 SingleHostUnderlay row).
"""

from __future__ import annotations

import socket
import struct
import time

from oversim_tpu.gateway import EXT_IN, _HDR

# ---------------------------------------------------------------------------
# IPv4/UDP codec (the TUN message-parser path)
# ---------------------------------------------------------------------------

_IP_HDR = struct.Struct("!BBHHHBBH4s4s")
_UDP_HDR = struct.Struct("!HHHH")


def _ip_checksum(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    s = sum(struct.unpack("!%dH" % (len(data) // 2), data))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return ~s & 0xFFFF


def parse_ipv4_udp(packet: bytes):
    """Raw IPv4 packet → (src_ip, src_port, dst_ip, dst_port, payload),
    or None if not a well-formed IPv4/UDP datagram (the reference's
    packet parser drops non-UDP traffic the same way)."""
    if len(packet) < _IP_HDR.size:
        return None
    (vihl, _tos, tot_len, _ident, frag, _ttl, proto, hdr_ck,
     src, dst) = _IP_HDR.unpack_from(packet)
    if vihl >> 4 != 4 or proto != 17:      # IPv4, UDP
        return None
    if frag & 0x3FFF:
        # fragmented datagram (MF set or nonzero offset): a non-first
        # fragment has no UDP header at all — drop like any standard
        # parser rather than misreading payload bytes as ports
        return None
    ihl = (vihl & 0xF) * 4
    if ihl < 20 or len(packet) < ihl + _UDP_HDR.size:
        return None
    if _ip_checksum(packet[:ihl]) != 0:
        return None
    sport, dport, ulen, _uck = _UDP_HDR.unpack_from(packet, ihl)
    payload = packet[ihl + _UDP_HDR.size: ihl + max(ulen, 8)]
    return (socket.inet_ntoa(src), sport, socket.inet_ntoa(dst), dport,
            payload)


def build_ipv4_udp(src_ip: str, src_port: int, dst_ip: str,
                   dst_port: int, payload: bytes, ttl: int = 64) -> bytes:
    """(addresses, payload) → raw IPv4/UDP packet with valid header
    checksum (UDP checksum 0 = disabled, RFC 768 legal)."""
    udp = _UDP_HDR.pack(src_port, dst_port, _UDP_HDR.size + len(payload),
                        0) + payload
    tot = _IP_HDR.size + len(udp)
    hdr = _IP_HDR.pack(0x45, 0, tot, 0, 0, ttl, 17, 0,
                       socket.inet_aton(src_ip), socket.inet_aton(dst_ip))
    ck = _ip_checksum(hdr)
    hdr = hdr[:10] + struct.pack("!H", ck) + hdr[12:]
    return hdr + udp


def open_tun(name: str = "oversim0"):
    """Attach a real TUN device (TUNSETIFF) — returns the fd, or None
    when the host forbids it (no /dev/net/tun, no CAP_NET_ADMIN)."""
    import fcntl
    import os
    TUNSETIFF = 0x400454CA
    IFF_TUN, IFF_NO_PI = 0x0001, 0x1000
    try:
        fd = os.open("/dev/net/tun", os.O_RDWR)
        ifr = struct.pack("16sH", name.encode()[:15], IFF_TUN | IFF_NO_PI)
        fcntl.ioctl(fd, TUNSETIFF, ifr)
        return fd
    except OSError:
        return None


class TunBridge:
    """Couples the raw-packet codec to a RealtimeGateway: feed raw
    IPv4/UDP packets in (as a TUN device would deliver them), collect
    raw reply packets out.  The session table maps overlay replies back
    to the originating (ip, port) exactly like the gateway's socket
    sessions."""

    def __init__(self, gateway, local_ip: str = "10.0.0.1",
                 local_port: int = 4000):
        self.gw = gateway
        self.local_ip = local_ip
        self.local_port = local_port
        self._tun_sessions: dict = {}    # sid -> (src_ip, src_port)
        self._by_addr: dict = {}         # (src_ip, src_port) -> sid
        self._outq: list = []            # raw packets drained mid-pump
        # drain between pump ticks — an unconsumed EXT_OUT would be
        # delivered back into the sim on the next tick and lost
        gateway.ext_drains.append(self._drain_to_queue)

    def feed_raw(self, packet: bytes) -> bool:
        """One inbound raw packet → EXT_IN message (True if parsed and
        addressed to the bridge's ip:port)."""
        parsed = parse_ipv4_udp(packet)
        if parsed is None:
            return False
        src_ip, src_port, dst_ip, dst_port, payload = parsed
        if (dst_ip, dst_port) != (self.local_ip, self.local_port):
            return False
        if len(payload) < _HDR.size:
            return False
        _kind, _a, b, c = _HDR.unpack_from(payload)
        # one session per remote endpoint (reused across packets — an
        # id per packet would grow the tables without bound)
        addr = (src_ip, src_port)
        sid = self._by_addr.get(addr)
        if sid is None:
            sid = self.gw._next_session
            self.gw._next_session += 1
            self._by_addr[addr] = sid
            self.gw._sessions[sid] = ("tun", addr)
            self._tun_sessions[sid] = addr
        self.gw.inject(EXT_IN, a=sid, b=b, c=c)
        return True

    def _drain_to_queue(self):
        """Drain EXT_OUT messages with tun sessions into the outbound
        packet queue (shared drain, gateway.drain_ext_out; runs between
        pump ticks via gateway.ext_drains)."""
        from oversim_tpu.gateway import EXT_OUT, drain_ext_out

        def handler(sid, b, c):
            sess = self._tun_sessions.get(sid)
            if sess is None:
                return False  # a socket session — the gateway drains it
            payload = _HDR.pack(EXT_OUT, sid, b, c)
            self._outq.append(
                build_ipv4_udp(self.local_ip, self.local_port,
                               sess[0], sess[1], payload))
            return True

        self.gw.state = drain_ext_out(self.gw.state, self.gw.gw, handler)

    def collect_raw(self) -> list:
        """Raw reply packets accumulated since the last call (the TUN
        write direction)."""
        self._drain_to_queue()
        out, self._outq = self._outq, []
        return out


# ---------------------------------------------------------------------------
# Zeroconf / mDNS DNS-SD bootstrap (ZeroconfConnector)
# ---------------------------------------------------------------------------

MDNS_GROUP = "224.0.0.251"
MDNS_PORT = 5353
SERVICE = b"_oversim._udp.local"


def _dns_name(labels: bytes) -> bytes:
    out = b""
    for part in labels.split(b"."):
        out += bytes([len(part)]) + part
    return out + b"\x00"


def _skip_name(buf: bytes, off: int) -> int:
    while off < len(buf):
        ln = buf[off]
        if ln == 0:
            return off + 1
        if ln & 0xC0:         # compression pointer
            return off + 2
        off += 1 + ln
    return off


def _read_name(buf: bytes, off: int, depth: int = 0) -> list:
    """Decode a (possibly compressed, RFC 1035 §4.1.4) DNS name into
    its label list — real mDNS responders (Avahi, the reference's
    Zeroconf backend) compress aggressively."""
    labels = []
    hops = 0
    while off < len(buf) and hops < 16:
        ln = buf[off]
        if ln == 0:
            break
        if ln & 0xC0:
            if off + 1 >= len(buf):
                break
            off = ((ln & 0x3F) << 8) | buf[off + 1]
            hops += 1
            continue
        labels.append(buf[off + 1:off + 1 + ln])
        off += 1 + ln
    return labels


def build_announce(instance: str, host: str, port: int) -> bytes:
    """mDNS response frame: PTR answer for the service type plus an SRV
    additional with the bootstrap endpoint (DNS-SD announce shape)."""
    inst = _dns_name(instance.encode() + b"." + SERVICE)
    svc = _dns_name(SERVICE)
    hdr = struct.pack("!HHHHHH", 0, 0x8400, 0, 1, 0, 1)  # response, 1 an, 1 ar
    ptr = svc + struct.pack("!HHIH", 12, 0x8001, 120, len(inst)) + inst
    target = _dns_name(host.encode() + b".local")
    srv_rd = struct.pack("!HHH", 0, 0, port) + target
    srv = inst + struct.pack("!HHIH", 33, 0x8001, 120, len(srv_rd)) + srv_rd
    return hdr + ptr + srv


def parse_announce(frame: bytes):
    """mDNS frame → (instance, host, port) if it announces our service
    type; None otherwise."""
    if len(frame) < 12:
        return None
    _tid, flags, qd, an, _ns, ar = struct.unpack_from("!HHHHHH", frame)
    if not flags & 0x8000:
        return None
    off = 12
    for _ in range(qd):
        off = _skip_name(frame, off) + 4
    svc_labels = SERVICE.split(b".")
    found = None
    for _ in range(an + ar):
        name_start = off
        off = _skip_name(frame, off)
        if off + 10 > len(frame):
            return None
        rtype, _rclass, _ttl, rdlen = struct.unpack_from("!HHIH", frame,
                                                         off)
        off += 10
        # names may be compressed (pointer into earlier records) —
        # decode them properly instead of substring-matching raw bytes
        if rtype == 33:
            owner = _read_name(frame, name_start)
            # rdata must actually HOLD prio/weight/port + >=1 target
            # byte — decoding past a short rdlen would read the next
            # record (or attacker-controlled trailing bytes) as a
            # bootstrap endpoint
            if owner[-len(svc_labels):] == svc_labels and len(owner) > \
                    len(svc_labels) and rdlen >= 7 \
                    and off + rdlen <= len(frame):
                port = struct.unpack_from("!H", frame, off + 4)[0]
                target = _read_name(frame, off + 6)
                host = b".".join(target[:-1] if len(target) > 1
                                 else target).decode("ascii", "replace")
                inst = owner[0].decode("ascii", "replace")
                found = (inst, host, port)
        off += rdlen
    return found


class ZeroconfDiscovery:
    """Announce this node's bootstrap endpoint and browse for peers
    (ZeroconfConnector.h:38-44 — the reference publishes via Avahi and
    enqueues discovered peers as bootstrap candidates)."""

    def __init__(self, group: str = MDNS_GROUP, port: int = MDNS_PORT,
                 iface_ip: str = "127.0.0.1"):
        self.group, self.port = group, port
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.multicast = True
        try:
            self.sock.bind(("", port))
            mreq = socket.inet_aton(group) + socket.inet_aton(iface_ip)
            self.sock.setsockopt(socket.IPPROTO_IP,
                                 socket.IP_ADD_MEMBERSHIP, mreq)
            self.sock.setsockopt(socket.IPPROTO_IP,
                                 socket.IP_MULTICAST_LOOP, 1)
        except OSError:
            # multicast unavailable (restricted sandbox): plain loopback
            self.multicast = False
            self.sock.close()
            self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self.sock.bind(("127.0.0.1", port))
        self.sock.setblocking(False)

    def announce(self, instance: str, host: str, port: int):
        frame = build_announce(instance, host, port)
        dests = [("127.0.0.1", self.port)]
        if self.multicast:
            # group first; the loopback copy covers sandboxes whose
            # multicast membership binds but never routes
            dests.insert(0, (self.group, self.port))
        for dest in dests:
            try:
                self.sock.sendto(frame, dest)
            except OSError:
                pass

    def browse(self, wait_s: float = 0.2) -> list:
        """Collect announcements seen within ``wait_s`` →
        [(instance, host, port)] bootstrap candidates."""
        # monotonic: a wall-clock NTP step must not stretch the wait
        deadline = time.monotonic() + wait_s
        seen = []
        while time.monotonic() < deadline:
            try:
                frame, _addr = self.sock.recvfrom(9000)
            except (BlockingIOError, OSError):
                time.sleep(0.01)
                continue
            rec = parse_announce(frame)
            if rec is not None and rec not in seen:
                seen.append(rec)
        return seen

    def close(self):
        self.sock.close()


# ---------------------------------------------------------------------------
# STUN NAT traversal (reference src/underlay/singlehostunderlay/stun/)
# ---------------------------------------------------------------------------
#
# The reference bundles the classic vovida STUN 0.96 client (stun.{h,cc}:
# BindRequestMsg/BindResponseMsg, MappedAddress/XorMappedAddress attrs,
# stunNatType()) and calls it from SingleHostUnderlayConfigurator.cc:108-134
# to learn the node's public address before joining.  This is the modern
# equivalent: an RFC 5389 binding-request client (magic-cookie header,
# XOR-MAPPED-ADDRESS, RTO-doubling retransmission) that also understands
# the classic MAPPED-ADDRESS replies the reference's library sends, plus a
# loopback responder for tests.

STUN_MAGIC = 0x2112A442
STUN_BIND_REQ = 0x0001        # BindRequestMsg, stun.h:53
STUN_BIND_RES = 0x0101        # BindResponseMsg
STUN_ATTR_MAPPED = 0x0001     # MappedAddress, stun.h:36
STUN_ATTR_XOR_MAPPED = 0x0020  # RFC 5389 (classic library used 0x8020)
STUN_ATTR_XOR_MAPPED_OLD = 0x8020
_STUN_HDR = struct.Struct("!HHI12s")


def build_binding_request(txid: bytes) -> bytes:
    """RFC 5389 §6 binding request (no attributes)."""
    if len(txid) != 12:
        raise ValueError("txid must be 12 bytes")
    return _STUN_HDR.pack(STUN_BIND_REQ, 0, STUN_MAGIC, txid)


def build_binding_response(txid: bytes, ip: str, port: int,
                           xor_mapped: bool = True) -> bytes:
    """Binding success response carrying the reflexive transport address."""
    fam = 0x01
    addr = struct.unpack("!I", socket.inet_aton(ip))[0]
    if xor_mapped:
        attr_v = struct.pack("!BBHI", 0, fam, port ^ (STUN_MAGIC >> 16),
                             addr ^ STUN_MAGIC)
        attr = struct.pack("!HH", STUN_ATTR_XOR_MAPPED, 8) + attr_v
    else:
        attr_v = struct.pack("!BBHI", 0, fam, port, addr)
        attr = struct.pack("!HH", STUN_ATTR_MAPPED, 8) + attr_v
    return _STUN_HDR.pack(STUN_BIND_RES, len(attr), STUN_MAGIC, txid) + attr


def parse_stun(data: bytes):
    """Parse a STUN message → dict(type, txid, mapped=(ip, port) | None).
    Returns None for non-STUN data (first two bits must be 00 and the
    magic cookie must match — RFC 5389 §6 demultiplexing)."""
    if len(data) < _STUN_HDR.size or data[0] & 0xC0:
        return None
    mtype, mlen, magic, txid = _STUN_HDR.unpack_from(data)
    if magic != STUN_MAGIC or len(data) < _STUN_HDR.size + mlen:
        return None
    out = {"type": mtype, "txid": txid, "mapped": None}
    off = _STUN_HDR.size
    end = off + mlen
    while off + 4 <= end:
        at, alen = struct.unpack_from("!HH", data, off)
        off += 4
        if off + alen > end:
            break
        val = data[off:off + alen]
        off += alen + ((4 - alen % 4) % 4)          # attrs pad to 32 bits
        if alen == 8 and at in (STUN_ATTR_XOR_MAPPED,
                                STUN_ATTR_XOR_MAPPED_OLD):
            _, fam, xport, xaddr = struct.unpack("!BBHI", val)
            if fam == 0x01:
                out["mapped"] = (
                    socket.inet_ntoa(struct.pack("!I", xaddr ^ STUN_MAGIC)),
                    xport ^ (STUN_MAGIC >> 16))
        elif alen == 8 and at == STUN_ATTR_MAPPED and out["mapped"] is None:
            _, fam, port, addr = struct.unpack("!BBHI", val)
            if fam == 0x01:
                out["mapped"] = (socket.inet_ntoa(struct.pack("!I", addr)),
                                 port)
    return out


def stun_discover(sock, server, rto_s: float = 0.5, retries: int = 3):
    """Send a binding request from ``sock`` and return the reflexive
    (ip, port) the server saw, or None.

    RFC 5389 §7.2.1 retransmission: RTO doubles per attempt (the
    reference's stunNatType() drives the same request/timeout loop,
    stun.cc).  Uses the caller's socket so the mapped address
    corresponds to the port the overlay will actually use — the whole
    point of the exercise for NAT traversal."""
    import os as _os
    txid = _os.urandom(12)
    req = build_binding_request(txid)
    old_to = sock.gettimeout()
    try:
        for attempt in range(retries):
            try:
                sock.sendto(req, server)
            except OSError:
                return None
            # monotonic: retransmit timeouts must survive clock steps
            deadline = time.monotonic() + rto_s * (2 ** attempt)
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                sock.settimeout(remain)
                try:
                    data, _addr = sock.recvfrom(2048)
                except (socket.timeout, OSError):
                    break
                msg = parse_stun(data)
                if (msg and msg["type"] == STUN_BIND_RES
                        and msg["txid"] == txid and msg["mapped"]):
                    return msg["mapped"]
        return None
    finally:
        sock.settimeout(old_to)


class StunResponder:
    """Minimal loopback STUN server (test double for a public server —
    the role stunServer plays in SingleHostUnderlayConfigurator.cc:108).
    Replies to binding requests with the sender's reflexive address;
    ``classic=True`` answers with the pre-RFC-5389 MAPPED-ADDRESS the
    reference's vovida library would send."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 classic: bool = False):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.addr = self.sock.getsockname()
        self.classic = classic
        self._stop = False
        import threading
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        self.sock.settimeout(0.1)
        while not self._stop:
            try:
                data, addr = self.sock.recvfrom(2048)
            except (socket.timeout, OSError):
                continue
            msg = parse_stun(data)
            if msg and msg["type"] == STUN_BIND_REQ:
                try:
                    self.sock.sendto(
                        build_binding_response(
                            msg["txid"], addr[0], addr[1],
                            xor_mapped=not self.classic), addr)
                except OSError:
                    pass

    def close(self):
        self._stop = True
        self._thread.join(timeout=1.0)
        self.sock.close()
