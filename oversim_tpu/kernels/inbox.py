"""Fused inbox kernel: top-R selection + packed payload gather in ONE
Pallas pass over the pool.

The scatter-min oracle (engine/pool.py ``build_inbox_scatter``) builds
the [N, R] inbox table in R rounds of two [P]->[N] scatter-mins each,
then ``Simulation._phase_inbox_gather`` issues a separate [P, W] block
gather — 2R+1 independent XLA ops, each streaming the pool through HBM.
This kernel keeps the per-destination top-R registers in VMEM and does
everything in one serial sweep:

  pass 1 (over P): for each due message, a stable insertion into its
    destination's R-row register file sorted by (t_deliver, pool index).
    Pool indices arrive in increasing order and (t, idx) keys are
    unique, so "count of existing entries with key <= mine" IS the
    insertion position — exactly the oracle's stable tie-break.  An
    insertion into a full row evicts the current last entry, whose
    delivered flag is undone (R-overflow retention: the evicted message
    stays pooled for next tick).
  pass 2 (over N*R): gather the packed [P, W] payload rows of the
    selected indices into the [N, R, W] message block (row 0 for empty
    slots, masked by ``inbox < 0`` downstream — the oracle's
    ``jnp.maximum(inbox, 0)`` gather semantics).

i64 on Pallas-TPU: the core has no 64-bit lanes, so ``t_deliver`` is
decomposed OUTSIDE the kernel into two non-negative i32 halves
(hi = t >> 31, lo = t & 0x7fffffff; t < 2^62 so both fit signed i32)
— lexicographic (hi, lo) compare reproduces the i64 order exactly.
The two i64 fields themselves (t_deliver, stamp) are gathered outside
the kernel off the returned index table ([N, R] gathers from [P], tiny
next to the [P, W] block).

Bit-identity with the oracle — including t ties, R-overflow eviction,
dead destinations and the ``ext_hold_slot`` hold mask (both applied
outside via ``pool._due_masks``) — is pinned by
tests/test_kernels.py under ``pallas_call(interpret=True)``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
_I32_MAX = jnp.iinfo(jnp.int32).max


def _inbox_kernel(occ_ref, due_ref, dst_ref, thi_ref, tlo_ref, *refs,
                  p, n, r, w, gather):
    """One program: select pass over P, then (optional) gather over N*R.

    khi/klo are the VMEM [N, R] sort-key registers mirroring inbox_ref
    (i32 max = empty, so any real key inserts before them).  All loop
    indices are cast to i32 — under x64 ``fori_loop`` counts in i64,
    which must not leak into i32 ref stores.

    ``occ_ref`` (SMEM scalar) is the OCCUPANCY early-out: the highest
    due pool index + 1, computed outside.  The select walk runs to occ,
    not capacity P — bit-identity is free (slots past the last due
    index can never insert) and a near-empty pool costs a near-empty
    walk.  ``gather=False`` (the sparse tick's select-only mode) skips
    the N*R gather pass entirely and takes no blk input.
    """
    if gather:
        blk_ref, inbox_ref, delivered_ref, gblk_ref, khi_ref, klo_ref = refs
    else:
        inbox_ref, delivered_ref, khi_ref, klo_ref = refs
    inbox_ref[:] = jnp.full((n, r), -1, I32)
    delivered_ref[:] = jnp.zeros((p,), I32)
    khi_ref[:] = jnp.full((n, r), _I32_MAX, I32)
    klo_ref[:] = jnp.full((n, r), _I32_MAX, I32)
    pos_iota = jax.lax.broadcasted_iota(I32, (r, 1), 0).reshape(r)

    def select_body(iv, carry):
        i = iv.astype(I32)

        @pl.when(due_ref[i] != 0)
        def _():
            d = dst_ref[i]
            hi = thi_ref[i]
            lo = tlo_ref[i]
            row_hi = khi_ref[d, :]
            row_lo = klo_ref[d, :]
            row_ix = inbox_ref[d, :]
            # stable position: entries with key <= (hi, lo) stay ahead;
            # earlier pool indices inserted at equal t compare <= via lo
            le = (row_hi < hi) | ((row_hi == hi) & (row_lo <= lo))
            pos = jnp.sum(le.astype(I32))

            @pl.when(pos < r)
            def _():
                evict = row_ix[r - 1]
                keep = pos_iota < pos
                shift = pos_iota > pos
                prev_hi = pltpu.roll(row_hi, 1, 0)
                prev_lo = pltpu.roll(row_lo, 1, 0)
                prev_ix = pltpu.roll(row_ix, 1, 0)
                khi_ref[d, :] = jnp.where(
                    keep, row_hi, jnp.where(shift, prev_hi, hi))
                klo_ref[d, :] = jnp.where(
                    keep, row_lo, jnp.where(shift, prev_lo, lo))
                inbox_ref[d, :] = jnp.where(
                    keep, row_ix, jnp.where(shift, prev_ix, i))
                delivered_ref[i] = I32(1)

                @pl.when(evict >= 0)
                def _():
                    # R-overflow: the displaced last entry goes back to
                    # "not delivered" — it stays pooled for next tick
                    delivered_ref[evict] = I32(0)

        return carry

    jax.lax.fori_loop(0, occ_ref[0], select_body, None)

    if gather:
        def gather_body(jv, carry):
            j = jv.astype(I32)
            nn = j // I32(r)
            rr = j % I32(r)
            ix = inbox_ref[nn, rr]
            gblk_ref[nn, rr, :] = blk_ref[jnp.maximum(ix, 0), :]
            return carry

        jax.lax.fori_loop(0, n * r, gather_body, None)


@functools.partial(jax.jit,
                   static_argnames=("n", "r", "interpret", "gather"))
def _fused_call(due, dstc, thi, tlo, blk, *, n, r, interpret, gather=True):
    p, w = blk.shape
    kernel = functools.partial(_inbox_kernel, p=p, n=n, r=r, w=w,
                               gather=gather)
    # occupancy bound: highest due index + 1 — the select walk's true
    # extent (SMEM scalar; kernel work scales with traffic, not P)
    occ = jnp.max(jnp.where(due != 0, jnp.arange(p, dtype=I32) + 1,
                            0)).reshape((1,))
    # array operands stay whole-array in VMEM (the pre-occupancy
    # default); only the occ scalar needs an explicit SMEM placement
    arr = pl.BlockSpec(memory_space=pltpu.VMEM)
    out_shape = [
        jax.ShapeDtypeStruct((n, r), I32),          # inbox
        jax.ShapeDtypeStruct((p,), I32),            # delivered
    ]
    operands = (occ, due, dstc, thi, tlo)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), arr, arr, arr, arr]
    if gather:
        out_shape.append(
            jax.ShapeDtypeStruct((n, r, w), I32))   # gathered block
        operands += (blk,)
        in_specs.append(arr)
    return pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        in_specs=in_specs,
        out_specs=tuple(arr for _ in out_shape),
        scratch_shapes=[
            pltpu.VMEM((n, r), I32),                # khi
            pltpu.VMEM((n, r), I32),                # klo
        ],
        interpret=interpret,
    )(*operands)


def fused_inbox(pool, n: int, r: int, t_end, alive, hold=None,
                interpret: bool | None = None, gather: bool = True):
    """Fused inbox select + gather.

    Same contract as ``pool.build_inbox`` plus the gathered payload:
    returns ``(inbox [N,R] i32, delivered [P] bool, dropped_dead [P]
    bool, gblk [N,R,W] i32)``.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (kernels.interpret_default).
    ``gather=False`` (the sparse tick) returns the 3-tuple without
    ``gblk`` and skips the N*R gather pass in-kernel."""
    from oversim_tpu import kernels

    if interpret is None:
        interpret = kernels.interpret_default()
    due, to_dead = pool_mod._due_masks(pool, n, t_end, alive, hold)
    # oracle semantics: destinations clip into [0, n) BEFORE grouping
    dstc = jnp.clip(pool.dst, 0, n - 1).astype(I32)
    # hi/lo i32 halves of t_deliver; non-due slots masked to 0 so the
    # T_INF sentinel (2^62) never overflows the decomposition — the
    # kernel only reads keys where due != 0
    t_m = jnp.where(due, pool.t_deliver, 0)
    thi = (t_m >> 31).astype(I32)
    tlo = (t_m & jnp.int64(0x7FFFFFFF)).astype(I32)
    out = _fused_call(
        due.astype(I32), dstc, thi, tlo, pool.blk,
        n=n, r=r, interpret=bool(interpret), gather=gather)
    if not gather:
        inbox, delivered = out
        return inbox, delivered.astype(bool), to_dead
    inbox, delivered, gblk = out
    return inbox, delivered.astype(bool), to_dead, gblk


def fused_select(pool, n: int, r: int, t_end, alive, hold=None,
                 interpret: bool | None = None):
    """Select-only fused inbox (sparse tick plane): ``pool.build_inbox``
    semantics — ``(inbox, delivered, dropped_dead)`` — with the
    occupancy-bounded kernel walk and NO payload gather."""
    return fused_inbox(pool, n, r, t_end, alive, hold=hold,
                       interpret=interpret, gather=False)


def fused_select_sharded(pool, n: int, r: int, t_end, alive, hold=None, *,
                         axis_name, base, p_total, interpret=None):
    """Shard-aware fused select (parallel/shard_tick.py): the kernel
    runs UNMODIFIED on each shard's local pool tile, producing that
    shard's per-destination top-R list; the global table is then a
    K-way sorted merge driven purely by ``lax.pmin``.

    Per round, every shard offers its list head ``(t, global idx)``;
    an i64 pmin picks the winning deliver time, an i32 pmin over the
    matching heads breaks ties by global pool index (tiles are
    contiguous, so local-index order IS global-index order within a
    shard — the oracle's exact (t_deliver, idx) tie-break), and the
    winning shard advances its head.  2R all-reduce:min per call, the
    same collective count and kind as the sharded scatter path.

    Correctness of the local prefilter: each destination's global
    top-R draws at most R entries from any one shard, and those are
    necessarily that shard's R earliest — so the global table is a
    subset of the union of local tables.  ``delivered`` is recomputed
    as membership of the local tile in the FINAL table (the local
    kernel's provisional flags — including its R-overflow evictions —
    are discarded; the oracle's delivered set is exactly the final
    table's membership).  Returns ``(inbox [N, R] GLOBAL pool indices,
    delivered [P_local] bool, dropped_dead [P_local] bool)``.
    """
    p_local = pool.capacity
    inbox_l, _prov, to_dead = fused_inbox(pool, n, r, t_end, alive,
                                          hold=hold, interpret=interpret,
                                          gather=False)
    valid_l = inbox_l >= 0
    safe_l = jnp.maximum(inbox_l, 0)
    t_tab = jnp.where(valid_l, pool.t_deliver[safe_l], pool_mod.T_INF)
    g_tab = jnp.where(valid_l, base + inbox_l, _I32_MAX)

    head = jnp.zeros((n,), I32)
    cols = []
    for _ in range(r):
        hc = jnp.minimum(head, r - 1)[:, None]
        in_range = head < r
        t_cand = jnp.where(
            in_range, jnp.take_along_axis(t_tab, hc, axis=1)[:, 0],
            pool_mod.T_INF)
        g_cand = jnp.where(
            in_range, jnp.take_along_axis(g_tab, hc, axis=1)[:, 0],
            _I32_MAX)
        t_win = jax.lax.pmin(t_cand, axis_name)
        g_win = jax.lax.pmin(
            jnp.where(t_cand == t_win, g_cand, _I32_MAX), axis_name)
        got = g_win < _I32_MAX  # global indices < p_total << i32 max
        cols.append(jnp.where(got, g_win, pool_mod.NO_NODE))
        head += ((t_cand == t_win) & (g_cand == g_win) & got).astype(I32)
    inbox = jnp.stack(cols, axis=1)

    flat = inbox.reshape(-1)
    loc = flat - base
    mine = (flat >= 0) & (loc >= 0) & (loc < p_local)
    delivered = jnp.zeros((p_local,), bool).at[
        jnp.where(mine, loc, p_local)].set(True, mode="drop")
    return inbox, delivered, to_dead
