"""Fused outbox-allocation kernel: free-slot compaction + destination
assignment in one Pallas pass.

The sort-free allocator (engine/pool.py ``alloc``) builds the
wanted-message -> free-slot mapping from two full-length exclusive
cumsums plus a compaction scatter (``fslot``).  This kernel replaces
that trio with two serial counting passes — the compacted free-slot
list lives in VMEM, the two running counters in SMEM:

  pass 1 (over P): append each free slot's index to the fslot list;
  pass 2 (over Q): each wanted message takes the next fslot entry (or
    the out-of-bounds sentinel ``p`` once the free supply is exhausted
    — exactly the oracle's ``mode="drop"`` overflow semantics).

The payload write itself (one gather + one scatter of the packed
[·, W] block plus the i64 fields) stays outside: it is already a
single fused scatter per field group, and keeping it in lax means the
kernel output is just the [Q] destination vector + the overflow count,
bit-identical to the cumsum path (tests/test_kernels.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32


def _dest_kernel(valid_ref, want_ref, dest_ref, over_ref,
                 fslot_ref, cnt_ref, *, p, q):
    """cnt_ref (SMEM): [0] = free slots seen, [1] = wanted msgs seen."""
    cnt_ref[0] = I32(0)
    cnt_ref[1] = I32(0)
    fslot_ref[:] = jnp.full((p,), p, I32)

    def free_body(iv, carry):
        i = iv.astype(I32)

        @pl.when(valid_ref[i] == 0)
        def _():
            fslot_ref[cnt_ref[0]] = i
            cnt_ref[0] = cnt_ref[0] + 1

        return carry

    jax.lax.fori_loop(0, p, free_body, None)
    n_free = cnt_ref[0]

    def want_body(jv, carry):
        j = jv.astype(I32)

        @pl.when(want_ref[j] != 0)
        def _():
            wr = cnt_ref[1]
            dest_ref[j] = jnp.where(wr < n_free,
                                    fslot_ref[jnp.minimum(wr, p - 1)],
                                    I32(p))
            cnt_ref[1] = wr + 1

        @pl.when(want_ref[j] == 0)
        def _():
            dest_ref[j] = I32(p)

        return carry

    jax.lax.fori_loop(0, q, want_body, None)
    over_ref[0] = jnp.maximum(cnt_ref[1] - n_free, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dest_call(valid, want, *, interpret):
    p = valid.shape[0]
    q = want.shape[0]
    kernel = functools.partial(_dest_kernel, p=p, q=q)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((q,), I32),        # dest
            jax.ShapeDtypeStruct((1,), I32),        # overflow
        ),
        scratch_shapes=[
            pltpu.VMEM((p,), I32),                  # fslot
            pltpu.SMEM((2,), I32),                  # counters
        ],
        interpret=interpret,
    )(valid, want)


def alloc_dest(valid, want, interpret: bool | None = None):
    """(dest [Q] i32, overflow i32 scalar) — the j-th wanted message maps
    to the j-th free slot, ``p`` (dropped) for unwanted/overflowed
    messages; bit-identical to the cumsum/fslot path in
    ``pool.alloc``."""
    from oversim_tpu import kernels

    if interpret is None:
        interpret = kernels.interpret_default()
    dest, over = _dest_call(valid.astype(I32), want.astype(I32),
                            interpret=bool(interpret))
    return dest, over[0]


def _compact_kernel(mask_ref, vals_ref, out_ref, count_ref, cnt_ref, *,
                    m, cap, sentinel):
    """Serial counting compaction: the k-th set mask bit (walk order)
    writes ``vals[i]`` to lane k; lanes past ``cap`` defer (the counter
    keeps running so the caller learns the TRUE active count)."""
    cnt_ref[0] = I32(0)
    out_ref[:] = jnp.full((cap,), sentinel, I32)

    def body(iv, carry):
        i = iv.astype(I32)

        @pl.when(mask_ref[i] != 0)
        def _():
            c = cnt_ref[0]

            @pl.when(c < cap)
            def _():
                out_ref[c] = vals_ref[i]

            cnt_ref[0] = c + 1

        return carry

    jax.lax.fori_loop(0, m, body, None)
    count_ref[0] = cnt_ref[0]


@functools.partial(jax.jit, static_argnames=("cap", "sentinel", "interpret"))
def _compact_call(mask, vals, *, cap, sentinel, interpret):
    m = mask.shape[0]
    kernel = functools.partial(_compact_kernel, m=m, cap=cap,
                               sentinel=sentinel)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((cap,), I32),      # compacted lanes
            jax.ShapeDtypeStruct((1,), I32),        # active count
        ),
        scratch_shapes=[
            pltpu.SMEM((1,), I32),                  # running counter
        ],
        interpret=interpret,
    )(mask, vals)


def compact_indices(mask, vals, cap: int, sentinel: int,
                    interpret: bool | None = None):
    """(lanes [cap] i32, count i32 scalar) — the sparse tick's
    active-set compaction (engine/sim.py ``_phase_active_compact``):
    lane k holds ``vals[i]`` for the k-th set ``mask`` bit, ``sentinel``
    beyond the active count; ``count`` is the total set-bit count (may
    exceed ``cap`` — overflowed entries defer to the next tick).
    Bit-identical to the cumsum-compaction idiom from ``pool.alloc``,
    pinned in tests/test_kernels.py."""
    from oversim_tpu import kernels

    if interpret is None:
        interpret = kernels.interpret_default()
    lanes, count = _compact_call(mask.astype(I32), vals.astype(I32),
                                 cap=int(cap), sentinel=int(sentinel),
                                 interpret=bool(interpret))
    return lanes, count[0]
