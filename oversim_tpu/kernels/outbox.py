"""Fused outbox-allocation kernel: free-slot compaction + destination
assignment in one Pallas pass.

The sort-free allocator (engine/pool.py ``alloc``) builds the
wanted-message -> free-slot mapping from two full-length exclusive
cumsums plus a compaction scatter (``fslot``).  This kernel replaces
that trio with two serial counting passes — the compacted free-slot
list lives in VMEM, the two running counters in SMEM:

  pass 1 (over P): append each free slot's index to the fslot list;
  pass 2 (over Q): each wanted message takes the next fslot entry (or
    the out-of-bounds sentinel ``p`` once the free supply is exhausted
    — exactly the oracle's ``mode="drop"`` overflow semantics).

The payload write itself (one gather + one scatter of the packed
[·, W] block plus the i64 fields) stays outside: it is already a
single fused scatter per field group, and keeping it in lax means the
kernel output is just the [Q] destination vector + the overflow count,
bit-identical to the cumsum path (tests/test_kernels.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32


def _dest_kernel(valid_ref, want_ref, dest_ref, over_ref,
                 fslot_ref, cnt_ref, *, p, q):
    """cnt_ref (SMEM): [0] = free slots seen, [1] = wanted msgs seen."""
    cnt_ref[0] = I32(0)
    cnt_ref[1] = I32(0)
    fslot_ref[:] = jnp.full((p,), p, I32)

    def free_body(iv, carry):
        i = iv.astype(I32)

        @pl.when(valid_ref[i] == 0)
        def _():
            fslot_ref[cnt_ref[0]] = i
            cnt_ref[0] = cnt_ref[0] + 1

        return carry

    jax.lax.fori_loop(0, p, free_body, None)
    n_free = cnt_ref[0]

    def want_body(jv, carry):
        j = jv.astype(I32)

        @pl.when(want_ref[j] != 0)
        def _():
            wr = cnt_ref[1]
            dest_ref[j] = jnp.where(wr < n_free,
                                    fslot_ref[jnp.minimum(wr, p - 1)],
                                    I32(p))
            cnt_ref[1] = wr + 1

        @pl.when(want_ref[j] == 0)
        def _():
            dest_ref[j] = I32(p)

        return carry

    jax.lax.fori_loop(0, q, want_body, None)
    over_ref[0] = jnp.maximum(cnt_ref[1] - n_free, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dest_call(valid, want, *, interpret):
    p = valid.shape[0]
    q = want.shape[0]
    kernel = functools.partial(_dest_kernel, p=p, q=q)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((q,), I32),        # dest
            jax.ShapeDtypeStruct((1,), I32),        # overflow
        ),
        scratch_shapes=[
            pltpu.VMEM((p,), I32),                  # fslot
            pltpu.SMEM((2,), I32),                  # counters
        ],
        interpret=interpret,
    )(valid, want)


def alloc_dest(valid, want, interpret: bool | None = None):
    """(dest [Q] i32, overflow i32 scalar) — the j-th wanted message maps
    to the j-th free slot, ``p`` (dropped) for unwanted/overflowed
    messages; bit-identical to the cumsum/fslot path in
    ``pool.alloc``."""
    from oversim_tpu import kernels

    if interpret is None:
        interpret = kernels.interpret_default()
    dest, over = _dest_call(valid.astype(I32), want.astype(I32),
                            interpret=bool(interpret))
    return dest, over[0]
