"""The kernel plane: fused Pallas TPU kernels for the tick hot path.

The per-tick cost at scale is dominated by message selection and
delivery (PERFORMANCE.md): the default scatter-min inbox issues 2R
separate [P]->[N] scatters plus a [P, W] payload gather, and the outbox
allocator adds a full-pool cumsum + compaction scatter — all independent
XLA ops that round-trip the pool block through HBM.  This package fuses
them:

  inbox.py   one kernel doing the R-round top-R inbox selection AND the
             packed [P, W] payload gather in a single pass over the
             pool block (serial stable insertion into per-destination
             sorted registers — bit-identical to the scatter-min
             oracle's (t_deliver, pool-index) order);
  outbox.py  the free-slot compaction + destination assignment of the
             sort-free allocator as one serial pass (replaces the
             cumsum/fslot-scatter pair).

Selection: ``EngineParams.inbox_impl="pallas"`` / ``**.inboxImpl =
"pallas"`` arms BOTH kernels; ``"scatter"`` (the default) stays the
bit-identity oracle, exactly as ``"sort"`` did for the scatter
migration (tests/test_kernels.py pins the three-way identity).

On hosts without a TPU the kernels run under
``pallas_call(interpret=True)``: the kernel body is discharged into
plain HLO (no custom-call), so tier-1 tests and the analysis plane pin
bit-identical behaviour AND the fused op-count reduction without
hardware.  On TPU the same bodies lower through Mosaic as
``tpu_custom_call`` ops — the ``fused_tick`` graph contract's
custom-call allowlist (oversim_tpu/analysis/contracts.py).
"""

from __future__ import annotations

_AVAILABLE = None


def available() -> bool:
    """True when the Pallas toolchain imports on this install — the
    scenario layer falls back to ``"scatter"`` (with a stderr note)
    when ``**.inboxImpl = "pallas"`` is requested without it."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            from jax.experimental import pallas  # noqa: F401
            from jax.experimental.pallas import tpu  # noqa: F401
            _AVAILABLE = True
        except Exception:  # noqa: BLE001 — any import failure = no plane
            _AVAILABLE = False
    return _AVAILABLE


def interpret_default() -> bool:
    """Interpret mode unless running on real TPU hardware: CPU CI runs
    the kernels through the Pallas interpreter (inline HLO, bit-exact),
    TPUs get the Mosaic-compiled kernels."""
    import jax
    return jax.default_backend() != "tpu"


# submodules import jax.experimental.pallas at module level; guard so
# `import oversim_tpu.kernels` (and the scenario fallback probe) still
# works on a pallas-less install
if available():
    from oversim_tpu.kernels import inbox, outbox  # noqa: E402,F401
