"""Campaign runner: S independent replicas as ONE compiled program.

See oversim_tpu/campaign/runner.py for the implementation and
README.md / COVERAGE.md ("Campaign subsystem") for the user guide.
"""

from oversim_tpu.campaign.runner import (  # noqa: F401
    Campaign,
    CampaignParams,
    expand_grid,
)
