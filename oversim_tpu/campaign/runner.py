"""Campaign runner: vmapped multi-replica simulation with device stats.

The reference workflow for a publishable hop-count distribution is N
repetitions of the same scenario (``./OverSim -r 0..N-1``) and a
hand-rolled average over N scalar files.  Here the N replicas ARE the
leading axis of one SimState pytree: ``jax.vmap`` of ``Simulation.step``
over every leaf turns the whole ensemble into ONE compiled program —
one compile amortized over S measurement streams, with the replica axis
shardable across chips (parallel/mesh.py REPLICA_AXIS) as pure data
parallelism: zero cross-replica collectives in the tick.

Replicas are either pure seed replicas (``CampaignParams.replicas`` per
grid point, per-replica rng = ``fold_in(PRNGKey(base_seed), r)``) or a
grid sweep: ``CampaignParams.sweep`` maps dotted parameter names
(``churn.lifetimeMean``, ``engine.window``, ``app.testMsgInterval``) to
value lists; the cartesian product is materialized as per-replica traced
scalars fed through ``Simulation.step(s, ov=...)`` — same graph, S
different parameter points.

Time semantics: replicas do NOT advance in lockstep.  Each replica's
tick horizon is its own earliest event, so after ``run_until_device``
(cond: ``any(t_now < target)``) fast replicas have overshot the target
by up to a window while slow ones just passed it — exactly like S
independent ``run_until_device`` calls, except replicas that finish
early keep ticking (harmlessly, past-target events only) until the last
one passes.  ``run_chunk`` (fixed tick count) is bit-identical to S solo
``run_chunk`` calls — the identity contract pinned by
tests/test_vmap_campaign.py.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from functools import partial

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.engine.sim import NS, SimState, _dedupe_buffers

I64 = jnp.int64


@dataclasses.dataclass(frozen=True)
class CampaignParams:
    """Static campaign shape.

    ``replicas``  — seed replicas PER grid point (S = replicas × #points)
    ``base_seed`` — replica r uses rng = fold_in(PRNGKey(base_seed), r)
    ``sweep``     — ((dotted_name, (v0, v1, ...)), ...) grid axes;
                    empty = pure seed sweep (ov=None, the engine's
                    bit-identical static-param trace)
    ``replica_ids`` — optional GLOBAL replica-id subset: run only these
                    replicas of the full replicas×grid campaign, with
                    their full-campaign rng and sweep point.  A fleet
                    worker (oversim_tpu/elastic/) holding shard
                    ``replica_ids=(4,5,6,7)`` advances rows 4..7 of the
                    full campaign bit-identically; None = all ids in
                    order (the classic full campaign).
    """

    replicas: int = 4
    base_seed: int = 1
    sweep: tuple = ()
    replica_ids: tuple | None = None


def expand_grid(sweep) -> list:
    """Cartesian product of sweep axes -> list of {name: value} dicts
    (one per grid point, declaration order = row-major)."""
    sweep = tuple(sweep)
    if not sweep:
        return [{}]
    names = [name for name, _ in sweep]
    axes = [tuple(vals) for _, vals in sweep]
    return [dict(zip(names, combo)) for combo in itertools.product(*axes)]


class Campaign:
    """Host-side driver running S replicas of one Simulation.

    Usage::

        camp = Campaign(sim, CampaignParams(replicas=8))
        cs = camp.init()                      # stacked [S, ...] SimState
        cs = camp.run_until_device(cs, 600.0) # ONE dispatch, donated
        report = camp.report(cs)              # ensemble mean/stddev/CI
    """

    def __init__(self, sim, params: CampaignParams | None = None):
        self.sim = sim
        self.p = params or CampaignParams()
        if self.p.replicas < 1:
            raise ValueError("campaign needs at least one replica")
        self.grid = expand_grid(self.p.sweep)
        # total extent of the FULL campaign; self.ids are global replica
        # ids into it (identity for classic whole-campaign runs)
        self.total = self.p.replicas * len(self.grid)
        if self.p.replica_ids is None:
            self.ids = tuple(range(self.total))
        else:
            self.ids = tuple(int(i) for i in self.p.replica_ids)
            if not self.ids:
                raise ValueError("campaign needs at least one replica id")
            bad = [i for i in self.ids if i < 0 or i >= self.total]
            if bad:
                raise ValueError(
                    f"replica_ids {bad} outside the campaign's "
                    f"0..{self.total - 1} id space")
        self.s = len(self.ids)
        # per-replica sweep values, stacked [S] in id order (global
        # replica id i belongs to grid point i // replicas)
        ftype = jnp.result_type(float)
        self.sweep_stack = {
            name: jnp.asarray(
                [self.grid[i // self.p.replicas][name] for i in self.ids],
                ftype)
            for name in (self.grid[0] or {})
        }

    # -- per-replica identities (the bit-identity contract) -----------------

    def replica_rng(self, r: int) -> jax.Array:
        """The rng replica r is initialized from — a solo
        ``sim.init_from_rng(camp.replica_rng(r))`` run IS replica r."""
        return jax.random.fold_in(
            jax.random.PRNGKey(self.p.base_seed), jnp.uint32(r))

    def replica_ov(self, r: int):
        """Local row r's sweep-override dict (None for pure seed
        sweeps) — pass to ``sim.step(s, ov=...)`` to reproduce that row
        solo.  ``r`` indexes THIS campaign's rows; ``self.ids[r]`` is
        the global replica id (identical for full campaigns)."""
        pt = self.grid[self.ids[r] // self.p.replicas]
        return dict(pt) if pt else None

    def describe(self) -> dict:
        """JSON-able campaign identity for checkpoint manifests: the
        reshard path (oversim_tpu/elastic/reshard.py) refuses to graft a
        checkpoint onto a campaign with a different base seed / grid,
        and prefix-checks ``replica_ids`` so row k always means the same
        replica before and after a grow/shrink."""
        return {
            "replicas": self.p.replicas,
            "base_seed": self.p.base_seed,
            "sweep": [[name, list(vals)] for name, vals in self.p.sweep],
            "replica_ids": list(self.ids),
            "s": self.s,
            "total": self.total,
            "inbox_impl": (self.sim.ep.inbox_impl
                           if self.sim is not None else None),
        }

    # -- init ---------------------------------------------------------------

    def init(self) -> SimState:
        """Stacked init: every SimState leaf gains a leading [S] axis.
        Row r is GLOBAL replica ``self.ids[r]`` — a subset campaign
        initializes exactly the corresponding rows of the full one."""
        rngs = jax.vmap(self.replica_rng)(jnp.asarray(self.ids))
        if self.sweep_stack:
            f = jax.jit(jax.vmap(
                lambda rng, ov: self.sim.init_from_rng(rng, ov=ov)))
            cs = f(rngs, self.sweep_stack)
        else:
            cs = jax.jit(jax.vmap(self.sim.init_from_rng))(rngs)
        # run_chunk donates; XLA CSE may alias identical stacked outputs
        # (e.g. two all-zero accumulators), so dedupe host-side like
        # Simulation.init does
        return _dedupe_buffers(cs)

    # -- stepping -----------------------------------------------------------

    def _vstep(self, cs: SimState) -> SimState:
        if self.sweep_stack:
            return jax.vmap(
                lambda s, ov: self.sim.step(s, ov=ov))(cs, self.sweep_stack)
        return jax.vmap(self.sim.step)(cs)

    @partial(jax.jit, static_argnames=("self", "n_ticks"),
             donate_argnums=(1,))
    def run_chunk(self, cs: SimState, n_ticks: int) -> SimState:
        """``n_ticks`` ticks of EVERY replica, one fused dispatch.
        Donated like Simulation.run_chunk — rebind the result."""
        def body(c, _):
            return self._vstep(c), None
        cs, _ = jax.lax.scan(body, cs, None, length=n_ticks)
        return cs

    @partial(jax.jit, static_argnames=("self", "chunk"), donate_argnums=(1,))
    def _run_until_device(self, cs: SimState, target, chunk: int) -> SimState:
        def cond(c):
            return jnp.any(c.t_now < target)

        def body(c):
            def sbody(cc, _):
                return self._vstep(cc), None
            cc, _ = jax.lax.scan(sbody, c, None, length=chunk)
            return cc

        return jax.lax.while_loop(cond, body, cs)

    def run_until_device(self, cs: SimState, t_sim: float,
                         chunk: int = 256) -> SimState:
        """Run ALL replicas past ``t_sim`` seconds in one dispatch.
        Replicas that pass the target early keep ticking (their
        past-target windows deliver only already-scheduled events) until
        the slowest replica crosses — see the module docstring."""
        target = jnp.int64(int(t_sim * NS))
        return self._run_until_device(cs, target, chunk)

    # -- reporting ----------------------------------------------------------

    # cs is deliberately NOT donated: report() is safe to call mid-run,
    # so the caller keeps using the state afterwards
    @partial(jax.jit, static_argnames=("self",))  # analysis: allow(undonated-jit)
    def _reduce(self, cs: SimState):
        return (stats_mod.ensemble_reduce(cs.stats),
                dict(t_now=cs.t_now, tick=cs.tick,
                     alive=jnp.sum(cs.alive, axis=1),
                     counters=cs.counters))

    def report(self, cs: SimState, confidence: float = 0.95) -> dict:  # analysis: allow(host-numpy, host-float, host-device-get)
        """Ensemble report: every metric as cross-replica mean/stddev/
        Student-t CI + per-replica breakdown (stats.ensemble_summary
        schema), plus ``_campaign`` metadata (grid, per-replica t_sim/
        ticks/alive, engine counters summed over replicas) and a derived
        ``kbr_delivery_ratio`` when the KBRTest counters are present.
        One jitted reduce + one device_get; safe to call mid-run."""
        import numpy as np

        reduced, meta = jax.device_get(self._reduce(cs))
        out = stats_mod.ensemble_summary(reduced, confidence)

        if "kbr_sent" in out and "kbr_delivered" in out:
            sent = np.asarray(out["kbr_sent"]["per_replica"], float)
            deliv = np.asarray(out["kbr_delivered"]["per_replica"], float)
            has = sent > 0
            ratio = np.where(has, deliv / np.maximum(sent, 1.0), np.nan)
            k = int(has.sum())
            mean = float(ratio[has].mean()) if k else math.nan
            stddev = float(ratio[has].std(ddof=1)) if k > 1 else 0.0
            sem = stddev / math.sqrt(k) if k else math.nan
            t = stats_mod.t_critical(k - 1, confidence) if k > 1 else math.nan
            out["kbr_delivery_ratio"] = {
                "kind": "derived", "k": k, "mean": mean, "stddev": stddev,
                "sem": sem, "ci": t * sem if k > 1 else math.nan,
                "confidence": confidence,
                "per_replica": [None if math.isnan(x) else float(x)
                                for x in ratio],
            }

        out["_campaign"] = {
            "replicas": self.p.replicas,
            "grid": self.grid,
            "s": self.s,
            "inbox_impl": self.sim.ep.inbox_impl,
            "replica_ids": list(self.ids),
            "base_seed": self.p.base_seed,
            "confidence": confidence,
            "t_sim": (np.asarray(meta["t_now"]) / NS).tolist(),
            "ticks": np.asarray(meta["tick"]).tolist(),
            "alive": np.asarray(meta["alive"]).tolist(),
            "engine": {k: int(np.asarray(v).sum())
                       for k, v in meta["counters"].items()},
        }
        return out

    def telemetry_report(self, cs: SimState,  # analysis: allow(host-device-get)
                         confidence: float = 0.95) -> dict:
        """Per-replica KPI time series + cross-replica CI bands off the
        stacked ``[S, W, ...]`` telemetry rings (oversim_tpu/telemetry.py
        ``ensemble_series``; bands via ``stats.series_summary``).  ONE
        device_get of the ring leaves; {"enabled": False} when the sim
        was built without ``telemetry.sample_ticks``."""
        if cs.telemetry is None:
            return {"enabled": False}
        from oversim_tpu import telemetry as telemetry_mod
        return telemetry_mod.ensemble_series(
            jax.device_get(cs.telemetry), confidence=confidence)

    def replica_state(self, cs: SimState, r: int) -> SimState:
        """Slice replica r out of the stacked state (host-side copy) —
        handy for ``sim.summary`` on one replica or debugging."""
        return jax.tree.map(lambda x: x[r], cs)
