"""GlobalTraceManager + TraceChurn: trace-file driven simulations.

Rebuild of the reference trace subsystem (src/common/GlobalTraceManager.
{h,cc} + src/common/TraceChurn.{h,cc}): a trace file drives node
creation/destruction and per-node application commands.  Line format
(simulations/dht.trace):

    <time> <nodeID> JOIN
    <time> <nodeID> LEAVE
    <time> <nodeID> PUT <key> <value>
    <time> <nodeID> GET <key>
    <time> 0 CONNECT_NODETYPES <a> <b>        (partition heal)
    <time> 0 DISCONNECT_NODETYPES <a> <b>     (partition split)

The reference mmaps the file in 32-page chunks and schedules one self-
message per line (GlobalTraceManager.h:57, ::readNextBlock); node
creation goes through UnderlayConfigurator and app commands are
forwarded as trace messages (BaseApp::handleTraceMessage, BaseApp.h:326).

TPU mapping: the whole trace is parsed host-side at build time into
static schedules —

  * JOIN/LEAVE → per-slot ``t_create``/``t_kill`` arrays consumed by the
    churn engine (churn.py model="trace"); trace nodeIDs map 1:1 onto
    engine slots;
  * PUT/GET → a `TraceWorkload` of per-slot command queues that a
    trace-aware app (apps/dht.py) drains from its timer hook;
  * CONNECT/DISCONNECT_NODETYPES → a partition-event schedule consumed
    by the underlay's connection matrix (underlay/simple.py).

String keys/values are hashed into the key space with the same sha1 the
DHT uses (core/keys.py sha1_key; reference GlobalDhtTestMap stores
OverlayKey::sha1(value)).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from oversim_tpu import churn as churn_mod
from oversim_tpu.core import keys as K


@dataclasses.dataclass
class TraceEvent:
    time: float
    node: int
    cmd: str
    args: tuple


@dataclasses.dataclass
class TraceWorkload:
    """Per-slot app command schedule ([N, Q] numpy arrays, host-side).

    ``kind``: 0 = none, 1 = PUT, 2 = GET.  ``key``/``value`` carry the
    sha1-hashed key and a stable integer id for the value string (the
    engine's DHT stores value ids, apps/dht.py).  ``key_pool`` lists the
    distinct keys (the GlobalDhtTestMap truth pool) and ``g`` each
    command's index into it."""

    t: np.ndarray        # [N, Q] f64 seconds (inf padded)
    kind: np.ndarray     # [N, Q] i32
    key: np.ndarray      # [N, Q, KL] u32
    value: np.ndarray    # [N, Q] i32
    key_pool: np.ndarray  # [G, KL] u32 distinct keys
    g: np.ndarray        # [N, Q] i32 key_pool index


@dataclasses.dataclass
class PartitionSchedule:
    """CONNECT/DISCONNECT_NODETYPES events (GlobalNodeList
    connectionMatrix, GlobalNodeList.h:232-235)."""

    t: np.ndarray        # [E] f64 seconds
    a: np.ndarray        # [E] i32 node type
    b: np.ndarray        # [E] i32 node type
    connect: np.ndarray  # [E] bool


def parse_trace(path_or_text: str | Path) -> list[TraceEvent]:
    """Parse a trace file (or literal text) into events, time-sorted.

    Files go through the native scanner (native/tracescan.c, the
    GlobalTraceManager-mmap equivalent) when the toolchain allows;
    literal text (or no compiler) uses the Python fallback."""
    text = path_or_text
    p = Path(str(path_or_text))
    if "\n" not in str(path_or_text) and p.exists():
        from oversim_tpu import native
        rows = native.scan_trace(p)
        if rows is not None:
            events = [TraceEvent(time=t, node=n, cmd=c, args=a)
                      for (t, n, c, a) in rows]
            events.sort(key=lambda e: e.time)
            return events
        text = p.read_text()
    events = []
    for line in str(text).splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) < 3:
            raise ValueError(f"bad trace line: {line!r}")
        events.append(TraceEvent(time=float(parts[0]), node=int(parts[1]),
                                 cmd=parts[2].upper(),
                                 args=tuple(parts[3:])))
    events.sort(key=lambda e: e.time)
    return events


def churn_from_trace(events, num_slots: int | None = None,
                     **kw) -> churn_mod.ChurnParams:
    """JOIN/LEAVE events → ChurnParams(model="trace").

    Trace nodeIDs are 1-based in the reference traces; slot = nodeID - min
    observed ID.  A re-JOIN of a departed ID reuses its slot only if the
    LEAVE precedes it — multiple sessions per ID are not supported (the
    dht.trace format uses one session per ID)."""
    joins: dict[int, float] = {}
    leaves: dict[int, float] = {}
    ids = [e.node for e in events if e.cmd in ("JOIN", "LEAVE")]
    if not ids:
        raise ValueError("trace contains no JOIN/LEAVE events")
    base = min(ids)
    for e in events:
        slot = e.node - base
        if e.cmd == "JOIN":
            if slot in joins:
                raise ValueError(
                    f"node {e.node}: multiple JOINs unsupported")
            joins[slot] = e.time
        elif e.cmd == "LEAVE":
            if slot not in joins or joins[slot] > e.time:
                raise ValueError(
                    f"node {e.node}: LEAVE without a prior JOIN")
            leaves[slot] = e.time
    n = num_slots or (max(joins) + 1)
    create = tuple(joins.get(i) for i in range(n))
    kill = tuple(leaves.get(i) for i in range(n))
    return churn_mod.ChurnParams(
        model="trace", target_num=n, trace_create=create, trace_kill=kill,
        **kw)


def workload_from_trace(events, num_slots: int,
                        spec: K.KeySpec = K.DEFAULT_SPEC) -> TraceWorkload:
    """PUT/GET events → per-slot command queues for a trace-driven app."""
    ids = [e.node for e in events if e.cmd in ("JOIN", "LEAVE")]
    base = min(ids) if ids else 0
    per_slot: dict[int, list] = {}
    values: dict[str, int] = {}
    pool: dict[str, int] = {}
    pool_keys: list = []
    for e in events:
        if e.cmd not in ("PUT", "GET"):
            continue
        slot = e.node - base
        if not 0 <= slot < num_slots:
            raise ValueError(f"trace command for unknown node {e.node}")
        key = np.asarray(K.sha1_key(e.args[0].encode(), spec))
        if e.args[0] not in pool:
            pool[e.args[0]] = len(pool_keys)
            pool_keys.append(key)
        gi = pool[e.args[0]]
        if e.cmd == "PUT":
            vid = values.setdefault(e.args[1], len(values) + 1)
            per_slot.setdefault(slot, []).append((e.time, 1, key, vid, gi))
        else:
            per_slot.setdefault(slot, []).append((e.time, 2, key, -1, gi))
    q = max((len(v) for v in per_slot.values()), default=1)
    t = np.full((num_slots, q), np.inf)
    kind = np.zeros((num_slots, q), np.int32)
    keys = np.zeros((num_slots, q, spec.lanes), np.uint32)
    value = np.full((num_slots, q), -1, np.int32)
    g = np.zeros((num_slots, q), np.int32)
    for slot, cmds in per_slot.items():
        for j, (tt, kk, key, vid, gi) in enumerate(cmds):
            t[slot, j] = tt
            kind[slot, j] = kk
            keys[slot, j] = key
            value[slot, j] = vid
            g[slot, j] = gi
    return TraceWorkload(t=t, kind=kind, key=keys, value=value,
                         key_pool=np.stack(pool_keys) if pool_keys
                         else np.zeros((1, spec.lanes), np.uint32), g=g)


def partitions_from_trace(events) -> PartitionSchedule:
    """CONNECT/DISCONNECT_NODETYPES events → partition schedule."""
    rows = [(e.time, int(e.args[0]), int(e.args[1]),
             e.cmd == "CONNECT_NODETYPES")
            for e in events
            if e.cmd in ("CONNECT_NODETYPES", "DISCONNECT_NODETYPES")]
    if not rows:
        return PartitionSchedule(t=np.zeros((0,)), a=np.zeros((0,), np.int32),
                                 b=np.zeros((0,), np.int32),
                                 connect=np.zeros((0,), bool))
    t, a, b, c = zip(*rows)
    return PartitionSchedule(t=np.asarray(t), a=np.asarray(a, np.int32),
                             b=np.asarray(b, np.int32),
                             connect=np.asarray(c, bool))
