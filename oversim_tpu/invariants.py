"""Runtime state-invariant checks — the debug-build sanitizer tier.

The reference runs its regression suite in a debug build whose assert
macros check structural invariants continuously (SURVEY.md §5: the
sanitizer-equivalent tier; OMNeT++ ASSERT/cRuntimeError throughout
BaseOverlay/Chord/Kademlia).  The TPU rebuild's jitted step cannot
afford in-graph asserts, so the equivalent is a HOST-side validator
run between chunks: fetch the state once, check every structural
invariant, raise with a precise diagnosis on violation.

Enable per run:     sim.run_until(..., check_invariants=True)
Enable globally:    OVERSIM_DEBUG_INVARIANTS=1  (engine/sim.py picks it
                    up in run_until; ~free when off, one device→host
                    fetch per chunk when on)

Checked invariants:

  * engine: READY ⊆ alive; pool validity within capacity; pool slots
    addressed to dead destinations are transient (bounded by pool TTL,
    not checked strictly); non-negative engine counters; monotone time.
  * Chord: successor lists NO_NODE-compacted (no live entry after a
    hole), successor entries alive-at-snapshot or NO_NODE, ring order
    of succ[0] consistent with key order for READY nodes (each ready
    node's succ0 is its clockwise-nearest ready node — the
    stabilization fixed point; only checked when the ring is quiet,
    i.e. every ready node's succ0 is ready).
  * Kademlia: per-bucket entries unique (no slot stored twice across
    the routing table), self never stored in an own bucket.

Usage pattern mirrors the reference's debug tier: tests and long
soak/scale runs switch it on; benches leave it off.
"""

from __future__ import annotations

import numpy as np

NO_NODE = -1


class InvariantViolation(AssertionError):
    pass


def _fail(name, detail):
    raise InvariantViolation(f"invariant '{name}' violated: {detail}")


def check_engine(state):
    alive = np.asarray(state.alive)  # analysis: allow(device-sync)
    pool_valid = np.asarray(state.pool.valid)  # analysis: allow(device-sync)
    t_now = int(state.t_now)  # analysis: allow(device-sync)
    if t_now < 0:
        _fail("time_monotone", f"t_now={t_now} < 0")
    n_valid = int(pool_valid.sum())
    if n_valid > pool_valid.shape[0]:
        _fail("pool_capacity", f"{n_valid} > {pool_valid.shape[0]}")
    for k, v in state.counters.items():
        if int(v) < 0:
            _fail("counter_nonnegative", f"{k}={int(v)}")
    return alive


def check_chord(state, alive):
    lg = state.logic
    if not hasattr(lg, "succ"):
        return
    succ = np.asarray(lg.succ)          # [N, S]
    n = succ.shape[0]
    # compaction: no live entry after a NO_NODE hole (the succ list is
    # maintained ring-sorted + NO_NODE padded, chord.py _succ_sorted)
    holes = succ == NO_NODE
    if np.any(holes[:, :-1] & (succ[:, 1:] != NO_NODE)):
        bad = np.nonzero(np.any(
            holes[:, :-1] & (succ[:, 1:] != NO_NODE), axis=1))[0][:5]
        _fail("chord_succ_compact", f"nodes {bad.tolist()}")
    # entries in range
    if np.any((succ != NO_NODE) & ((succ < 0) | (succ >= n))):
        _fail("chord_succ_range", "slot index out of range")
    # quiet-ring order check: when every ready node's succ0 is ready,
    # succ0 must be the clockwise-nearest ready node by key order
    try:
        ready = np.asarray(lg.state) == 2       # READY enum
    except (AttributeError, TypeError):
        return
    ready = ready & alive
    if ready.sum() < 3:
        return
    s0 = succ[:, 0]
    ready_idx = np.nonzero(ready)[0]
    quiet = all(s0[i] != NO_NODE and ready[s0[i]] for i in ready_idx)
    if not quiet:
        return
    # The gate above is necessary but not sufficient: when B joins
    # between A and A's successor C and reaches READY before A's next
    # stabilize, every ready node's succ0 is still ready yet A.succ0==C
    # is no longer clockwise-nearest — a correct transient, not a bug.
    # Only fire the order check once succ0 forms a SINGLE CYCLE over
    # exactly the ready set (the stabilization fixed point): in the
    # transient above C is succ0 of both A and B, so the map is not a
    # permutation and the check stays quiet.
    targets = s0[ready_idx]
    if (len(set(targets.tolist())) != len(ready_idx)
            or set(targets.tolist()) != set(ready_idx.tolist())):
        return
    start = ready_idx[0]
    cur, cycle_len = int(s0[start]), 1
    while cur != start and cycle_len <= len(ready_idx):
        cur = int(s0[cur])
        cycle_len += 1
    if cycle_len != len(ready_idx):
        return
    keys = np.asarray(state.node_keys)  # analysis: allow(device-sync)
    kints = [int.from_bytes(b"".join(
        int(x).to_bytes(4, "big") for x in keys[i]), "big")
        for i in range(n)]
    order = sorted(np.nonzero(ready)[0], key=lambda i: kints[i])
    for pos, i in enumerate(order):
        expect = order[(pos + 1) % len(order)]
        if s0[i] != expect:
            _fail("chord_ring_order",
                  f"node {i}: succ0={int(s0[i])} expected {expect}")


def check_kademlia(state, alive):
    lg = state.logic
    if not hasattr(lg, "buckets"):
        return
    bucket = np.asarray(lg.buckets)     # [N, B, K]
    n = bucket.shape[0]
    if np.any((bucket != NO_NODE) & ((bucket < 0) | (bucket >= n))):
        _fail("kad_bucket_range", "slot index out of range")
    flat = bucket.reshape(n, -1)
    for i in range(n):
        ent = flat[i][flat[i] != NO_NODE]
        if ent.size != np.unique(ent).size:
            _fail("kad_bucket_unique", f"node {i} stores a duplicate")
        if np.any(ent == i):
            _fail("kad_no_self", f"node {i} stores itself")


def check_state(state):
    """Run every applicable invariant check on a fetched SimState."""
    alive = check_engine(state)
    check_chord(state, alive)
    check_kademlia(state, alive)
