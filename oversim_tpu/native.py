"""ctypes bindings for the native host components (native/*.c).

The reference's runtime front-end is C++ (GlobalTraceManager's mmap
reader, the OMNeT++ ini/NED machinery); the TPU rebuild keeps the hot
host-side file path native too: ``native/tracescan.c`` scans trace
files at memory bandwidth and this module exposes it as
``scan_trace(path) -> list[TraceEvent]``.

The shared library builds lazily with the system compiler on first use
(`cc -O2 -shared -fPIC`); when no toolchain is available the caller
falls back to the pure-Python parser (oversim_tpu/trace.py parse_trace)
— same output, slower on million-line traces.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
_SRC = _ROOT / "native" / "tracescan.c"
_SO = _ROOT / "native" / "tracescan.so"
_lock = threading.Lock()
_lib = None
_failed = False

_CMD_NAMES = ("JOIN", "LEAVE", "PUT", "GET",
              "CONNECT_NODETYPES", "DISCONNECT_NODETYPES")


class _TsEvent(ctypes.Structure):
    _fields_ = [("time", ctypes.c_double),
                ("node", ctypes.c_int32),
                ("cmd", ctypes.c_int32),
                ("arg0_off", ctypes.c_int64),
                ("arg0_len", ctypes.c_int32),
                ("arg1_off", ctypes.c_int64),
                ("arg1_len", ctypes.c_int32)]


def _build() -> bool:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return True
    for cc in ("cc", "gcc", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", str(_SRC), "-o", str(_SO)],
                capture_output=True, timeout=120)
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load():
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        if not _build():
            _failed = True
            return None
        lib = ctypes.CDLL(str(_SO))
        lib.ts_scan.restype = ctypes.c_void_p
        lib.ts_scan.argtypes = [ctypes.c_char_p]
        lib.ts_count.restype = ctypes.c_long
        lib.ts_count.argtypes = [ctypes.c_void_p]
        lib.ts_buf.restype = ctypes.c_void_p
        lib.ts_buf.argtypes = [ctypes.c_void_p]
        lib.ts_events.restype = ctypes.POINTER(_TsEvent)
        lib.ts_events.argtypes = [ctypes.c_void_p]
        lib.ts_free.restype = ctypes.c_long
        lib.ts_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def scan_trace(path):
    """Native trace scan → list of (time, node, cmd, args) tuples in the
    shape trace.TraceEvent expects; None when the native path is
    unavailable (caller falls back to the Python parser)."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.ts_scan(str(path).encode())
    if not handle:
        return None
    try:
        n = lib.ts_count(handle)
        evs = lib.ts_events(handle)
        buf = lib.ts_buf(handle)
        out = []
        for i in range(n):
            e = evs[i]
            args = []
            for off, ln in ((e.arg0_off, e.arg0_len),
                            (e.arg1_off, e.arg1_len)):
                if off >= 0 and ln > 0:
                    args.append(ctypes.string_at(buf + off, ln).decode())
            out.append((e.time, e.node, _CMD_NAMES[e.cmd], tuple(args)))
        return out
    finally:
        lib.ts_free(handle)


# ---------------------------------------------------------------------------
# coordpool (native/coordpool.c): node-coordinate XML pools
# (SimpleUnderlay nodeCoordinateSource, default.ini:555)
# ---------------------------------------------------------------------------
_CP_SRC = _ROOT / "native" / "coordpool.c"
_CP_SO = _ROOT / "native" / "coordpool.so"
_cp_lib = None
_cp_failed = False


def _cp_load_lib():
    global _cp_lib, _cp_failed
    with _lock:
        if _cp_lib is not None or _cp_failed:
            return _cp_lib
        ok = False
        if _CP_SO.exists() and _CP_SO.stat().st_mtime >= _CP_SRC.stat().st_mtime:
            ok = True
        else:
            for cc in ("cc", "gcc", "clang"):
                try:
                    r = subprocess.run(
                        [cc, "-O2", "-shared", "-fPIC", str(_CP_SRC),
                         "-o", str(_CP_SO)],
                        capture_output=True, timeout=120)
                    if r.returncode == 0:
                        ok = True
                        break
                except (OSError, subprocess.TimeoutExpired):
                    continue
        if not ok:
            _cp_failed = True
            return None
        lib = ctypes.CDLL(str(_CP_SO))
        lib.cp_load.restype = ctypes.c_void_p
        lib.cp_load.argtypes = [ctypes.c_char_p]
        lib.cp_n.restype = ctypes.c_long
        lib.cp_n.argtypes = [ctypes.c_void_p]
        lib.cp_dims.restype = ctypes.c_int
        lib.cp_dims.argtypes = [ctypes.c_void_p]
        lib.cp_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.cp_data.argtypes = [ctypes.c_void_p]
        lib.cp_free.restype = None
        lib.cp_free.argtypes = [ctypes.c_void_p]
        _cp_lib = lib
        return lib


def load_coord_pool(path):
    """[P, D] float numpy array from a nodes_*.xml pool; falls back to a
    pure-Python regex parse when no toolchain is available."""
    import numpy as np
    lib = _cp_load_lib()
    if lib is not None:
        h = lib.cp_load(str(path).encode())
        if h:
            try:
                n = lib.cp_n(h)
                d = lib.cp_dims(h)
                flat = np.ctypeslib.as_array(lib.cp_data(h),
                                             shape=(n,)).copy()
                return flat.reshape(-1, d)
            finally:
                lib.cp_free(h)
    # fallback: python scan
    import re
    text = open(path).read()
    m = re.search(r'dimensions="(\d+)"', text)
    d = int(m.group(1)) if m else 2
    vals = [float(x) for x in re.findall(r"<coord>\s*([-\d.eE+]+)", text)]
    vals = vals[:len(vals) - len(vals) % d]
    return np.asarray(vals, float).reshape(-1, d)
