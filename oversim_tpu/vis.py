"""Topology visualization dump — the TopologyVis equivalent.

The reference's TopologyVis (src/common/TopologyVis.h:37-70) draws
overlay neighbor arrows in the OMNeT++ GUI (showOverlayNeighborArrow /
deleteOverlayNeighborArrow).  The engine has no GUI; the equivalent
debug surface is a SNAPSHOT extractor: pull every node's neighbor
arrows out of a live SimState and emit Graphviz DOT or JSON, so a run
can be inspected (or rendered with standard tooling) at any tick.

Arrow sources mirror what the reference draws: each overlay's
characteristic neighbor pointers —

  * Chord/Koorde: successor (ring edge) + finger arrows;
  * Kademlia: sibling-table arrows;
  * Pastry/Bamboo: leafset arrows;
  * EpiChord: successor/predecessor lists;
  * Broose: brother bucket;
  * GIA / spatial overlays (Vast/Quon): neighbor sets;
  * generic fallback: any [N, D]-shaped ``succ``/``nbr``/``sib`` field.

Usage::

    from oversim_tpu import vis
    dot = vis.to_dot(state)               # Graphviz text
    data = vis.snapshot(state)            # {"nodes": [...], "edges": [...]}
"""

from __future__ import annotations

import json

import numpy as np

# state-field name → edge kind, in priority order (first hit per field)
_EDGE_FIELDS = (
    ("succ", "successor"),
    ("pred", "predecessor"),
    ("finger", "finger"),
    ("sib", "sibling"),
    ("leaf", "leafset"),
    ("nbr", "neighbor"),
    ("bb", "brother"),
    ("db_list", "debruijn"),
)


def snapshot(state) -> dict:
    """Extract the overlay topology from a live SimState.

    Returns {"t_sim": s, "nodes": [{"id", "alive", "key"}...],
    "edges": [{"src", "dst", "kind"}...]} — the engine-side equivalent
    of the reference's per-node arrow set."""
    alive = np.asarray(state.alive)
    n = alive.shape[0]
    keys = np.asarray(state.node_keys)
    nodes = [{"id": int(i), "alive": bool(alive[i]),
              "key": "".join(f"{int(w):08x}" for w in keys[i])}
             for i in range(n)]
    edges = []
    logic = state.logic
    seen_pairs = set()
    for field, kind in _EDGE_FIELDS:
        arr = getattr(logic, field, None)
        if arr is None:
            continue
        a = np.asarray(arr)
        if a.ndim == 1:
            a = a[:, None]
        if a.ndim != 2 or a.dtype.kind not in "iu":
            continue
        for i in range(n):
            if not alive[i]:
                continue
            for j in a[i]:
                j = int(j)
                if j < 0 or j >= n or j == i:
                    continue
                pair = (i, j, kind)
                if pair in seen_pairs:
                    continue
                seen_pairs.add(pair)
                edges.append({"src": int(i), "dst": j, "kind": kind})
    return {"t_sim": float(np.asarray(state.t_now)) / 1e9,
            "nodes": nodes, "edges": edges}


_STYLE = {
    "successor": "color=black",
    "predecessor": "color=gray,style=dashed",
    "finger": "color=blue,style=dotted",
    "sibling": "color=forestgreen",
    "leafset": "color=forestgreen",
    "neighbor": "color=purple",
    "brother": "color=forestgreen",
    "debruijn": "color=red,style=dotted",
}


def to_dot(state) -> str:
    """Graphviz DOT of the current overlay topology (render with any
    standard dot/neato; the showOverlayNeighborArrow styles map to edge
    colors)."""
    snap = snapshot(state)
    lines = ["digraph overlay {", "  node [shape=circle,fontsize=8];",
             f'  label="t={snap["t_sim"]:.1f}s";']
    for nd in snap["nodes"]:
        if nd["alive"]:
            lines.append(
                f'  n{nd["id"]} [label="{nd["id"]}\\n'
                f'{nd["key"][:8]}"];')
    for e in snap["edges"]:
        style = _STYLE.get(e["kind"], "color=black")
        lines.append(f'  n{e["src"]} -> n{e["dst"]} '
                     f'[{style},tooltip="{e["kind"]}"];')
    lines.append("}")
    return "\n".join(lines)


def to_json(state) -> str:
    return json.dumps(snapshot(state), indent=1)
