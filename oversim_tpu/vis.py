"""Topology visualization dump — the TopologyVis equivalent.

The reference's TopologyVis (src/common/TopologyVis.h:37-70) draws
overlay neighbor arrows in the OMNeT++ GUI (showOverlayNeighborArrow /
deleteOverlayNeighborArrow).  The engine has no GUI; the equivalent
debug surface is a SNAPSHOT extractor: pull every node's neighbor
arrows out of a live SimState and emit Graphviz DOT or JSON, so a run
can be inspected (or rendered with standard tooling) at any tick.

Arrow sources mirror what the reference draws: each overlay's
characteristic neighbor pointers —

  * Chord/Koorde: successor (ring edge) + finger arrows;
  * Kademlia: sibling-table arrows;
  * Pastry/Bamboo: leafset arrows;
  * EpiChord: successor/predecessor lists;
  * Broose: brother bucket;
  * GIA / spatial overlays (Vast/Quon): neighbor sets;
  * generic fallback: any [N, D]-shaped ``succ``/``nbr``/``sib`` field.

Usage::

    from oversim_tpu import vis
    dot = vis.to_dot(state)               # Graphviz text
    data = vis.snapshot(state)            # {"nodes": [...], "edges": [...]}
"""

from __future__ import annotations

import json

import numpy as np

# state-field name → edge kind, in priority order (first hit per field)
_EDGE_FIELDS = (
    ("succ", "successor"),
    ("pred", "predecessor"),
    ("finger", "finger"),
    ("sib", "sibling"),
    ("leaf", "leafset"),
    ("nbr", "neighbor"),
    ("bb", "brother"),
    ("db_list", "debruijn"),
)


def snapshot(state) -> dict:
    """Extract the overlay topology from a live SimState.

    Returns {"t_sim": s, "nodes": [{"id", "alive", "key"}...],
    "edges": [{"src", "dst", "kind"}...]} — the engine-side equivalent
    of the reference's per-node arrow set."""
    alive = np.asarray(state.alive)  # analysis: allow(device-sync)
    n = alive.shape[0]
    keys = np.asarray(state.node_keys)  # analysis: allow(device-sync)
    nodes = [{"id": int(i), "alive": bool(alive[i]),
              "key": "".join(f"{int(w):08x}" for w in keys[i])}
             for i in range(n)]
    edges = []
    logic = state.logic
    seen_pairs = set()
    for field, kind in _EDGE_FIELDS:
        arr = getattr(logic, field, None)
        if arr is None:
            continue
        a = np.asarray(arr)
        if a.ndim == 1:
            a = a[:, None]
        if a.ndim != 2 or a.dtype.kind not in "iu":
            continue
        for i in range(n):
            if not alive[i]:
                continue
            for j in a[i]:
                j = int(j)
                if j < 0 or j >= n or j == i:
                    continue
                pair = (i, j, kind)
                if pair in seen_pairs:
                    continue
                seen_pairs.add(pair)
                edges.append({"src": int(i), "dst": j, "kind": kind})
    return {"t_sim": float(np.asarray(state.t_now)) / 1e9,  # analysis: allow(device-sync)
            "nodes": nodes, "edges": edges}


_STYLE = {
    "successor": "color=black",
    "predecessor": "color=gray,style=dashed",
    "finger": "color=blue,style=dotted",
    "sibling": "color=forestgreen",
    "leafset": "color=forestgreen",
    "neighbor": "color=purple",
    "brother": "color=forestgreen",
    "debruijn": "color=red,style=dotted",
}


def to_dot(state) -> str:
    """Graphviz DOT of the current overlay topology (render with any
    standard dot/neato; the showOverlayNeighborArrow styles map to edge
    colors)."""
    snap = snapshot(state)
    lines = ["digraph overlay {", "  node [shape=circle,fontsize=8];",
             f'  label="t={snap["t_sim"]:.1f}s";']
    for nd in snap["nodes"]:
        if nd["alive"]:
            lines.append(
                f'  n{nd["id"]} [label="{nd["id"]}\\n'
                f'{nd["key"][:8]}"];')
    for e in snap["edges"]:
        style = _STYLE.get(e["kind"], "color=black")
        lines.append(f'  n{e["src"]} -> n{e["dst"]} '
                     f'[{style},tooltip="{e["kind"]}"];')
    lines.append("}")
    return "\n".join(lines)


def to_json(state) -> str:
    return json.dumps(snapshot(state), indent=1)


# ---------------------------------------------------------------------------
# telemetry time-series plots (dependency-free SVG)
# ---------------------------------------------------------------------------

_PALETTE = ("#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e",
            "#17becf", "#8c564b", "#e377c2")


def _finite_pairs(t, v):
    return [(float(ti), float(vi)) for ti, vi in zip(t, v)
            if ti is not None and vi is not None
            and float(vi) == float(vi)]


def series_svg(rec, names=None, width=720, height=320) -> str:
    """Render telemetry KPI time series as a standalone SVG string.

    ``rec`` is either a solo ``telemetry.kpi_series`` dict (``t_s`` +
    ``series``) or a campaign ``telemetry.ensemble_series`` record
    (``t_s`` per replica + ``bands``) — the ensemble form draws the
    cross-replica mean line with a translucent ±CI band behind it.
    ``names`` selects tracks (default: up to 8, sorted).  No plotting
    dependency: write the string to a ``.svg`` and open it anywhere.
    """
    ensemble = "bands" in rec
    if ensemble:
        t = rec["t_s"][0] if rec.get("t_s") else []
        tracks = rec["bands"]
    else:
        t = list(np.asarray(rec["t_s"], float))
        tracks = rec["series"]
    names = list(names or sorted(tracks))[:len(_PALETTE)]

    # data extent over every plotted track (CI band edges included)
    pts_all, band_all = {}, {}
    for name in names:
        if ensemble:
            b = tracks[name]
            mean = b["mean"]
            ci = b.get("ci") or [None] * len(mean)
            pts_all[name] = _finite_pairs(t, mean)
            band_all[name] = [
                (float(ti), float(m) - float(c), float(m) + float(c))
                for ti, m, c in zip(t, mean, ci)
                if ti is not None and m is not None and c is not None]
        else:
            pts_all[name] = _finite_pairs(t, tracks[name])
    xs = [p[0] for ps in pts_all.values() for p in ps]
    ys = ([p[1] for ps in pts_all.values() for p in ps]
          + [y for bs in band_all.values() for b in bs for y in b[1:]])
    if not xs or not ys:
        return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
                f'height="{height}"><text x="10" y="20">no telemetry '
                f'samples</text></svg>')
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    ml, mr, mt, mb = 50, 160, 10, 30            # margins (legend right)
    px = lambda x: ml + (x - x0) / xr * (width - ml - mr)  # noqa: E731
    py = lambda y: (height - mb                             # noqa: E731
                    - (y - y0) / yr * (height - mt - mb))

    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="sans-serif" font-size="10">',
           f'<rect x="{ml}" y="{mt}" width="{width - ml - mr}" '
           f'height="{height - mt - mb}" fill="none" stroke="#999"/>']
    for frac in (0.0, 0.5, 1.0):                # axis labels
        out.append(f'<text x="{ml - 4}" y="{py(y0 + frac * yr) + 3:.0f}" '
                   f'text-anchor="end">{y0 + frac * yr:.4g}</text>')
        out.append(f'<text x="{px(x0 + frac * xr):.0f}" '
                   f'y="{height - mb + 14}" text-anchor="middle">'
                   f'{x0 + frac * xr:.4g}s</text>')
    for i, name in enumerate(names):
        color = _PALETTE[i % len(_PALETTE)]
        band = band_all.get(name)
        if band:
            top = " ".join(f"{px(ti):.1f},{py(hi):.1f}"
                           for ti, _, hi in band)
            bot = " ".join(f"{px(ti):.1f},{py(lo):.1f}"
                           for ti, lo, _ in reversed(band))
            out.append(f'<polygon points="{top} {bot}" fill="{color}" '
                       f'fill-opacity="0.15" stroke="none"/>')
        pts = " ".join(f"{px(xi):.1f},{py(yi):.1f}"
                       for xi, yi in pts_all[name])
        if pts:
            out.append(f'<polyline points="{pts}" fill="none" '
                       f'stroke="{color}" stroke-width="1.5"/>')
        ly = mt + 12 + i * 14                   # legend entry
        out.append(f'<rect x="{width - mr + 8}" y="{ly - 8}" width="10" '
                   f'height="10" fill="{color}"/>')
        out.append(f'<text x="{width - mr + 22}" y="{ly}">{name}</text>')
    out.append("</svg>")
    return "\n".join(out)


def write_series_svg(rec, path, names=None, **kw) -> str:
    """series_svg to a file; returns the path."""
    svg = series_svg(rec, names=names, **kw)
    with open(path, "w") as f:
        f.write(svg)
    return str(path)


def histogram_svg(counts, uppers, *, title=None, unit="",
                  width=720, height=320) -> str:
    """Render one histogram (per-bucket counts) as a standalone SVG.

    ``counts[i]`` is the NON-cumulative count of bucket i and
    ``uppers[i]`` its inclusive upper bound (``obs.Histogram
    .bucket_counts()`` shape; the final ``inf`` bucket renders as
    ``>last``).  Same dependency-free style as :func:`series_svg` —
    the run-artifact home for scripts/loadgen.py's request-latency
    histogram."""
    counts = [int(c) for c in counts]
    if len(counts) != len(uppers) or not counts:
        return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
                f'height="{height}"><text x="10" y="20">no histogram '
                f'samples</text></svg>')
    labels = []
    for u in uppers:
        u = float(u)
        if u == float("inf"):
            labels.append(f">{float(uppers[-2]):.4g}" if len(uppers) > 1
                          else ">0")
        else:
            labels.append(f"{u:.4g}")
    top = max(max(counts), 1)
    ml, mr, mt, mb = 50, 20, 24, 46             # margins (labels below)
    plot_w = width - ml - mr
    plot_h = height - mt - mb
    slot = plot_w / len(counts)
    bar_w = max(slot * 0.8, 1.0)

    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="sans-serif" font-size="10">',
           f'<rect x="{ml}" y="{mt}" width="{plot_w}" '
           f'height="{plot_h}" fill="none" stroke="#999"/>']
    if title:
        out.append(f'<text x="{ml}" y="{mt - 8}">{title}</text>')
    for frac in (0.0, 0.5, 1.0):                # count axis
        y = height - mb - frac * plot_h
        out.append(f'<text x="{ml - 4}" y="{y + 3:.0f}" '
                   f'text-anchor="end">{frac * top:.4g}</text>')
    color = _PALETTE[0]
    for i, c in enumerate(counts):
        h = c / top * plot_h
        x = ml + i * slot + (slot - bar_w) / 2
        y = height - mb - h
        out.append(f'<rect x="{x:.1f}" y="{y:.1f}" width="{bar_w:.1f}" '
                   f'height="{h:.1f}" fill="{color}">'
                   f'<title>&#8804;{labels[i]}{unit}: {c}</title></rect>')
        out.append(f'<text x="{ml + (i + 0.5) * slot:.1f}" '
                   f'y="{height - mb + 12}" text-anchor="end" '
                   f'transform="rotate(-45 {ml + (i + 0.5) * slot:.1f} '
                   f'{height - mb + 12})">{labels[i]}</text>')
    if unit:
        out.append(f'<text x="{ml + plot_w / 2:.0f}" y="{height - 4}" '
                   f'text-anchor="middle">bucket upper bound ({unit})'
                   f'</text>')
    out.append("</svg>")
    return "\n".join(out)


def write_histogram_svg(counts, uppers, path, **kw) -> str:
    """histogram_svg to a file; returns the path."""
    svg = histogram_svg(counts, uppers, **kw)
    with open(path, "w") as f:
        f.write(svg)
    return str(path)
