"""SimMud — region-based MMOG over Scribe multicast, vectorized.

Rebuild of the reference SimMud (src/tier2/simmud/SimMud.{h,cc}): the
game map is divided into square regions; each region is a multicast
group (region key = group id) on Scribe over any KBR overlay; players
multicast their position updates to their current region and re-join
the region group when they cross a boundary (SimMud.h:33-46
regionSize/playerMoveMessages).

Engine mapping: extends apps/scribe.py's tree machinery with a movement
layer (apps/movement.py generators).  The movement timer advances the
position every ``move_interval``; a region change re-targets ``group``
and forces an immediate re-subscribe; the Scribe publish IS the
position-update multicast (alm_* stats double as SimMud's move-delivery
KPIs).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.apps import movement as move_mod
from oversim_tpu.apps.scribe import (ScribeApp, ScribeParams, ScribeState,
                                     M_SUB)
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@dataclasses.dataclass(frozen=True)
class SimMudParams(ScribeParams):
    grid: int = 2                 # regions per axis (num_groups = grid²)
    move_interval: float = 5.0    # movementDelay
    move: move_mod.MoveParams = move_mod.MoveParams(field=1000.0)

    def __post_init__(self):
        object.__setattr__(self, "num_groups", self.grid * self.grid)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SimMudState(ScribeState):
    pos: jnp.ndarray       # [N, 2] f32
    wp: jnp.ndarray        # [N, 2] f32 movement waypoint
    t_move: jnp.ndarray    # [N] i64
    region_moves: jnp.ndarray  # [N] i32 — boundary crossings (stat aid)


class SimMudApp(ScribeApp):
    """Tier-2 game app (interface: apps/base.py docstring)."""

    def __init__(self, params: SimMudParams = SimMudParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC):
        super().__init__(params, spec)

    def _region_of(self, pos):
        p: SimMudParams = self.p
        cell = jnp.clip((pos / (p.move.field / p.grid)).astype(I32),
                        0, p.grid - 1)
        return cell[..., 0] * p.grid + cell[..., 1]

    def init(self, n: int) -> SimMudState:
        base_st = super().init(n)
        kw = {f.name: getattr(base_st, f.name)
              for f in dataclasses.fields(base_st)}
        pos, wp = move_mod.init_positions(jax.random.PRNGKey(97), n,
                                          self.p.move)
        return SimMudState(**kw, pos=pos, wp=wp,
                           t_move=jnp.full((n,), T_INF, I64),
                           region_moves=jnp.zeros((n,), I32))

    def on_ready(self, app, en, now, rng):
        app = super().on_ready(app, en, now, rng)
        # the joined group is the region under our feet, not random
        return dataclasses.replace(
            app,
            group=jnp.where(en, self._region_of(app.pos), app.group),
            t_move=jnp.where(en, now + jnp.int64(
                int(self.p.move_interval * NS)), app.t_move))

    def on_stop(self, app, en):
        app = super().on_stop(app, en)
        return dataclasses.replace(
            app, t_move=jnp.where(en, T_INF, app.t_move))

    def next_event(self, app):
        return jnp.minimum(super().next_event(app), app.t_move)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p: SimMudParams = self.p
        # movement tick (SimMud::handleMove): advance position, and on a
        # region crossing re-target the group + force a re-subscribe
        mv = en & (app.t_move < ctx.t_end)
        r_mv, r_rest = jax.random.split(rng)
        new_pos, new_wp = move_mod.step(app.pos, app.wp,
                                        jnp.float32(p.move_interval),
                                        r_mv, p.move,
                                        t_s=ctx.t_start.astype(
                                            jnp.float32) / NS)
        new_pos = jnp.where(mv, new_pos, app.pos)
        new_wp = jnp.where(mv, new_wp, app.wp)
        new_region = self._region_of(new_pos)
        crossed = mv & (app.group >= 0) & (new_region != app.group)
        app = dataclasses.replace(
            app,
            pos=new_pos, wp=new_wp,
            group=jnp.where(crossed, new_region, app.group),
            parent=jnp.where(crossed, NO_NODE, app.parent),
            is_root=jnp.where(crossed, False, app.is_root),
            region_moves=app.region_moves + crossed.astype(I32),
            t_sub=jnp.where(crossed, now, app.t_sub),
            t_move=jnp.where(mv, now + jnp.int64(
                int(p.move_interval * NS)), app.t_move))
        return super().on_timer(app, en, ctx, now, r_rest, ev, node_idx)
