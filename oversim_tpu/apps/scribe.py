"""Scribe application-level multicast + ALMTest workload, vectorized.

TPU-native rebuild of the reference Scribe (src/applications/scribe/
Scribe.{h,cc}: groupId rendezvous, reverse-path multicast tree with
child tables and subscription refresh/timeouts, Scribe.h:57-152) with
the ALMTest driver folded in (src/applications/almtest/ALMTest.{h,cc}:
join a group, multicast periodically, record delivery).

Engine mapping: Scribe is a tier app over any KBR overlay (apps/base.py
interface).  Each node joins one group (drawn on READY like ALMTest's
groupNum draw); a subscription resolves the group key to its rendezvous
root via the overlay lookup, then sends ScribeSubscribe directly.  The
root accepts up to ``children`` subscribers; a full table redirects the
subscriber to one of the existing children (b=1 + payload), which grows
a bounded-degree dissemination tree — the reference grows its tree from
KBR route convergence with forwarder state on interior nodes
(handleJoinMessage/children tables); redirect-on-full is the engine
equivalent (documented deviation: interior tree nodes are always group
members here).  Publishes route to the root and flood down the child
tables (ScribeDataMessage), TTL-bounded against transient cycles.
Subscriptions refresh periodically; parents prune children whose
refresh is overdue (childTimeout, Scribe.h parent/child timers).

Stats: alm_published / alm_received / alm_delivery-relevant counters —
ALMTest's delivery measurement (received vs group size is asserted by
the tests against the membership oracle).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

M_SUB, M_PUB = 0, 1     # lookup tag modes


@dataclasses.dataclass(frozen=True)
class ScribeParams:
    num_groups: int = 4
    children: int = 4             # child-table capacity per node
    subscribe_refresh: float = 30.0   # parent/subscription refresh
    child_timeout: float = 90.0   # prune silent children (childTimeout)
    publish_interval: float = 30.0    # ALMTest multicast interval
    mcast_ttl: int = 12
    payload_bytes: int = 100


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ScribeState:
    group: jnp.ndarray     # [N] i32 — joined group (-1 until READY)
    parent: jnp.ndarray    # [N] i32 — tree parent (NO_NODE = root/unjoined)
    is_root: jnp.ndarray   # [N] bool — responsible for the group key
    # per-group child tables: any node can serve as rendezvous/forwarder
    # for any group (reference Scribe keeps per-group children tables on
    # interior nodes regardless of membership, Scribe.h:57-152)
    children: jnp.ndarray  # [N, G, CH] i32
    child_seen: jnp.ndarray  # [N, G, CH] i64
    t_sub: jnp.ndarray     # [N] i64 — subscribe/refresh timer
    t_pub: jnp.ndarray     # [N] i64 — publish timer
    seq: jnp.ndarray       # [N] i32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ScribeGlobal:
    keys: jnp.ndarray      # [G, KL] u32 — group rendezvous keys


class ScribeApp:
    """Tier app (interface: apps/base.py docstring)."""

    def __init__(self, params: ScribeParams = ScribeParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC):
        self.p = params
        self.spec = spec

    def stat_spec(self):
        return dict(
            scalars=("alm_hops", "alm_latency_s"),
            hists=(),
            counters=("alm_joins", "alm_published", "alm_received",
                      "alm_sub_redirects", "alm_lookup_failed"))

    def init(self, n: int) -> ScribeState:
        ch, g = self.p.children, self.p.num_groups
        return ScribeState(
            group=jnp.full((n,), -1, I32),
            parent=jnp.full((n,), NO_NODE, I32),
            is_root=jnp.zeros((n,), bool),
            children=jnp.full((n, g, ch), NO_NODE, I32),
            child_seen=jnp.zeros((n, g, ch), I64),
            t_sub=jnp.full((n,), T_INF, I64),
            t_pub=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32))

    def glob_init(self, rng) -> ScribeGlobal:
        return ScribeGlobal(keys=keys_mod.random_keys(
            rng, (self.p.num_groups,), self.spec))

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        """Join a random group and schedule subscribe + publish
        (ALMTest::initializeApp joinGroup)."""
        r_g, r_o = jax.random.split(rng)
        g = jax.random.randint(r_g, (), 0, self.p.num_groups, dtype=I32)
        off = (jax.random.uniform(r_o, ())
               * self.p.publish_interval * NS).astype(I64)
        return dataclasses.replace(
            app,
            group=jnp.where(en, g, app.group),
            t_sub=jnp.where(en, now, app.t_sub),
            t_pub=jnp.where(en, now + off, app.t_pub))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_sub=jnp.where(en, T_INF, app.t_sub),
            t_pub=jnp.where(en, T_INF, app.t_pub))

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        """Scribe state is soft (refresh-rebuilt); nothing to hand over."""
        return app

    def next_event(self, app):
        return jnp.minimum(app.t_sub, app.t_pub)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        """Fire the subscribe-refresh or the publish; each resolves the
        group key via an overlay lookup first."""
        p = self.p
        glob: ScribeGlobal = ctx.glob
        # prune silent children (childTimeout)
        stale = (app.children != NO_NODE) & (
            app.child_seen + jnp.int64(int(p.child_timeout * NS)) < now)
        app = dataclasses.replace(
            app,
            children=jnp.where(stale, NO_NODE, app.children),
            child_seen=jnp.where(stale, 0, app.child_seen))

        sub_due = en & (app.t_sub < ctx.t_end)
        pub_due = en & ~sub_due & (app.t_pub < ctx.t_end)
        mode = jnp.where(sub_due, M_SUB, M_PUB)
        fire = (sub_due | pub_due) & (app.group >= 0)
        key = glob.keys[jnp.maximum(app.group, 0)]
        ev.count("alm_published", fire & pub_due & ctx.measuring)
        app = dataclasses.replace(
            app,
            t_sub=jnp.where(sub_due, now + jnp.int64(
                int(p.subscribe_refresh * NS)), app.t_sub),
            t_pub=jnp.where(pub_due, now + jnp.int64(
                int(p.publish_interval * NS)), app.t_pub),
            seq=app.seq + (fire & pub_due).astype(I32))
        return app, base.LookupReq(want=fire, key=key,
                                   tag=app.seq * 4 + mode)

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        en = done.en
        mode = done.tag % 4
        suc = done.success & (done.results[0] != NO_NODE)
        root = done.results[0]
        ev.count("alm_lookup_failed", en & ~suc)

        # subscribe: we ARE the root when the lookup resolves to self
        en_s = en & suc & (mode == M_SUB)
        self_root = en_s & (root == node_idx)
        ev.count("alm_joins", self_root & ~app.is_root)
        app = dataclasses.replace(
            app,
            is_root=jnp.where(en_s, self_root, app.is_root),
            parent=jnp.where(self_root, NO_NODE, app.parent))
        ob.send(en_s & ~self_root, now, root, wire.SCRIBE_SUB,
                a=app.group, size_b=wire.BASE_CALL_B + 4)

        # publish: hand the payload to the root (self-root floods locally
        # via the child table on the next on_msg loopback send)
        en_p = en & suc & (mode == M_PUB)
        ob.send(en_p, now, root, wire.SCRIBE_MCAST, a=app.group,
                b=done.tag // 4, c=jnp.int32(self.p.mcast_ttl),
                stamp=now, hops=jnp.int32(0),
                size_b=self.p.payload_bytes)
        return app

    def _child_add(self, app, en, g, child, now):
        """Add/refresh a child-table entry in group row ``g``; returns
        (app, accepted)."""
        ch = app.children.shape[-1]
        g = jnp.clip(g, 0, self.p.num_groups - 1)
        row = app.children[g]
        rseen = app.child_seen[g]
        match = (row == child) & (child != NO_NODE)
        have = jnp.any(match)
        free = row == NO_NODE
        col = jnp.where(have, jnp.argmax(match), jnp.argmax(free)).astype(I32)
        ok = en & (have | jnp.any(free))
        col = jnp.where(ok, col, ch)
        return dataclasses.replace(
            app,
            children=app.children.at[g, col].set(child, mode="drop"),
            child_seen=app.child_seen.at[g, col].set(now, mode="drop")), ok

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        p = self.p
        now = m.t_deliver

        # ScribeSubscribe → accept as child, or redirect to a child
        # (bounded-degree tree; module docstring).  Any node serves any
        # group's subscribers (rendezvous responsibility is by key)
        en = m.valid & (m.kind == wire.SCRIBE_SUB)
        mg = jnp.clip(m.a, 0, p.num_groups - 1)
        app, ok = self._child_add(app, en, mg, m.src, now)
        redirect = en & ~ok
        # pick the least-recently-refreshed child as redirect target
        grow = app.children[mg]
        gseen = app.child_seen[mg]
        tgt = grow[jnp.argmin(gseen).astype(I32)]
        redirect &= (tgt != NO_NODE) & (tgt != m.src)
        ev.count("alm_sub_redirects", redirect)
        payload = jnp.full((grow.shape[0],), NO_NODE, I32)
        ob.send(en & (ok | redirect), now, m.src, wire.SCRIBE_SUB_ACK,
                a=m.a, b=redirect.astype(I32),
                nodes=jnp.where(redirect, payload.at[0].set(tgt), payload),
                size_b=wire.BASE_CALL_B + 4)

        # SubscribeAck → adopt parent (or chase the redirect)
        en = m.valid & (m.kind == wire.SCRIBE_SUB_ACK) & (
            m.a == app.group)
        direct = en & (m.b == 0)
        app = dataclasses.replace(
            app,
            parent=jnp.where(direct, m.src, app.parent),
            is_root=jnp.where(direct, False, app.is_root))
        red_tgt = m.nodes[0]
        ob.send(en & (m.b != 0) & (red_tgt != NO_NODE), now,
                jnp.maximum(red_tgt, 0), wire.SCRIBE_SUB, a=app.group,
                size_b=wire.BASE_CALL_B + 4)

        # multicast data → deliver (members only) + forward down the
        # group's child table (forwarders need not be members)
        en = m.valid & (m.kind == wire.SCRIBE_MCAST) & (m.c > 0)
        member = en & (m.a == app.group)
        ev.count("alm_received", member & ctx.measuring)
        ev.value("alm_hops", m.hops.astype(jnp.float32),
                 member & ctx.measuring)
        ev.value("alm_latency_s",
                 (now - m.stamp).astype(jnp.float32) / NS,
                 member & ctx.measuring)
        mg = jnp.clip(m.a, 0, p.num_groups - 1)
        for i in range(p.children):
            c = app.children[mg, i]
            fwd = en & (c != NO_NODE) & (c != m.src)
            ob.send(fwd, now, c, wire.SCRIBE_MCAST, a=m.a, b=m.b,
                    c=m.c - 1, hops=m.hops + 1, stamp=m.stamp,
                    size_b=p.payload_bytes)
        return app

    @property
    def hist_map(self):
        return {}
