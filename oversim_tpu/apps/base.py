"""Tier-app interface: the Common API between overlays and applications.

The reference stacks pluggable tier apps over any overlay via the Common
API (BaseApp deliver/forward/update + routed RPC, SURVEY.md §1/§2.4,
reference src/common/BaseApp.{h,cc}).  Here an app is a strategy object
the overlay logic drives from inside its vmapped per-node step:

  stat_spec() -> dict(scalars=(), hists=(), counters=())
  init(n) -> state pytree of [N, ...] arrays
  glob_init(rng) -> simulation-global pytree (or None)   # oracle maps etc.
  post_step(ctx, app_state, glob, events) -> (app_state, glob)
      # runs un-vmapped after the node sweep: fold per-node staging
      # fields / "g:" events into the global part, clear the staging
  on_ready(state, en, now, rng) -> state    # overlay became READY
  on_stop(state, en) -> state               # node left / lost READY
  next_event(state) -> [N] i64              # earliest app timer
  on_timer(state_n, en, ctx, now, rng, ev, node_idx)
      -> (state_n, LookupReq)
      # fire app timers due in the window; optionally request ONE lookup
  on_lookup_done(state_n, done, ctx, ob, ev, now, node_idx) -> state_n
      # a requested lookup finished; ``done`` is a LookupDone; the app
      # emits follow-up messages (payload hop, DHT puts/gets) via ``ob``
  on_msg(state_n, m, ctx, ob, ev, is_sib) -> state_n
      # one inbound message of an app-owned kind (wire.py kind >= 30)
  on_leave(state_n, en, ctx, ob, ev, now, node_idx, handover) -> state_n
      # graceful-leave grace window (ctx.graceful; reference
      # NF_OVERLAY_NODE_GRACEFUL_LEAVE): hand state over to ``handover``
      # (the overlay's succession candidate) before the final kill

Optional hooks (overlays probe with hasattr; absent = zero graph cost):

  kpi_spec() -> tuple of stat names (no "s:"/"h:"/"c:" class prefix)
      # telemetry tap registry (oversim_tpu/telemetry.py resolve_taps):
      # the subset of stat_spec() worth a device-resident time-series
      # ring track when **.telemetry.sampleTicks is set.  Absent (or
      # matching nothing) = every stat is tapped;
      # **.telemetry.include substring filters override the registry.

  forward(state_n, msgs, ctx) -> veto bool (same shape as msgs.valid)
      # Common API forward() (BaseApp.h:214, BaseOverlay::callForward
      # :523): inspect messages being recursively routed THROUGH this
      # node; True vetoes the hop (the message is dropped — the
      # reference's forwardResponse without a next hop)
  on_update(state_n, en, ctx, ob, ev, now, node_idx, added,
            sib_keys=None, sib_valid=None) -> state_n
      # Common API update() (BaseApp.h:223, BaseOverlay::callUpdate
      # :640): ``added`` lists nodes that ENTERED this node's
      # sibling/replica set this tick (NO_NODE padded); the DHT uses it
      # for update()-driven maintenance re-replication.  ``sib_keys``
      # [S, KL] / ``sib_valid`` [S] carry the overlay's CURRENT local
      # sibling view (succ list / sibling table / leafset) so the app
      # can evaluate the reference's isSiblingFor responsibility test
      # (DHT.cc:746-747) per stored record
  on_tick(state_n, ctx, ob, ev, node_idx) -> state_n
      # every-tick outbox access (paced pumps); called by
      # ``leave_protocol`` from every overlay step

All hooks are pure functions over one node's slice (vmapped), except
``init/glob_init/post_step/on_ready/on_stop/next_event`` which see full
[N, ...] arrays.  ``ev`` is an `AppEvents` accumulator; ``ob`` the
engine Outbox.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

I32 = jnp.int32


@dataclasses.dataclass
class LookupReq:
    """App asks the overlay to resolve ``key``; ``tag`` comes back in the
    completion (reference: callRoute / LookupCall context pointer)."""

    want: jnp.ndarray        # bool
    key: jnp.ndarray         # [KL]
    tag: jnp.ndarray         # i32 opaque app payload


@dataclasses.dataclass
class LookupDone:
    """Completion of an app lookup (overlay → app)."""

    en: jnp.ndarray          # bool — a completion is being dispatched
    success: jnp.ndarray     # bool
    tag: jnp.ndarray         # i32
    target: jnp.ndarray      # [KL] the looked-up key
    results: jnp.ndarray     # [R] i32 sibling slots (NO_NODE padded)
    hops: jnp.ndarray        # i32
    t0: jnp.ndarray          # i64 lookup start time


def leave_protocol(app_obj, app_state, ctx, ob, ev, t0, node_idx,
                   handover, ready):
    """Per-tick app housekeeping shared by every overlay step: graceful
    leavers hand data to ``handover`` (on_leave), every leaver parks its
    app timers (on_stop — the reference's
    BaseApp::handleNodeLeaveNotification cancels the periodic tests),
    and apps with an ``on_tick`` hook (e.g. the DHT's update()-driven
    maintenance-replication pump) get their per-tick outbox access."""
    if hasattr(app_obj, "on_tick"):
        app_state = app_obj.on_tick(app_state, ctx, ob, ev, node_idx)
    app_state = app_obj.on_leave(
        app_state, ctx.graceful[node_idx] & ready, ctx, ob, ev, t0,
        node_idx, handover)
    return app_obj.on_stop(app_state, ctx.leaving[node_idx] & ready)


class AppEvents:
    """Accumulates stat events across the overlay step's unrolled handler
    calls, then finalizes into the engine events dict (values emitted
    multiple times stack into batched (values, mask) arrays)."""

    def __init__(self):
        self._counts: dict = {}
        self._vals: dict = {}

    def count(self, name: str, inc):
        inc = jnp.asarray(inc)
        if inc.dtype == bool:
            inc = inc.astype(I32)
        if inc.ndim > 0:
            inc = jnp.sum(inc)     # vector emissions fold immediately
        self._counts[name] = self._counts.get(name, jnp.int32(0)) + inc

    def value(self, name: str, val, mask):
        """``val``/``mask`` may be scalar or vector-shaped; everything is
        flattened so scalar and batched emissions of one stat coexist."""
        val = jnp.asarray(val, jnp.float32).reshape(-1)
        mask = jnp.broadcast_to(jnp.asarray(mask), val.shape).reshape(-1)
        self._vals.setdefault(name, []).append((val, mask))

    def finish(self, events: dict, hist_bins: dict | None = None):
        """Write accumulated events; ``hist_bins`` maps a scalar-event name
        to a histogram event name to emit alongside."""
        for name, v in self._counts.items():
            events["c:" + name] = events.get("c:" + name, 0) + v
        for name, pairs in self._vals.items():
            vals = jnp.concatenate([p[0] for p in pairs])
            mask = jnp.concatenate([p[1] for p in pairs])
            events["s:" + name] = (vals, mask)
            if hist_bins and name in hist_bins:
                events["h:" + hist_bins[name]] = (vals.astype(I32), mask)
        return events
