"""KBRTestApp — the reference's benchmark workload, vectorized.

Rebuild of src/applications/kbrtestapp/KBRTestApp.{h,cc}: three periodic
tests (KBRTestApp.cc:131-216), each drawing its destination key from a
random live node's nodeId (lookupNodeIds=true, default.ini:40;
KBRTestApp::createDestKey):

  * **one-way test** (testMsgInterval=60s, default.ini:38): route a test
    payload to the key; the receiver checks it is actually responsible
    and records delivery, hop count and latency; wrong-node deliveries
    count as failures (KBRTestApp.cc:252-292).  Delivery ratio =
    delivered/sent is THE headline KPI (GlobalStatistics
    sentKBRTestAppMessages/deliveredKBRTestAppMessages,
    GlobalStatistics.h:79-80);
  * **routed-RPC test** (kbrRpcTest): KbrTestCall routed to the key, the
    responsible node responds directly; success ratio + RTT recorded
    (handleRpcResponse KBRTestApp.cc:237-292).  An unanswered call is
    failed when the next RPC fires (single outstanding call per node);
  * **lookup test** (kbrLookupTest): resolve the key to its sibling set
    and validate against the global oracle — since the key IS a live
    node's nodeId, the lookup succeeds iff the first returned sibling is
    that (still-alive) node (handleLookupResponse KBRTestApp.cc:331+,
    lookupNodeIds oracle check).

Engine mapping (documented deviation): the reference runs three
independent timers with the same interval; here one timer round-robins
the enabled modes at interval/len(modes), preserving each mode's rate
while keeping the one-lookup-per-timer app interface (apps/base.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
ANY_NODE = jnp.int32(-2)   # rpc_dst wildcard: recursive routed call — the
                           # responder is unknown until the response lands
                           # (reference BaseRpc matches by nonce, not node)

# test modes (tag low bits)
M_ONEWAY, M_RPC, M_LOOKUP = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class KbrTestParams:
    test_interval: float = 60.0     # testMsgInterval, default.ini:38
    test_msg_bytes: int = 100       # testMsgSize, default.ini:37
    hop_hist_bins: int = 16
    oneway_test: bool = True        # kbrOneWayTest
    rpc_test: bool = False          # kbrRpcTest
    lookup_test: bool = False       # kbrLookupTest
    rpc_timeout: float = 10.0       # rpcKeyTimeout, default.ini:485
    msg_handle_buf: int = 8         # msgHandleBufSize, default.ini:39

    @property
    def modes(self) -> tuple:
        out = []
        if self.oneway_test:
            out.append(M_ONEWAY)
        if self.rpc_test:
            out.append(M_RPC)
        if self.lookup_test:
            out.append(M_LOOKUP)
        return tuple(out) or (M_ONEWAY,)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KbrTestState:
    t_test: jnp.ndarray   # [N] i64 — next test fire
    seq: jnp.ndarray      # [N] i32 — sequence number
    rpc_dst: jnp.ndarray  # [N] i32 — outstanding routed-RPC responder
    rpc_to: jnp.ndarray   # [N] i64 — its timeout
    rpc_t0: jnp.ndarray   # [N] i64 — its start (RTT base)
    rpc_nonce: jnp.ndarray  # [N] i32 — call nonce (stale-response guard)
    # circular (src, seqTag) duplicate filter (KBRTestApp::checkSeen,
    # KBRTestApp.cc:458-476, msgHandleBufSize ring).  Width 0 when the
    # overlay routes iteratively — the pool delivers exactly once there,
    # duplicates only arise from the recursive ACK/reroute path.
    seen_src: jnp.ndarray   # [N, B] i32
    seen_seq: jnp.ndarray   # [N, B] i32
    seen_ptr: jnp.ndarray   # [N] i32


class KbrTestApp:
    """Tier-1 app object (interface: apps/base.py docstring).

    ``rcfg`` is set by a recursive-routing overlay (common/route.py
    RouteConfig): RPC replies then travel in the transport the routing
    mode dictates (rt_mod.reply) instead of direct UDP, mirroring
    BaseRpc's routingType-driven response transport."""

    def __init__(self, params: KbrTestParams = KbrTestParams(), rcfg=None):
        self.p = params
        self.rcfg = rcfg

    @property
    def buf(self) -> int:
        """Dedup-ring width: active only under recursive routing (the
        iterative pool delivers exactly once).  A property, not frozen at
        construction — overlays patch ``app.rcfg`` after constructing the
        default app (chord.py/kademlia.py ``self.app.rcfg = rcfg``),
        before ``init`` sizes the state arrays."""
        return self.p.msg_handle_buf if self.rcfg is not None else 0

    def route_policy(self, tag):
        """Which of this app's lookup requests a recursive overlay may
        route as data instead (returns (routable, inner_kind, is_rpc)).
        One-way and routed-RPC test payloads route; the lookup test needs
        a sibling resolution and stays on the lookup engine."""
        mode = (tag // 2) % 4
        routable = (mode == M_ONEWAY) | (mode == M_RPC)
        inner = jnp.where(mode == M_ONEWAY, jnp.int32(wire.APP_ONEWAY),
                          jnp.int32(wire.APP_RPC_CALL))
        return routable, inner, mode == M_RPC

    def on_route_fired(self, app, fired, now, tag):
        """A recursive overlay routed our APP_RPC_CALL payload (no lookup
        completion will follow): arm the single-outstanding-call state
        with the ANY_NODE responder wildcard."""
        return dataclasses.replace(
            app,
            rpc_dst=jnp.where(fired, ANY_NODE, app.rpc_dst),
            rpc_to=jnp.where(fired, now + jnp.int64(
                int(self.p.rpc_timeout * NS)), app.rpc_to),
            rpc_t0=jnp.where(fired, now, app.rpc_t0),
            rpc_nonce=jnp.where(fired, tag, app.rpc_nonce))

    def kpi_spec(self):
        """Telemetry tap registry (apps/base.py; oversim_tpu/telemetry.py
        ``resolve_taps``): the KPI subset of ``stat_spec`` worth a
        time-resolved ring-buffer track — the headline parity metrics
        (hop count + its histogram, one-way latency) and the counters
        the derived delivery ratio needs.  The remaining stats stay
        end-of-run accumulators (``**.telemetry.include`` overrides)."""
        return ("kbr_hopcount", "kbr_latency_s", "kbr_hop_hist",
                "kbr_sent", "kbr_delivered", "kbr_wrong_node",
                "kbr_lookup_failed")

    def stat_spec(self):
        return dict(
            scalars=("kbr_hopcount", "kbr_latency_s", "kbr_rpc_rtt_s",
                     "kbr_lookup_latency_s"),
            hists=(("kbr_hop_hist", self.p.hop_hist_bins),),
            counters=("kbr_sent", "kbr_delivered", "kbr_wrong_node",
                      "kbr_lookup_failed", "kbr_rpc_sent",
                      "kbr_rpc_success", "kbr_rpc_failed",
                      "kbr_lookups_sent", "kbr_lookup_success",
                      "kbr_lookup_wrong"))

    def init(self, n: int) -> KbrTestState:
        return KbrTestState(t_test=jnp.full((n,), T_INF, I64),
                            seq=jnp.zeros((n,), I32),
                            rpc_dst=jnp.full((n,), NO_NODE, I32),
                            rpc_to=jnp.full((n,), T_INF, I64),
                            rpc_t0=jnp.zeros((n,), I64),
                            rpc_nonce=jnp.full((n,), -1, I32),
                            seen_src=jnp.full((n, self.buf), NO_NODE, I32),
                            seen_seq=jnp.zeros((n, self.buf), I32),
                            seen_ptr=jnp.zeros((n,), I32))

    def _check_seen(self, app, src, seq, cand):
        """Circular (src, seqTag) duplicate filter — KBRTestApp::checkSeen
        (KBRTestApp.cc:458-476).  ``cand`` [R] marks lanes to screen;
        returns (app', dup [R]).  Fresh lanes are inserted into the ring
        (oldest-overwritten), duplicates-within-the-batch also flagged."""
        b = self.buf
        dup_buf = ((app.seen_src[None, :] == src[:, None])
                   & (app.seen_seq[None, :] == seq[:, None])).any(-1)
        same = (src[:, None] == src[None, :]) & (seq[:, None] == seq[None, :])
        earlier = (jnp.tril(same, k=-1) & cand[None, :]).any(-1)
        dup = cand & (dup_buf | earlier)
        fresh = cand & ~dup
        rank = jnp.cumsum(fresh.astype(I32)) - fresh.astype(I32)
        # a batch with more than ``b`` fresh entries would wrap the ring
        # WITHIN one scatter — later lanes silently overwriting earlier
        # ones that then never entered the dedup ring.  Overflow lanes
        # are dropped from insertion instead (still screened this batch
        # via ``earlier``; the reference ring is bounded the same way,
        # KBRTestApp.cc:458-476 overwrites oldest)
        ins = fresh & (rank < b)
        pos = jnp.where(ins, (app.seen_ptr + rank) % b, b)
        app = dataclasses.replace(
            app,
            seen_src=app.seen_src.at[pos].set(src, mode="drop"),
            seen_seq=app.seen_seq.at[pos].set(seq, mode="drop"),
            seen_ptr=(app.seen_ptr
                      + jnp.sum(ins.astype(I32), dtype=I32)) % b)
        return app, dup

    def glob_init(self, rng):
        return None

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        """Overlay became READY: first test after a uniform offset
        (reference: BaseApp periodicTimer starts uniform(0, interval))."""
        off = jax.random.uniform(rng, (), minval=0.0,
                                 maxval=self.p.test_interval)
        t = now + (off * NS).astype(I64)
        return dataclasses.replace(app,
                                   t_test=jnp.where(en, t, app.t_test))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_test=jnp.where(en, T_INF, app.t_test),
            rpc_dst=jnp.where(en, NO_NODE, app.rpc_dst),
            rpc_to=jnp.where(en, T_INF, app.rpc_to))

    def next_event(self, app):
        return jnp.minimum(app.t_test, app.rpc_to)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        """Fire the periodic test; round-robin the enabled modes."""
        modes = self.p.modes
        # outstanding routed RPC timed out → failed (KBRTestApp counts
        # RPC timeouts as failures, handleRpcTimeout)
        rpc_dead = en & (app.rpc_to < ctx.t_end)
        # gate on the call's send-time measurement bit (tag low bit), like
        # handleRpcTimeout's getMeasurementPhase() check
        ev.count("kbr_rpc_failed", rpc_dead & ((app.rpc_nonce % 2) != 0))
        app = dataclasses.replace(
            app,
            rpc_dst=jnp.where(rpc_dead, NO_NODE, app.rpc_dst),
            rpc_to=jnp.where(rpc_dead, T_INF, app.rpc_to))

        en = en & (app.t_test < ctx.t_end)
        mode_idx = app.seq % len(modes)
        mode = jnp.asarray(modes, I32)[mode_idx]
        dest = ctx.sample_ready(rng)
        dest_key = ctx.keys[jnp.maximum(dest, 0)]
        want = en & (dest != NO_NODE)
        ev.count("kbr_sent", want & (mode == M_ONEWAY))
        ev.count("kbr_rpc_sent", want & (mode == M_RPC))
        ev.count("kbr_lookups_sent", want & (mode == M_LOOKUP))
        # campaign sweep hook (Ctx.ov_get): "app.testMsgInterval"
        # overrides the steady-state re-arm interval per replica.  The
        # initial on_ready offset has no Ctx and stays at the static
        # param — documented COVERAGE.md gap, irrelevant in steady state.
        iv = ctx.ov_get("app.testMsgInterval")
        if iv is None:
            interval_ns = jnp.int64(
                int(self.p.test_interval / len(modes) * NS))
        else:
            interval_ns = (jnp.asarray(iv) / len(modes) * NS).astype(I64)
        app2 = dataclasses.replace(
            app,
            t_test=jnp.where(en, now + interval_ns, app.t_test),
            seq=app.seq + en.astype(I32))
        # tag layout: (seq*4 + mode)*2 + measuring-at-SEND-time.  The low
        # bit rides through the lookup/route so delivery stats gate on the
        # send-time measurement phase exactly like the reference's
        # setMeasurementPhase-at-creation (KBRTestApp.cc:165-202) — a
        # lookup straddling measurement start can then never count as
        # delivered-but-not-sent (delivered <= sent is a reference
        # invariant, KBRTestApp::evaluateData numSent < numDelivered check)
        return app2, base.LookupReq(
            want=want, key=dest_key,
            tag=(app.seq * 4 + mode) * 2 + ctx.measuring.astype(I32))

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        en = done.en
        mode = (done.tag // 2) % 4
        meas = (done.tag % 2) != 0      # measuring at SEND time (tag bit)
        suc = done.success & (done.results[0] != NO_NODE)
        res = done.results[0]

        # ---- one-way: final payload hop to the sibling -----------------
        en_1 = en & (mode == M_ONEWAY)
        ev.count("kbr_lookup_failed", en_1 & ~suc)
        # hops on the wire = total overlay hops including this final one,
        # so iterative (lookup hops + final hop) and recursive (per-hop
        # increments) deliveries record identically.  ``c`` carries the
        # send-time measurement flag; ``a`` the seq tag for receiver dedup.
        ob.send(en_1 & suc & (res != node_idx), now, res, wire.APP_ONEWAY,
                key=done.target, hops=done.hops + 1, a=done.tag,
                c=meas.astype(I32), stamp=done.t0,
                size_b=self.p.test_msg_bytes)
        # lookup ended on ourselves → local delivery
        self_del = en_1 & suc & (res == node_idx)
        ev.count("kbr_delivered", self_del & meas)
        ev.value("kbr_hopcount", done.hops, self_del & meas)
        ev.value("kbr_latency_s",
                 (now - done.t0).astype(jnp.float32) / NS,
                 self_del & meas)

        # ---- routed RPC: KbrTestCall to the responsible node -----------
        en_r = en & (mode == M_RPC)
        ev.count("kbr_rpc_failed", en_r & ~suc & meas)
        fire_r = en_r & suc & (res != node_idx)
        ob.send(fire_r, now, res, wire.APP_RPC_CALL, key=done.target,
                a=done.tag, stamp=done.t0, size_b=self.p.test_msg_bytes)
        # resolved to ourselves → trivially successful zero-RTT call
        self_r = en_r & suc & (res == node_idx)
        ev.count("kbr_rpc_success", self_r & meas)
        app = dataclasses.replace(
            app,
            rpc_dst=jnp.where(fire_r, res, app.rpc_dst),
            rpc_to=jnp.where(fire_r, now + jnp.int64(
                int(self.p.rpc_timeout * NS)), app.rpc_to),
            rpc_t0=jnp.where(fire_r, done.t0, app.rpc_t0),
            rpc_nonce=jnp.where(fire_r, done.tag, app.rpc_nonce))

        # ---- lookup test: oracle validation ----------------------------
        # the target IS a live node's key, so the first sibling must be
        # exactly that node (KBRTestApp lookupNodeIds oracle check)
        en_l = en & (mode == M_LOOKUP)
        resk = ctx.keys[jnp.maximum(res, 0)]
        target_alive = ctx.alive[jnp.maximum(res, 0)]
        right = suc & jnp.all(resk == done.target) & target_alive
        ev.count("kbr_lookup_success", en_l & right & meas)
        ev.count("kbr_lookup_wrong", en_l & suc & ~right & meas)
        ev.count("kbr_lookup_failed", en_l & ~suc & meas)
        ev.value("kbr_lookup_latency_s",
                 (now - done.t0).astype(jnp.float32) / NS,
                 en_l & right & meas)
        return app

    def on_lookup_done_batch(self, app, done: base.LookupDone, ctx, ob, ev,
                             now, node_idx):
        """Batched completion hook: ``done`` fields are [L]-shaped (one
        lane per lookup slot).  Semantics = folding :meth:`on_lookup_done`
        over the L lanes; the at-most-one outstanding routed RPC keeps
        last-fired-wins semantics like the fold did."""
        en = done.en                                   # [L]
        mode = (done.tag // 2) % 4
        meas = (done.tag % 2) != 0      # measuring at SEND time (tag bit)
        suc = done.success & (done.results[:, 0] != NO_NODE)
        res = done.results[:, 0]

        # ---- one-way: final payload hop to the sibling -----------------
        en_1 = en & (mode == M_ONEWAY)
        ev.count("kbr_lookup_failed", en_1 & ~suc)
        ob.send(en_1 & suc & (res != node_idx), now, res, wire.APP_ONEWAY,
                key=done.target, hops=done.hops + 1, a=done.tag,
                c=meas.astype(I32), stamp=done.t0,
                size_b=self.p.test_msg_bytes)
        self_del = en_1 & suc & (res == node_idx)
        ev.count("kbr_delivered", self_del & meas)
        ev.value("kbr_hopcount", done.hops, self_del & meas)
        ev.value("kbr_latency_s",
                 (now - done.t0).astype(jnp.float32) / NS,
                 self_del & meas)

        # ---- routed RPC: KbrTestCall to the responsible node -----------
        en_r = en & (mode == M_RPC)
        ev.count("kbr_rpc_failed", en_r & ~suc & meas)
        fire_r = en_r & suc & (res != node_idx)
        ob.send(fire_r, now, res, wire.APP_RPC_CALL, key=done.target,
                a=done.tag, stamp=done.t0, size_b=self.p.test_msg_bytes)
        self_r = en_r & suc & (res == node_idx)
        ev.count("kbr_rpc_success", self_r & meas)
        # one outstanding call per node: the LAST fired lane wins (the
        # sequential fold's later where() overwrote earlier ones)
        l_dim = en.shape[0]
        any_f = jnp.any(fire_r)
        last = l_dim - 1 - jnp.argmax(fire_r[::-1]).astype(I32)
        sel = jnp.clip(last, 0, l_dim - 1)
        app = dataclasses.replace(
            app,
            rpc_dst=jnp.where(any_f, res[sel], app.rpc_dst),
            rpc_to=jnp.where(any_f, now + jnp.int64(
                int(self.p.rpc_timeout * NS)), app.rpc_to),
            rpc_t0=jnp.where(any_f, done.t0[sel], app.rpc_t0),
            rpc_nonce=jnp.where(any_f, done.tag[sel], app.rpc_nonce))

        # ---- lookup test: oracle validation ----------------------------
        en_l = en & (mode == M_LOOKUP)
        resk = ctx.keys[jnp.maximum(res, 0)]
        target_alive = ctx.alive[jnp.maximum(res, 0)]
        right = suc & jnp.all(resk == done.target, axis=-1) & target_alive
        ev.count("kbr_lookup_success", en_l & right & meas)
        ev.count("kbr_lookup_wrong", en_l & suc & ~right & meas)
        ev.count("kbr_lookup_failed", en_l & ~suc & meas)
        ev.value("kbr_lookup_latency_s",
                 (now - done.t0).astype(jnp.float32) / NS,
                 en_l & right & meas)
        return app

    def on_msgs(self, app, msgs, ctx, ob, ev, is_sib, node_idx=None):
        """Batched deliver hook: ``msgs`` is the [R]-batch Msg view and
        ``is_sib[r]`` the receiver's responsibility flag for msgs.key[r].
        Semantics = folding :meth:`on_msg` over the R slots (at most one
        outstanding RPC means at most one lane can match the client
        response check)."""
        v = msgs.valid
        en = v & (msgs.kind == wire.APP_ONEWAY)
        if self.buf:
            # duplicate screen BEFORE any accounting (checkSeen early
            # return, KBRTestApp.cc:390-399) — the recursive ACK/reroute
            # path can deliver the same payload twice
            app, dup = self._check_seen(app, msgs.src, msgs.a, en)
            en = en & ~dup
        good = en & is_sib & (msgs.c != 0)
        ev.count("kbr_delivered", good)
        ev.count("kbr_wrong_node", en & ~is_sib & (msgs.c != 0))
        ev.value("kbr_hopcount", msgs.hops, good)
        ev.value("kbr_latency_s",
                 (msgs.t_deliver - msgs.stamp).astype(jnp.float32) / NS,
                 good)

        # routed-RPC server: reply in the routing mode's transport
        # (direct UDP unless a recursive overlay set rcfg full/source)
        en = v & (msgs.kind == wire.APP_RPC_CALL)
        if (self.rcfg is not None and self.rcfg.mode in ("full", "source")
                and node_idx is not None):
            from oversim_tpu.common import route as rt_mod
            rt_mod.reply(ob, self.rcfg, en, msgs.t_deliver, msgs, ctx,
                         node_idx, wire.APP_RPC_RES, key=msgs.key,
                         a=msgs.a, stamp=msgs.stamp,
                         size_b=wire.BASE_CALL_B)
        else:
            ob.send(en, msgs.t_deliver, msgs.src, wire.APP_RPC_RES,
                    key=msgs.key, a=msgs.a, stamp=msgs.stamp,
                    size_b=wire.BASE_CALL_B)

        # routed-RPC client: RTT + success (nonce-matched; ANY_NODE
        # wildcard when the call was routed recursively)
        en = v & (msgs.kind == wire.APP_RPC_RES) & (
            (msgs.src == app.rpc_dst) | (app.rpc_dst == ANY_NODE)) & (
            msgs.a == app.rpc_nonce)
        # one success per call even if the reroute path duplicated the
        # request and both responses land in this batch (nonce matching
        # in the reference consumes the RPC state on the first response)
        en = en & (jnp.cumsum(en.astype(I32)) == 1)
        hit = jnp.any(en)
        meas_r = (app.rpc_nonce % 2) != 0   # call's send-time phase bit
        ev.count("kbr_rpc_success", en & meas_r)
        ev.value("kbr_rpc_rtt_s",
                 (msgs.t_deliver - msgs.stamp).astype(jnp.float32) / NS,
                 en & meas_r)
        app = dataclasses.replace(
            app,
            rpc_dst=jnp.where(hit, NO_NODE, app.rpc_dst),
            rpc_to=jnp.where(hit, T_INF, app.rpc_to))
        return app

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        """No state to hand over; leaving nodes just stop testing (the
        engine stops firing app timers during the grace window)."""
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        """KBRTestApp::deliver — (src, seq) dedup under recursive routing
        (checkSeen ring); wrong-node check mirrors KBRTestApp.cc:252-286."""
        en = m.valid & (m.kind == wire.APP_ONEWAY)
        if self.buf:
            app, dup = self._check_seen(app, m.src[None], m.a[None],
                                        en[None])
            en = en & ~dup[0]
        good = en & is_sib & (m.c != 0)
        ev.count("kbr_delivered", good)
        ev.count("kbr_wrong_node", en & ~is_sib & (m.c != 0))
        ev.value("kbr_hopcount", m.hops, good)
        ev.value("kbr_latency_s",
                 (m.t_deliver - m.stamp).astype(jnp.float32) / NS, good)

        # routed-RPC server: reply directly (KbrTestCall → Response)
        en = m.valid & (m.kind == wire.APP_RPC_CALL)
        ob.send(en, m.t_deliver, m.src, wire.APP_RPC_RES, key=m.key,
                a=m.a, stamp=m.stamp, size_b=wire.BASE_CALL_B)

        # routed-RPC client: RTT + success.  The echoed nonce (a) rejects
        # a straggler response from a previously timed-out call to the
        # same responder (BaseRpc nonce matching, BaseRpc.cc:293)
        en = m.valid & (m.kind == wire.APP_RPC_RES) & (
            m.src == app.rpc_dst) & (m.a == app.rpc_nonce)
        meas_r = (app.rpc_nonce % 2) != 0   # call's send-time phase bit
        ev.count("kbr_rpc_success", en & meas_r)
        ev.value("kbr_rpc_rtt_s",
                 (m.t_deliver - m.stamp).astype(jnp.float32) / NS,
                 en & meas_r)
        app = dataclasses.replace(
            app,
            rpc_dst=jnp.where(en, NO_NODE, app.rpc_dst),
            rpc_to=jnp.where(en, T_INF, app.rpc_to))
        return app

    @property
    def hist_map(self):
        return {"kbr_hopcount": "kbr_hop_hist"}
