"""KBRTestApp — the reference's benchmark workload, vectorized.

Rebuild of src/applications/kbrtestapp/KBRTestApp.{h,cc}: each node
periodically (testMsgInterval=60s, default.ini:38) routes a one-way test
message to a key drawn from a random live node's nodeId
(lookupNodeIds=true, default.ini:40; KBRTestApp::createDestKey).  The
receiving node checks it is actually responsible for the key and records
delivery, hop count and latency; wrong-node deliveries count as failures
(KBRTestApp.cc:252-292).  Delivery ratio = delivered/sent is THE headline
KPI (GlobalStatistics sentKBRTestAppMessages/deliveredKBRTestAppMessages,
GlobalStatistics.h:79-80).

The app is a passive strategy object used by the overlay logic: the
overlay calls the hooks below from inside its vmapped per-node step and
runs the actual lookups/routing.  RPC and lookup test modes
(kbrRpcTest/kbrLookupTest, off by default) are TODO.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@dataclasses.dataclass(frozen=True)
class KbrTestParams:
    test_interval: float = 60.0     # testMsgInterval, default.ini:38
    test_msg_bytes: int = 100       # testMsgSize, default.ini:37
    hop_hist_bins: int = 16


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KbrTestState:
    t_test: jnp.ndarray   # [] i64 per node — next one-way test
    seq: jnp.ndarray      # [] i32 — sequence number


def init(n: int) -> KbrTestState:
    return KbrTestState(t_test=jnp.full((n,), T_INF, I64),
                        seq=jnp.zeros((n,), I32))


STAT_SCALARS = ("kbr_hopcount", "kbr_latency_s")
STAT_COUNTERS = ("kbr_sent", "kbr_delivered", "kbr_wrong_node",
                 "kbr_lookup_failed")


def stat_spec(p: KbrTestParams):
    return dict(scalars=STAT_SCALARS,
                hists=(("kbr_hop_hist", p.hop_hist_bins),),
                counters=STAT_COUNTERS)


# -- per-node hooks (used inside the overlay's vmapped step) ---------------

def on_ready(app: KbrTestState, en, now, rng, p: KbrTestParams):
    """Overlay became READY: schedule the first test after a uniform offset
    (reference: BaseApp periodicTimer starts uniform(0, testMsgInterval))."""
    off = jax.random.uniform(rng, (), minval=0.0, maxval=p.test_interval)
    t = now + (off * NS).astype(I64)
    return dataclasses.replace(app, t_test=jnp.where(en, t, app.t_test))


def on_stop(app: KbrTestState, en):
    """Node left / lost READY: park the timer."""
    return dataclasses.replace(app,
                               t_test=jnp.where(en, T_INF, app.t_test))


def on_timer(app: KbrTestState, en, ctx, now, rng, p: KbrTestParams):
    """Fire the periodic one-way test.  Returns
    (app', want_route bool, dest_key [KL], seq i32): the overlay starts an
    iterative lookup for dest_key and sends the payload to the sibling."""
    dest = ctx.sample_ready(rng)
    dest_key = ctx.keys[jnp.maximum(dest, 0)]
    want = en & (dest != NO_NODE)
    app = dataclasses.replace(
        app,
        t_test=jnp.where(en, now + jnp.int64(int(p.test_interval * NS)),
                         app.t_test),
        seq=app.seq + en.astype(I32))
    return app, want, dest_key, app.seq


def next_event(app: KbrTestState):
    return app.t_test
