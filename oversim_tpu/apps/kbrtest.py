"""KBRTestApp — the reference's benchmark workload, vectorized.

Rebuild of src/applications/kbrtestapp/KBRTestApp.{h,cc}: each node
periodically (testMsgInterval=60s, default.ini:38) routes a one-way test
message to a key drawn from a random live node's nodeId
(lookupNodeIds=true, default.ini:40; KBRTestApp::createDestKey).  The
receiving node checks it is actually responsible for the key and records
delivery, hop count and latency; wrong-node deliveries count as failures
(KBRTestApp.cc:252-292).  Delivery ratio = delivered/sent is THE headline
KPI (GlobalStatistics sentKBRTestAppMessages/deliveredKBRTestAppMessages,
GlobalStatistics.h:79-80).

Implements the tier-app interface of apps/base.py; the RPC and lookup
test modes (kbrRpcTest/kbrLookupTest, off by default) are TODO.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@dataclasses.dataclass(frozen=True)
class KbrTestParams:
    test_interval: float = 60.0     # testMsgInterval, default.ini:38
    test_msg_bytes: int = 100       # testMsgSize, default.ini:37
    hop_hist_bins: int = 16


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KbrTestState:
    t_test: jnp.ndarray   # [N] i64 — next one-way test
    seq: jnp.ndarray      # [N] i32 — sequence number


class KbrTestApp:
    """Tier-1 app object (interface: apps/base.py docstring)."""

    def __init__(self, params: KbrTestParams = KbrTestParams()):
        self.p = params

    def stat_spec(self):
        return dict(
            scalars=("kbr_hopcount", "kbr_latency_s"),
            hists=(("kbr_hop_hist", self.p.hop_hist_bins),),
            counters=("kbr_sent", "kbr_delivered", "kbr_wrong_node",
                      "kbr_lookup_failed"))

    def init(self, n: int) -> KbrTestState:
        return KbrTestState(t_test=jnp.full((n,), T_INF, I64),
                            seq=jnp.zeros((n,), I32))

    def glob_init(self, rng):
        return None

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        """Overlay became READY: first test after a uniform offset
        (reference: BaseApp periodicTimer starts uniform(0, interval))."""
        off = jax.random.uniform(rng, (), minval=0.0,
                                 maxval=self.p.test_interval)
        t = now + (off * NS).astype(I64)
        return dataclasses.replace(app,
                                   t_test=jnp.where(en, t, app.t_test))

    def on_stop(self, app, en):
        return dataclasses.replace(app,
                                   t_test=jnp.where(en, T_INF, app.t_test))

    def next_event(self, app):
        return app.t_test

    def on_timer(self, app, en, ctx, now, rng, ev):
        """Fire the periodic one-way test: request a route to a key drawn
        from a random live node (createDestKey, lookupNodeIds=true)."""
        en = en & (app.t_test < ctx.t_end)
        dest = ctx.sample_ready(rng)
        dest_key = ctx.keys[jnp.maximum(dest, 0)]
        want = en & (dest != NO_NODE)
        ev.count("kbr_sent", want)
        app2 = dataclasses.replace(
            app,
            t_test=jnp.where(en, now + jnp.int64(
                int(self.p.test_interval * NS)), app.t_test),
            seq=app.seq + en.astype(I32))
        return app2, base.LookupReq(want=want, key=dest_key, tag=app.seq)

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        en = done.en
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("kbr_lookup_failed", en & ~suc)
        res = done.results[0]
        # final hop: payload to the sibling (sendToKey final direct hop).
        # hops on the wire = total overlay hops including this one, so
        # iterative (lookup hops + final hop) and recursive (per-hop
        # increments) deliveries record identically.
        ob.send(en & suc & (res != node_idx), now, res, wire.APP_ONEWAY,
                key=done.target, hops=done.hops + 1,
                c=ctx.measuring.astype(I32), stamp=done.t0,
                size_b=self.p.test_msg_bytes)
        # lookup ended on ourselves → local delivery
        self_del = en & suc & (res == node_idx)
        ev.count("kbr_delivered", self_del & ctx.measuring)
        ev.value("kbr_hopcount", done.hops,
                 self_del & ctx.measuring)
        ev.value("kbr_latency_s",
                 (now - done.t0).astype(jnp.float32) / NS,
                 self_del & ctx.measuring)
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        """KBRTestApp::deliver — seqnum dedup is subsumed by exactly-once
        pool delivery; wrong-node check mirrors KBRTestApp.cc:252-286."""
        en = m.valid & (m.kind == wire.APP_ONEWAY)
        good = en & is_sib & (m.c != 0)
        ev.count("kbr_delivered", good)
        ev.count("kbr_wrong_node", en & ~is_sib & (m.c != 0))
        ev.value("kbr_hopcount", m.hops, good)
        ev.value("kbr_latency_s",
                 (m.t_deliver - m.stamp).astype(jnp.float32) / NS, good)
        return app

    @property
    def hist_map(self):
        return {"kbr_hopcount": "kbr_hop_hist"}
