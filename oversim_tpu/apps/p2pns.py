"""P2PNS — P2P name service (register/resolve with cache), vectorized.

TPU-native rebuild of the reference P2PNS (src/tier2/p2pns/P2pns.{h,cc}:
a SIP/DNS-style name service over KBR — register name→id bindings at the
responsible node, resolve with a local id cache and keepalive refresh,
P2pns.h:45-99; used by XML-RPC clients in SingleHost mode).

Engine mapping (apps/base.py tier-app interface over any KBR overlay):

  * every node owns one name (its slot's entry in the global name table,
    ``glob.name_keys`` — the oracle equivalent of registering a
    user-chosen name);
  * **register**: on READY and every ``keepalive`` seconds, resolve the
    name's key and store the binding (name id → own slot) at the
    responsible node (P2pns::registerId; the reference stores via the
    tier-1 DHT with a TTL — here a direct record at the sibling with
    ``record_ttl``);
  * **resolve**: every ``resolve_interval``, pick a random live node and
    resolve its name: local cache first (P2pns twoStageResolution local
    cache), else lookup + P2pnsResolveCall to the responsible node;
    success = the returned value matches the oracle owner; successful
    resolutions fill the cache with ``cache_ttl``.

Stats: registers, resolves, cache hits, success/failure — the
reference's resolution-delay/success KPIs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
NO_VAL = jnp.int32(-1)

M_REG, M_RESOLVE = 0, 1
OP_NONE, OP_REG, OP_RESOLVE = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class P2pnsParams:
    keepalive: float = 120.0      # re-register interval
    resolve_interval: float = 30.0
    record_ttl: float = 300.0     # stored binding TTL
    cache_ttl: float = 60.0       # resolved-binding cache TTL
    cache_size: int = 8
    storage_slots: int = 16
    op_timeout: float = 10.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class P2pnsState:
    # stored bindings at the responsible node
    r_name: jnp.ndarray    # [N, D] i32 name id (-1 empty)
    r_val: jnp.ndarray     # [N, D] i32
    r_expire: jnp.ndarray  # [N, D] i64
    # local resolution cache
    c_name: jnp.ndarray    # [N, C] i32
    c_val: jnp.ndarray     # [N, C] i32
    c_expire: jnp.ndarray  # [N, C] i64
    # timers + one outstanding op
    t_reg: jnp.ndarray     # [N] i64
    t_res: jnp.ndarray     # [N] i64
    op: jnp.ndarray        # [N] i32
    op_seq: jnp.ndarray    # [N] i32
    op_name: jnp.ndarray   # [N] i32 — name id being registered/resolved
    op_expect: jnp.ndarray  # [N] i32 — oracle owner for pending resolve
    op_to: jnp.ndarray     # [N] i64
    op_t0: jnp.ndarray     # [N] i64
    seq: jnp.ndarray       # [N] i32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class P2pnsGlobal:
    name_keys: jnp.ndarray   # [N, KL] u32 — slot i owns name i


class P2pnsApp:
    """Tier-2 app (interface: apps/base.py docstring)."""

    def __init__(self, params: P2pnsParams = P2pnsParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC,
                 num_slots: int = 0):
        if num_slots <= 0:
            raise ValueError("P2pnsApp needs num_slots (= engine slots) "
                             "for the global name table")
        self.p = params
        self.spec = spec
        self.n = num_slots

    def stat_spec(self):
        return dict(
            scalars=("p2pns_resolve_latency_s",),
            hists=(),
            counters=("p2pns_registers", "p2pns_resolves",
                      "p2pns_cache_hits", "p2pns_resolve_success",
                      "p2pns_resolve_failed", "p2pns_stored"))

    def init(self, n: int) -> P2pnsState:
        p = self.p
        return P2pnsState(
            r_name=jnp.full((n, p.storage_slots), -1, I32),
            r_val=jnp.full((n, p.storage_slots), NO_VAL, I32),
            r_expire=jnp.zeros((n, p.storage_slots), I64),
            c_name=jnp.full((n, p.cache_size), -1, I32),
            c_val=jnp.full((n, p.cache_size), NO_VAL, I32),
            c_expire=jnp.zeros((n, p.cache_size), I64),
            t_reg=jnp.full((n,), T_INF, I64),
            t_res=jnp.full((n,), T_INF, I64),
            op=jnp.zeros((n,), I32),
            op_seq=jnp.zeros((n,), I32),
            op_name=jnp.full((n,), -1, I32),
            op_expect=jnp.full((n,), NO_VAL, I32),
            op_to=jnp.full((n,), T_INF, I64),
            op_t0=jnp.zeros((n,), I64),
            seq=jnp.zeros((n,), I32))

    def glob_init(self, rng) -> P2pnsGlobal:
        # one name per slot (the oracle name table)
        return P2pnsGlobal(name_keys=keys_mod.random_keys(
            rng, (self.n,), self.spec))

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        off = (jax.random.uniform(rng, ())
               * self.p.resolve_interval * NS).astype(I64)
        return dataclasses.replace(
            app,
            t_reg=jnp.where(en, now, app.t_reg),
            t_res=jnp.where(en, now + off, app.t_res))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_reg=jnp.where(en, T_INF, app.t_reg),
            t_res=jnp.where(en, T_INF, app.t_res),
            op=jnp.where(en, OP_NONE, app.op),
            op_to=jnp.where(en, T_INF, app.op_to))

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        """Bindings are soft state with keepalive refresh; hand the
        stored records to the successor like the DHT does."""
        en = en & (handover != NO_NODE) & (handover != node_idx)
        valid = app.r_name >= 0
        has = en & jnp.any(valid)
        col = jnp.argmax(valid).astype(I32)
        ob.send(has, now, handover, wire.P2PNS_REG_CALL,
                a=app.r_name[col], b=app.r_val[col],
                stamp=app.r_expire[col], size_b=wire.BASE_CALL_B + 12)
        ccol = jnp.where(has, col, app.r_name.shape[0])
        return dataclasses.replace(
            app, r_name=app.r_name.at[ccol].set(-1, mode="drop"))

    def next_event(self, app):
        t = jnp.minimum(app.t_reg, app.t_res)
        return jnp.minimum(t, app.op_to)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        glob: P2pnsGlobal = ctx.glob

        to = en & (app.op != OP_NONE) & (app.op_to < ctx.t_end)
        ev.count("p2pns_resolve_failed", to & (app.op == OP_RESOLVE))
        app = dataclasses.replace(
            app,
            op=jnp.where(to, OP_NONE, app.op),
            op_to=jnp.where(to, T_INF, app.op_to))

        idle = app.op == OP_NONE
        # a due timer must ALWAYS advance, even when an op is in flight —
        # otherwise the engine's event horizon pins simulated time on the
        # stale timer and the tick loop spins (the action just waits for
        # the next period)
        reg_hit = en & (app.t_reg < ctx.t_end)
        res_hit = en & (app.t_res < ctx.t_end)
        reg_due = reg_hit & idle
        res_due = res_hit & ~reg_due & idle

        # resolve target: a random live node's name
        tgt = ctx.sample_ready(rng)
        tgt_ok = tgt != NO_NODE
        # cache check (twoStageResolution stage 1)
        chit_mask = (app.c_name == tgt) & (app.c_expire > now) & tgt_ok
        chit = res_due & jnp.any(chit_mask)
        cval = app.c_val[jnp.argmax(chit_mask)]
        ev.count("p2pns_resolves", res_due & tgt_ok)
        ev.count("p2pns_cache_hits", chit)
        ev.count("p2pns_resolve_success",
                 chit & (cval == tgt) & ctx.measuring)
        ev.count("p2pns_registers", reg_due)

        # own name slot index == our node slot; the engine passes no
        # node_idx here, so we register via the lookup tag round-trip
        fire_reg = reg_due
        fire_res = res_due & tgt_ok & ~chit
        name_id = jnp.where(fire_reg, node_idx, tgt)
        lk_key = glob.name_keys[jnp.maximum(name_id, 0)]
        app = dataclasses.replace(
            app,
            t_reg=jnp.where(reg_hit, now + jnp.int64(
                int(p.keepalive * NS)), app.t_reg),
            t_res=jnp.where(res_hit, now + jnp.int64(
                int(p.resolve_interval * NS)), app.t_res),
            op=jnp.where(fire_reg, OP_REG,
                         jnp.where(fire_res, OP_RESOLVE, app.op)),
            op_seq=jnp.where(fire_reg | fire_res, app.seq, app.op_seq),
            op_name=jnp.where(fire_reg | fire_res, name_id, app.op_name),
            op_expect=jnp.where(fire_res, tgt, app.op_expect),
            op_to=jnp.where(fire_reg | fire_res, now + jnp.int64(
                int(p.op_timeout * NS)), app.op_to),
            op_t0=jnp.where(fire_reg | fire_res, now, app.op_t0),
            seq=app.seq + (fire_reg | fire_res).astype(I32))
        mode = jnp.where(fire_reg, M_REG, M_RESOLVE)
        return app, base.LookupReq(
            want=fire_reg | fire_res, key=lk_key,
            tag=app.op_seq * 4 + mode)

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        p = self.p
        glob: P2pnsGlobal = ctx.glob
        en = done.en & (app.op != OP_NONE) & (
            (done.tag // 4) == app.op_seq)
        suc = done.success & (done.results[0] != NO_NODE)
        fail = en & ~suc
        ev.count("p2pns_resolve_failed", fail & (app.op == OP_RESOLVE))
        app = dataclasses.replace(
            app,
            op=jnp.where(fail, OP_NONE, app.op),
            op_to=jnp.where(fail, T_INF, app.op_to))

        # register: store the binding at the responsible node
        en_r = en & suc & (app.op == OP_REG)
        ob.send(en_r, now, done.results[0], wire.P2PNS_REG_CALL,
                a=node_idx, b=node_idx,
                stamp=now + jnp.int64(int(p.record_ttl * NS)),
                size_b=wire.BASE_CALL_B + 12)
        app = dataclasses.replace(
            app,
            op=jnp.where(en_r, OP_NONE, app.op),
            op_to=jnp.where(en_r, T_INF, app.op_to))

        # resolve: query the responsible node
        en_v = en & suc & (app.op == OP_RESOLVE)
        ob.send(en_v, now, done.results[0], wire.P2PNS_RES_CALL,
                a=app.op_name, b=app.op_seq, size_b=wire.BASE_CALL_B + 8)
        return app

    def _cache_put(self, app, en, name, val, now):
        match = (app.c_name == name) & (name >= 0)
        have = jnp.any(match)
        free_col = jnp.argmin(app.c_expire).astype(I32)   # oldest/empty
        col = jnp.where(have, jnp.argmax(match), free_col).astype(I32)
        col = jnp.where(en, col, app.c_name.shape[0])
        return dataclasses.replace(
            app,
            c_name=app.c_name.at[col].set(name, mode="drop"),
            c_val=app.c_val.at[col].set(val, mode="drop"),
            c_expire=app.c_expire.at[col].set(
                now + jnp.int64(int(self.p.cache_ttl * NS)), mode="drop"))

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        p = self.p
        now = m.t_deliver

        # RegisterCall → store binding (overwrite same name / free slot /
        # evict earliest expiry)
        en = m.valid & (m.kind == wire.P2PNS_REG_CALL)
        same = (app.r_name == m.a) & (m.a >= 0)
        have = jnp.any(same)
        free = app.r_name < 0
        col = jnp.where(have, jnp.argmax(same),
                        jnp.where(jnp.any(free), jnp.argmax(free),
                                  jnp.argmin(app.r_expire))).astype(I32)
        col = jnp.where(en, col, app.r_name.shape[0])
        app = dataclasses.replace(
            app,
            r_name=app.r_name.at[col].set(m.a, mode="drop"),
            r_val=app.r_val.at[col].set(m.b, mode="drop"),
            r_expire=app.r_expire.at[col].set(m.stamp, mode="drop"))
        ev.count("p2pns_stored", en)
        # b echoes the caller's op nonce (external XML-RPC register
        # matches its ack on it; in-sim callers ignore it)
        ob.send(en, now, m.src, wire.P2PNS_REG_RES, a=m.a, b=m.b,
                size_b=wire.BASE_CALL_B)

        # ResolveCall → storage probe
        en = m.valid & (m.kind == wire.P2PNS_RES_CALL)
        hit = (app.r_name == m.a) & (m.a >= 0) & (app.r_expire > now)
        val = jnp.where(jnp.any(hit), app.r_val[jnp.argmax(hit)], NO_VAL)
        ob.send(en, now, m.src, wire.P2PNS_RES_RES, a=m.a, b=m.b, c=val,
                size_b=wire.BASE_CALL_B + 4)

        # ResolveResponse → validate vs oracle + cache
        en = (m.valid & (m.kind == wire.P2PNS_RES_RES)
              & (app.op == OP_RESOLVE) & (m.b == app.op_seq))
        good = en & (m.c == app.op_expect) & (m.c != NO_VAL)
        ev.count("p2pns_resolve_success", good & ctx.measuring)
        ev.count("p2pns_resolve_failed", en & ~good)
        ev.value("p2pns_resolve_latency_s",
                 (now - app.op_t0).astype(jnp.float32) / NS,
                 good & ctx.measuring)
        app = self._cache_put(app, good, m.a, m.c, now)
        app = dataclasses.replace(
            app,
            op=jnp.where(en, OP_NONE, app.op),
            op_to=jnp.where(en, T_INF, app.op_to))
        return app

    @property
    def hist_map(self):
        return {}
