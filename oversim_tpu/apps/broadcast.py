"""BroadcastTestApp — exercises the KBR broadcast API.

Rebuild of src/tier2/broadcasttestapp/ (849 LoC): periodically issue a
keyspace-partitioned broadcast (BaseOverlay::forwardBroadcast /
BroadcastRequestCall, BaseOverlay.h:817-818) and measure how many nodes
each blind search reaches (ChordBroadcast/PastryBroadcast configs,
omnetpp.ini:87-106).

Engine mapping: the app emits one wire.BROADCAST to itself with the full
circle as the limit (limit = own key); the OVERLAY's broadcast handler
(e.g. chord.py Chord::forwardBroadcast port) splits the range over its
routing entries hop by hop.  Every node receiving a copy counts
bcast_received; the initiator counts bcast_started — reached nodes per
broadcast ≈ received/started, the reference's coverage KPI.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@dataclasses.dataclass(frozen=True)
class BroadcastTestParams:
    interval: float = 60.0        # broadcast period per node
    payload_bytes: int = 100


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BroadcastTestState:
    t_test: jnp.ndarray   # [N] i64
    seq: jnp.ndarray      # [N] i32


class BroadcastTestApp:
    """Tier app (interface: apps/base.py docstring)."""

    def __init__(self, params: BroadcastTestParams = BroadcastTestParams()):
        self.p = params

    def stat_spec(self):
        return dict(
            scalars=("bcast_hops",),
            hists=(),
            counters=("bcast_started", "bcast_received"))

    def init(self, n: int) -> BroadcastTestState:
        return BroadcastTestState(t_test=jnp.full((n,), T_INF, I64),
                                  seq=jnp.zeros((n,), I32))

    def glob_init(self, rng):
        return None

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        off = (jax.random.uniform(rng, ()) * self.p.interval * NS
               ).astype(I64)
        return dataclasses.replace(
            app, t_test=jnp.where(en, now + off, app.t_test))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app, t_test=jnp.where(en, T_INF, app.t_test))

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        return app

    def next_event(self, app):
        return app.t_test

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        """Kick a broadcast: request a lookup of the OWN key — it
        completes locally at once (we are our own sibling) and the
        completion hook, which owns an outbox, emits the initial
        self-addressed BROADCAST with the full circle as its limit."""
        fire = en & (app.t_test < ctx.t_end)
        ev.count("bcast_started", fire & ctx.measuring)
        app = dataclasses.replace(
            app,
            t_test=jnp.where(fire, now + jnp.int64(
                int(self.p.interval * NS)), app.t_test),
            seq=app.seq + fire.astype(I32))
        return app, base.LookupReq(want=fire, key=ctx.keys[node_idx],
                                   tag=app.seq)

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        # own-key lookups resolve locally; the self-send loops back
        # through the pool at zero delay and the overlay's BROADCAST
        # handler fans it out over the routing table
        fire = done.en & done.success & (done.results[0] == node_idx)
        ob.send(fire, now, node_idx, wire.BROADCAST,
                key=ctx.keys[node_idx], a=done.tag, b=node_idx,
                hops=jnp.int32(0), size_b=self.p.payload_bytes)
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        en = m.valid & (m.kind == wire.BROADCAST)
        ev.count("bcast_received", en & ctx.measuring)
        ev.value("bcast_hops", m.hops.astype(jnp.float32),
                 en & ctx.measuring)
        return app

    @property
    def hist_map(self):
        return {}
