"""Movement generators for game workloads, vectorized.

Rebuild of the reference SimpleGameClient movement family
(src/applications/simplegameclient/MovementGenerator.{h,cc} +
RandomRoaming.cc, HotspotRoaming.cc, TraverseRoaming.cc,
GreatGathering.cc; selected by ``movementGenerator``, default.ini game
client namespace).  Every generator advances [N, 2] positions by
``speed``·dt toward a per-node waypoint and redraws the waypoint when
reached:

  * randomRoaming — uniform waypoints in the field;
  * hotspotRoaming — waypoints biased into a hotspot disc (nodes flock);
  * traverseRoaming — waypoints on the field corners (long crossings);
  * greatGathering — everyone converges on the field center.

Used by the game overlays (Vast/Quon/NTree/PubSubMMOG) and SimMud: the
same positions feed AOI neighborhoods / region subscriptions.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

F32 = jnp.float32

GEN_RANDOM, GEN_HOTSPOT, GEN_TRAVERSE, GEN_GATHER = 0, 1, 2, 3

GENERATORS = {
    "randomRoaming": GEN_RANDOM,
    "hotspotRoaming": GEN_HOTSPOT,
    "traverseRoaming": GEN_TRAVERSE,
    "greatGathering": GEN_GATHER,
}


@dataclasses.dataclass(frozen=True)
class MoveParams:
    generator: str = "randomRoaming"
    field: float = 1000.0         # areaDimension
    speed: float = 5.0            # movementSpeed (units/s)
    hotspot_radius: float = 100.0


def init_positions(rng, n: int, p: MoveParams):
    """(pos [N,2], waypoint [N,2]) uniform in the field."""
    r1, r2 = jax.random.split(rng)
    pos = jax.random.uniform(r1, (n, 2), F32, 0.0, p.field)
    return pos, draw_waypoints(r2, pos, p)


def draw_waypoints(rng, pos, p: MoveParams):
    """Per-generator waypoint draw (shape-agnostic: works on a [N, 2]
    batch or a single [2] position inside a vmapped handler)."""
    batch = pos.shape[:-1]
    g = GENERATORS[p.generator]
    if g == GEN_RANDOM:
        return jax.random.uniform(rng, pos.shape, F32, 0.0, p.field)
    if g == GEN_HOTSPOT:
        # a fixed hotspot at 1/4-field; waypoints inside its disc
        r1, r2 = jax.random.split(rng)
        center = jnp.asarray([p.field / 4, p.field / 4], F32)
        ang = jax.random.uniform(r1, batch, F32, 0.0, 2 * jnp.pi)
        rad = jnp.sqrt(jax.random.uniform(r2, batch, F32)) \
            * p.hotspot_radius
        return center + jnp.stack(
            [rad * jnp.cos(ang), rad * jnp.sin(ang)], axis=-1)
    if g == GEN_TRAVERSE:
        corner = jax.random.randint(rng, batch, 0, 4)
        cx = jnp.where((corner == 1) | (corner == 3), p.field, 0.0)
        cy = jnp.where(corner >= 2, p.field, 0.0)
        return jnp.stack([cx, cy], axis=-1).astype(F32)
    if g == GEN_GATHER:
        return jnp.broadcast_to(
            jnp.asarray([p.field / 2, p.field / 2], F32), pos.shape)
    raise ValueError(p.generator)


def step(pos, wp, dt_s, rng, p: MoveParams):
    """Advance toward the waypoint; redraw reached waypoints.

    All-[N] form (callers slice per node if needed)."""
    d = wp - pos
    dist = jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True))
    stepv = p.speed * dt_s
    reach = dist[..., 0] <= stepv
    unit = d / jnp.maximum(dist, 1e-6)
    new_pos = jnp.where(reach[..., None], wp, pos + unit * stepv)
    new_wp = jnp.where(reach[..., None], draw_waypoints(rng, pos, p), wp)
    return new_pos, new_wp
