"""Movement generators for game workloads, vectorized.

Rebuild of the reference SimpleGameClient movement family
(src/applications/simplegameclient/MovementGenerator.{h,cc} +
randomRoaming/hotspotRoaming/traverseRoaming/greatGathering/
groupRoaming/realWorldRoaming.cc; selected by ``movementGenerator``,
default.ini game client namespace).  Every generator advances [N, 2]
positions by ``speed``·dt toward a per-node waypoint and redraws the
waypoint when reached:

  * randomRoaming — uniform waypoints in the field;
  * hotspotRoaming — waypoints biased into a hotspot disc (nodes flock);
  * traverseRoaming — waypoints on the field corners (long crossings);
  * greatGathering — everyone converges on the field center;
  * groupRoaming — nodes form groups of ``group_size`` sharing one
    roaming target (groupRoaming.cc: the GlobalCoordinator stores a
    per-group target that a reaching member redraws).  The vectorized
    build derives the shared target deterministically from
    (group, epoch) with epoch = t / traversal-period — the same
    all-members-chase-one-target dynamics without cross-node shared
    state (documented deviation: redraws are time-sliced instead of
    member-triggered);
  * realWorldRoaming — positions driven by an external trajectory
    (realWorldRoaming.cc::setPosition fed from GlobalCoordinator
    scenery): a supplied waypoint script [W, 2] is played back with a
    per-node phase offset.

Used by the game overlays (Vast/Quon/NTree/PubSubMMOG) and SimMud: the
same positions feed AOI neighborhoods / region subscriptions.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

F32 = jnp.float32

(GEN_RANDOM, GEN_HOTSPOT, GEN_TRAVERSE, GEN_GATHER, GEN_GROUP,
 GEN_REALWORLD) = 0, 1, 2, 3, 4, 5

GENERATORS = {
    "randomRoaming": GEN_RANDOM,
    "hotspotRoaming": GEN_HOTSPOT,
    "traverseRoaming": GEN_TRAVERSE,
    "greatGathering": GEN_GATHER,
    "groupRoaming": GEN_GROUP,
    "realWorldRoaming": GEN_REALWORLD,
}


@dataclasses.dataclass(frozen=True)
class MoveParams:
    generator: str = "randomRoaming"
    field: float = 1000.0         # areaDimension
    speed: float = 5.0            # movementSpeed (units/s)
    hotspot_radius: float = 100.0
    group_size: int = 8           # groupRoaming groupSize
    group_seed: int = 7           # seeds the shared per-(group, epoch)
                                  # target draw — NOT the per-step rng,
                                  # which changes every tick and would
                                  # turn the held target into a walk
    # realWorldRoaming trajectory script: ((x, y), ...) waypoints the
    # external feed would deliver; played back cyclically per node
    script: tuple = ((0.0, 0.0), (500.0, 500.0), (1000.0, 0.0))


def init_positions(rng, n: int, p: MoveParams):
    """(pos [N,2], waypoint [N,2]) uniform in the field."""
    r1, r2 = jax.random.split(rng)
    pos = jax.random.uniform(r1, (n, 2), F32, 0.0, p.field)
    return pos, draw_waypoints(r2, pos, p)


def draw_waypoints(rng, pos, p: MoveParams, t_s=0.0):
    """Per-generator waypoint draw.  Shape-agnostic ([N, 2] batch or a
    single [2] position) for the classic generators; the time-sliced
    ones (group/realWorld) need the FULL [N, 2] batch — node identity
    is positional (slot // group_size, slot phase) and a per-node
    vmapped call would collapse every node onto slot 0.
    ``t_s`` (sim seconds) drives their epoch."""
    batch = pos.shape[:-1]
    g = GENERATORS[p.generator]
    if g in (GEN_GROUP, GEN_REALWORLD) and not batch:
        raise ValueError(
            f"{p.generator} requires the all-[N] form (node identity is "
            "positional); call with the full position batch")
    if g == GEN_GROUP:
        # shared per-group target, epoch-rotated: every member of group
        # gid derives the SAME uniform draw from the FIXED group seed —
        # the per-step rng must not leak in or the held target would
        # resample every tick (groupRoaming.cc holds it for a whole
        # traversal)
        n = batch[0]
        gid = jnp.arange(n) // p.group_size
        period = p.field / max(p.speed, 1e-6)          # ~one traversal
        epoch = jnp.asarray(t_s / period, jnp.int32)
        base = jax.random.PRNGKey(p.group_seed)
        def one(g_i):
            k = jax.random.fold_in(jax.random.fold_in(base, g_i), epoch)
            return jax.random.uniform(k, (2,), F32, 0.0, p.field)
        return jax.vmap(one)(gid.astype(jnp.int32))
    if g == GEN_REALWORLD:
        # external trajectory playback: script waypoint per node phase
        script = jnp.asarray(p.script, F32)            # [W, 2]
        w = script.shape[0]
        n = batch[0]
        period = p.field / max(p.speed, 1e-6)
        epoch = jnp.asarray(t_s / period, jnp.int32)
        idx = (jnp.arange(n) + epoch) % w
        return script[idx]
    if g == GEN_RANDOM:
        return jax.random.uniform(rng, pos.shape, F32, 0.0, p.field)
    if g == GEN_HOTSPOT:
        # a fixed hotspot at 1/4-field; waypoints inside its disc
        r1, r2 = jax.random.split(rng)
        center = jnp.asarray([p.field / 4, p.field / 4], F32)
        ang = jax.random.uniform(r1, batch, F32, 0.0, 2 * jnp.pi)
        rad = jnp.sqrt(jax.random.uniform(r2, batch, F32)) \
            * p.hotspot_radius
        return center + jnp.stack(
            [rad * jnp.cos(ang), rad * jnp.sin(ang)], axis=-1)
    if g == GEN_TRAVERSE:
        corner = jax.random.randint(rng, batch, 0, 4)
        cx = jnp.where((corner == 1) | (corner == 3), p.field, 0.0)
        cy = jnp.where(corner >= 2, p.field, 0.0)
        return jnp.stack([cx, cy], axis=-1).astype(F32)
    if g == GEN_GATHER:
        return jnp.broadcast_to(
            jnp.asarray([p.field / 2, p.field / 2], F32), pos.shape)
    raise ValueError(p.generator)


def step(pos, wp, dt_s, rng, p: MoveParams, t_s=0.0):
    """Advance toward the waypoint; redraw reached waypoints.

    All-[N] form (callers slice per node if needed)."""
    d = wp - pos
    dist = jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True))
    stepv = p.speed * dt_s
    reach = dist[..., 0] <= stepv
    unit = d / jnp.maximum(dist, 1e-6)
    new_pos = jnp.where(reach[..., None], wp, pos + unit * stepv)
    g = GENERATORS[p.generator]
    if g in (GEN_GROUP, GEN_REALWORLD):
        # time-sliced generators retarget on epoch rotation regardless
        # of per-node arrival (the shared target moves for everyone)
        new_wp = draw_waypoints(rng, pos, p, t_s)
    else:
        new_wp = jnp.where(reach[..., None],
                           draw_waypoints(rng, pos, p, t_s), wp)
    return new_pos, new_wp
