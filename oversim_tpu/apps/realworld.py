"""Real-network test apps served through the gateway.

Rebuilds of src/applications/realworldtestapp/ (RealWorldTestApp.{h,cc}:
echoes packets arriving from the real network through the
singlehost underlay, uppercasing the payload) and src/applications/
tcpexampleapp/ (TCPExampleApp.{h,cc}: a TCP echo/request-response demo
over SimpleTCP).

Both collapse to the same sim-side behavior here because the gateway
(oversim_tpu/gateway.py) normalizes UDP datagrams and TCP frames into
``EXT_IN`` messages: the app answers every EXT_IN with an EXT_OUT
carrying the transformed payload word, routed back to the originating
real peer by the gateway's session table.  The transport difference
(datagram vs length-prefixed stream) lives entirely in the gateway,
exactly as the reference keeps it inside the underlay's message
parsers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps.dummy import TierDummyApp, _Empty
from oversim_tpu.gateway import EXT_IN, EXT_OUT

I32 = jnp.int32


class RealworldEchoApp(TierDummyApp):
    """EXT_IN → EXT_OUT responder (RealWorldTestApp::handleRealworldPacket
    semantics: respond to the real peer with a transformed payload)."""

    def __init__(self, transform: int = 1):
        # the reference uppercases the text payload; the 32-bit payload
        # word comes back incremented by ``transform`` so tests can
        # verify the packet actually traversed the simulated node
        self.transform = transform

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        en = m.valid & (m.kind == EXT_IN)
        ob.send(en, m.t_deliver, m.src, EXT_OUT, a=m.a, b=m.b,
                c=m.c + self.transform, size_b=16)
        return app


class TcpEchoApp(RealworldEchoApp):
    """TCPExampleApp equivalent — identical sim-side logic; pair with a
    gateway constructed with ``tcp_port`` so frames arrive via TCP."""
