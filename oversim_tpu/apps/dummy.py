"""TierDummy + MyApplication — tier filler and the tutorial app.

Rebuilds of src/applications/tierdummy/ (a no-op tier pass-through used
to fill unused tier slots) and src/applications/myapplication/ (the
website tutorial's minimal app: a periodic timer that routes one test
message — a pared-down KBRTestApp).

`TierDummyApp` satisfies the tier-app interface (apps/base.py) with no
state, no timers, and no messages — plug it into any overlay logic when
no workload is wanted.  `MyApp` is the tutorial shape: one timer, one
routed message to a random key, one delivery counter.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class _Empty:
    zero: jnp.ndarray    # [N] placeholder (pytrees need one leaf)


class TierDummyApp:
    """No-op tier filler (src/applications/tierdummy, 61 LoC)."""

    def stat_spec(self):
        return dict(scalars=(), hists=(), counters=())

    def init(self, n: int) -> _Empty:
        return _Empty(zero=jnp.zeros((n,), I32))

    def glob_init(self, rng):
        return None

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        return app

    def on_stop(self, app, en):
        return app

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        return app

    def next_event(self, app):
        return jnp.full(app.zero.shape, T_INF, I64)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        return app, base.LookupReq(
            want=jnp.bool_(False),
            key=jnp.zeros((keys_mod.DEFAULT_SPEC.lanes,), jnp.uint32),
            tag=jnp.int32(0))

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        return app

    @property
    def hist_map(self):
        return {}


@dataclasses.dataclass(frozen=True)
class MyAppParams:
    interval: float = 60.0       # sendPeriod (tutorial)
    msg_bytes: int = 100


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MyAppState:
    t_send: jnp.ndarray   # [N] i64


class MyApp(TierDummyApp):
    """The tutorial application (src/applications/myapplication): send a
    message to a random key every ``interval``; count deliveries."""

    def __init__(self, params: MyAppParams = MyAppParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC):
        self.p = params
        self.spec = spec

    def stat_spec(self):
        return dict(scalars=(), hists=(),
                    counters=("myapp_sent", "myapp_delivered"))

    def init(self, n: int) -> MyAppState:
        return MyAppState(t_send=jnp.full((n,), T_INF, I64))

    def on_ready(self, app, en, now, rng):
        off = (jax.random.uniform(rng, ()) * self.p.interval * NS
               ).astype(I64)
        return dataclasses.replace(
            app, t_send=jnp.where(en, now + off, app.t_send))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app, t_send=jnp.where(en, T_INF, app.t_send))

    def next_event(self, app):
        return app.t_send

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        fire = en & (app.t_send < ctx.t_end)
        key = keys_mod.random_keys(rng, (), self.spec)
        ev.count("myapp_sent", fire & ctx.measuring)
        app = dataclasses.replace(app, t_send=jnp.where(
            fire, now + jnp.int64(int(self.p.interval * NS)), app.t_send))
        return app, base.LookupReq(want=fire, key=key, tag=jnp.int32(0))

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        suc = done.en & done.success & (done.results[0] != NO_NODE)
        ob.send(suc & (done.results[0] != node_idx), now, done.results[0],
                wire.APP_ONEWAY, key=done.target, hops=done.hops + 1,
                c=ctx.measuring.astype(I32), stamp=done.t0,
                size_b=self.p.msg_bytes)
        ev.count("myapp_delivered",
                 suc & (done.results[0] == node_idx) & ctx.measuring)
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        en = m.valid & (m.kind == wire.APP_ONEWAY) & (m.c != 0) & is_sib
        ev.count("myapp_delivered", en)
        return app
