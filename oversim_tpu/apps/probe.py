"""ConnectivityProbe: global connectivity metrics for spatial overlays.

Rebuild of the reference ConnectivityProbeApp
(src/applications/simplegameclient/ConnectivityProbeApp.{h,cc}): a
global function that periodically extracts the game overlay's topology
and records, against the ground-truth AOI neighborhoods implied by the
actual positions:

  * node count;
  * nodes with ZERO missing AOI neighbors;
  * average / maximum missing-neighbor count;
  * average drift between a node's own position and where its
    neighbors believe it is (cOV_AverageDrift).

Host-side analysis over the overlay's [N, ...] state arrays — the
reference's probe also reads every SimpleGameClient's state directly
(extractTopology); no wire traffic is involved in either build.
Works for any overlay exposing (pos [N,2], nbr [N,D], nbr_pos
[N,D,2]) — Vast and Quon do.
"""

from __future__ import annotations

import numpy as np

NO_NODE = -1


def connectivity_probe(pos, alive, nbr, nbr_pos, aoi: float) -> dict:
    """Compute the ConnectivityProbeApp metric set.

    Args: pos [N,2] actual positions; alive [N] bool; nbr [N,D] overlay
    neighbor slots (NO_NODE padded); nbr_pos [N,D,2] the believed
    positions of those neighbors; aoi — the AOI radius.
    """
    pos = np.asarray(pos, np.float64)
    alive = np.asarray(alive, bool)
    nbr = np.asarray(nbr)
    nbr_pos = np.asarray(nbr_pos, np.float64)
    n = pos.shape[0]

    d = np.linalg.norm(pos[:, None, :] - pos[None, :, :], axis=-1)
    truth = (d <= aoi) & alive[:, None] & alive[None, :]
    np.fill_diagonal(truth, False)

    known = np.zeros_like(truth)
    rows = np.repeat(np.arange(n), nbr.shape[1])
    cols = nbr.reshape(-1)
    okm = (cols != NO_NODE) & alive[rows]
    known[rows[okm], np.clip(cols[okm], 0, n - 1)] = True

    missing = (truth & ~known).sum(axis=1)[alive]
    node_count = int(alive.sum())

    # drift: |believed position of neighbor - its actual position|
    drift_num, drift_den = 0.0, 0
    for i in np.nonzero(alive)[0]:
        for j, slot in enumerate(nbr[i]):
            if slot != NO_NODE and alive[slot]:
                drift_num += float(
                    np.linalg.norm(nbr_pos[i, j] - pos[slot]))
                drift_den += 1

    return {
        "node_count": node_count,
        "zero_missing": int((missing == 0).sum()) if node_count else 0,
        "avg_missing": float(missing.mean()) if node_count else 0.0,
        "max_missing": int(missing.max()) if node_count else 0,
        "avg_drift": drift_num / drift_den if drift_den else 0.0,
    }
