"""TierStack — generic multi-tier app composition (the ITier equivalent).

The reference stacks up to three tier applications over any overlay via
string config (``tier1Type/tier2Type/tier3Type``,
SimpleOverlayHost.ned:14-100, default.ini:622-628); each tier speaks the
Common API downward.  Here a :class:`TierStack` is itself an app object
(apps/base.py interface) delegating to an ordered tuple of member apps,
so ANY overlay logic hosts any combination without per-combo wiring
(config/scenario.py's former special cases).

Mechanics:

  * state/glob are tuples of the members' states/globs (pytrees);
  * inbound messages go to every member in order — apps already filter
    by their own wire kinds;
  * lookups multiplex on the tag: ``tag' = tag * T + tier`` — each
    completion dispatches back to its owning tier; route_policy /
    on_route_fired follow the same encoding;
  * one lookup request per node per window (the engine app contract):
    when several tiers' timers are due in one window, the earliest-due
    tier fires and the others keep their timers — the engine's event
    horizon re-fires them next tick (delay ≤ one window);
  * optional hooks (forward/on_update/on_tick/on_msgs/route_policy)
    exist on the stack only if some member has them, preserving the
    overlays' hasattr-probing zero-cost-when-absent convention.

Stat names must be disjoint across members (they are for the shipped
apps; stacking two instances of the same app needs distinct prefixes).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base

I32 = jnp.int32
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)


class TierStack:
    """Composite tier app (interface: apps/base.py docstring)."""

    def __init__(self, apps):
        if not apps:
            raise ValueError("TierStack needs at least one app")
        self.apps = tuple(apps)
        # optional hooks mirror the members (hasattr probing)
        if any(hasattr(a, "on_msgs") for a in self.apps):
            self.on_msgs = self._on_msgs
        if any(hasattr(a, "forward") for a in self.apps):
            self.forward = self._forward
        if any(hasattr(a, "on_update") for a in self.apps):
            self.on_update = self._on_update
        if any(hasattr(a, "on_tick") for a in self.apps):
            self.on_tick = self._on_tick
        if any(hasattr(a, "route_policy") for a in self.apps):
            self.route_policy = self._route_policy
            self.on_route_fired = self._on_route_fired
        names = [n for a in self.apps for n in a.stat_spec()["counters"]]
        if len(names) != len(set(names)):
            raise ValueError("stacked apps must have disjoint stat names")

    # rcfg pass-through: overlays patch ``app.rcfg`` — fan out
    @property
    def rcfg(self):
        return getattr(self.apps[0], "rcfg", None)

    @rcfg.setter
    def rcfg(self, value):
        for a in self.apps:
            if hasattr(a, "rcfg"):
                a.rcfg = value

    # dist_fn pass-through: ring overlays patch ``app.dist_fn`` with
    # their responsibility metric (chord.py:173/pastry.py) — without
    # this forwarding, a DhtApp INSIDE a stack would silently keep the
    # XOR fallback and the maintenance responsibility filter would
    # judge ring keyspace with the wrong metric
    @property
    def dist_fn(self):
        # None while ANY member still awaits its metric, so the
        # overlay's ``getattr(app, "dist_fn", "no") is None`` probe
        # fires and the setter fans out
        if any(getattr(a, "dist_fn", "set") is None for a in self.apps):
            return None
        return getattr(self.apps[0], "dist_fn", None)

    @dist_fn.setter
    def dist_fn(self, value):
        for a in self.apps:
            if getattr(a, "dist_fn", "set") is None:
                a.dist_fn = value

    def stat_spec(self):
        out = dict(scalars=(), hists=(), counters=())
        for a in self.apps:
            s = a.stat_spec()
            out["scalars"] += tuple(s["scalars"])
            out["hists"] += tuple(s["hists"])
            out["counters"] += tuple(s["counters"])
        return out

    @property
    def hist_map(self):
        out = {}
        for a in self.apps:
            out.update(a.hist_map)
        return out

    def _ctx(self, ctx, i):
        """Member view of the tick context: its own glob slice."""
        if isinstance(ctx.glob, tuple):
            return dataclasses.replace(ctx, glob=ctx.glob[i])
        return ctx

    def init(self, n: int):
        return tuple(a.init(n) for a in self.apps)

    def glob_init(self, rng):
        rngs = jax.random.split(rng, len(self.apps))
        return tuple(a.glob_init(r) for a, r in zip(self.apps, rngs))

    def post_step(self, ctx, states, globs, events):
        outs = [a.post_step(self._ctx(ctx, i), s, g, events)
                for i, (a, s, g) in enumerate(zip(self.apps, states,
                                                  globs))]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    def on_ready(self, states, en, now, rng):
        rngs = jax.random.split(rng, len(self.apps))
        return tuple(a.on_ready(s, en, now, r)
                     for a, s, r in zip(self.apps, states, rngs))

    def on_stop(self, states, en):
        return tuple(a.on_stop(s, en)
                     for a, s in zip(self.apps, states))

    def on_leave(self, states, en, ctx, ob, ev, now, node_idx, handover):
        return tuple(a.on_leave(s, en, self._ctx(ctx, i), ob, ev, now,
                                node_idx, handover)
                     for i, (a, s) in enumerate(zip(self.apps, states)))

    def next_event(self, states):
        t = self.apps[0].next_event(states[0])
        for a, s in zip(self.apps[1:], states[1:]):
            t = jnp.minimum(t, a.next_event(s))
        return t

    # -- timers: earliest-due tier fires this window ---------------------

    def on_timer(self, states, en, ctx, now, rng, ev, node_idx):
        T = len(self.apps)
        rngs = jax.random.split(rng, T)
        # pick on each tier's on_timer-relevant clock (timer_event when
        # defined), NOT next_event: the DHT maintenance pump holds
        # next_event at 0 for its whole duration (it runs via on_tick)
        # and would monopolize the stack's one timer slot per window,
        # deferring other tiers' rpc-timeout processing unboundedly
        nevs = jnp.stack([getattr(a, "timer_event", a.next_event)(s)
                          for a, s in zip(self.apps, states)])
        pick = jnp.argmin(nevs).astype(I32)
        new_states = []
        want = jnp.bool_(False)
        key = None
        tag = jnp.int32(0)
        for i, (a, s, r) in enumerate(zip(self.apps, states, rngs)):
            en_i = en & (pick == i)
            s2, req = a.on_timer(s, en_i, self._ctx(ctx, i), now, r, ev,
                                 node_idx)
            new_states.append(s2)
            fire_i = req.want & en_i
            key = req.key if key is None else jnp.where(fire_i, req.key,
                                                        key)
            tag = jnp.where(fire_i, req.tag * T + i, tag)
            want = want | fire_i
        return tuple(new_states), base.LookupReq(want=want, key=key,
                                                 tag=tag)

    def on_lookup_done(self, states, done, ctx, ob, ev, now, node_idx):
        T = len(self.apps)
        tier = done.tag % T
        inner = dataclasses.replace(done, tag=done.tag // T)
        return tuple(
            a.on_lookup_done(
                s, dataclasses.replace(inner, en=done.en & (tier == i)),
                self._ctx(ctx, i), ob, ev, now, node_idx)
            for i, (a, s) in enumerate(zip(self.apps, states)))

    # -- messages ---------------------------------------------------------

    def on_msg(self, states, m, ctx, ob, ev, is_sib):
        return tuple(a.on_msg(s, m, self._ctx(ctx, i), ob, ev, is_sib)
                     for i, (a, s) in enumerate(zip(self.apps, states)))

    def _on_msgs(self, states, msgs, ctx, ob, ev, is_sib, node_idx=None):
        import inspect
        out = []
        for i, (a, s) in enumerate(zip(self.apps, states)):
            ctx_i = self._ctx(ctx, i)
            if hasattr(a, "on_msgs"):
                # signature-probe for the optional node_idx kwarg (a
                # try/except around the CALL would swallow genuine
                # TypeErrors and replay the handler's Outbox sends)
                params = inspect.signature(a.on_msgs).parameters
                if "node_idx" in params:
                    s = a.on_msgs(s, msgs, ctx_i, ob, ev, is_sib,
                                  node_idx=node_idx)
                else:
                    s = a.on_msgs(s, msgs, ctx_i, ob, ev, is_sib)
            else:
                for r in range(msgs.valid.shape[0]):
                    s = a.on_msg(s, msgs.slot(r), ctx_i, ob, ev,
                                 is_sib[r])
            out.append(s)
        return tuple(out)

    # -- optional hooks (installed in __init__ when any member has them) --

    def _forward(self, states, msgs, ctx):
        veto = jnp.zeros_like(msgs.valid)
        for i, (a, s) in enumerate(zip(self.apps, states)):
            if hasattr(a, "forward"):
                veto = veto | a.forward(s, msgs, self._ctx(ctx, i))
        return veto

    def _on_update(self, states, en, ctx, ob, ev, now, node_idx, added,
                   sib_keys=None, sib_valid=None, urgent=None):
        return tuple(
            a.on_update(s, en, self._ctx(ctx, i), ob, ev, now, node_idx,
                        added, sib_keys=sib_keys, sib_valid=sib_valid,
                        urgent=urgent)
            if hasattr(a, "on_update") else s
            for i, (a, s) in enumerate(zip(self.apps, states)))

    def _on_tick(self, states, ctx, ob, ev, node_idx):
        return tuple(
            a.on_tick(s, self._ctx(ctx, i), ob, ev, node_idx)
            if hasattr(a, "on_tick") else s
            for i, (a, s) in enumerate(zip(self.apps, states)))

    def _route_policy(self, tag):
        T = len(self.apps)
        tier = tag % T
        routable = jnp.bool_(False)
        inner = jnp.int32(0)
        is_rpc = jnp.bool_(False)
        for i, a in enumerate(self.apps):
            if not hasattr(a, "route_policy"):
                continue
            r_i, k_i, rpc_i = a.route_policy(tag // T)
            hit = tier == i
            routable = jnp.where(hit, r_i, routable)
            inner = jnp.where(hit, k_i, inner)
            is_rpc = jnp.where(hit, rpc_i, is_rpc)
        return routable, inner, is_rpc

    def _on_route_fired(self, states, fired, now, tag):
        T = len(self.apps)
        tier = tag % T
        return tuple(
            a.on_route_fired(s, fired & (tier == i), now, tag // T)
            if hasattr(a, "on_route_fired") else s
            for i, (a, s) in enumerate(zip(self.apps, states)))
