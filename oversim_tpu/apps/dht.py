"""DHT storage tier + DHTTestApp driver + GlobalDhtTestMap oracle.

TPU-native rebuild of the reference stack (SURVEY.md §2.4/§3.4):

  * tier 1 — DHT (src/applications/dht/DHT.{h,cc} + DHTDataStorage):
    PUT = sibling lookup for numReplica replicas, then a routed
    ``DHTPutCall`` to each (sendPutLookupCall DHT.cc:504); GET = lookup +
    ``DHTGetCall``; per-key TTL eviction.  Values travel as 32-bit ids —
    arbitrary payload bytes live host-side, keyed by id (the delay model
    only needs sizes; reference BinaryValue semantics preserved for the
    test workload);
  * tier 2 — DHTTestApp (src/tier2/dhttestapp/DHTTestApp.{h,cc}):
    periodic alternating PUT(random oracle key, fresh value) /
    GET(known key) every testInterval=60s (default.ini:76), validated
    against the global truth;
  * GlobalDhtTestMap (src/tier2/dhttestapp/GlobalDhtTestMap.{h,cc}):
    simulation-global key→value truth.  Vmapped node handlers cannot
    write shared state, so commits flow as "g:" events folded in by
    ``post_step`` (engine/logic.py LogicBase discipline).  A PUT's truth
    is recorded when the initiator's quorum completes — the same moment
    the reference's DHTTestApp stores into GlobalDhtTestMap (on
    DHTputCAPIResponse, DHTTestApp.cc:163-182).

GET quorum: numGetRequests parallel DHTGetCalls whose responses are
majority-voted with ratioIdentical (DHT.cc:620-648).  Graceful-leave
handover pushes stored records to the overlay succession candidate
during the grace window (on_leave; reference GRACEFUL_LEAVE
notification + DHT maintenance puts).

Maintenance replication: graceful-leave handover (on_leave) AND
update()-driven puts — when the overlay reports a node entering this
node's replica set (Common API update(), BaseApp.h:223), stored
records replicate to it via the on_update/on_tick pump, so crash-kill
churn re-replicates without a graceful leave (DHT.cc update path).

Simplification vs the reference (documented): one outstanding DHT
operation per node (the reference allows several concurrent CAPI
calls).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
NO_VAL = jnp.int32(-1)

OP_NONE, OP_PUT, OP_GET = 0, 1, 2

# append-to-pool marker for op_g / commit_g (fresh-key put; mods and
# trace commands address an existing slot >= 0)
G_APPEND = jnp.int32(-2)


def _dist64(d):
    """Compress a key-shaped distance [..., KL] to its top 64 bits (the
    two most-significant u32 lanes).  Exact for comparisons between
    distances of uniform-random node keys (the same argument as
    keys.sort_by_distance's compressed comparator — ties below 2^-64
    probability); the maintenance responsibility filter only ranks
    node-key distances, never structured/team-offset keys."""
    hi = d[..., 0].astype(jnp.uint64)
    lo = d[..., 1].astype(jnp.uint64) if d.shape[-1] > 1 else 0
    return (hi << 32) | lo


@dataclasses.dataclass(frozen=True)
class DhtParams:
    """default.ini:67-77 + tier2 dhtTestApp namespace."""

    num_replica: int = 4          # numReplica
    num_get_requests: int = 4     # numGetRequests, default.ini:68
    ratio_identical: float = 0.5  # ratioIdentical, default.ini:69
    test_interval: float = 60.0   # dhtTestApp.testInterval
    test_ttl: float = 300.0       # dhtTestApp.testTtl
    storage_slots: int = 32       # per-node DHTDataStorage capacity
    # GlobalDhtTestMap capacity: the reference map grows unboundedly
    # (every put inserts a FRESH random key, DHTTestApp.cc:334-346);
    # here it is a ring of this many slots — size it so a run's puts
    # don't wrap.  A get whose slot IS recycled mid-op counts as
    # dht_get_notfound (the reference's entry==NULL numGetError path,
    # DHTTestApp.cc:193-198), never as wrong-data
    num_test_keys: int = 1024
    op_timeout: float = 10.0      # CAPI timeout (lookup+put round)
    mod_test: bool = True         # dhttest_mod_timer (re-put known key)
    # DHT variants (src/applications/dht/{RepeatedHashing,Symmetric}DHT
    # .cc — key-derivation wrappers over the base DHT):
    #   "plain"     — one replica team at the key itself;
    #   "symmetric" — team t stores at key + t*(max/teams)
    #                 (SymmetricDHT.cc:44 overlayKeyOffset);
    #   "repeated"  — team t at an iterated rehash chain of the key
    #                 (RepeatedHashingDHT.cc:96; the in-graph chain uses
    #                 a bijective odd-multiplier mix instead of sha1 —
    #                 same uniform independent placement, documented
    #                 deviation).
    # numReplica splits across teams (initializeDHT); teams run
    # SEQUENTIALLY per op here (one outstanding lookup per node — the
    # reference fires them in parallel; latency scales by the team
    # count, placement and durability semantics identical).
    variant: str = "plain"
    num_replica_teams: int = 1


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DhtState:
    """Per-node tier-1 storage + tier-2 driver state ([N, ...])."""

    # DHTDataStorage
    s_key: jnp.ndarray     # [N, D, KL] u32
    s_val: jnp.ndarray     # [N, D] i32 (NO_VAL = empty)
    s_expire: jnp.ndarray  # [N, D] i64
    # test driver
    t_test: jnp.ndarray    # [N] i64
    seq: jnp.ndarray       # [N] i32
    # trace-driven command queues (empty [N, 0] arrays when not tracing)
    tr_t: jnp.ndarray      # [N, Q] i64 command times
    tr_kind: jnp.ndarray   # [N, Q] i32 (1=PUT, 2=GET)
    tr_key: jnp.ndarray    # [N, Q, KL] u32
    tr_val: jnp.ndarray    # [N, Q] i32
    tr_g: jnp.ndarray      # [N, Q] i32 truth-pool slot
    tr_cur: jnp.ndarray    # [N] i32 queue cursor
    # one outstanding operation
    op: jnp.ndarray        # [N] i32 OP_*
    op_seq: jnp.ndarray    # [N] i32 — op nonce (stale-completion guard)
    op_g: jnp.ndarray      # [N] i32 oracle slot (G_APPEND = fresh key)
    op_key: jnp.ndarray    # [N, KL] u32 — the op's BASE key
    op_team: jnp.ndarray   # [N] i32 — replica-team cursor (variants)
    op_cont: jnp.ndarray   # [N] bool — next team's lookup pending
    op_val: jnp.ndarray    # [N] i32 value being put
    op_pending: jnp.ndarray  # [N] i32 replica responses awaited
    op_acks: jnp.ndarray   # [N] i32
    op_votes: jnp.ndarray  # [N, Q] i32 — GET quorum response values
    op_to: jnp.ndarray     # [N] i64 op timeout
    op_t0: jnp.ndarray     # [N] i64 op start (latency stat)
    # staged truth commit, folded into DhtGlobal by post_step
    # (-1 = none, G_APPEND = append fresh key, >= 0 = write slot)
    commit_g: jnp.ndarray      # [N] i32
    commit_key: jnp.ndarray    # [N, KL] u32
    commit_val: jnp.ndarray    # [N] i32
    commit_expire: jnp.ndarray  # [N] i64
    # update()-driven maintenance replication (BaseApp::update,
    # BaseApp.h:223; DHT.cc update path): a node that entered my
    # replica set receives my stored records, paced 2 per tick
    mnt_dst: jnp.ndarray       # [N] i32 — replication target (NO_NODE idle)
    mnt_pos: jnp.ndarray       # [N] i32 — next storage slot to push
    mnt_resp: jnp.ndarray      # [N, D] bool — per-record responsibility
    #   mask frozen at on_update staging time (DHT.cc:777 isSiblingFor)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DhtGlobal:
    """GlobalDhtTestMap: the known-key pool and current truth values.

    Mirrors the reference's grow-on-put map (GlobalDhtTestMap::insertEntry,
    GlobalDhtTestMap.cc:86): fresh-key puts APPEND at ``cursor`` (ring);
    mods/trace commands overwrite their slot."""

    keys: jnp.ndarray   # [G, KL] u32 — keys put so far (ring)
    val: jnp.ndarray    # [G] i32 — current truth (-1 = never put)
    expire: jnp.ndarray  # [G] i64 — truth TTL deadline
    cursor: jnp.ndarray  # i32 scalar — next append slot


class DhtApp:
    """Tier app (interface: apps/base.py).

    With ``trace`` set (a trace.TraceWorkload), the random PUT/GET test
    driver is replaced by the trace's per-node command queues (reference
    DHTTestApp::handleTraceMessage, DHTTestApp.cc:247-287, driven by
    GlobalTraceManager) and the truth-map key pool is the trace's
    distinct keys."""

    def __init__(self, params: DhtParams = DhtParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC,
                 trace=None, dist_fn=None):
        self.p = params
        self.spec = spec
        self.trace = trace
        # overlay distance metric for the maintenance responsibility
        # filter (reference overlay->distance in DHT::update,
        # DHT.cc:732-764).  Signature dist_fn(node_key, record_key) ->
        # key-shaped distance; None falls back to XOR (exact for the
        # Kademlia family).  Ring overlays patch theirs in their
        # constructors (chord.py/pastry.py ``app.dist_fn = ...``), the
        # same late-binding convention as ``app.rcfg``.
        self.dist_fn = dist_fn
        # replica-team machinery (DhtParams.variant docstring)
        t = max(1, params.num_replica_teams)
        if params.variant != "plain" and params.num_replica % t:
            raise ValueError("numReplica must be a multiple of "
                             "numReplicaTeams (initializeDHT)")
        if params.variant != "plain" and trace is not None:
            raise ValueError("trace workloads drive the plain DHT")
        self.teams = t if params.variant != "plain" else 1
        self.per_team = params.num_replica // self.teams
        if params.variant == "symmetric":
            step = (2 ** spec.bits) // self.teams
            self._team_off = jnp.stack(
                [keys_mod.from_int((step * i) % (2 ** spec.bits), spec)
                 for i in range(self.teams)])
        elif params.variant == "repeated":
            import numpy as _np
            r = _np.random.RandomState(0xD47)
            consts = r.randint(0, 2 ** 32, size=(self.teams, spec.lanes),
                               dtype=_np.uint32)
            consts[0] = 0          # team 0 = the base key itself
            self._team_mix = jnp.asarray(consts)

    def _team_key(self, base, t):
        """Team t's wire key for a base key (SymmetricDHT additive
        offsets / RepeatedHashingDHT rehash chain — the chain here is a
        bijective lane-rotation + xor mix, see DhtParams.variant)."""
        p = self.p
        if self.teams == 1:
            return base
        if p.variant == "symmetric":
            return keys_mod.add(base, self._team_off[t], self.spec)
        kl = base.shape[-1]
        rot = base[(jnp.arange(kl) + t) % kl]
        return jnp.where(t == 0, base, rot ^ self._team_mix[t])

    @property
    def dist(self):
        return self.dist_fn or keys_mod.xor_metric

    def stat_spec(self):
        return dict(
            scalars=("dht_put_latency_s", "dht_get_latency_s"),
            hists=(),
            counters=("dht_put_attempts", "dht_put_success",
                      "dht_get_attempts", "dht_get_success",
                      "dht_get_wrong", "dht_get_notfound",
                      "dht_lookup_failed", "dht_stored",
                      "dht_mnt_puts"))

    def init(self, n: int) -> DhtState:
        p, kl = self.p, self.spec.lanes
        d = p.storage_slots
        if self.trace is not None:
            if self.trace.t.shape[0] != n:
                raise ValueError("trace workload slot count != num nodes")
            tr_t = jnp.asarray(
                jnp.where(jnp.isinf(jnp.asarray(self.trace.t)),
                          T_INF, jnp.asarray(self.trace.t) * NS), I64)
            tr_kind = jnp.asarray(self.trace.kind, I32)
            tr_key = jnp.asarray(self.trace.key, U32)
            tr_val = jnp.asarray(self.trace.value, I32)
            tr_g = jnp.asarray(self.trace.g, I32)
        else:
            tr_t = jnp.full((n, 0), T_INF, I64)
            tr_kind = jnp.zeros((n, 0), I32)
            tr_key = jnp.zeros((n, 0, kl), U32)
            tr_val = jnp.zeros((n, 0), I32)
            tr_g = jnp.zeros((n, 0), I32)
        return DhtState(
            s_key=jnp.zeros((n, d, kl), U32),
            s_val=jnp.full((n, d), NO_VAL, I32),
            s_expire=jnp.zeros((n, d), I64),
            t_test=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32),
            tr_t=tr_t, tr_kind=tr_kind, tr_key=tr_key, tr_val=tr_val,
            tr_g=tr_g, tr_cur=jnp.zeros((n,), I32),
            op=jnp.zeros((n,), I32),
            op_seq=jnp.zeros((n,), I32),
            op_g=jnp.zeros((n,), I32),
            op_key=jnp.zeros((n, kl), U32),
            op_team=jnp.zeros((n,), I32),
            op_cont=jnp.zeros((n,), bool),
            op_val=jnp.full((n,), NO_VAL, I32),
            op_pending=jnp.zeros((n,), I32),
            op_acks=jnp.zeros((n,), I32),
            op_votes=jnp.full((n, p.num_get_requests), NO_VAL - 1, I32),
            op_to=jnp.full((n,), T_INF, I64),
            op_t0=jnp.zeros((n,), I64),
            commit_g=jnp.full((n,), -1, I32),
            commit_key=jnp.zeros((n, kl), U32),
            commit_val=jnp.full((n,), NO_VAL, I32),
            commit_expire=jnp.zeros((n,), I64),
            mnt_dst=jnp.full((n,), NO_NODE, I32),
            mnt_pos=jnp.zeros((n,), I32),
            mnt_resp=jnp.zeros((n, d), bool),
        )

    def glob_init(self, rng) -> DhtGlobal:
        del rng
        if self.trace is not None:
            pool = jnp.asarray(self.trace.key_pool, U32)
            return DhtGlobal(
                keys=pool,
                val=jnp.full((pool.shape[0],), NO_VAL, I32),
                expire=jnp.zeros((pool.shape[0],), I64),
                cursor=jnp.int32(0))
        # the map starts EMPTY and grows as puts complete, exactly like
        # GlobalDhtTestMap (first gets find no key and are skipped,
        # DHTTestApp.cc:356-363 "No key available")
        g = self.p.num_test_keys
        return DhtGlobal(
            keys=jnp.zeros((g, self.spec.lanes), U32),
            val=jnp.full((g,), NO_VAL, I32),
            expire=jnp.zeros((g,), I64),
            cursor=jnp.int32(0))

    def post_step(self, ctx, state: DhtState, glob: DhtGlobal, events):
        """Fold per-node staged put-commits into the truth map (the
        moment the reference's DHTTestApp stores into GlobalDhtTestMap,
        DHTTestApp.cc:151-153 — on EVERY put completion, success or
        not).  Fresh-key puts append at the ring cursor; mod/trace
        commits overwrite their slot, guarded on the slot still holding
        the op's key (ring recycling)."""
        del events
        g_n = glob.val.shape[0]
        slot_w = state.commit_g >= 0
        gs = jnp.clip(state.commit_g, 0, g_n - 1)
        still = jnp.all(glob.keys[gs] == state.commit_key, axis=-1)
        rows = jnp.where(slot_w & still, gs, g_n)
        val = glob.val.at[rows].set(state.commit_val, mode="drop")
        expire = glob.expire.at[rows].set(state.commit_expire, mode="drop")
        app_w = state.commit_g == G_APPEND
        rank = jnp.cumsum(app_w.astype(I32)) - app_w.astype(I32)
        pos = jnp.where(app_w, (glob.cursor + rank) % g_n, g_n)
        glob = dataclasses.replace(
            glob,
            keys=glob.keys.at[pos].set(state.commit_key, mode="drop"),
            val=val.at[pos].set(state.commit_val, mode="drop"),
            expire=expire.at[pos].set(state.commit_expire, mode="drop"),
            cursor=(glob.cursor
                    + jnp.sum(app_w.astype(I32), dtype=I32)) % g_n)
        n = state.commit_g.shape[0]
        state = dataclasses.replace(
            state, commit_g=jnp.full((n,), -1, I32))
        return state, glob

    def on_ready(self, app, en, now, rng):
        if self.trace is not None:
            # trace commands fire at absolute times: expose the next
            # queued command as the app timer
            q = jnp.clip(app.tr_cur, 0, max(app.tr_t.shape[-1] - 1, 0))
            nxt = app.tr_t[q] if app.tr_t.shape[-1] else T_INF
            return dataclasses.replace(
                app, t_test=jnp.where(en, nxt, app.t_test))
        off = jax.random.uniform(rng, (), minval=0.0,
                                 maxval=self.p.test_interval)
        t = now + (off * NS).astype(I64)
        return dataclasses.replace(app, t_test=jnp.where(en, t, app.t_test))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_test=jnp.where(en, T_INF, app.t_test),
            op=jnp.where(en, OP_NONE, app.op),
            op_cont=app.op_cont & ~en,
            op_to=jnp.where(en, T_INF, app.op_to),
            mnt_dst=jnp.where(en, NO_NODE, app.mnt_dst))

    def next_event(self, app):
        t = jnp.minimum(app.t_test, app.op_to)
        # a pending next-team lookup fires on the next tick (variants)
        t = jnp.where(app.op_cont, jnp.int64(0), t)
        # an active maintenance replication pumps every tick until done
        return jnp.where(app.mnt_dst != NO_NODE, jnp.int64(0), t)

    def timer_event(self, app):
        """Events needing an ``on_timer`` dispatch — EXCLUDES the
        maintenance-pump sentinel (the pump runs via on_tick, which
        every overlay calls unconditionally).  TierStack bases its
        earliest-tier pick on this so an active pump can't monopolize
        the stack's one timer slot per window and starve other tiers'
        timeout processing."""
        t = jnp.minimum(app.t_test, app.op_to)
        return jnp.where(app.op_cont, jnp.int64(0), t)

    def _vote_winner(self, votes, n_acks):
        """Quorum bookkeeping shared by the response path and the
        timeout path: per-value counts over the filled vote prefix and
        the data-preferring winner (a value vote beats an equal count
        of notfound votes — the reference's hash-map iteration order
        breaks such ties arbitrarily; preferring data keeps a
        partially-covered replica set readable)."""
        q = self.p.num_get_requests
        filled = jnp.arange(q) < jnp.clip(n_acks, 0, q)
        counts = jnp.sum((votes[:, None] == votes[None, :])
                         & filled[None, :], axis=1)
        counts = jnp.where(filled, counts, 0)
        winner = votes[jnp.argmax(counts * 2
                                  + (votes != NO_VAL).astype(I32))]
        return counts, winner

    def _truth_outcomes(self, glob, op_g, op_key, winner, now, final):
        """Truth-map validation shared by the response and timeout
        paths (DHTTestApp::handleGetResponse, DHTTestApp.cc:173-232):
        a recycled ring slot maps to the reference's entry==NULL error;
        expired truth means an empty result is SUCCESS ("deleted key
        gone") and a value is an error; live truth compares values.
        ``final`` gates all three outcome masks."""
        g_n = glob.val.shape[0]
        gslot = jnp.clip(op_g, 0, g_n - 1)
        slot_ok = jnp.all(glob.keys[gslot] == op_key) & (op_g >= 0)
        expired = now > glob.expire[gslot]
        has_val = winner != NO_VAL
        good = final & slot_ok & jnp.where(
            expired, ~has_val,
            has_val & (winner == glob.val[gslot]))
        wrong = final & slot_ok & has_val & (
            expired | (winner != glob.val[gslot]))
        notfound = final & ((slot_ok & ~expired & ~has_val) | ~slot_ok)
        return slot_ok, expired, has_val, good, wrong, notfound

    def _stage_commit(self, app, en):
        """Stage the pending op's (key, value, expiry) as a truth-map
        commit for post_step — shared by put-complete, put-lookup-fail
        and put-timeout (the reference inserts into GlobalDhtTestMap on
        every put response path, DHTTestApp.cc:151-153)."""
        return dataclasses.replace(
            app,
            commit_g=jnp.where(en, app.op_g, app.commit_g),
            commit_key=jnp.where(en, app.op_key, app.commit_key),
            commit_val=jnp.where(en, app.op_val, app.commit_val),
            commit_expire=jnp.where(
                en, app.op_t0 + jnp.int64(int(self.p.test_ttl * NS)),
                app.commit_expire))

    def on_update(self, app, en, ctx, ob, ev, now, node_idx, added,
                  sib_keys=None, sib_valid=None, urgent=None):
        """BaseApp::update (BaseApp.h:223) — the overlay reports a node
        that ENTERED this node's replica/sibling set; my stored records
        replicate to it (the reference DHT's update()-driven maintenance
        puts).  ``added`` [A] NO_NODE-padded; one target is staged at a
        time and pumped 2 records/tick by on_timer.

        Responsibility filter (DHT.cc:746-747 / :777 isSiblingFor): a
        record replicates to the added node only if that node falls
        within the numReplica sibling set for the record's key, judged
        from this node's local sibling view (``sib_keys``/``sib_valid``,
        passed by the overlay: succ list / sibling table / leafset —
        the reference's overlay->local_lookup(key, numReplica).back()
        comparison).  With fewer than numReplica members known, every
        added node is admitted (matching the reference's over-send on
        Chord's isSiblingFor err path, DHT.cc:779-797).  The mask is
        frozen per record at staging time (``mnt_resp``)."""
        first = added[jnp.argmax(added != NO_NODE)]
        # an active pump is normally not preempted — the in-flight
        # target would silently lose its tail records; a member missed
        # while busy is re-replicated on its next set delta.  EXCEPT
        # when the overlay marks the delta ``urgent`` (Chord's new-
        # predecessor ownership transfer — that delta never recurs, so
        # missing it would orphan the transferred keyspace): an urgent
        # delta restarts the pump at the new target.
        idle = app.mnt_dst == NO_NODE
        if urgent is not None:
            idle = idle | urgent
        en = en & (first != NO_NODE) & (first != node_idx) & jnp.any(
            app.s_val != NO_VAL) & idle
        tgt_key = ctx.keys[jnp.maximum(first, 0)]
        d_tgt = _dist64(self.dist(tgt_key[None, :], app.s_key))   # [D]
        if sib_keys is None:
            resp = jnp.ones(app.s_val.shape, bool)
        else:
            me_key = ctx.keys[node_idx]
            # [D, S+1] compressed distances of {me} ∪ sibling view to
            # each record key; invalid members push to +inf so a short
            # view leaves the numReplica-th slot at +inf (admit-all)
            d_me = _dist64(self.dist(me_key[None, :], app.s_key))
            d_sib = _dist64(self.dist(sib_keys[:, None, :],
                                      app.s_key[None, :, :]))      # [S, D]
            d_sib = jnp.where(sib_valid[:, None], d_sib,
                              jnp.uint64(2**64 - 1))
            all_d = jnp.concatenate([d_me[None, :], d_sib], axis=0)
            kth = jnp.sort(all_d, axis=0)[
                min(self.p.num_replica, all_d.shape[0]) - 1]       # [D]
            resp = d_tgt <= kth
        return dataclasses.replace(
            app,
            mnt_dst=jnp.where(en, first, app.mnt_dst),
            mnt_resp=jnp.where(en, resp, app.mnt_resp),
            mnt_pos=jnp.where(en, 0, app.mnt_pos))

    def on_tick(self, app, ctx, ob, ev, node_idx):
        """Maintenance-replication pump: 2 stored records per tick to
        the staged new replica-set member (apps/base.py on_tick hook).
        Skips empty storage slots so a sparse store finishes in
        ceil(records/2) ticks instead of slots/2 (the pump holds the
        sim-wide event horizon down while active).

        Only records whose frozen responsibility mask (``mnt_resp``,
        the sibling-set membership test staged by on_update) admits the
        target are pushed — flooding the target with records it is not
        responsible for could, with bounded storage, evict ones it
        is."""
        d = app.s_val.shape[0]
        idx = jnp.arange(d, dtype=I32)
        resp = app.mnt_resp
        for _ in range(2):
            cand = (app.s_val != NO_VAL) & (idx >= app.mnt_pos) & resp
            m_en = (app.mnt_dst != NO_NODE) & jnp.any(cand)
            col = jnp.argmax(cand).astype(I32)
            ob.send(m_en, ctx.t_start, app.mnt_dst, wire.DHT_PUT_CALL,
                    key=app.s_key[col], a=app.s_val[col], b=jnp.int32(-1),
                    stamp=app.s_expire[col],
                    size_b=wire.BASE_CALL_B + 20 + 8)
            ev.count("dht_mnt_puts", m_en)
            app = dataclasses.replace(
                app, mnt_pos=jnp.where(m_en, col + 1, app.mnt_pos))
        done = ~jnp.any((app.s_val != NO_VAL) & (idx >= app.mnt_pos)
                        & resp)
        return dataclasses.replace(
            app, mnt_dst=jnp.where(done, NO_NODE, app.mnt_dst))

    # -- timers --------------------------------------------------------------

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        glob: DhtGlobal = ctx.glob
        g_n = glob.val.shape[0]

        # op timeout → failed operation.  A timed-out PUT still records
        # its value as the truth — the reference's DHTTestApp inserts
        # into GlobalDhtTestMap on EVERY put response including
        # isSuccess=false (DHTTestApp.cc:151-153 insertEntry precedes
        # the success check), so later gets of that key must expect the
        # failed put's value
        to = (app.op != OP_NONE) & (app.op_to < ctx.t_end)
        # a timed-out GET with responses in hand is evaluated with what
        # it has — the reference's DHTGet timeout path picks the value
        # with the highest count among received responses, explicitly
        # WITHOUT the ratioIdentical bar (DHT::handleRpcTimeout "no more
        # nodes to ask, see what we can do with what we have"; the ratio
        # check there is an #if 0 block).  Under churn a dead replica in
        # the fan-out otherwise turns every such get into a guaranteed
        # failure
        to_get = to & (app.op == OP_GET) & (app.op_acks > 0)
        _, winner_t = self._vote_winner(app.op_votes, app.op_acks)
        _, _, _, good_t, wrong_t, nf_t = self._truth_outcomes(
            glob, app.op_g, app.op_key, winner_t, now, to_get)
        ev.count("dht_get_success", good_t)
        ev.count("dht_get_wrong", wrong_t)
        ev.count("dht_get_notfound", nf_t)
        ev.count("dht_lookup_failed", to & ~to_get)
        app = self._stage_commit(app, to & (app.op == OP_PUT))
        app = dataclasses.replace(
            app,
            op=jnp.where(to, OP_NONE, app.op),
            op_cont=app.op_cont & ~to,
            op_to=jnp.where(to, T_INF, app.op_to))


        if self.trace is not None:
            # trace-driven commands (DHTTestApp::handleTraceMessage)
            qn = app.tr_t.shape[-1]
            q = jnp.clip(app.tr_cur, 0, max(qn - 1, 0))
            due = en & (app.t_test < ctx.t_end) & (app.tr_cur < qn)
            fire = due & (app.op == OP_NONE)
            # a due command blocked by an in-flight op must still advance
            # the timer (retry shortly) or the event horizon pins
            # simulated time on it and the tick loop spins
            blocked = due & ~fire
            do_put = fire & (app.tr_kind[q] == 1)
            do_get = fire & (app.tr_kind[q] == 2)
            ev.count("dht_put_attempts", do_put)
            ev.count("dht_get_attempts", do_get)
            key = app.tr_key[q]
            val = app.tr_val[q]
            g = app.tr_g[q]
            cur2 = app.tr_cur + fire.astype(I32)
            q2 = jnp.clip(cur2, 0, max(qn - 1, 0))
            nxt_t = jnp.where(cur2 < qn, app.tr_t[q2], T_INF)
            nxt_t = jnp.where(blocked, now + NS, nxt_t)   # retry in 1s
            app = dataclasses.replace(
                app,
                tr_cur=cur2,
                t_test=jnp.where(due, nxt_t, app.t_test),
                seq=app.seq + fire.astype(I32),
                op=jnp.where(do_put, OP_PUT,
                             jnp.where(do_get, OP_GET, app.op)),
                op_seq=jnp.where(fire, app.seq, app.op_seq),
                op_g=jnp.where(fire, g, app.op_g),
                op_key=jnp.where(fire, key, app.op_key),
                op_val=jnp.where(do_put, val, app.op_val),
                op_pending=jnp.where(fire, 0, app.op_pending),
                op_acks=jnp.where(fire, 0, app.op_acks),
                op_to=jnp.where(fire, now + jnp.int64(
                    int(p.op_timeout * NS)), app.op_to),
                op_t0=jnp.where(fire, now, app.op_t0))
            return app, base.LookupReq(want=do_put | do_get, key=key,
                                       tag=app.op_seq)

        # periodic test: cycle PUT (fresh random key) / GET (known key) /
        # MOD (re-put of a known key) — the reference runs three
        # independent timers at testInterval each with staggered offsets
        # (DHTTestApp.cc:104-118); the round-robin at interval/modes
        # preserves each mode's rate under the one-op-per-timer app
        # interface.  Fresh-key puts are what keeps concurrent same-key
        # writes rare in the reference workload (OverlayKey::random()
        # per put, DHTTestApp.cc:334-346) — a fixed key pool manufactures
        # write-write collisions whose mixed replica orders surface as
        # wrong-value gets.
        fire = en & (app.t_test < ctx.t_end) & (app.op == OP_NONE)
        due = en & (app.t_test < ctx.t_end)
        r_g, r_v, r_k = jax.random.split(rng, 3)
        n_modes = 3 if p.mod_test else 2
        mode = app.seq % n_modes        # 0 = put, 1 = get, 2 = mod
        # known-key draw: uniform over live truth entries (getRandomKey)
        valid = (glob.val != NO_VAL) & (glob.expire > now)
        vcum = jnp.cumsum(valid.astype(I32))
        n_valid = vcum[-1]
        k = jax.random.randint(r_g, (), 0, jnp.maximum(n_valid, 1),
                               dtype=I32)
        g = jnp.clip(jnp.searchsorted(vcum, k + 1, side="left").astype(I32),
                     0, g_n - 1)
        have_known = n_valid > 0
        do_put = fire & (mode == 0)
        do_get = fire & (mode == 1) & have_known
        do_mod = fire & (mode == 2) & have_known
        ev.count("dht_put_attempts", do_put | do_mod)
        ev.count("dht_get_attempts", do_get)
        # fresh value id: unique per (node, seq) — 30 bits of rng
        val = jnp.abs(jax.random.randint(r_v, (), 0, 2**30, dtype=I32))
        key = jnp.where(do_put, keys_mod.random_keys(r_k, (), self.spec),
                        glob.keys[g])
        put_like = do_put | do_mod
        any_op = put_like | do_get
        app = dataclasses.replace(
            app,
            t_test=jnp.where(due,
                             jnp.maximum(app.t_test, now) + jnp.int64(
                                 int(p.test_interval / n_modes * NS)),
                             app.t_test),
            seq=app.seq + due.astype(I32),
            op=jnp.where(put_like, OP_PUT,
                         jnp.where(do_get, OP_GET, app.op)),
            op_seq=jnp.where(any_op, app.seq, app.op_seq),
            op_g=jnp.where(do_put, G_APPEND, jnp.where(any_op, g, app.op_g)),
            op_key=jnp.where(any_op, key, app.op_key),
            op_team=jnp.where(any_op, 0, app.op_team),
            op_val=jnp.where(put_like, val, app.op_val),
            op_pending=jnp.where(any_op, 0, app.op_pending),
            op_acks=jnp.where(any_op, 0, app.op_acks),
            op_to=jnp.where(any_op, now + jnp.int64(int(p.op_timeout * NS)),
                            app.op_to),
            op_t0=jnp.where(any_op, now, app.op_t0))
        # next-team continuation (variants): an active multi-team op
        # with op_cont set issues its NEXT team's sibling lookup —
        # mutually exclusive with a fresh op (op != NONE blocks `fire`)
        cont = en & app.op_cont & (app.op != OP_NONE)
        if self.teams > 1:
            ckey = self._team_key(app.op_key, app.op_team)
            key = jnp.where(cont, ckey, key)
        app = dataclasses.replace(app, op_cont=app.op_cont & ~cont)
        return app, base.LookupReq(want=any_op | cont, key=key,
                                   tag=app.op_seq)

    # -- lookup completion → replica fan-out ---------------------------------

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        p = self.p
        # op nonce match rejects completions of a previously-timed-out op
        # (a fresh op may have started in the same window)
        en = done.en & (app.op != OP_NONE) & (done.tag == app.op_seq)
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("dht_lookup_failed", en & ~suc)
        # a PUT whose sibling lookup failed still inserts its value into
        # the truth map — the reference's isSuccess=false CAPI response
        # path (DHTTestApp::handlePutResponse inserts BEFORE the success
        # check, DHTTestApp.cc:151-153)
        app = self._stage_commit(app, en & ~suc & (app.op == OP_PUT))
        app = dataclasses.replace(
            app,
            op=jnp.where(en & ~suc, OP_NONE, app.op),
            op_to=jnp.where(en & ~suc, T_INF, app.op_to))

        # PUT: DHTPutCall to up to numReplica siblings (DHT.cc:210-237);
        # with replica teams, numReplica/numReplicaTeams per team
        # (initializeDHT)
        is_put = en & suc & (app.op == OP_PUT)
        nrep = jnp.int32(0)
        for i in range(min(self.per_team, done.results.shape[0])):
            tgt = done.results[i]
            send = is_put & (tgt != NO_NODE)
            # self-replica: store locally via on_msg loopback (send to self
            # costs nothing in the delay model, SimpleUDP.cc:322)
            # ns-precise expiry rides the stamp field — replica and truth
            # map must share the exact same deadline
            # b carries the op nonce; responders echo it so stragglers
            # from a timed-out op can't ack a newer op's quorum
            ob.send(send, now, tgt, wire.DHT_PUT_CALL, key=done.target,
                    a=app.op_val, b=app.op_seq,
                    stamp=app.op_t0 + jnp.int64(int(self.p.test_ttl * NS)),
                    size_b=wire.BASE_CALL_B + 20 + 8)
            nrep += send.astype(I32)
        app = dataclasses.replace(
            app, op_pending=jnp.where(is_put, nrep, app.op_pending))

        # GET: DHTGetCall to numGetRequests siblings — the responses are
        # quorum-voted with ratioIdentical (DHT.cc:262,636; default.ini:
        # numGetRequests=4, ratioIdentical=0.5).  With replica teams the
        # fan-out caps at the team's replica count: querying past the
        # team's replica set only stacks notfound votes against it
        is_get = en & suc & (app.op == OP_GET)
        nget = jnp.int32(0)
        get_w = (min(p.num_get_requests, self.per_team)
                 if self.teams > 1 else p.num_get_requests)
        for i in range(min(get_w, done.results.shape[0])):
            tgt = done.results[i]
            send = is_get & (tgt != NO_NODE)
            ob.send(send, now, tgt, wire.DHT_GET_CALL,
                    key=done.target, b=app.op_seq,
                    size_b=wire.BASE_CALL_B + 20)
            nget += send.astype(I32)
        app = dataclasses.replace(
            app,
            op_pending=jnp.where(is_get, nget, app.op_pending),
            op_acks=jnp.where(is_get, 0, app.op_acks),
            op_votes=jnp.where(is_get, NO_VAL - 1, app.op_votes))
        return app

    # -- inbound messages ----------------------------------------------------

    def _store(self, app, en, key, val, expire, maintenance=None):
        """DHTDataStorage::addData: overwrite same key, else free slot,
        else evict the earliest-expiring entry.

        ``maintenance`` marks replication copies (update()-driven puts /
        leave handover): they must never roll a record BACK — a copy
        whose expiry (= put time + ttl, monotone in put order for one
        key) is not newer than the stored one is dropped, so a slow
        replica can't resurrect a stale value into the get quorum."""
        same_mask = jnp.all(app.s_key == key[None, :], axis=-1) & (
            app.s_val != NO_VAL)
        same = en & jnp.any(same_mask)
        col_same = jnp.argmax(same_mask).astype(I32)
        free = app.s_val == NO_VAL
        if maintenance is not None:
            stale = maintenance & same & (app.s_expire[col_same] >= expire)
            en = en & ~stale
            # a replication copy never EVICTS a legitimately stored
            # record (the reference's DHTDataStorage is unbounded —
            # maintenance bursts cannot destroy owned data there, so a
            # bounded store must drop the copy instead)
            en = en & (same | jnp.any(free) | ~maintenance)
        did = en
        col_free = jnp.argmax(free).astype(I32)
        col_evict = jnp.argmin(app.s_expire).astype(I32)
        col = jnp.where(same, col_same,
                        jnp.where(jnp.any(free), col_free, col_evict))
        col = jnp.where(en, col, app.s_val.shape[0])  # OOB drop
        return dataclasses.replace(
            app,
            s_key=app.s_key.at[col].set(key, mode="drop"),
            s_val=app.s_val.at[col].set(val, mode="drop"),
            s_expire=app.s_expire.at[col].set(expire, mode="drop"),
            # an active maintenance pump's frozen responsibility mask
            # (mnt_resp, staged by on_update) was computed for this
            # slot's PREVIOUS contents — drop the bit so the pump never
            # pushes a just-stored record under a stale judgment (the
            # new record reached us via a fresh put/copy; it is
            # re-replicated on the target's next set delta if needed)
            mnt_resp=app.mnt_resp.at[col].set(False, mode="drop")), did

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        """Graceful-leave data handover: push stored records to the
        overlay's succession candidate before dying (the reference's
        NF_OVERLAY_NODE_GRACEFUL_LEAVE → overlay handover + DHT
        maintenance puts, Kademlia.cc:964 / DHT update()).  Paced at
        two records per tick through the grace window; pushed records
        are cleared locally (the node is about to die anyway)."""
        en = en & (handover != NO_NODE) & (handover != node_idx)
        valid = app.s_val != NO_VAL
        for _ in range(2):
            has = en & jnp.any(valid)
            col = jnp.argmax(valid).astype(I32)
            ob.send(has, now, handover, wire.DHT_PUT_CALL,
                    key=app.s_key[col], a=app.s_val[col], b=jnp.int32(-1),
                    stamp=app.s_expire[col],
                    size_b=wire.BASE_CALL_B + 20 + 8)
            ccol = jnp.where(has, col, app.s_val.shape[0])
            app = dataclasses.replace(
                app, s_val=app.s_val.at[ccol].set(NO_VAL, mode="drop"))
            valid = valid.at[ccol].set(False, mode="drop")
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        """Single-slot fallback: wraps the batched ``on_msgs`` with a
        one-message batch (overlays without an on_msgs dispatch)."""
        return self.on_msgs(
            app, jax.tree.map(lambda x: x[None], m), ctx, ob, ev,
            jnp.atleast_1d(is_sib))

    def on_msgs(self, app, msgs, ctx, ob, ev, is_sib, node_idx=None):
        """Batched inbox handler: ONE pass over all R inbox slots.

        The per-slot ``on_msg`` unrolled R× was the dominant compile
        cost of every DHT-bearing graph (the round-4 suite/dryrun
        compile stall): R copies of the quorum-vote + storage-scan
        graph, vmapped over N.  This batched form issues each piece
        once with [R]-shaped masks — vector Outbox sends, one storage
        probe [R, D], one quorum evaluation per tick.

        Semantic deltas vs the sequential unroll (both within one
        50 ms delivery window, where message order is arbitrary
        anyway): puts apply before gets batch-wide, and the GET quorum
        is evaluated once after folding the whole batch's votes rather
        than after each response.
        """
        del is_sib, node_idx
        p = self.p
        now = msgs.t_deliver                                   # [R]
        r_in = msgs.valid.shape[0]

        # DHTPutCall → store + ack (DHT::handlePutRequest); b == -1 marks
        # replication copies (maintenance/handover), which may not roll
        # a newer record back.  _store stays sequential per slot (exact
        # same-key overwrite / free-slot / eviction semantics); it is
        # [D]-cheap — the expensive pieces below are all batched.
        en_put = msgs.valid & (msgs.kind == wire.DHT_PUT_CALL)  # [R]
        stored = []
        for r in range(r_in):
            app, did_r = self._store(app, en_put[r], msgs.key[r],
                                     msgs.a[r], msgs.stamp[r],
                                     maintenance=(msgs.b[r] == -1))
            stored.append(did_r)
        ev.count("dht_stored", jnp.stack(stored))
        ob.send(en_put, now, msgs.src, wire.DHT_PUT_RES, key=msgs.key,
                b=msgs.b, size_b=wire.BASE_CALL_B)

        # DHTPutResponse → ack counting; majority = success.  The op
        # nonce echoed in b rejects straggler acks from a timed-out op
        # (the reference ties CAPI responses to RPC nonces); the key
        # match rejects a previous TEAM's stragglers (variants)
        cur_key = (self._team_key(app.op_key, app.op_team)
                   if self.teams > 1 else app.op_key)
        en_ack = (msgs.valid & (msgs.kind == wire.DHT_PUT_RES)
                  & (app.op == OP_PUT) & (msgs.b == app.op_seq)
                  & jnp.all(msgs.key == cur_key[None, :], axis=-1))  # [R]
        en = jnp.any(en_ack)
        now_s = jnp.max(jnp.where(en_ack, now, jnp.int64(0)))
        acks = app.op_acks + jnp.sum(en_ack.astype(I32), dtype=I32)
        # a MAJORITY of replica acks completes the put (DHT.cc
        # handlePutResponse: numResponses/numSent > 0.5) — requiring all
        # acks makes every stale replica-set entry a guaranteed failure
        # under churn
        team_done = en & (2 * acks > app.op_pending) & (app.op_pending > 0)
        more = app.op_team + 1 < self.teams
        complete = team_done & ~more
        next_team = team_done & more
        ev.count("dht_put_success", complete)
        ev.value("dht_put_latency_s",
                 (now_s - app.op_t0).astype(jnp.float32) / NS, complete)
        app = self._stage_commit(app, complete)   # truth commit
        app = dataclasses.replace(
            app,
            op_acks=jnp.where(next_team, 0, acks),
            op_pending=jnp.where(next_team, 0, app.op_pending),
            op_team=app.op_team + next_team.astype(I32),
            op_cont=app.op_cont | next_team,
            op=jnp.where(complete, OP_NONE, app.op),
            # each team round gets a fresh timeout budget (the parallel
            # reference teams each carry their own CAPI timeout)
            op_to=jnp.where(complete, T_INF,
                            jnp.where(next_team, now_s + jnp.int64(
                                int(p.op_timeout * NS)), app.op_to)))

        # DHTGetCall → storage probe + reply (DHT::handleGetRequest):
        # one [R, D] probe for the whole batch
        en_get = msgs.valid & (msgs.kind == wire.DHT_GET_CALL)
        hit = (jnp.all(app.s_key[None, :, :] == msgs.key[:, None, :],
                       axis=-1)
               & (app.s_val != NO_VAL)[None, :]
               & (app.s_expire[None, :] > now[:, None]))       # [R, D]
        found = jnp.any(hit, axis=-1)
        val = jnp.where(found,
                        app.s_val[jnp.argmax(hit, axis=-1)], NO_VAL)
        ob.send(en_get, now, msgs.src, wire.DHT_GET_RES, key=msgs.key,
                a=val, b=msgs.b, size_b=wire.BASE_CALL_B + 8)

        # DHTGetResponse → quorum vote, then validate the winning value
        # vs the CURRENT truth (the reference hashes the responses and
        # requires a ratioIdentical majority, DHT.cc:620-648; DHTTestApp
        # reads GlobalDhtTestMap at response time, DHTTestApp.cc:121-182).
        # Nonce + key match guard against stale responses completing a
        # newer GET with a mismatched value.  The whole batch's votes
        # fold in ONE scatter; the quorum evaluates once per tick.
        q = p.num_get_requests
        cur_key = (self._team_key(app.op_key, app.op_team)
                   if self.teams > 1 else app.op_key)
        en_v = (msgs.valid & (msgs.kind == wire.DHT_GET_RES)
                & (app.op == OP_GET) & (msgs.b == app.op_seq)
                & jnp.all(msgs.key == cur_key[None, :], axis=-1))   # [R]
        en = jnp.any(en_v)
        now_g = jnp.max(jnp.where(en_v, now, jnp.int64(0)))
        rank = jnp.cumsum(en_v.astype(I32)) - en_v.astype(I32)
        slot = jnp.where(en_v, jnp.clip(app.op_acks + rank, 0, q - 1), q)
        votes = app.op_votes.at[slot].set(msgs.a, mode="drop")
        n_acks = app.op_acks + jnp.sum(en_v.astype(I32), dtype=I32)
        counts, winner = self._vote_winner(votes, n_acks)
        need = jnp.ceil(p.ratio_identical
                        * app.op_pending.astype(jnp.float32)).astype(I32)
        need = jnp.maximum(need, 1)
        win = en & jnp.any(counts >= need)
        exhausted = en & ~win & (n_acks >= app.op_pending)
        slot_ok, expired, has_val, good, wrong, nf = self._truth_outcomes(
            ctx.glob, app.op_g, app.op_key, winner, now_g,
            # gate on `win`: an exhausted vote with no ratioIdentical
            # majority is a plain failure in the reference
            # (DHT.cc:635-668 isSuccess false), not wrong data
            final=jnp.bool_(True))
        # a live-truth team miss tries the NEXT replica team (variants;
        # the reference queries all teams in parallel and takes any hit)
        want_retry = (((win & ~has_val) | exhausted) & slot_ok
                      & ~expired)
        retry_team = want_retry & (app.op_team + 1 < self.teams)
        final = (win | exhausted) & ~retry_team
        good = good & final & win
        wrong = wrong & final & win
        ev.count("dht_get_success", good)
        ev.count("dht_get_wrong", wrong)
        ev.count("dht_get_notfound", nf & final & win)
        ev.value("dht_get_latency_s",
                 (now_g - app.op_t0).astype(jnp.float32) / NS, good)
        # NOTE: no votes/acks/pending reset here on retry_team — the
        # continuation lookup's completion resets them (on_lookup_done
        # is_get), stale-team responses are key-guarded out by cur_key,
        # AND the extra where-resets sent this box's XLA-CPU compile
        # into a >10-minute stall (bisected empirically; the slim form
        # compiles in ~50 s)
        app = dataclasses.replace(
            app,
            op_votes=votes,
            op_acks=n_acks,
            op_team=app.op_team + retry_team.astype(I32),
            op_cont=app.op_cont | retry_team,
            op=jnp.where(final, OP_NONE, app.op),
            op_to=jnp.where(final, T_INF,
                            jnp.where(retry_team, now_g + jnp.int64(
                                int(p.op_timeout * NS)), app.op_to)))
        return app

    @property
    def hist_map(self):
        return {}
