"""DHT storage tier + DHTTestApp driver + GlobalDhtTestMap oracle.

TPU-native rebuild of the reference stack (SURVEY.md §2.4/§3.4):

  * tier 1 — DHT (src/applications/dht/DHT.{h,cc} + DHTDataStorage):
    PUT = sibling lookup for numReplica replicas, then a routed
    ``DHTPutCall`` to each (sendPutLookupCall DHT.cc:504); GET = lookup +
    ``DHTGetCall``; per-key TTL eviction.  Values travel as 32-bit ids —
    arbitrary payload bytes live host-side, keyed by id (the delay model
    only needs sizes; reference BinaryValue semantics preserved for the
    test workload);
  * tier 2 — DHTTestApp (src/tier2/dhttestapp/DHTTestApp.{h,cc}):
    periodic alternating PUT(random oracle key, fresh value) /
    GET(known key) every testInterval=60s (default.ini:76), validated
    against the global truth;
  * GlobalDhtTestMap (src/tier2/dhttestapp/GlobalDhtTestMap.{h,cc}):
    simulation-global key→value truth.  Vmapped node handlers cannot
    write shared state, so commits flow as "g:" events folded in by
    ``post_step`` (engine/logic.py LogicBase discipline).  A PUT's truth
    is recorded when the initiator's quorum completes — the same moment
    the reference's DHTTestApp stores into GlobalDhtTestMap (on
    DHTputCAPIResponse, DHTTestApp.cc:163-182).

Simplifications vs the reference (documented): one outstanding DHT
operation per node (the reference allows several concurrent CAPI calls);
GET quorum is first-response (numGetRequests=1) rather than
ratioIdentical voting over 4 parallel gets; no ownership handover puts
on churn yet (update() maintenance TODO).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
NO_VAL = jnp.int32(-1)

OP_NONE, OP_PUT, OP_GET = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class DhtParams:
    """default.ini:67-77 + tier2 dhtTestApp namespace."""

    num_replica: int = 4          # numReplica
    test_interval: float = 60.0   # dhtTestApp.testInterval
    test_ttl: float = 300.0       # dhtTestApp.testTtl
    storage_slots: int = 32       # per-node DHTDataStorage capacity
    num_test_keys: int = 64       # GlobalDhtTestMap key pool size
    op_timeout: float = 10.0      # CAPI timeout (lookup+put round)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DhtState:
    """Per-node tier-1 storage + tier-2 driver state ([N, ...])."""

    # DHTDataStorage
    s_key: jnp.ndarray     # [N, D, KL] u32
    s_val: jnp.ndarray     # [N, D] i32 (NO_VAL = empty)
    s_expire: jnp.ndarray  # [N, D] i64
    # test driver
    t_test: jnp.ndarray    # [N] i64
    seq: jnp.ndarray       # [N] i32
    # one outstanding operation
    op: jnp.ndarray        # [N] i32 OP_*
    op_seq: jnp.ndarray    # [N] i32 — op nonce (stale-completion guard)
    op_g: jnp.ndarray      # [N] i32 oracle slot
    op_val: jnp.ndarray    # [N] i32 value being put
    op_expect: jnp.ndarray  # [N] i32 truth value for pending GET
    op_pending: jnp.ndarray  # [N] i32 replica responses awaited
    op_acks: jnp.ndarray   # [N] i32
    op_to: jnp.ndarray     # [N] i64 op timeout
    op_t0: jnp.ndarray     # [N] i64 op start (latency stat)
    # staged truth commit, folded into DhtGlobal by post_step
    commit_g: jnp.ndarray      # [N] i32 oracle slot (-1 = none)
    commit_val: jnp.ndarray    # [N] i32
    commit_expire: jnp.ndarray  # [N] i64


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DhtGlobal:
    """GlobalDhtTestMap: the key pool and current truth values."""

    keys: jnp.ndarray   # [G, KL] u32 — fixed random test keys
    val: jnp.ndarray    # [G] i32 — current truth (-1 = never put)
    expire: jnp.ndarray  # [G] i64 — truth TTL deadline


class DhtApp:
    """Tier app (interface: apps/base.py)."""

    def __init__(self, params: DhtParams = DhtParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC):
        self.p = params
        self.spec = spec

    def stat_spec(self):
        return dict(
            scalars=("dht_put_latency_s", "dht_get_latency_s"),
            hists=(),
            counters=("dht_put_attempts", "dht_put_success",
                      "dht_get_attempts", "dht_get_success",
                      "dht_get_wrong", "dht_get_notfound",
                      "dht_lookup_failed", "dht_stored"))

    def init(self, n: int) -> DhtState:
        p, kl = self.p, self.spec.lanes
        d = p.storage_slots
        return DhtState(
            s_key=jnp.zeros((n, d, kl), U32),
            s_val=jnp.full((n, d), NO_VAL, I32),
            s_expire=jnp.zeros((n, d), I64),
            t_test=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32),
            op=jnp.zeros((n,), I32),
            op_seq=jnp.zeros((n,), I32),
            op_g=jnp.zeros((n,), I32),
            op_val=jnp.full((n,), NO_VAL, I32),
            op_expect=jnp.full((n,), NO_VAL, I32),
            op_pending=jnp.zeros((n,), I32),
            op_acks=jnp.zeros((n,), I32),
            op_to=jnp.full((n,), T_INF, I64),
            op_t0=jnp.zeros((n,), I64),
            commit_g=jnp.full((n,), -1, I32),
            commit_val=jnp.full((n,), NO_VAL, I32),
            commit_expire=jnp.zeros((n,), I64),
        )

    def glob_init(self, rng) -> DhtGlobal:
        g = self.p.num_test_keys
        return DhtGlobal(
            keys=keys_mod.random_keys(rng, (g,), self.spec),
            val=jnp.full((g,), NO_VAL, I32),
            expire=jnp.zeros((g,), I64))

    def post_step(self, ctx, state: DhtState, glob: DhtGlobal, events):
        """Fold per-node staged put-commits into the truth map (the
        moment the reference's DHTTestApp stores into GlobalDhtTestMap)."""
        del events
        rows = jnp.where(state.commit_g >= 0, state.commit_g,
                         glob.val.shape[0])
        glob = dataclasses.replace(
            glob,
            val=glob.val.at[rows].set(state.commit_val, mode="drop"),
            expire=glob.expire.at[rows].set(state.commit_expire,
                                            mode="drop"))
        n = state.commit_g.shape[0]
        state = dataclasses.replace(
            state, commit_g=jnp.full((n,), -1, I32))
        return state, glob

    def on_ready(self, app, en, now, rng):
        off = jax.random.uniform(rng, (), minval=0.0,
                                 maxval=self.p.test_interval)
        t = now + (off * NS).astype(I64)
        return dataclasses.replace(app, t_test=jnp.where(en, t, app.t_test))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_test=jnp.where(en, T_INF, app.t_test),
            op=jnp.where(en, OP_NONE, app.op),
            op_to=jnp.where(en, T_INF, app.op_to))

    def next_event(self, app):
        return jnp.minimum(app.t_test, app.op_to)

    # -- timers --------------------------------------------------------------

    def on_timer(self, app, en, ctx, now, rng, ev):
        p = self.p
        glob: DhtGlobal = ctx.glob
        g_n = glob.val.shape[0]

        # op timeout → failed operation
        to = (app.op != OP_NONE) & (app.op_to < ctx.t_end)
        ev.count("dht_lookup_failed", to)
        app = dataclasses.replace(
            app,
            op=jnp.where(to, OP_NONE, app.op),
            op_to=jnp.where(to, T_INF, app.op_to))

        # periodic test: alternate PUT / GET (DHTTestApp::handleTimerEvent
        # issues a put or get per tick of its own timers; we alternate on
        # the sequence number)
        fire = en & (app.t_test < ctx.t_end) & (app.op == OP_NONE)
        r_g, r_v = jax.random.split(rng)
        g = jax.random.randint(r_g, (), 0, g_n, dtype=I32)
        do_get_pref = (app.seq % 2) == 1
        truth_ok = (glob.val[g] != NO_VAL) & (glob.expire[g] > now)
        do_get = fire & do_get_pref & truth_ok
        do_put = fire & ~do_get
        ev.count("dht_put_attempts", do_put)
        ev.count("dht_get_attempts", do_get)
        # fresh value id: unique per (node, seq) — 24 bits of rng + seq mix
        val = jnp.abs(jax.random.randint(r_v, (), 0, 2**30, dtype=I32))
        key = glob.keys[g]
        app = dataclasses.replace(
            app,
            t_test=jnp.where(fire | (en & (app.t_test < ctx.t_end)),
                             jnp.maximum(app.t_test, now) + jnp.int64(
                                 int(p.test_interval * NS)),
                             app.t_test),
            seq=app.seq + fire.astype(I32),
            op=jnp.where(do_put, OP_PUT, jnp.where(do_get, OP_GET, app.op)),
            op_seq=jnp.where(fire, app.seq, app.op_seq),
            op_g=jnp.where(fire, g, app.op_g),
            op_val=jnp.where(do_put, val, app.op_val),
            op_expect=jnp.where(do_get, glob.val[g], app.op_expect),
            op_pending=jnp.where(fire, 0, app.op_pending),
            op_acks=jnp.where(fire, 0, app.op_acks),
            op_to=jnp.where(fire, now + jnp.int64(int(p.op_timeout * NS)),
                            app.op_to),
            op_t0=jnp.where(fire, now, app.op_t0))
        return app, base.LookupReq(want=do_put | do_get, key=key,
                                   tag=app.op_seq)

    # -- lookup completion → replica fan-out ---------------------------------

    def on_lookup_done(self, app, done: base.LookupDone, ctx, ob, ev, now,
                       node_idx):
        p = self.p
        # op nonce match rejects completions of a previously-timed-out op
        # (a fresh op may have started in the same window)
        en = done.en & (app.op != OP_NONE) & (done.tag == app.op_seq)
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("dht_lookup_failed", en & ~suc)
        app = dataclasses.replace(
            app,
            op=jnp.where(en & ~suc, OP_NONE, app.op),
            op_to=jnp.where(en & ~suc, T_INF, app.op_to))

        # PUT: DHTPutCall to up to numReplica siblings (DHT.cc:210-237)
        is_put = en & suc & (app.op == OP_PUT)
        nrep = jnp.int32(0)
        for i in range(min(p.num_replica, done.results.shape[0])):
            tgt = done.results[i]
            send = is_put & (tgt != NO_NODE)
            # self-replica: store locally via on_msg loopback (send to self
            # costs nothing in the delay model, SimpleUDP.cc:322)
            # ns-precise expiry rides the stamp field — replica and truth
            # map must share the exact same deadline
            # b carries the op nonce; responders echo it so stragglers
            # from a timed-out op can't ack a newer op's quorum
            ob.send(send, now, tgt, wire.DHT_PUT_CALL, key=done.target,
                    a=app.op_val, b=app.op_seq,
                    stamp=app.op_t0 + jnp.int64(int(self.p.test_ttl * NS)),
                    size_b=wire.BASE_CALL_B + 20 + 8)
            nrep += send.astype(I32)
        app = dataclasses.replace(
            app, op_pending=jnp.where(is_put, nrep, app.op_pending))

        # GET: DHTGetCall to the closest sibling
        is_get = en & suc & (app.op == OP_GET)
        ob.send(is_get, now, done.results[0], wire.DHT_GET_CALL,
                key=done.target, b=app.op_seq,
                size_b=wire.BASE_CALL_B + 20)
        return app

    # -- inbound messages ----------------------------------------------------

    def _store(self, app, en, key, val, expire):
        """DHTDataStorage::addData: overwrite same key, else free slot,
        else evict the earliest-expiring entry."""
        same = en & jnp.any(jnp.all(app.s_key == key[None, :], axis=-1)
                            & (app.s_val != NO_VAL))
        col_same = jnp.argmax(
            jnp.all(app.s_key == key[None, :], axis=-1)
            & (app.s_val != NO_VAL)).astype(I32)
        free = app.s_val == NO_VAL
        col_free = jnp.argmax(free).astype(I32)
        col_evict = jnp.argmin(app.s_expire).astype(I32)
        col = jnp.where(same, col_same,
                        jnp.where(jnp.any(free), col_free, col_evict))
        col = jnp.where(en, col, app.s_val.shape[0])  # OOB drop
        return dataclasses.replace(
            app,
            s_key=app.s_key.at[col].set(key, mode="drop"),
            s_val=app.s_val.at[col].set(val, mode="drop"),
            s_expire=app.s_expire.at[col].set(expire, mode="drop"))

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        p = self.p
        now = m.t_deliver

        # DHTPutCall → store + ack (DHT::handlePutRequest)
        en = m.valid & (m.kind == wire.DHT_PUT_CALL)
        expire = m.stamp
        app = self._store(app, en, m.key, m.a, expire)
        ev.count("dht_stored", en)
        ob.send(en, now, m.src, wire.DHT_PUT_RES, key=m.key, b=m.b,
                size_b=wire.BASE_CALL_B)

        # DHTPutResponse → ack counting; full quorum = success.  The op
        # nonce echoed in b rejects straggler acks from a timed-out op
        # (the reference ties CAPI responses to RPC nonces)
        en = (m.valid & (m.kind == wire.DHT_PUT_RES) & (app.op == OP_PUT)
              & (m.b == app.op_seq))
        acks = app.op_acks + en.astype(I32)
        complete = en & (acks >= app.op_pending) & (app.op_pending > 0)
        ev.count("dht_put_success", complete)
        ev.value("dht_put_latency_s",
                 (now - app.op_t0).astype(jnp.float32) / NS, complete)
        app = dataclasses.replace(
            app,
            op_acks=acks,
            op=jnp.where(complete, OP_NONE, app.op),
            op_to=jnp.where(complete, T_INF, app.op_to),
            # stage the truth commit for post_step
            commit_g=jnp.where(complete, app.op_g, app.commit_g),
            commit_val=jnp.where(complete, app.op_val, app.commit_val),
            commit_expire=jnp.where(
                complete, app.op_t0 + jnp.int64(int(p.test_ttl * NS)),
                app.commit_expire))

        # DHTGetCall → storage probe + reply (DHT::handleGetRequest)
        en = m.valid & (m.kind == wire.DHT_GET_CALL)
        hit = (jnp.all(app.s_key == m.key[None, :], axis=-1)
               & (app.s_val != NO_VAL) & (app.s_expire > now))
        found = jnp.any(hit)
        val = jnp.where(found, app.s_val[jnp.argmax(hit)], NO_VAL)
        ob.send(en, now, m.src, wire.DHT_GET_RES, key=m.key, a=val, b=m.b,
                size_b=wire.BASE_CALL_B + 8)

        # DHTGetResponse → validate vs the CURRENT truth (the reference
        # reads GlobalDhtTestMap at response time, DHTTestApp.cc:121-182).
        # Nonce + key match guard against stale responses completing a
        # newer GET with a mismatched value
        op_key = ctx.glob.keys[jnp.clip(app.op_g, 0,
                                        ctx.glob.val.shape[0] - 1)]
        en = (m.valid & (m.kind == wire.DHT_GET_RES) & (app.op == OP_GET)
              & (m.b == app.op_seq) & jnp.all(m.key == op_key))
        expect = ctx.glob.val[jnp.clip(app.op_g, 0,
                                       ctx.glob.val.shape[0] - 1)]
        good = en & (m.a == expect) & (m.a != NO_VAL)
        ev.count("dht_get_success", good)
        ev.count("dht_get_wrong", en & (m.a != expect) & (m.a != NO_VAL))
        ev.count("dht_get_notfound", en & (m.a == NO_VAL))
        ev.value("dht_get_latency_s",
                 (now - app.op_t0).astype(jnp.float32) / NS, good)
        app = dataclasses.replace(
            app,
            op=jnp.where(en, OP_NONE, app.op),
            op_to=jnp.where(en, T_INF, app.op_to))
        return app

    @property
    def hist_map(self):
        return {}
