"""NTree — server-less quadtree game overlay, vectorized.

Rebuild of the reference NTree (src/overlay/ntree/NTree.{h,cc}: the game
world is a quadtree of groups; a group divides when its membership
exceeds maxChildren and collapses when it shrinks (handleDivideCall,
NTree.h:124-137); game events route to the responsible tree nodes which
disseminate to the region's members).

Engine mapping (documented): the reference's self-organized tree-node
ownership is replaced by **rendezvous hashing over the KBR overlay
underneath** — the leader of quadtree cell c is the node responsible
for hash(c) (the engine's generic responsibility oracle), so NTree runs
as a tier app on any KBR logic.  The quadtree DYNAMICS are preserved:

  * every player registers with the leader of its current cell at its
    current depth, refreshing periodically (soft state);
  * a leader whose cell exceeds ``max_children`` members answers with
    DIVIDE — members descend one level (deeper cell, new leader), the
    reference's group division;
  * a leader seeing ≤ ``collapse_below`` members at depth > 0 answers
    COLLAPSE — members ascend one level (group collapse);
  * game events go to the cell leader, which fans them out to the
    registered members (event dissemination through the tree level).

Stats: registrations, divides, collapses, events sent/delivered — the
reference's group-size/latency KPIs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.apps import movement as move_mod
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

# wire kinds (NTree family: 120+)
NT_JOIN = 120       # register at cell leader: a=cell id, b=depth
NT_JOIN_ACK = 121   # b=1 → DIVIDE (descend), b=2 → COLLAPSE (ascend)
NT_EVENT = 122      # game event to leader: a=cell id, stamp=t0
NT_EVENT_FWD = 123  # leader → member fan-out

M_REG, M_EVENT = 0, 1


@dataclasses.dataclass(frozen=True)
class NTreeParams:
    max_depth: int = 3            # static quadtree depth bound
    max_children: int = 5         # divide threshold (maxChildren)
    collapse_below: int = 2       # collapse threshold
    member_slots: int = 8         # per-led-cell member table
    led_cells: int = 4            # cells one node can lead
    move_interval: float = 5.0
    refresh: float = 10.0         # registration refresh
    event_interval: float = 10.0
    move: move_mod.MoveParams = move_mod.MoveParams(field=1000.0, speed=20.0)

    @property
    def num_cells(self) -> int:
        # 1 + 4 + 16 + ... = (4^(L+1) - 1) / 3
        return (4 ** (self.max_depth + 1) - 1) // 3


def cell_of(pos, depth: int, p: NTreeParams):
    """Quadtree cell id for a position at static ``depth`` (row-major per
    level, levels packed: offset(l) = (4^l - 1)/3)."""
    side = 1 << depth                         # cells per axis = 2^depth
    cw = p.move.field / side
    cx = jnp.clip((pos[..., 0] / cw).astype(I32), 0, side - 1)
    cy = jnp.clip((pos[..., 1] / cw).astype(I32), 0, side - 1)
    return ((4 ** depth) - 1) // 3 + cx * side + cy


def cell_of_dyn(pos, depth, p: NTreeParams):
    """Traced-depth variant (depth is an i32 array)."""
    side = (jnp.int32(1) << depth).astype(I32)
    cw = p.move.field / side.astype(jnp.float32)
    cx = jnp.clip((pos[..., 0] / cw).astype(I32), 0, side - 1)
    cy = jnp.clip((pos[..., 1] / cw).astype(I32), 0, side - 1)
    offset = (((jnp.int32(1) << (2 * depth)) - 1) // 3).astype(I32)
    return offset + cx * side + cy


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NTreeState:
    pos: jnp.ndarray       # [N, 2]
    wp: jnp.ndarray        # [N, 2]
    depth: jnp.ndarray     # [N] i32 current subscription depth
    cell: jnp.ndarray      # [N] i32 registered cell (-1 none)
    # leader-side: led cells + their member tables
    led_cell: jnp.ndarray  # [N, C] i32 cell id (-1 free)
    led_mem: jnp.ndarray   # [N, C, M] i32
    led_seen: jnp.ndarray  # [N, C, M] i64
    t_move: jnp.ndarray    # [N] i64
    t_reg: jnp.ndarray     # [N] i64
    t_evt: jnp.ndarray     # [N] i64
    seq: jnp.ndarray       # [N] i32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class NTreeGlobal:
    cell_keys: jnp.ndarray   # [num_cells, KL] u32 rendezvous keys


class NTreeApp:
    """Tier app (interface: apps/base.py docstring)."""

    def __init__(self, params: NTreeParams = NTreeParams(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC):
        self.p = params
        self.spec = spec

    def stat_spec(self):
        return dict(
            scalars=("ntree_event_latency_s", "ntree_group_size"),
            hists=(),
            counters=("ntree_registers", "ntree_divides",
                      "ntree_collapses", "ntree_events",
                      "ntree_event_delivered", "ntree_lookup_failed"))

    def init(self, n: int) -> NTreeState:
        p = self.p
        pos, wp = move_mod.init_positions(jax.random.PRNGKey(131), n,
                                          p.move)
        return NTreeState(
            pos=pos, wp=wp,
            depth=jnp.zeros((n,), I32),
            cell=jnp.full((n,), -1, I32),
            led_cell=jnp.full((n, p.led_cells), -1, I32),
            led_mem=jnp.full((n, p.led_cells, p.member_slots), NO_NODE,
                             I32),
            led_seen=jnp.zeros((n, p.led_cells, p.member_slots), I64),
            t_move=jnp.full((n,), T_INF, I64),
            t_reg=jnp.full((n,), T_INF, I64),
            t_evt=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32))

    def glob_init(self, rng) -> NTreeGlobal:
        return NTreeGlobal(cell_keys=keys_mod.random_keys(
            rng, (self.p.num_cells,), self.spec))

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        off = (jax.random.uniform(rng, ())
               * self.p.event_interval * NS).astype(I64)
        return dataclasses.replace(
            app,
            t_move=jnp.where(en, now + jnp.int64(
                int(self.p.move_interval * NS)), app.t_move),
            t_reg=jnp.where(en, now, app.t_reg),
            t_evt=jnp.where(en, now + off, app.t_evt))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_move=jnp.where(en, T_INF, app.t_move),
            t_reg=jnp.where(en, T_INF, app.t_reg),
            t_evt=jnp.where(en, T_INF, app.t_evt))

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        return app    # tree state is soft (refresh-rebuilt)

    def next_event(self, app):
        return jnp.minimum(app.t_move,
                           jnp.minimum(app.t_reg, app.t_evt))

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        glob: NTreeGlobal = ctx.glob

        # movement
        mv = en & (app.t_move < ctx.t_end)
        r_mv, _ = jax.random.split(rng)
        npos, nwp = move_mod.step(app.pos, app.wp,
                                  jnp.float32(p.move_interval), r_mv,
                                  p.move,
                                  t_s=ctx.t_start.astype(
                                      jnp.float32) / NS)
        app = dataclasses.replace(
            app,
            pos=jnp.where(mv, npos, app.pos),
            wp=jnp.where(mv, nwp, app.wp),
            t_move=jnp.where(mv, now + jnp.int64(
                int(p.move_interval * NS)), app.t_move))

        # registration refresh / event — one lookup per fire
        reg_hit = en & (app.t_reg < ctx.t_end)
        evt_hit = en & (app.t_evt < ctx.t_end)
        reg_due = reg_hit
        evt_due = evt_hit & ~reg_due
        my_cell = cell_of_dyn(app.pos, app.depth, p)
        tgt_cell = jnp.clip(my_cell, 0, p.num_cells - 1)
        key = glob.cell_keys[tgt_cell]
        ev.count("ntree_registers", reg_due)
        ev.count("ntree_events", evt_due & ctx.measuring)
        app = dataclasses.replace(
            app,
            t_reg=jnp.where(reg_hit, now + jnp.int64(
                int(p.refresh * NS)), app.t_reg),
            t_evt=jnp.where(evt_hit, now + jnp.int64(
                int(p.event_interval * NS)), app.t_evt),
            seq=app.seq + (reg_due | evt_due).astype(I32))
        mode = jnp.where(reg_due, M_REG, M_EVENT)
        return app, base.LookupReq(want=reg_due | evt_due, key=key,
                                   tag=tgt_cell * 4 + mode)

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        p = self.p
        en = done.en
        mode = done.tag % 4
        cell = done.tag // 4
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("ntree_lookup_failed", en & ~suc)
        leader = done.results[0]
        ob.send(en & suc & (mode == M_REG), now, leader, NT_JOIN,
                a=cell, b=app.depth, size_b=24)
        ob.send(en & suc & (mode == M_EVENT), now, leader, NT_EVENT,
                a=cell, stamp=now, size_b=64)
        return app

    def _led_row(self, app, cell):
        """(row index for this cell, have_row) in the led-cell table."""
        hit = app.led_cell == cell
        have = jnp.any(hit)
        free = app.led_cell < 0
        row = jnp.where(have, jnp.argmax(hit),
                        jnp.argmax(free)).astype(I32)
        return row, have | jnp.any(free)

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        p = self.p
        now = m.t_deliver

        # member registration at the leader (NTree join/divide logic)
        en = m.valid & (m.kind == NT_JOIN)
        row, ok = self._led_row(app, m.a)
        row_ok = en & ok
        mem = app.led_mem[row]
        seen = app.led_seen[row]
        # refresh or insert member (LRU slot on overflow)
        mh = mem == m.src
        col = jnp.where(jnp.any(mh), jnp.argmax(mh),
                        jnp.argmin(seen)).astype(I32)
        wrow = jnp.where(row_ok, row, p.led_cells)
        app = dataclasses.replace(
            app,
            led_cell=app.led_cell.at[wrow].set(m.a, mode="drop"),
            led_mem=app.led_mem.at[wrow, col].set(m.src, mode="drop"),
            led_seen=app.led_seen.at[wrow, col].set(now, mode="drop"))
        # census after insert (count fresh members)
        mem2 = app.led_mem[jnp.clip(row, 0, p.led_cells - 1)]
        seen2 = app.led_seen[jnp.clip(row, 0, p.led_cells - 1)]
        fresh = (mem2 != NO_NODE) & (
            seen2 + jnp.int64(int(3 * p.refresh * NS)) > now)
        n_mem = jnp.sum(fresh.astype(I32))
        ev.value("ntree_group_size", n_mem.astype(jnp.float32),
                 row_ok & ctx.measuring)
        # divide when too big and not at max depth; collapse when
        # too small and below the root
        divide = row_ok & (n_mem > p.max_children) & (
            m.b < p.max_depth)
        collapse = row_ok & ~divide & (n_mem <= p.collapse_below) & (
            m.b > 0)
        ev.count("ntree_divides", divide)
        ev.count("ntree_collapses", collapse)
        code = jnp.where(divide, 1, jnp.where(collapse, 2, 0))
        ob.send(row_ok, now, m.src, NT_JOIN_ACK, a=m.a, b=code,
                size_b=16)

        # registration answer at the member
        en = m.valid & (m.kind == NT_JOIN_ACK)
        descend = en & (m.b == 1)
        ascend = en & (m.b == 2)
        app = dataclasses.replace(
            app,
            cell=jnp.where(en, m.a, app.cell),
            depth=jnp.clip(app.depth + descend.astype(I32)
                           - ascend.astype(I32), 0, p.max_depth),
            # re-register right away after a depth change
            t_reg=jnp.where(descend | ascend, now, app.t_reg))

        # event at the leader → fan out to the cell's members
        en = m.valid & (m.kind == NT_EVENT)
        row, ok = self._led_row(app, m.a)
        row = jnp.clip(row, 0, p.led_cells - 1)
        mem = app.led_mem[row]
        seen = app.led_seen[row]
        fresh = (mem != NO_NODE) & (
            seen + jnp.int64(int(3 * p.refresh * NS)) > now)
        for j in range(p.member_slots):
            tgt = mem[j]
            ob.send(en & ok & fresh[j] & (tgt != m.src), now,
                    jnp.maximum(tgt, 0), NT_EVENT_FWD, a=m.a,
                    stamp=m.stamp, size_b=64)

        # event delivery at members
        en = m.valid & (m.kind == NT_EVENT_FWD)
        ev.count("ntree_event_delivered", en & ctx.measuring)
        ev.value("ntree_event_latency_s",
                 (now - m.stamp).astype(jnp.float32) / NS,
                 en & ctx.measuring)
        return app

    @property
    def hist_map(self):
        return {}
