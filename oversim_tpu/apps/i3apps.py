"""i3 sample applications (reference src/applications/i3/i3Apps/*).

Vectorized rebuilds of the reference's I3BaseApp demo suite over the
I3App server core (apps/i3.py).  Each app keeps the reference's
rendezvous structure: identifiers share a CLASS PREFIX, and
``asOverlayKey`` uses only the prefix bytes (I3Identifier.cc:124-127) —
so every identifier of a class resolves to the SAME responsible server,
where longest-prefix matching picks among the class's triggers.

  * I3MulticastApp  — i3Apps/I3Multicast.cc: all group members register
    the IDENTICAL identifier; a packet to it fans out to the whole
    trigger set (I3.cc sendPacket's per-identifier loop).
  * I3AnycastApp    — i3Apps/I3Anycast.cc: members register
    prefix+own-suffix triggers; a packet to prefix+random-suffix lands
    on the closest match (one random member), which re-sends — a
    perpetual anycast ping chain.
  * I3MobilityApp   — i3Apps/I3HostMobility.cc: members register
    prefix+suffix ids, anycast-discover partners (MSG_QUERY_ID /
    MSG_REPLY_ID), then ping them; a mobility event re-randomizes the
    member's identifier (doMobilityEvent → reinsert), so pings to the
    stale id are lost until the next partner rediscovery — the lost-
    packet KPI.
  * I3StretchApp    — i3Apps/I3LatencyStretch.cc: each ping crosses the
    indirection point while the pong returns directly; the latency
    ratio of the two legs is the i3 stretch KPI.

Identifier mapping: class key = ``glob.trigger_ids[slot]`` (lookup key,
the "prefix hash"); wire id = top ``min_prefix_bits`` of that key's
head lane (the class prefix) | a per-node or random suffix in the low
bits.  Payload kinds ride the pooled ``d`` field (the reference's typed
cPacket kinds, I3HostMobility.cc MSG_*).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.apps.i3 import (I3App, I3Global, I3Params, I3State,
                                 M_INSERT, M_SEND, NO_NODE, NS, T_INF,
                                 wire_id)
from oversim_tpu.common import wire

I32 = jnp.int32
I64 = jnp.int64

# payload kinds in ``d`` (I3HostMobility.cc MSG_QUERY_ID/MSG_REPLY_ID/
# MSG_PING/MSG_REPLY; I3LatencyStretch's ping/pong)
D_DATA = 0
D_QUERY = 1
D_REPLY_ID = 2
D_PING = 3
D_PONG = 4


def _prefix_of(glob: I3Global, slot, bits: int):
    """Class prefix: top ``bits`` of the slot's oracle wire id."""
    mask = jnp.uint32(0xFFFFFFFF) << (32 - bits)
    return (wire_id(glob, slot).astype(jnp.uint32) & mask)


def _class_id(glob: I3Global, slot, suffix, bits: int):
    """prefix | suffix, top bit cleared (-1 is the empty marker)."""
    mask_lo = (jnp.uint32(1) << (32 - bits)) - 1
    raw = _prefix_of(glob, slot, bits) | (
        jnp.asarray(suffix).astype(jnp.uint32) & mask_lo)
    return (raw & jnp.uint32(0x7FFFFFFF)).astype(I32)


def _mix(x):
    """Cheap deterministic 32-bit mixer for in-graph random suffixes."""
    x = jnp.asarray(x).astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


class I3MulticastApp(I3App):
    """All members of group g register the identical identifier; every
    send to it reaches the whole group (i3Apps/I3Multicast.cc: "All
    nodes register the same identifier ... all participating nodes
    receive the packet")."""

    def __init__(self, params: I3Params = I3Params(), num_groups: int = 1,
                 **kw):
        super().__init__(params, **kw)
        self.num_groups = num_groups

    def stat_spec(self):
        s = super().stat_spec()
        s["counters"] = s["counters"] + ("i3_mcast_recv",)
        return s

    def _group(self, node_idx):
        return node_idx % self.num_groups

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        g = self._group(node_idx)
        ins_hit = en & (app.t_ins < ctx.t_end)
        snd_hit = en & (app.t_send < ctx.t_end)
        ins_due = ins_hit
        snd_due = snd_hit & ~ins_due
        ev.count("i3_inserts", ins_due)
        ev.count("i3_sent", snd_due & ctx.measuring)
        key = ctx.glob.trigger_ids[g]          # class key: same server
        app = dataclasses.replace(
            app,
            t_ins=jnp.where(ins_hit, now + jnp.int64(
                int(p.refresh * NS)), app.t_ins),
            t_send=jnp.where(snd_hit, now + jnp.int64(
                int(p.send_interval * NS)), app.t_send),
            seq=app.seq + (ins_due | snd_due).astype(I32))
        mode = jnp.where(ins_due, M_INSERT, M_SEND)
        # base on_lookup_done derives the wire id from tag//4 — the
        # group slot — so insert and send both use the group identifier
        return app, base.LookupReq(want=ins_due | snd_due, key=key,
                                   tag=g * 4 + mode)

    def _on_deliver(self, app, m, ctx, ob, ev, en):
        mine = m.a == wire_id(ctx.glob, self._group(m.dst))
        ev.count("i3_misdelivered", en & ~mine & ctx.measuring)
        en = en & mine
        ev.count("i3_delivered", en & ctx.measuring)
        ev.count("i3_mcast_recv", en & ctx.measuring)
        ev.value("i3_latency_s",
                 (m.t_deliver - m.stamp).astype(jnp.float32) / NS,
                 en & ctx.measuring)
        return app


class I3AnycastApp(I3App):
    """Anycast ping chain (i3Apps/I3Anycast.cc): every member registers
    prefix|own-suffix; initiators send prefix|random-suffix once, and
    every delivery re-sends to a fresh random suffix — packets hop
    member-to-member forever through the rendezvous server."""

    POOL = 0   # class slot: glob.trigger_ids[0] is the pool identifier

    def _suffix(self, node_idx):
        return _mix(node_idx.astype(jnp.uint32) ^ jnp.uint32(0xA17C)) | 1

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        ins_hit = en & (app.t_ins < ctx.t_end)
        snd_hit = en & (app.t_send < ctx.t_end)
        ins_due = ins_hit
        snd_due = snd_hit & ~ins_due
        ev.count("i3_inserts", ins_due)
        ev.count("i3_sent", snd_due & ctx.measuring)
        key = ctx.glob.trigger_ids[self.POOL]
        app = dataclasses.replace(
            app,
            t_ins=jnp.where(ins_hit, now + jnp.int64(
                int(p.refresh * NS)), app.t_ins),
            # the reference seeds the chain once (node 0); circulating
            # packets can die here (drops, ttl gaps), so members re-seed
            # at a slow cadence to keep the chain population stable
            t_send=jnp.where(snd_hit, now + jnp.int64(
                int(4 * p.send_interval * NS)), app.t_send),
            seq=app.seq + (ins_due | snd_due).astype(I32))
        mode = jnp.where(ins_due, M_INSERT, M_SEND)
        return app, base.LookupReq(want=ins_due | snd_due, key=key,
                                   tag=node_idx * 4 + mode)

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        p = self.p
        en = done.en
        mode = done.tag % 4
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("i3_lookup_failed", en & ~suc)
        server = done.results[0]
        my_id = _class_id(ctx.glob, self.POOL, self._suffix(node_idx),
                          p.min_prefix_bits)
        rnd_id = _class_id(ctx.glob, self.POOL,
                           _mix(now.astype(jnp.uint32)
                                ^ node_idx.astype(jnp.uint32)),
                           p.min_prefix_bits)
        ob.send(en & suc & (mode == M_INSERT), now, server,
                wire.I3_INSERT, a=my_id, b=node_idx, c=jnp.int32(-1),
                stamp=now + jnp.int64(int(p.trigger_ttl * NS)),
                size_b=wire.BASE_CALL_B + 12)
        ob.send(en & suc & (mode == M_SEND), now, server,
                wire.I3_PACKET, a=rnd_id, b=node_idx, stamp=now,
                size_b=p.payload_bytes)
        return app

    def _on_deliver(self, app, m, ctx, ob, ev, en):
        p = self.p
        now = m.t_deliver
        # prefix membership is the only validity test (any member is a
        # legitimate anycast target)
        mine = (m.a.astype(jnp.uint32)
                & (jnp.uint32(0xFFFFFFFF) << (32 - p.min_prefix_bits))
                ) == _prefix_of(ctx.glob, self.POOL, p.min_prefix_bits)
        ev.count("i3_misdelivered", en & ~mine & ctx.measuring)
        en = en & mine
        ev.count("i3_delivered", en & ctx.measuring)
        ev.value("i3_latency_s",
                 (now - m.stamp).astype(jnp.float32) / NS,
                 en & ctx.measuring)
        # deliver() re-sends to a fresh random suffix (I3Anycast.cc:
        # "after arrival, repeat the same process"); the rendezvous
        # server is the forwarder (m.src), no fresh lookup needed
        nxt = _class_id(ctx.glob, self.POOL,
                        _mix(now.astype(jnp.uint32) * jnp.uint32(2654435761)
                             ^ m.dst.astype(jnp.uint32)),
                        p.min_prefix_bits)
        ob.send(en, now, m.src, wire.I3_PACKET, a=nxt, b=m.dst,
                stamp=now, size_b=p.payload_bytes)
        return app


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MobilityState(I3State):
    gen: jnp.ndarray        # [N] i32 — identifier generation (mobility)
    partner: jnp.ndarray    # [N, 2] i32 — learned partner wire ids
    srv: jnp.ndarray        # [N] i32 — cached rendezvous server slot
    t_ping: jnp.ndarray     # [N] i64
    t_disc: jnp.ndarray     # [N] i64 — partner (re)discovery timer
    t_move: jnp.ndarray     # [N] i64 — next mobility event


class I3MobilityApp(I3App):
    """Host mobility over i3 (i3Apps/I3HostMobility.cc): partners are
    discovered by anycast MSG_QUERY_ID (answered with the responder's
    CURRENT identifier), pinged periodically, and a mobility event
    re-randomizes the identifier — pings addressed to the stale id are
    lost until the next rediscovery round, the recorded lost-packet
    KPI (I3HostMobility::finish)."""

    def __init__(self, params: I3Params = I3Params(),
                 ping_interval: float = 2.0,
                 rediscover_interval: float = 60.0,
                 move_interval: float = 120.0, **kw):
        super().__init__(params, **kw)
        self.ping_interval = ping_interval
        self.rediscover_interval = rediscover_interval
        self.move_interval = move_interval

    POOL = 0

    def stat_spec(self):
        s = super().stat_spec()
        s["counters"] = s["counters"] + (
            "i3_mob_ping_sent", "i3_mob_pong_recv", "i3_mob_moves",
            "i3_mob_partners")
        return s

    def _my_id(self, glob, node_idx, gen):
        sfx = _mix(node_idx.astype(jnp.uint32) * jnp.uint32(2654435761)
                   ^ gen.astype(jnp.uint32))
        return _class_id(glob, self.POOL, sfx, self.p.min_prefix_bits)

    def init(self, n: int) -> MobilityState:
        b = super().init(n)
        fields = {f.name: getattr(b, f.name)
                  for f in dataclasses.fields(I3State)}
        return MobilityState(
            **fields,
            gen=jnp.zeros((n,), I32),
            partner=jnp.full((n, 2), -1, I32),
            srv=jnp.full((n,), NO_NODE, I32),
            t_ping=jnp.full((n,), T_INF, I64),
            t_disc=jnp.full((n,), T_INF, I64),
            t_move=jnp.full((n,), T_INF, I64))

    def on_ready(self, app, en, now, rng):
        # NOTE: called from inside the overlay's vmapped step — all
        # fields are per-node scalars here (unlike init's [N] arrays)
        app = super().on_ready(app, en, now, rng)
        r1, r2 = jax.random.split(rng)
        joff = (jax.random.uniform(r1, ())
                * self.ping_interval * NS).astype(I64)
        moff = (jax.random.uniform(r2, ())
                * self.move_interval * NS).astype(I64)
        return dataclasses.replace(
            app,
            # the base random-workload send timer is unused here (the
            # discovery/ping cadence replaces it) — park it or it pins
            # the event horizon with no on_timer branch advancing it
            t_send=jnp.where(en, T_INF, app.t_send),
            t_ping=jnp.where(en, now + jnp.int64(int(5 * NS)) + joff,
                             app.t_ping),
            t_disc=jnp.where(en, now + jnp.int64(int(2 * NS)),
                             app.t_disc),
            t_move=jnp.where(
                en, now + jnp.int64(int(self.move_interval * NS)) + moff,
                app.t_move))

    def on_stop(self, app, en):
        app = super().on_stop(app, en)
        return dataclasses.replace(
            app,
            t_ping=jnp.where(en, T_INF, app.t_ping),
            t_disc=jnp.where(en, T_INF, app.t_disc),
            t_move=jnp.where(en, T_INF, app.t_move))

    def next_event(self, app):
        t = jnp.minimum(super().next_event(app), app.t_ping)
        return jnp.minimum(t, jnp.minimum(app.t_disc, app.t_move))

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        glob = ctx.glob
        # mobility event (doMobilityEvent → MSG_TIMER_RESET_ID): bump
        # the generation and re-insert the NEW identifier promptly
        mv = en & (app.t_move < ctx.t_end)
        ev.count("i3_mob_moves", mv)
        app = dataclasses.replace(
            app,
            gen=app.gen + mv.astype(I32),
            t_move=jnp.where(mv, now + jnp.int64(
                int(self.move_interval * NS)), app.t_move),
            t_ins=jnp.where(mv, now, app.t_ins))

        ins_hit = en & (app.t_ins < ctx.t_end)
        disc_hit = en & (app.t_disc < ctx.t_end) & ~ins_hit
        ins_due = ins_hit
        ev.count("i3_inserts", ins_due)
        key = glob.trigger_ids[self.POOL]
        app = dataclasses.replace(
            app,
            t_ins=jnp.where(ins_hit, now + jnp.int64(
                int(p.refresh * NS)), app.t_ins),
            t_disc=jnp.where(disc_hit, now + jnp.int64(
                int(self.rediscover_interval * NS)), app.t_disc))

        mode = jnp.where(ins_due, M_INSERT, M_SEND)
        return app, base.LookupReq(want=ins_due | disc_hit, key=key,
                                   tag=node_idx * 4 + mode)

    def on_tick(self, app, ctx, ob, ev, node_idx):
        """Ping a learned partner directly through the cached server
        (the on_timer hook has no outbox access; pings pace here, the
        same discipline as the DHT maintenance pump).  No lookup — the
        reference client caches its i3 server too."""
        now = ctx.t_start
        ping_hit = (ctx.ready[node_idx] & (app.t_ping < ctx.t_end)
                    & (app.t_ping != T_INF))
        kp = (_mix(now.astype(jnp.uint32) ^ node_idx.astype(jnp.uint32))
              & 1).astype(I32)
        pid = app.partner[kp]
        do_ping = ping_hit & (pid >= 0) & (app.srv != NO_NODE)
        ev.count("i3_mob_ping_sent", do_ping & ctx.measuring)
        ob.send(do_ping, now, jnp.maximum(app.srv, 0), wire.I3_PACKET,
                a=pid, b=node_idx, d=jnp.int32(D_PING), stamp=now,
                size_b=self.p.payload_bytes)
        return dataclasses.replace(
            app, t_ping=jnp.where(ping_hit, now + jnp.int64(
                int(self.ping_interval * NS)), app.t_ping))

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        p = self.p
        en = done.en
        mode = done.tag % 4
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("i3_lookup_failed", en & ~suc)
        server = done.results[0]
        app = dataclasses.replace(
            app, srv=jnp.where(en & suc, server, app.srv))
        my_id = self._my_id(ctx.glob, node_idx, app.gen)
        ob.send(en & suc & (mode == M_INSERT), now, server,
                wire.I3_INSERT, a=my_id, b=node_idx, c=jnp.int32(-1),
                stamp=now + jnp.int64(int(p.trigger_ttl * NS)),
                size_b=wire.BASE_CALL_B + 12)
        # partner discovery: anycast MSG_QUERY_ID to a random suffix
        # (discoverPartners, I3HostMobility.cc:185-200)
        rnd_id = _class_id(ctx.glob, self.POOL,
                           _mix(now.astype(jnp.uint32)
                                ^ node_idx.astype(jnp.uint32)),
                           p.min_prefix_bits)
        ob.send(en & suc & (mode == M_SEND), now, server,
                wire.I3_PACKET, a=rnd_id, b=node_idx,
                d=jnp.int32(D_QUERY), stamp=now,
                size_b=p.payload_bytes)
        return app

    def _on_deliver(self, app, m, ctx, ob, ev, en):
        p = self.p
        now = m.t_deliver
        my_id = self._my_id(ctx.glob, m.dst, app.gen)
        is_q = en & (m.d == D_QUERY)
        is_rid = en & (m.d == D_REPLY_ID)
        # a ping addressed to a PREVIOUS-generation identifier is LOST:
        # the reference host's old trigger points at its pre-move
        # address (I3HostMobility's lost-packet KPI) — the stale
        # trigger still matches at the server, but the owner is no
        # longer reachable under that identity
        is_ping = en & (m.d == D_PING) & (m.a == my_id)
        is_pong = en & (m.d == D_PONG)
        # MSG_QUERY_ID → reply directly with my CURRENT identifier
        ob.send(is_q & (m.b != m.dst), now, jnp.maximum(m.b, 0),
                wire.I3_DELIVER, a=my_id, b=m.dst,
                d=jnp.int32(D_REPLY_ID), stamp=m.stamp,
                size_b=p.payload_bytes)
        # MSG_REPLY_ID → store the partner id (ring of 2)
        slot = (app.seq % 2).astype(I32)
        slot = jnp.where(is_rid, slot, app.partner.shape[0])
        ev.count("i3_mob_partners", is_rid)
        app = dataclasses.replace(
            app,
            partner=app.partner.at[slot].set(m.a, mode="drop"),
            seq=app.seq + is_rid.astype(I32))
        # MSG_PING → direct MSG_REPLY to the sender (echo send stamp)
        ob.send(is_ping, now, jnp.maximum(m.b, 0), wire.I3_DELIVER,
                a=m.a, b=m.dst, d=jnp.int32(D_PONG), stamp=m.stamp,
                size_b=p.payload_bytes)
        ev.count("i3_delivered", is_ping & ctx.measuring)
        # MSG_REPLY → round-trip complete
        ev.count("i3_mob_pong_recv", is_pong & ctx.measuring)
        ev.value("i3_latency_s",
                 (now - m.stamp).astype(jnp.float32) / NS,
                 is_pong & ctx.measuring)
        return app


class I3StretchApp(I3App):
    """Latency stretch (i3Apps/I3LatencyStretch.cc): the ping leg
    crosses the rendezvous server, the pong leg returns directly; the
    per-leg latencies are recorded separately and their ratio is the
    i3 stretch KPI (the reference records exactly these two
    end-to-end legs per exchange)."""

    def stat_spec(self):
        s = super().stat_spec()
        s["scalars"] = s["scalars"] + ("i3_leg_s", "direct_leg_s")
        return s

    def _on_deliver(self, app, m, ctx, ob, ev, en):
        p = self.p
        now = m.t_deliver
        is_ping = en & (m.d == D_DATA)
        is_pong = en & (m.d == D_PONG)
        glob = ctx.glob
        xor_o = jnp.bitwise_xor(m.a, wire_id(glob, m.dst)).astype(
            jnp.uint32)
        plo = jnp.where(xor_o == 0, 32, jax.lax.clz(xor_o).astype(I32))
        mine = plo >= p.min_prefix_bits
        ev.count("i3_misdelivered", is_ping & ~mine & ctx.measuring)
        is_ping = is_ping & mine
        ev.count("i3_delivered", is_ping & ctx.measuring)
        # i3 leg: send-time → delivery through the indirection point
        ev.value("i3_leg_s", (now - m.stamp).astype(jnp.float32) / NS,
                 is_ping & ctx.measuring)
        # pong goes back DIRECTLY (the reference's direct-IP leg)
        ob.send(is_ping & (m.b != m.dst), now, jnp.maximum(m.b, 0),
                wire.I3_DELIVER, a=m.a, b=m.dst, d=jnp.int32(D_PONG),
                stamp=now, size_b=p.payload_bytes)
        ev.value("direct_leg_s", (now - m.stamp).astype(jnp.float32) / NS,
                 is_pong & ctx.measuring)
        return app
