"""i3 — Internet Indirection Infrastructure, vectorized.

Rebuild of the reference i3 (src/applications/i3/I3.{h,cc} + I3BaseApp:
rendezvous indirection — servers keep a trigger table (id → address
stack), clients insert/refresh soft-state triggers and send packets to
ids; the server matching a packet's id forwards it to the trigger's
address, I3.h:56-120 with `findClosestMatch` anycast).

Engine mapping (apps/base.py tier app over any KBR overlay):

  * every node is both i3 server (trigger storage) and client (I3BaseApp);
  * each node owns one trigger id (``glob.trigger_ids`` oracle) which it
    inserts at the responsible node on READY and refreshes every
    ``refresh`` seconds (soft-state TTL — expired triggers drop);
  * every ``send_interval`` a node picks a random live node and sends a
    packet to that node's trigger id: lookup id → I3_PACKET to the
    server → trigger match → I3_DELIVER forwarded to the owner, who
    validates it was truly the intended rendezvous (delivery + end-to-end
    latency through the indirection point — the reference's i3 KPI).

Longest-prefix anycast (I3::findClosestMatch, I3.h:56-120) over the
32-bit trigger ids with a min_prefix_bits threshold.  Trigger stacks
(id → continuation id, bounded by stack_hop_max): a matched trigger
with tr_next set repacketizes the payload to the continuation id.
When the stack entry carries the continuation's full overlay key and
the overlay processes recursive routes (``app.rcfg`` set), the
repacketized id travels THROUGH the overlay to its own responsible
server via KBR_ROUTE (the reference's cross-server identifier-stack
forwarding, I3.h:56-120 + common/route.py); without a key or route
support it falls back to a local table rematch.  The built-in random
workload registers plain triggers; stacked triggers ride the same
I3_INSERT wire format (continuation id in ``c``, full key in ``key``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.apps import base
from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

M_INSERT, M_SEND = 0, 1


@dataclasses.dataclass(frozen=True)
class I3Params:
    refresh: float = 30.0         # trigger refresh (soft state)
    trigger_ttl: float = 90.0
    send_interval: float = 20.0
    storage_slots: int = 16
    payload_bytes: int = 100
    # longest-prefix anycast (I3::findClosestMatch, I3.h:56-120): a
    # packet matches the stored trigger sharing the LONGEST id prefix,
    # provided at least min_prefix_bits match (the reference requires a
    # minimum 64-bit match of its 256-bit ids; scaled to the 32-bit
    # trigger ids here).  32 = exact-match only.
    min_prefix_bits: int = 12
    # trigger stacks (I3 trigger = id -> stack of ids/addresses): a
    # matched trigger whose next_id is set re-routes the packet to that
    # id instead of delivering — local chaining only (module docstring),
    # bounded by stack_hop_max
    stack_hop_max: int = 4


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class I3State:
    # server-side trigger table
    tr_id: jnp.ndarray     # [N, D] i32 trigger id (-1 empty)
    tr_owner: jnp.ndarray  # [N, D] i32
    tr_expire: jnp.ndarray  # [N, D] i64
    tr_next: jnp.ndarray   # [N, D] i32 — stack chaining: next trigger id
                           # the packet re-routes to (-1 = deliver)
    tr_next_key: jnp.ndarray  # [N, D, KL] u32 — the continuation id's
                           # FULL overlay key (the reference trigger
                           # stack carries complete 256-bit ids,
                           # I3.h:56-120 I3IdentifierStack); all-zero =
                           # none known → local-rematch fallback
    # client timers
    t_ins: jnp.ndarray     # [N] i64
    t_send: jnp.ndarray    # [N] i64
    seq: jnp.ndarray       # [N] i32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class I3Global:
    trigger_ids: jnp.ndarray   # [N, KL] u32 — node i owns trigger i


def wire_id(glob: "I3Global", slot):
    """32-bit wire trigger id = head lane of the node's 160-bit oracle
    trigger key (spread over the full id space so longest-prefix
    anycast is meaningful, as with the reference's random 256-bit
    ids).  Top bit cleared: the table uses -1 as the empty marker."""
    return (glob.trigger_ids[jnp.maximum(slot, 0), 0]
            & jnp.uint32(0x7FFFFFFF)).astype(I32)


class I3App:
    """Tier app (interface: apps/base.py docstring)."""

    def __init__(self, params: I3Params = I3Params(),
                 spec: keys_mod.KeySpec = keys_mod.DEFAULT_SPEC,
                 num_slots: int = 0):
        if num_slots <= 0:
            raise ValueError("I3App needs num_slots for the trigger oracle")
        self.p = params
        self.spec = spec
        self.n = num_slots
        # recursive-route config: set by the overlay (same late-binding
        # convention as KbrTestApp.rcfg) when it processes KBR_ROUTE —
        # enables CROSS-SERVER trigger-stack continuations (I3.h:56-120:
        # the matched trigger's continuation id is repacketized and
        # routed through the overlay).  None → local-rematch fallback.
        self.rcfg = None

    def stat_spec(self):
        return dict(
            scalars=("i3_latency_s",),
            hists=(),
            counters=("i3_inserts", "i3_stored", "i3_sent",
                      "i3_delivered", "i3_misdelivered",
                      "i3_lookup_failed"))

    def init(self, n: int) -> I3State:
        p = self.p
        return I3State(
            tr_id=jnp.full((n, p.storage_slots), -1, I32),
            tr_owner=jnp.full((n, p.storage_slots), NO_NODE, I32),
            tr_expire=jnp.zeros((n, p.storage_slots), I64),
            tr_next=jnp.full((n, p.storage_slots), -1, I32),
            tr_next_key=jnp.zeros(
                (n, p.storage_slots, self.spec.lanes), jnp.uint32),
            t_ins=jnp.full((n,), T_INF, I64),
            t_send=jnp.full((n,), T_INF, I64),
            seq=jnp.zeros((n,), I32))

    def glob_init(self, rng) -> I3Global:
        return I3Global(trigger_ids=keys_mod.random_keys(
            rng, (self.n,), self.spec))

    def post_step(self, ctx, state, glob, events):
        return state, glob

    def on_ready(self, app, en, now, rng):
        off = (jax.random.uniform(rng, ())
               * self.p.send_interval * NS).astype(I64)
        return dataclasses.replace(
            app,
            t_ins=jnp.where(en, now, app.t_ins),
            t_send=jnp.where(en, now + off, app.t_send))

    def on_stop(self, app, en):
        return dataclasses.replace(
            app,
            t_ins=jnp.where(en, T_INF, app.t_ins),
            t_send=jnp.where(en, T_INF, app.t_send))

    def on_leave(self, app, en, ctx, ob, ev, now, node_idx, handover):
        """Triggers are soft state (refresh-rebuilt); push the stored
        table to the successor like the DHT handover."""
        en = en & (handover != NO_NODE) & (handover != node_idx)
        valid = app.tr_id >= 0
        has = en & jnp.any(valid)
        col = jnp.argmax(valid).astype(I32)
        ob.send(has, now, handover, wire.I3_INSERT,
                a=app.tr_id[col], b=app.tr_owner[col],
                c=app.tr_next[col], key=app.tr_next_key[col],
                stamp=app.tr_expire[col], size_b=wire.BASE_CALL_B + 12)
        ccol = jnp.where(has, col, app.tr_id.shape[0])
        return dataclasses.replace(
            app, tr_id=app.tr_id.at[ccol].set(-1, mode="drop"))

    def next_event(self, app):
        return jnp.minimum(app.t_ins, app.t_send)

    def on_timer(self, app, en, ctx, now, rng, ev, node_idx):
        p = self.p
        glob: I3Global = ctx.glob
        ins_hit = en & (app.t_ins < ctx.t_end)
        snd_hit = en & (app.t_send < ctx.t_end)
        ins_due = ins_hit
        snd_due = snd_hit & ~ins_due
        tgt = ctx.sample_ready(rng)
        fire_snd = snd_due & (tgt != NO_NODE)
        ev.count("i3_inserts", ins_due)
        ev.count("i3_sent", fire_snd & ctx.measuring)
        name = jnp.where(ins_due, node_idx, tgt)
        key = glob.trigger_ids[jnp.maximum(name, 0)]
        app = dataclasses.replace(
            app,
            t_ins=jnp.where(ins_hit, now + jnp.int64(
                int(p.refresh * NS)), app.t_ins),
            t_send=jnp.where(snd_hit, now + jnp.int64(
                int(p.send_interval * NS)), app.t_send),
            seq=app.seq + (ins_due | fire_snd).astype(I32))
        mode = jnp.where(ins_due, M_INSERT, M_SEND)
        return app, base.LookupReq(want=ins_due | fire_snd, key=key,
                                   tag=name * 4 + mode)

    def on_lookup_done(self, app, done, ctx, ob, ev, now, node_idx):
        p = self.p
        en = done.en
        mode = done.tag % 4
        name = done.tag // 4
        suc = done.success & (done.results[0] != NO_NODE)
        ev.count("i3_lookup_failed", en & ~suc)
        server = done.results[0]
        # trigger insert/refresh at the responsible server
        tid = wire_id(ctx.glob, name)
        ob.send(en & suc & (mode == M_INSERT), now, server, wire.I3_INSERT,
                a=tid, b=node_idx, c=jnp.int32(-1),
                stamp=now + jnp.int64(int(p.trigger_ttl * NS)),
                size_b=wire.BASE_CALL_B + 12)
        # data packet to the id's rendezvous server
        ob.send(en & suc & (mode == M_SEND), now, server, wire.I3_PACKET,
                a=tid, b=node_idx, stamp=now,
                size_b=p.payload_bytes)
        return app

    def on_msg(self, app, m, ctx, ob, ev, is_sib):
        p = self.p
        now = m.t_deliver

        # trigger insert (I3::insertTrigger): the table holds a SET of
        # triggers per identifier (triggerTable[id] is a std::set keyed
        # by the full trigger incl. owner, I3.cc:100) — overwrite is
        # keyed on (id, owner) so two owners sharing an id coexist
        # (that set IS i3 multicast); else free slot, else evict
        # earliest expiry
        en = m.valid & (m.kind == wire.I3_INSERT)
        same = (app.tr_id == m.a) & (app.tr_owner == m.b) & (m.a >= 0)
        free = app.tr_id < 0
        col = jnp.where(jnp.any(same), jnp.argmax(same),
                        jnp.where(jnp.any(free), jnp.argmax(free),
                                  jnp.argmin(app.tr_expire))).astype(I32)
        col = jnp.where(en, col, app.tr_id.shape[0])
        app = dataclasses.replace(
            app,
            tr_id=app.tr_id.at[col].set(m.a, mode="drop"),
            tr_owner=app.tr_owner.at[col].set(m.b, mode="drop"),
            tr_expire=app.tr_expire.at[col].set(m.stamp, mode="drop"),
            # c carries the stack continuation id (-1 = plain trigger);
            # the wire key carries the continuation's FULL overlay key
            # for cross-server forwarding
            tr_next=app.tr_next.at[col].set(m.c, mode="drop"),
            tr_next_key=app.tr_next_key.at[col].set(m.key, mode="drop"))
        ev.count("i3_stored", en)

        # data packet → longest-prefix anycast match
        # (I3::forwardPacket via findClosestMatch, I3.h:56-120): among
        # live triggers, pick the one sharing the longest id prefix with
        # the packet id; at least min_prefix_bits must match.  The
        # packet then goes to EVERY trigger stored under the winning
        # identifier — the reference's per-identifier std::set loop
        # (I3.cc sendPacket "send to all friends") — which is what makes
        # a shared identifier a multicast group (i3Apps/I3Multicast.cc).
        en = m.valid & (m.kind == wire.I3_PACKET)
        # ``c`` multiplexes chain depth (low 16 bits) with the typed
        # payload kind (high bits, biased by +1 so 0 = "not encoded"):
        # the cross-server KBR_ROUTE leg below needs ``d`` for the decap
        # kind (common/route.py reads msgs.d at delivery), so the
        # payload kind from the sample apps (i3apps.py D_*) rides c's
        # high bits through the route and is restored here.  Direct
        # I3_PACKET sends never set the high bits → pk == 0 → m.d wins.
        depth = m.c & 0xFFFF
        pk = m.c >> 16
        d_eff = jnp.where(pk > 0, pk - 1, m.d)
        live = (app.tr_id >= 0) & (app.tr_expire > now)
        xor = jnp.bitwise_xor(app.tr_id, m.a).astype(jnp.uint32)
        # shared leading bits of two 32-bit ids = clz(xor) (32 on equal)
        pl = jnp.where(xor == 0, 32, jax.lax.clz(xor).astype(I32))
        pl = jnp.where(live & (m.a >= 0), pl, -1)
        best = jnp.argmax(pl).astype(I32)
        matched = pl[best] >= p.min_prefix_bits
        # the matched identifier's full trigger set ([D] mask)
        grp = en & matched & live & (app.tr_id == app.tr_id[best])
        # trigger stacks (I3.h:56-120): a matched trigger with a
        # continuation id repacketizes the payload addressed to that id
        # (per trigger — each set member carries its own stack).  Chain
        # depth rides ``c`` (``hops`` belongs to the route layer),
        # bounded by stack_hop_max; plain triggers deliver to the owner.
        chain_v = grp & (app.tr_next >= 0) & (depth < p.stack_hop_max)
        deliver_v = grp & ~chain_v
        # CROSS-SERVER continuation: when the stored stack entry carries
        # the continuation's full overlay key and the overlay processes
        # recursive routes, the repacketized id is routed THROUGH the
        # overlay to its own responsible server (the reference's
        # sendPacket on the popped identifier stack) via a KBR_ROUTE
        # self-send — the overlay decapsulates it back into I3_PACKET at
        # the responsible node, where the match/chain cycle repeats.
        # All sends are [D]-vectorized (one Outbox call per kind).
        if self.rcfg is not None:
            ew = self.rcfg.ext_words
            vis0 = jnp.full(m.nodes.shape, NO_NODE, I32).at[ew].set(
                m.dst)
            if ew:
                vis0 = vis0.at[:ew].set(0)
            have_key = jnp.any(app.tr_next_key != 0, axis=-1)      # [D]
            cross_v = chain_v & have_key
            # the typed payload kind survives the route leg in c's high
            # bits (route.py forwards + decapsulates ``c`` untouched);
            # ``d`` must stay I3_PACKET — it becomes the kind at decap
            ob.send(cross_v, now, m.dst, wire.KBR_ROUTE,
                    key=app.tr_next_key,
                    d=jnp.int32(wire.I3_PACKET), a=app.tr_next, b=m.b,
                    c=((d_eff + 1) << 16) | (depth + 1),
                    hops=0, nodes=vis0, stamp=m.stamp,
                    size_b=p.payload_bytes + self.rcfg.overhead_b)
            chain_local = chain_v & ~have_key
        else:
            chain_local = chain_v
        # local-rematch fallback (no full key / no recursive routing):
        # the packet re-enters this server's own table next tick
        ob.send(chain_local, now, m.dst, wire.I3_PACKET, a=app.tr_next,
                b=m.b, c=depth + 1, d=d_eff, stamp=m.stamp,
                size_b=p.payload_bytes)
        # ``d`` carries the sample apps' payload kind end-to-end
        # (I3SessionMessage-style typed payloads, i3Apps/*.cc)
        ob.send(deliver_v, now, jnp.maximum(app.tr_owner, 0),
                wire.I3_DELIVER, a=m.a, b=m.b, d=d_eff, stamp=m.stamp,
                size_b=p.payload_bytes)

        # delivery at the trigger owner
        en = m.valid & (m.kind == wire.I3_DELIVER)
        return self._on_deliver(app, m, ctx, ob, ev, en)

    def _on_deliver(self, app, m, ctx, ob, ev, en):
        """Owner-side delivery accounting for the built-in random
        workload; sample apps (apps/i3apps.py) override this with their
        own payload handling (the I3BaseApp::deliver upcall)."""
        p = self.p
        now = m.t_deliver
        glob: I3Global = ctx.glob
        # truly ours? an anycast delivery is legitimate when the packet
        # id shares >= min_prefix_bits with OUR trigger id (longest-
        # prefix semantics, I3.h findClosestMatch) — an exact-match test
        # would count every anycast completion as misdelivered
        xor_o = jnp.bitwise_xor(m.a, wire_id(glob, m.dst)).astype(
            jnp.uint32)
        plo = jnp.where(xor_o == 0, 32, jax.lax.clz(xor_o).astype(I32))
        mine = plo >= p.min_prefix_bits
        ev.count("i3_misdelivered", en & ~mine & ctx.measuring)
        en = en & mine
        ev.count("i3_delivered", en & ctx.measuring)
        ev.value("i3_latency_s",
                 (now - m.stamp).astype(jnp.float32) / NS,
                 en & ctx.measuring)
        return app

    @property
    def hist_map(self):
        return {}
