"""Per-replica multi-tenancy: one compiled program, S isolated tenants.

The campaign runner (oversim_tpu/campaign/) already stacks S replicas
of one scenario into a single vmapped program — pure data parallelism,
zero cross-replica collectives.  This module turns that stack into S
independently *served* tenants: tenant id == replica row, so every
tenant gets its own overlay, its own message pool, its own admission
bound and its own request trace, while the device still sees exactly
one dispatch and one batched pool write per serving window.

  * :class:`TenantTable` — tenant id ↔ replica row mapping plus
    per-tenant admission bounds, counters and (duck-typed) tracers;
  * :func:`inject_ext_batch_stacked` — the stacked analogue of
    ``gateway.inject_ext_batch``: per-row frame lists padded to one
    ``[S, n_max]`` batch, written by ONE ``jax.vmap(pool.alloc)``;
  * :func:`drain_ext_out_stacked` — the stacked analogue of
    ``gateway.drain_ext_out``: ONE ``device_get`` of the stacked pool
    columns, a host scan per row, ONE vmapped ``pool.free``;
  * :class:`TenantIngest` — the service-loop ingest source
    (``before_window``/``after_window`` protocol, service/ingest.py)
    routing submissions to their tenant row and responses back by sid.

Tracers are duck-typed parameters (obs.RequestTracer-shaped: ``mint`` /
``settle`` / ``nack`` with a ``window=`` kwarg) so this module never
imports the observability plane — the daemon wires per-tenant tracers
whose metrics carry ``oversim_tenant_*`` families with a
``tenant="<id>"`` label.

Isolation contract: each tenant's ``max_pending`` bound sheds THAT
tenant's overload with explicit NACKs while every other tenant's
requests keep flowing — the per-tenant identity
``minted == settled + nacked + outstanding`` holds at every boundary
(pinned by tests/test_daemon.py and the slo_soak gate).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from oversim_tpu import gateway as gateway_mod
from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
I64 = jnp.int64
NO_NODE = jnp.int32(-1)
_HDR = gateway_mod._HDR


@dataclasses.dataclass
class TenantSpec:
    """One tenant row: admission bound, counters, optional tracer."""

    tid: int
    max_pending: int | None = None
    tracer: object = None
    minted: int = 0
    settled: int = 0
    nacked: int = 0
    shed: int = 0                 # nacked at submit by admission ctl
    injected: int = 0

    @property
    def outstanding(self) -> int:
        return self.minted - self.settled - self.nacked

    def snapshot(self) -> dict:
        return {"tenant": self.tid, "minted": self.minted,
                "settled": self.settled, "nacked": self.nacked,
                "shed": self.shed, "injected": self.injected,
                "outstanding": self.outstanding}


class TenantTable:
    """Tenant id ↔ campaign replica row mapping.

    ``tenants`` is the row count S of the stacked state; tenant ids are
    the row indices ``0..S-1`` (a campaign-stacked run becomes S
    independent tenants from one compiled program).  ``max_pending``
    is the per-tenant admission bound (int for all, or a list per
    tenant); ``tracers`` an optional per-tenant tracer list."""

    def __init__(self, tenants: int, max_pending=None, tracers=None):
        if tenants < 1:
            raise ValueError("need at least one tenant")
        if tracers is not None and len(tracers) != tenants:
            raise ValueError("tracers must have one entry per tenant")
        bounds = (max_pending if isinstance(max_pending, (list, tuple))
                  else [max_pending] * tenants)
        if len(bounds) != tenants:
            raise ValueError("max_pending list must match tenant count")
        self.specs = [TenantSpec(tid=t, max_pending=bounds[t],
                                 tracer=tracers[t] if tracers else None)
                      for t in range(tenants)]

    def __len__(self) -> int:
        return len(self.specs)

    def valid(self, tid) -> bool:
        return isinstance(tid, int) and 0 <= tid < len(self.specs)

    def spec(self, tid) -> TenantSpec:
        return self.specs[tid]

    def snapshot(self) -> list:
        return [s.snapshot() for s in self.specs]


def inject_ext_batch_stacked(state, rows, gw_slot: int, t_deliver=None):
    """Write per-row frame lists into the stacked pool as ONE batched
    vmapped alloc.

    ``rows`` is a length-S list of ``gateway.ExtFrame`` lists (row r =
    tenant r's frames this window).  Rows are padded to the longest
    row; padding slots carry ``want=False`` and cost nothing.  Returns
    ``(state', overflow)`` with ``overflow`` the lazy ``[S]`` device
    vector of frames that did not fit per row (``None`` when every row
    is empty)."""
    S = len(rows)
    n = max((len(r) for r in rows), default=0)
    if n == 0:
        return state, None
    kl = state.pool.kl
    rmax = state.pool.rmax
    want = np.zeros((S, n), bool)
    a = np.zeros((S, n), np.int32)
    b = np.zeros((S, n), np.int32)
    c = np.zeros((S, n), np.int32)
    kind = np.full((S, n), gateway_mod.EXT_IN, np.int32)
    src = np.full((S, n), gw_slot, np.int32)
    dst = np.full((S, n), gw_slot, np.int32)
    key = np.zeros((S, n, kl), np.uint32)
    for r, frames in enumerate(rows):
        for i, f in enumerate(frames):
            want[r, i] = True
            a[r, i] = f.a
            b[r, i] = f.b
            c[r, i] = f.c
            kind[r, i] = f.kind
            if f.dst is not None:
                dst[r, i] = f.dst
            if f.src is not None:
                src[r, i] = f.src
            if f.key is not None:
                key[r, i] = np.asarray(f.key, np.uint32)
    t_next = state.t_now[:, None] + 1                       # [S, 1]
    when = (jnp.broadcast_to(t_next, (S, n)) if t_deliver is None
            else jnp.maximum(jnp.asarray(t_deliver, I64),
                             jnp.broadcast_to(t_next, (S, n))))
    out = dict(
        t_deliver=when.astype(I64),
        src=jnp.asarray(src), dst=jnp.asarray(dst),
        kind=jnp.asarray(kind), key=jnp.asarray(key),
        nonce=jnp.zeros((S, n), I32), hops=jnp.zeros((S, n), I32),
        a=jnp.asarray(a), b=jnp.asarray(b), c=jnp.asarray(c),
        d=jnp.zeros((S, n), I32),
        nodes=jnp.full((S, n, rmax), NO_NODE, I32),
        size_b=jnp.full((S, n), _HDR.size, I32),
        stamp=jnp.broadcast_to(state.t_now[:, None], (S, n)).astype(I64),
    )
    new_pool, overflow = jax.vmap(
        lambda p, o, w: pool_mod.alloc(p, o, w))(
            state.pool, out, jnp.asarray(want))
    return dataclasses.replace(state, pool=new_pool), overflow


def drain_ext_out_stacked(state, gw_slot: int, handler):
    """Scan every replica row for EXT_OUT messages addressed to
    ``gw_slot`` and offer each to ``handler(row, sid, b, c) ->
    consumed``; free exactly the consumed slots with ONE vmapped free.

    The stacked analogue of ``gateway.drain_ext_out``: one
    ``device_get`` of the pool columns is the window's host read (the
    ingest tier's documented sync), then a pure host scan."""
    cols = jax.vmap(lambda p: (p.valid, p.kind, p.dst, p.a, p.b, p.c))(
        state.pool)
    valid, kind, dst, a, b, c = jax.device_get(cols)      # [S, P] each
    sel = valid & (kind == gateway_mod.EXT_OUT) & (dst == gw_slot)
    if not sel.any():
        return state
    consumed = np.zeros(valid.shape, bool)
    for r, i in zip(*np.nonzero(sel)):
        if handler(int(r), int(a[r, i]), int(b[r, i]), int(c[r, i])):
            consumed[r, i] = True
    if not consumed.any():
        return state
    new_pool = jax.vmap(pool_mod.free)(state.pool, jnp.asarray(consumed))
    return dataclasses.replace(state, pool=new_pool)


class TenantIngest:
    """Multi-tenant ingest source over the stacked campaign state.

    The service-loop protocol (service/ingest.py): ``submit(tenant, b,
    c)`` mints a sid and queues the frame for its tenant's replica row;
    ``before_window`` writes every queued row as ONE vmapped batched
    alloc; ``after_window`` drains EXT_OUT responses with ONE stacked
    host read, settles their traces, and calls ``on_response(sid,
    tenant, b, c)`` (the daemon's sid-routing hook).

    Shed semantics: a submit past the tenant's ``max_pending`` is
    NACKed immediately (``nacked[sid]``, tenant + global tracer nack,
    per-tenant ``shed`` counter) and never queued — one hot tenant
    sheds without starving the rest.  ``nack_outstanding()`` closes
    every still-open sid at drain so
    ``minted == settled + nacked + outstanding`` ends balanced."""

    def __init__(self, table: TenantTable, gw_slot: int = 0,
                 tracer=None, on_response=None):
        self.table = table
        self.gw = gw_slot
        self.tracer = tracer          # duck-typed GLOBAL tracer
        self.on_response = on_response
        self.windows = 0
        self.responses: dict = {}     # sid -> (b, c)
        self.nacked: dict = {}        # sid -> (b, c)
        self.rx_shed = 0
        self.num_batches = 0
        self.num_injected = 0
        self._pending: list = [[] for _ in range(len(table))]
        self._open: dict = {}         # sid -> (tenant, b, c)
        self._overflow: list = []     # lazy [S] device vectors
        self._next_sid = 1

    # ------------------------------------------------ submission -------
    def submit(self, tenant: int, b: int = 0, c: int = 0) -> int:
        if not self.table.valid(tenant):
            raise ValueError(f"unknown tenant {tenant!r}")
        spec = self.table.spec(tenant)
        sid = self._next_sid
        self._next_sid += 1
        spec.minted += 1
        if self.tracer is not None:
            self.tracer.mint(sid, window=self.windows)
        if spec.tracer is not None:
            spec.tracer.mint(sid, window=self.windows)
        if (spec.max_pending is not None
                and len(self._pending[tenant]) >= spec.max_pending):
            self._nack(sid, tenant, b, c, shed=True)
            return sid
        self._open[sid] = (tenant, b, c)
        self._pending[tenant].append(gateway_mod.ExtFrame(
            a=sid, b=b, c=c))
        return sid

    def _nack(self, sid, tenant, b, c, *, shed: bool = False):
        spec = self.table.spec(tenant)
        spec.nacked += 1
        if shed:
            spec.shed += 1
            self.rx_shed += 1
        self.nacked[sid] = (b, c)
        if self.tracer is not None and hasattr(self.tracer, "nack"):
            self.tracer.nack(sid, window=self.windows)
        if spec.tracer is not None and hasattr(spec.tracer, "nack"):
            spec.tracer.nack(sid, window=self.windows)

    def outstanding(self) -> int:
        return len(self._open)

    def pending(self, tenant: int | None = None) -> int:
        if tenant is None:
            return sum(len(q) for q in self._pending)
        return len(self._pending[tenant])

    def nack_outstanding(self) -> list:
        """Close EVERY still-open sid as NACKed (drain/shutdown: a
        request whose response never drained — pool overflow, client
        gone — must not leak).  Returns ``[(sid, tenant, b, c), ...]``
        so the daemon can transmit the NACK frames."""
        closed = []
        for sid, (tenant, b, c) in list(self._open.items()):
            del self._open[sid]
            self._nack(sid, tenant, b, c)
            closed.append((sid, tenant, b, c))
        self._pending = [[] for _ in range(len(self.table))]
        return closed

    def overflow(self) -> int:
        """Frames lost to pool overflow so far (forces a host sync)."""
        total = sum(int(np.asarray(h).sum()) for h in self._overflow)
        self._overflow = []
        return total

    def accounting(self) -> dict:
        """The serving identity, globally and per tenant."""
        per = self.table.snapshot()
        return {"minted": sum(p["minted"] for p in per),
                "settled": sum(p["settled"] for p in per),
                "nacked": sum(p["nacked"] for p in per),
                "shed": self.rx_shed,
                "outstanding": self.outstanding(),
                "windows": self.windows,
                "per_tenant": per}

    # ------------------------------------------------ loop protocol ----
    def before_window(self, state, target_ns: int):
        if not any(self._pending):
            return state
        rows, self._pending = self._pending, [
            [] for _ in range(len(self.table))]
        for t, frames in enumerate(rows):
            self.table.spec(t).injected += len(frames)
            self.num_injected += len(frames)
        state, overflow = inject_ext_batch_stacked(state, rows, self.gw)
        if overflow is not None:
            self._overflow.append(overflow)
        self.num_batches += 1
        return state

    def after_window(self, state):
        def handler(row, sid, b, c):
            rec = self._open.pop(sid, None)
            if rec is None:
                # not ours (already NACKed / duplicate): free it so the
                # hold slot doesn't pin the pool full forever
                if self.tracer is not None:
                    self.tracer.settle(sid, window=self.windows)
                return True
            tenant = rec[0]
            if tenant != row:
                # a response surfacing in a foreign replica row would
                # mean cross-tenant leakage — refuse to route it
                self._open[sid] = rec
                return False
            spec = self.table.spec(tenant)
            spec.settled += 1
            self.responses[sid] = (b, c)
            if self.tracer is not None:
                self.tracer.settle(sid, window=self.windows)
            if spec.tracer is not None:
                spec.tracer.settle(sid, window=self.windows)
            if self.on_response is not None:
                self.on_response(sid, tenant, b, c)
            return True

        state = drain_ext_out_stacked(state, self.gw, handler)
        self.windows += 1
        return state
