"""Request sources for the serving loop.

The ingest protocol the loop drives (service/loop.py):

    before_window(state, target_ns) -> state'
        called at the window boundary BEFORE dispatch; inject every
        accumulated request as ONE batched ``EXT_IN`` pool write
        (gateway.inject_ext_batch), delivered at the start of the
        window about to run.
    after_window(state) -> state'
        called after the window's drain; collect ``EXT_OUT`` responses
        (gateway.drain_ext_out — a host read of the pool, which is why
        ingest mode runs single-buffered).

The served Simulation MUST be built with
``EngineParams(ext_hold_slot=<gw_slot>)``: a window runs many ticks
between drains, and without the hold the engine re-delivers each
``EXT_OUT`` response into the gateway node's inbox on the tick after it
is sent — consuming it long before the boundary drain runs.  With the
hold, responses park in the pool until ``after_window`` frees them.
(The per-tick ``pump()``/``run_realtime`` path drains between ticks and
works either way.)

``InProcessIngest`` is the test/program-embedding source (a plain
submit() queue); ``GatewayIngest`` adapts a RealtimeGateway so real
UDP/TCP clients are served at window granularity.  Both attach to a
SOLO Simulation state; the stacked campaign state has no per-replica
session plumbing and is served without ingest.
"""

from __future__ import annotations

from oversim_tpu import gateway as gateway_mod


class InProcessIngest:
    """In-process request queue (the test stand-in for real sockets).

    ``submit`` assigns a session id and buffers the frame;
    ``responses[sid]`` holds the drained ``(b, c)`` answer after the
    window that served it.

    ``tracer`` is duck-typed (obs.RequestTracer-shaped: ``mint(sid,
    window=)`` / ``settle(sid, window=)`` / ``nack(sid, window=)``) so
    this module never imports the observability plane; ``windows``
    counts completed after_window drains and is the window index the
    tracer latencies are phrased in.

    ``max_pending`` is the admission-control bound: once that many
    submitted frames await injection, further ``submit`` calls are SHED
    — the sid lands in ``nacked`` (and the tracer's nack counter), the
    frame never enters the pool, and the caller can tell refusal apart
    from a reply that merely has not arrived yet.  None = unbounded.
    """

    def __init__(self, gw_slot: int = 0, collect_responses: bool = True,
                 tracer=None, max_pending: int | None = None):
        self.gw = gw_slot
        self.collect = collect_responses
        self.tracer = tracer
        self.max_pending = max_pending
        self.windows = 0              # after_window drains completed
        self.responses: dict = {}     # sid -> (b, c)
        self.nacked: dict = {}        # sid -> (b, c) refused on submit
        self.rx_shed = 0              # frames refused by admission ctl
        self.num_batches = 0          # batched pool writes performed
        self.num_injected = 0         # frames injected across batches
        self._pending: list = []
        self._overflow: list = []     # lazy device scalars (no hot sync)
        self._next_sid = 1

    def submit(self, b: int = 0, c: int = 0, *,
               kind: int = gateway_mod.EXT_IN,
               dst: int | None = None, key=None) -> int:
        sid = self._next_sid
        self._next_sid += 1
        if self.tracer is not None:
            self.tracer.mint(sid, window=self.windows)
        if (self.max_pending is not None
                and len(self._pending) >= self.max_pending):
            # explicit NACK, never a silent drop: every minted request
            # either settles with a response or lands here
            self.rx_shed += 1
            self.nacked[sid] = (b, c)
            if self.tracer is not None and hasattr(self.tracer, "nack"):
                self.tracer.nack(sid, window=self.windows)
            return sid
        self._pending.append(gateway_mod.ExtFrame(
            a=sid, b=b, c=c, kind=kind, dst=dst, key=key))
        return sid

    def overflow(self) -> int:
        """Frames lost to pool overflow so far (forces a host sync)."""
        import numpy as np
        total = sum(int(np.asarray(h)) for h in self._overflow)
        self._overflow = []
        return total

    def before_window(self, state, target_ns: int):
        if not self._pending:
            return state
        frames, self._pending = self._pending, []
        state, overflow = gateway_mod.inject_ext_batch(
            state, frames, self.gw)
        self._overflow.append(overflow)
        self.num_batches += 1
        self.num_injected += len(frames)
        return state

    def after_window(self, state):
        if not self.collect:
            self.windows += 1
            return state

        def handler(sid, b, c):
            self.responses[sid] = (b, c)
            if self.tracer is not None:
                self.tracer.settle(sid, window=self.windows)
            return True

        state = gateway_mod.drain_ext_out(state, self.gw, handler)
        self.windows += 1
        return state


class GatewayIngest:
    """Serve a RealtimeGateway's sockets at window granularity.

    The gateway object keeps owning the sockets, session table and
    crypto; this adapter only moves its poll → batch-inject → drain
    cycle onto the service loop's boundaries (state flows through the
    loop, ``gateway.state`` is kept in step for the drain helpers)."""

    def __init__(self, gateway):
        self.gateway = gateway
        self.windows = 0              # after_window drains completed

    def before_window(self, state, target_ns: int):
        gw = self.gateway
        gw.state = state
        # pin the serving-window index on the gateway so the sids it
        # mints/settles this boundary trace latency in window units
        gw._window = self.windows
        gw._poll_udp()
        gw._poll_tcp()
        gw.flush_rx()
        return gw.state

    def after_window(self, state):
        gw = self.gateway
        gw.state = state
        gw._window = self.windows
        gw._drain_ext_out()
        for fn in gw.ext_drains:
            fn()
        self.windows += 1
        return gw.state
