"""Socket-scale client mux: one listener set, thousands of clients.

The RealtimeGateway (oversim_tpu/gateway.py) bridges ONE simulation
node to real sockets with a hand-rolled poll over a dict of
connections — fine for a handful of peers, quadratic pain at serving
scale.  The daemon front-end (service/daemon.py) instead multiplexes
every client through this selectors-based event loop: one UDP socket
plus one TCP listener, no thread per connection, per-connection read
AND write buffers so a slow or hostile client can never desync, stall
or interleave anyone else's frames.

Wire contract (the gateway's native external frame, gateway._HDR):

    client -> daemon   u32 EXT_IN  | u32 tenant | u32 b | u32 c
    daemon -> client   u32 EXT_OUT | u32 sid    | u32 b | u32 c
                       u32 EXT_NACK| u32 sid    | u32 b | u32 c

UDP frames are bare datagrams; TCP frames carry the gateway's 4-byte
big-endian length prefix (SimpleTCP stream framing, same desync bound
``gateway._MAX_TCP_FRAME``).  The ``a`` word is the CLIENT's tenant id
on the way in and the daemon-minted session id on the way out — the
daemon owns sid minting, the mux only moves frames.

Partial-write discipline: every outbound TCP byte goes through the
per-connection ``tx`` buffer.  ``send()`` appends prefix+payload
atomically and opportunistically drains with non-blocking ``send``;
whatever the kernel refuses stays buffered and is drained on the
selector's EVENT_WRITE — a full socket buffer can delay a frame but
never truncate or interleave it.  (``sendall`` on a non-blocking
socket is exactly the bug this module exists to avoid: it can raise
after a PARTIAL write and corrupt the stream framing.)

Pure stdlib, host-side only — no jax, no obs imports.
"""

from __future__ import annotations

import selectors
import socket

from oversim_tpu import gateway as gateway_mod

_HDR = gateway_mod._HDR
_MAX_TCP_FRAME = gateway_mod._MAX_TCP_FRAME

# a client that stops reading accumulates tx bytes; past this bound the
# connection is dropped (counted) rather than growing without limit
_MAX_TX_BUFFER = 4 << 20


class MuxConn:
    """One TCP client connection: socket + rx/tx byte buffers."""

    __slots__ = ("sock", "addr", "rx", "tx", "closed", "rx_frames",
                 "tx_frames")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.rx = bytearray()
        self.tx = bytearray()
        self.closed = False
        self.rx_frames = 0
        self.tx_frames = 0

    def __repr__(self):
        return (f"MuxConn({self.addr}, closed={self.closed}, "
                f"rx={self.rx_frames}, tx={self.tx_frames})")


class MuxFrame:
    """One parsed inbound frame: ``client`` is the reply handle (a
    :class:`MuxConn` for TCP, ``("udp", addr)`` for datagrams)."""

    __slots__ = ("client", "kind", "a", "b", "c")

    def __init__(self, client, kind, a, b, c):
        self.client = client
        self.kind = kind
        self.a = a
        self.b = b
        self.c = c


class SocketMux:
    """Selectors event loop over one UDP socket + one TCP listener.

    ``pump()`` at every serving-window boundary: accepts, reads,
    parses, and drains pending writes; parsed frames accumulate until
    ``take_frames()``.  ``send(client, payload)`` routes a raw frame
    back (the daemon builds payloads with its GenericPacketParser) —
    UDP as one datagram, TCP length-prefixed through the per-connection
    write buffer."""

    def __init__(self, host: str = "127.0.0.1", udp_port: int = 0,
                 tcp_port: int = 0, backlog: int = 1024,
                 max_tx_buffer: int = _MAX_TX_BUFFER):
        self.sel = selectors.DefaultSelector()
        self.max_tx_buffer = max_tx_buffer
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind((host, udp_port))
        self.udp.setblocking(False)
        self.udp_port = self.udp.getsockname()[1]
        self.sel.register(self.udp, selectors.EVENT_READ, "udp")
        self.tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.tcp.bind((host, tcp_port))
        self.tcp.listen(backlog)
        self.tcp.setblocking(False)
        self.tcp_port = self.tcp.getsockname()[1]
        self.sel.register(self.tcp, selectors.EVENT_READ, "accept")
        self.conns: set = set()         # live MuxConn objects
        self._frames: list = []
        self.accepted = 0
        self.disconnected = 0
        self.rx_frames = 0
        self.rx_dropped = 0             # malformed/undersized frames
        self.rx_socket_errors = 0
        self.tx_frames = 0
        self.tx_partial_writes = 0      # kernel took only part of tx
        self.tx_overflow_drops = 0      # conns dropped at max_tx_buffer

    # ------------------------------------------------ event loop -------
    def pump(self, timeout: float = 0.0, max_rounds: int = 8):
        """Process every ready socket; returns parsed-frame count so
        far.  Bounded rounds: a client flooding faster than we parse
        must not starve the serving loop."""
        for _ in range(max_rounds):
            events = self.sel.select(timeout)
            timeout = 0.0
            if not events:
                break
            for key, mask in events:
                if key.data == "accept":
                    self._accept()
                elif key.data == "udp":
                    self._read_udp()
                else:
                    conn = key.data
                    if mask & selectors.EVENT_READ:
                        self._read_tcp(conn)
                    if mask & selectors.EVENT_WRITE and not conn.closed:
                        self._flush(conn)
        return len(self._frames)

    def take_frames(self) -> list:
        frames, self._frames = self._frames, []
        return frames

    # ------------------------------------------------ inbound ----------
    def _accept(self):
        while True:
            try:
                sock, addr = self.tcp.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = MuxConn(sock, addr)
            self.conns.add(conn)
            self.accepted += 1
            self.sel.register(sock, selectors.EVENT_READ, conn)

    def _read_udp(self):
        while True:
            try:
                data, addr = self.udp.recvfrom(65536)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                # ICMP port-unreachable from an earlier sendto to a
                # dead peer — not our problem, keep draining
                self.rx_socket_errors += 1
                return
            self._parse(("udp", addr), data)

    def _read_tcp(self, conn: MuxConn):
        try:
            chunk = conn.sock.recv(65536)
        except BlockingIOError:
            chunk = None
        except OSError:
            self.rx_socket_errors += 1
            self._drop(conn)
            return
        if chunk == b"":
            self._drop(conn)
            return
        if chunk:
            conn.rx.extend(chunk)
        buf = conn.rx
        while len(buf) >= 4:
            ln = int.from_bytes(buf[:4], "big")
            if ln > _MAX_TCP_FRAME:
                # garbage where the prefix should be: the stream is
                # desynced forever (gateway._poll_tcp's bound)
                self.rx_dropped += 1
                self._drop(conn)
                return
            if len(buf) < 4 + ln:
                return
            frame = bytes(buf[4:4 + ln])
            del buf[:4 + ln]
            self._parse(conn, frame)

    def _parse(self, client, data: bytes):
        """One wire frame -> MuxFrame; malformed frames are counted and
        dropped WITHOUT touching the connection — one hostile client's
        short frame must never perturb another client's stream."""
        if len(data) < _HDR.size:
            self.rx_dropped += 1
            return
        kind, a, b, c = _HDR.unpack_from(data)
        if kind != gateway_mod.EXT_IN:
            self.rx_dropped += 1
            return
        if isinstance(client, MuxConn):
            client.rx_frames += 1
        self.rx_frames += 1
        self._frames.append(MuxFrame(client, kind, a, b, c))

    # ------------------------------------------------ outbound ---------
    def send(self, client, payload: bytes) -> bool:
        """Queue one frame to ``client``; False if the client is gone.
        TCP frames are length-prefixed and buffered (never sendall);
        UDP frames go out as single datagrams immediately."""
        if isinstance(client, tuple):       # ("udp", addr)
            try:
                self.udp.sendto(payload, client[1])
            except OSError:
                self.rx_socket_errors += 1
                return False
            self.tx_frames += 1
            return True
        conn = client
        if conn.closed:
            return False
        conn.tx += len(payload).to_bytes(4, "big") + payload
        conn.tx_frames += 1
        self.tx_frames += 1
        self._flush(conn)
        return not conn.closed

    def _flush(self, conn: MuxConn):
        """Drain as much of conn.tx as the kernel accepts; keep the
        rest registered for EVENT_WRITE."""
        while conn.tx:
            try:
                n = conn.sock.send(conn.tx)
            except BlockingIOError:
                break
            except OSError:
                self._drop(conn)
                return
            if n < len(conn.tx):
                self.tx_partial_writes += 1
            del conn.tx[:n]
        if len(conn.tx) > self.max_tx_buffer:
            # the client stopped reading: bound the buffer by dropping
            # the connection, never by silently truncating a frame
            self.tx_overflow_drops += 1
            self._drop(conn)
            return
        want = selectors.EVENT_READ
        if conn.tx:
            want |= selectors.EVENT_WRITE
        try:
            self.sel.modify(conn.sock, want, conn)
        except (KeyError, ValueError, OSError):
            pass

    def flush_all(self):
        """Opportunistically drain every pending write buffer (called
        after a window's responses are queued)."""
        for conn in list(self.conns):
            if conn.tx and not conn.closed:
                self._flush(conn)

    # ------------------------------------------------ lifecycle --------
    def _drop(self, conn: MuxConn):
        if conn.closed:
            return
        conn.closed = True
        self.disconnected += 1
        self.conns.discard(conn)
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def close(self):
        for conn in list(self.conns):
            self._drop(conn)
        for sock in (self.udp, self.tcp):
            try:
                self.sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.sel.close()

    def stats(self) -> dict:
        return {"accepted": self.accepted,
                "disconnected": self.disconnected,
                "connections": len(self.conns),
                "rx_frames": self.rx_frames,
                "rx_dropped": self.rx_dropped,
                "rx_socket_errors": self.rx_socket_errors,
                "tx_frames": self.tx_frames,
                "tx_partial_writes": self.tx_partial_writes,
                "tx_overflow_drops": self.tx_overflow_drops}
