"""Service plane: resident, checkpointed, double-buffered serving loop.

Composes the pieces the batch tiers already have — ``run_until_device``
windows (bench.py), exact checkpoint/restore (checkpoint.py), real-socket
ingestion (gateway.py), telemetry exporters — into a long-running service
(ROADMAP item 5).  See service/loop.py for the pipeline and
service/ingest.py for the request sources.
"""

from oversim_tpu.service.loop import (  # noqa: F401
    ServiceLoop,
    ServiceParams,
    campaign_summarize_leaves,
    counter_leaf_refs,
    summarize_counter_leaves,
)
from oversim_tpu.service.ingest import (  # noqa: F401
    GatewayIngest,
    InProcessIngest,
)
