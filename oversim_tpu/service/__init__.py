"""Service plane: resident, checkpointed, double-buffered serving loop.

Composes the pieces the batch tiers already have — ``run_until_device``
windows (bench.py), exact checkpoint/restore (checkpoint.py), real-socket
ingestion (gateway.py), telemetry exporters — into a long-running service
(ROADMAP item 5).  See service/loop.py for the pipeline,
service/ingest.py for the request sources, and the daemon tier
(service/mux.py + service/tenant.py + service/daemon.py) for
overlay-as-a-service: socket-scale client muxing with per-replica
multi-tenant sessions over ONE compiled campaign program.
"""

from oversim_tpu.service.loop import (  # noqa: F401
    ServiceLoop,
    ServiceParams,
    campaign_summarize_leaves,
    counter_leaf_refs,
    summarize_counter_leaves,
)
from oversim_tpu.service.ingest import (  # noqa: F401
    GatewayIngest,
    InProcessIngest,
)
from oversim_tpu.service.mux import (  # noqa: F401
    MuxConn,
    MuxFrame,
    SocketMux,
)
from oversim_tpu.service.tenant import (  # noqa: F401
    TenantIngest,
    TenantSpec,
    TenantTable,
    drain_ext_out_stacked,
    inject_ext_batch_stacked,
)
from oversim_tpu.service.daemon import (  # noqa: F401
    LocalCall,
    OverlayDaemon,
)
