"""Overlay-as-a-service daemon: mux → tenants → one compiled window.

The front door of the service plane.  An :class:`OverlayDaemon` is an
ingest-protocol source (service/ingest.py) gluing three layers:

  * :class:`~oversim_tpu.service.mux.SocketMux` — thousands of UDP/TCP
    clients on one listener set, selectors event loop, per-connection
    buffers (no thread per connection);
  * :class:`~oversim_tpu.service.tenant.TenantIngest` — tenant id ↔
    campaign replica row, per-tenant admission + tracing, ONE vmapped
    batched pool write per window;
  * the resident :class:`~oversim_tpu.service.loop.ServiceLoop` — the
    daemon plugs in as ``ingest=``, so the device keeps the exact
    one-dispatch-one-fetch window cadence regardless of client count.

Per window boundary: ``before_window`` pumps the mux, validates each
frame's tenant word, mints sids (sessions map sid → originating
connection), sheds over-bound tenants with explicit ``EXT_NACK``
frames, and injects everything admitted as one stacked batch.
``after_window`` drains the stacked EXT_OUT responses and routes each
back to its originating connection by sid — a client that disconnected
mid-flight settles normally (counted ``orphaned``; its response is
freed, never leaked), so ``minted == settled + nacked + outstanding``
holds at drain no matter what clients do.

A thread-safe local submit queue (:meth:`submit_local`) lets non-socket
front-ends — the XML-RPC bridge in oversim_tpu/xmlrpcif.py — mint
frames through the same admission/injection path and block on the same
sid routing.

Host-side only; never imports jax or obs (tracers arrive duck-typed
inside the TenantTable).
"""

from __future__ import annotations

import collections
import threading

from oversim_tpu import gateway as gateway_mod


class LocalCall:
    """One in-process request riding the daemon's window cadence: the
    submitting thread blocks on ``done`` until the serving loop drains
    the response (``status`` in {"ok", "nack", "pending"})."""

    __slots__ = ("tenant", "b", "c", "sid", "status", "resp_b",
                 "resp_c", "done")

    def __init__(self, tenant, b, c):
        self.tenant = tenant
        self.b = b
        self.c = c
        self.sid = None
        self.status = "pending"
        self.resp_b = None
        self.resp_c = None
        self.done = threading.Event()

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)


class OverlayDaemon:
    """Socket-scale serving front-end with per-replica tenancy.

    ``ingest`` is a TenantIngest (its ``on_response`` hook is claimed
    by the daemon); ``mux`` a SocketMux (None for local-only serving,
    e.g. an XML-RPC-only daemon or the unit tests' direct driving)."""

    def __init__(self, ingest, mux=None, parser=None):
        self.ingest = ingest
        self.mux = mux
        self.parser = parser or gateway_mod.GenericPacketParser()
        ingest.on_response = self._on_response
        self.sessions: dict = {}      # sid -> MuxConn | ("udp", addr) | LocalCall
        self.orphaned = 0             # responses to vanished clients
        self.bad_tenant = 0           # frames naming an unknown tenant
        self._local_q: collections.deque = collections.deque()
        self._draining = False

    # ------------------------------------------------ local front-end --
    def submit_local(self, tenant: int, b: int = 0,
                     c: int = 0) -> LocalCall:
        """Thread-safe submit from a non-socket front-end; the call is
        admitted at the NEXT window boundary (deque.append is atomic —
        the XML-RPC handler threads never touch ingest state)."""
        call = LocalCall(tenant, b, c)
        self._local_q.append(call)
        return call

    # ------------------------------------------------ loop protocol ----
    def before_window(self, state, target_ns: int):
        if self.mux is not None:
            self.mux.pump()
            for frame in self.mux.take_frames():
                tenant = frame.a
                if not self.ingest.table.valid(tenant):
                    # sid 0 is never minted: the NACK is addressable to
                    # the client without opening a session
                    self.bad_tenant += 1
                    self.mux.send(frame.client,
                                  self.parser.nack(0, frame.b, frame.c))
                    continue
                sid = self.ingest.submit(tenant, frame.b, frame.c)
                if sid in self.ingest.nacked:
                    self.mux.send(frame.client,
                                  self.parser.nack(sid, frame.b, frame.c))
                else:
                    self.sessions[sid] = frame.client
        while self._local_q:
            call = self._local_q.popleft()
            if not self.ingest.table.valid(call.tenant):
                self.bad_tenant += 1
                call.status = "nack"
                call.done.set()
                continue
            sid = self.ingest.submit(call.tenant, call.b, call.c)
            call.sid = sid
            if sid in self.ingest.nacked:
                call.status = "nack"
                call.done.set()
            else:
                self.sessions[sid] = call
        return self.ingest.before_window(state, target_ns)

    def after_window(self, state):
        state = self.ingest.after_window(state)
        if self.mux is not None:
            self.mux.flush_all()
        return state

    # ------------------------------------------------ sid routing ------
    def _on_response(self, sid, tenant, b, c):
        client = self.sessions.pop(sid, None)
        if client is None:
            self.orphaned += 1
            return
        if isinstance(client, LocalCall):
            client.status = "ok"
            client.resp_b = b
            client.resp_c = c
            client.done.set()
            return
        payload = self.parser.encapsulate(sid, b, c)
        if self.mux is None or not self.mux.send(client, payload):
            # the client disconnected mid-flight: its sid still
            # settled above — counted, freed, never leaked
            self.orphaned += 1

    # ------------------------------------------------ drain ------------
    def drain(self, loop, max_windows: int = 16) -> dict:
        """Run extra (empty-submission) windows until every in-flight
        request settles, then close whatever is left as NACKed — the
        shutdown guarantee that ``minted == settled + nacked +
        outstanding`` ends with zero outstanding.  Returns the final
        accounting dict."""
        self._draining = True
        ran = 0
        while self.ingest.outstanding() > 0 and ran < max_windows:
            loop.run(n_windows=1)
            ran += 1
        for sid, tenant, b, c in self.ingest.nack_outstanding():
            client = self.sessions.pop(sid, None)
            if client is None:
                continue
            if isinstance(client, LocalCall):
                client.status = "nack"
                client.done.set()
            elif self.mux is not None:
                self.mux.send(client, self.parser.nack(sid, b, c))
        if self.mux is not None:
            self.mux.flush_all()
        acct = self.accounting()
        acct["drain_windows"] = ran
        return acct

    def accounting(self) -> dict:
        acct = self.ingest.accounting()
        acct["orphaned"] = self.orphaned
        acct["bad_tenant"] = self.bad_tenant
        acct["leaked_sessions"] = len(self.sessions) - sum(
            1 for s in self.sessions if s in self.ingest._open)
        if self.mux is not None:
            acct["mux"] = self.mux.stats()
        return acct

    def close(self):
        if self.mux is not None:
            self.mux.close()
