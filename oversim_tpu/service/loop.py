"""The double-buffered serving loop with preemption-safe checkpointing.

The batch tiers drive windows strictly serially: dispatch window k,
block on its counter fetch, report, dispatch k+1
(bench.run_measurement_windows).  The host-side fetch + summary +
artifact write all happen while the device sits idle.  The service loop
pipelines them instead:

      device   |  win k   |  win k+1  |  win k+2  |
      host          | fetch k-1 | fetch k  | fetch k+1 |
                      ckpt? report          ckpt? report

  * dispatch window k+1 (``run_until_device`` — async under jax's
    dispatch model, the host returns as soon as the computation is
    enqueued), THEN block on window k's fetch.  The device never idles
    between windows; the host drains k while k+1 computes.  Pinned by
    the fake-timer harness in tests/test_service.py (dispatch k+1
    strictly before fetch k, exactly ONE host sync per window) and
    visible as overlapping ``window_dispatch``/``window_fetch`` spans in
    the PerfettoTrace export.
  * every ``checkpoint_every`` windows the FULL state is device-copied
    right after dispatch and written through checkpoint.py during the
    next window's compute — the npz write rides the non-critical path.
    The write is tmp+rename atomic, so a SIGKILL at any instant leaves a
    complete previous checkpoint; ``ServiceLoop.resume`` restores it and
    continues BIT-IDENTICALLY (window targets are computed as
    ``start + (k+1)*window_sim_s`` from the checkpointed bookkeeping,
    never accumulated, so resumed targets equal uninterrupted ones
    exactly).
  * donation safety: ``run_until_device`` donates the state buffers, so
    the counter snapshot (and the checkpoint snapshot) are real device
    copies (``jnp.array(x, copy=True)``, the _dedupe_buffers idiom)
    enqueued BEFORE the next dispatch — stream order guarantees they
    read window k's values before window k+1 overwrites the donated
    buffers.

``runner`` is anything with the ``run_until_device(state, t_sim,
chunk=)`` contract: a Simulation (solo SimState) or a Campaign (stacked
[S] CampaignState) — checkpointing and summaries handle both.

With an ``ingest`` source attached (service/ingest.py) the loop runs
single-buffered: requests are batch-injected at the window boundary
(one ``inject_ext_batch`` pool write), served inside the window, and
their ``EXT_OUT`` responses — parked in the pool by the engine's
``ext_hold_slot`` hold (EngineParams) — are drained synchronously on
the fresh state.  The drain is a host read of the pool, which forces
the sync the double-buffer mode avoids: throughput mode and serving
mode are explicit park positions, not a silent middle ground.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np  # analysis: allow(host-numpy)  (host-side summaries off fetched leaves)

NS = 1_000_000_000


@dataclasses.dataclass(frozen=True)
class ServiceParams:
    """Knobs of the serving loop (``**.service.*`` ini keys)."""

    window_sim_s: float = 1.0     # simulated seconds per window
    chunk: int = 32               # ticks per device-resident scan chunk
    checkpoint_every: int = 0     # windows between checkpoints (0 = off)
    checkpoint_path: str | None = None
    max_windows: int = 0          # absolute window count to serve (0 = ∞)
    max_wall_s: float = 0.0       # wall-clock budget per run() (0 = ∞)
    double_buffer: bool = True    # pipeline fetch k under dispatch k+1
    realtime: bool = False        # pace windows to wall clock (gateway)


@dataclasses.dataclass
class _Pending:
    """An in-flight window: dispatched, not yet drained."""

    window: int                   # 0-based window index
    target_sim_t: float
    t_d0: float                   # dispatch span (host clock)
    t_d1: float
    snap: dict                    # device COPIES of the counter leaves
    ckpt: object = None           # device copy of the full state, or None


def counter_leaf_refs(s):
    """The per-window counter leaves as device REFERENCES — no fetch.

    Same selection as bench's ``_fetch_window_leaves`` (stats
    accumulators, engine counters, clock, alive mask, telemetry rings
    when present); the service loop copies these before the next
    dispatch and fetches the copy one window later."""
    leaves = {"stats": s.stats, "counters": s.counters,
              "t_now": s.t_now, "tick": s.tick, "alive": s.alive}
    tel = getattr(s, "telemetry", None)
    if tel is not None:
        leaves["telemetry"] = tel
    return leaves


def summarize_counter_leaves(leaves) -> dict:  # analysis: allow(host-float)
    """Host-side summary off already-fetched leaves (no device access —
    the per-window sync stays the loop's single fetch)."""
    from oversim_tpu import stats as stats_mod
    out = stats_mod.summarize(leaves["stats"])
    out["_engine"] = {k: int(v) for k, v in leaves["counters"].items()}
    out["_t_sim"] = float(leaves["t_now"]) / 1e9
    out["_ticks"] = int(leaves["tick"])
    out["_alive"] = int(leaves["alive"].sum())
    return out


def campaign_summarize_leaves(leaves) -> dict:  # analysis: allow(host-numpy, host-float)
    """Campaign tier: every leaf carries a leading [S] replica axis.
    Aggregate ACROSS replicas first (scalar accumulators merge exactly:
    sum n/sum/sumsq, min of mins, max of maxes; hist + counter leaves
    just sum), then reuse the single-run ``summarize`` — so the emitted
    record keeps the exact schema of the solo tier and ``on_window``'s
    consumers need no campaign awareness."""
    from oversim_tpu import stats as stats_mod
    agg = {}
    for key, v in leaves["stats"].items():
        v = np.asarray(v)
        if key.startswith("s:"):
            agg[key] = np.concatenate(
                [v[:, :3].sum(axis=0), [v[:, 3].min()], [v[:, 4].max()]])
        else:
            agg[key] = v.sum(axis=0)
    out = stats_mod.summarize(agg)
    out["_engine"] = {k: int(np.asarray(v).sum())
                      for k, v in leaves["counters"].items()}
    # replicas advance on independent event horizons — report the
    # LAGGING clock so "simulated seconds covered" is never overstated
    out["_t_sim"] = float(np.asarray(leaves["t_now"]).min()) / 1e9
    out["_ticks"] = int(np.asarray(leaves["tick"]).sum())
    out["_alive"] = int(np.asarray(leaves["alive"]).sum())
    return out


def _default_fetch(tree):  # analysis: allow(host-device-get)
    import jax
    return jax.device_get(tree)


def _default_copy(tree):
    # REAL device copies: jnp.array(copy=True), never a jitted identity
    # (jax returns the input alias for those) — the copies must outlive
    # the next dispatch's donation of the originals
    import jax
    import jax.numpy as jnp
    return jax.tree.map(lambda x: jnp.array(x, copy=True), tree)


def _min_sim_t(t_now) -> float:  # analysis: allow(host-numpy, host-float)
    # solo state: i64 scalar; campaign state: [S] vector — the lagging
    # replica clock is the campaign's window position
    return float(np.asarray(t_now).min()) / NS


class ServiceLoop:
    """Resident serving loop over a Simulation or Campaign runner.

    Parameters beyond ``(runner, state, params)``:

    config          scenario description dict; its telemetry.config_hash
                    is embedded in checkpoints and enforced on resume
    on_window       ``f(window, summary, wall_s)`` per drained window
    ingest          request source (service/ingest.py protocol:
                    ``before_window(state, target_ns)`` /
                    ``after_window(state)``); forces single-buffering
    trace           telemetry.PerfettoTrace — window_dispatch /
                    window_fetch / checkpoint_write spans
    events          ``f(kind, **fields)`` lifecycle sink — fired at the
                    loop's EXISTING host-sync points only
                    (``window_dispatched`` / ``window_fetched`` /
                    ``checkpoint_written``); the live observability
                    plane plugs its flight recorder in here
                    (oversim_tpu/obs/ RunObserver.loop_event) without
                    this module ever importing ``obs``
    summarize       fetched-leaves → dict (campaign_summarize_leaves for
                    a Campaign runner)
    fetch / copy    host-sync and device-copy hooks (fake harnesses)
    checkpoint_meta extra keys merged into the checkpoint manifest
    now             host clock (fake-timer tests)
    windows_done / start_sim_t
                    resume bookkeeping — use :meth:`resume` instead of
                    passing these directly
    """

    def __init__(self, runner, state, params: ServiceParams, *,
                 config=None, on_window=None, ingest=None, trace=None,
                 events=None, summarize=None, fetch=None, copy=None,
                 checkpoint_meta=None, now=time.perf_counter,
                 windows_done: int = 0, start_sim_t: float | None = None):
        self.runner = runner
        self.state = state
        self.p = params
        self.config = config
        self.config_hash = None
        if config is not None:
            from oversim_tpu import telemetry as telemetry_mod
            self.config_hash = telemetry_mod.config_hash(config)
        self.on_window = on_window
        self.ingest = ingest
        self.trace = trace
        self.events = events
        self.now = now
        self.checkpoint_meta = dict(checkpoint_meta or {})
        self.summarize = summarize or summarize_counter_leaves
        self.fetch = fetch or _default_fetch
        self.copy = copy or _default_copy
        self.windows_done = windows_done
        self.checkpoints_written = 0
        self.last_checkpoint = None   # windows_done of the newest ckpt
        if start_sim_t is None:
            # fresh start: the window origin is the state's current
            # clock (resume paths get the ORIGINAL origin from the
            # checkpoint manifest instead — t_now overshoots targets)
            start_sim_t = _min_sim_t(self.fetch(state.t_now))
        # host float by construction (manifest value or fetched scalar)
        self.start_sim_t = float(start_sim_t)  # analysis: allow(host-float)
        self._launched = windows_done  # next window index to dispatch
        self._pending: _Pending | None = None
        self._last_sim_t = None       # clock of the last drained window
        self._stop = False
        self._t0 = None

    # ---------------------------------------------------- lifecycle ----
    @classmethod
    def resume(cls, runner, example_state, params: ServiceParams, *,
               path: str | None = None, config=None,
               override_cadence: bool = False, reshard: bool = False,
               **kw):
        """Restore the last checkpoint and continue bit-identically.

        ``example_state`` supplies the pytree structure (``sim.init()``
        / ``campaign.init()``); ``config`` (when given) must hash to the
        checkpoint's recorded ``config_hash`` — a checkpoint from a
        different scenario is refused (checkpoint.load ``expect_config``).
        The checkpointed window cadence must match ``params``: a changed
        ``window_sim_s``/``chunk`` would move every subsequent window
        target and silently break the bit-identity guarantee, so it
        raises — unless ``override_cadence=True``, the explicit escape
        hatch, which RE-ANCHORS the window origin at the restored clock
        (next target = restored t_now + new window_sim_s; all later
        targets recomputed from the new origin, never accumulated).
        The caller trades the uninterrupted-run identity for the new
        cadence, knowingly.

        ``reshard=True`` restores at a DIFFERENT replica extent:
        ``runner`` must be a Campaign, and the checkpointed stacked
        state is grown/shrunk onto its extent via
        ``oversim_tpu.elastic.reshard_load`` (surviving rows
        bit-identical, grown rows deterministically re-seeded)."""
        from oversim_tpu import checkpoint as ckpt_mod
        path = path or params.checkpoint_path
        if path is None:
            raise ValueError("resume needs a checkpoint path")
        expect = None
        if config is not None:
            from oversim_tpu import telemetry as telemetry_mod
            expect = telemetry_mod.config_hash(config)
        if reshard:
            from oversim_tpu.elastic import reshard_load
            state, meta = reshard_load(path, runner,
                                       expect_config=expect,
                                       fresh=example_state)
            svc = meta.get("service") or {}
        else:
            state = ckpt_mod.load(path, example_state,
                                  expect_config=expect)
            svc = ckpt_mod.read_meta(path).get("service") or {}
        mismatch = [name for name in ("window_sim_s", "chunk")
                    if svc.get(name) is not None
                    and svc.get(name) != getattr(params, name)]
        windows_done = int(svc.get("windows_done", 0))
        start_sim_t = svc.get("start_sim_t")
        if mismatch and not override_cadence:
            name = mismatch[0]
            raise ValueError(
                f"resume cadence mismatch: checkpoint ran with "
                f"{name}={svc.get(name)} but params say "
                f"{getattr(params, name)}"
                " — window targets would diverge from the uninterrupted"
                " run (pass override_cadence=True / --override-cadence"
                " to re-anchor the window origin at the restored clock"
                " instead)")
        if mismatch:
            # re-anchor: choose the origin that puts the NEXT window
            # target one new-cadence window past the restored clock;
            # subsequent targets are start + (k+1)*w from this origin —
            # recomputed, never accumulated (pinned in test_service.py)
            start_sim_t = (_min_sim_t(state.t_now)
                           - windows_done * params.window_sim_s)
        return cls(runner, state, params, config=config,
                   windows_done=windows_done,
                   start_sim_t=start_sim_t, **kw)

    def stop(self):
        """Request a graceful stop after the current window drains."""
        self._stop = True

    def checkpoint_now(self) -> bool:
        """Write a checkpoint of the CURRENT state immediately.

        The graceful-shutdown path: a SIGTERM handler calls
        :meth:`stop`, :meth:`run` drains the in-flight window, then the
        caller invokes this so the final state is resumable even when
        the cadence checkpoint isn't due.  Returns False when no
        checkpoint path is configured."""
        if not self.p.checkpoint_path:
            return False
        self._write_checkpoint(self.copy(self.state))
        return True

    # ---------------------------------------------------- the loop -----
    def run(self, n_windows: int | None = None):
        """Serve windows until a limit hits: ``n_windows`` more from
        here, the absolute ``params.max_windows``, the per-call
        ``params.max_wall_s`` wall budget, or :meth:`stop`.  Returns
        ``(state, windows_done)``; always drains the trailing in-flight
        window before returning."""
        p = self.p
        limit = None
        if n_windows is not None:
            limit = self.windows_done + n_windows
        elif p.max_windows:
            limit = p.max_windows
        self._t0 = self.now()
        self._stop = False
        rt0 = time.monotonic()
        # realtime pacing origin: sim offset of this run()'s first window
        self._rt_sim0 = self.start_sim_t + self._launched * p.window_sim_s
        while not self._stop:
            if limit is not None and self._launched >= limit:
                break
            if p.max_wall_s and self.now() - self._t0 >= p.max_wall_s:
                break
            self._step_window(rt0)
        if self._pending is not None:
            rec, self._pending = self._pending, None
            self._drain(rec)
        return self.state, self.windows_done

    def _step_window(self, rt0):
        p = self.p
        k = self._launched
        target = self.start_sim_t + (k + 1) * p.window_sim_s
        if self.ingest is not None:
            # serving windows track the ACTUAL clock: event-driven ticks
            # and whole-chunk dispatch can overshoot the grid by many
            # windows, and a grid target below t_now would run ZERO
            # ticks — leaving just-injected requests undelivered.  The
            # clock comes from the PREVIOUS window's drained snapshot
            # (nothing between the drain and this boundary advances
            # t_now), so serving windows keep exactly ONE fetch-hook
            # sync per window — the daemon's fake-timer pin; only the
            # very first window (no drain yet) pays a fresh read.  The
            # fixed grid (and with it the resume bit-identity pin) is
            # the no-ingest tiers' contract.
            if self._pending is None and self._last_sim_t is not None:
                cur = self._last_sim_t
            else:
                cur = _min_sim_t(self.fetch(self.state.t_now))
            target = max(target, cur + p.window_sim_s)
        if p.realtime:
            # simulated time must not run ahead of wall clock
            # (realtimescheduler.cc pacing, at window granularity): the
            # window about to be served ends at sim offset target-_rt_sim0
            ahead = (target - self._rt_sim0
                     - (time.monotonic() - rt0))
            if ahead > 0:
                time.sleep(ahead)
        if self.ingest is not None:
            # batched request injection at the boundary — one pool
            # write, delivered at the start of the window about to run
            s = self.ingest.before_window(self.state,
                                          int(target * NS))
            if s is not None:
                self.state = s
        t_d0 = self.now()
        self.state = self.runner.run_until_device(self.state, target,
                                                  chunk=p.chunk)
        t_d1 = self.now()
        self._launched = k + 1
        # device copies enqueued behind the dispatch, ahead of the NEXT
        # dispatch's donation — snapshot without a host sync
        snap = self.copy(counter_leaf_refs(self.state))
        ckpt = None
        if (p.checkpoint_every and p.checkpoint_path
                and (k + 1) % p.checkpoint_every == 0):
            ckpt = self.copy(self.state)
        rec = _Pending(window=k, target_sim_t=target, t_d0=t_d0,
                       t_d1=t_d1, snap=snap, ckpt=ckpt)
        if self.events is not None:
            self.events("window_dispatched", window=k,
                        target_sim_t=target)
        if p.double_buffer and self.ingest is None:
            prev, self._pending = self._pending, rec
            if prev is not None:
                self._drain(prev)     # fetch k-1 AFTER dispatching k
        else:
            self._drain(rec)
            if self.ingest is not None:
                s = self.ingest.after_window(self.state)
                if s is not None:
                    self.state = s

    def _drain(self, rec: _Pending):
        """Window k's host side: the ONE sync (fetch of the snapshot
        copies), trace spans, the non-critical-path checkpoint write,
        and the report callback."""
        t_f0 = self.now()
        leaves = self.fetch(rec.snap)
        t_f1 = self.now()
        if "t_now" in leaves:
            # remember the drained clock: the next ingest boundary's
            # current-time read reuses it instead of a second fetch
            self._last_sim_t = _min_sim_t(leaves["t_now"])
        if self.trace is not None:
            self.trace.span("window_dispatch", rec.t_d0,
                            rec.t_d1 - rec.t_d0,
                            args={"window": rec.window,
                                  "target_sim_t": rec.target_sim_t})
            self.trace.span("window_fetch", t_f0, t_f1 - t_f0,
                            args={"window": rec.window})
        summary = self.summarize(leaves)
        self.windows_done = rec.window + 1
        if self.events is not None:
            self.events("window_fetched", window=rec.window,
                        fetch_s=t_f1 - t_f0)
        if rec.ckpt is not None:
            t_c0 = self.now()
            self._write_checkpoint(rec.ckpt)
            if self.trace is not None:
                self.trace.span("checkpoint_write", t_c0,
                                self.now() - t_c0,
                                args={"windows_done": self.windows_done})
        if self.on_window is not None:
            self.on_window(rec.window, summary, self.now() - self._t0)

    def _write_checkpoint(self, snapshot):
        from oversim_tpu import checkpoint as ckpt_mod
        p = self.p
        meta = dict(self.checkpoint_meta)
        if self.config_hash is not None:
            meta.setdefault("config_hash", self.config_hash)
        # reshard-aware meta: a Campaign runner records its identity so
        # elastic.reshard_load can check grown-slot seeding at restore
        if hasattr(self.runner, "describe"):
            meta.setdefault("campaign", self.runner.describe())
        meta["service"] = {
            "windows_done": self.windows_done,
            "start_sim_t": self.start_sim_t,
            "window_sim_s": p.window_sim_s,
            "chunk": p.chunk,
            "checkpoint_every": p.checkpoint_every,
        }
        ckpt_mod.save(p.checkpoint_path, snapshot, meta=meta)
        self.checkpoints_written += 1
        self.last_checkpoint = self.windows_done
        if self.events is not None:
            self.events("checkpoint_written",
                        windows_done=self.windows_done,
                        path=p.checkpoint_path)
