"""Fixed-width overlay-key arithmetic on packed uint32 lanes.

TPU-native equivalent of the reference's GMP-backed ``OverlayKey``
(reference: src/common/OverlayKey.{h,cc} — arbitrary-width keys on
``mp_limb_t`` arrays, MAX_KEYLENGTH=512, ring/xor/prefix metrics used by
every overlay protocol).  Instead of per-object bignum limbs we represent a
key as a little vector of ``KL`` uint32 lanes, **most-significant lane
first**, so a batch of N keys is a ``[N, KL]`` uint32 array and every
operation below vectorizes over arbitrary leading batch dimensions.

keyLength is a static (trace-time) property carried by the module-level
``KeySpec``; 160-bit keys (the default, default.ini:393 ``keyLength=160``)
pack into KL=5 lanes.  All ops are pure jnp and fuse under jit; the
multi-lane compares unroll a python loop over the (static, tiny) lane count.
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32
U64 = jnp.uint64
LANE_BITS = 32
MAX_KEY_BITS = 512


@dataclasses.dataclass(frozen=True)
class KeySpec:
    """Static description of the key space (reference: OverlayKey keyLength
    global, set from par("keyLength") in BaseOverlay.cc:80)."""

    bits: int = 160

    def __post_init__(self):
        if not (0 < self.bits <= MAX_KEY_BITS):
            raise ValueError(f"keyLength must be in (0, {MAX_KEY_BITS}]")

    @property
    def lanes(self) -> int:
        return (self.bits + LANE_BITS - 1) // LANE_BITS

    @property
    def top_lane_bits(self) -> int:
        """Number of significant bits in lane 0."""
        r = self.bits % LANE_BITS
        return LANE_BITS if r == 0 else r

    @property
    def top_lane_mask(self) -> int:
        return (1 << self.top_lane_bits) - 1


DEFAULT_SPEC = KeySpec(160)


# ---------------------------------------------------------------------------
# construction / conversion
# ---------------------------------------------------------------------------

def from_int(value: int, spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    """Build a single [KL] key from a python int (host-side helper)."""
    value &= (1 << spec.bits) - 1
    lanes = [(value >> (LANE_BITS * i)) & 0xFFFFFFFF for i in range(spec.lanes)]
    return jnp.asarray(lanes[::-1], dtype=U32)


def to_int(key, spec: KeySpec = DEFAULT_SPEC) -> int:
    """Convert a single [KL] key back to a python int (host-side helper)."""
    lanes = np.asarray(key, dtype=np.uint64)
    out = 0
    for lane in lanes:
        out = (out << LANE_BITS) | int(lane)
    return out


def zero(spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    return jnp.zeros((spec.lanes,), dtype=U32)


def max_key(spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    k = jnp.full((spec.lanes,), 0xFFFFFFFF, dtype=U32)
    return k.at[0].set(jnp.uint32(spec.top_lane_mask))


def mask_to_width(key, spec: KeySpec = DEFAULT_SPEC):
    """Clear the unused high bits of lane 0."""
    top = key[..., :1] & jnp.uint32(spec.top_lane_mask)
    return jnp.concatenate([top, key[..., 1:]], axis=-1) if spec.lanes > 1 else top


def random_keys(rng: jax.Array, batch_shape, spec: KeySpec = DEFAULT_SPEC):
    """Uniform random keys, shape ``batch_shape + (KL,)``.

    Reference: OverlayKey::random() (OverlayKey.cc:477) draws each limb from
    the module RNG; we draw uint32 lanes from a counter-based PRNG instead.
    """
    bits = jax.random.bits(rng, tuple(batch_shape) + (spec.lanes,), dtype=U32)
    return mask_to_width(bits, spec)


def sha1_key(data: bytes, spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    """Host-side sha1 → key (reference: OverlayKey::sha1, OverlayKey.cc:493).

    Used for hashing values/names into the key space (DHT, Scribe groups);
    runs on host at config/workload-build time, never inside jit.
    """
    digest = hashlib.sha1(data).digest()
    value = int.from_bytes(digest, "big")
    if spec.bits < 160:
        value >>= 160 - spec.bits
    return from_int(value, spec)


# ---------------------------------------------------------------------------
# comparisons (lexicographic over most-significant-first lanes)
# ---------------------------------------------------------------------------

def eq(a, b):
    return jnp.all(a == b, axis=-1)


def _lex(a, b):
    """Returns (lt, gt) bool arrays comparing multi-lane keys."""
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    done = jnp.zeros(a.shape[:-1], dtype=bool)
    for i in range(a.shape[-1]):  # static, tiny lane count — unrolled
        ai, bi = a[..., i], b[..., i]
        lt = jnp.where(~done & (ai < bi), True, lt)
        gt = jnp.where(~done & (ai > bi), True, gt)
        done = done | (ai != bi)
    return lt, gt


def lt(a, b):
    return _lex(a, b)[0]


def gt(a, b):
    return _lex(a, b)[1]


def le(a, b):
    return ~gt(a, b)


def ge(a, b):
    return ~lt(a, b)


# ---------------------------------------------------------------------------
# modular ring arithmetic (mod 2**bits)
# ---------------------------------------------------------------------------

def add(a, b, spec: KeySpec = DEFAULT_SPEC):
    """(a + b) mod 2**bits, lane-wise with carry propagation."""
    kl = spec.lanes
    out = []
    carry = jnp.zeros(a.shape[:-1], dtype=U64)
    for i in range(kl - 1, -1, -1):  # least-significant lane last in layout
        s = a[..., i].astype(U64) + b[..., i].astype(U64) + carry
        out.append((s & jnp.uint64(0xFFFFFFFF)).astype(U32))
        carry = s >> jnp.uint64(32)
    key = jnp.stack(out[::-1], axis=-1)
    return mask_to_width(key, spec)


def neg(a, spec: KeySpec = DEFAULT_SPEC):
    """Two's complement: (-a) mod 2**bits."""
    one = jnp.zeros_like(a).at[..., -1].set(jnp.uint32(1))
    return add(~a, one, spec)


def sub(a, b, spec: KeySpec = DEFAULT_SPEC):
    """(a - b) mod 2**bits."""
    return add(a, neg(b, spec), spec)


def bit(key, index, spec: KeySpec = DEFAULT_SPEC):
    """Bit ``index`` of the key, where index 0 is the LSB (reference:
    OverlayKey::getBit).  ``index`` may be a traced int array."""
    index = jnp.asarray(index)
    lane = spec.lanes - 1 - (index // LANE_BITS)
    off = index % LANE_BITS
    word = jnp.take_along_axis(key, lane[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return (word >> off.astype(U32)) & jnp.uint32(1)


def digit(key, index, b: int, spec: KeySpec = DEFAULT_SPEC):
    """b-bit digit ``index`` counted from the MSB (Pastry prefix digits;
    reference OverlayKey::getBitRange as used by PastryRoutingTable).
    ``index`` may be traced."""
    index = jnp.asarray(index)
    out = jnp.zeros(jnp.broadcast_shapes(index.shape, key.shape[:-1]),
                    dtype=jnp.int32)
    for j in range(b):
        pos = spec.bits - 1 - (index * b + j)
        out = (out << 1) | jnp.where(
            pos >= 0, bit(key, jnp.maximum(pos, 0), spec).astype(jnp.int32), 0)
    return out


def shared_prefix_digits(a, b_key, b: int, spec: KeySpec = DEFAULT_SPEC):
    """Number of common leading b-bit digits (Pastry row index)."""
    return shared_prefix_length(a, b_key, spec) // b


def abs_diff(a, b, spec: KeySpec = DEFAULT_SPEC):
    """Plain numerical |a - b| (NON-modular; Pastry's numeric-closeness
    metric, BasePastry 'numerically closest' comparisons)."""
    a_ge = ge(a, b)
    d1 = sub(a, b, spec)
    d2 = sub(b, a, spec)
    return jnp.where(a_ge[..., None], d1, d2)


def pow2(exponent: int, spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    """Single key 2**exponent (host-side; finger-table offsets)."""
    return from_int(1 << exponent, spec)


def pow2_table(spec: KeySpec = DEFAULT_SPEC) -> jnp.ndarray:
    """[bits, KL] table of 2**i for i in 0..bits-1 (finger offsets)."""
    return jnp.stack([from_int(1 << i, spec) for i in range(spec.bits)])


def shl_const(key, c: int, spec: KeySpec = DEFAULT_SPEC):
    """Logical left shift by a STATIC bit count (reference OverlayKey
    operator<<; Koorde digit-shift routing)."""
    if c == 0:
        return mask_to_width(key, spec)
    kl = spec.lanes
    lane_sh, bit_sh = c // LANE_BITS, c % LANE_BITS
    out = []
    for i in range(kl):
        src = i + lane_sh
        lo = key[..., src] if src < kl else jnp.zeros_like(key[..., 0])
        if bit_sh:
            nxt = key[..., src + 1] if src + 1 < kl else jnp.zeros_like(
                key[..., 0])
            lo = (lo << jnp.uint32(bit_sh)) | (
                nxt >> jnp.uint32(LANE_BITS - bit_sh))
        out.append(lo)
    return mask_to_width(jnp.stack(out, axis=-1), spec)


def shr_const(key, c: int, spec: KeySpec = DEFAULT_SPEC):
    """Logical right shift by a STATIC bit count (counts from the
    significant width: the unused high bits of lane 0 stay zero)."""
    if c == 0:
        return mask_to_width(key, spec)
    kl = spec.lanes
    key = mask_to_width(key, spec)
    lane_sh, bit_sh = c // LANE_BITS, c % LANE_BITS
    out = []
    for i in range(kl):
        src = i - lane_sh
        lo = key[..., src] if src >= 0 else jnp.zeros_like(key[..., 0])
        if bit_sh:
            prv = key[..., src - 1] if src - 1 >= 0 else jnp.zeros_like(
                key[..., 0])
            lo = (lo >> jnp.uint32(bit_sh)) | (
                prv << jnp.uint32(LANE_BITS - bit_sh))
        out.append(lo)
    return jnp.stack(out, axis=-1)


def _barrel(key, n, spec: KeySpec, const_fn):
    """Dynamic shift by traced ``n`` via a barrel of static shifts."""
    n = jnp.asarray(n, jnp.int32)
    out = key
    p = 0
    while (1 << p) < spec.bits:
        amt = 1 << p
        bit = ((n >> p) & 1) != 0
        out = jnp.where(bit[..., None], const_fn(out, amt, spec), out)
        p += 1
    # shifts >= bits clear everything
    return jnp.where((n >= spec.bits)[..., None], jnp.zeros_like(out), out)


def shl_dyn(key, n, spec: KeySpec = DEFAULT_SPEC):
    """Left shift by a TRACED amount (Koorde findStartKey)."""
    return _barrel(key, n, spec, shl_const)


def shr_dyn(key, n, spec: KeySpec = DEFAULT_SPEC):
    """Right shift by a TRACED amount (Koorde findStartKey)."""
    return _barrel(key, n, spec, shr_const)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def xor_metric(a, b):
    """XOR distance (Kademlia; reference KeyXorMetric, Comparator.h)."""
    return a ^ b


def ring_distance(a, b, spec: KeySpec = DEFAULT_SPEC):
    """Clockwise (unidirectional) ring distance a→b: (b - a) mod 2**bits.

    Reference: KeyRingMetric / Chord::distance (Chord.cc:1403).
    """
    return sub(b, a, spec)


def cw_ring_distance(a, b, spec: KeySpec = DEFAULT_SPEC):
    """Counter-clockwise ring distance (KeyCwRingMetric): (a - b) mod 2**bits."""
    return sub(a, b, spec)


def bidir_ring_distance(a, b, spec: KeySpec = DEFAULT_SPEC):
    """min(|a-b|, |b-a|) on the ring (used by e.g. Broose bucket metrics)."""
    d1 = sub(b, a, spec)
    d2 = sub(a, b, spec)
    use1 = lt(d1, d2)
    return jnp.where(use1[..., None], d1, d2)


def is_between(key, a, b, spec: KeySpec = DEFAULT_SPEC):
    """True iff key ∈ (a, b) on the ring, endpoints excluded.

    Reference: OverlayKey::isBetween.  Implemented as
    0 < (key - a) < (b - a) in modular arithmetic, which handles wraparound
    uniformly; degenerate a==b follows the reference convention (empty
    interval unless key != a: the full-ring interval (a,a) contains every
    key except a itself).
    """
    dk = sub(key, a, spec)
    db = sub(b, a, spec)
    k_nonzero = ~eq(key, a)
    full = eq(a, b)
    return jnp.where(full, k_nonzero, lt(dk, db) & k_nonzero)


def is_between_r(key, a, b, spec: KeySpec = DEFAULT_SPEC):
    """key ∈ (a, b] (right-closed; reference OverlayKey::isBetweenR)."""
    return is_between(key, a, b, spec) | eq(key, b)


def is_between_l(key, a, b, spec: KeySpec = DEFAULT_SPEC):
    """key ∈ [a, b) (left-closed; reference OverlayKey::isBetweenL)."""
    return is_between(key, a, b, spec) | eq(key, a)


def is_between_lr(key, a, b, spec: KeySpec = DEFAULT_SPEC):
    """key ∈ [a, b] (closed; reference OverlayKey::isBetweenLR)."""
    return is_between(key, a, b, spec) | eq(key, a) | eq(key, b)


def shared_prefix_length(a, b, spec: KeySpec = DEFAULT_SPEC):
    """Length of the common MSB prefix (reference OverlayKey.cc:411).

    Counts from the top of the *significant* width (spec.bits), i.e. the
    unused high bits of lane 0 are ignored.
    """
    x = a ^ b
    # clz per lane, then accumulate full-lane prefixes lexicographically.
    total = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    done = jnp.zeros(x.shape[:-1], dtype=bool)
    for i in range(spec.lanes):
        lane = x[..., i]
        lane_clz = jax.lax.clz(lane).astype(jnp.int32)
        if i == 0:
            # ignore the dead bits above the key width
            lane_clz = jnp.minimum(lane_clz - (LANE_BITS - spec.top_lane_bits),
                                   spec.top_lane_bits)
            lane_bits = spec.top_lane_bits
        else:
            lane_bits = LANE_BITS
        contrib = jnp.where(lane == 0, lane_bits, lane_clz)
        total = total + jnp.where(done, 0, contrib)
        done = done | (lane != 0)
    return jnp.minimum(total, spec.bits)


def log2_floor(key, spec: KeySpec = DEFAULT_SPEC):
    """floor(log2(key)) as int32; -1 for key == 0 (bucket indexing)."""
    return spec.bits - 1 - shared_prefix_length(key, jnp.zeros_like(key), spec)


def dup_mask(vec):
    """[C] → [C] bool marking every later duplicate of an earlier entry
    (keep-first semantics).  Shared dedupe primitive for candidate-set
    merges (NodeVector::add rejects keys already present, NodeVector.h)."""
    c = vec.shape[0]
    eq = vec[None, :] == vec[:, None]
    return jnp.any(eq & jnp.tril(jnp.ones((c, c), bool), k=-1), axis=1)


# ---------------------------------------------------------------------------
# sorting / top-k by multi-lane distance
# ---------------------------------------------------------------------------

def sort_by_distance(dist, payload, num_keys: int | None = None, *,
                     approx: bool = False):
    """Sort ``payload`` (tuple of [..., C] arrays) by multi-lane distance
    ``dist`` [..., C, KL], ascending lexicographically.

    TPU-native replacement for the reference's ``BaseKeySortedVector`` /
    ``NodeVector`` (src/common/NodeVector.h:40-44: fixed-capacity vector kept
    sorted by a pluggable key comparator) — instead of incremental sorted
    insertion we batch-sort candidate sets with XLA's lexicographic
    ``lax.sort`` and take a prefix.

    The DEFAULT comparator is exact (all KL lanes — NodeVector.h:40-44
    semantics).  ``approx=True`` opts into sort-key compression: only
    the top TWO u32 lanes (64 bits) of the distance feed the
    comparator.  That is exact-in-practice ONLY for high-entropy
    distances — distinct 160+-bit node keys drawn uniformly
    (engine/sim.py random nodeIds) tie in the top 64 bits of a
    ring/XOR distance with probability ~N²·2⁻⁶⁴ per simulation — and
    it halves-to-thirds the lax.sort operand count on the hot
    findNode/frontier paths (the tick graph is op-issue-bound,
    PERFORMANCE.md).  A caller sorting STRUCTURED or low-entropy
    distances (keys sharing long prefixes by construction, team-offset
    keys, distances clamped to a small range) must NOT pass approx:
    compression was previously the silent default and was flagged as a
    wrongness trap (VERDICT r3/r4) — it is now opt-in at every site.

    Returns (sorted_dist, sorted_payloads).  On the compressed path
    sorted_dist carries only the comparator lanes (no caller consumes
    it — every call site takes ``[1]``).  ``num_keys`` still forces an
    exact sort with that many comparator lanes (back-compat).
    """
    kl = dist.shape[-1]
    if num_keys is None and approx:
        nk = min(2, kl)
        lanes = tuple(dist[..., i] for i in range(nk))
    else:
        nk = kl if num_keys is None else num_keys
        lanes = tuple(dist[..., i] for i in range(kl))
    operands = lanes + tuple(payload)
    out = jax.lax.sort(operands, dimension=-1, num_keys=nk)
    sorted_dist = jnp.stack(out[:len(lanes)], axis=-1)
    return sorted_dist, tuple(out[len(lanes):])
