"""oversim_tpu — a TPU-native overlay-network simulation framework.

A from-scratch JAX/XLA rebuild of the capabilities of OverSim (the OMNeT++
P2P overlay simulator, reference at /root/reference): structured KBR/DHT
overlays, unstructured search, churn models, an analytic underlay network
model and oracle-validated test workloads — with all N simulated nodes'
state held as structure-of-arrays device tensors and every simulation tick
a vmapped message-passing gather/scatter step.

Design (see SURVEY.md §7):
  - state: pytree of [N, ...] arrays, shardable over a jax Mesh on the node axis
  - time: int64 nanoseconds (reference uses simtime-scale=-9, default.ini:26-28)
  - events: a global bounded message pool + per-node periodic timers;
    each tick advances simulated time to the next event horizon
  - randomness: counter-based jax.random with per-node fold_in
"""

import sys

# zstd's C extension segfaults on this box (tests/conftest.py note) —
# poison it BEFORE anything can import jax's compilation cache, so the
# cache falls back to zlib wherever this package is imported.  Kept in
# sync with hostcache.enable(), which re-asserts it for script entry
# points that configure the cache explicitly.
if "zstandard" not in sys.modules:
    sys.modules["zstandard"] = None

import jax

# Simulated time is int64 nanoseconds; without x64 JAX silently
# canonicalizes int64 -> int32 which overflows after 2.1 simulated seconds.
# All other arrays declare explicit narrow dtypes (i32/f32/u32/bool).
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
