"""XML-RPC front-end: drive a running simulation from real clients.

Rebuild of src/tier3/xmlrpcinterface/ (XmlRpcInterface.h:102-166 — an
XML-RPC server on the singlehost node exposing the KBR/DHT Common API
to external tools: local_lookup, lookup, register/resolve, put/get,
dump_dht).  The TPU equivalent serves the same surface over Python's
stdlib XML-RPC server, executing against a live Simulation + state:

  * ``local_lookup(key_hex, num)`` — closest READY nodes to the key
    from the global node table (the reference answers from the local
    routing table without network traffic; the engine's oracle is the
    natural equivalent — BaseOverlay::local_lookup semantics);
  * ``put(key_hex, value, ttl)`` / ``get(key_hex)`` — issue the real
    tier-1 DHT RPCs (common/wire.py DHT_PUT_CALL/DHT_GET_CALL — the
    same messages DHT.cc exchanges) from a host-injected call to each
    replica holder, then run the simulation until the responses land;
  * ``stats()`` — GlobalStatistics scalars (XmlRpcInterface has no
    direct equivalent; exposed because every external driver wants it);
  * ``advance(seconds)`` — step simulated time (the singlehost build
    advances in realtime instead; see gateway.RealtimeGateway).

Responses are observed in the message pool between ticks (the gateway
drain pattern): DHT_PUT_RES/DHT_GET_RES addressed to the injector slot
are collected and freed before the app layer would mis-consume them.
"""

from __future__ import annotations

import dataclasses
import threading
from xmlrpc.server import SimpleXMLRPCServer

import jax.numpy as jnp
import numpy as np

from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod
from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
NO_NODE = -1


class XmlRpcInterface:
    """Method container; also usable directly (no server) in tests."""

    def __init__(self, sim, state, injector_slot: int = 0):
        self.sim = sim
        self.state = state
        self.slot = injector_slot

    # ------------------------------------------------ helpers ----------
    def _key(self, key_hex: str):
        return keys_mod.from_int(int(key_hex, 16), self.sim.spec)

    def _closest_ready(self, key, num: int):
        st = self.state
        ready = np.asarray(st.alive) & np.asarray(  # analysis: allow(device-sync)
            self.sim.logic.ready_mask(st.logic))
        kt = np.asarray(st.node_keys, dtype=np.uint64)  # analysis: allow(device-sync)
        tgt = np.asarray(key, dtype=np.uint64)
        # big-endian lane compare == ring xor-free distance on the key
        # table; python bignum per node is fine host-side
        lanes = kt.shape[1]
        ints = np.zeros(kt.shape[0], object)
        for l in range(lanes):
            ints = ints * (1 << 32) + kt[:, l]
        t_int = 0
        for l in range(lanes):
            t_int = (t_int << 32) + int(tgt[l])
        bits = self.sim.spec.bits
        mod = 1 << bits
        dist = np.array([min((int(i) - t_int) % mod,
                             (t_int - int(i)) % mod) for i in ints],
                        object)
        dist[~ready] = mod
        order = np.argsort([int(d) for d in dist])
        return [int(i) for i in order[:num] if ready[i]]

    def _inject(self, dst: int, kind: int, key, a=0, b=0, stamp=0):
        s = self.state
        rmax = s.pool.nodes.shape[1]
        out = dict(
            t_deliver=jnp.asarray([s.t_now + 1], I64),
            src=jnp.asarray([self.slot], I32),
            dst=jnp.asarray([dst], I32),
            kind=jnp.asarray([kind], I32),
            key=jnp.asarray(key)[None, :],
            nonce=jnp.zeros((1,), I32),
            hops=jnp.zeros((1,), I32),
            a=jnp.asarray([a], I32), b=jnp.asarray([b], I32),
            c=jnp.zeros((1,), I32), d=jnp.zeros((1,), I32),
            nodes=jnp.full((1, rmax), NO_NODE, I32),
            size_b=jnp.asarray([wire.BASE_CALL_B + 28], I32),
            stamp=jnp.asarray([stamp], I64),
        )
        new_pool, _ = pool_mod.alloc(s.pool, out, jnp.asarray([True]))
        self.state = dataclasses.replace(s, pool=new_pool)

    def _collect(self, kinds, nonce, max_ticks: int = 400,
                 want_payload: bool = False, a_match=None):
        """Step until responses with our nonce arrive (drained between
        ticks so the injector node's app never sees them).  Each hit is
        (kind, a) — or (kind, a, c, [nodes...]) with ``want_payload``."""
        got = []
        for _ in range(max_ticks):
            self.state = self.sim.step(self.state)
            pool = self.state.pool
            valid = np.asarray(pool.valid)
            kind = np.asarray(pool.kind)
            dst = np.asarray(pool.dst)
            b = np.asarray(pool.b)
            sel = (valid & np.isin(kind, kinds) & (dst == self.slot)
                   & (b == nonce))
            if a_match is not None:
                sel = sel & (np.asarray(pool.a) == a_match)
            hits = np.nonzero(sel)[0]
            if len(hits):
                a = np.asarray(pool.a)
                c = np.asarray(pool.c)
                nodes = np.asarray(pool.nodes)
                for i in hits:
                    if want_payload:
                        got.append((int(kind[i]), int(a[i]), int(c[i]),
                                    [int(x) for x in nodes[i]
                                     if x != NO_NODE]))
                    else:
                        got.append((int(kind[i]), int(a[i])))
                mask = jnp.zeros(pool.valid.shape, bool).at[
                    jnp.asarray(hits, I32)].set(True)
                self.state = dataclasses.replace(
                    self.state, pool=pool_mod.free(pool, mask))
                return got
        return got

    # ------------------------------------------------ RPC surface ------
    def stats(self):
        out = self.sim.summary(self.state)
        clean = {}
        for k, v in out.items():
            if isinstance(v, dict):
                clean[k] = {kk: float(vv) for kk, vv in v.items()}
            elif isinstance(v, (list, tuple)):
                clean[k] = [float(x) for x in v]
            else:
                clean[k] = float(v)
        return clean

    def advance(self, seconds: float):
        t = (int(self.state.t_now) / NS) + float(seconds)  # analysis: allow(device-sync)
        self.state = self.sim.run_until(self.state, t)
        return int(self.state.t_now)  # analysis: allow(device-sync)

    def local_lookup(self, key_hex: str, num: int = 4):
        """Closest READY nodes (XmlRpcInterface::localLookup)."""
        return self._closest_ready(self._key(key_hex), num)

    def put(self, key_hex: str, value: int, ttl: float = 300.0):
        """DHT put: DHTPutCall to each replica holder; returns the
        number of acks (XmlRpcInterface::put → DHTputCAPI)."""
        key = self._key(key_hex)
        nrep = getattr(getattr(self.sim.logic, "app", None), "p",
                       None)
        num = nrep.num_replica if nrep is not None and hasattr(
            nrep, "num_replica") else 4
        holders = self._closest_ready(key, num)
        nonce = (int(self.state.t_now) // 1000) % (2**30) + 7  # analysis: allow(device-sync)
        expire = int(self.state.t_now) + int(ttl * NS)  # analysis: allow(device-sync)
        for h in holders:
            self._inject(h, wire.DHT_PUT_CALL, key, a=int(value),
                         b=nonce, stamp=expire)
        acks = self._collect([int(wire.DHT_PUT_RES)], nonce)
        return len(acks)

    def get(self, key_hex: str):
        """DHT get: DHTGetCall to the closest holder; returns the value
        id or -1 (XmlRpcInterface::get → DHTgetCAPI)."""
        key = self._key(key_hex)
        holders = self._closest_ready(key, 1)
        if not holders:
            return -1
        nonce = (int(self.state.t_now) // 1000) % (2**30) + 13  # analysis: allow(device-sync)
        self._inject(holders[0], wire.DHT_GET_CALL, key, b=nonce)
        got = self._collect([int(wire.DHT_GET_RES)], nonce)
        return got[0][1] if got else -1

    def lookup(self, key_hex: str, num_siblings: int = 4):
        """Full KBR lookup over the real wire (XmlRpcInterface::lookup →
        LookupCall): iterative FindNode rounds driven from the injector
        slot — the same FINDNODE_CALL/RES exchange the in-sim lookup
        engine performs — until a responder flags sibling
        responsibility.  Returns the sibling slot list ([] on failure)."""
        key = self._key(key_hex)
        frontier = self._closest_ready(key, 1)
        visited: set = set()
        for _ in range(16):
            cand = next((h for h in frontier if h not in visited), None)
            if cand is None:
                return []
            visited.add(cand)
            nonce = (int(self.state.t_now) // 1000) % (2**30) + 21  # analysis: allow(device-sync)
            self._inject(cand, wire.FINDNODE_CALL, key, b=nonce)
            got = self._collect([int(wire.FINDNODE_RES)], nonce,
                                want_payload=True)
            if not got:
                continue
            _, _, sib_flag, nodes = got[0]
            if sib_flag and nodes:
                return nodes[:num_siblings]
            frontier = nodes + [h for h in frontier if h not in visited]
        return []

    @staticmethod
    def _name_id(name: str) -> int:
        import hashlib
        return int.from_bytes(
            hashlib.sha1(name.encode()).digest()[:4], "big") & 0x7FFFFFFF

    def register(self, name: str, value: int, ttl: float = 3600.0):
        """P2PNS register (XmlRpcInterface::register → P2pnsRegisterCall):
        binds name→value at the node responsible for sha1(name).
        Returns True on the registrar's ack.  Requires the P2PNS tier
        (apps/p2pns.py) in the running stack, as in the reference."""
        nid = self._name_id(name)
        key = keys_mod.sha1_key(name.encode(), self.sim.spec)
        holders = self.lookup(
            hex(keys_mod.to_int(key))[2:], 1) or self._closest_ready(key, 1)
        if not holders:
            return False
        expire = int(self.state.t_now) + int(ttl * NS)  # analysis: allow(device-sync)
        # wire protocol: a=name id, b=VALUE (stored by the registrar);
        # the ack echoes both — matching on (a, b) keeps in-sim P2PNS
        # traffic to the injector slot from false-acking us
        self._inject(holders[0], wire.P2PNS_REG_CALL, key, a=nid,
                     b=int(value), stamp=expire)
        got = self._collect([int(wire.P2PNS_REG_RES)], int(value),
                            a_match=nid)
        return bool(got)

    def resolve(self, name: str):
        """P2PNS resolve (XmlRpcInterface::resolve → P2pnsResolveCall):
        returns the registered value or -1."""
        nid = self._name_id(name)
        key = keys_mod.sha1_key(name.encode(), self.sim.spec)
        holders = self.lookup(
            hex(keys_mod.to_int(key))[2:], 1) or self._closest_ready(key, 1)
        if not holders:
            return -1
        nonce = (int(self.state.t_now) // 1000) % (2**30) + 29  # analysis: allow(device-sync)
        self._inject(holders[0], wire.P2PNS_RES_CALL, key, a=nid, b=nonce)
        got = self._collect([int(wire.P2PNS_RES_RES)], nonce,
                            want_payload=True)
        return got[0][2] if got else -1

    def dump_dht(self):
        """Aggregate every live node's DHTDataStorage
        (XmlRpcInterface::dumpDht → DHTdump): [[key_hex, value], ...].
        Reads storage state directly, as the reference dumps the local
        DHT module's storage map."""
        app = getattr(self.state.logic, "app", None)
        if app is None or not hasattr(app, "s_key"):
            return []
        alive = np.asarray(self.state.alive)  # analysis: allow(device-sync)
        s_key = np.asarray(app.s_key)
        s_val = np.asarray(app.s_val)
        out = []
        lanes = s_key.shape[-1]
        for i in np.nonzero(alive)[0]:
            for d in range(s_val.shape[1]):
                if s_val[i, d] != -1:
                    k = 0
                    for l in range(lanes):
                        k = (k << 32) + int(s_key[i, d, l])
                    out.append([hex(k)[2:], int(s_val[i, d])])
        return out

    def join_overlay(self):
        """Spawn a node into the overlay (XmlRpcInterface::joinOverlay):
        revives a dead slot with a fresh nodeId and schedules its join.
        Returns the slot index, or -1 when every slot is alive."""
        import jax
        alive = np.asarray(self.state.alive)  # analysis: allow(device-sync)
        dead = np.nonzero(~alive)[0]
        if not len(dead):
            return -1
        slot = int(dead[0])
        s = self.state
        n = alive.shape[0]
        mask = jnp.zeros((n,), bool).at[slot].set(True)
        rng, r_key, r_reset, r_mig = jax.random.split(s.rng, 4)
        fresh_keys = jnp.where(
            mask[:, None],
            keys_mod.random_keys(r_key, (n,), self.sim.spec), s.node_keys)
        logic2 = self.sim.logic.reset(s.logic, mask, mask, s.t_now,
                                      r_reset)
        # mirror the engine's churn-create path (engine/sim.py):
        # fresh coordinates, reset queues, dead TCP connections cleared
        ul2 = self.sim.ul.migrate(s.underlay, mask, r_mig, self.sim.up)
        self.state = dataclasses.replace(
            s, rng=rng, alive=s.alive | mask, node_keys=fresh_keys,
            underlay=ul2, logic=logic2)
        return slot


def serve(iface: XmlRpcInterface, host: str = "127.0.0.1",
          port: int = 0):
    """Start the XML-RPC server on a daemon thread; returns (server,
    port).  Mirrors XmlRpcInterface's abyss-server setup (:102)."""
    server = SimpleXMLRPCServer((host, port), allow_none=True,
                                logRequests=False)
    for name in ("stats", "advance", "local_lookup", "lookup", "put",
                 "get", "register", "resolve", "dump_dht",
                 "join_overlay"):
        server.register_function(getattr(iface, name), name)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


class XmlRpcFrontend:
    """XML-RPC front door onto a running OverlayDaemon.

    Unlike :class:`XmlRpcInterface` (which owns and steps its own solo
    state), this bridge mints every call through the daemon's
    thread-safe local queue (``OverlayDaemon.submit_local``) — the same
    per-tenant admission, batched injection, and sid routing the socket
    clients ride — and blocks the handler thread on the
    :class:`~oversim_tpu.service.daemon.LocalCall` event until the
    serving loop drains the response.  XML-RPC ``put(tenant, b, c)``
    therefore answers with the echo-transformed ``c`` exactly as an
    ``EXT_OUT`` frame would, and an over-bound tenant gets the same
    deterministic refusal as an ``EXT_NACK``.
    """

    def __init__(self, daemon, timeout_s: float = 30.0):
        self.daemon = daemon
        self.timeout_s = timeout_s
        self.timeouts = 0

    def _call(self, tenant: int, b: int, c: int) -> dict:
        call = self.daemon.submit_local(int(tenant), int(b), int(c))
        if not call.wait(self.timeout_s):
            self.timeouts += 1
            return {"status": "timeout", "sid": call.sid}
        out = {"status": call.status, "sid": call.sid}
        if call.status == "ok":
            out["b"] = int(call.resp_b)
            out["c"] = int(call.resp_c)
        return out

    def put(self, tenant: int, key: int, value: int) -> dict:
        """Mint one request on ``tenant``'s replica row (``b`` = key,
        ``c`` = value) and wait for its settled response."""
        return self._call(tenant, key, value)

    def get(self, tenant: int, key: int) -> dict:
        """Same window path as put; apps distinguish on payload."""
        return self._call(tenant, key, 0)

    def call(self, tenant: int, b: int = 0, c: int = 0) -> dict:
        """Raw EXT_IN with explicit payload words."""
        return self._call(tenant, b, c)

    def tenants(self) -> list:
        """Per-tenant accounting snapshot (the serving identity)."""
        return self.daemon.ingest.table.snapshot()

    def accounting(self) -> dict:
        acct = self.daemon.accounting()
        acct["rpc_timeouts"] = self.timeouts
        return acct


def serve_frontend(frontend: XmlRpcFrontend, host: str = "127.0.0.1",
                   port: int = 0):
    """Start the daemon-bridge XML-RPC server on a daemon thread;
    returns (server, port).  Threaded handlers only ever touch the
    submit queue and per-call events — never ingest state."""
    from socketserver import ThreadingMixIn

    class _Server(ThreadingMixIn, SimpleXMLRPCServer):
        daemon_threads = True

    server = _Server((host, port), allow_none=True, logRequests=False)
    for name in ("put", "get", "call", "tenants", "accounting"):
        server.register_function(getattr(frontend, name), name)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]
