"""XML-RPC front-end: drive a running simulation from real clients.

Rebuild of src/tier3/xmlrpcinterface/ (XmlRpcInterface.h:102-166 — an
XML-RPC server on the singlehost node exposing the KBR/DHT Common API
to external tools: local_lookup, lookup, register/resolve, put/get,
dump_dht).  The TPU equivalent serves the same surface over Python's
stdlib XML-RPC server, executing against a live Simulation + state:

  * ``local_lookup(key_hex, num)`` — closest READY nodes to the key
    from the global node table (the reference answers from the local
    routing table without network traffic; the engine's oracle is the
    natural equivalent — BaseOverlay::local_lookup semantics);
  * ``put(key_hex, value, ttl)`` / ``get(key_hex)`` — issue the real
    tier-1 DHT RPCs (common/wire.py DHT_PUT_CALL/DHT_GET_CALL — the
    same messages DHT.cc exchanges) from a host-injected call to each
    replica holder, then run the simulation until the responses land;
  * ``stats()`` — GlobalStatistics scalars (XmlRpcInterface has no
    direct equivalent; exposed because every external driver wants it);
  * ``advance(seconds)`` — step simulated time (the singlehost build
    advances in realtime instead; see gateway.RealtimeGateway).

Responses are observed in the message pool between ticks (the gateway
drain pattern): DHT_PUT_RES/DHT_GET_RES addressed to the injector slot
are collected and freed before the app layer would mis-consume them.
"""

from __future__ import annotations

import dataclasses
import threading
from xmlrpc.server import SimpleXMLRPCServer

import jax.numpy as jnp
import numpy as np

from oversim_tpu.common import wire
from oversim_tpu.core import keys as keys_mod
from oversim_tpu.engine import pool as pool_mod

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
NO_NODE = -1


class XmlRpcInterface:
    """Method container; also usable directly (no server) in tests."""

    def __init__(self, sim, state, injector_slot: int = 0):
        self.sim = sim
        self.state = state
        self.slot = injector_slot

    # ------------------------------------------------ helpers ----------
    def _key(self, key_hex: str):
        return keys_mod.from_int(int(key_hex, 16), self.sim.spec)

    def _closest_ready(self, key, num: int):
        st = self.state
        ready = np.asarray(st.alive) & np.asarray(
            self.sim.logic.ready_mask(st.logic))
        kt = np.asarray(st.node_keys, dtype=np.uint64)
        tgt = np.asarray(key, dtype=np.uint64)
        # big-endian lane compare == ring xor-free distance on the key
        # table; python bignum per node is fine host-side
        lanes = kt.shape[1]
        ints = np.zeros(kt.shape[0], object)
        for l in range(lanes):
            ints = ints * (1 << 32) + kt[:, l]
        t_int = 0
        for l in range(lanes):
            t_int = (t_int << 32) + int(tgt[l])
        bits = self.sim.spec.bits
        mod = 1 << bits
        dist = np.array([min((int(i) - t_int) % mod,
                             (t_int - int(i)) % mod) for i in ints],
                        object)
        dist[~ready] = mod
        order = np.argsort([int(d) for d in dist])
        return [int(i) for i in order[:num] if ready[i]]

    def _inject(self, dst: int, kind: int, key, a=0, b=0, stamp=0):
        s = self.state
        rmax = s.pool.nodes.shape[1]
        out = dict(
            t_deliver=jnp.asarray([s.t_now + 1], I64),
            src=jnp.asarray([self.slot], I32),
            dst=jnp.asarray([dst], I32),
            kind=jnp.asarray([kind], I32),
            key=jnp.asarray(key)[None, :],
            nonce=jnp.zeros((1,), I32),
            hops=jnp.zeros((1,), I32),
            a=jnp.asarray([a], I32), b=jnp.asarray([b], I32),
            c=jnp.zeros((1,), I32), d=jnp.zeros((1,), I32),
            nodes=jnp.full((1, rmax), NO_NODE, I32),
            size_b=jnp.asarray([wire.BASE_CALL_B + 28], I32),
            stamp=jnp.asarray([stamp], I64),
        )
        new_pool, _ = pool_mod.alloc(s.pool, out, jnp.asarray([True]))
        self.state = dataclasses.replace(s, pool=new_pool)

    def _collect(self, kinds, nonce, max_ticks: int = 400):
        """Step until responses with our nonce arrive (drained between
        ticks so the injector node's app never sees them)."""
        got = []
        for _ in range(max_ticks):
            self.state = self.sim.step(self.state)
            pool = self.state.pool
            valid = np.asarray(pool.valid)
            kind = np.asarray(pool.kind)
            dst = np.asarray(pool.dst)
            b = np.asarray(pool.b)
            hits = np.nonzero(valid & np.isin(kind, kinds) &
                              (dst == self.slot) & (b == nonce))[0]
            if len(hits):
                a = np.asarray(pool.a)
                for i in hits:
                    got.append((int(kind[i]), int(a[i])))
                mask = jnp.zeros(pool.valid.shape, bool).at[
                    jnp.asarray(hits, I32)].set(True)
                self.state = dataclasses.replace(
                    self.state, pool=pool_mod.free(pool, mask))
                return got
        return got

    # ------------------------------------------------ RPC surface ------
    def stats(self):
        out = self.sim.summary(self.state)
        clean = {}
        for k, v in out.items():
            if isinstance(v, dict):
                clean[k] = {kk: float(vv) for kk, vv in v.items()}
            elif isinstance(v, (list, tuple)):
                clean[k] = [float(x) for x in v]
            else:
                clean[k] = float(v)
        return clean

    def advance(self, seconds: float):
        t = (int(self.state.t_now) / NS) + float(seconds)
        self.state = self.sim.run_until(self.state, t)
        return int(self.state.t_now)

    def local_lookup(self, key_hex: str, num: int = 4):
        """Closest READY nodes (XmlRpcInterface::localLookup)."""
        return self._closest_ready(self._key(key_hex), num)

    def put(self, key_hex: str, value: int, ttl: float = 300.0):
        """DHT put: DHTPutCall to each replica holder; returns the
        number of acks (XmlRpcInterface::put → DHTputCAPI)."""
        key = self._key(key_hex)
        nrep = getattr(getattr(self.sim.logic, "app", None), "p",
                       None)
        num = nrep.num_replica if nrep is not None and hasattr(
            nrep, "num_replica") else 4
        holders = self._closest_ready(key, num)
        nonce = (int(self.state.t_now) // 1000) % (2**30) + 7
        expire = int(self.state.t_now) + int(ttl * NS)
        for h in holders:
            self._inject(h, wire.DHT_PUT_CALL, key, a=int(value),
                         b=nonce, stamp=expire)
        acks = self._collect([int(wire.DHT_PUT_RES)], nonce)
        return len(acks)

    def get(self, key_hex: str):
        """DHT get: DHTGetCall to the closest holder; returns the value
        id or -1 (XmlRpcInterface::get → DHTgetCAPI)."""
        key = self._key(key_hex)
        holders = self._closest_ready(key, 1)
        if not holders:
            return -1
        nonce = (int(self.state.t_now) // 1000) % (2**30) + 13
        self._inject(holders[0], wire.DHT_GET_CALL, key, b=nonce)
        got = self._collect([int(wire.DHT_GET_RES)], nonce)
        return got[0][1] if got else -1


def serve(iface: XmlRpcInterface, host: str = "127.0.0.1",
          port: int = 0):
    """Start the XML-RPC server on a daemon thread; returns (server,
    port).  Mirrors XmlRpcInterface's abyss-server setup (:102)."""
    server = SimpleXMLRPCServer((host, port), allow_none=True,
                                logRequests=False)
    for name in ("stats", "advance", "local_lookup", "put", "get"):
        server.register_function(getattr(iface, name), name)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]
