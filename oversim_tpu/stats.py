"""Global statistics collection with measurement-phase gating.

TPU-native equivalent of the reference's ``GlobalStatistics`` singleton
(src/common/GlobalStatistics.{h,cc}): named StdDev accumulators
(``addStdDev`` :97), histograms (:103) and the measurement gating that only
records after init + transition phases finish (``startMeasuring`` :113-118,
RECORD_STATS macro GlobalStatistics.h:35-39).  Instead of per-call mutexed
accumulators, per-node handler code emits (value, mask) event arrays and
the engine folds them in with masked reductions each tick.

Scalar accumulators keep (n, sum, sumsq, min, max) so finish() can report
name.mean/.stddev/.min/.max exactly like GlobalStatistics::finish
(GlobalStatistics.cc:107-145).
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp

F32 = jnp.float32
F64 = jnp.float64  # accumulators: f32 would silently drop increments >2^24
I64 = jnp.int64


@dataclasses.dataclass(frozen=True)
class StatSpec:
    """Static declaration of a simulation's metric namespace."""

    scalars: tuple = ()            # names of StdDev-style accumulators
    hists: tuple = ()              # (name, num_bins) pairs
    counters: tuple = ()           # monotonically increasing counts


def init_stats(spec: StatSpec) -> dict:
    s = {}
    for name in spec.scalars:
        s["s:" + name] = jnp.zeros((5,), F64).at[3].set(jnp.inf).at[4].set(-jnp.inf)
    for name, bins in spec.hists:
        s["h:" + name] = jnp.zeros((bins,), I64)
    for name in spec.counters:
        s["c:" + name] = jnp.zeros((), I64)
    return s


def record(stats: dict, events: dict, gate) -> dict:
    """Fold one tick's events into the accumulators.

    ``events`` maps "s:name" -> (values, mask), "h:name" -> (bin_idx, mask),
    "c:name" -> count; ``gate`` is the measurement-phase flag (scalar bool).
    """
    out = dict(stats)
    for key, ev in events.items():
        if key.startswith("s:"):
            vals, mask = ev
            vals = vals.astype(F64)
            m = (mask & gate).astype(F64)
            acc = out[key]
            n = jnp.sum(m)
            out[key] = jnp.stack([
                acc[0] + n,
                acc[1] + jnp.sum(vals * m),
                acc[2] + jnp.sum(vals * vals * m),
                jnp.minimum(acc[3], jnp.min(jnp.where(m > 0, vals, jnp.inf))),
                jnp.maximum(acc[4], jnp.max(jnp.where(m > 0, vals, -jnp.inf))),
            ])
        elif key.startswith("h:"):
            idx, mask = ev
            acc = out[key]
            bins = acc.shape[0]
            idx = jnp.clip(idx, 0, bins - 1).ravel()
            add = (mask & gate).astype(I64).ravel()
            out[key] = acc.at[idx].add(add)
        elif key.startswith("c:"):
            out[key] = out[key] + jnp.sum(jnp.asarray(ev, I64)) * gate.astype(I64)
        elif key.startswith("g:"):
            pass  # logic-global update request, consumed by post_step
        else:
            raise KeyError(f"unknown stat class: {key}")
    return out


def summarize(stats: dict) -> dict:
    """Host-side: accumulators -> {name: {mean, stddev, min, max, count}} /
    histograms -> list / counters -> int (GlobalStatistics::finish style)."""
    out = {}
    for key, val in stats.items():
        import numpy as np
        v = np.asarray(val)
        name = key[2:]
        if key.startswith("s:"):
            n, s, s2 = float(v[0]), float(v[1]), float(v[2])
            mean = s / n if n else math.nan
            var = max(s2 / n - mean * mean, 0.0) if n else math.nan
            out[name] = {
                "count": int(n), "mean": mean, "stddev": math.sqrt(var) if n else math.nan,
                "min": float(v[3]) if n else math.nan,
                "max": float(v[4]) if n else math.nan,
            }
        elif key.startswith("h:"):
            out[name] = v.tolist()
        else:
            out[name] = int(v)
    return out
