"""Global statistics collection with measurement-phase gating.

TPU-native equivalent of the reference's ``GlobalStatistics`` singleton
(src/common/GlobalStatistics.{h,cc}): named StdDev accumulators
(``addStdDev`` :97), histograms (:103) and the measurement gating that only
records after init + transition phases finish (``startMeasuring`` :113-118,
RECORD_STATS macro GlobalStatistics.h:35-39).  Instead of per-call mutexed
accumulators, per-node handler code emits (value, mask) event arrays and
the engine folds them in with masked reductions each tick.

Scalar accumulators keep (n, sum, sumsq, min, max) so finish() can report
name.mean/.stddev/.min/.max exactly like GlobalStatistics::finish
(GlobalStatistics.cc:107-145).
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp

F32 = jnp.float32
F64 = jnp.float64  # accumulators: f32 would silently drop increments >2^24
I64 = jnp.int64


@dataclasses.dataclass(frozen=True)
class StatSpec:
    """Static declaration of a simulation's metric namespace."""

    scalars: tuple = ()            # names of StdDev-style accumulators
    hists: tuple = ()              # (name, num_bins) pairs
    counters: tuple = ()           # monotonically increasing counts


def init_stats(spec: StatSpec) -> dict:
    s = {}
    for name in spec.scalars:
        s["s:" + name] = jnp.zeros((5,), F64).at[3].set(jnp.inf).at[4].set(-jnp.inf)
    for name, bins in spec.hists:
        s["h:" + name] = jnp.zeros((bins,), I64)
    for name in spec.counters:
        s["c:" + name] = jnp.zeros((), I64)
    return s


def record(stats: dict, events: dict, gate) -> dict:
    """Fold one tick's events into the accumulators.

    ``events`` maps "s:name" -> (values, mask), "h:name" -> (bin_idx, mask),
    "c:name" -> count; ``gate`` is the measurement-phase flag (scalar bool).
    """
    out = dict(stats)
    for key, ev in events.items():
        if key.startswith("s:"):
            vals, mask = ev
            vals = vals.astype(F64)
            m = (mask & gate).astype(F64)
            acc = out[key]
            n = jnp.sum(m)
            out[key] = jnp.stack([
                acc[0] + n,
                acc[1] + jnp.sum(vals * m),
                acc[2] + jnp.sum(vals * vals * m),
                jnp.minimum(acc[3], jnp.min(jnp.where(m > 0, vals, jnp.inf))),
                jnp.maximum(acc[4], jnp.max(jnp.where(m > 0, vals, -jnp.inf))),
            ])
        elif key.startswith("h:"):
            idx, mask = ev
            acc = out[key]
            bins = acc.shape[0]
            idx = jnp.clip(idx, 0, bins - 1).ravel()
            add = (mask & gate).astype(I64).ravel()
            out[key] = acc.at[idx].add(add)
        elif key.startswith("c:"):
            out[key] = out[key] + jnp.sum(jnp.asarray(ev, I64)) * gate.astype(I64)
        elif key.startswith("g:"):
            pass  # logic-global update request, consumed by post_step
        else:
            raise KeyError(f"unknown stat class: {key}")
    return out


def summarize(stats: dict) -> dict:
    """Host-side: accumulators -> {name: {mean, stddev, min, max, count}} /
    histograms -> list / counters -> int (GlobalStatistics::finish style)."""
    out = {}
    for key, val in stats.items():
        import numpy as np
        v = np.asarray(val)
        name = key[2:]
        if key.startswith("s:"):
            n, s, s2 = float(v[0]), float(v[1]), float(v[2])
            mean = s / n if n else math.nan
            var = max(s2 / n - mean * mean, 0.0) if n else math.nan
            out[name] = {
                "count": int(n), "mean": mean, "stddev": math.sqrt(var) if n else math.nan,
                "min": float(v[3]) if n else math.nan,
                "max": float(v[4]) if n else math.nan,
            }
        elif key.startswith("h:"):
            out[name] = v.tolist()
        else:
            out[name] = int(v)
    return out


# -- cross-replica ensemble layer (oversim_tpu/campaign/) -------------------
#
# A campaign state stacks every accumulator with a leading replica axis:
# "s:name" -> [S, 5], "h:name" -> [S, bins], "c:name" -> [S].  The reduce
# runs ON DEVICE (one jit, one device_get of small [S]-shaped leaves);
# the CI half-widths (Student-t, no scipy dependency) attach host-side in
# ``ensemble_summary``.  This is the TPU-native analogue of scripting
# ``./OverSim -r N`` and averaging the N scalar files by hand.

def ensemble_reduce(stats: dict) -> dict:
    """Device-side: stacked accumulators -> per-replica + cross-replica
    moments.  Returns a dict of small jnp arrays, safe to device_get.

    Scalars ("s:") -> {per_mean[S], per_stddev[S], per_count[S],
    mean, stddev, sem, k} where the cross-replica moments are over the
    k replicas that recorded data (sample stddev, /(k-1)).
    Histograms ("h:") -> per-replica probability mass functions and
    their cross-replica mean/stddev/sem per bin (+ raw count sums).
    Counters ("c:") -> per-replica values + cross-replica mean/stddev.
    """
    out = {}
    for key, acc in stats.items():
        if key.startswith("s:"):
            n = acc[:, 0]                                    # [S]
            has = n > 0
            safe_n = jnp.maximum(n, 1.0)
            per_mean = acc[:, 1] / safe_n
            per_var = jnp.maximum(acc[:, 2] / safe_n - per_mean * per_mean,
                                  0.0)
            per_stddev = jnp.sqrt(per_var)
            k = jnp.sum(has.astype(F64))
            safe_k = jnp.maximum(k, 1.0)
            mean = jnp.sum(jnp.where(has, per_mean, 0.0)) / safe_k
            dev2 = jnp.where(has, (per_mean - mean) ** 2, 0.0)
            var = jnp.sum(dev2) / jnp.maximum(k - 1.0, 1.0)
            stddev = jnp.sqrt(var)
            sem = stddev / jnp.sqrt(safe_k)
            out[key] = dict(
                per_count=n, per_mean=per_mean,
                per_stddev=per_stddev, mean=mean, stddev=stddev,
                sem=sem, k=k)
        elif key.startswith("h:"):
            counts = acc.astype(F64)                         # [S, B]
            tot = jnp.sum(counts, axis=1, keepdims=True)     # [S, 1]
            has = tot[:, 0] > 0
            pmf = counts / jnp.maximum(tot, 1.0)             # [S, B]
            k = jnp.sum(has.astype(F64))
            safe_k = jnp.maximum(k, 1.0)
            mean = jnp.sum(jnp.where(has[:, None], pmf, 0.0),
                           axis=0) / safe_k                  # [B]
            dev2 = jnp.where(has[:, None], (pmf - mean[None, :]) ** 2, 0.0)
            var = jnp.sum(dev2, axis=0) / jnp.maximum(k - 1.0, 1.0)
            stddev = jnp.sqrt(var)
            sem = stddev / jnp.sqrt(safe_k)
            out[key] = dict(
                per_counts=acc, per_total=tot[:, 0],
                per_pmf=pmf, mean=mean, stddev=stddev, sem=sem, k=k,
                total=jnp.sum(acc, axis=0))
        elif key.startswith("c:"):
            v = acc.astype(F64)                              # [S]
            s = v.shape[0]
            mean = jnp.mean(v)
            var = (jnp.sum((v - mean) ** 2) / (s - 1.0)) if s > 1 \
                else jnp.zeros(())
            out[key] = dict(
                per_replica=acc, total=jnp.sum(acc),
                mean=mean, stddev=jnp.sqrt(var),
                sem=jnp.sqrt(var) / math.sqrt(s))
    return out


# two-sided Student-t critical values, t_{df, 1-alpha/2} — enough rows
# for any sane replica count; falls back to the normal quantile past 30
_T_TABLE = {
    0.95: (12.706, 4.303, 3.182, 2.776, 2.571, 2.447, 2.365, 2.306, 2.262,
           2.228, 2.201, 2.179, 2.160, 2.145, 2.131, 2.120, 2.110, 2.101,
           2.093, 2.086, 2.080, 2.074, 2.069, 2.064, 2.060, 2.056, 2.052,
           2.048, 2.045, 2.042),
    0.99: (63.657, 9.925, 5.841, 4.604, 4.032, 3.707, 3.499, 3.355, 3.250,
           3.169, 3.106, 3.055, 3.012, 2.977, 2.947, 2.921, 2.898, 2.878,
           2.861, 2.845, 2.831, 2.819, 2.807, 2.797, 2.787, 2.779, 2.771,
           2.763, 2.756, 2.750),
}
_T_NORMAL = {0.95: 1.960, 0.99: 2.576}


def t_critical(df: int, confidence: float = 0.95) -> float:
    """Two-sided Student-t critical value (table lookup, no scipy)."""
    if confidence not in _T_TABLE:
        raise ValueError(f"confidence must be one of {sorted(_T_TABLE)}")
    if df < 1:
        return math.nan
    tab = _T_TABLE[confidence]
    return tab[df - 1] if df <= len(tab) else _T_NORMAL[confidence]


def series_summary(values, confidence: float = 0.95) -> dict:
    """Cross-replica CI bands for a stacked time series (host-side).

    ``values`` is a ``[S, K]`` array: S replica series over K aligned
    sample points (the telemetry plane's ring samples — replicas share
    the tick-based sampling cadence, see oversim_tpu/telemetry.py).
    NaN entries (e.g. a scalar mean before its first event) are
    excluded per sample point.  Returns JSON-ready lists — {kind, k,
    mean[K], stddev[K], sem[K], ci[K], confidence} with the Student-t
    half-width over the replicas that carry data at each point (None
    where fewer than two do)."""
    import numpy as np

    v = np.asarray(values, float)
    if v.ndim != 2:
        raise ValueError(f"series_summary wants [S, K], got {v.shape}")
    s, _ = v.shape
    has = ~np.isnan(v)
    k = has.sum(axis=0)                                   # [K]
    safe_k = np.maximum(k, 1)
    mean = np.where(k > 0, np.nansum(v, axis=0) / safe_k, np.nan)
    dev2 = np.where(has, (v - mean[None, :]) ** 2, 0.0)
    var = dev2.sum(axis=0) / np.maximum(k - 1, 1)
    stddev = np.sqrt(var)
    sem = stddev / np.sqrt(safe_k)
    t = np.array([t_critical(int(ki) - 1, confidence) if ki > 1
                  else math.nan for ki in k])
    ci = t * sem
    clean = lambda a: [None if x != x else float(x)  # noqa: E731
                       for x in np.asarray(a, float)]
    return {"kind": "series", "replicas": s, "k": k.astype(int).tolist(),
            "mean": clean(mean), "stddev": clean(stddev),
            "sem": clean(sem), "ci": clean(ci), "confidence": confidence}


def ensemble_summary(reduced: dict, confidence: float = 0.95) -> dict:
    """Host-side: attach Student-t CI half-widths (ci = t_{k-1} * sem)
    to a (device_get of a) ``ensemble_reduce`` result and convert leaves
    to plain python.  Schema per metric — scalar: {kind, k, mean, stddev,
    sem, ci, confidence, per_replica: {count, mean, stddev}[S]};
    hist: the same per-bin (lists of length B) plus raw counts;
    counter: {kind, total, mean, stddev, sem, ci, per_replica[S]}."""
    import numpy as np

    out = {}
    for key, r in reduced.items():
        name = key[2:]
        if key.startswith("s:"):
            k = int(np.asarray(r["k"]))
            t = t_critical(k - 1, confidence) if k > 1 else math.nan
            sem = float(np.asarray(r["sem"]))
            out[name] = {
                "kind": "scalar", "k": k,
                "mean": float(np.asarray(r["mean"])),
                "stddev": float(np.asarray(r["stddev"])),
                "sem": sem,
                "ci": t * sem if k > 1 else math.nan,
                "confidence": confidence,
                "per_replica": {
                    "count": np.asarray(r["per_count"]).astype(int).tolist(),
                    "mean": np.asarray(r["per_mean"]).tolist(),
                    "stddev": np.asarray(r["per_stddev"]).tolist(),
                },
            }
        elif key.startswith("h:"):
            k = int(np.asarray(r["k"]))
            t = t_critical(k - 1, confidence) if k > 1 else math.nan
            sem = np.asarray(r["sem"])
            ci = (t * sem).tolist() if k > 1 \
                else [math.nan] * sem.shape[0]
            out[name] = {
                "kind": "hist", "k": k,
                "mean": np.asarray(r["mean"]).tolist(),
                "stddev": np.asarray(r["stddev"]).tolist(),
                "sem": sem.tolist(),
                "ci": ci,
                "confidence": confidence,
                "total": np.asarray(r["total"]).astype(int).tolist(),
                "per_replica": {
                    "counts": np.asarray(r["per_counts"]).astype(int).tolist(),
                    "total": np.asarray(r["per_total"]).astype(int).tolist(),
                },
            }
        else:
            pr = np.asarray(r["per_replica"])
            s = pr.shape[0]
            t = t_critical(s - 1, confidence) if s > 1 else math.nan
            sem = float(np.asarray(r["sem"]))
            out[name] = {
                "kind": "counter",
                "total": int(np.asarray(r["total"])),
                "mean": float(np.asarray(r["mean"])),
                "stddev": float(np.asarray(r["stddev"])),
                "sem": sem,
                "ci": t * sem if s > 1 else math.nan,
                "confidence": confidence,
                "per_replica": pr.astype(int).tolist(),
            }
    return out
