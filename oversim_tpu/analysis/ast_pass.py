"""AST lint pass: host-hazard rules over the hot-path layers.

The compiled-graph contracts (hlo_pass.py) catch a regression AFTER it
reaches XLA; this pass catches the source patterns that cause them —
host numpy / ``.item()`` / ``float()`` / ``jax.device_get`` /
``time.time()`` inside the hot-path modules, ``lax.sort`` family calls
outside the allowlisted ``inbox_impl="sort"`` oracle, un-donated ``jit``
decorators on state-carrying functions, and silent host reads of
SimState leaves anywhere in the package.

Rule tiers
----------
* HOT tier (``oversim_tpu/engine``, ``overlay``, ``campaign``,
  ``service/loop.py``): every rule.  Host-side reporting functions that
  legitimately touch numpy/floats are tagged in-tree.
* WIDE tier (the rest of ``oversim_tpu``): only the rules that are
  hazards everywhere — ``.item()``, ``time.time()`` wall-clock reads,
  and ``device-sync`` (``float()``/``int()``/``np.asarray()`` directly
  over a SimState leaf attribute — an implicit device→host sync).

Suppressions
------------
``# analysis: allow(host-numpy, host-float)`` on the offending line
suppresses those rules for that line; on a ``def`` line it suppresses
them for the whole function body — host-side functions inside hot-path modules carry
one def-level marker each, so the allowlist is greppable in-tree
(``grep -rn 'analysis: allow'``).  An ``allow`` naming an unknown rule
is itself a finding (``bad-allow``) so stale markers can't rot.

Bytecode guards
---------------
``scan`` also walks the target trees for bytecode that could shadow
sources: legacy ``*.pyc`` files OUTSIDE ``__pycache__`` (importable in
place of a ``.py``), orphaned ``__pycache__/*.pyc`` whose source is
gone, and git-TRACKED bytecode (committed ``.pyc`` shadowed a source
edit once before — PR 1 removed one).
"""

from __future__ import annotations

import ast
import dataclasses
import re
import subprocess
from pathlib import Path

from oversim_tpu.analysis.findings import Finding

# -- rule registry -----------------------------------------------------------

RULES = {
    "host-numpy": "host numpy (np.*) in a hot-path module — traced code "
                  "must use jnp; host reporting needs an allow marker",
    "host-item": ".item() forces a device→host sync",
    "host-float": "float(...) in a hot-path module — a tracer here would "
                  "force a host sync; host-side math needs an allow marker",
    "host-device-get": "jax.device_get in a hot-path module — fetches "
                       "belong to the designated window-drain points",
    "wall-clock": "time.time() is not monotonic — use time.monotonic()/"
                  "perf_counter() for intervals and pacing",
    "sort-call": "lax/jnp sort-family call — the tick is pinned "
                 "zero-full-pool-sort; every sort site must be "
                 "explicitly allowlisted",
    "undonated-jit": "jit on a state-carrying function without "
                     "donate_argnums — every chunk round-trips the "
                     "state through fresh allocations",
    "device-sync": "float()/int()/np.asarray() directly over a SimState "
                   "leaf — an implicit device→host sync",
    "bad-allow": "allow marker names an unknown rule",
    "legacy-pyc": "*.pyc outside __pycache__ can shadow its source",
    "orphan-pyc": "__pycache__ bytecode whose source file is gone",
    "tracked-bytecode": "bytecode committed to git can shadow source edits",
    "untracked-pycache": "__pycache__ not git-ignored — stray bytecode "
                         "pollutes grep/status and is one `git add .` "
                         "from being committed",
    "obs-import": "oversim_tpu.obs import outside the obs package — the "
                  "live observability plane is host-runner-only "
                  "(scripts/, bench.py); in-package code takes "
                  "tracer/observer objects as duck-typed parameters",
}

HOT_RULES = ("host-numpy", "host-item", "host-float", "host-device-get",
             "wall-clock", "sort-call", "undonated-jit", "device-sync",
             "obs-import")
WIDE_RULES = ("host-item", "wall-clock", "device-sync", "obs-import")

# hot-path layers (ISSUE/ROADMAP: the modules whose compiled graphs the
# HLO contracts pin) — paths relative to the repo root
HOT_PATHS = ("oversim_tpu/engine", "oversim_tpu/overlay",
             "oversim_tpu/campaign", "oversim_tpu/service/loop.py")
WIDE_PATH = "oversim_tpu"

# SimState leaves whose direct host conversion is an implicit sync
STATE_LEAF_ATTRS = frozenset({
    "t_now", "tick", "alive", "node_keys", "pool", "stats", "counters",
    "telemetry", "churn", "malicious"})

_SORT_NAMES = frozenset({"sort", "argsort", "lexsort"})
_STATEISH_PARAMS = frozenset({"s", "cs", "state", "carry"})

_ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([^)]*)\)")


# -- suppression map ---------------------------------------------------------

def _parse_allows(src: str) -> dict:
    """line number -> set of rule names allowed on that line."""
    allows = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _ALLOW_RE.search(line)
        if m:
            allows[i] = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
    return allows


class _Suppressions:
    """Per-line allows + def-scope allows (marker on the ``def`` line
    covers the whole function body, nested defs included)."""

    def __init__(self, tree: ast.AST, allows: dict):
        self.line_allows = allows
        self.spans = []       # (first, last, rules)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the marker may sit on ANY signature line (multi-line
                # defs put it after the closing paren)
                sig_end = (node.body[0].lineno - 1 if node.body
                           else node.lineno)
                rules = set()
                for ln in range(node.lineno, sig_end + 1):
                    rules |= allows.get(ln, set())
                if rules:
                    self.spans.append(
                        (node.lineno, node.end_lineno, rules))

    def allowed(self, line: int, rule: str) -> bool:
        if rule in self.line_allows.get(line, ()):
            return True
        return any(a <= line <= b and rule in rules
                   for a, b, rules in self.spans)

    def bad_allows(self) -> list:
        return [(ln, r) for ln, rules in self.line_allows.items()
                for r in sorted(rules) if r not in RULES]


# -- the visitor -------------------------------------------------------------

def _base_name(node):
    """Leftmost Name id of an attribute chain (jax.lax.sort -> 'jax')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _mentions_state_leaf(node) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr in STATE_LEAF_ATTRS
               for n in ast.walk(node))


def _jit_decorator_kind(dec):
    """('jit'|'partial-jit'|None, has_donation) for a decorator node."""
    def is_jit(n):
        return ((isinstance(n, ast.Attribute) and n.attr == "jit")
                or (isinstance(n, ast.Name) and n.id == "jit"))

    if is_jit(dec):
        return "jit", False
    if isinstance(dec, ast.Call):
        if is_jit(dec.func):
            donated = any(kw.arg and kw.arg.startswith("donate")
                          for kw in dec.keywords)
            return "jit", donated
        if (isinstance(dec.func, ast.Name) and dec.func.id == "partial"
                and dec.args and is_jit(dec.args[0])):
            donated = any(kw.arg and kw.arg.startswith("donate")
                          for kw in dec.keywords)
            return "partial-jit", donated
    return None, False


class _Linter(ast.NodeVisitor):
    def __init__(self, rules, rel, sup):
        self.rules = frozenset(rules)
        self.rel = rel
        self.sup = sup
        self.findings = []
        self._seen = set()

    def _emit(self, node, rule, message, measured=None):
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 0)
        if self.sup.allowed(line, rule):
            return
        key = (line, rule)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(
            pass_name="ast", rule=rule, where=f"{self.rel}:{line}",
            message=message, measured=measured, limit="0 occurrences"))

    # imports ---------------------------------------------------------------
    def visit_Import(self, node):
        for alias in node.names:
            if alias.name.split(".")[0] == "numpy":
                self._emit(node, "host-numpy",
                           "imports numpy in a hot-path module")
            if alias.name.split(".")[:2] == ["oversim_tpu", "obs"]:
                self._emit(node, "obs-import",
                           f"imports {alias.name} inside the package")
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module and node.module.split(".")[0] == "numpy":
            self._emit(node, "host-numpy",
                       "imports from numpy in a hot-path module")
        if node.module:
            parts = node.module.split(".")
            if parts[:2] == ["oversim_tpu", "obs"]:
                self._emit(node, "obs-import",
                           f"imports from {node.module} inside the "
                           f"package")
            elif parts == ["oversim_tpu"] and any(
                    alias.name == "obs" for alias in node.names):
                self._emit(node, "obs-import",
                           "imports obs from oversim_tpu inside the "
                           "package")
        self.generic_visit(node)

    # attribute / call rules ------------------------------------------------
    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "np":
            self._emit(node, "host-numpy", f"np.{node.attr} host-numpy use")
        if node.attr == "device_get":
            self._emit(node, "host-device-get", "jax.device_get call site")
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Name):
            if f.id == "float":
                self._emit(node, "host-float", "float(...) call")
            if f.id in ("float", "int") and any(
                    _mentions_state_leaf(a) for a in node.args):
                self._emit(node, "device-sync",
                           f"{f.id}(...) over a SimState leaf")
        elif isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                self._emit(node, "host-item", ".item() call")
            if (f.attr == "time" and isinstance(f.value, ast.Name)
                    and f.value.id == "time"):
                self._emit(node, "wall-clock", "time.time() call")
            if f.attr in _SORT_NAMES:
                base = _base_name(f.value)
                is_lax = (isinstance(f.value, ast.Attribute)
                          and f.value.attr == "lax")
                if base in ("jnp", "lax", "jax", "np") or is_lax:
                    self._emit(node, "sort-call",
                               f"{ast.unparse(f)} call")
            if (f.attr in ("asarray", "array")
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "np"
                    and any(_mentions_state_leaf(a) for a in node.args)):
                self._emit(node, "device-sync",
                           f"np.{f.attr}(...) over a SimState leaf")
        self.generic_visit(node)

    # donation rule ---------------------------------------------------------
    def _first_real_param(self, node):
        args = [a.arg for a in node.args.args if a.arg not in ("self",
                                                               "cls")]
        return args[0] if args else None

    def visit_FunctionDef(self, node, _async=False):
        for dec in node.decorator_list:
            kind, donated = _jit_decorator_kind(dec)
            if kind and not donated:
                first = self._first_real_param(node)
                if first in _STATEISH_PARAMS:
                    self._emit(
                        dec, "undonated-jit",
                        f"jit of {node.name}({first}, ...) without "
                        f"donate_argnums — the state buffer is copied "
                        f"every call")
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


# -- file / tree scanning ----------------------------------------------------

def lint_source(src: str, rel: str, rules=HOT_RULES) -> list:
    """Lint one module's source text; returns Finding rows."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(pass_name="ast", rule="syntax",
                        where=f"{rel}:{e.lineno or 0}",
                        message=f"does not parse: {e.msg}")]
    sup = _Suppressions(tree, _parse_allows(src))
    linter = _Linter(rules, rel, sup)
    linter.visit(tree)
    for line, rule in sup.bad_allows():
        linter.findings.append(Finding(
            pass_name="ast", rule="bad-allow", where=f"{rel}:{line}",
            message=f"allow({rule}) names an unknown rule "
                    f"(known: {', '.join(sorted(RULES))})"))
    return linter.findings


def _is_hot(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return any(rel == p or rel.startswith(p.rstrip("/") + "/")
               for p in HOT_PATHS)


def iter_targets(root: Path):
    """(path, rel, rules) for every scanned module under ``root``."""
    for path in sorted((root / WIDE_PATH).rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = str(path.relative_to(root))
        rules = HOT_RULES if _is_hot(rel) else WIDE_RULES
        if rel.replace("\\", "/").startswith("oversim_tpu/obs/"):
            # the plane may of course import itself
            rules = tuple(r for r in rules if r != "obs-import")
        yield path, rel, rules


def bytecode_findings(root: Path,
                      trees=("oversim_tpu", "scripts", "tests")) -> list:
    """Stale/shadowing-bytecode + __pycache__-hygiene guards over the
    source trees — the runner entry points under ``scripts/`` are
    covered the same as the package (a stale scripts/__pycache__ once
    fed binary .pyc matches into every repo grep)."""
    out = []
    pycache_dirs = []
    for tree in trees:
        base = root / tree
        if not base.is_dir():
            continue
        pycache_dirs.extend(sorted(
            p for p in base.rglob("__pycache__") if p.is_dir()))
        for pyc in sorted(base.rglob("*.pyc")):
            rel = str(pyc.relative_to(root))
            if "__pycache__" not in pyc.parts:
                out.append(Finding(
                    pass_name="ast", rule="legacy-pyc", where=rel,
                    message="bytecode outside __pycache__ shadows its "
                            "source on import — delete it"))
                continue
            src_name = pyc.name.split(".")[0] + ".py"
            if not (pyc.parent.parent / src_name).exists():
                out.append(Finding(
                    pass_name="ast", rule="orphan-pyc", where=rel,
                    message=f"orphaned bytecode: {src_name} no longer "
                            f"exists next to its __pycache__"))
    try:
        r = subprocess.run(
            ["git", "ls-files", "*.pyc", "**/__pycache__/*"],
            capture_output=True, text=True, timeout=15, cwd=root)
        tracked = [ln for ln in r.stdout.splitlines() if ln.strip()]
    except (OSError, subprocess.TimeoutExpired):
        tracked = []
    for rel in tracked:
        out.append(Finding(
            pass_name="ast", rule="tracked-bytecode", where=rel,
            message="bytecode is committed to git — `git rm --cached` "
                    "it and keep __pycache__/ in .gitignore"))
    if pycache_dirs:
        rels = [str(p.relative_to(root)) for p in pycache_dirs]
        try:
            # rc 0 = some ignored, 1 = none ignored; 128 (not a git
            # work tree) skips the rule rather than spamming findings
            r = subprocess.run(["git", "check-ignore", *rels],
                               capture_output=True, text=True,
                               timeout=15, cwd=root)
            if r.returncode in (0, 1):
                ignored = set(r.stdout.splitlines())
                for rel in rels:
                    if rel not in ignored:
                        out.append(Finding(
                            pass_name="ast", rule="untracked-pycache",
                            where=rel,
                            message="__pycache__ is not git-ignored — "
                                    "add `__pycache__/` to .gitignore "
                                    "so bytecode never reaches grep or "
                                    "a commit"))
        except (OSError, subprocess.TimeoutExpired):
            pass
    return out


def run(root, *, include_bytecode_guards: bool = True):
    """The whole AST pass: (findings, summary-dict)."""
    root = Path(root)
    findings = []
    files = 0
    for path, rel, rules in iter_targets(root):
        files += 1
        findings.extend(lint_source(
            path.read_text(encoding="utf-8"), rel, rules))
    if include_bytecode_guards:
        findings.extend(bytecode_findings(root))
    summary = {"files_scanned": files,
               "rules": {"hot": list(HOT_RULES), "wide": list(WIDE_RULES)},
               "findings": len(findings)}
    return findings, summary
