"""The declarative graph-contract registry.

Every compiled entry point of the simulator — the solo tick, the fused
``run_chunk``, the device-resident ``run_until_device`` while-loop, the
vmapped replica-sharded campaign tick, the telemetry-enabled tick, and
the service window — registers ONE :class:`EntryPoint` here: how to
build it (:class:`EntryContext` → jitted fn + fresh-args factory) and
what its compiled graph is allowed to look like (:class:`GraphContract`)
— op budgets, collective allowlist, host-transfer pin, donation
requirement, dtype allowlist, plus the trace-time limits (recompiles /
implicit host syncs) enforced by trace_pass.py.

``scripts/analyze.py --all`` walks the registry; a new subsystem makes
its graph a checked contract by calling :func:`register_entry` (or
adding to :data:`DEFAULT_ENTRIES`) instead of hand-extending a script.

The budgets consolidate what used to be three ad-hoc
``scripts/hlo_breakdown.py`` modes: ``--budget`` → ``solo_tick``,
``--campaign`` → ``campaign_tick``, ``--telemetry`` → the
``telemetry_tick`` delta contract (hlo_breakdown's modes are now shims
over this registry).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

# result dtypes a compiled entry may contain.  x64 is globally enabled:
# time/keys/accumulators are s64/f64, rng bits u32, masks pred.  Reduced
# precision (bf16/f16/f8*) anywhere in the tick means an accumulator
# silently lost precision — disallowed until a PR introduces it
# deliberately (with its own contract revision).
DEFAULT_DTYPES = frozenset({
    "pred", "token",
    "s8", "s16", "s32", "s64",
    "u8", "u16", "u32", "u64",
    "f32", "f64",
})

# measured at -O0/inbox=8: kademlia 151 / chord 123 scatters per tick
# (mostly small per-node logic scatters; engine share 8 + 2*inbox) — 200
# catches gross regressions while zero-full-pool-sorts stays the sharp pin
DEFAULT_MAX_SCATTERS = 200


@dataclasses.dataclass(frozen=True)
class GraphContract:
    """What one compiled entry point's optimized HLO may contain."""

    max_full_pool_sorts: int = 0
    max_sorts: int | None = None          # total sorts; None = unpinned
    max_scatters: int = DEFAULT_MAX_SCATTERS
    # collective census tokens allowed in the graph ("all-gather",
    # "all-reduce:min", ...).  Enforced only when collectives_enforced —
    # node-sharded single-replica steps legitimately carry collectives
    # whose census is mesh-dependent.
    allowed_collectives: frozenset = frozenset()
    collectives_enforced: bool = True
    # custom-call allowlist (hlo_text.custom_call_census targets).  The
    # kernel plane's entries enforce it: on TPU the fused Pallas
    # kernels appear as Mosaic ``tpu_custom_call`` ops and NOTHING else
    # may — under interpret mode (CPU CI) the census is empty, so the
    # allowlist is an upper bound both backends satisfy.  Off by
    # default: pre-kernel entries never audited their custom-calls.
    allowed_custom_calls: frozenset = frozenset()
    custom_calls_enforced: bool = False
    max_host_transfers: int = 0
    # donation: the optimized module header must carry input→output
    # buffer aliases (may-/must-alias) — dropped donation round-trips
    # the full state through fresh allocations every dispatch
    require_donation: bool = False
    min_donated_leaves: int = 1
    dtype_allowlist: frozenset = DEFAULT_DTYPES
    # trace-time limits (trace_pass.py): the second same-shape call may
    # not recompile, and no tracer/array may be host-synced
    # (__bool__/__index__/__int__/__float__/__array__/device_get)
    # inside the harnessed calls
    max_recompiles: int = 0
    max_host_syncs: int = 0
    max_device_gets: int = 0
    check_leaks: bool = True
    # compile-seconds budget (hlo_pass times lower+compile per entry):
    # None defers to the analyzer-wide --compile-budget ceiling; a float
    # pins THIS entry tighter.  Wall-clock, so budgets must carry slack
    # for a loaded CI box — the point is catching 2x compile blowups
    # (the unrolled-on_msg class), not 10% noise.
    max_compile_seconds: float | None = None


@dataclasses.dataclass(frozen=True)
class DeltaContract:
    """A contract on the DIFF between two entries' op counts.

    ``telemetry_tick`` pins its cost relative to ``solo_tick``: zero
    full-pool sorts, no new sorts anywhere, a bounded scatter delta (one
    gated ``mode="drop"`` scatter per ring buffer), zero new
    collectives (replicated [W] rings must not create traffic)."""

    base: str                           # name of the baseline entry
    max_full_pool_sorts: int = 0
    max_sort_delta: int = 0
    max_scatter_delta: int = 64
    max_collective_delta: int = 0
    # wide-gather delta (hlo_text.gather_counts: gathers whose result
    # keeps a full-width leading dim — N or P).  None = recorded in the
    # verdict JSON but unenforced; a NEGATIVE bound is a REQUIRED
    # reduction (sparse_tick must actually drop the [N, R, W] payload
    # gather, not just add compaction on top of it).
    max_wide_gather_delta: int | None = None


@dataclasses.dataclass(frozen=True)
class EntryContext:
    """Build-time knobs shared by every entry (mirrors the historical
    hlo_breakdown CLI positionals).  ``fast`` shrinks sizes for the
    tier-1 gate; op counts are size-independent, so the contracts hold
    at any n."""

    n: int = 256
    overlay: str = "kademlia"
    window: float = 0.2
    inbox: int = 8
    pool_factor: int = 4
    replicas: int = 4
    tel_ticks: int = 4
    chunk: int = 4
    fast: bool = False

    @classmethod
    def make(cls, *, fast: bool = False, **kw):
        if fast:
            kw.setdefault("n", 64)
            kw.setdefault("replicas", 2)
        return cls(fast=fast, **kw)


@dataclasses.dataclass
class EntryBuild:
    """What :attr:`EntryPoint.build` returns: a jitted callable plus a
    fresh-argument factory (donated entries consume their state — every
    call needs fresh buffers), and the pool dimension for full-pool-sort
    classification."""

    fn: Callable                        # jit wrapper (.lower works)
    make_args: Callable[[], tuple]      # fresh args per call
    pool_dim: int
    info: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    name: str
    doc: str
    contract: GraphContract
    build: Callable[[EntryContext], EntryBuild]
    delta: DeltaContract | None = None


# ---------------------------------------------------------------------------
# builders (import jax lazily — the registry itself stays import-safe)
# ---------------------------------------------------------------------------

def build_sim(ctx: EntryContext, *, inbox_impl: str = "scatter",
              telemetry_ticks: int = 0, ext_hold_slot: int = -1,
              tick_impl: str = "dense", active_cap: int = 0):
    """The bench-shaped Simulation every entry compiles (KbrTestApp over
    chord/kademlia, churn off — the same construction the historical
    hlo_breakdown modes used)."""
    from oversim_tpu import churn as churn_mod
    from oversim_tpu import telemetry as telemetry_mod
    from oversim_tpu.apps import kbrtest
    from oversim_tpu.apps.kbrtest import KbrTestApp
    from oversim_tpu.common import lookup as lk_mod
    from oversim_tpu.engine import sim as sim_mod

    app = KbrTestApp(kbrtest.KbrTestParams(test_interval=0.2))
    if ctx.overlay == "chord":
        from oversim_tpu.overlay.chord import ChordLogic
        logic = ChordLogic(app=app, lcfg=lk_mod.LookupConfig(slots=8))
    else:
        from oversim_tpu.overlay.kademlia import KademliaLogic
        logic = KademliaLogic(app=app,
                              lcfg=lk_mod.LookupConfig(slots=8, merge=True))
    cp = churn_mod.ChurnParams(model="none", target_num=ctx.n,
                               init_interval=20.0 / ctx.n,
                               init_deviation=2.0 / ctx.n)
    ep = sim_mod.EngineParams(
        window=ctx.window, inbox_slots=ctx.inbox,
        pool_factor=ctx.pool_factor, inbox_impl=inbox_impl,
        ext_hold_slot=ext_hold_slot, tick_impl=tick_impl,
        active_cap=active_cap,
        telemetry=telemetry_mod.TelemetryParams(
            sample_ticks=telemetry_ticks))
    return sim_mod.Simulation(logic, cp, engine_params=ep)


def _build_solo_tick(ctx):
    import jax
    sim = build_sim(ctx)
    fn = jax.jit(sim.step)
    s0 = sim.init(seed=7)
    return EntryBuild(fn=fn, make_args=lambda: (s0,),
                      pool_dim=sim.ep.pool_factor * ctx.n,
                      info={"n": ctx.n, "overlay": ctx.overlay})


def _build_solo_chunk(ctx):
    sim = build_sim(ctx)
    # run_chunk donates s: every call needs freshly initialized buffers.
    # `self` is a static argname — reuse ONE sim instance or the cache
    # keys differ and the recompile pin trips on its own harness.  Use
    # the UNBOUND class-level jit (type(sim).run_chunk) so __call__ and
    # .lower see the same explicit-self signature.
    return EntryBuild(
        fn=type(sim).run_chunk,
        make_args=lambda: (sim, sim.init(seed=7), ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "n_ticks": ctx.chunk})


def _build_run_until_device(ctx):
    import jax.numpy as jnp
    from oversim_tpu.engine.sim import NS
    sim = build_sim(ctx)
    target = jnp.int64(int(2 * ctx.window * NS))
    return EntryBuild(
        fn=type(sim)._run_until_device,
        make_args=lambda: (sim, sim.init(seed=7), target, ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "chunk": ctx.chunk})


def _campaign_step(ctx, sim):
    """(jitted sharded _vstep, fresh-stacked-state factory, n_dev)."""
    import jax
    from oversim_tpu.campaign import Campaign, CampaignParams
    from oversim_tpu.parallel import mesh as mesh_mod

    camp = Campaign(sim, CampaignParams(replicas=ctx.replicas, base_seed=7))
    cs0 = camp.init()
    avail = len(jax.devices())
    n_dev = max(d for d in range(1, min(avail, camp.s) + 1)
                if camp.s % d == 0)
    mesh = mesh_mod.make_replica_mesh(n_dev)
    sh = mesh_mod.campaign_state_shardings(cs0, mesh)
    step = jax.jit(camp._vstep, in_shardings=(sh,), out_shardings=sh)
    return step, (lambda: (cs0,)), n_dev


def _build_campaign_tick(ctx):
    sim = build_sim(ctx)
    step, make_args, n_dev = _campaign_step(ctx, sim)
    return EntryBuild(
        fn=step, make_args=make_args,
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay,
              "replicas": ctx.replicas, "devices": n_dev})


def _build_telemetry_tick(ctx):
    import jax
    sim = build_sim(ctx, telemetry_ticks=ctx.tel_ticks)
    fn = jax.jit(sim.step)
    s0 = sim.init(seed=7)
    return EntryBuild(fn=fn, make_args=lambda: (s0,),
                      pool_dim=sim.ep.pool_factor * ctx.n,
                      info={"n": ctx.n, "overlay": ctx.overlay,
                            "sample_ticks": ctx.tel_ticks})


def _build_resharded_resume(ctx):
    """Reshard-on-resume (oversim_tpu/elastic/): a campaign checkpoint
    written at HALF the replica extent is restored into the full-width
    campaign via ``elastic.reshard_load`` (surviving rows bit-identical,
    grown rows re-seeded), and the compiled entry is the replica-sharded
    campaign tick on the RESHARDED state.  Resharding is a host-side
    restore — the compiled graph must be indistinguishable from
    ``campaign_tick``'s: the collective allowlist stays EMPTY."""
    import os
    import tempfile

    from oversim_tpu import checkpoint as ckpt_mod
    from oversim_tpu.campaign import Campaign, CampaignParams
    from oversim_tpu.elastic import reshard_load

    sim = build_sim(ctx)
    small = Campaign(sim, CampaignParams(
        replicas=max(1, ctx.replicas // 2), base_seed=7))
    fd, path = tempfile.mkstemp(suffix=".ckpt.npz")
    os.close(fd)
    try:
        ckpt_mod.save(path, small.init(),
                      meta={"campaign": small.describe()})
        full_sim = build_sim(ctx)
        step, _, n_dev = _campaign_step(ctx, full_sim)
        camp = Campaign(full_sim,
                        CampaignParams(replicas=ctx.replicas, base_seed=7))
        cs, _ = reshard_load(path, camp)
    finally:
        os.unlink(path)
    return EntryBuild(
        fn=step, make_args=lambda: (cs,),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay,
              "replicas_from": small.s, "replicas_to": camp.s,
              "devices": n_dev})


def _build_fused_tick(ctx):
    import jax
    sim = build_sim(ctx, inbox_impl="pallas")
    fn = jax.jit(sim.step)
    s0 = sim.init(seed=7)
    return EntryBuild(fn=fn, make_args=lambda: (s0,),
                      pool_dim=sim.ep.pool_factor * ctx.n,
                      info={"n": ctx.n, "overlay": ctx.overlay,
                            "inbox_impl": "pallas"})


def _build_fused_chunk(ctx):
    sim = build_sim(ctx, inbox_impl="pallas")
    # same static-self discipline as solo_chunk: ONE sim instance, the
    # unbound class-level jit, fresh donated state per call
    return EntryBuild(
        fn=type(sim).run_chunk,
        make_args=lambda: (sim, sim.init(seed=7), ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "n_ticks": ctx.chunk,
              "inbox_impl": "pallas"})


def _build_sparse_tick(ctx):
    import jax
    # a genuinely sparse lane count (cap < n) so the compiled graph has
    # the [A]-shaped step, not a full-width alias of the dense tick
    cap = max(8, ctx.n // 4)
    sim = build_sim(ctx, tick_impl="sparse", active_cap=cap)
    # donation REQUIRED by the contract: the sparse plane exists for the
    # steady-state loop, where the full-width state must update in place
    fn = jax.jit(sim.step, donate_argnums=(0,))
    return EntryBuild(fn=fn, make_args=lambda: (sim.init(seed=7),),
                      pool_dim=sim.ep.pool_factor * ctx.n,
                      info={"n": ctx.n, "overlay": ctx.overlay,
                            "tick_impl": "sparse", "active_cap": cap})


def _build_sparse_chunk(ctx):
    cap = max(8, ctx.n // 4)
    sim = build_sim(ctx, tick_impl="sparse", active_cap=cap)
    # same static-self discipline as solo_chunk/fused_chunk
    return EntryBuild(
        fn=type(sim).run_chunk,
        make_args=lambda: (sim, sim.init(seed=7), ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "n_ticks": ctx.chunk,
              "tick_impl": "sparse", "active_cap": cap})


def _node_shard_extent(n: int, p: int, avail: int) -> int:
    """Largest node-shard count ≤ avail dividing BOTH n and the pool."""
    return max(d for d in range(1, avail + 1) if n % d == 0 and p % d == 0)


def _build_sharded_tick(ctx):
    """The genuinely node-sharded tick (parallel/shard_tick.py): K-way
    shard_map over the (1, K) 2-D mesh, every cross-shard exchange a
    hand-written min-gather — the compiled step's collective census is
    ``all-reduce:min`` and nothing else, with zero sorts (the sort
    path's all-to-all merge exchange never enters the graph)."""
    import jax
    from oversim_tpu.parallel import mesh as mesh_mod
    from oversim_tpu.parallel.shard_tick import ShardedSim

    sim = build_sim(ctx)
    k = _node_shard_extent(ctx.n, sim.ep.pool_factor * ctx.n,
                           len(jax.devices()))
    mesh = mesh_mod.make_mesh_2d(1, k)
    ssim = ShardedSim(sim, mesh)
    fn = jax.jit(ssim.step, in_shardings=(ssim.shardings,),
                 out_shardings=ssim.shardings, donate_argnums=(0,))
    return EntryBuild(
        fn=fn, make_args=lambda: (ssim.place(sim.init(seed=7)),),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "node_shards": k,
              "mesh": [1, k]})


def _build_sharded_campaign_tick(ctx):
    """S stacked replicas × K node shards on one (R, K) 2-D mesh: the
    campaign axis composed with node sharding.  Same allowlist as
    ``sharded_tick`` — and since every pmin names NODE_AXIS only, the
    replica groups span node subgroups: cross-replica traffic stays
    structurally zero (scripts/shard_gate.py pins the replica_groups)."""
    import jax
    from oversim_tpu.campaign import Campaign, CampaignParams
    from oversim_tpu.parallel import mesh as mesh_mod
    from oversim_tpu.parallel.shard_tick import ShardedCampaign

    sim = build_sim(ctx)
    camp = Campaign(sim, CampaignParams(replicas=ctx.replicas, base_seed=7))
    avail = len(jax.devices())
    r_dev = max(d for d in range(1, min(avail, camp.s) + 1)
                if camp.s % d == 0)
    k = _node_shard_extent(ctx.n, sim.ep.pool_factor * ctx.n,
                           avail // r_dev)
    mesh = mesh_mod.make_mesh_2d(r_dev, k)
    scamp = ShardedCampaign(camp, mesh)
    fn = jax.jit(scamp.vstep, in_shardings=(scamp.shardings,),
                 out_shardings=scamp.shardings, donate_argnums=(0,))
    return EntryBuild(
        fn=fn, make_args=lambda: (scamp.place(camp.init()),),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay,
              "replicas": ctx.replicas, "node_shards": k,
              "mesh": [r_dev, k]})


def _build_service_window(ctx):
    import jax.numpy as jnp
    from oversim_tpu.engine.sim import NS
    # the serving loop's dispatch unit: run_until_device with the
    # EXT_OUT hold slot armed (gateway responses parked until the
    # window-boundary drain, oversim_tpu/service/loop.py)
    sim = build_sim(ctx, ext_hold_slot=0)
    target = jnp.int64(int(2 * ctx.window * NS))
    return EntryBuild(
        fn=type(sim)._run_until_device,
        make_args=lambda: (sim, sim.init(seed=7), target, ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay, "ext_hold_slot": 0})


def _build_daemon_window(ctx):
    import jax.numpy as jnp
    from oversim_tpu.campaign import Campaign, CampaignParams
    from oversim_tpu.engine.sim import NS
    # the daemon tier's dispatch unit: the CAMPAIGN-stacked
    # run_until_device with the EXT_OUT hold armed — S tenants (replica
    # rows, service/tenant.py) served by one compiled program.  Same
    # donated-window contract as service_window: tenancy adds batched
    # pool writes at the boundary, never graph structure.
    sim = build_sim(ctx, ext_hold_slot=0)
    camp = Campaign(sim, CampaignParams(replicas=ctx.replicas,
                                        base_seed=7))
    target = jnp.int64(int(2 * ctx.window * NS))
    return EntryBuild(
        fn=type(camp)._run_until_device,
        make_args=lambda: (camp, camp.init(), target, ctx.chunk),
        pool_dim=sim.ep.pool_factor * ctx.n,
        info={"n": ctx.n, "overlay": ctx.overlay,
              "replicas": ctx.replicas, "ext_hold_slot": 0})


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

_TICK = GraphContract()
_DONATED = GraphContract(require_donation=True)

# the only custom-call the kernel plane may introduce: the Mosaic
# lowering of pl.pallas_call on TPU.  Interpret mode (CPU CI) lowers
# the kernels inline — zero custom-calls — so the allowlist holds on
# both backends (oversim_tpu/kernels/).
KERNEL_CUSTOM_CALLS = frozenset({"tpu_custom_call"})
_FUSED_TICK = GraphContract(
    custom_calls_enforced=True,
    allowed_custom_calls=KERNEL_CUSTOM_CALLS)
_FUSED_CHUNK = GraphContract(
    require_donation=True,
    custom_calls_enforced=True,
    allowed_custom_calls=KERNEL_CUSTOM_CALLS)

DEFAULT_ENTRIES = (
    EntryPoint(
        name="solo_tick",
        doc="jit(sim.step): one engine tick, telemetry off",
        contract=_TICK,
        build=_build_solo_tick),
    EntryPoint(
        name="solo_chunk",
        doc="sim.run_chunk: fused n-tick scan, donated state",
        contract=_DONATED,
        build=_build_solo_chunk),
    EntryPoint(
        name="run_until_device",
        doc="sim._run_until_device: while-loop run-to-time, donated",
        contract=_DONATED,
        build=_build_run_until_device),
    EntryPoint(
        name="campaign_tick",
        doc="vmapped replica-sharded campaign tick: ZERO cross-replica "
            "collectives (pure data parallelism)",
        contract=GraphContract(),       # allowed_collectives stays empty
        build=_build_campaign_tick),
    EntryPoint(
        name="telemetry_tick",
        doc="jit(sim.step) with telemetry rings: delta vs solo_tick "
            "bounded (one drop-scatter per ring, no sorts, no "
            "collectives)",
        contract=GraphContract(max_scatters=DEFAULT_MAX_SCATTERS + 64),
        build=_build_telemetry_tick,
        delta=DeltaContract(base="solo_tick")),
    EntryPoint(
        name="service_window",
        doc="service window: run_until_device with EXT_OUT hold armed",
        contract=_DONATED,
        build=_build_service_window),
    EntryPoint(
        name="daemon_window",
        doc="daemon serving window: campaign-stacked run_until_device "
            "with EXT_OUT hold armed — S tenants from one compiled "
            "program, donated, zero cross-replica collectives",
        contract=_DONATED,
        build=_build_daemon_window),
    EntryPoint(
        name="fused_tick",
        doc="jit(sim.step) with the Pallas kernel plane armed "
            "(inbox_impl=\"pallas\"; interpret mode off-TPU): zero "
            "full-pool sorts, Mosaic-custom-calls only, and a NEGATIVE "
            "scatter delta vs solo_tick — the fused kernel must "
            "actually replace the 2R scatter-min rounds + fslot "
            "compaction",
        contract=_FUSED_TICK,
        build=_build_fused_tick,
        # negative bound = a REQUIRED reduction: the fused tick must
        # carry at least 2 fewer scatters than solo_tick (measured:
        # 2R+1 fewer; tests/test_kernels.py pins the exact count)
        delta=DeltaContract(base="solo_tick", max_scatter_delta=-2)),
    EntryPoint(
        name="fused_chunk",
        doc="run_chunk with the kernel plane armed: donation must "
            "survive the pallas path (the pool block stays in-place "
            "across chunks)",
        contract=_FUSED_CHUNK,
        build=_build_fused_chunk),
    EntryPoint(
        name="sparse_tick",
        doc="jit(sim.step, donate) with the sparse active-set plane "
            "armed (tick_impl=\"sparse\"): donation required, zero "
            "full-pool sorts, no new collectives, and a NEGATIVE "
            "wide-gather delta vs solo_tick — the [A]-lane step must "
            "actually replace the full [N, R, W] payload gather",
        contract=GraphContract(require_donation=True,
                               max_scatters=DEFAULT_MAX_SCATTERS + 128),
        build=_build_sparse_tick,
        # scatter delta bounded, not negative: the A-lane scatter-backs
        # (logic-state leaves + outbox/event planes) are each one gated
        # drop-scatter; the REQUIRED reduction is the wide-gather one
        delta=DeltaContract(base="solo_tick", max_scatter_delta=128,
                            max_wide_gather_delta=-1)),
    EntryPoint(
        name="sparse_chunk",
        doc="run_chunk with the sparse plane armed: donation must "
            "survive the compacted step (the full-width state updates "
            "in place across chunks)",
        contract=GraphContract(require_donation=True,
                               max_scatters=DEFAULT_MAX_SCATTERS + 128),
        build=_build_sparse_chunk),
    EntryPoint(
        name="sharded_tick",
        doc="node-sharded tick on the (1, K) 2-D mesh (shard_map, "
            "parallel/shard_tick.py): donation required and the "
            "collective allowlist is all-reduce:min ONLY — no "
            "all-to-all, no all-gather of pool payloads, zero sorts "
            "(bit-identity vs the solo oracle is pinned by "
            "tests/test_mesh.py and scripts/shard_gate.py)",
        contract=GraphContract(
            require_donation=True,
            allowed_collectives=frozenset({"all-reduce:min"}),
            max_scatters=DEFAULT_MAX_SCATTERS + 64),
        build=_build_sharded_tick),
    EntryPoint(
        name="sharded_campaign_tick",
        doc="S replicas × K node shards on the (R, K) 2-D mesh: the "
            "same all-reduce:min-only allowlist; every collective "
            "names the node axis only, so replica groups span node "
            "subgroups — zero cross-replica collectives stays pinned "
            "(replica_groups structure checked by shard_gate.py)",
        contract=GraphContract(
            require_donation=True,
            allowed_collectives=frozenset({"all-reduce:min"}),
            max_scatters=DEFAULT_MAX_SCATTERS + 64),
        build=_build_sharded_campaign_tick),
    EntryPoint(
        name="resharded_resume",
        doc="campaign tick on a state reshard-restored from a "
            "half-width checkpoint (oversim_tpu/elastic/): identical "
            "contract to campaign_tick — resharding happens at restore "
            "time, never in the graph",
        contract=GraphContract(),       # allowlist unchanged vs base
        build=_build_resharded_resume),
)

REGISTRY: dict = {e.name: e for e in DEFAULT_ENTRIES}


def register_entry(entry: EntryPoint, *, replace: bool = False) -> None:
    """How a future subsystem joins the gate (see README 'Analysis
    plane').  Entries run in registration order; a DeltaContract's base
    must be registered first."""
    if entry.name in REGISTRY and not replace:
        raise ValueError(f"entry {entry.name!r} already registered")
    if entry.delta is not None and entry.delta.base not in REGISTRY:
        raise ValueError(f"delta base {entry.delta.base!r} not registered")
    REGISTRY[entry.name] = entry


def entries(names=None) -> list:
    """Resolve ``--entries`` selections (None = everything, in order)."""
    if names is None:
        return list(REGISTRY.values())
    missing = [n for n in names if n not in REGISTRY]
    if missing:
        raise KeyError(f"unknown entries: {', '.join(missing)} "
                       f"(known: {', '.join(REGISTRY)})")
    return [REGISTRY[n] for n in names]


# ---------------------------------------------------------------------------
# scenario pins (config-level contracts — no compilation needed)
# ---------------------------------------------------------------------------

_DEFAULT_INI = """
[General]
**.overlayType = "oversim.overlay.kademlia.KademliaModules"
**.targetOverlayTerminalNum = 16
"""


def scenario_pins() -> list:
    """Config-level contract: the DEFAULT scenario resolution must never
    pick ``inbox_impl="sort"`` — the legacy sort path is oracle-only
    (ROADMAP item 6); only an explicit ``**.inboxImpl = "sort"`` key may
    select it.  The kernel plane adds two pins: an explicit
    ``"pallas"`` key is honored when the plane is importable, and a
    pallas request on a kernel-less install falls back to ``"scatter"``
    (never to ``"sort"``, never an error).  Returns Finding rows
    (empty = pinned)."""
    from oversim_tpu.analysis.findings import Finding
    from oversim_tpu.config import scenario
    from oversim_tpu.config.ini import IniFile

    out = []
    ini = IniFile.loads(_DEFAULT_INI)
    sim = scenario.build_simulation(ini, "General")
    if sim.ep.inbox_impl != "scatter":
        out.append(Finding(
            pass_name="hlo", rule="default-inbox-impl",
            where="config/scenario.py",
            message="default scenario resolved inbox_impl="
                    f"{sim.ep.inbox_impl!r} — the sort path is "
                    "oracle-only and must require an explicit "
                    "**.inboxImpl key",
            measured=sim.ep.inbox_impl, limit="scatter"))
    sort_ini = IniFile.loads(_DEFAULT_INI
                             + '\n**.inboxImpl = "sort"\n')
    sim_sort = scenario.build_simulation(sort_ini, "General")
    if sim_sort.ep.inbox_impl != "sort":
        out.append(Finding(
            pass_name="hlo", rule="inbox-impl-override",
            where="config/scenario.py",
            message="explicit **.inboxImpl = \"sort\" was not honored "
                    "— the oracle path became unreachable",
            measured=sim_sort.ep.inbox_impl, limit="sort"))
    # kernel-plane availability fallback: a "pallas" request without
    # the plane resolves to the scatter default, loudly but non-fatally
    fallback = scenario.resolve_inbox_impl("pallas", available=False,
                                           warn=False)
    if fallback != "scatter":
        out.append(Finding(
            pass_name="hlo", rule="pallas-unavailable-fallback",
            where="config/scenario.py",
            message="inboxImpl \"pallas\" on a kernel-less install "
                    f"resolved to {fallback!r} — must fall back to "
                    "the scatter default",
            measured=fallback, limit="scatter"))
    from oversim_tpu import kernels
    if kernels.available():
        pallas_ini = IniFile.loads(_DEFAULT_INI
                                   + '\n**.inboxImpl = "pallas"\n')
        sim_k = scenario.build_simulation(pallas_ini, "General")
        if sim_k.ep.inbox_impl != "pallas":
            out.append(Finding(
                pass_name="hlo", rule="inbox-impl-override",
                where="config/scenario.py",
                message="explicit **.inboxImpl = \"pallas\" was not "
                        "honored despite an available kernel plane",
                measured=sim_k.ep.inbox_impl, limit="pallas"))
    return out
