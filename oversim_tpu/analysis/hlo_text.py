"""Pure-text analysis of optimized HLO — the measurement half of the
graph-contract plane (oversim_tpu/analysis/contracts.py).

Import-safe: no jax at module level, so the fast test tier can pin the
counting semantics on synthetic HLO strings without a backend
(tests/test_hlo_budget.py, tests/test_analysis.py).  Everything here
consumes ``compiled.as_text()`` output.

History: ``hlo_op_counts`` / ``check_budget`` / ``check_telemetry_budget``
grew up inside scripts/hlo_breakdown.py's three ad-hoc budget modes
(--budget / --campaign / --telemetry).  They now live here as the shared
measurement layer; hlo_breakdown re-exports them for back-compat and the
contract registry drives them for every compiled entry point.

XLA-CPU at -O0 expands scatters into ``while`` loops (ScatterExpander),
so :func:`hlo_op_counts` counts native ``scatter(`` ops PLUS while ops
carrying a ``.../scatter`` op_name — the same graph compiled for TPU
keeps them as native scatters.
"""

from __future__ import annotations

import collections
import re

_SCATTER_WHILE = re.compile(r'op_name="[^"]*/scatter')

# cross-device collective opcodes (GSPMD partitioning output).  The
# campaign budget pins their count at ZERO inside the replica-sharded
# tick: the replica axis is pure data parallelism (oversim_tpu/campaign/)
# — any collective appearing there means the partitioner found a
# cross-replica data dependency, i.e. replicas stopped being independent.
_COLLECTIVE_OPS = ("all-reduce(", "all-gather(", "all-to-all(",
                   "collective-permute(", "reduce-scatter(",
                   "collective-broadcast(")

# ops that talk to the host mid-execution: infeed/outfeed, cross-program
# send/recv, and python-callback custom-calls.  The device-resident run
# loops pin these at ZERO — a host transfer inside the compiled window
# breaks the one-dispatch/one-fetch contract.
_HOST_OPS = (" infeed(", " outfeed(", " send(", " recv(",
             " send-done(", " recv-done(")

# result-dtype tokens as they appear in HLO shapes (``f64[8]{0}``).
_DTYPE_RE = re.compile(
    r"=\s*\(?\s*((?:pred|token|[sf]\d+|u\d+|bf16|f8e\w+|c\d+)"
    r"(?:\[[^\]]*\]\{?[^)\s,]*\}?)?"
    r"(?:\s*,\s*(?:pred|token|[sf]\d+|u\d+|bf16|f8e\w+|c\d+)"
    r"\[[^\]]*\]\{?[^)\s,]*\}?)*)")
_DTYPE_TOKEN = re.compile(r"\b(pred|token|bf16|f8e\w+|[sfuc]\d+)\[?")


def hlo_op_counts(txt: str, pool_dim: int | None = None) -> dict:
    """Count sort/scatter/collective ops in optimized HLO text.

    Returns ``{"sort_count", "full_pool_sort_count", "scatter_count",
    "collective_count"}``.
    ``full_pool_sort_count`` counts sorts whose operand shape contains
    the pool dimension ``pool_dim`` (0 when pool_dim is None).
    ``scatter_count`` = native ``scatter(`` ops + XLA-CPU's
    scatter-expanded ``while`` loops (identified by op_name metadata).
    ``collective_count`` = cross-device collectives (all-reduce /
    all-gather / all-to-all / collective-permute / reduce-scatter /
    collective-broadcast, including their ``-start`` async forms).
    """
    sorts = full = scatters = collectives = 0
    # the pool dim counts as "full-pool" wherever it sits in the shape:
    # leading ([P,...]) in the solo step, second ([S,P,...]) under the
    # campaign's replica vmap
    pool_re = (re.compile(rf"\[(\d+,)?{pool_dim}[\],]")
               if pool_dim is not None else None)
    for ln in txt.splitlines():
        if " sort(" in ln:
            sorts += 1
            if pool_re is not None and pool_re.search(ln):
                full += 1
        elif " scatter(" in ln:
            scatters += 1
        elif " while(" in ln and _SCATTER_WHILE.search(ln):
            scatters += 1
        # async collectives lower to op-start/op-done pairs — counting
        # only the -start (plus the sync form) avoids double counting
        if any((" " + op in ln) or (" " + op[:-1] + "-start(" in ln)
               for op in _COLLECTIVE_OPS):
            collectives += 1
    return {"sort_count": sorts, "full_pool_sort_count": full,
            "scatter_count": scatters, "collective_count": collectives}


_GATHER_RESULT = re.compile(r"=\s*(?:pred|[sfuc]\d+|u\d+|bf16)\[(\d+)[,\]]")


def gather_counts(txt: str, wide_dims=()) -> dict:
    """Gather census: ``{"gather_count", "wide_gather_count"}``.

    A SEPARATE function from :func:`hlo_op_counts` — its return keys are
    pinned by synthetic-HLO tests and by every recorded artifact, so the
    sparse-plane gather census (ISSUE 16) adds a new dict instead of
    widening the old one.  ``wide_gather_count`` counts gathers whose
    RESULT's leading dimension is in ``wide_dims`` (the full node count
    N or the pool capacity P): the dense tick's [N, R, W] payload gather
    is wide, the sparse tick's [A, R, W] gather is not — the
    ``sparse_tick`` delta contract pins that replacement as a REQUIRED
    wide-gather reduction vs ``solo_tick``.  ``" gather("`` with the
    leading space keeps ``all-gather(`` out of the census.
    """
    wide = {int(d) for d in wide_dims if d}
    gathers = wides = 0
    for ln in txt.splitlines():
        if " gather(" not in ln:
            continue
        gathers += 1
        m = _GATHER_RESULT.search(ln)
        if m and int(m.group(1)) in wide:
            wides += 1
    return {"gather_count": gathers, "wide_gather_count": wides}


def collective_census(txt: str) -> dict:
    """Per-opcode collective census, all-reduce refined by its reduce
    computation when recognizable.

    Returns a ``{token: count}`` dict where ``token`` is the collective
    opcode (``"all-gather"``, ``"all-to-all"``, ...) or, for all-reduce,
    ``"all-reduce:min"`` / ``"all-reduce:add"`` / ... when the
    ``to_apply=`` computation reveals the combiner — the contract
    language for "all-reduce-min-only sharded ticks".  The combiner is
    read from the computation NAME when it carries one (``%min_s64``)
    and otherwise resolved from the computation BODY: compiler-named
    regions (``%region_1.7``) say nothing, but their root op
    (``minimum``/``add``/...) does.  Unrecognizable combiners stay
    plain ``"all-reduce"``.
    """
    body_comb = _combiner_by_region(txt)
    out = collections.Counter()
    for ln in txt.splitlines():
        for op in _COLLECTIVE_OPS:
            base = op[:-1]
            if (" " + op in ln) or (" " + base + "-start(" in ln):
                token = base
                if base == "all-reduce":
                    m = re.search(r"to_apply=%?([\w.\-]+)", ln)
                    if m:
                        name = m.group(1).lower()
                        for comb in ("min", "max", "add", "sum", "and",
                                     "or", "mul"):
                            if comb in name:
                                token = f"all-reduce:{comb}"
                                break
                        else:
                            comb = body_comb.get(m.group(1))
                            if comb:
                                token = f"all-reduce:{comb}"
                out[token] += 1
    return dict(out)


_ROOT_COMBINERS = (("minimum(", "min"), ("maximum(", "max"),
                   ("add(", "add"), ("multiply(", "mul"),
                   ("and(", "and"), ("or(", "or"))


def _combiner_by_region(txt: str) -> dict:
    """Map computation name -> combiner token, resolved from each
    computation's ROOT op.  Covers compiler-generated region names
    (``%region_1.7``) whose names carry no combiner hint."""
    out = {}
    name = None
    for ln in txt.splitlines():
        m = re.match(r"%([\w.\-]+)\s*\([^)]*\)\s*->\s*[^{]+{", ln)
        if m:
            name = m.group(1)
            continue
        if name is None:
            continue
        if ln.strip().startswith("}"):
            name = None
            continue
        if "ROOT " in ln:
            for needle, comb in _ROOT_COMBINERS:
                if needle in ln:
                    out[name] = comb
                    break
            name = None
    return out


_CUSTOM_TARGET = re.compile(r'custom_call_target="([^"]+)"')


def custom_call_census(txt: str) -> dict:
    """Per-target custom-call census: ``{custom_call_target: count}``.

    The contract language for the kernel plane (oversim_tpu/kernels/):
    on TPU the fused Pallas kernels lower to Mosaic ``tpu_custom_call``
    ops — the ``fused_tick`` allowlist pins that nothing ELSE enters
    the graph as an unvetted external call.  Under
    ``pallas_call(interpret=True)`` (the CPU CI path) the kernels
    discharge to inline HLO and the census is empty — the allowlist is
    an upper bound, so both backends pass the same contract.  Targets
    missing the ``custom_call_target=`` attribute count as
    ``"<unknown>"``.
    """
    out = collections.Counter()
    for ln in txt.splitlines():
        if " custom-call(" in ln or " custom-call-start(" in ln:
            m = _CUSTOM_TARGET.search(ln)
            out[m.group(1) if m else "<unknown>"] += 1
    return dict(out)


def host_transfer_count(txt: str) -> int:
    """Ops that reach the host mid-execution: infeed/outfeed/send/recv
    plus python-callback custom-calls (io_callback/pure_callback/debug
    prints)."""
    n = 0
    for ln in txt.splitlines():
        if any(op in ln for op in _HOST_OPS):
            n += 1
        elif " custom-call(" in ln and "callback" in ln:
            n += 1
    return n


def dtype_census(txt: str) -> dict:
    """Instruction-result dtype census: ``{dtype_token: count}``.

    Used for the contract's dtype allowlist — with x64 enabled the
    engine's accumulators are pinned s64/f64; a bf16/f16 appearing in
    the tick means an accumulator silently lost precision.
    """
    out = collections.Counter()
    for ln in txt.splitlines():
        m = _DTYPE_RE.search(ln)
        if not m:
            continue
        for tok in _DTYPE_TOKEN.findall(m.group(1)):
            out[tok] += 1
    return dict(out)


def donated_leaf_count(txt: str) -> int:
    """Number of input→output aliased buffers in the module header.

    Donation that survived to the optimized module shows up as
    ``input_output_alias={ {}: (0, {}, may-alias), ... }`` — one
    ``may-alias``/``must-alias`` entry per aliased leaf.  0 means the
    donation was dropped (or never requested): every chunk would then
    round-trip the full state through fresh HBM allocations.
    """
    for ln in txt.splitlines():
        if "input_output_alias=" in ln:
            return len(re.findall(r"(?:may|must)-alias", ln))
    return 0


def check_budget(txt: str, pool_dim: int, max_full_pool_sorts: int,
                 max_scatters: int, max_collectives: int | None = None):
    """(ok, counts) — does the compiled tick fit the pinned op budget?
    ``max_collectives`` is only enforced when given (the campaign budget
    pins it at 0; single-replica node-sharded steps legitimately carry
    collectives)."""
    counts = hlo_op_counts(txt, pool_dim)
    ok = (counts["full_pool_sort_count"] <= max_full_pool_sorts
          and counts["scatter_count"] <= max_scatters)
    if max_collectives is not None:
        ok = ok and counts["collective_count"] <= max_collectives
    return ok, counts


def check_telemetry_budget(base_counts: dict, tel_counts: dict,
                           max_full_pool_sorts: int = 0,
                           max_scatter_delta: int = 64,
                           max_new_collectives: int = 0):
    """(ok, delta) — the telemetry-enabled tick vs the telemetry-off tick.

    The telemetry plane's entire graph cost is one gated ``mode="drop"``
    scatter per ring buffer (oversim_tpu/telemetry.py fold), so the
    pinned contract is: still ZERO full-pool sorts (no sort may appear
    anywhere — the rings never sort), a BOUNDED scatter delta (one per
    ring; KBRTest taps + engine counters + time/tick/alive meta fit well
    under 64), and ZERO new collectives (the [W] rings are replicated /
    per-replica — sampling must not create cross-device traffic).
    ``base_counts``/``tel_counts`` are :func:`hlo_op_counts` dicts.
    """
    delta = {
        "full_pool_sort_count": tel_counts["full_pool_sort_count"],
        "sort_delta": (tel_counts["sort_count"]
                       - base_counts["sort_count"]),
        "scatter_delta": (tel_counts["scatter_count"]
                          - base_counts["scatter_count"]),
        "collective_delta": (tel_counts["collective_count"]
                             - base_counts["collective_count"]),
    }
    ok = (delta["full_pool_sort_count"] <= max_full_pool_sorts
          and delta["sort_delta"] <= 0
          and delta["scatter_delta"] <= max_scatter_delta
          and delta["collective_delta"] <= max_new_collectives)
    return ok, delta
