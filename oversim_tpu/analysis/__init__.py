"""Graph-contract analysis plane (ISSUE 10).

Three passes over every compiled entry point, driven by
``scripts/analyze.py`` (exit non-zero on any breach):

* ``hlo_pass``  — lower/compile each registered entry and diff the
  optimized module against its declarative :class:`GraphContract`
  (sorts / scatters / collectives / host transfers / donation / dtypes).
* ``trace_pass`` — run each entry twice; fail on recompilation across
  same-shape calls, tracer leaks, and implicit host syncs.
* ``ast_pass``  — host-hazard lint over the hot-path modules with
  in-tree ``# analysis: allow(host-numpy)``-style suppressions, plus
  stale-bytecode guards.

Import-safe: nothing here imports jax at module level — the fast test
tier exercises the text/AST layers without a backend.
"""

from oversim_tpu.analysis.contracts import (      # noqa: F401
    DEFAULT_DTYPES,
    DeltaContract,
    EntryBuild,
    EntryContext,
    EntryPoint,
    GraphContract,
    REGISTRY,
    entries,
    register_entry,
    scenario_pins,
)
from oversim_tpu.analysis.findings import (       # noqa: F401
    Finding,
    document,
    errors,
    verdict_summary,
    write_document,
)
from oversim_tpu.analysis.hlo_text import (       # noqa: F401
    check_budget,
    check_telemetry_budget,
    collective_census,
    donated_leaf_count,
    dtype_census,
    hlo_op_counts,
    host_transfer_count,
)
