"""HLO contract pass: lower + compile every registered entry point and
diff its optimized module against the entry's :class:`GraphContract`.

For each entry the pass records a census dict (op counts, collective
census, host transfers, donated leaves, off-allowlist dtypes) in the
verdict document's ``passes.hlo.entries`` — delta contracts
(telemetry_tick vs solo_tick) diff against the base entry's recorded
counts, so registration order matters (contracts.register_entry
enforces base-first).
"""

from __future__ import annotations

import time

from oversim_tpu.analysis import hlo_text
from oversim_tpu.analysis.findings import Finding


def measure_entry(txt: str, pool_dim: int, wide_dims=()) -> dict:
    """Every census the contracts can pin, from one optimized module.

    ``wide_dims`` feeds the gather census (hlo_text.gather_counts):
    the full-width leading dims — node count N and pool capacity P —
    whose gathers the sparse plane exists to eliminate."""
    m = dict(hlo_text.hlo_op_counts(txt, pool_dim))
    m.update(hlo_text.gather_counts(txt, wide_dims))
    m["collectives"] = hlo_text.collective_census(txt)
    m["custom_calls"] = hlo_text.custom_call_census(txt)
    m["host_transfers"] = hlo_text.host_transfer_count(txt)
    m["donated_leaves"] = hlo_text.donated_leaf_count(txt)
    m["dtypes"] = hlo_text.dtype_census(txt)
    return m


def check_contract(name: str, contract, m: dict) -> list:
    """Diff one entry's measurements against its GraphContract."""
    out = []

    def breach(rule, message, measured, limit):
        out.append(Finding(pass_name="hlo", rule=rule, where=name,
                           message=message, measured=measured, limit=limit))

    if m["full_pool_sort_count"] > contract.max_full_pool_sorts:
        breach("full-pool-sorts",
               "full-pool sorts appeared in the compiled graph — the "
               "zero-sort tick regressed (engine/pool.py scatter-min "
               "selection)",
               m["full_pool_sort_count"], contract.max_full_pool_sorts)
    if contract.max_sorts is not None and \
            m["sort_count"] > contract.max_sorts:
        breach("sorts", "total sort ops over budget",
               m["sort_count"], contract.max_sorts)
    if m["scatter_count"] > contract.max_scatters:
        breach("scatters",
               "scatter count (incl. XLA-CPU while-expanded scatters) "
               "over budget",
               m["scatter_count"], contract.max_scatters)
    if contract.collectives_enforced:
        bad = {k: v for k, v in m["collectives"].items()
               if k not in contract.allowed_collectives}
        if bad:
            breach("collectives",
                   "cross-device collectives outside the allowed set — "
                   "for replica-sharded entries this means the "
                   "partitioner found a cross-replica data dependency",
                   bad, sorted(contract.allowed_collectives))
    if contract.custom_calls_enforced:
        bad = {k: v for k, v in m["custom_calls"].items()
               if k not in contract.allowed_custom_calls}
        if bad:
            breach("custom-calls",
                   "custom-calls outside the kernel allowlist — an "
                   "unvetted external call entered the compiled tick "
                   "(the fused Pallas kernels may only appear as "
                   "Mosaic tpu_custom_call ops)",
                   bad, sorted(contract.allowed_custom_calls))
    if m["host_transfers"] > contract.max_host_transfers:
        breach("host-transfers",
               "infeed/outfeed/send/recv/host-callback ops inside the "
               "compiled module break the one-dispatch/one-fetch "
               "contract",
               m["host_transfers"], contract.max_host_transfers)
    if contract.require_donation and \
            m["donated_leaves"] < contract.min_donated_leaves:
        breach("donation",
               "input→output buffer aliasing missing from the optimized "
               "module — donation was dropped; every dispatch "
               "round-trips the state through fresh allocations",
               m["donated_leaves"], f">= {contract.min_donated_leaves}")
    bad_dtypes = {k: v for k, v in m["dtypes"].items()
                  if k not in contract.dtype_allowlist}
    if bad_dtypes:
        breach("dtypes",
               "instruction result dtypes outside the allowlist — an "
               "x64 accumulator silently lost precision",
               bad_dtypes, sorted(contract.dtype_allowlist))
    return out


def check_delta(name: str, delta, base_m: dict, m: dict) -> list:
    """Diff one entry against its DeltaContract base entry."""
    out = []
    d = {
        "full_pool_sort_count": m["full_pool_sort_count"],
        "sort_delta": m["sort_count"] - base_m["sort_count"],
        "scatter_delta": m["scatter_count"] - base_m["scatter_count"],
        "collective_delta": (m["collective_count"]
                             - base_m["collective_count"]),
        # gather deltas are RECORDED for every delta entry (the verdict
        # JSON carries the sparse plane's measured reduction); only
        # max_wide_gather_delta != None enforces one
        "gather_delta": (m.get("gather_count", 0)
                         - base_m.get("gather_count", 0)),
        "wide_gather_delta": (m.get("wide_gather_count", 0)
                              - base_m.get("wide_gather_count", 0)),
    }

    def breach(rule, message, measured, limit):
        out.append(Finding(pass_name="hlo", rule=rule,
                           where=f"{name} (vs {delta.base})",
                           message=message, measured=measured, limit=limit))

    if d["full_pool_sort_count"] > delta.max_full_pool_sorts:
        breach("delta-full-pool-sorts",
               "full-pool sorts in the delta entry",
               d["full_pool_sort_count"], delta.max_full_pool_sorts)
    if d["sort_delta"] > delta.max_sort_delta:
        breach("delta-sorts", "new sorts relative to the base entry",
               d["sort_delta"], delta.max_sort_delta)
    if d["scatter_delta"] > delta.max_scatter_delta:
        breach("delta-scatters",
               "scatter delta over budget (one gated drop-scatter per "
               "telemetry ring is the whole allowance)",
               d["scatter_delta"], delta.max_scatter_delta)
    if d["collective_delta"] > delta.max_collective_delta:
        breach("delta-collectives",
               "new cross-device collectives relative to the base entry",
               d["collective_delta"], delta.max_collective_delta)
    if delta.max_wide_gather_delta is not None and \
            d["wide_gather_delta"] > delta.max_wide_gather_delta:
        breach("delta-wide-gathers",
               "full-width gather delta over budget — a negative bound "
               "is a REQUIRED reduction: the sparse tick must replace "
               "the [N, R, W] payload gather with the [A]-lane one, "
               "not stack compaction on top of it",
               d["wide_gather_delta"], delta.max_wide_gather_delta)
    return out, d


def timed_lower_compile(built) -> tuple:
    """(optimized HLO text, compile-seconds dict) for one EntryBuild,
    timing lower (trace+StableHLO) and compile (XLA backend) apart —
    the two stages the AOT artifact plane (oversim_tpu/aot/) and the
    persistent cache attack separately.  The timing is also stashed in
    ``built.info["compile_seconds"]`` for the verdict document."""
    t0 = time.perf_counter()
    lowered = built.fn.lower(*built.make_args())
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    timing = {"lower": round(t_lower, 3), "compile": round(t_compile, 3),
              "total": round(t_lower + t_compile, 3)}
    built.info["compile_seconds"] = timing
    return compiled.as_text(), timing


def check_compile_budget(name: str, budget, timing: dict) -> list:
    """Budget breach finding (empty when within budget or unbudgeted)."""
    if budget is None or timing["total"] <= budget:
        return []
    return [Finding(
        pass_name="hlo", rule="compile-seconds", where=name,
        message="lower+compile wall time over the CI compile budget — "
                "compile-latency regressions burn the TPU deadline "
                "before the first measured window (--compile-budget / "
                "GraphContract.max_compile_seconds)",
        measured=timing["total"], limit=budget)]


def lower_entry(entry, ctx, builds=None) -> tuple:
    """(optimized HLO text, EntryBuild) for one registry entry."""
    if builds is not None and entry.name in builds:
        built = builds[entry.name]
    else:
        built = entry.build(ctx)
        if builds is not None:
            builds[entry.name] = built
    txt, _ = timed_lower_compile(built)
    return txt, built


def run(ctx, selected=None, *, progress=None, builds=None,
        compile_budget=None):
    """The whole pass: (findings, summary) over the selected entries.

    ``progress`` is an optional ``callable(str)`` for per-entry status
    lines (compiles are the slow part of the analyzer); ``builds`` an
    optional shared ``{name: EntryBuild}`` cache across passes.
    ``compile_budget`` (seconds, ``--compile-budget``) is the default
    per-entry lower+compile ceiling; an entry's
    ``contract.max_compile_seconds`` overrides it.  Timings are
    recorded in the summary regardless — only enforcement is gated."""
    from oversim_tpu.analysis import contracts as contracts_mod

    findings = []
    entries_summary = {}
    measured = {}
    for entry in contracts_mod.entries(selected):
        if progress:
            progress(f"hlo: compiling {entry.name} ...")
        txt, built = lower_entry(entry, ctx, builds)
        m = measure_entry(txt, built.pool_dim,
                          wide_dims=(built.info.get("n"), built.pool_dim))
        measured[entry.name] = m
        findings.extend(check_contract(entry.name, entry.contract, m))
        timing = built.info.get("compile_seconds",
                                {"lower": 0.0, "compile": 0.0,
                                 "total": 0.0})
        budget = entry.contract.max_compile_seconds
        if budget is None:
            budget = compile_budget
        findings.extend(check_compile_budget(entry.name, budget, timing))
        delta_info = None
        if entry.delta is not None:
            base_m = measured.get(entry.delta.base)
            if base_m is None:
                findings.append(Finding(
                    pass_name="hlo", rule="delta-base-missing",
                    where=entry.name,
                    message=f"delta base {entry.delta.base!r} was not "
                            f"measured in this run (select it too)"))
            else:
                delta_findings, delta_info = check_delta(
                    entry.name, entry.delta, base_m, m)
                findings.extend(delta_findings)
        entries_summary[entry.name] = {
            "counts": {k: m[k] for k in
                       ("sort_count", "full_pool_sort_count",
                        "scatter_count", "collective_count",
                        "gather_count", "wide_gather_count")},
            "collectives": m["collectives"],
            "custom_calls": m["custom_calls"],
            "host_transfers": m["host_transfers"],
            "donated_leaves": m["donated_leaves"],
            "compile_seconds": timing,
            "info": built.info,
            **({"delta": delta_info} if delta_info else {}),
        }
    findings.extend(contracts_mod.scenario_pins())
    summary = {"entries": entries_summary,
               "scenario_pins": "checked",
               "findings": len(findings)}
    return findings, summary
