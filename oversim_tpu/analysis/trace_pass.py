"""Trace-time pass: run each registered entry point twice and fail on
recompilation across same-shape calls, tracer leaks, or implicit host
syncs inside the harnessed window.

Why not ``jax.transfer_guard``: on the CPU backend (the tier-1 test
platform) the device→host transfer guards are no-ops — ``bool(x > 0)``
on a committed array does not trip ``transfer_guard_device_to_host
("disallow")``.  The harness instead patches the array dunders that ARE
the implicit-sync surface (``ArrayImpl.__bool__`` / ``__index__`` /
``__int__`` / ``__float__`` / ``__array__``) plus ``jax.device_get``,
and counts hits while an entry executes.  Recompiles are detected via
the jit wrapper's ``_cache_size()`` (a second same-shape call must not
add a cache entry); leaks via ``jax.checking_leaks()`` around the first
(tracing) call.
"""

from __future__ import annotations

import contextlib

from oversim_tpu.analysis.findings import Finding

_SYNC_DUNDERS = ("__bool__", "__index__", "__int__", "__float__",
                 "__array__")


class HostSyncMonitor:
    """Counts implicit device→host syncs while active.

    Patches ``jax._src.array.ArrayImpl``'s conversion dunders and the
    ``jax.device_get`` module attribute; restores them on exit.  The
    originals still run — the monitor observes, it does not block, so a
    harnessed entry that genuinely syncs still completes and the finding
    reports the real count."""

    def __init__(self):
        self.syncs = {}            # dunder name -> count
        self.device_gets = 0
        self._saved = {}

    @property
    def total_syncs(self) -> int:
        return sum(self.syncs.values())

    def __enter__(self):
        import jax
        from jax._src import array as _array
        cls = _array.ArrayImpl
        mon = self

        def wrap(name, orig):
            def patched(self_, *a, **kw):
                mon.syncs[name] = mon.syncs.get(name, 0) + 1
                return orig(self_, *a, **kw)
            return patched

        for name in _SYNC_DUNDERS:
            orig = getattr(cls, name, None)
            if orig is None:
                continue
            self._saved[name] = orig
            setattr(cls, name, wrap(name, orig))

        orig_get = jax.device_get

        def patched_get(*a, **kw):
            mon.device_gets += 1
            return orig_get(*a, **kw)

        self._saved["device_get"] = (jax, orig_get)
        jax.device_get = patched_get
        return self

    def __exit__(self, *exc):
        from jax._src import array as _array
        for name, orig in self._saved.items():
            if name == "device_get":
                mod, fn = orig
                mod.device_get = fn
            else:
                setattr(_array.ArrayImpl, name, orig)
        self._saved.clear()
        return False


def _cache_size(fn):
    try:
        return fn._cache_size()
    except Exception:
        return None


def harness_entry(name: str, built, contract) -> tuple:
    """Run one entry twice under the harness: (findings, stats)."""
    import jax

    findings = []
    stats = {}

    # call 1: trace + compile under the leak checker
    leak = None
    try:
        cm = (jax.checking_leaks() if contract.check_leaks
              else contextlib.nullcontext())
        with cm:
            out = built.fn(*built.make_args())
            jax.block_until_ready(out)
    except Exception as e:                          # checking_leaks raises
        if "Leaked" in str(e) or "leak" in type(e).__name__.lower():
            leak = str(e).splitlines()[0]
        else:
            raise
    if leak:
        findings.append(Finding(
            pass_name="trace", rule="tracer-leak", where=name,
            message=f"tracer leaked out of the traced function: {leak}",
            measured=1, limit=0))
        return findings, {"leak": leak}

    baseline = _cache_size(built.fn)

    # call 2: same shapes — must hit the cache, must not touch the host.
    # Fresh args are made OUTSIDE the monitor: init legitimately runs
    # host-side; the contract is about the dispatch itself.
    args = built.make_args()
    with HostSyncMonitor() as mon:
        out = built.fn(*args)
    jax.block_until_ready(out)

    after = _cache_size(built.fn)
    stats["cache_size"] = after
    if baseline is not None and after is not None:
        recompiles = after - baseline
        stats["recompiles"] = recompiles
        if recompiles > contract.max_recompiles:
            findings.append(Finding(
                pass_name="trace", rule="recompile", where=name,
                message="a second same-shape call recompiled — the "
                        "entry's cache key is unstable (unhashable "
                        "static arg, fresh closure, or weak-type drift) "
                        "and every serving window would pay a compile",
                measured=recompiles, limit=contract.max_recompiles))
    stats["host_syncs"] = dict(mon.syncs)
    stats["device_gets"] = mon.device_gets
    if mon.total_syncs > contract.max_host_syncs:
        findings.append(Finding(
            pass_name="trace", rule="host-sync", where=name,
            message="implicit device→host syncs "
                    f"({', '.join(sorted(mon.syncs))}) inside the "
                    "dispatch window — a __bool__/__index__/__float__ "
                    "forced the host to block on device values",
            measured=mon.syncs, limit=contract.max_host_syncs))
    if mon.device_gets > contract.max_device_gets:
        findings.append(Finding(
            pass_name="trace", rule="device-get", where=name,
            message="jax.device_get inside the dispatch window — "
                    "fetches belong to the window-boundary drain",
            measured=mon.device_gets, limit=contract.max_device_gets))
    return findings, stats


def run(ctx, selected=None, *, progress=None, builds=None):
    """The whole pass over the selected registry entries.  ``builds``:
    optional shared ``{name: EntryBuild}`` cache so the CLI constructs
    each entry once across passes."""
    from oversim_tpu.analysis import contracts as contracts_mod

    findings = []
    entries_summary = {}
    for entry in contracts_mod.entries(selected):
        if progress:
            progress(f"trace: harnessing {entry.name} ...")
        if builds is not None and entry.name in builds:
            built = builds[entry.name]
        else:
            built = entry.build(ctx)
            if builds is not None:
                builds[entry.name] = built
        f, stats = harness_entry(entry.name, built, entry.contract)
        findings.extend(f)
        entries_summary[entry.name] = stats
    summary = {"entries": entries_summary, "findings": len(findings)}
    return findings, summary
