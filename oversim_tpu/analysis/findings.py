"""Finding records + the machine-readable verdict document.

Every analysis pass (hlo / trace / ast) reduces to a list of
:class:`Finding` rows; ``scripts/analyze.py`` serializes them as ONE
JSON document on stdout (and optionally ``--json PATH``) and exits
non-zero when any finding is an error.  The document's compact
``verdict`` form feeds ``run_manifest``'s ``hlo_budget`` field
(oversim_tpu/telemetry.py ``analysis_verdict``) so every bench/campaign/
service artifact records which contract revision its graph passed.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class Finding:
    """One contract breach (or informational note) from a pass."""

    pass_name: str          # "hlo" | "trace" | "ast"
    rule: str               # e.g. "full-pool-sorts", "host-item"
    where: str              # entry-point name or "path/file.py:LINE"
    message: str
    measured: object = None     # what the pass saw
    limit: object = None        # what the contract allows
    severity: str = "error"     # "error" fails the run; "info" does not

    def to_dict(self) -> dict:
        d = {"pass": self.pass_name, "rule": self.rule,
             "where": self.where, "message": self.message,
             "severity": self.severity}
        if self.measured is not None:
            d["measured"] = self.measured
        if self.limit is not None:
            d["limit"] = self.limit
        return d


def errors(findings) -> list:
    return [f for f in findings if f.severity == "error"]


def document(findings, passes: dict, *, fast: bool) -> dict:
    """The analyzer's single JSON output document."""
    errs = errors(findings)
    return {
        "kind": "graph_contract_verdict",
        "ok": not errs,
        "fast": bool(fast),
        "errors": len(errs),
        "passes": passes,
        "findings": [f.to_dict() for f in findings],
    }


def verdict_summary(doc: dict) -> dict:
    """Compact form of :func:`document` for run_manifest embedding."""
    hlo = doc.get("passes", {}).get("hlo") or {}
    return {
        "ok": doc.get("ok"),
        "fast": doc.get("fast"),
        "errors": doc.get("errors", 0),
        "entries": sorted(hlo.get("entries", {})),
        "passes": sorted(k for k, v in doc.get("passes", {}).items() if v),
    }


def write_document(doc: dict, path) -> None:
    """Atomic write (tmp + replace), like every other artifact."""
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, str(path))
