"""Host-keyed persistent-compile-cache path.

XLA-CPU AOT executables embed machine features; an entry compiled on a
different host poisons the cache with load-time machine-feature
mismatches (the round-4 goldens-regen failure).  Keying the cache
directory on the CPU model + ISA flags makes a foreign entry simply
invisible instead of fatal.  Pure stdlib — safe to import before jax.
"""

from __future__ import annotations

import hashlib
import platform


def cache_dir(prefix: str = "/tmp/oversim_jax_cache") -> str:
    sig = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            lines = f.read().splitlines()
        sig += "".join(ln for ln in lines
                       if ln.startswith(("model name", "flags")))[:8192]
    except OSError:
        sig += platform.processor() or ""
    return prefix + "_" + hashlib.sha1(sig.encode()).hexdigest()[:10]
