"""Host/device-keyed compile-cache setup shared by every runner script.

Two jobs:

* :func:`cache_dir` — the host-keyed persistent-compile-cache path.
  XLA-CPU AOT executables embed machine features; an entry compiled on
  a different host poisons the cache with load-time machine-feature
  mismatches (the round-4 goldens-regen failure).  Keying the cache
  directory on the CPU model + ISA flags makes a foreign entry simply
  invisible instead of fatal.
* :func:`enable` — the ONE compile-cache boilerplate block.  Before this
  helper existed, six scripts each carried the same zstandard poisoning
  + x64 + ``jax_compilation_cache_dir`` stanza (bench.py,
  campaign_run.py, service_run.py, hlo_breakdown.py, diag_ring64.py,
  dev_dht_*.py); drift between the copies is how the round-4 cache
  poisoning shipped.  ``persistent=False`` is the per-script opt-out —
  this box's XLA-CPU ``executable.serialize()`` segfaults sporadically
  on big sim-step graphs (tests/conftest.py), so the CPU tier disables
  persistence entirely.

:func:`device_signature` keys the AOT export artifacts
(oversim_tpu/aot/) on the accelerator actually visible at warm-up time;
:func:`host_signature` is the raw CPU identity string the cache dir
hashes.  Module import stays pure stdlib — safe before jax.
"""

from __future__ import annotations

import hashlib
import platform
import sys

_CPUINFO = "/proc/cpuinfo"


def host_signature(cpuinfo_path: str = _CPUINFO) -> str:
    """CPU identity string: machine arch + model name + ISA flags.
    Falls back to ``platform.processor()`` when cpuinfo is unreadable
    (non-Linux, restricted /proc)."""
    sig = platform.machine()
    try:
        with open(cpuinfo_path) as f:
            lines = f.read().splitlines()
        sig += "".join(ln for ln in lines
                       if ln.startswith(("model name", "flags")))[:8192]
    except OSError:
        sig += platform.processor() or ""
    return sig


def cache_dir(prefix: str = "/tmp/oversim_jax_cache", *,
              cpuinfo_path: str = _CPUINFO) -> str:
    sig = host_signature(cpuinfo_path)
    return prefix + "_" + hashlib.sha1(sig.encode()).hexdigest()[:10]


def device_signature() -> str:
    """Identity of the visible accelerator set, for keying exported AOT
    artifacts: ``platform:kind0[+kind1...]:xN``.  Imports jax lazily —
    call only after the backend env (JAX_PLATFORMS/XLA_FLAGS) is set."""
    import jax
    devs = jax.devices()
    if not devs:
        return "none:x0"
    kinds = sorted({str(getattr(d, "device_kind", "?")) for d in devs})
    return f"{devs[0].platform}:{'+'.join(kinds)}:x{len(devs)}"


def enable(*, persistent: bool = True, min_compile_secs: float = 1.0,
           prefix: str = "/tmp/oversim_jax_cache",
           x64: bool = True) -> str | None:
    """Configure jax's compile cache the one blessed way.

    Poisons the zstandard C extension (segfaults on this box), nulls the
    already-bound ``compilation_cache`` module references when jax beat
    us to the import, enables x64, then either points the persistent
    cache at the host-keyed directory (``persistent=True``; returns the
    path) or disables persistence entirely (``persistent=False``; the
    CPU-tier opt-out — returns None).  Call AFTER platform env vars are
    final; safe whether or not jax is already imported.
    """
    sys.modules["zstandard"] = None
    import jax
    from jax._src import compilation_cache as _cc
    for attr in ("zstandard", "zstd"):
        if getattr(_cc, attr, None) is not None:
            setattr(_cc, attr, None)
    if x64:
        jax.config.update("jax_enable_x64", True)
    if not persistent:
        jax.config.update("jax_enable_compilation_cache", False)
        return None
    d = cache_dir(prefix)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    return d
