"""Result recording: OMNeT++-format .vec/.sca output for a running sim.

The reference records every statistic through the OMNeT++ envir —
cOutVector time series into ``results/*.vec`` and finish()-time
scalars into ``results/*.sca`` (GlobalStatistics.cc recordScalar /
addStdDev; **.vector-recording flags in simulations/default.ini) — and
post-processing tooling consumes those textual formats.

The TPU build batches: the engine folds per-tick events into device
accumulators, and the recorder SAMPLES the running simulation at a
host-side period (one snapshot per ``run_until`` chunk boundary),
appending whole row-blocks per flush.  The formatter is native C
(native/vecwriter.c, built lazily like native/tracescan.c) so
million-row vector files write at memory bandwidth; a pure-Python
writer with identical output is the fallback.

Usage:
    rec = VectorRecorder(sim, "out.vec", run_id="Chord-0")
    state = rec.run(state, t_sim=600.0, sample_every=5.0)
    rec.close()
    write_scalars(sim, state, "out.sca", run_id="Chord-0")

Recorded vectors: every engine counter plus the workload counters and
the alive population — the same quantities the reference's vectors
cover for its KPI plots (delivered/sent over time, population, drops).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

NS = 1_000_000_000

_ROOT = Path(__file__).resolve().parent.parent
_SRC = _ROOT / "native" / "vecwriter.c"
_SO = _ROOT / "native" / "vecwriter.so"
_lock = threading.Lock()
_lib = None
_failed = False


def _build() -> bool:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return True
    for cc in ("cc", "gcc", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", str(_SRC), "-o",
                 str(_SO)], capture_output=True, timeout=120)
            if r.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load():
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed:
            return _lib
        if not _build():
            _failed = True
            # one-line warning, once: the fallback is byte-identical but
            # ~50x slower on million-row vectors — a silent downgrade
            # would look like a perf regression with no cause
            import sys
            sys.stderr.write(
                "oversim_tpu.recorder: native vecwriter build failed — "
                "using the pure-Python .vec writer (byte-identical "
                "output, slower on large vectors)\n")
            return None
        lib = ctypes.CDLL(str(_SO))
        lib.vw_open.restype = ctypes.c_void_p
        lib.vw_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.vw_declare.restype = ctypes.c_int
        lib.vw_declare.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p]
        lib.vw_rows.restype = None
        lib.vw_rows.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_long,
                                ctypes.POINTER(ctypes.c_double),
                                ctypes.POINTER(ctypes.c_double)]
        lib.vw_scalar.restype = None
        lib.vw_scalar.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_double]
        lib.vw_close.restype = None
        lib.vw_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class _PyWriter:
    """Fallback with byte-identical output to native/vecwriter.c."""

    def __init__(self, path, run_id):
        self.f = open(path, "w")
        self.next_id = 0
        self.f.write(f"version 2\nrun {run_id}\n")

    def declare(self, module, name):
        vid = self.next_id
        self.next_id += 1
        self.f.write(f"vector {vid} {module} {name} TV\n")
        return vid

    def rows(self, vid, t, v):
        w = self.f.write
        for ti, vi in zip(t, v):
            w(f"{vid}\t{ti:.9g}\t{vi:.12g}\n")

    def scalar(self, module, name, value):
        self.f.write(f"scalar {module} {name} {value:.12g}\n")

    def close(self):
        self.f.close()


class _CWriter:
    def __init__(self, lib, path, run_id):
        self.lib = lib
        self.h = lib.vw_open(str(path).encode(), run_id.encode())
        if not self.h:
            raise OSError(f"vw_open failed: {path}")

    def declare(self, module, name):
        return self.lib.vw_declare(self.h, module.encode(),
                                   name.encode())

    def rows(self, vid, t, v):
        t = np.ascontiguousarray(t, np.float64)
        v = np.ascontiguousarray(v, np.float64)
        self.lib.vw_rows(
            self.h, vid, len(t),
            t.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))

    def scalar(self, module, name, value):
        self.lib.vw_scalar(self.h, module.encode(), name.encode(),
                           float(value))

    def close(self):
        self.lib.vw_close(self.h)
        self.h = None


def _writer(path, run_id):
    lib = _load()
    if lib is not None:
        return _CWriter(lib, path, run_id)
    return _PyWriter(path, run_id)


class VectorRecorder:
    """Samples a Simulation's counters into an OMNeT++ .vec file."""

    MODULE = "OverSimTpu.globalStatistics"

    def __init__(self, sim, path, run_id: str = "run-0"):
        self.sim = sim
        self.w = _writer(path, run_id)
        self._ids = {}
        self._buf_t = []
        self._buf = {}

    def _vec(self, name):
        if name not in self._ids:
            self._ids[name] = self.w.declare(self.MODULE, name)
            self._buf[name] = []
        return self._ids[name]

    def sample(self, state):
        """Snapshot the counter set at the state's current sim time."""
        out = self.sim.summary(state)
        t = out["_t_sim"]
        self._buf_t.append(t)
        flat = {"aliveNodes": float(out["_alive"])}
        for k, v in out.items():
            if k.startswith("_") and k != "_engine":
                continue
            if k == "_engine":
                for ek, evv in v.items():
                    flat[f"engine.{ek}"] = float(evv)
            elif isinstance(v, dict):
                flat[f"{k}.mean"] = float(v.get("mean", 0.0))
            elif isinstance(v, (int, float)):
                flat[k] = float(v)
        for name, val in flat.items():
            self._vec(name)
            self._buf[name].append(val)

    def run(self, state, t_sim: float, sample_every: float = 10.0):
        """run_until with periodic sampling (vector-recording-interval)."""
        t = float(int(state.t_now)) / NS  # analysis: allow(device-sync)
        while t < t_sim:
            t = min(t + sample_every, t_sim)
            state = self.sim.run_until(state, t)
            t = float(int(state.t_now)) / NS  # analysis: allow(device-sync)
            self.sample(state)
        return state

    def close(self):
        for name, vid in self._ids.items():
            vals = self._buf[name]
            self.w.rows(vid, self._buf_t[:len(vals)], vals)
        self.w.close()


def write_scalars(sim, state, path, run_id: str = "run-0"):
    """finish()-time .sca dump (GlobalStatistics recordScalar set)."""
    w = _writer(path, run_id)
    mod = VectorRecorder.MODULE
    out = sim.summary(state)
    rename = {"_alive": "aliveNodes", "_t_sim": "simTime",
              "_ticks": "ticks"}
    for k, v in out.items():
        if k == "_engine":
            for ek, evv in v.items():
                w.scalar(mod, f"engine.{ek}", float(evv))
        elif isinstance(v, dict):
            for kk in ("mean", "stddev", "min", "max", "count"):
                if kk in v:
                    w.scalar(mod, f"{k}.{kk}", float(v[kk]))
        elif isinstance(v, (int, float)):
            w.scalar(mod, rename.get(k, k), float(v))
    w.close()
