"""Checkpoint / resume: snapshot the full simulation state.

The reference has NO simulation-state snapshotting — only per-peer
context survival across rejoins (BaseOverlay.cc:823-831 restoreContext;
SURVEY.md §5 "Checkpoint/resume").  The TPU rebuild's state is one pytree
of device arrays (engine/sim.py SimState), so a checkpoint is a flat
array dump and resume is exact: a restored run continues bit-identically
(same RNG key, same pool contents, same timers).

Format: one ``.npz`` with the pytree leaves in flatten order plus a
structure fingerprint.  Restoring requires a structurally identical
state (same Simulation configuration — logic type, N, engine params);
the fingerprint check turns mismatches into clear errors instead of
silent corruption.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

FORMAT = "oversim-tpu-ckpt-v1"


def _fingerprint(leaves) -> str:
    sig = ";".join(f"{tuple(x.shape)}:{x.dtype}" for x in leaves)
    return hashlib.sha1(sig.encode()).hexdigest()


def save(path: str, state) -> None:
    """Write ``state`` (any pytree of arrays, e.g. SimState) to ``path``."""
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)}
    np.savez_compressed(
        path, __format__=np.asarray(FORMAT),
        __fingerprint__=np.asarray(_fingerprint(leaves)), **arrays)


def load(path: str, example):
    """Restore a checkpoint into the structure of ``example``.

    ``example`` is a state with the same configuration (typically
    ``sim.init()``); its values are discarded, only the pytree structure
    and array shapes/dtypes are used.
    """
    data = np.load(path, allow_pickle=False)
    if str(data["__format__"]) != FORMAT:
        raise ValueError(f"not an oversim-tpu checkpoint: {path}")
    leaves, treedef = jax.tree.flatten(example)
    want = _fingerprint(leaves)
    got = str(data["__fingerprint__"])
    if want != got:
        raise ValueError(
            "checkpoint structure mismatch (different Simulation "
            f"configuration): checkpoint {got[:12]} vs example {want[:12]}")
    new = []
    for i, ex in enumerate(leaves):
        arr = data[f"leaf{i}"]
        new.append(jnp.asarray(arr, dtype=ex.dtype))
    return jax.tree.unflatten(treedef, new)
