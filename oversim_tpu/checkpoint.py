"""Checkpoint / resume: snapshot the full simulation state.

The reference has NO simulation-state snapshotting — only per-peer
context survival across rejoins (BaseOverlay.cc:823-831 restoreContext;
SURVEY.md §5 "Checkpoint/resume").  The TPU rebuild's state is one pytree
of device arrays (engine/sim.py SimState), so a checkpoint is a flat
array dump and resume is exact: a restored run continues bit-identically
(same RNG key, same pool contents, same timers).

Format ``oversim-tpu-ckpt-v2``: one ``.npz`` with the pytree leaves in
flatten order, a structure fingerprint, and a JSON ``__meta__`` manifest
(tick / t_now, config sha256, git rev, plus caller extras such as the
service loop's window bookkeeping).  Restoring requires a structurally
identical state (same Simulation configuration — logic type, N, engine
params); the fingerprint check turns shape mismatches into clear errors
instead of silent corruption, and ``expect_config`` additionally refuses
a checkpoint whose recorded config hash names a DIFFERENT scenario that
happens to share the array layout.  v1 checkpoints (no meta) still load.

Writes are KILL-SAFE and POWER-LOSS-SAFE: the ``.npz`` is written to
``path + ".tmp"``, fsynced, ``os.replace``d, and then the CONTAINING
DIRECTORY is fsynced — a SIGKILL at any point leaves either the previous
complete checkpoint or the new complete one, and a power loss after the
rename cannot roll it back (an unfsynced directory entry may be lost on
crash even when the file data survived).  Platforms where directories
refuse fsync (some network/overlay filesystems raise EINVAL/EBADF) are
tolerated: the rename-level atomicity still holds there.

RESHARD-AWARE META: campaign-stacked checkpoints record the stack layout
(``meta["stack"]`` — leading axis extent + per-replica fingerprint) so
:mod:`oversim_tpu.elastic.reshard` can restore them at a DIFFERENT
replica count; callers (fleet workers, service loops over a Campaign)
additionally record ``meta["campaign"]`` (``Campaign.describe()``) so the
grown-slot re-seed is checked against the original base seed/grid.
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

FORMAT = "oversim-tpu-ckpt-v2"
FORMAT_V1 = "oversim-tpu-ckpt-v1"


def _fingerprint(leaves) -> str:
    sig = ";".join(f"{tuple(x.shape)}:{x.dtype}" for x in leaves)
    return hashlib.sha1(sig.encode()).hexdigest()


def _git_rev() -> str | None:
    from oversim_tpu import telemetry as telemetry_mod
    return telemetry_mod.git_rev()


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so the ``os.replace``
    rename itself is durable (file-data fsync does not persist the
    directory entry; a power loss could otherwise roll the rename back).
    Filesystems that refuse directory fsync (EINVAL/EBADF on some
    network/overlay mounts) are tolerated — rename atomicity still holds
    there, only power-loss durability is best-effort."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save(path: str, state, meta: dict | None = None) -> None:
    """Atomically write ``state`` (any pytree of arrays, e.g. SimState)
    to ``path``.

    ``meta`` is an optional JSON-serializable manifest merged into the
    checkpoint's ``__meta__`` record; ``tick``/``t_now`` (read off the
    state when it carries those attributes — scalars solo, lists for
    stacked campaign state), ``git_rev`` and ``format`` are filled in
    automatically when absent.  The write is tmp+rename atomic: a kill
    mid-write never clobbers an existing checkpoint.
    """
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)}
    m = dict(meta or {})
    m.setdefault("format", FORMAT)
    for name in ("tick", "t_now"):
        v = getattr(state, name, None)
        if v is not None and name not in m:
            m[name] = np.asarray(v).tolist()
    if "git_rev" not in m:
        m["git_rev"] = _git_rev()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f, __format__=np.asarray(FORMAT),
            __fingerprint__=np.asarray(_fingerprint(leaves)),
            __meta__=np.asarray(json.dumps(m, sort_keys=True)),
            **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path)


def read_meta(path: str) -> dict:
    """The checkpoint's ``__meta__`` manifest without touching the array
    payload ({"format": "oversim-tpu-ckpt-v1"} for v1 checkpoints)."""
    with np.load(path, allow_pickle=False) as data:
        fmt = str(data["__format__"])
        if fmt == FORMAT_V1:
            return {"format": FORMAT_V1}
        if fmt != FORMAT:
            raise ValueError(f"not an oversim-tpu checkpoint: {path}")
        return json.loads(str(data["__meta__"]))


def load_raw(path: str):
    """The checkpoint's leaves (flatten order, host numpy arrays) plus
    its meta manifest, WITHOUT an example structure.

    The reshard path (oversim_tpu/elastic/reshard.py) needs the raw
    arrays at their CHECKPOINTED replica extent before unflattening into
    a campaign of a different size — :func:`load` can't express that
    (its example fixes every shape).  No fingerprint check here; the
    caller is responsible for structural validation against whatever it
    unflattens into."""
    with np.load(path, allow_pickle=False) as data:
        fmt = str(data["__format__"])
        if fmt not in (FORMAT, FORMAT_V1):
            raise ValueError(f"not an oversim-tpu checkpoint: {path}")
        meta = ({"format": FORMAT_V1} if fmt == FORMAT_V1
                else json.loads(str(data["__meta__"])))
        meta.setdefault("format", fmt)
        leaves = []
        while f"leaf{len(leaves)}" in data.files:
            leaves.append(data[f"leaf{len(leaves)}"])
    return leaves, meta


def load(path: str, example, *, expect_config: str | None = None):
    """Restore a checkpoint into the structure of ``example``.

    ``example`` is a state with the same configuration (typically
    ``sim.init()``); its values are discarded, only the pytree structure
    and array shapes/dtypes are used.

    ``expect_config`` — a ``telemetry.config_hash`` of the scenario the
    caller is about to resume.  A v2 checkpoint recording a DIFFERENT
    ``config_hash`` is refused even when the array layout matches (two
    scenarios can share shapes yet disagree on every static parameter);
    v1 checkpoints carry no hash and pass the check on fingerprint alone.
    """
    data = np.load(path, allow_pickle=False)
    fmt = str(data["__format__"])
    if fmt not in (FORMAT, FORMAT_V1):
        raise ValueError(f"not an oversim-tpu checkpoint: {path}")
    meta = ({} if fmt == FORMAT_V1
            else json.loads(str(data["__meta__"])))
    if expect_config is not None:
        got = meta.get("config_hash")
        if got is not None and got != expect_config:
            raise ValueError(
                "checkpoint scenario mismatch: checkpoint was written by "
                f"config {got} but this run is config {expect_config} "
                f"({path})")
    leaves, treedef = jax.tree.flatten(example)
    want = _fingerprint(leaves)
    got = str(data["__fingerprint__"])
    if want != got:
        raise ValueError(
            "checkpoint structure mismatch (different Simulation "
            f"configuration): checkpoint {got[:12]} vs example {want[:12]}")
    new = []
    for i, ex in enumerate(leaves):
        arr = data[f"leaf{i}"]
        new.append(jnp.asarray(arr, dtype=ex.dtype))
    return jax.tree.unflatten(treedef, new)
