"""Quon — quadrant-based spatial AOI overlay (QuON), vectorized.

TPU-native rebuild of the reference Quon (src/overlay/quon/Quon.{h,cc}:
quadtree-quadrant AOI overlay — per-quadrant *binding* neighbors keep
the overlay connected in every direction while *direct* neighbors cover
the AOI disc; softstate alive timeouts, dynamic AOI adaptation,
params default.ini:338-348).

Engine mapping: shares the whole Vast machinery (overlay/vast.py —
greedy point-query join, MOVE multicast + HINT discovery, soft-state
pruning); the neighbor-set admission is the QuON rule: the position
plane around the node is split into four quadrants and the NEAREST
candidate in each quadrant is always retained (binding neighbor,
Quon.h binding/direct classification) before the remaining slots fill
with the nearest direct neighbors.  This guarantees a neighbor in every
direction — the property the reference's quadrant sets exist for."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from oversim_tpu.core import keys as K
from oversim_tpu.overlay.vast import (NO_NODE, VastLogic, VastParams)

I32 = jnp.int32
F32 = jnp.float32


@dataclasses.dataclass(frozen=True)
class QuonParams(VastParams):
    """default.ini:338-348 (AOI + softstate timeouts)."""


class QuonLogic(VastLogic):
    """Vast machinery with QuON quadrant-binding neighbor admission."""

    PREFIX = "quon"

    def _nbr_put(self, st, cands, cand_pos, now, me_pos, node_idx):
        d = self.p.max_nbr
        cands = jnp.where(cands == node_idx, NO_NODE, cands)
        aug = jnp.concatenate([st.nbr, cands])
        augp = jnp.concatenate([st.nbr_pos, cand_pos])
        augs = jnp.concatenate([st.nbr_seen,
                                jnp.where(cands != NO_NODE, now, 0)])
        rev = aug[::-1]
        dup = K.dup_mask(rev)[::-1]
        aug = jnp.where(dup, NO_NODE, aug)
        delta = augp - me_pos[None, :]
        dist = jnp.sqrt(jnp.sum(delta * delta, axis=-1))
        dist = jnp.where(aug == NO_NODE, jnp.float32(1e30), dist)
        # quadrant classification (QuON binding neighbors): the nearest
        # candidate per quadrant sorts ahead of every direct neighbor
        quad = (delta[:, 0] > 0).astype(I32) * 2 + (
            delta[:, 1] > 0).astype(I32)
        binding = jnp.zeros(aug.shape, bool)
        for q in range(4):
            inq = (quad == q) & (aug != NO_NODE)
            qd = jnp.where(inq, dist, jnp.float32(1e30))
            jmin = jnp.argmin(qd)
            binding = binding.at[jmin].set(
                jnp.where(jnp.any(inq), True, binding[jmin]))
        sortkey = jnp.where(binding, dist, dist + jnp.float32(1e9))
        order = jnp.argsort(sortkey)  # analysis: allow(sort-call)
        aug, augp, augs = aug[order], augp[order], augs[order]
        return dataclasses.replace(
            st, nbr=aug[:d], nbr_pos=augp[:d], nbr_seen=augs[:d])
