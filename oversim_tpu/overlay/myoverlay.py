"""MyOverlay — the tutorial overlay skeleton.

Rebuild of src/overlay/myoverlay/ (490 LoC; the website tutorial's
minimal example, omnetpp.ini MyConfig :502): the smallest complete
overlay logic the engine accepts, for framework users to copy when
writing a new protocol.  Ring routing with a single successor pointer:

  * join: draw a bootstrap peer from the oracle and greedy-walk
    RING_JOIN messages clockwise until the responsible node adopts the
    joiner (like the tutorial's neighbor exchange);
  * routing: ``findNode`` returns self when the key falls in
    (pred, me], else the successor — O(N) hops, deliberately naive;
  * maintenance: a periodic HELLO to the successor; a silent successor
    is replaced at the next join retry.

Every engine hook (init/reset/ready_mask/next_event/step) is written in
the plainest possible style — read this file top to bottom to learn the
logic interface (engine/logic.py)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.dummy import MyApp
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)

DEAD, JOINING, READY = 0, 1, 2

RING_JOIN = 140     # a=joiner
RING_JOIN_ACK = 141  # a=your new successor
RING_HELLO = 142


@dataclasses.dataclass(frozen=True)
class MyOverlayParams:
    join_delay: float = 10.0
    hello_interval: float = 20.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MyOverlayState:
    state: jnp.ndarray   # [N]
    succ: jnp.ndarray    # [N] the ONE routing pointer
    pred: jnp.ndarray    # [N]
    t_join: jnp.ndarray
    t_hello: jnp.ndarray
    app: object
    app_glob: object


class MyOverlayLogic:
    """Tutorial logic (engine interface: engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: MyOverlayParams = MyOverlayParams(), app=None):
        self.key_spec = spec
        self.p = params
        self.app = app or MyApp()

    def stat_spec(self):
        a = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(a["scalars"]) + ("ring_hops",),
            hists=tuple(a["hists"]),
            counters=tuple(a["counters"]) + ("ring_joins",))

    def split(self, st):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def init(self, rng, n: int) -> MyOverlayState:
        return MyOverlayState(
            state=jnp.zeros((n,), I32),
            succ=jnp.full((n,), NO_NODE, I32),
            pred=jnp.full((n,), NO_NODE, I32),
            t_join=jnp.full((n,), T_INF, I64),
            t_hello=jnp.full((n,), T_INF, I64),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng))

    def reset(self, st, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st):
        return st.state == READY

    def next_event(self, st):
        t = jnp.where(st.state == JOINING, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(st.state == READY, st.t_hello, T_INF))
        t = jnp.minimum(t, jnp.where(st.state == READY,
                                     self.app.next_event(st.app), T_INF))
        return t

    def _is_mine(self, ctx, st, me_key, key):
        pred_ok = st.pred != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        return (st.state == READY) & (
            ~pred_ok | K.is_between_r(key, pk, me_key, self.key_spec))

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, spec = self.p, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 4)
        t0, t_end = ctx.t_start, ctx.t_end
        ev = app_base.AppEvents()
        joins = jnp.int32(0)

        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # RING_JOIN: adopt the joiner as predecessor if its key is
            # ours to cover, else pass clockwise
            en = v & (m.kind == RING_JOIN) & (st.state == READY)
            jk = ctx.keys[jnp.maximum(m.a, 0)]
            mine = self._is_mine(ctx, st, me_key, jk)
            adopt = en & mine
            ob.send(adopt, now, m.a, RING_JOIN_ACK, a=node_idx, b=st.pred,
                    size_b=16)
            fwd = en & ~mine & (st.succ != NO_NODE)
            ob.send(fwd, now, jnp.maximum(st.succ, 0), RING_JOIN, a=m.a,
                    hops=m.hops + 1, size_b=16)
            st = dataclasses.replace(
                st, pred=jnp.where(adopt, m.a, st.pred))

            # RING_JOIN_ACK: my successor is the adopter
            en = v & (m.kind == RING_JOIN_ACK) & (st.state == JOINING)
            joins += en.astype(I32)
            st = dataclasses.replace(
                st,
                succ=jnp.where(en, m.src, st.succ),
                pred=jnp.where(en & (m.b != NO_NODE), m.b, st.pred),
                state=jnp.where(en, READY, st.state),
                t_join=jnp.where(en, T_INF, st.t_join),
                t_hello=jnp.where(en, now, st.t_hello),
                app=self.app.on_ready(st.app, en, now, rngs[0]))
            # tell the old predecessor its successor changed
            ob.send(en & (m.b != NO_NODE), now, jnp.maximum(m.b, 0),
                    RING_HELLO, a=node_idx, size_b=16)

            # RING_HELLO: adopt a closer successor
            en = v & (m.kind == RING_HELLO) & (st.state == READY)
            hk = ctx.keys[jnp.maximum(m.a, 0)]
            sk = ctx.keys[jnp.maximum(st.succ, 0)]
            closer = en & (m.a != NO_NODE) & (
                (st.succ == NO_NODE)
                | K.is_between(hk, me_key, sk, spec))
            st = dataclasses.replace(
                st, succ=jnp.where(closer, m.a, st.succ))

            # routed payload: deliver when responsible (the app checks
            # the is_sib flag), else forward clockwise
            en = v & (m.kind == wire.APP_ONEWAY) & (st.state == READY)
            mine = self._is_mine(ctx, st, me_key, m.key)
            ev.value("ring_hops", m.hops.astype(jnp.float32), en & mine)
            ob.send(en & ~mine & (st.succ != NO_NODE), now,
                    jnp.maximum(st.succ, 0), wire.APP_ONEWAY, key=m.key,
                    c=m.c, stamp=m.stamp, hops=m.hops + 1, size_b=m.size_b)
            st = dataclasses.replace(st, app=self.app.on_msg(
                st.app, m, ctx, ob, ev, mine))

        # join timer
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        alone = en_j & (boot == NO_NODE)
        joins += alone.astype(I32)
        st = dataclasses.replace(
            st,
            state=jnp.where(alone, READY, st.state),
            t_hello=jnp.where(alone, now_j, st.t_hello),
            app=self.app.on_ready(st.app, alone, now_j, rngs[2]),
            t_join=jnp.where(en_j & ~alone, now_j + jnp.int64(
                int(p.join_delay * NS)), st.t_join))
        ob.send(en_j & ~alone, now_j, jnp.maximum(boot, 0), RING_JOIN,
                a=node_idx, hops=jnp.int32(0), size_b=16)

        # hello timer
        en_h = (st.state == READY) & (st.t_hello < t_end)
        now_h = jnp.maximum(st.t_hello, t0)
        ob.send(en_h & (st.succ != NO_NODE), now_h,
                jnp.maximum(st.succ, 0), RING_HELLO, a=node_idx, size_b=16)
        st = dataclasses.replace(st, t_hello=jnp.where(
            en_h, now_h + jnp.int64(int(p.hello_interval * NS)),
            st.t_hello))

        # app timer: route the payload clockwise from here
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.succ,
            st.state == READY))
        en_a = (st.state == READY) & (self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[3],
                                     ev, node_idx)
        st = dataclasses.replace(st, app=app)
        mine = self._is_mine(ctx, st, me_key, req.key)
        # local: complete through the app hook; remote: ship clockwise
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=req.want & mine, success=req.want & mine, tag=req.tag,
                target=req.key,
                results=jnp.full((4,), NO_NODE, I32).at[0].set(node_idx),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        ob.send(req.want & ~mine & (st.succ != NO_NODE), now_a,
                jnp.maximum(st.succ, 0), wire.APP_ONEWAY, key=req.key,
                c=ctx.measuring.astype(I32), stamp=now_a,
                hops=jnp.int32(1), size_b=100)

        events = {"c:ring_joins": joins}
        ev.finish(events, self.app.hist_map)
        return st, ob, events
