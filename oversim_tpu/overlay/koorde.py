"""Koorde de Bruijn DHT — extends Chord with digit-shift routing.

TPU-native rebuild of the reference Koorde
(src/overlay/koorde/Koorde.{h,cc}, `class Koorde : public Chord`,
Koorde.h:50; params default.ini:268-277: stabilizeDelay 10s,
successorListSize 16, deBruijnDelay 30s, deBruijnListSize 16,
shiftingBits 4).  Koorde reuses the whole Chord machinery — ring
join/stabilize/notify, successor lists, predecessor pings — and replaces
finger routing with a de Bruijn graph walk:

  * every node maintains a **de Bruijn pointer**: the node responsible
    for (own key << shiftingBits, nudged back by half a successor span),
    plus that node's successors as a backup list
    (handleDeBruijnTimerExpired Koorde.cc:163-229; resolved here via an
    iterative lookup — the engine equivalent of the routed DeBruijnCall);
  * a lookup carries mutable routing state with the MESSAGE — the
    imaginary de Bruijn ``routeKey`` and the bit ``step``
    (KoordeFindNodeExtMessage; Koorde.cc findDeBruijnHop) — mapped onto
    the lookup engine's opaque ext words (common/lookup.py ext_words =
    key lanes + 1; calls carry it in nodes[:EW], responses return the
    updated ext in the nodes tail);
  * at each hop (Koorde::findNode, Koorde.cc:293-358): keys in
    (pred, me] are ours, keys in (me, succ] go to the successor;
    otherwise the walk shifts ``shiftingBits`` destination bits into the
    route key and forwards to the de Bruijn pointer (or the closest
    route-key predecessor in the de Bruijn / successor lists —
    useOtherLookup/useSucList optimizations, both on).

Deviations (documented): the reference's tail recursion when
findDeBruijnHop returns the node itself (Koorde.cc:340-346) is unrolled
``SELF_HOPS`` times and then falls back to the ring successor — bounded
control flow, identical termination, marginally more ring hops in tiny
overlays.  The de Bruijn backup list is filled from the resolution
lookup's sibling set (≤ lookup frontier wide) rather than a full
DeBruijnResponse successor copy.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.core import keys as K
from oversim_tpu.overlay.chord import (ChordLogic, ChordParams, ChordState,
                                       READY, NO_NODE, T_INF)

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000

P_DEBRUIJN = 7          # lookup purpose tag (chord uses 1-3)
SELF_HOPS = 3           # unrolled self-recursion bound (module doc)


@dataclasses.dataclass(frozen=True)
class KoordeParams(ChordParams):
    """default.ini:268-277."""

    stabilize_delay: float = 10.0
    succ_size: int = 16
    # the reference stubs out Chord's fixfingers for Koorde (Koorde.cc
    # handleFixFingersTimerExpired dummy) — park the timer
    fixfingers_delay: float = 1e9
    de_bruijn_delay: float = 30.0
    de_bruijn_size: int = 16
    shifting_bits: int = 4
    use_other_lookup: bool = True
    use_suc_list: bool = True


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KoordeState(ChordState):
    db_node: jnp.ndarray   # [N] i32 — de Bruijn pointer
    db_list: jnp.ndarray   # [N, DL] i32 — its successors (backup)
    t_db: jnp.ndarray      # [N] i64 — de Bruijn timer


class KoordeLogic(ChordLogic):
    """Chord with de Bruijn routing (engine interface unchanged)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: KoordeParams = KoordeParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None, rcfg=None):
        lcfg = lcfg or lk_mod.LookupConfig(ext_words=spec.lanes + 1)
        if lcfg.ext_words != spec.lanes + 1:
            raise ValueError("Koorde needs ext_words == key lanes + 1")
        if rcfg is not None:
            # the de Bruijn routeKey/step ext rides the head of the
            # routed message's nodes field (KoordeFindNodeExtMessage
            # attached to BaseRouteMessage in the reference); chord.py's
            # recursive pre-pass partitions nodes as [ext | visited]
            import dataclasses as _dc
            if rcfg.ext_words != lcfg.ext_words:
                rcfg = _dc.replace(rcfg, ext_words=lcfg.ext_words)
        super().__init__(spec, params, lcfg, app, rcfg=rcfg)
        if (rcfg is not None and getattr(self.app, "rcfg", None) is not None
                and self.app.rcfg.ext_words != rcfg.ext_words):
            # keep the app's reply-transport config in sync with the
            # ext-words rewrite above
            self.app.rcfg = rcfg

    def init(self, rng, n: int) -> KoordeState:
        base = super().init(rng, n)
        kw = {f.name: getattr(base, f.name)
              for f in dataclasses.fields(base)}
        return KoordeState(
            **kw,
            db_node=jnp.full((n,), NO_NODE, I32),
            db_list=jnp.full((n, self.p.de_bruijn_size), NO_NODE, I32),
            t_db=jnp.full((n,), T_INF, I64))

    def next_event(self, st: KoordeState):
        t = super().next_event(st)
        return jnp.minimum(t, jnp.where(st.state == READY, st.t_db, T_INF))

    def _become_ready(self, ctx, st, en, now, rng):
        st = super()._become_ready(ctx, st, en, now, rng)
        return dataclasses.replace(st, t_db=jnp.where(en, now, st.t_db))

    def _handle_failed(self, ctx, st, me_key, node_idx, failed, now):
        """Chord repair + de Bruijn pointer/list repair
        (Koorde::handleFailedNode Koorde.cc:129-160: promote the first
        backup when the pointer dies, compact the list)."""
        st = super()._handle_failed(ctx, st, me_key, node_idx, failed, now)
        any_failed = jnp.any(failed != NO_NODE)
        db_hit = (st.db_node[..., None] == failed).any(-1) & (
            st.db_node != NO_NODE)
        lhit = (st.db_list[..., None] == failed).any(-1) & (
            st.db_list != NO_NODE)
        # compact the backup list (drop failed entries, keep order)
        order = jnp.argsort(jnp.where(lhit, 1, 0), stable=True)  # analysis: allow(sort-call)
        compacted = jnp.where(lhit, NO_NODE, st.db_list)[order]
        new_db = jnp.where(db_hit, compacted[0], st.db_node)
        compacted = jnp.where(
            db_hit, jnp.roll(compacted, -1).at[-1].set(NO_NODE), compacted)
        return dataclasses.replace(
            st,
            db_node=jnp.where(any_failed, new_db, st.db_node),
            db_list=jnp.where(any_failed, compacted, st.db_list))

    # -- de Bruijn timer (handleDeBruijnTimerExpired, Koorde.cc:163) ------

    def _extra_timers(self, ctx, st, ob, me_key, node_idx, t0, t_end, rng):
        p, spec, lcfg = self.p, self.key_spec, self.lcfg
        en = (st.state == READY) & (st.t_db < t_end)
        now = jnp.maximum(st.t_db, t0)

        s0 = st.succ[0]
        s0k = ctx.keys[jnp.maximum(s0, 0)]
        has_succ = s0 != NO_NODE
        # lookup key = (me << s) - (succ[S/2] - me): a little before the
        # exact de Bruijn key for failure redundancy (Koorde.cc:165-173)
        lk_key = K.shl_const(me_key, p.shifting_bits, spec)
        n_succ = jnp.sum((st.succ != NO_NODE).astype(I32))
        mid = st.succ[jnp.clip(n_succ // 2, 0, st.succ.shape[0] - 1)]
        midk = ctx.keys[jnp.maximum(mid, 0)]
        lk_key = jnp.where(has_succ,
                           K.sub(lk_key, K.sub(midk, me_key, spec), spec),
                           lk_key)

        pred_ok = st.pred != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        dl = p.de_bruijn_size

        def pad_dl(vec):
            out = jnp.full((dl,), NO_NODE, I32)
            return out.at[:min(vec.shape[0], dl)].set(vec[:dl])

        # case 1: we are responsible → db = self, list = successors
        own = en & (~has_succ | K.is_between_r(lk_key, me_key, s0k, spec))
        lst1 = pad_dl(st.succ)
        # case 2: predecessor is responsible → db = pred, list = self+succ
        pre = en & ~own & pred_ok & K.is_between_r(lk_key, pk, me_key, spec)
        lst2 = pad_dl(jnp.concatenate([node_idx[None], st.succ]))

        st = dataclasses.replace(
            st,
            db_node=jnp.where(own, node_idx,
                              jnp.where(pre, st.pred, st.db_node)),
            db_list=jnp.where(own, lst1, jnp.where(pre, lst2, st.db_list)))

        # case 3: resolve by lookup (the engine form of the routed
        # DeBruijnCall, Koorde.cc:205-211)
        need_lk = en & ~own & ~pre
        no_db_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_DEBRUIJN))
        slot, have = lk_mod.free_slot(st.lk)
        nxt, sib = self._find_node(ctx, st, me_key, node_idx, lk_key)
        start = need_lk & no_db_lk & have & ~sib & (nxt != NO_NODE)
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(nxt)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start, slot, P_DEBRUIJN, 0, lk_key, seed, now, lcfg))

        return dataclasses.replace(st, t_db=jnp.where(
            en, now + jnp.int64(int(p.de_bruijn_delay * NS)), st.t_db))

    def _on_completion(self, ctx, st, ob, li, comp, en, suc, res, t0):
        """De Bruijn resolution finished: pointer = closest sibling,
        backups = the rest of the returned sibling set."""
        enr = en & (comp["purpose"][li] == P_DEBRUIJN) & suc
        results = comp["results"][li]
        dl = self.p.de_bruijn_size
        lst = results[1:]
        if lst.shape[0] < dl:
            lst = jnp.concatenate(
                [lst, jnp.full((dl - lst.shape[0],), NO_NODE, I32)])
        return dataclasses.replace(
            st,
            db_node=jnp.where(enr, results[0], st.db_node),
            db_list=jnp.where(enr, lst[:dl], st.db_list))

    # -- routing (Koorde::findNode + findDeBruijnHop) ---------------------

    def _walk_pred(self, ctx, lst, key):
        """Closest clockwise predecessor of ``key`` in a node list
        (walkSuccessorList/walkDeBruijnList, Koorde.cc:379-409): entry
        minimizing (key - entry) ring distance; NO_NODE if list empty."""
        spec = self.key_spec
        ek = ctx.keys[jnp.maximum(lst, 0)]
        d = K.sub(jnp.broadcast_to(key, ek.shape), ek, spec)
        d = jnp.where((lst == NO_NODE)[:, None], jnp.uint32(0xFFFFFFFF), d)
        (srt,) = K.sort_by_distance(d, (lst,), approx=True)[1]
        return jnp.where(jnp.any(lst != NO_NODE), srt[0], NO_NODE)

    def _find_start_key(self, me_key, s0k, key):
        """findStartKey (Koorde.cc): imaginary start key within
        (me, succ] aligned to the shifting-bit grid → (route_key, step).
        """
        spec, s = self.key_spec, self.p.shifting_bits
        diff = K.sub(s0k, me_key, spec)
        nbits = jnp.maximum(K.log2_floor(diff, spec), 0)
        # largest nbits' <= nbits with (bits - nbits') % s == 0
        nbits = jnp.maximum(nbits - jnp.mod(nbits - spec.bits, s), 0)
        step = nbits + 1
        new_start = K.shl_dyn(K.shr_dyn(me_key, nbits, spec), nbits, spec)
        tmp_dest = K.shr_dyn(key, spec.bits - nbits, spec)
        new_key = K.add(tmp_dest, new_start, spec)
        ok1 = K.is_between_r(new_key, me_key, s0k, spec)
        bump = self._pow2[jnp.clip(nbits, 0, spec.bits - 1)]
        rk = jnp.where(ok1, new_key, K.add(new_key, bump, spec))
        # degenerate single-node interval: route key = me
        rk = jnp.where(K.eq(diff, jnp.zeros_like(diff)), me_key, rk)
        return rk, step

    def _db_hop(self, ctx, st, me_key, node_idx, key, route_key, step):
        """One findDeBruijnHop evaluation (Koorde.cc findDeBruijnHop).

        Returns (hop, route_key', step')."""
        p, spec, s = self.p, self.key_spec, self.p.shifting_bits
        s0 = st.succ[0]
        s0k = ctx.keys[jnp.maximum(s0, 0)]
        no_db = st.db_node == NO_NODE
        dbk = ctx.keys[jnp.maximum(st.db_node, 0)]
        db0 = st.db_list[0]
        db0k = ctx.keys[jnp.maximum(db0, 0)]

        in_resp = K.is_between_r(route_key, me_key, s0k, spec)

        # shift the next s destination bits into the route key (reference
        # uses LSB-indexed positions bits-step, bits-step-1, ...)
        add_val = jnp.int32(0)
        for i in range(s):
            pos = spec.bits - step - i
            bit = jnp.where(pos >= 0,
                            K.bit(key, jnp.maximum(pos, 0), spec), 0)
            add_val = (add_val << 1) | bit.astype(I32)
        add_key = jnp.zeros((spec.lanes,), U32).at[-1].set(
            add_val.astype(U32))
        rk_shift = K.add(K.shl_const(route_key, s, spec), add_key, spec)

        # in our responsibility → advance and jump along the de Bruijn edge
        walk_db = self._walk_pred(ctx, st.db_list, rk_shift)
        db_direct = (db0 != NO_NODE) & K.is_between_r(rk_shift, dbk, db0k,
                                                      spec)
        hop_db = jnp.where(db_direct | (db0 == NO_NODE), st.db_node,
                           jnp.where(walk_db != NO_NODE, walk_db,
                                     st.db_node))
        if p.use_suc_list:
            hop_nodb = self._walk_pred(ctx, st.succ, rk_shift)
            hop_nodb = jnp.where(hop_nodb == NO_NODE, s0, hop_nodb)
        else:
            hop_nodb = s0
        hop_in = jnp.where(no_db, hop_nodb, hop_db)

        # outside our responsibility → ring-walk toward the route key
        # (breakLookup path; optionally prefer the de Bruijn pointer)
        walk_s = self._walk_pred(ctx, st.succ, route_key)
        hop_out = jnp.where(walk_s != NO_NODE, walk_s, s0)
        if p.use_suc_list:
            better_db = ~no_db & K.is_between(
                dbk, ctx.keys[jnp.maximum(hop_out, 0)], route_key, spec)
            hop_out = jnp.where(better_db, st.db_node, hop_out)

        hop = jnp.where(in_resp, hop_in, hop_out)
        rk_out = jnp.where(in_resp, rk_shift, route_key)
        step_out = jnp.where(in_resp, step + s, step)
        return hop, rk_out, step_out

    def _respond_find(self, ctx, st, me_key, node_idx, m, rmax, pad_nodes):
        """Koorde::findNode (Koorde.cc:293-358) with the lookup ext
        (routeKey, step) unpacked from the call and the updated ext
        repacked into the response tail (lookup.py ext layout)."""
        p, spec, lcfg = self.p, self.key_spec, self.lcfg
        ew = lcfg.ext_words
        key = m.key
        ready = st.state == READY

        ext_in = m.nodes[:ew]
        route_key_in = jax.lax.bitcast_convert_type(
            ext_in[:spec.lanes], U32)
        step_in = ext_in[spec.lanes]

        pred_ok = st.pred != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        s0 = st.succ[0]
        s0k = ctx.keys[jnp.maximum(s0, 0)]
        has_succ = s0 != NO_NODE
        alone = ~pred_ok & ~has_succ

        is_sib = ready & (alone
                          | (~pred_ok & K.eq(key, me_key))
                          | (pred_ok & K.is_between_r(key, pk, me_key,
                                                      spec)))
        succ_case = ready & has_succ & ~is_sib & K.is_between_r(
            key, me_key, s0k, spec)

        # useOtherLookup (Koorde.cc:299-306): if a successor other than
        # the farthest already precedes the key, ring-walk it directly
        n_succ = jnp.sum((st.succ != NO_NODE).astype(I32))
        far = st.succ[jnp.clip(n_succ - 1, 0, st.succ.shape[0] - 1)]
        walk = self._walk_pred(ctx, st.succ, key)
        other_ok = jnp.bool_(p.use_other_lookup) & (walk != NO_NODE) & (
            walk != far)

        # lazy route-key initialization (findDeBruijnHop init path); with
        # no de Bruijn pointer yet the hop is the plain successor and the
        # ext stays unset (breakLookup, Koorde.cc:296-301)
        need_init = step_in == 0
        no_db = st.db_node == NO_NODE
        rk0, step0 = self._find_start_key(me_key, s0k, key)
        rk_cur = jnp.where(need_init, rk0, route_key_in)
        step_cur = jnp.where(need_init, step0, step_in)

        # de Bruijn walk with the self-recursion unrolled (module doc)
        hop = s0
        rk_fin, step_fin = rk_cur, step_cur
        done = jnp.bool_(False)
        for _ in range(SELF_HOPS):
            h, rk2, st2 = self._db_hop(ctx, st, me_key, node_idx, key,
                                       rk_cur, step_cur)
            stop_now = ~done & (h != node_idx)
            hop = jnp.where(stop_now, h, hop)
            rk_fin = jnp.where(stop_now, rk2, rk_fin)
            step_fin = jnp.where(stop_now, st2, step_fin)
            done = done | stop_now
            rk_cur = jnp.where(done, rk_cur, rk2)
            step_cur = jnp.where(done, step_cur, st2)
        # still self after the unroll → ring successor with the advanced
        # route key (bounded fallback; reference recurses)
        rk_fin = jnp.where(done, rk_fin, rk_cur)
        step_fin = jnp.where(done, step_fin, step_cur)

        db_path = ready & ~is_sib & ~succ_case & ~other_ok & ~(
            need_init & no_db)
        nxt = jnp.where(
            is_sib, node_idx,
            jnp.where(succ_case, s0,
                      jnp.where(other_ok, walk,
                                jnp.where(need_init & no_db, s0, hop))))
        nxt = jnp.where(ready, nxt, NO_NODE)

        # response payload: sibling set when responsible, else the hop
        # with the updated ext in the tail; ext passes through untouched
        # on every non-de-Bruijn path
        sib_set = pad_nodes(jnp.concatenate([node_idx[None], st.succ]))
        res = jnp.where(
            is_sib, sib_set,
            jnp.full((rmax,), NO_NODE, I32).at[0].set(nxt))
        ext_key = jnp.where(db_path, rk_fin, route_key_in)
        ext_step = jnp.where(db_path, step_fin, step_in)
        ext_out = jnp.concatenate(
            [jax.lax.bitcast_convert_type(ext_key, I32), ext_step[None]])
        res = jnp.where(is_sib, res, res.at[rmax - ew:].set(ext_out))
        return res, is_sib
