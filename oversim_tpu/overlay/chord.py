"""Chord ring DHT as vectorized per-node logic.

TPU-native rebuild of the reference Chord (src/overlay/chord/Chord.{h,cc} +
ChordSuccessorList/ChordFingerTable), with protocol semantics preserved and
state held as structure-of-arrays:

  * successor list [N, S] node slots kept ring-distance sorted (reference
    ChordSuccessorList: std::map sorted by distance from own key);
  * predecessor [N]; finger table [N, B] (B = key bits) with 2^i targets;
  * aggressive join (rpcJoin Chord.cc:917: responsible node adopts the
    joiner as predecessor, hints its old predecessor in the JoinResponse,
    and sends NEWSUCCESSORHINT to the old predecessor);
  * periodic stabilize (StabilizeCall → successor's predecessor; adopt if
    in (me, succ); then NotifyCall; NotifyResponse carries the successor's
    successor list which replaces ours — Chord.cc:793/rpcStabilize/
    rpcNotify/handleRpcNotifyResponse);
  * periodic fixfingers (handleFixFingersTimerExpired Chord.cc:845: route
    a lookup to me+2^i for every non-trivial finger — offset greater than
    the distance to the successor; trivial fingers are removed).  We mark
    those fingers dirty and repair them one lookup at a time, chained off
    lookup completions (same convergence, bounded concurrency);
  * predecessor liveness via periodic ping (checkPredecessorDelay=5s,
    default.ini:172, handleCheckPredecessorTimerExpired);
  * failure repair (handleFailedNode Chord.cc:502: drop from successor
    list / fingers / predecessor, immediate re-stabilize, rejoin when the
    last successor is gone);
  * findNode (Chord.cc:548): siblings if responsible; successor list if
    key in (me, succ]; otherwise closest preceding node over fingers +
    successor list (closestPreceedingNode Chord.cc:602).

Defaults follow simulations/default.ini:167-183 (joinDelay 10s,
stabilizeDelay 20s, fixfingersDelay 120s, checkPredecessorDelay 5s,
successorListSize 8, aggressiveJoinMode true, iterative routing).

The embedded tier-1 app is pluggable in spirit; this first slice wires
KBRTestApp (apps/kbrtest.py) directly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import kbrtest
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

# node lifecycle (reference BaseOverlay States, BaseOverlay.h:86-102)
DEAD, JOINING, READY = 0, 1, 2

# lookup purposes (owner dispatch tags)
P_JOIN, P_FINGER, P_APP = 1, 2, 3


@dataclasses.dataclass(frozen=True)
class ChordParams:
    """default.ini:167-183."""

    join_delay: float = 10.0
    stabilize_delay: float = 20.0
    fixfingers_delay: float = 120.0
    check_pred_delay: float = 5.0
    succ_size: int = 8
    aggressive_join: bool = True
    rpc_timeout: float = 1.5        # rpcUdpTimeout, default.ini:483


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChordState:
    state: jnp.ndarray         # [N] i32 DEAD/JOINING/READY
    pred: jnp.ndarray          # [N] i32
    succ: jnp.ndarray          # [N, S] i32 ring-sorted, NO_NODE padded
    finger: jnp.ndarray        # [N, B] i32
    finger_dirty: jnp.ndarray  # [N, B] bool
    t_join: jnp.ndarray        # [N] i64
    t_stab: jnp.ndarray        # [N] i64
    t_fix: jnp.ndarray         # [N] i64
    t_cp: jnp.ndarray          # [N] i64
    stab_op: jnp.ndarray       # [N] i32 0=idle 1=stabilize 2=notify pending
    stab_dst: jnp.ndarray      # [N] i32
    stab_to: jnp.ndarray       # [N] i64
    cp_to: jnp.ndarray         # [N] i64 pending predecessor-ping timeout
    lk: lk_mod.LookupState     # [N, L, ...]
    app: kbrtest.KbrTestState  # [N]


def _sort_lanes(dist, payload):
    return K.sort_by_distance(dist, payload)[1]


def _lex_argmin(dist):
    """Index of the lexicographically smallest [C, KL] distance row."""
    idx = jnp.arange(dist.shape[0], dtype=I32)
    (best,) = _sort_lanes(dist, (idx,))
    return best[0]


class ChordLogic:
    """Implements the engine logic interface (engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: ChordParams = ChordParams(),
                 lcfg: lk_mod.LookupConfig = lk_mod.LookupConfig(),
                 app_params: kbrtest.KbrTestParams = kbrtest.KbrTestParams()):
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg
        self.ap = app_params
        self._pow2 = K.pow2_table(spec)          # [B, KL] finger offsets

    # -- engine interface ---------------------------------------------------

    def stat_spec(self) -> stats_mod.StatSpec:
        app = kbrtest.stat_spec(self.ap)
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "chord_joins", "lookup_success", "lookup_failed"),
        )

    def init(self, rng, n: int) -> ChordState:
        del rng
        s, b = self.p.succ_size, self.key_spec.bits
        return ChordState(
            state=jnp.zeros((n,), I32),
            pred=jnp.full((n,), NO_NODE, I32),
            succ=jnp.full((n, s), NO_NODE, I32),
            finger=jnp.full((n, b), NO_NODE, I32),
            finger_dirty=jnp.zeros((n, b), bool),
            t_join=jnp.full((n,), T_INF, I64),
            t_stab=jnp.full((n,), T_INF, I64),
            t_fix=jnp.full((n,), T_INF, I64),
            t_cp=jnp.full((n,), T_INF, I64),
            stab_op=jnp.zeros((n,), I32),
            stab_dst=jnp.full((n,), NO_NODE, I32),
            stab_to=jnp.full((n,), T_INF, I64),
            cp_to=jnp.full((n,), T_INF, I64),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            app=kbrtest.init(n),
        )

    def reset(self, st: ChordState, clear, join, t_now, rng) -> ChordState:
        n = st.state.shape[0]
        fresh = self.init(None, n)
        st = select_tree(clear, fresh, st)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: ChordState):
        return st.state == READY

    def next_event(self, st: ChordState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        for timer in (st.t_stab, st.t_fix, st.t_cp):
            t = jnp.minimum(t, jnp.where(ready, timer, T_INF))
        t = jnp.minimum(t, st.stab_to)
        t = jnp.minimum(t, st.cp_to)
        t = jnp.minimum(t, jnp.where(ready, kbrtest.next_event(st.app), T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        return t

    # -- internals (all per-node; vmapped by the engine) ---------------------

    def _find_node(self, ctx, st, me_key, node_idx, key):
        """Chord::findNode (Chord.cc:548) with numRedundantNodes=1.

        Returns (next_hop i32 slot, is_sibling bool).  NO_NODE next hop
        when not READY (reference returns an empty NodeVector).
        """
        spec = self.key_spec
        ready = st.state == READY
        pred_ok = st.pred != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        succ0 = st.succ[0]
        has_succ = succ0 != NO_NODE
        s0k = ctx.keys[jnp.maximum(succ0, 0)]

        alone = ~pred_ok & ~has_succ
        is_sib = ready & (alone
                          | (~pred_ok & K.eq(key, me_key))
                          | (pred_ok & K.is_between_r(key, pk, me_key, spec)))
        succ_case = ready & has_succ & ~is_sib & K.is_between_r(
            key, me_key, s0k, spec)

        # closest preceding node over fingers + successor list
        cands = jnp.concatenate([st.finger, st.succ])
        cks = ctx.keys[jnp.maximum(cands, 0)]
        me_b = jnp.broadcast_to(me_key, cks.shape)
        key_b = jnp.broadcast_to(key, cks.shape)
        usable = (cands != NO_NODE) & (cands != node_idx) & K.is_between_r(
            cks, me_b, key_b, spec)
        d = K.sub(key_b, cks, spec)            # clockwise candidate→key
        d = jnp.where(usable[:, None], d, UMAX)
        best = cands[_lex_argmin(d)]
        best = jnp.where(jnp.any(usable), best, succ0)  # fallback: successor

        nxt = jnp.where(is_sib, node_idx, jnp.where(succ_case, succ0, best))
        nxt = jnp.where(ready, nxt, NO_NODE)
        return nxt, is_sib

    def _succ_sorted(self, ctx, me_key, node_idx, cands):
        """Ring-distance-sorted unique successor list from candidate slots
        (ChordSuccessorList semantics: excludes self, sorted by clockwise
        distance from own key, capacity S)."""
        s = self.p.succ_size
        c = cands
        ck = ctx.keys[jnp.maximum(c, 0)]
        eq = c[None, :] == c[:, None]
        dup = jnp.any(eq & jnp.tril(jnp.ones((c.shape[0],) * 2, bool), -1),
                      axis=1)
        bad = (c == NO_NODE) | (c == node_idx) | dup
        d = K.sub(ck, jnp.broadcast_to(me_key, ck.shape), self.key_spec)
        d = jnp.where(bad[:, None], UMAX, d)
        c_s, bad_s = _sort_lanes(d, (c, bad.astype(I32)))
        out = jnp.where(bad_s[:s] != 0, NO_NODE, c_s[:s])
        if out.shape[0] < s:
            out = jnp.concatenate(
                [out, jnp.full((s - out.shape[0],), NO_NODE, I32)])
        return out

    def _succ_add(self, ctx, me_key, node_idx, succ, node, en):
        node = jnp.where(en, node, NO_NODE)
        return self._succ_sorted(ctx, me_key, node_idx,
                                 jnp.concatenate([succ, node[None]]))

    def _handle_failed(self, ctx, st, me_key, node_idx, failed, now):
        """Chord::handleFailedNode (Chord.cc:502) for one failed slot."""
        en = failed != NO_NODE
        pred = jnp.where(en & (st.pred == failed), NO_NODE, st.pred)
        was_succ0 = en & (st.succ[0] == failed)
        succ_masked = jnp.where(st.succ == failed, NO_NODE, st.succ)
        succ = self._succ_sorted(ctx, me_key, node_idx, succ_masked)
        succ = jnp.where(en, succ, st.succ)
        fhit = en & (st.finger == failed)
        finger = jnp.where(fhit, NO_NODE, st.finger)
        finger_dirty = st.finger_dirty | fhit
        t_stab = jnp.where(was_succ0, now, st.t_stab)

        # lost the last successor while READY → rejoin
        # (handleFailedNode: successorList empty → cancel timers, wait for
        # join; BaseOverlay rejoinOnFailure path)
        rejoin = en & (st.state == READY) & (succ[0] == NO_NODE)
        st = dataclasses.replace(
            st, pred=pred, succ=succ, finger=finger,
            finger_dirty=finger_dirty, t_stab=t_stab)
        fresh_lk = lk_mod.init(self.lcfg, self.key_spec.lanes)
        st = dataclasses.replace(
            st,
            state=jnp.where(rejoin, JOINING, st.state),
            t_join=jnp.where(rejoin, now, st.t_join),
            t_stab=jnp.where(rejoin, T_INF, st.t_stab),
            t_fix=jnp.where(rejoin, T_INF, st.t_fix),
            t_cp=jnp.where(rejoin, T_INF, st.t_cp),
            stab_op=jnp.where(rejoin, 0, st.stab_op),
            stab_to=jnp.where(rejoin, T_INF, st.stab_to),
            cp_to=jnp.where(rejoin, T_INF, st.cp_to),
            lk=select_tree(rejoin, fresh_lk, st.lk),
            app=kbrtest.on_stop(st.app, rejoin))
        return st

    def _become_ready(self, ctx, st, en, now, rng):
        """Schedule periodic protocols on entering READY.

        Join response handler schedules immediate stabilize + fixfingers
        (handleRpcJoinResponse Chord.cc: scheduleAt(simTime(), ...))."""
        p = self.p
        st = dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            t_stab=jnp.where(en, now, st.t_stab),
            t_fix=jnp.where(en, now, st.t_fix),
            t_cp=jnp.where(en, now + jnp.int64(int(p.check_pred_delay * NS)),
                           st.t_cp),
            app=kbrtest.on_ready(st.app, en, now, rng, self.ap))
        return st

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rpc_to_ns = jnp.int64(int(p.rpc_timeout * NS))
        rngs = jax.random.split(rng, 6)
        t0 = ctx.t_start

        def pad_nodes(vec):
            out = jnp.full((rmax,), NO_NODE, I32)
            return out.at[:vec.shape[0]].set(vec[:rmax])

        def metric_fn(cand_slots, target):
            ck = ctx.keys[jnp.maximum(cand_slots, 0)]
            return K.sub(jnp.broadcast_to(target, ck.shape), ck, spec)

        # event accumulators
        joins_cnt = jnp.int32(0)
        sent_cnt = jnp.int32(0)
        wrong_cnt = jnp.int32(0)
        lkfail_cnt = jnp.int32(0)   # failed app routes only (KBR KPI)
        anyfail_cnt = jnp.int32(0)  # failed lookups of any purpose
        lksucc_cnt = jnp.int32(0)
        deliv_hops, deliv_lat, deliv_mask = [], [], []

        # ------------------------------------------------------- inbox -----
        for r in range(msgs.valid.shape[0]):
            m = msgs.slot(r)
            now = m.t_deliver
            v = m.valid

            # FindNodeCall → findNode + sibling flag (findNodeRpc,
            # BaseOverlay.cc:1841)
            en = v & (m.kind == wire.FINDNODE_CALL)
            nxt, sib = self._find_node(ctx, st, me_key, node_idx, m.key)
            ob.send(en, now, m.src, wire.FINDNODE_RES, key=m.key,
                    a=m.a, b=m.b, c=sib.astype(I32),
                    nodes=jnp.full((rmax,), NO_NODE, I32).at[0].set(nxt),
                    size_b=wire.findnode_res_b(1))

            # FindNodeResponse → lookup engine
            en = v & (m.kind == wire.FINDNODE_RES)
            st = dataclasses.replace(st, lk=lk_mod.on_response(
                st.lk, dataclasses.replace(m, valid=en), metric_fn, lcfg))

            # JoinCall (rpcJoin, Chord.cc:917) — response compiled BEFORE
            # the aggressive-join mutations (reference order)
            en = v & (m.kind == wire.CHORD_JOIN_CALL) & (st.state == READY)
            alone = (st.pred == NO_NODE) & (st.succ[0] == NO_NODE)
            pred_hint = jnp.where(alone, node_idx, st.pred)
            ob.send(en, now, m.src, wire.CHORD_JOIN_RES, a=pred_hint,
                    nodes=pad_nodes(st.succ),
                    size_b=wire.BASE_CALL_B
                    + wire.NODEHANDLE_B * (p.succ_size + 1))
            if p.aggressive_join:
                ob.send(en & (st.pred != NO_NODE), now, st.pred,
                        wire.CHORD_SUCC_HINT, a=m.src,
                        size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)
                pred2 = jnp.where(en, m.src, st.pred)
            else:
                pred2 = st.pred
            succ2 = jnp.where(en & (st.succ[0] == NO_NODE),
                              st.succ.at[0].set(m.src), st.succ)
            st = dataclasses.replace(st, pred=pred2, succ=succ2)

            # JoinResponse (handleRpcJoinResponse)
            en = v & (m.kind == wire.CHORD_JOIN_RES) & (st.state == JOINING)
            succ3 = self._succ_sorted(
                ctx, me_key, node_idx,
                jnp.concatenate([m.nodes[:p.succ_size], m.src[None]]))
            got_succ = en & (succ3[0] != NO_NODE)
            joins_cnt += got_succ.astype(I32)
            st = dataclasses.replace(
                st,
                succ=jnp.where(got_succ, succ3, st.succ),
                pred=jnp.where(got_succ & (m.a != NO_NODE)
                               & jnp.bool_(p.aggressive_join), m.a, st.pred))
            st = self._become_ready(ctx, st, got_succ, now, rngs[0])

            # StabilizeCall → reply with predecessor (rpcStabilize)
            en = v & (m.kind == wire.CHORD_STABILIZE_CALL) & (
                st.state == READY)
            ob.send(en, now, m.src, wire.CHORD_STABILIZE_RES, a=st.pred,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)

            # StabilizeResponse (handleRpcStabilizeResponse)
            en = v & (m.kind == wire.CHORD_STABILIZE_RES) & (
                st.state == READY) & (st.stab_op == 1) & (m.src == st.stab_dst)
            cand = m.a
            ck = ctx.keys[jnp.maximum(cand, 0)]
            s0 = st.succ[0]
            s0k = ctx.keys[jnp.maximum(s0, 0)]
            succ_empty = s0 == NO_NODE
            adopt = (cand != NO_NODE) & (succ_empty | K.is_between(
                ck, me_key, s0k, spec))
            new_node = jnp.where(adopt, cand,
                                 jnp.where(succ_empty, m.src, NO_NODE))
            succ4 = self._succ_add(ctx, me_key, node_idx, st.succ, new_node,
                                   en)
            succ4 = jnp.where(en, succ4, st.succ)
            # notify the (possibly new) successor
            ob.send(en & (succ4[0] != NO_NODE), now, succ4[0],
                    wire.CHORD_NOTIFY_CALL,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)
            st = dataclasses.replace(
                st, succ=succ4,
                stab_op=jnp.where(en, 2, st.stab_op),
                stab_dst=jnp.where(en, succ4[0], st.stab_dst),
                stab_to=jnp.where(en, now + rpc_to_ns, st.stab_to))

            # NotifyCall (rpcNotify): adopt closer predecessor, reply with
            # successor list
            en = v & (m.kind == wire.CHORD_NOTIFY_CALL) & (st.state == READY)
            sk = ctx.keys[jnp.maximum(m.src, 0)]
            pk = ctx.keys[jnp.maximum(st.pred, 0)]
            newpred = en & ((st.pred == NO_NODE)
                            | K.is_between(sk, pk, me_key, spec))
            succ5 = jnp.where(newpred & (st.succ[0] == NO_NODE),
                              st.succ.at[0].set(m.src), st.succ)
            st = dataclasses.replace(
                st, pred=jnp.where(newpred, m.src, st.pred), succ=succ5)
            ob.send(en, now, m.src, wire.CHORD_NOTIFY_RES,
                    nodes=pad_nodes(st.succ),
                    size_b=wire.BASE_CALL_B
                    + wire.NODEHANDLE_B * (p.succ_size + 1))

            # NotifyResponse (handleRpcNotifyResponse): replace successor
            # list with successor's list
            en = v & (m.kind == wire.CHORD_NOTIFY_RES) & (
                st.state == READY) & (st.stab_op == 2) & (
                m.src == st.stab_dst) & (m.src == st.succ[0])
            succ6 = self._succ_sorted(
                ctx, me_key, node_idx,
                jnp.concatenate([m.nodes[:p.succ_size], m.src[None]]))
            fin = v & (m.kind == wire.CHORD_NOTIFY_RES) & (st.stab_op == 2) & (
                m.src == st.stab_dst)
            st = dataclasses.replace(
                st, succ=jnp.where(en, succ6, st.succ),
                stab_op=jnp.where(fin, 0, st.stab_op),
                stab_to=jnp.where(fin, T_INF, st.stab_to))

            # NewSuccessorHint (handleNewSuccessorHint)
            en = v & (m.kind == wire.CHORD_SUCC_HINT) & (st.state == READY)
            hk = ctx.keys[jnp.maximum(m.a, 0)]
            s0k2 = ctx.keys[jnp.maximum(st.succ[0], 0)]
            take = en & (m.a != NO_NODE) & (
                (st.succ[0] == NO_NODE)
                | K.is_between(hk, me_key, s0k2, spec))
            st = dataclasses.replace(st, succ=jnp.where(
                take, self._succ_add(ctx, me_key, node_idx, st.succ, m.a,
                                     take), st.succ))

            # app one-way payload (KBRTestApp::deliver).  Reuse the
            # findNode result computed for this slot above: no handler
            # between there and here fires for an APP_ONEWAY kind, so the
            # state it read is unchanged.
            en = v & (m.kind == wire.APP_ONEWAY)
            sib_here = sib
            good = en & sib_here
            deliv_mask.append(good & (m.c != 0))
            deliv_hops.append(m.hops + 1)
            deliv_lat.append((now - m.stamp).astype(jnp.float32) / NS)
            wrong_cnt += (en & ~sib_here & (m.c != 0)).astype(I32)

            # ping (predecessor liveness + generic)
            ob.send(v & (m.kind == wire.PING_CALL), now, m.src,
                    wire.PING_RES, a=m.a, size_b=wire.BASE_CALL_B)
            en = v & (m.kind == wire.PING_RES) & (m.src == st.pred)
            st = dataclasses.replace(
                st, cp_to=jnp.where(en, T_INF, st.cp_to))

        # ------------------------------------------------------- timers ----
        t_end = ctx.t_end

        # join (joinOverlay / handleJoinTimerExpired Chord.cc:758)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1])
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone_start = en_j & (boot == NO_NODE)
        st = self._become_ready(ctx, st, alone_start, now_j, rngs[2])
        joins_cnt += alone_start.astype(I32)
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone_start,
            now_j + jnp.int64(int(p.join_delay * NS)), st.t_join))

        # stabilize (handleStabilizeTimerExpired)
        en_s = (st.state == READY) & (st.t_stab < t_end)
        now_s = jnp.maximum(st.t_stab, t0)
        has_succ = st.succ[0] != NO_NODE
        fire_s = en_s & has_succ
        ob.send(fire_s, now_s, st.succ[0], wire.CHORD_STABILIZE_CALL,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(
            st,
            stab_op=jnp.where(fire_s, 1, st.stab_op),
            stab_dst=jnp.where(fire_s, st.succ[0], st.stab_dst),
            stab_to=jnp.where(fire_s, now_s + rpc_to_ns, st.stab_to),
            t_stab=jnp.where(en_s, now_s + jnp.int64(
                int(p.stabilize_delay * NS)), st.t_stab))

        # fixfingers (handleFixFingersTimerExpired): mark non-trivial
        # fingers dirty, remove trivial ones
        en_f = (st.state == READY) & (st.t_fix < t_end) & has_succ
        s0k = ctx.keys[jnp.maximum(st.succ[0], 0)]
        sdist = K.sub(s0k, me_key, spec)                    # me → succ
        nontrivial = K.gt(self._pow2, jnp.broadcast_to(sdist,
                                                       self._pow2.shape))
        st = dataclasses.replace(
            st,
            finger_dirty=jnp.where(en_f, nontrivial, st.finger_dirty),
            finger=jnp.where(en_f & ~nontrivial, NO_NODE, st.finger),
            t_fix=jnp.where((st.state == READY) & (st.t_fix < t_end),
                            jnp.maximum(st.t_fix, t0)
                            + jnp.int64(int(p.fixfingers_delay * NS)),
                            st.t_fix))

        # predecessor check (handleCheckPredecessorTimerExpired)
        en_c = (st.state == READY) & (st.t_cp < t_end)
        now_c = jnp.maximum(st.t_cp, t0)
        fire_c = en_c & (st.pred != NO_NODE) & (st.cp_to == T_INF)
        ob.send(fire_c, now_c, st.pred, wire.PING_CALL,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(
            st,
            cp_to=jnp.where(fire_c, now_c + rpc_to_ns, st.cp_to),
            t_cp=jnp.where(en_c, now_c + jnp.int64(
                int(p.check_pred_delay * NS)), st.t_cp))

        # app timer → start an app lookup (KBRTestApp::handleTimerEvent →
        # callRoute → iterative lookup, SURVEY §3.2)
        en_a = (st.state == READY) & (st.app.t_test < t_end)
        now_a = jnp.maximum(st.app.t_test, t0)
        app, want, dest_key, seq = kbrtest.on_timer(
            st.app, en_a, ctx, now_a, rngs[3], self.ap)
        st = dataclasses.replace(st, app=app)
        nxt_a, sib_a = self._find_node(ctx, st, me_key, node_idx, dest_key)
        sent_cnt += want.astype(I32)
        # local delivery (sendToKey with local sibling → direct deliver,
        # hopCount 0)
        local = want & sib_a
        deliv_mask.append(local & ctx.measuring)
        deliv_hops.append(jnp.int32(0))
        deliv_lat.append(jnp.float32(0))
        slot, have = lk_mod.free_slot(st.lk)
        start_app = want & ~sib_a & have & (nxt_a != NO_NODE)
        lkfail_cnt += (want & ~sib_a & ~start_app).astype(I32)
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(nxt_a)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, seq, dest_key, seed, now_a, lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        for li in range(lcfg.slots):
            st = self._handle_failed(ctx, st, me_key, node_idx,
                                     failed_nodes[li], t0)

        # stabilize / notify RPC timeout → failed successor
        en = (st.stab_op != 0) & (st.stab_to < t_end)
        st = dataclasses.replace(
            st, stab_op=jnp.where(en, 0, st.stab_op),
            stab_to=jnp.where(en, T_INF, st.stab_to))
        st = self._handle_failed(ctx, st, me_key, node_idx,
                                 jnp.where(en, st.stab_dst, NO_NODE), t0)

        # predecessor ping timeout → drop predecessor
        en = st.cp_to < t_end
        st = dataclasses.replace(
            st, pred=jnp.where(en, NO_NODE, st.pred),
            cp_to=jnp.where(en, T_INF, st.cp_to))

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        comp["taken"] & comp["success"])
        for li in range(lcfg.slots):
            en = comp["taken"][li]
            suc = comp["success"][li] & (comp["result"][li] != NO_NODE)
            res = comp["result"][li]
            pur = comp["purpose"][li]
            lksucc_cnt += (en & suc).astype(I32)
            anyfail_cnt += (en & ~suc).astype(I32)
            # the KBR KPI only counts the app's own routes failing
            # (reference KBRTestApp records only its own lookups)
            lkfail_cnt += (en & ~suc & (pur == P_APP)).astype(I32)

            # join: contact our successor directly
            ob.send(en & suc & (pur == P_JOIN), t0, res,
                    wire.CHORD_JOIN_CALL,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)

            # finger repair result
            enf = en & (pur == P_FINGER)
            fi = jnp.clip(comp["aux"][li], 0, spec.bits - 1)
            st = dataclasses.replace(
                st,
                finger=jnp.where(enf & suc,
                                 st.finger.at[fi].set(res), st.finger),
                finger_dirty=jnp.where(
                    enf, st.finger_dirty.at[fi].set(False),
                    st.finger_dirty))

            # app route: final hop to the sibling
            ena = en & (pur == P_APP)
            ob.send(ena & suc & (res != node_idx), t0, res, wire.APP_ONEWAY,
                    key=comp["target"][li], hops=comp["hops"][li],
                    c=ctx.measuring.astype(I32), stamp=comp["t0"][li],
                    size_b=self.ap.test_msg_bytes)
            # lookup ended on ourselves → local delivery
            self_del = ena & suc & (res == node_idx)
            deliv_mask.append(self_del & ctx.measuring)
            deliv_hops.append(comp["hops"][li])
            deliv_lat.append((t0 - comp["t0"][li]).astype(jnp.float32) / NS)

        # -------------------------------------------- finger repair pump ---
        dirty_any = (st.state == READY) & jnp.any(st.finger_dirty)
        no_finger_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_FINGER))
        fi = jnp.argmax(st.finger_dirty).astype(I32)
        target = K.add(me_key, self._pow2[fi], spec)
        nxt_f, sib_f = self._find_node(ctx, st, me_key, node_idx, target)
        # responsible ourselves → no finger needed (covered by succ list)
        self_fix = dirty_any & no_finger_lk & sib_f
        st = dataclasses.replace(
            st,
            finger_dirty=jnp.where(self_fix,
                                   st.finger_dirty.at[fi].set(False),
                                   st.finger_dirty))
        slot, have = lk_mod.free_slot(st.lk)
        start_fix = dirty_any & no_finger_lk & ~sib_f & have & (
            nxt_f != NO_NODE)
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(nxt_f)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_fix, slot, P_FINGER, fi, target, seed, t0, lcfg))

        # ------------------------------------------------------- pump ------
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[4], lcfg)
        st = dataclasses.replace(st, lk=new_lk)

        # ------------------------------------------------------ events -----
        dh = jnp.stack([jnp.asarray(x, jnp.float32) for x in deliv_hops])
        dl = jnp.stack([jnp.asarray(x, jnp.float32) for x in deliv_lat])
        dm = jnp.stack(deliv_mask)
        events = {
            "c:chord_joins": joins_cnt,
            "c:kbr_sent": sent_cnt,
            "c:kbr_delivered": jnp.sum(dm.astype(I32)),
            "c:kbr_wrong_node": wrong_cnt,
            "c:kbr_lookup_failed": lkfail_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "s:kbr_hopcount": (dh, dm),
            "s:kbr_latency_s": (dl, dm),
            "h:kbr_hop_hist": (dh.astype(I32), dm),
            "s:lookup_hops": comp_hops_ev,
        }
        return st, ob, events
