"""Chord ring DHT as vectorized per-node logic.

TPU-native rebuild of the reference Chord (src/overlay/chord/Chord.{h,cc} +
ChordSuccessorList/ChordFingerTable), with protocol semantics preserved and
state held as structure-of-arrays:

  * successor list [N, S] node slots kept ring-distance sorted (reference
    ChordSuccessorList: std::map sorted by distance from own key);
  * predecessor [N]; finger table [N, B] (B = key bits) with 2^i targets;
  * aggressive join (rpcJoin Chord.cc:917: responsible node adopts the
    joiner as predecessor, hints its old predecessor in the JoinResponse,
    and sends NEWSUCCESSORHINT to the old predecessor);
  * periodic stabilize (StabilizeCall → successor's predecessor; adopt if
    in (me, succ); then NotifyCall; NotifyResponse carries the successor's
    successor list which replaces ours — Chord.cc:793/rpcStabilize/
    rpcNotify/handleRpcNotifyResponse);
  * periodic fixfingers (handleFixFingersTimerExpired Chord.cc:845: route
    a lookup to me+2^i for every non-trivial finger — offset greater than
    the distance to the successor; trivial fingers are removed).  We mark
    those fingers dirty and repair them one lookup at a time, chained off
    lookup completions (same convergence, bounded concurrency);
  * predecessor liveness via periodic ping (checkPredecessorDelay=5s,
    default.ini:172, handleCheckPredecessorTimerExpired);
  * failure repair (handleFailedNode Chord.cc:502: drop from successor
    list / fingers / predecessor, immediate re-stabilize, rejoin when the
    last successor is gone);
  * findNode (Chord.cc:548): siblings if responsible; successor list if
    key in (me, succ]; otherwise closest preceding node over fingers +
    successor list (closestPreceedingNode Chord.cc:602).

Defaults follow simulations/default.ini:167-183 (joinDelay 10s,
stabilizeDelay 20s, fixfingersDelay 120s, checkPredecessorDelay 5s,
successorListSize 8, aggressiveJoinMode true, iterative routing).

The embedded tier-1 app is pluggable in spirit; this first slice wires
KBRTestApp (apps/kbrtest.py) directly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps import kbrtest
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import malicious as mal_mod
from oversim_tpu.common import ncs as ncs_mod
from oversim_tpu.common import neighborcache as nc_mod
from oversim_tpu.common import route as rt_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

# node lifecycle (reference BaseOverlay States, BaseOverlay.h:86-102)
DEAD, JOINING, READY = 0, 1, 2

# lookup purposes (owner dispatch tags)
P_JOIN, P_FINGER, P_APP, P_MERGE = 1, 2, 3, 4

BCAST_FANOUT = 8   # broadcast copies per hop (≥ distinct fingers at test N)


@dataclasses.dataclass(frozen=True)
class ChordParams:
    """default.ini:167-183."""

    join_delay: float = 10.0
    stabilize_delay: float = 20.0
    fixfingers_delay: float = 120.0
    check_pred_delay: float = 5.0
    succ_size: int = 8
    aggressive_join: bool = True
    rpc_timeout: float = 1.5        # rpcUdpTimeout, default.ini:483
    # BootstrapList::mergeOverlayPartitions (BootstrapList.cc:273,
    # default.ini:436-438, default false): periodically look up an
    # oracle-drawn candidate's key through the OWN overlay; if the
    # lookup does not find the candidate, it lives in a foreign
    # partition (two formed rings after a network heal) →
    # joinForeignPartition: adopt it as a successor candidate and hint
    # ourselves to it, knitting the rings back together
    merge_partitions: bool = False
    merge_interval: float = 20.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ChordState:
    state: jnp.ndarray         # [N] i32 DEAD/JOINING/READY
    pred: jnp.ndarray          # [N] i32
    succ: jnp.ndarray          # [N, S] i32 ring-sorted, NO_NODE padded
    finger: jnp.ndarray        # [N, B] i32
    finger_dirty: jnp.ndarray  # [N, B] bool
    t_join: jnp.ndarray        # [N] i64
    t_stab: jnp.ndarray        # [N] i64
    t_fix: jnp.ndarray         # [N] i64
    t_cp: jnp.ndarray          # [N] i64
    stab_op: jnp.ndarray       # [N] i32 0=idle 1=stabilize 2=notify pending
    stab_dst: jnp.ndarray      # [N] i32
    stab_to: jnp.ndarray       # [N] i64
    cp_to: jnp.ndarray         # [N] i64 pending predecessor-ping timeout
    cp_dst: jnp.ndarray        # [N] i32 the node that ping targeted
    lk: lk_mod.LookupState     # [N, L, ...]
    rr: rt_mod.RouteState      # [N, Q, ...] pending-ACK recursive routes
    cp_sent: jnp.ndarray       # [N] i64 — predecessor-ping send time (RTT)
    t_merge: jnp.ndarray       # [N] i64 — partition-merge probe timer
    t_nps: jnp.ndarray         # [N] i64 — GNP/NPS landmark-probe timer
    nps_dst: jnp.ndarray       # [N] i32 — in-flight probe target
    nps_sent: jnp.ndarray      # [N] i64 — its send time (RTT base)
    ncs: ncs_mod.NcsState      # [N, ...] coordinates (common/ncs.py:
                               # vivaldi/svivaldi or gnp/nps landmark
                               # layers, Nps.h:119-133)
    nc: nc_mod.NcState         # [N, C] RTT cache (adaptive RPC timeouts)
    app: object                # [N, ...] tier-app state (apps/base.py)
    app_glob: object           # simulation-global app state (oracle maps)


def _sort_lanes(dist, payload):
    return K.sort_by_distance(dist, payload, approx=True)[1]


def _lex_argmin(dist):
    """Index of the lexicographically smallest [C, KL] distance row."""
    idx = jnp.arange(dist.shape[0], dtype=I32)
    (best,) = _sort_lanes(dist, (idx,))
    return best[0]


class ChordLogic:
    """Implements the engine logic interface (engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: ChordParams = ChordParams(),
                 lcfg: lk_mod.LookupConfig = lk_mod.LookupConfig(),
                 app=None,
                 mparams: mal_mod.MaliciousParams = mal_mod.MaliciousParams(),
                 ncs_params: ncs_mod.NcsParams = ncs_mod.NcsParams(),
                 nc_params: nc_mod.NcParams = nc_mod.NcParams(),
                 rcfg: rt_mod.RouteConfig | None = None):
        """``rcfg=None`` keeps the reference Chord default (iterative
        routing, default.ini:167-183); a RouteConfig switches the app
        data path to the recursive family — rcfg.mode selects
        SEMI_RECURSIVE / FULL_RECURSIVE / RECURSIVE_SOURCE_ROUTING
        (verify.ini's ChordSource config = mode="source").  App lookups
        (M_LOOKUP / DHT LookupCall) stay on the iterative engine either
        way (documented deviation: the reference wraps them in
        RecursiveLookup; the sibling resolution is equivalent, the
        FindNode round trips differ)."""
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg
        self.app = app or KbrTestApp()
        self.rcfg = rcfg
        # hand the routing mode to the app's RPC-reply path (BaseRpc
        # response transport follows the call's routingType)
        if rcfg is not None and getattr(self.app, "rcfg", "no") is None:
            self.app.rcfg = rcfg
        # overlay->distance for the DHT maintenance responsibility
        # filter: Chord responsibility is CLOCKWISE distance key→node
        # (successor-of-key holds it; Chord::distance, Chord.cc:1403)
        if getattr(self.app, "dist_fn", "no") is None:
            self.app.dist_fn = (
                lambda nk, rk: K.ring_distance(rk, nk, spec))
        self.mp = mparams
        self.ncs = ncs_params
        self.ncp = nc_params
        if spec.lanes < ncs_params.dims + (
                2 if ncs_params.is_landmark_type else 1):
            raise ValueError("key lanes too narrow for the NCS piggyback")
        self._pow2 = K.pow2_table(spec)          # [B, KL] finger offsets

    # -- engine interface ---------------------------------------------------

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "chord_joins", "lookup_success", "lookup_failed",
                "route_dropped"),
        )

    def split(self, st: ChordState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: ChordState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: ChordState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def init(self, rng, n: int) -> ChordState:
        s, b = self.p.succ_size, self.key_spec.bits
        return ChordState(
            state=jnp.zeros((n,), I32),
            pred=jnp.full((n,), NO_NODE, I32),
            succ=jnp.full((n, s), NO_NODE, I32),
            finger=jnp.full((n, b), NO_NODE, I32),
            finger_dirty=jnp.zeros((n, b), bool),
            t_join=jnp.full((n,), T_INF, I64),
            t_stab=jnp.full((n,), T_INF, I64),
            t_fix=jnp.full((n,), T_INF, I64),
            t_cp=jnp.full((n,), T_INF, I64),
            stab_op=jnp.zeros((n,), I32),
            stab_dst=jnp.full((n,), NO_NODE, I32),
            stab_to=jnp.full((n,), T_INF, I64),
            cp_to=jnp.full((n,), T_INF, I64),
            cp_dst=jnp.full((n,), NO_NODE, I32),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            rr=jax.vmap(lambda _: rt_mod.init(
                self.rcfg or rt_mod.RouteConfig(), self.key_spec.lanes,
                16))(jnp.arange(n)),
            cp_sent=jnp.zeros((n,), I64),
            t_merge=jnp.full((n,), T_INF, I64),
            t_nps=jnp.full((n,), T_INF, I64),
            nps_dst=jnp.full((n,), NO_NODE, I32),
            nps_sent=jnp.zeros((n,), I64),
            ncs=ncs_mod.init(rng, n, self.ncs),
            nc=nc_mod.init(n, self.ncp),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: ChordState, clear, join, t_now, rng) -> ChordState:
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: ChordState):
        return st.state == READY

    def next_event(self, st: ChordState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        for timer in (st.t_stab, st.t_fix, st.t_cp):
            t = jnp.minimum(t, jnp.where(ready, timer, T_INF))
        t = jnp.minimum(t, st.stab_to)
        t = jnp.minimum(t, st.cp_to)
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        if self.ncs.is_landmark_type:
            t = jnp.minimum(t, jnp.where(ready, st.t_nps, T_INF))
        if self.p.merge_partitions:
            t = jnp.minimum(t, jnp.where(ready, st.t_merge, T_INF))
        if self.rcfg is not None:
            t = jnp.minimum(t, jax.vmap(rt_mod.next_event)(st.rr))
        return t

    # -- internals (all per-node; vmapped by the engine) ---------------------

    def _find_node(self, ctx, st, me_key, node_idx, key):
        """Chord::findNode (Chord.cc:548) with numRedundantNodes=1.

        Returns (next_hop i32 slot, is_sibling bool).  NO_NODE next hop
        when not READY (reference returns an empty NodeVector).
        """
        spec = self.key_spec
        ready = st.state == READY
        pred_ok = st.pred != NO_NODE
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        succ0 = st.succ[0]
        has_succ = succ0 != NO_NODE
        s0k = ctx.keys[jnp.maximum(succ0, 0)]

        alone = ~pred_ok & ~has_succ
        is_sib = ready & (alone
                          | (~pred_ok & K.eq(key, me_key))
                          | (pred_ok & K.is_between_r(key, pk, me_key, spec)))
        succ_case = ready & has_succ & ~is_sib & K.is_between_r(
            key, me_key, s0k, spec)

        # closest preceding node over fingers + successor list
        cands = jnp.concatenate([st.finger, st.succ])
        cks = ctx.keys[jnp.maximum(cands, 0)]
        me_b = jnp.broadcast_to(me_key, cks.shape)
        key_b = jnp.broadcast_to(key, cks.shape)
        usable = (cands != NO_NODE) & (cands != node_idx) & K.is_between_r(
            cks, me_b, key_b, spec)
        d = K.sub(key_b, cks, spec)            # clockwise candidate→key
        d = jnp.where(usable[:, None], d, UMAX)
        best = cands[_lex_argmin(d)]
        best = jnp.where(jnp.any(usable), best, succ0)  # fallback: successor

        nxt = jnp.where(is_sib, node_idx, jnp.where(succ_case, succ0, best))
        nxt = jnp.where(ready, nxt, NO_NODE)
        return nxt, is_sib

    def _respond_find(self, ctx, st, me_key, node_idx, m, rmax, pad_nodes):
        """FindNode RPC server payload: ([rmax] result slots, sibling
        flag).  Overridable hop-choice hook (Koorde)."""
        nxt, sib = self._find_node(ctx, st, me_key, node_idx, m.key)
        sib_set = pad_nodes(jnp.concatenate([node_idx[None], st.succ]))
        return jnp.where(
            sib, sib_set,
            jnp.full((rmax,), NO_NODE, I32).at[0].set(nxt)), sib

    def _extra_timers(self, ctx, st, ob, me_key, node_idx, t0, t_end, rng):
        """Subclass timer hook (Koorde de Bruijn stabilization)."""
        return st

    def _on_completion(self, ctx, st, ob, li, comp, en, suc, res, t0):
        """Subclass lookup-purpose dispatch hook (per completion slot)."""
        return st

    def _succ_sorted(self, ctx, me_key, node_idx, cands):
        """Ring-distance-sorted unique successor list from candidate slots
        (ChordSuccessorList semantics: excludes self, sorted by clockwise
        distance from own key, capacity S)."""
        s = self.p.succ_size
        c = cands
        ck = ctx.keys[jnp.maximum(c, 0)]
        bad = (c == NO_NODE) | (c == node_idx) | K.dup_mask(c)
        d = K.sub(ck, jnp.broadcast_to(me_key, ck.shape), self.key_spec)
        d = jnp.where(bad[:, None], UMAX, d)
        c_s, bad_s = _sort_lanes(d, (c, bad.astype(I32)))
        out = jnp.where(bad_s[:s] != 0, NO_NODE, c_s[:s])
        if out.shape[0] < s:
            out = jnp.concatenate(
                [out, jnp.full((s - out.shape[0],), NO_NODE, I32)])
        return out

    def _succ_add(self, ctx, me_key, node_idx, succ, node, en):
        node = jnp.where(en, node, NO_NODE)
        return self._succ_sorted(ctx, me_key, node_idx,
                                 jnp.concatenate([succ, node[None]]))

    def _handle_failed(self, ctx, st, me_key, node_idx, failed, now):
        """Chord::handleFailedNode (Chord.cc:502) for a [F] vector of
        failed slots (NO_NODE entries ignored) — one sort for the whole
        batch instead of one call per failure source."""
        failed = jnp.where(failed == node_idx, NO_NODE, failed)
        any_failed = jnp.any(failed != NO_NODE)

        def hit(x):
            return (x[..., None] == failed).any(-1) & (x != NO_NODE)

        en = any_failed
        pred = jnp.where(hit(st.pred), NO_NODE, st.pred)
        was_succ0 = hit(st.succ[0])
        succ_masked = jnp.where(hit(st.succ), NO_NODE, st.succ)
        succ = self._succ_sorted(ctx, me_key, node_idx, succ_masked)
        succ = jnp.where(en, succ, st.succ)
        fhit = hit(st.finger)
        finger = jnp.where(fhit, NO_NODE, st.finger)
        finger_dirty = st.finger_dirty | fhit
        t_stab = jnp.where(was_succ0, now, st.t_stab)

        # lost the last successor while READY → rejoin
        # (handleFailedNode: successorList empty → cancel timers, wait for
        # join; BaseOverlay rejoinOnFailure path)
        rejoin = en & (st.state == READY) & (succ[0] == NO_NODE)
        st = dataclasses.replace(
            st, pred=pred, succ=succ, finger=finger,
            finger_dirty=finger_dirty, t_stab=t_stab)
        fresh_lk = lk_mod.init(self.lcfg, self.key_spec.lanes)
        st = dataclasses.replace(
            st,
            state=jnp.where(rejoin, JOINING, st.state),
            t_join=jnp.where(rejoin, now, st.t_join),
            t_stab=jnp.where(rejoin, T_INF, st.t_stab),
            t_fix=jnp.where(rejoin, T_INF, st.t_fix),
            t_cp=jnp.where(rejoin, T_INF, st.t_cp),
            stab_op=jnp.where(rejoin, 0, st.stab_op),
            stab_to=jnp.where(rejoin, T_INF, st.stab_to),
            cp_to=jnp.where(rejoin, T_INF, st.cp_to),
            cp_dst=jnp.where(rejoin, NO_NODE, st.cp_dst),
            lk=select_tree(rejoin, fresh_lk, st.lk),
            app=self.app.on_stop(st.app, rejoin))
        return st

    def _become_ready(self, ctx, st, en, now, rng):
        """Schedule periodic protocols on entering READY.

        Join response handler schedules immediate stabilize + fixfingers
        (handleRpcJoinResponse Chord.cc: scheduleAt(simTime(), ...))."""
        p = self.p
        st = dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            t_stab=jnp.where(en, now, st.t_stab),
            t_fix=jnp.where(en, now, st.t_fix),
            t_cp=jnp.where(en, now + jnp.int64(int(p.check_pred_delay * NS)),
                           st.t_cp),
            app=self.app.on_ready(st.app, en, now, rng))
        if self.ncs.is_landmark_type:
            st = dataclasses.replace(st, t_nps=jnp.where(
                en, now + jnp.int64(int(0.3 * NS)), st.t_nps))
        if p.merge_partitions:
            st = dataclasses.replace(st, t_merge=jnp.where(
                en, now + jnp.int64(int(p.merge_interval * NS)),
                st.t_merge))
        return st

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rpc_to_ns = jnp.int64(int(p.rpc_timeout * NS))
        rngs = jax.random.split(rng, 7)
        t0 = ctx.t_start

        def pad_nodes(vec):
            out = jnp.full((rmax,), NO_NODE, I32)
            k = min(vec.shape[0], rmax)
            return out.at[:k].set(vec[:k])

        def metric_fn(cand_slots, target):
            ck = ctx.keys[jnp.maximum(cand_slots, 0)]
            return K.sub(jnp.broadcast_to(target, ck.shape), ck, spec)

        # event accumulators
        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)  # failed lookups of any purpose
        lksucc_cnt = jnp.int32(0)
        routedrop_cnt = jnp.int32(0)
        old_succ = st.succ                   # update() delta base
        old_pred = st.pred

        # --------------------------------------------- inbox (batched) -----
        # Kind-major batching: each message kind is handled in ONE masked
        # pass over the R inbox slots (kinds in the original per-slot
        # order) instead of R unrolled handler chains — the round-2 tick
        # graph was op-issue-bound on exactly that unrolling (52k eqns).
        # Within-window ordering across slots is already relaxed by the
        # engine (engine/sim.py docstring); the kind-major permutation is
        # the same relaxation.  Each kind's reads see every earlier
        # kind's writes; response payloads read the state as of their
        # kind's turn (the unrolled loop exposed mid-loop state the same
        # way, just slot-major).
        v_r = msgs.valid                                     # [R]
        now_r = msgs.t_deliver                               # [R]
        r_in = v_r.shape[0]

        # FindNode + sibling flag for EVERY inbox slot's key (findNodeRpc,
        # BaseOverlay.cc:1841), vmapped.  Subclasses (Koorde) override
        # _respond_find for their own hop choice + lookup extension
        # handling.  Computed before the recursive-route pre-pass: route
        # forwarding reuses these results as its next-hop candidates, and
        # decapsulation preserves msgs.key, so the flags stay valid for
        # the decapsulated inner kinds below.
        res_b, sib_b = jax.vmap(
            lambda mm: self._respond_find(ctx, st, me_key, node_idx, mm,
                                          rmax, pad_nodes))(msgs)

        if self.rcfg is not None:
            rcfg = self.rcfg
            # per-hop ACKs for routes we forwarded (NextHopResponse)
            st = dataclasses.replace(st, rr=rt_mod.on_acks(
                st.rr, dataclasses.replace(
                    msgs,
                    valid=v_r & (msgs.kind == wire.KBR_ROUTE_ACK))))

            # source-routed replies: pop one hop / deliver at originator
            en_sro = v_r & (msgs.kind == wire.KBR_SROUTE)
            deliver_sr = rt_mod.sroute_step(ob, msgs)
            msgs = dataclasses.replace(
                msgs,
                kind=jnp.where(deliver_sr, msgs.d, msgs.kind),
                src=jnp.where(deliver_sr, msgs.c, msgs.src),
                valid=v_r & (~en_sro | deliver_sr))
            v_r = msgs.valid

            # recursive route pre-pass (sendToKey recursive branch,
            # BaseOverlay.cc:1441-1581): ACK the last hop, then either
            # decapsulate (responsible) or forward to the first
            # candidate surviving loop detection.  visitedHops ride
            # msgs.nodes; the originator is visited[0].
            en_rt = v_r & (msgs.kind == wire.KBR_ROUTE) & (
                st.state == READY)
            ob.send(en_rt & (msgs.nonce > 0), now_r, msgs.src,
                    wire.KBR_ROUTE_ACK, nonce=msgs.nonce,
                    size_b=wire.BASE_CALL_B)
            deliver_rt = en_rt & sib_b
            # overlay routing ext (Koorde routeKey/step) rides the head
            # of msgs.nodes; the visited list occupies the tail.  The
            # responder writes its updated ext into res_b's tail (the
            # same packing _respond_find uses for FINDNODE_RES), which
            # must be masked out of the next-hop candidate scan.
            ew = rcfg.ext_words
            if ew:
                vis_in = msgs.nodes[:, ew:]
                cands = res_b.at[:, rmax - ew:].set(NO_NODE)
            else:
                vis_in = msgs.nodes
                cands = res_b
            nxt_v, found_v = jax.vmap(
                rt_mod.pick_next_hop, in_axes=(0, 0, 0, 0, None, 0))(
                cands, vis_in, msgs.src, vis_in[:, 0], node_idx,
                sib_b)
            fwd = en_rt & ~sib_b & found_v & (msgs.hops < rcfg.hop_max)
            if hasattr(self.app, "forward"):
                # Common API forward() (BaseApp.h:214 / callForward,
                # BaseOverlay.cc:523): the app may veto messages being
                # routed THROUGH this node (veto = drop, the reference's
                # forwardResponse without a next hop)
                fwd = fwd & ~self.app.forward(st.app, msgs, ctx)
            # visitedHops appended unconditionally (deviation: the
            # reference records only for source/recordRoute and falls
            # back to last-hop-only loop detection in semi/full —
            # recording always makes pick_next_hop's visited check real
            # in every mode for a few wire bytes; pastry.py does the same)
            visited2 = rt_mod.append_visited(vis_in, node_idx, fwd)
            if ew:
                nodes_out = jnp.concatenate(
                    [res_b[:, rmax - ew:], visited2], axis=1)
            else:
                nodes_out = visited2
            st = dataclasses.replace(st, rr=rt_mod.forward_batch(
                st.rr, ob, fwd, now_r, nxt_v, key=msgs.key, inner=msgs.d,
                a=msgs.a, b=msgs.b, c=msgs.c, hops=msgs.hops + 1,
                stamp=msgs.stamp, size_b=msgs.size_b - rcfg.overhead_b,
                visited=nodes_out, cfg=rcfg))
            routedrop_cnt += jnp.sum((en_rt & ~sib_b & ~fwd).astype(I32))
            # decapsulate at the responsible node: the payload kind takes
            # over and src becomes the originator; handlers below (incl.
            # the app kinds) consume it as if it arrived directly.
            # msgs.nodes keeps the visitedHops for source-routed replies.
            msgs = dataclasses.replace(
                msgs,
                kind=jnp.where(deliver_rt, msgs.d, msgs.kind),
                src=jnp.where(deliver_rt, msgs.nodes[:, ew], msgs.src),
                valid=v_r & (~en_rt | deliver_rt))
            v_r = msgs.valid

        en_call = v_r & (msgs.kind == wire.FINDNODE_CALL)
        # byzantine switches (common/malicious.py; statically no-op by
        # default).  Only the wire copy is attacked; the honest ``sib_b``
        # feeds the app deliver check below (wrong-node detection,
        # KBRTestApp.cc:252-286 oracle check)
        if self.mp.active:
            res_atk, sib_atk, respond = jax.vmap(
                lambda rr, ss, rg: mal_mod.attack_findnode(
                    ctx, self.mp, node_idx, rr, ss, rg))(
                res_b, sib_b, jax.random.split(rngs[6], r_in))
        else:
            res_atk, sib_atk, respond = res_b, sib_b, jnp.ones((r_in,), bool)
        n_res = jnp.sum((res_atk != NO_NODE).astype(I32), axis=1)
        ob.send(en_call & respond, now_r, msgs.src, wire.FINDNODE_RES,
                key=msgs.key, a=msgs.a, b=msgs.b, c=sib_atk.astype(I32),
                nodes=res_atk,
                size_b=wire.BASE_CALL_B + 1 + wire.NODEHANDLE_B * n_res)

        # FindNodeResponse -> lookup engine (one batched pass)
        en_res = v_r & (msgs.kind == wire.FINDNODE_RES)
        st = dataclasses.replace(st, lk=lk_mod.on_responses(
            st.lk, dataclasses.replace(msgs, valid=en_res), metric_fn, lcfg))

        # JoinCall (rpcJoin, Chord.cc:917) — response compiled BEFORE
        # the aggressive-join mutations (reference order).
        #
        # RESPONSIBILITY GUARD: the reference's JoinCall is ROUTED to
        # the joiner's key, so the receiver is the responsible node
        # by construction; our joiner sends directly to its lookup
        # result, which can be stale during mass joins.  Accepting a
        # joiner whose key is NOT in (pred, me] would drag pred
        # backwards, widen this node's claimed range, attract more
        # mis-routed joins, and cascade into a loopy succ
        # permutation that weak stabilization provably cannot repair
        # (observed: N=64 interleaved-ring fixed point).  A
        # non-responsible receiver stays silent; the joiner's join
        # timer retries with a fresh lookup.
        en = v_r & (msgs.kind == wire.CHORD_JOIN_CALL) & (st.state == READY)
        alone = (st.pred == NO_NODE) & (st.succ[0] == NO_NODE)
        jk = ctx.keys[jnp.maximum(msgs.src, 0)]              # [R, KL]
        pk_j = ctx.keys[jnp.maximum(st.pred, 0)]
        responsible = alone | (st.pred == NO_NODE) | K.is_between(
            jk, jnp.broadcast_to(pk_j, jk.shape),
            jnp.broadcast_to(me_key, jk.shape), spec)
        en = en & responsible
        pred_hint = jnp.where(alone, node_idx, st.pred)
        ob.send(en, now_r, msgs.src, wire.CHORD_JOIN_RES, a=pred_hint,
                nodes=pad_nodes(st.succ),
                size_b=wire.BASE_CALL_B
                + wire.NODEHANDLE_B * (p.succ_size + 1))
        if p.aggressive_join:
            # the sequential fold adopted each joiner in slot order and
            # sent each SUCC_HINT to the predecessor adopted SO FAR —
            # chaining pred -> j1 -> j2.  Reproduce the chain: joiner k's
            # hint goes to the previous enabled joiner (k=0: the pre-tick
            # predecessor), so each ex-predecessor learns its new
            # successor and the ring stays linked through a mass join.
            idxs = jnp.arange(r_in, dtype=I32)
            cm = jax.lax.cummax(jnp.where(en, idxs, -1))
            prev = jnp.concatenate([jnp.full((1,), -1, I32), cm[:-1]])
            hint_dst = jnp.where(prev >= 0,
                                 msgs.src[jnp.maximum(prev, 0)], st.pred)
            ob.send(en & (hint_dst != NO_NODE), now_r, hint_dst,
                    wire.CHORD_SUCC_HINT, a=msgs.src,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)
            # final adopted predecessor = the LAST enabled joiner
            any_en = jnp.any(en)
            last_j = r_in - 1 - jnp.argmax(en[::-1]).astype(I32)
            pred2 = jnp.where(any_en,
                              msgs.src[jnp.clip(last_j, 0, r_in - 1)],
                              st.pred)
        else:
            pred2 = st.pred
        # empty successor list is seeded by the FIRST enabled joiner
        first_j = jnp.clip(jnp.argmax(en).astype(I32), 0, r_in - 1)
        succ2 = jnp.where(jnp.any(en) & (st.succ[0] == NO_NODE),
                          st.succ.at[0].set(msgs.src[first_j]), st.succ)
        st = dataclasses.replace(st, pred=pred2, succ=succ2)

        # JoinResponse (handleRpcJoinResponse): merge every enabled
        # response's successor candidates in one sorted pass
        en = v_r & (msgs.kind == wire.CHORD_JOIN_RES) & (st.state == JOINING)
        cand_jr = jnp.where(
            en[:, None],
            jnp.concatenate([msgs.nodes[:, :p.succ_size],
                             msgs.src[:, None]], axis=1),
            NO_NODE).reshape(-1)                             # [R*(S+1)]
        succ3 = self._succ_sorted(ctx, me_key, node_idx, cand_jr)
        got_succ = jnp.any(en) & (succ3[0] != NO_NODE)
        joins_cnt += got_succ.astype(I32)
        hint_ok = en & (msgs.a != NO_NODE)
        last_h = jnp.clip(r_in - 1 - jnp.argmax(hint_ok[::-1]).astype(I32),
                          0, r_in - 1)
        st = dataclasses.replace(
            st,
            succ=jnp.where(got_succ, succ3, st.succ),
            pred=jnp.where(got_succ & jnp.any(hint_ok)
                           & jnp.bool_(p.aggressive_join),
                           msgs.a[last_h], st.pred))
        st = self._become_ready(ctx, st, got_succ,
                                jnp.max(jnp.where(en, now_r, 0)), rngs[0])

        # StabilizeCall -> reply with predecessor (rpcStabilize)
        en = v_r & (msgs.kind == wire.CHORD_STABILIZE_CALL) & (
            st.state == READY)
        ob.send(en, now_r, msgs.src, wire.CHORD_STABILIZE_RES, a=st.pred,
                size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)

        # StabilizeResponse (handleRpcStabilizeResponse): at most one
        # inbox slot matches the single in-flight stabilize RPC
        en_sr = v_r & (msgs.kind == wire.CHORD_STABILIZE_RES) & (
            st.state == READY) & (st.stab_op == 1) & (
            msgs.src == st.stab_dst)
        any_sr = jnp.any(en_sr)
        r_sr = jnp.clip(jnp.argmax(en_sr).astype(I32), 0, r_in - 1)
        src_sr = msgs.src[r_sr]
        now_sr = msgs.t_deliver[r_sr]
        cand = msgs.a[r_sr]
        ck = ctx.keys[jnp.maximum(cand, 0)]
        s0 = st.succ[0]
        s0k = ctx.keys[jnp.maximum(s0, 0)]
        succ_empty = s0 == NO_NODE
        adopt = (cand != NO_NODE) & (succ_empty | K.is_between(
            ck, me_key, s0k, spec))
        new_node = jnp.where(adopt, cand,
                             jnp.where(succ_empty, src_sr, NO_NODE))
        succ4 = self._succ_add(ctx, me_key, node_idx, st.succ, new_node,
                               any_sr)
        succ4 = jnp.where(any_sr, succ4, st.succ)
        # notify the (possibly new) successor
        ob.send(any_sr & (succ4[0] != NO_NODE), now_sr, succ4[0],
                wire.CHORD_NOTIFY_CALL,
                size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)
        st = dataclasses.replace(
            st, succ=succ4,
            stab_op=jnp.where(any_sr, 2, st.stab_op),
            stab_dst=jnp.where(any_sr, succ4[0], st.stab_dst),
            stab_to=jnp.where(any_sr, now_sr + rpc_to_ns, st.stab_to))

        # NotifyCall (rpcNotify): adopt closer predecessor, reply with
        # successor list.  The sequential fold adopts every strictly
        # closer notifier in turn; its fixed point is the clockwise-
        # closest enabled source — pick it with one distance argmin.
        en = v_r & (msgs.kind == wire.CHORD_NOTIFY_CALL) & (
            st.state == READY)
        sk = ctx.keys[jnp.maximum(msgs.src, 0)]              # [R, KL]
        pk = ctx.keys[jnp.maximum(st.pred, 0)]
        closer = en & ((st.pred == NO_NODE) | K.is_between(
            sk, jnp.broadcast_to(pk, sk.shape),
            jnp.broadcast_to(me_key, sk.shape), spec))
        d_nc = K.sub(jnp.broadcast_to(me_key, sk.shape), sk, spec)
        d_nc = jnp.where(closer[:, None], d_nc, UMAX)
        best_r = _lex_argmin(d_nc)
        any_nc = jnp.any(closer)
        newpred_src = msgs.src[best_r]
        succ5 = jnp.where(any_nc & (st.succ[0] == NO_NODE),
                          st.succ.at[0].set(newpred_src), st.succ)
        st = dataclasses.replace(
            st, pred=jnp.where(any_nc, newpred_src, st.pred), succ=succ5)
        ob.send(en, now_r, msgs.src, wire.CHORD_NOTIFY_RES,
                nodes=pad_nodes(st.succ),
                size_b=wire.BASE_CALL_B
                + wire.NODEHANDLE_B * (p.succ_size + 1))

        # NotifyResponse (handleRpcNotifyResponse): replace successor
        # list with successor's list; at most one slot matches the
        # in-flight notify
        fin_m = v_r & (msgs.kind == wire.CHORD_NOTIFY_RES) & (
            st.stab_op == 2) & (msgs.src == st.stab_dst)
        any_fin = jnp.any(fin_m)
        r_nr = jnp.clip(jnp.argmax(fin_m).astype(I32), 0, r_in - 1)
        take_nr = any_fin & (st.state == READY) & (
            msgs.src[r_nr] == st.succ[0])
        succ6 = self._succ_sorted(
            ctx, me_key, node_idx,
            jnp.concatenate([msgs.nodes[r_nr][:p.succ_size],
                             msgs.src[r_nr][None]]))
        st = dataclasses.replace(
            st, succ=jnp.where(take_nr, succ6, st.succ),
            stab_op=jnp.where(any_fin, 0, st.stab_op),
            stab_to=jnp.where(any_fin, T_INF, st.stab_to))

        # NewSuccessorHint (handleNewSuccessorHint): adopt hinted nodes
        # inside (me, succ0) — batch = one sorted merge of all taken
        # hints.  Documented deviation from the sequential fold: the fold
        # re-checks each hint against the SHRINKING (me, succ0) interval,
        # so with two same-tick hints h1 < h2 < succ0 it would adopt only
        # h1; the batch checks both against the pre-tick succ0 and keeps
        # both (h2 is still a valid, closer-than-old-succ0 successor that
        # the next stabilize round would have learned anyway).
        en = v_r & (msgs.kind == wire.CHORD_SUCC_HINT) & (st.state == READY)
        hk = ctx.keys[jnp.maximum(msgs.a, 0)]
        s0k2 = ctx.keys[jnp.maximum(st.succ[0], 0)]
        take = en & (msgs.a != NO_NODE) & (
            (st.succ[0] == NO_NODE)
            | K.is_between(hk, jnp.broadcast_to(me_key, hk.shape),
                           jnp.broadcast_to(s0k2, hk.shape), spec))
        succ7 = self._succ_sorted(
            ctx, me_key, node_idx,
            jnp.concatenate([st.succ, jnp.where(take, msgs.a, NO_NODE)]))
        st = dataclasses.replace(
            st, succ=jnp.where(jnp.any(take), succ7, st.succ))

        # KBR broadcast (Chord::forwardBroadcast, Chord.cc:1410-1446):
        # walk fingers+successors by DESCENDING clockwise distance;
        # every candidate inside (me, limit) gets a copy whose limit
        # is the previous candidate, shrinking the covered range.
        # Fan-out is capped at BCAST_FANOUT copies with the closest
        # successor always last so the near range stays covered
        # (distinct fingers ~ log N; the cap only binds at huge N).
        # The per-slot fanout walk is vmapped; all copies leave in one
        # vector send.
        en_b = v_r & (msgs.kind == wire.BROADCAST) & (st.state == READY)
        bc = jnp.concatenate([st.finger, st.succ])
        bck = ctx.keys[jnp.maximum(bc, 0)]
        me_bb = jnp.broadcast_to(me_key, bck.shape)
        dup_bc = K.dup_mask(bc)
        d_bc = K.sub(bck, me_bb, spec)          # cw distance me -> cand

        def _bcast_slot(mkey, enb):
            lim_b = jnp.broadcast_to(mkey, bck.shape)
            ok_b = (bc != NO_NODE) & (bc != node_idx) & ~dup_bc \
                & K.is_between(bck, me_bb, lim_b, spec)
            db = jnp.where(ok_b[:, None], d_bc, jnp.zeros_like(d_bc))
            (bc_s,) = _sort_lanes(db, (jnp.where(ok_b, bc, NO_NODE),))
            n_ok = jnp.sum(ok_b.astype(I32))
            cdim = bc_s.shape[0]
            j = jnp.arange(BCAST_FANOUT, dtype=I32)
            idx_j = jnp.clip(cdim - 1 - j, 0, cdim - 1)
            tgt = jnp.where(j < n_ok, bc_s[idx_j], NO_NODE)  # far -> near
            # copy j's limit = the previous copy's target key (j=0: mkey)
            tk = ctx.keys[jnp.maximum(tgt, 0)]               # [F, KL]
            lim = jnp.concatenate([mkey[None], tk[:-1]], axis=0)
            fire = enb & (tgt != NO_NODE)
            # cap bound (> FANOUT candidates): one extra copy to the
            # NEAREST candidate carries the remaining (me, limit) range,
            # which it re-splits recursively — without it the near range
            # would never see the broadcast.  fire_n requires n_ok >
            # FANOUT, so the last fired copy is always index FANOUT-1.
            near = bc_s[jnp.clip(cdim - n_ok, 0, cdim - 1)]
            fire_n = enb & (n_ok > BCAST_FANOUT) & (near != NO_NODE)
            lim_n = tk[BCAST_FANOUT - 1]
            return tgt, lim, fire, near, fire_n, lim_n

        tgt_v, lim_v, fire_v, near_v, firen_v, limn_v = jax.vmap(
            _bcast_slot)(msgs.key, en_b)
        bshape = (r_in, BCAST_FANOUT)
        ob.send(fire_v.reshape(-1),
                jnp.broadcast_to(now_r[:, None], bshape).reshape(-1),
                tgt_v.reshape(-1), wire.BROADCAST,
                key=lim_v.reshape(r_in * BCAST_FANOUT, -1),
                a=jnp.broadcast_to(msgs.a[:, None], bshape).reshape(-1),
                b=jnp.broadcast_to(msgs.b[:, None], bshape).reshape(-1),
                hops=jnp.broadcast_to((msgs.hops + 1)[:, None],
                                      bshape).reshape(-1),
                size_b=wire.BASE_CALL_B + 20)
        ob.send(firen_v, now_r, jnp.maximum(near_v, 0), wire.BROADCAST,
                key=limn_v, a=msgs.a, b=msgs.b, hops=msgs.hops + 1,
                size_b=wire.BASE_CALL_B + 20)

        # app-owned message kinds (Common API deliver path,
        # BaseApp::handleCommonAPIMessage), with the per-slot findNode
        # sibling flags computed above
        if hasattr(self.app, "on_msgs"):
            st = dataclasses.replace(st, app=self.app.on_msgs(
                st.app, msgs, ctx, ob, ev, sib_b, node_idx=node_idx))
        else:
            for r in range(r_in):
                st = dataclasses.replace(st, app=self.app.on_msg(
                    st.app, msgs.slot(r), ctx, ob, ev, sib_b[r]))

        # ping (predecessor liveness + generic); the response piggybacks
        # this node's Vivaldi coordinates (the reference attaches
        # ncsInfo[] to every RPC response, CommonMessages.msg:233 /
        # NeighborCache piggybacking)
        if self.ncs.is_landmark_type:
            ping_key = ncs_mod.pack_wire_nps(
                st.ncs.coords, st.ncs.error, st.ncs.layer, spec.lanes)
        else:
            ping_key = ncs_mod.pack_wire(st.ncs.coords, st.ncs.error,
                                         spec.lanes)
        ob.send(v_r & (msgs.kind == wire.PING_CALL), now_r, msgs.src,
                wire.PING_RES, a=msgs.a, key=ping_key,
                size_b=wire.BASE_CALL_B + 4 * (
                    self.ncs.dims
                    + (2 if self.ncs.is_landmark_type else 1)))
        # ping response: at most one slot matches the in-flight
        # predecessor ping (a == -3 marks NPS probe pongs — the probe
        # target can BE the predecessor, so src alone is ambiguous)
        en_p = v_r & (msgs.kind == wire.PING_RES) & (
            msgs.src == st.cp_dst) & (msgs.a != -3)
        any_p = jnp.any(en_p)
        r_p = jnp.clip(jnp.argmax(en_p).astype(I32), 0, r_in - 1)
        now_p = msgs.t_deliver[r_p]
        rtt_s = (now_p - st.cp_sent).astype(jnp.float32) / NS
        nc_row = dict(peer=st.nc.peer, rtt_mean=st.nc.rtt_mean,
                      rtt_var=st.nc.rtt_var, last=st.nc.last,
                      live=st.nc.live)
        nc_row = nc_mod.insert_rtt(nc_row, msgs.src[r_p], rtt_s, now_p,
                                   any_p)
        st = dataclasses.replace(st, nc=nc_mod.NcState(**nc_row))
        if self.ncs.ncs_type in ("vivaldi", "svivaldi"):
            xj, ej = ncs_mod.unpack_wire(msgs.key[r_p], self.ncs.dims)
            me_ncs = dict(coords=st.ncs.coords, height=st.ncs.height,
                          error=st.ncs.error, loss=st.ncs.loss)
            upd = ncs_mod.update(me_ncs, jnp.where(any_p, rtt_s, -1.0),
                                 xj, ej, jnp.float32(0.0), self.ncs)
            st = dataclasses.replace(
                st, ncs=dataclasses.replace(st.ncs, **upd))
        st = dataclasses.replace(
            st, cp_to=jnp.where(any_p, T_INF, st.cp_to),
            cp_dst=jnp.where(any_p, NO_NODE, st.cp_dst))

        # GNP/NPS landmark-probe pong: RTT sample to the reference point
        # → triangulate own coords (Nps::doTriangulation equivalent) and
        # adopt layer = max(ref layers)+1 (Nps.h:119-133)
        if self.ncs.is_landmark_type:
            en_np = (v_r & (msgs.kind == wire.PING_RES)
                     & (msgs.src == st.nps_dst) & (msgs.a == -3))
            any_np = jnp.any(en_np)
            r_np = jnp.clip(jnp.argmax(en_np).astype(I32), 0, r_in - 1)
            xj, ej, lj = ncs_mod.unpack_wire_nps(msgs.key[r_np],
                                                 self.ncs.dims)
            rtt_np = (msgs.t_deliver[r_np] - st.nps_sent).astype(
                jnp.float32) / NS
            me_np = dict(coords=st.ncs.coords, error=st.ncs.error,
                         layer=st.ncs.layer, ref_rtt=st.ncs.ref_rtt,
                         ref_xy=st.ncs.ref_xy, ref_layer=st.ncs.ref_layer,
                         ref_n=st.ncs.ref_n)
            me_np = ncs_mod.nps_add_sample(
                me_np, jnp.where(any_np, rtt_np, -1.0), xj, lj, self.ncs)
            me_np = ncs_mod.nps_solve(me_np, self.ncs)
            st = dataclasses.replace(
                st,
                ncs=dataclasses.replace(st.ncs, **{
                    k: jnp.where(any_np, v, getattr(st.ncs, k))
                    for k, v in me_np.items()}),
                nps_dst=jnp.where(any_np, NO_NODE, st.nps_dst))

        # ------------------------------------------------------- timers ----
        t_end = ctx.t_end

        # join (joinOverlay / handleJoinTimerExpired Chord.cc:758)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone_start = en_j & (boot == NO_NODE)
        st = self._become_ready(ctx, st, alone_start, now_j, rngs[2])
        joins_cnt += alone_start.astype(I32)
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone_start,
            now_j + jnp.int64(int(p.join_delay * NS)), st.t_join))

        # GNP/NPS probe timer: measure RTT to a reference point — GNP
        # pings landmarks only; NPS alternates landmarks and random
        # positioned nodes so higher layers form (Nps.h:119-133)
        if self.ncs.is_landmark_type:
            en_np = (st.state == READY) & (st.t_nps < t_end)
            now_np = jnp.maximum(st.t_nps, t0)
            # a probe still unanswered when the next probe tick arrives
            # has timed out (probe_interval >> rpc timeout): clear it so
            # a lost pong never wedges probing (lost-probe expiry)
            st = dataclasses.replace(st, nps_dst=jnp.where(
                en_np, NO_NODE, st.nps_dst))
            r_a, r_b, r_c = jax.random.split(
                jax.random.fold_in(rngs[5], 17), 3)
            lm = jax.random.randint(r_a, (), 0,
                                    self.ncs.num_landmarks).astype(I32)
            lm = jnp.where(ctx.alive[jnp.clip(lm, 0, ctx.alive.shape[0]
                                              - 1)], lm, NO_NODE)
            if self.ncs.ncs_type == "nps":
                alt = ctx.sample_ready(r_b, node_idx)
                use_alt = (jax.random.uniform(r_c, ()) < 0.5) & (
                    alt != NO_NODE)
                target_np = jnp.where(use_alt, alt, lm)
            else:
                target_np = lm
            target_np = jnp.where(target_np == node_idx, NO_NODE,
                                  target_np)
            fire_np = en_np & (target_np != NO_NODE)
            ob.send(fire_np, now_np, target_np, wire.PING_CALL,
                    a=jnp.int32(-3), size_b=wire.BASE_CALL_B)
            st = dataclasses.replace(
                st,
                nps_dst=jnp.where(fire_np, target_np, st.nps_dst),
                nps_sent=jnp.where(fire_np, now_np, st.nps_sent),
                t_nps=jnp.where(
                    en_np, now_np + jnp.int64(
                        int(self.ncs.probe_interval * NS)), st.t_nps))

        # partition-merge probe (BootstrapList::locateBootstrapNode,
        # BootstrapList.cc:268-280; mergeOverlayPartitions): look up an
        # oracle-drawn candidate's key through the OWN overlay — the
        # completion handler detects a foreign partition when the lookup
        # does not come back with the candidate itself
        if p.merge_partitions:
            en_m = (st.state == READY) & (st.t_merge < t_end)
            now_m = jnp.maximum(st.t_merge, t0)
            cand_m = ctx.sample_ready(jax.random.fold_in(rngs[1], 23),
                                      node_idx)
            ck_m = ctx.keys[jnp.maximum(cand_m, 0)]
            nxt_m, sib_m = self._find_node(ctx, st, me_key, node_idx,
                                           ck_m)
            no_merge_lk = ~jnp.any(st.lk.active & (st.lk.purpose
                                                   == P_MERGE))
            slot, have = lk_mod.free_slot(st.lk)
            start_m = (en_m & (cand_m != NO_NODE) & (cand_m != node_idx)
                       & ~sib_m & no_merge_lk & have
                       & (nxt_m != NO_NODE))
            seed_m = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(
                nxt_m)
            st = dataclasses.replace(st, lk=lk_mod.start(
                st.lk, start_m, slot, P_MERGE, cand_m, ck_m, seed_m,
                now_m, lcfg))
            st = dataclasses.replace(st, t_merge=jnp.where(
                en_m, now_m + jnp.int64(int(p.merge_interval * NS)),
                st.t_merge))

        # stabilize (handleStabilizeTimerExpired)
        en_s = (st.state == READY) & (st.t_stab < t_end)
        now_s = jnp.maximum(st.t_stab, t0)
        has_succ = st.succ[0] != NO_NODE
        fire_s = en_s & has_succ
        ob.send(fire_s, now_s, st.succ[0], wire.CHORD_STABILIZE_CALL,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(
            st,
            stab_op=jnp.where(fire_s, 1, st.stab_op),
            stab_dst=jnp.where(fire_s, st.succ[0], st.stab_dst),
            stab_to=jnp.where(fire_s, now_s + rpc_to_ns, st.stab_to),
            t_stab=jnp.where(en_s, now_s + jnp.int64(
                int(p.stabilize_delay * NS)), st.t_stab))

        # fixfingers (handleFixFingersTimerExpired): mark non-trivial
        # fingers dirty, remove trivial ones
        en_f = (st.state == READY) & (st.t_fix < t_end) & has_succ
        s0k = ctx.keys[jnp.maximum(st.succ[0], 0)]
        sdist = K.sub(s0k, me_key, spec)                    # me → succ
        nontrivial = K.gt(self._pow2, jnp.broadcast_to(sdist,
                                                       self._pow2.shape))
        st = dataclasses.replace(
            st,
            finger_dirty=jnp.where(en_f, nontrivial, st.finger_dirty),
            finger=jnp.where(en_f & ~nontrivial, NO_NODE, st.finger),
            t_fix=jnp.where((st.state == READY) & (st.t_fix < t_end),
                            jnp.maximum(st.t_fix, t0)
                            + jnp.int64(int(p.fixfingers_delay * NS)),
                            st.t_fix))

        # subclass periodic protocols (Koorde de Bruijn timer)
        st = self._extra_timers(ctx, st, ob, me_key, node_idx, t0, t_end,
                                rngs[5])

        # predecessor check (handleCheckPredecessorTimerExpired)
        en_c = (st.state == READY) & (st.t_cp < t_end)
        now_c = jnp.maximum(st.t_cp, t0)
        fire_c = en_c & (st.pred != NO_NODE) & (st.cp_to == T_INF)
        ob.send(fire_c, now_c, st.pred, wire.PING_CALL,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(
            st,
            cp_to=jnp.where(fire_c, now_c + rpc_to_ns, st.cp_to),
            cp_dst=jnp.where(fire_c, st.pred, st.cp_dst),
            cp_sent=jnp.where(fire_c, now_c, st.cp_sent),
            t_cp=jnp.where(en_c, now_c + jnp.int64(
                int(p.check_pred_delay * NS)), st.t_cp))

        # app timer → start an app lookup (KBRTestApp::handleTimerEvent →
        # callRoute → iterative lookup, SURVEY §3.2)
        # graceful-leave: hand app data to the successor and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.succ[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[3], ev, node_idx)
        st = dataclasses.replace(st, app=app)
        nxt_a, sib_a = self._find_node(ctx, st, me_key, node_idx, req.key)
        # local responsibility → immediate completion, hopCount 0
        # (sendToKey with local sibling → direct deliver).  The result set
        # is the full sibling set — self + successor list — matching the
        # responder-side FINDNODE_RES payload (Chord::findNode sibling
        # case, Chord.cc:548-560), so numReplica consumers (DHT puts) get
        # the whole replica set for locally-owned keys too.
        local = req.want & sib_a
        res_local = jnp.concatenate([node_idx[None], st.succ])[
            :lcfg.frontier]
        if res_local.shape[0] < lcfg.frontier:
            res_local = jnp.concatenate([res_local, jnp.full(
                (lcfg.frontier - res_local.shape[0],), NO_NODE, I32)])
        slot, have = lk_mod.free_slot(st.lk)
        if self.rcfg is None:
            start_app = req.want & ~sib_a & have & (nxt_a != NO_NODE)
            route_fire = jnp.bool_(False)
        elif hasattr(self.app, "route_policy"):
            # recursive data path (sendToKey recursive branch at the
            # originator): payloads the app declares routable are
            # forwarded hop-by-hop; everything else (lookup test, DHT
            # LookupCall) keeps the iterative engine.  Gated on the app
            # speaking the protocol — an app without route_policy never
            # has its lookups diverted.
            routable, inner_a, is_rpc = self.app.route_policy(req.tag)
            route_fire = req.want & ~sib_a & routable & (nxt_a != NO_NODE)
            ew0 = self.rcfg.ext_words
            vis0 = jnp.full((rmax,), NO_NODE, I32).at[ew0].set(node_idx)
            if ew0:
                # zeroed ext head → the first hop lazily initializes the
                # overlay routing ext (Koorde findDeBruijnHop init path)
                vis0 = vis0.at[:ew0].set(0)
            st = dataclasses.replace(st, rr=rt_mod.forward(
                st.rr, ob, route_fire, now_a, nxt_a, key=req.key,
                inner=inner_a, a=req.tag, b=jnp.int32(0),
                c=ctx.measuring.astype(I32), hops=jnp.int32(1),
                stamp=now_a, size_b=jnp.int32(100), visited=vis0,
                cfg=self.rcfg))
            if hasattr(self.app, "on_route_fired"):
                st = dataclasses.replace(st, app=self.app.on_route_fired(
                    st.app, route_fire & is_rpc, now_a, req.tag))
            start_app = (req.want & ~sib_a & ~routable & have
                         & (nxt_a != NO_NODE))
        else:
            start_app = req.want & ~sib_a & have & (nxt_a != NO_NODE)
            route_fire = jnp.bool_(False)
        # could not even start (no slot / empty local findNode) → failed
        # completion right away
        insta_fail = req.want & ~sib_a & ~start_app & ~route_fire
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local | insta_fail, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, res_local, NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(nxt_a)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key, seed, now_a,
            lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes, _ = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)

        # stabilize / notify RPC timeout → failed successor
        en = (st.stab_op != 0) & (st.stab_to < t_end)
        stab_failed = jnp.where(en, st.stab_dst, NO_NODE)
        st = dataclasses.replace(
            st, stab_op=jnp.where(en, 0, st.stab_op),
            stab_to=jnp.where(en, T_INF, st.stab_to))

        # predecessor ping timeout → the PINGED node failed (a predecessor
        # adopted after the ping was sent is NOT dropped)
        en = st.cp_to < t_end
        cp_failed = jnp.where(en, st.cp_dst, NO_NODE)
        st = dataclasses.replace(
            st, cp_to=jnp.where(en, T_INF, st.cp_to),
            cp_dst=jnp.where(en, NO_NODE, st.cp_dst))

        # route-hop ACK timeouts: unresponsive next hops are failures too
        if self.rcfg is not None:
            new_rr, rt_failed, rt_retry = rt_mod.on_timeouts(
                st.rr, t_end, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
        else:
            rt_failed = jnp.full((0,), NO_NODE, I32)

        # one batched repair pass for every failure source this tick
        st = self._handle_failed(
            ctx, st, me_key, node_idx,
            jnp.concatenate([failed_nodes, stab_failed[None],
                             cp_failed[None], rt_failed]), t0)

        # reroute parked route messages around the failed hop (it was
        # just dropped from the tables, so findNode picks an alternative;
        # internalHandleRpcTimeout reroute, BaseOverlay.cc:1697-1729).
        # One vmapped findNode over the Q slot keys; a node that became
        # responsible for a parked key meanwhile self-forwards so the
        # message still delivers (pastry.py does the same).
        if self.rcfg is not None:
            ew_q = self.rcfg.ext_words
            nxt_q, sib_q = jax.vmap(
                lambda kk: self._find_node(ctx, st, me_key, node_idx, kk))(
                st.rr.key)
            nxt_q2, found_q = jax.vmap(
                rt_mod.pick_next_hop, in_axes=(0, 0, 0, 0, None, 0))(
                nxt_q[:, None], st.rr.visited[:, ew_q:], rt_failed,
                st.rr.visited[:, ew_q], node_idx, sib_q)
            nxt_fin = jnp.where(sib_q, node_idx, nxt_q2)
            ok_q = rt_retry & (sib_q | found_q)
            st = dataclasses.replace(st, rr=rt_mod.reforward_batch(
                st.rr, ob, ok_q, t0, nxt_fin, self.rcfg))
            give_up = rt_retry & ~ok_q
            st = dataclasses.replace(st, rr=rt_mod.drop_slots(
                st.rr, give_up))
            routedrop_cnt += jnp.sum(give_up.astype(I32))

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        taken = comp["taken"]                                # [L]
        suc_l = comp["success"] & (comp["result"] != NO_NODE)
        pur_l = comp["purpose"]
        res_l = comp["result"]
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        taken & comp["success"])
        lksucc_cnt += jnp.sum((taken & suc_l).astype(I32))
        anyfail_cnt += jnp.sum((taken & ~suc_l).astype(I32))

        # join: contact our successor directly (one vector send)
        ob.send(taken & suc_l & (pur_l == P_JOIN), t0, res_l,
                wire.CHORD_JOIN_CALL,
                size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)

        # partition-merge probe completions (handleLookupResponse,
        # BootstrapList.cc:171-195): the candidate's key resolved to a
        # sibling set that does NOT contain the candidate → it lives in
        # a foreign formed ring.  joinForeignPartition equivalent: adopt
        # it as a successor candidate and hint ourselves to it — the
        # rings then knit via normal stabilize/notify rounds.
        if p.merge_partitions:
            enm_l = taken & (pur_l == P_MERGE) & suc_l
            any_m = jnp.any(enm_l)
            li_m = jnp.clip(jnp.argmax(enm_l).astype(I32), 0,
                            lcfg.slots - 1)
            x_m = comp["aux"][li_m]
            foreign = any_m & jnp.all(comp["results"][li_m] != x_m) & (
                x_m != NO_NODE) & ctx.alive[jnp.maximum(x_m, 0)]
            succ_m = self._succ_sorted(
                ctx, me_key, node_idx,
                jnp.concatenate([st.succ,
                                 jnp.where(foreign, x_m, NO_NODE)[None]]))
            st = dataclasses.replace(
                st, succ=jnp.where(foreign, succ_m, st.succ))
            ob.send(foreign, t0, x_m, wire.CHORD_SUCC_HINT, a=node_idx,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)

        # finger repair results (one scatter per field)
        enf = taken & (pur_l == P_FINGER)
        fi_l = jnp.clip(comp["aux"], 0, spec.bits - 1)
        st = dataclasses.replace(
            st,
            finger=st.finger.at[jnp.where(enf & suc_l, fi_l, spec.bits)]
            .set(res_l, mode="drop"),
            finger_dirty=st.finger_dirty
            .at[jnp.where(enf, fi_l, spec.bits)].set(False, mode="drop"))

        # app lookups → app completion hook (batched when supported)
        ena_l = taken & (pur_l == P_APP)
        if hasattr(self.app, "on_lookup_done_batch"):
            st = dataclasses.replace(st, app=self.app.on_lookup_done_batch(
                st.app, app_base.LookupDone(
                    en=ena_l, success=ena_l & suc_l, tag=comp["aux"],
                    target=comp["target"], results=comp["results"],
                    hops=comp["hops"], t0=comp["t0"]),
                ctx, ob, ev, t0, node_idx))
        else:
            for li in range(lcfg.slots):
                st = dataclasses.replace(st, app=self.app.on_lookup_done(
                    st.app, app_base.LookupDone(
                        en=ena_l[li], success=ena_l[li] & suc_l[li],
                        tag=comp["aux"][li], target=comp["target"][li],
                        results=comp["results"][li], hops=comp["hops"][li],
                        t0=comp["t0"][li]),
                    ctx, ob, ev, t0, node_idx))

        # subclass purposes (Koorde de Bruijn resolution) — the per-slot
        # hook only traces when a subclass actually overrides it
        if type(self)._on_completion is not ChordLogic._on_completion:
            for li in range(lcfg.slots):
                st = self._on_completion(
                    ctx, st, ob, li, comp, taken[li], suc_l[li], res_l[li],
                    t0)

        # -------------------------------------------- finger repair pump ---
        dirty_any = (st.state == READY) & jnp.any(st.finger_dirty)
        no_finger_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_FINGER))
        fi = jnp.argmax(st.finger_dirty).astype(I32)
        target = K.add(me_key, self._pow2[fi], spec)
        nxt_f, sib_f = self._find_node(ctx, st, me_key, node_idx, target)
        # responsible ourselves → no finger needed (covered by succ list)
        self_fix = dirty_any & no_finger_lk & sib_f
        st = dataclasses.replace(
            st,
            finger_dirty=jnp.where(self_fix,
                                   st.finger_dirty.at[fi].set(False),
                                   st.finger_dirty))
        slot, have = lk_mod.free_slot(st.lk)
        start_fix = dirty_any & no_finger_lk & ~sib_f & have & (
            nxt_f != NO_NODE)
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(nxt_f)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_fix, slot, P_FINGER, fi, target, seed, t0, lcfg))

        # ------------------------------------------------------- pump ------
        # adaptive per-destination RPC timeouts from the RTT cache
        # (NeighborCache::getNodeTimeout, NeighborCache.cc:802)
        new_lk, _ = lk_mod.pump(
            st.lk, ob, ctx, node_idx, t0, rngs[4], lcfg,
            timeout_fn=nc_mod.adaptive_timeout_fn(st.nc,
                                                  lcfg.rpc_timeout_ns),
            prox_fn=(nc_mod.prox_fn(st.nc) if lcfg.prox_aware else None))
        st = dataclasses.replace(st, lk=new_lk)

        # Common API update() (BaseOverlay::callUpdate → BaseApp::update,
        # BaseApp.h:223): nodes that entered the successor list — Chord's
        # replica/sibling set — trigger app re-replication this tick
        if hasattr(self.app, "on_update"):
            new_in = jnp.where(
                (st.succ != NO_NODE)
                & ~jnp.any(st.succ[:, None] == old_succ[None, :], axis=1),
                st.succ, NO_NODE)
            # a NEW PREDECESSOR is an ownership transfer: the joiner
            # inherits the keyspace between the old and new pred, and
            # must receive this node's records for it.  The reference
            # reaches the same spot via the isSiblingFor err-hack
            # (DHT.cc:779-797 "For Chord: we've got a new predecessor"
            # → sendMaintenancePutCall regardless) — without it every
            # join creates a data-less primary and DHT get-success
            # erodes under churn.  Listed FIRST so the app's one-target
            # stager prioritizes the ownership transfer over ordinary
            # succ-list deltas.
            new_pred = jnp.where(
                (st.pred != NO_NODE) & (st.pred != old_pred)
                & (st.pred != node_idx), st.pred, NO_NODE)
            new_in = jnp.concatenate([new_pred[None], new_in])
            st = dataclasses.replace(st, app=self.app.on_update(
                st.app, st.state == READY, ctx, ob, ev, t0, node_idx,
                new_in,
                sib_keys=ctx.keys[jnp.maximum(st.succ, 0)],
                sib_valid=st.succ != NO_NODE,
                urgent=new_pred != NO_NODE))

        # ------------------------------------------------------ events -----
        events = {
            "c:chord_joins": joins_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "c:route_dropped": routedrop_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events
