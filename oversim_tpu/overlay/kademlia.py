"""Kademlia XOR-metric DHT as vectorized per-node logic.

TPU-native rebuild of the reference Kademlia
(src/overlay/kademlia/Kademlia.{h,cc} + KademliaBucket/KademliaBucketEntry),
default configuration (simulations/default.ini:185-200: k=8, s=8, b=1,
maxStaleCount=0, lookupMerge=true, iterative routing, exhaustiveRefresh,
minSibling/BucketRefreshInterval=1000s).  State is structure-of-arrays:

  * sibling table [N, S]: the s XOR-closest known nodes, kept sorted by
    distance from own key (KademliaBucket extends BaseKeySortedVector);
  * k-buckets [N, B, K] with last-seen [N, B, K] and stale counters:
    bucket index = sharedPrefixLength(own, other) clipped to B-1
    (reference routingBucketIndex Kademlia.cc:357 = first non-zero digit
    of the XOR delta — identical partition for b=1; distant-prefix
    buckets beyond B-1 collapse onto the last row, which only matters
    for astronomically-close non-sibling keys);
  * routingAdd (Kademlia.cc:432): every message source is added alive
    with the full policy (sibling merge incl. displacement of the
    furthest sibling into a bucket; in-bucket lastSeen refresh; free-slot
    insert; stale-entry replacement).  Nodes learned from
    FindNodeResponse payloads are added unverified (isAlive=false,
    Kademlia.cc:1412): they merge into the sibling table and fill FREE
    bucket slots only — no displacement (the reference's replacement
    cache and bucket-ping machinery are TODO);
  * isSiblingFor (Kademlia.cc:888): table smaller than numSiblings →
    true; key farther than the furthest sibling while full → false;
    otherwise membership of self in the numSiblings closest of
    siblings ∪ self;
  * findNode (Kademlia.cc:1101): top-R by XOR distance over
    self ∪ siblings ∪ all buckets (the reference walks best bucket →
    surrounding buckets → siblings; same result set);
  * join (Kademlia.cc:1027-1081): iterative lookup of the own key seeded
    from the bootstrap node, then bucket refresh;
  * periodic refresh: sibling-table refresh = lookup own key; bucket
    refresh = lookup a random key with the bucket's exact shared-prefix
    length, for buckets unused for minBucketRefreshInterval
    (handleBucketRefreshTimerExpired Kademlia.cc:1591) — repaired one
    lookup at a time off a dirty mask (bounded concurrency);
  * handleFailedNode (Kademlia.cc:979): drop from siblings; stale+1 in
    buckets, evict when staleCount > maxStaleCount;
  * downlists (lookupFinished Kademlia.cc:1543) are TODO.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import malicious as mal_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

DEAD, JOINING, READY = 0, 1, 2

# lookup purposes
P_JOIN, P_REFRESH, P_APP, P_SIB = 1, 2, 3, 4


@dataclasses.dataclass(frozen=True)
class KademliaParams:
    """default.ini:185-200 + Kademlia.ned defaults."""

    k: int = 8                    # bucket size
    s: int = 8                    # sibling table size
    num_buckets: int = 32         # B — prefix-length clip (see module doc)
    max_stale: int = 0            # maxStaleCount
    join_delay: float = 10.0      # joinDelay (BaseOverlay)
    sibling_refresh: float = 1000.0   # minSiblingTableRefreshInterval
    bucket_refresh: float = 1000.0    # minBucketRefreshInterval
    redundant_nodes: int = 8      # lookupRedundantNodes
    rpc_timeout: float = 1.5


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KademliaState:
    state: jnp.ndarray      # [N] i32
    sib: jnp.ndarray        # [N, S] i32 sorted by xor distance from own key
    buckets: jnp.ndarray    # [N, B, K] i32
    b_seen: jnp.ndarray     # [N, B, K] i64 — lastSeen (0 = unverified)
    b_stale: jnp.ndarray    # [N, B, K] i32
    b_used: jnp.ndarray     # [N, B] i64 — bucket lastUsage
    refresh_dirty: jnp.ndarray  # [N, B] bool
    t_join: jnp.ndarray     # [N] i64
    t_refresh: jnp.ndarray  # [N] i64 — periodic bucket/sibling refresh tick
    sib_used: jnp.ndarray   # [N] i64 — sibling table lastUsage
    lk: lk_mod.LookupState
    app: object                # [N, ...] tier-app state (apps/base.py)
    app_glob: object           # simulation-global app state (oracle maps)


class KademliaLogic:
    """Engine logic interface (see engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: KademliaParams = KademliaParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None,
                 mparams: mal_mod.MaliciousParams = mal_mod.MaliciousParams()):
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg or lk_mod.LookupConfig(merge=True)
        self.app = app or KbrTestApp()
        self.mp = mparams
        self._pow2 = K.pow2_table(spec)

    # -- engine interface ---------------------------------------------------

    def split(self, st: KademliaState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: KademliaState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: KademliaState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "kad_joins", "lookup_success", "lookup_failed"),
        )

    def init(self, rng, n: int) -> KademliaState:
        p = self.p
        return KademliaState(
            state=jnp.zeros((n,), I32),
            sib=jnp.full((n, p.s), NO_NODE, I32),
            buckets=jnp.full((n, p.num_buckets, p.k), NO_NODE, I32),
            b_seen=jnp.zeros((n, p.num_buckets, p.k), I64),
            b_stale=jnp.zeros((n, p.num_buckets, p.k), I32),
            b_used=jnp.zeros((n, p.num_buckets), I64),
            refresh_dirty=jnp.zeros((n, p.num_buckets), bool),
            t_join=jnp.full((n,), T_INF, I64),
            t_refresh=jnp.full((n,), T_INF, I64),
            sib_used=jnp.zeros((n,), I64),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: KademliaState, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: KademliaState):
        return st.state == READY

    def next_event(self, st: KademliaState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(ready, st.t_refresh, T_INF))
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        return t

    # -- key-space helpers (single node slice) -------------------------------

    def _xor_to(self, ctx, slots, key):
        """[C] slots → [C, KL] xor distance of slot keys to ``key``
        (NO_NODE → max distance)."""
        ck = ctx.keys[jnp.maximum(slots, 0)]
        d = ck ^ jnp.broadcast_to(key, ck.shape)
        return jnp.where((slots == NO_NODE)[:, None], UMAX, d)

    def _bucket_index(self, me_key, other_key):
        """Shared-prefix bucket index, clipped to B-1."""
        pl = K.shared_prefix_length(me_key, other_key, self.key_spec)
        return jnp.clip(pl, 0, self.p.num_buckets - 1)

    def _sib_merge(self, ctx, me_key, node_idx, sib, cands, cand_ok):
        """Merge candidate slots into the sibling table.

        Returns (new_sib [S], displaced [S] i32): every node pushed out of
        a previously-full table (NO_NODE padded) — reference routingAdd
        moves verified ex-siblings into their buckets (Kademlia.cc:613
        area); a batch merge can displace several at once.
        """
        s = self.p.s
        c = jnp.concatenate([sib, jnp.where(cand_ok, cands, NO_NODE)])
        # dedupe (keep first occurrence — table entries win over candidates)
        bad = (c == NO_NODE) | (c == node_idx) | K.dup_mask(c)
        c = jnp.where(bad, NO_NODE, c)
        d = self._xor_to(ctx, c, me_key)
        (c_s,) = K.sort_by_distance(d, (c,))[1]
        new_sib = c_s[:s]
        # displaced: previously a sibling, no longer one
        was = sib != NO_NODE
        still = jnp.any(sib[:, None] == new_sib[None, :], axis=1)
        disp = jnp.where(was & ~still, sib, NO_NODE)
        return new_sib, disp

    def _bucket_update_batch(self, ctx, st, me_key, cands, alive, now):
        """One-pass batched bucket update — the bucket half of routingAdd
        (Kademlia.cc:432-700) for ALL of a tick's C candidates at once.

        Per-candidate policy, equal to the sequential reference up to
        within-tick ordering: present+alive → lastSeen refresh / stale
        reset; absent → take a free slot, or (alive candidates only)
        evict a stale entry (staleCount > maxStaleCount, highest count
        first); unverified candidates (alive=False, nodes learned from
        FindNodeResponse payloads, Kademlia.cc:1412) fill free slots only
        and never displace.  Alive candidates get slot priority.  The
        whole policy is one candidate sort + one column sort + three
        scatters instead of C unrolled scatter chains (the round-2 tick
        graph was dominated by exactly those chains).

        ``cands`` must be deduplicated by the caller; NO_NODE = disabled.
        """
        p = self.p
        num_b, kk = p.num_buckets, p.k
        c_dim = cands.shape[0]
        en = cands != NO_NODE
        ck = ctx.keys[jnp.maximum(cands, 0)]
        bi = jnp.where(en, self._bucket_index(me_key, ck), num_b)

        # --- presence refresh (alive contacts only) ---
        acand = jnp.where(en & alive, cands, NO_NODE)
        hit = jnp.any(
            st.buckets[:, :, None] == acand[None, None, :], axis=-1) & (
            st.buckets != NO_NODE)
        b_seen = jnp.where(hit, now, st.b_seen)
        b_stale = jnp.where(hit, 0, st.b_stale)
        buckets = st.buckets

        # --- slot assignment for absent candidates ---
        row_c = buckets[jnp.minimum(bi, num_b - 1)]           # [C, K]
        present = jnp.any(row_c == cands[:, None], axis=1)
        need = en & ~present
        # candidates ordered by (bucket, alive-first, arrival order)
        k1 = jnp.where(need, bi, num_b).astype(I32)
        k2 = (~alive).astype(I32)
        k3 = jnp.arange(c_dim, dtype=I32)
        b_s, a_s, idx_s = jax.lax.sort((k1, k2, k3), num_keys=3)
        rank = k3 - jnp.searchsorted(b_s, b_s, side="left").astype(I32)
        # per-bucket column order: free columns first, then evictable by
        # stale count descending, then untouchable
        free = buckets == NO_NODE
        evictable = ~free & (b_stale > p.max_stale)
        cls = jnp.where(free, 0, jnp.where(evictable, 1, 2))
        colkey = cls * (1 << 20) - jnp.where(
            evictable, jnp.minimum(b_stale, (1 << 19) - 1), 0)
        order = jnp.argsort(colkey, axis=1).astype(I32)       # [B, K]
        free_cnt = jnp.sum(free, axis=1, dtype=I32)           # [B]
        avail_cnt = free_cnt + jnp.sum(evictable, axis=1, dtype=I32)

        bi_c = jnp.minimum(b_s, num_b - 1)
        limit = jnp.where(a_s == 0, avail_cnt[bi_c], free_cnt[bi_c])
        okc = (b_s < num_b) & (rank < limit) & (rank < kk)
        col = order[bi_c, jnp.clip(rank, 0, kk - 1)]
        rows = jnp.where(okc, bi_c, num_b)
        vals = cands[idx_s]
        al_v = a_s == 0
        return dataclasses.replace(
            st,
            buckets=buckets.at[rows, col].set(vals, mode="drop"),
            b_seen=b_seen.at[rows, col].set(
                jnp.where(al_v, now, jnp.int64(0)), mode="drop"),
            b_stale=b_stale.at[rows, col].set(0, mode="drop"))

    def _routing_add_batch(self, ctx, st, me_key, node_idx, cands, alive,
                           now):
        """Batched routingAdd (Kademlia.cc:432) for a tick's whole
        candidate set: one sibling-table merge sort + one batched bucket
        pass.  ``alive`` marks verified contacts (message sources); false
        = unverified learned nodes.  An alive occurrence of a node wins
        over an unverified duplicate."""
        en = (cands != NO_NODE) & (cands != node_idx)
        cands = jnp.where(en, cands, NO_NODE)
        eq = cands[None, :] == cands[:, None]
        alive = jnp.any(eq & (alive & en)[None, :], axis=1) & en
        dup = K.dup_mask(cands)
        en = en & ~dup
        cands = jnp.where(en, cands, NO_NODE)

        new_sib, disp_vec = self._sib_merge(ctx, me_key, node_idx, st.sib,
                                            cands, en)
        st = dataclasses.replace(st, sib=new_sib)
        became_sib = jnp.any(cands[:, None] == new_sib[None, :], axis=1) & en
        # bucket candidates: displaced ex-siblings re-file as verified
        # contacts (reference routingAdd moves them into their buckets,
        # Kademlia.cc:613 area); non-sibling candidates keep their flag.
        # A displaced node that is ALSO a same-tick candidate must enter
        # the bucket once (the bucket pass requires caller-side dedup):
        # drop the disp_vec copy and promote the candidate to verified.
        in_disp = jnp.any(cands[:, None] == disp_vec[None, :], axis=1) & en
        disp_vec = jnp.where(
            jnp.any(disp_vec[:, None] == jnp.where(en, cands, NO_NODE)[None, :],
                    axis=1), NO_NODE, disp_vec)
        bc = jnp.concatenate([disp_vec,
                              jnp.where(became_sib, NO_NODE, cands)])
        ba = jnp.concatenate([jnp.ones(disp_vec.shape, bool),
                              alive | in_disp])
        return self._bucket_update_batch(ctx, st, me_key, bc, ba, now)

    def _find_node(self, ctx, st, me_key, node_idx, key, rmax):
        """Top-R closest known nodes by XOR distance (Kademlia.cc:1101).

        Returns ([rmax] i32 slots NO_NODE-padded, is_sibling bool)."""
        out, is_sib = self._find_node_batch(ctx, st, me_key, node_idx,
                                            key[None], rmax)
        return out[0], is_sib[0]

    def _find_node_batch(self, ctx, st, me_key, node_idx, keys, rmax):
        """Batched findNode for T target keys at once ([T, KL] → ([T, rmax]
        slots, [T] is_sibling)) — ONE sort over the shared candidate set
        per tick instead of one per unrolled call site.

        findNode: top-R by XOR distance over self ∪ siblings ∪ all buckets
        (Kademlia.cc:1101 walks best bucket → surrounding buckets →
        siblings; same result set).  isSiblingFor: Kademlia.cc:888."""
        p = self.p
        t_dim = keys.shape[0]
        # mask bucket entries that were since promoted into the sibling
        # table (routingAdd can adopt a bucket resident without purging
        # its bucket slot) so the result set never repeats a node
        flat = st.buckets.reshape(-1)
        in_sib = jnp.any(flat[:, None] == st.sib[None, :], axis=1)
        flat = jnp.where(in_sib, NO_NODE, flat)
        cands = jnp.concatenate([node_idx[None], st.sib, flat])    # [C]
        ck = ctx.keys[jnp.maximum(cands, 0)]                       # [C, KL]
        d = ck[None, :, :] ^ keys[:, None, :]                      # [T, C, KL]
        d = jnp.where((cands == NO_NODE)[None, :, None], UMAX, d)
        (c_s,) = K.sort_by_distance(
            d, (jnp.broadcast_to(cands, (t_dim, cands.shape[0])),))[1]
        ready = st.state == READY
        out = jnp.where(ready, c_s[:, :rmax], NO_NODE)
        r = p.redundant_nodes
        if r < rmax:
            out = out.at[:, r:].set(NO_NODE)

        # isSiblingFor(self, key, numSiblings=1) (Kademlia.cc:888)
        n_sib = jnp.sum((st.sib != NO_NODE).astype(I32))
        full = n_sib >= p.s
        d_me = me_key[None, :] ^ keys                              # [T, KL]
        d_far = self._xor_to(ctx, st.sib[-1:], me_key)             # [1, KL]
        not_ours = full & K.gt(d_me, jnp.broadcast_to(d_far, d_me.shape))
        sk = ctx.keys[jnp.maximum(st.sib, 0)]                      # [S, KL]
        d_sib_key = sk[None, :, :] ^ keys[:, None, :]              # [T, S, KL]
        d_sib_key = jnp.where((st.sib == NO_NODE)[None, :, None], UMAX,
                              d_sib_key)
        closer_sib = jnp.any(
            K.lt(d_sib_key, jnp.broadcast_to(d_me[:, None, :],
                                             d_sib_key.shape)), axis=1)
        is_sib = ready & (n_sib < 1) | (ready & ~not_ours & ~closer_sib)
        return out, is_sib

    def _handle_failed(self, ctx, st, me_key, node_idx, failed):
        """handleFailedNode (Kademlia.cc:979): drop sibling / stale+evict.

        ``failed`` may be a scalar or a [K] batch — the whole tick's
        failure list is folded in one sort + one bucket sweep (each
        occurrence of a node in the batch counts one stale strike, like
        the reference's one call per RPC timeout)."""
        failed = jnp.atleast_1d(jnp.asarray(failed, I32))
        en = jnp.any(failed != NO_NODE)
        # sibling drop + re-sort
        hit = jnp.any(st.sib[:, None] == failed[None, :], axis=-1) & (
            st.sib != NO_NODE)
        sib_masked = jnp.where(hit, NO_NODE, st.sib)
        d = self._xor_to(ctx, sib_masked, me_key)
        (sib_s,) = K.sort_by_distance(d, (sib_masked,))[1]
        st = dataclasses.replace(
            st, sib=jnp.where(en, sib_s, st.sib))
        # bucket stale increment (one strike per batch occurrence)
        strikes = jnp.sum(
            st.buckets[..., None] == failed[None, None, :], axis=-1,
            dtype=I32)
        strikes = jnp.where(st.buckets != NO_NODE, strikes, 0)
        stale = st.b_stale + strikes
        evict = (strikes > 0) & (stale > self.p.max_stale)
        return dataclasses.replace(
            st,
            buckets=jnp.where(evict, NO_NODE, st.buckets),
            b_stale=jnp.where(evict, 0, stale),
            b_seen=jnp.where(evict, 0, st.b_seen))

    def _become_ready(self, ctx, st, en, now, rng):
        p = self.p
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            # immediate bucket refresh pass after join (Kademlia.cc:1043)
            t_refresh=jnp.where(en, now, st.t_refresh),
            app=self.app.on_ready(st.app, en, now, rng))

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 8)
        t0 = ctx.t_start
        t_end = ctx.t_end
        r_in = msgs.valid.shape[0]

        def metric_fn(cand_slots, target):
            return self._xor_to(ctx, cand_slots, target)

        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)
        lksucc_cnt = jnp.int32(0)

        # --------------------------------------------- inbox (batched) -----
        # All R inbox slots are consumed in ONE pass per handler class —
        # a masked [R]-batch instead of R unrolled handler chains (the
        # round-2 graph was op-issue-bound on exactly that unrolling).
        # Within-window ordering across the slots is already relaxed by
        # the engine; the batch passes commute the same way.
        v_r = msgs.valid
        t_del_r = msgs.t_deliver

        # FindNodeResponses → lookup engine (one batched pass)
        en_res = v_r & (msgs.kind == wire.FINDNODE_RES)
        st = dataclasses.replace(st, lk=lk_mod.on_responses(
            st.lk, dataclasses.replace(msgs, valid=en_res), metric_fn, lcfg))

        # batched routingAdd (Kademlia.cc:1027/1419): every message source
        # as a verified contact + every FindNodeResponse payload node as
        # an unverified learn (Kademlia.cc:1412)
        learned = jnp.where(en_res[:, None], msgs.nodes[:, :lcfg.frontier],
                            NO_NODE)                            # [R, F]
        add_cands = jnp.concatenate(
            [jnp.where(v_r, msgs.src, NO_NODE), learned.reshape(-1)])
        add_alive = jnp.concatenate(
            [jnp.ones((r_in,), bool),
             jnp.zeros((learned.size,), bool)])
        now_add = jnp.max(jnp.where(v_r, t_del_r, 0))
        st = self._routing_add_batch(ctx, st, me_key, node_idx, add_cands,
                                     add_alive, now_add)

        # FindNodeCalls → batched findNode + sibling flags
        en_call = v_r & (msgs.kind == wire.FINDNODE_CALL)
        res_b, sib_b = self._find_node_batch(ctx, st, me_key, node_idx,
                                             msgs.key, rmax)
        # byzantine switches (common/malicious.py; statically no-op by
        # default).  Only the wire copy is attacked; the honest ``sib_b``
        # feeds the app deliver check below (wrong-node detection)
        if self.mp.active:
            res_atk, sib_atk, respond = jax.vmap(
                lambda rr, ss, rg: mal_mod.attack_findnode(
                    ctx, self.mp, node_idx, rr, ss, rg))(
                res_b, sib_b, jax.random.split(rngs[7], r_in))
        else:
            res_atk, sib_atk, respond = res_b, sib_b, jnp.ones((r_in,), bool)
        ob.send(en_call & respond, t_del_r, msgs.src, wire.FINDNODE_RES,
                key=msgs.key, a=msgs.a, b=msgs.b, c=sib_atk.astype(I32),
                nodes=res_atk,
                size_b=wire.findnode_res_b(p.redundant_nodes))

        # ping (generic liveness)
        ob.send(v_r & (msgs.kind == wire.PING_CALL), t_del_r, msgs.src,
                wire.PING_RES, a=msgs.a, size_b=wire.BASE_CALL_B)

        # app-owned message kinds (Common API deliver path)
        if hasattr(self.app, "on_msgs"):
            st = dataclasses.replace(st, app=self.app.on_msgs(
                st.app, msgs, ctx, ob, ev, sib_b))
        else:
            for r in range(r_in):
                st = dataclasses.replace(st, app=self.app.on_msg(
                    st.app, msgs.slot(r), ctx, ob, ev, sib_b[r]))

        # ------------------------------------------------------- timers ----
        # join (joinOverlay: lookup own key via bootstrap,
        # Kademlia.cc:1027-1081)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone_start = en_j & (boot == NO_NODE)
        st = self._become_ready(ctx, st, alone_start, now_j, rngs[2])
        joins_cnt += alone_start.astype(I32)
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone_start,
            now_j + jnp.int64(int(p.join_delay * NS)), st.t_join))

        # periodic refresh tick: mark stale buckets dirty + sibling refresh
        en_r = (st.state == READY) & (st.t_refresh < t_end)
        now_r = jnp.maximum(st.t_refresh, t0)
        refresh_ns = jnp.int64(int(p.bucket_refresh * NS))
        # only buckets for prefixes we can actually populate: any bucket
        # whose index <= index of the furthest sibling (reference refreshes
        # buckets up to routingBucketIndex(siblingTable->back()),
        # Kademlia.cc:1591 area)
        far_sib = st.sib[-1]
        has_sib = far_sib != NO_NODE
        max_bi = jnp.where(
            has_sib,
            self._bucket_index(me_key, ctx.keys[jnp.maximum(far_sib, 0)]),
            -1)
        bi_range = jnp.arange(p.num_buckets, dtype=I32)
        stale_bucket = st.b_used + refresh_ns < now_r
        mark = en_r & (bi_range <= max_bi) & stale_bucket
        st = dataclasses.replace(
            st,
            refresh_dirty=st.refresh_dirty | mark,
            t_refresh=jnp.where(en_r, now_r + refresh_ns, st.t_refresh))
        # sibling-table refresh timing: lookup own key when unused for the
        # interval (start fires below, after the batched findNode)
        sib_stale = en_r & (st.sib_used + jnp.int64(
            int(p.sibling_refresh * NS)) < now_r)

        # app timer
        # graceful-leave: hand app data to the closest sibling and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.sib[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[3], ev, node_idx)
        st = dataclasses.replace(st, app=app)

        # bucket-refresh target (pump below): random key with
        # sharedPrefixLength(me, target) == bi —
        # delta = 2^(bits-1-bi) | (rand & (2^(bits-1-bi) - 1)); target=me^delta
        bi_ref = jnp.argmax(st.refresh_dirty).astype(I32)
        jbit = jnp.clip(spec.bits - 1 - bi_ref, 0, spec.bits - 1)
        top = self._pow2[jbit]
        mask = K.sub(top, K.from_int(1, spec), spec)
        rnd = K.random_keys(rngs[5], (), spec)
        target_ref = me_key ^ (top | (rnd & mask))

        # ONE batched findNode for every timer consumer: sibling refresh
        # (own key), the app lookup seed, and the bucket-refresh seed
        seeds3, sib3 = self._find_node_batch(
            ctx, st, me_key, node_idx,
            jnp.stack([me_key, req.key, target_ref]), rmax)
        res0, seed_a, seed_r = seeds3[0], seeds3[1], seeds3[2]
        sib_a = sib3[1]

        # sibling refresh start
        no_sib_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_SIB))
        slot, have = lk_mod.free_slot(st.lk)
        start_sib = sib_stale & no_sib_lk & have & (res0[0] != NO_NODE)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_sib, slot, P_SIB, 0, me_key,
            res0[:lcfg.frontier], now_r, lcfg))
        st = dataclasses.replace(
            st, sib_used=jnp.where(start_sib, now_r, st.sib_used))
        # local responsibility → full sibling set (top-s of self ∪
        # siblings by XOR distance to the key), matching the responder-side
        # FINDNODE_RES payload so numReplica consumers get the replica set
        local = req.want & sib_a
        loc_cands = jnp.concatenate([node_idx[None], st.sib])
        loc_d = self._xor_to(ctx, loc_cands, req.key)
        (loc_s,) = K.sort_by_distance(loc_d, (loc_cands,))[1]
        res_local = loc_s[:lcfg.frontier]
        if res_local.shape[0] < lcfg.frontier:
            res_local = jnp.concatenate([res_local, jnp.full(
                (lcfg.frontier - res_local.shape[0],), NO_NODE, I32)])
        slot, have = lk_mod.free_slot(st.lk)
        start_app = req.want & ~sib_a & have & (seed_a[0] != NO_NODE)
        insta_fail = req.want & ~sib_a & ~start_app
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local | insta_fail, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, res_local, NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key,
            seed_a[:lcfg.frontier], now_a, lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes = lk_mod.on_timeouts(st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        # one batched repair for the tick's [L * parallel_rpcs] failures
        st = self._handle_failed(ctx, st, me_key, node_idx, failed_nodes)

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        taken = comp["taken"]                                   # [L]
        suc_l = comp["success"] & (comp["result"] != NO_NODE)
        pur_l = comp["purpose"]
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        taken & comp["success"])
        lksucc_cnt += jnp.sum((taken & suc_l).astype(I32))
        anyfail_cnt += jnp.sum((taken & ~suc_l).astype(I32))

        # join completion → READY (even on failure if we learned nodes;
        # reference joins as long as the sibling table is non-empty).
        # At most one join lookup exists per node (no_join_lk gate above).
        enj = taken & (pur_l == P_JOIN)
        any_j = jnp.any(enj)
        got = any_j & (jnp.any(st.sib != NO_NODE) | jnp.any(enj & suc_l))
        joins_cnt += got.astype(I32)
        st = self._become_ready(ctx, st, got, t0, rngs[4])
        # join failed with nothing learned → retry via t_join
        st = dataclasses.replace(st, t_join=jnp.where(
            any_j & ~got, t0 + jnp.int64(int(p.join_delay * NS)),
            st.t_join))

        # bucket refresh completions → clear dirty bits (one scatter)
        enr_l = taken & (pur_l == P_REFRESH)
        rows_r = jnp.where(enr_l, jnp.clip(comp["aux"], 0,
                                           p.num_buckets - 1),
                           p.num_buckets)
        st = dataclasses.replace(
            st,
            refresh_dirty=st.refresh_dirty.at[rows_r].set(
                False, mode="drop"),
            b_used=st.b_used.at[rows_r].set(t0, mode="drop"))

        # app lookups → app completion hook (batched when the app
        # supports it; per-slot fold otherwise)
        ena_l = taken & (pur_l == P_APP)
        if hasattr(self.app, "on_lookup_done_batch"):
            st = dataclasses.replace(st, app=self.app.on_lookup_done_batch(
                st.app, app_base.LookupDone(
                    en=ena_l, success=ena_l & suc_l, tag=comp["aux"],
                    target=comp["target"], results=comp["results"],
                    hops=comp["hops"], t0=comp["t0"]),
                ctx, ob, ev, t0, node_idx))
        else:
            for li in range(lcfg.slots):
                st = dataclasses.replace(st, app=self.app.on_lookup_done(
                    st.app, app_base.LookupDone(
                        en=ena_l[li], success=ena_l[li] & suc_l[li],
                        tag=comp["aux"][li], target=comp["target"][li],
                        results=comp["results"][li], hops=comp["hops"][li],
                        t0=comp["t0"][li]),
                    ctx, ob, ev, t0, node_idx))

        # ------------------------------------------- bucket refresh pump ---
        # target/seed were computed in the batched findNode above; gate on
        # the POST-completion dirty bit so a bucket whose refresh just
        # finished is not immediately re-queried
        dirty_now = st.refresh_dirty[jnp.minimum(bi_ref, p.num_buckets - 1)]
        dirty_any = (st.state == READY) & dirty_now
        no_ref_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_REFRESH))
        slot, have = lk_mod.free_slot(st.lk)
        start_ref = dirty_any & no_ref_lk & have & (seed_r[0] != NO_NODE)
        # no candidates at all → just clear the bit
        clear_only = dirty_any & no_ref_lk & (seed_r[0] == NO_NODE)
        st = dataclasses.replace(
            st,
            refresh_dirty=jnp.where(clear_only,
                                    st.refresh_dirty.at[bi_ref].set(False),
                                    st.refresh_dirty),
            lk=lk_mod.start(st.lk, start_ref, slot, P_REFRESH, bi_ref,
                            target_ref, seed_r[:lcfg.frontier], t0, lcfg))

        # ------------------------------------------------------- pump ------
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[6], lcfg,
                                num_redundant=p.redundant_nodes)
        st = dataclasses.replace(st, lk=new_lk)

        # ------------------------------------------------------ events -----
        events = {
            "c:kad_joins": joins_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events
