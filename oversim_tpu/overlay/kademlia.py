"""Kademlia XOR-metric DHT as vectorized per-node logic.

TPU-native rebuild of the reference Kademlia
(src/overlay/kademlia/Kademlia.{h,cc} + KademliaBucket/KademliaBucketEntry),
default configuration (simulations/default.ini:185-200: k=8, s=8, b=1,
maxStaleCount=0, lookupMerge=true, iterative routing, exhaustiveRefresh,
minSibling/BucketRefreshInterval=1000s).  State is structure-of-arrays:

  * sibling table [N, S]: the s XOR-closest known nodes, kept sorted by
    distance from own key (KademliaBucket extends BaseKeySortedVector);
  * k-buckets [N, B, K] with last-seen [N, B, K] and stale counters:
    bucket index = sharedPrefixLength(own, other) clipped to B-1
    (reference routingBucketIndex Kademlia.cc:357 = first non-zero digit
    of the XOR delta — identical partition for b=1; distant-prefix
    buckets beyond B-1 collapse onto the last row, which only matters
    for astronomically-close non-sibling keys);
  * routingAdd (Kademlia.cc:432): every message source is added alive
    with the full policy (sibling merge incl. displacement of the
    furthest sibling into a bucket; in-bucket lastSeen refresh; free-slot
    insert; stale-entry replacement).  Nodes learned from
    FindNodeResponse payloads are added unverified (isAlive=false,
    Kademlia.cc:1412): they merge into the sibling table and fill FREE
    bucket slots only — no displacement;
  * replacement cache (enableReplacementCache/replacementCandidates,
    Kademlia.h:86-89): alive candidates rejected by a full bucket enter
    a per-bucket candidate ring; evictions promote from it
    (_promote_from_cache); replacementCachePing probes the
    least-recently-seen entry of a cache-fed bucket;
  * bucket pings (bucketPingInterval): periodic liveness probe of the
    oldest-seen routing-table entry, via a bounded per-node ping table
    (KAD_PING kinds);
  * downlists (enableDownlists, Kademlia.cc:1543-1585): when a lookup's
    RPC target finally times out, the responder that reported it gets a
    KAD_DOWNLIST naming the dead node and pings it before evicting
    (downlist forwarding to siblings is not modeled);
  * S/Kademlia secure lookups via LookupConfig(verify_siblings=True)
    (common/lookup.py: candidate siblings are ping-verified before a
    lookup completes, IterativeLookup.cc:295-340);
  * R/Kademlia recursive routing via rcfg (common/route.py:
    recursiveRoutingHook equivalent — per-hop forwarding over k-bucket
    findNode with ACK/reroute; Kademlia.cc:1022, Heep ATNAC 2010);
  * isSiblingFor (Kademlia.cc:888): table smaller than numSiblings →
    true; key farther than the furthest sibling while full → false;
    otherwise membership of self in the numSiblings closest of
    siblings ∪ self;
  * findNode (Kademlia.cc:1101): top-R by XOR distance over
    self ∪ siblings ∪ all buckets (the reference walks best bucket →
    surrounding buckets → siblings; same result set);
  * join (Kademlia.cc:1027-1081): iterative lookup of the own key seeded
    from the bootstrap node, then bucket refresh;
  * periodic refresh: sibling-table refresh = lookup own key; bucket
    refresh = lookup a random key with the bucket's exact shared-prefix
    length, for buckets unused for minBucketRefreshInterval
    (handleBucketRefreshTimerExpired Kademlia.cc:1591) — repaired one
    lookup at a time off a dirty mask (bounded concurrency);
  * handleFailedNode (Kademlia.cc:979): drop from siblings; stale+1 in
    buckets, evict when staleCount > maxStaleCount, promote a
    replacement-cache candidate into the freed slot.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from oversim_tpu import stats as stats_mod
from oversim_tpu.apps import base as app_base
from oversim_tpu.apps.kbrtest import KbrTestApp
from oversim_tpu.common import lookup as lk_mod
from oversim_tpu.common import malicious as mal_mod
from oversim_tpu.common import neighborcache as nc_mod
from oversim_tpu.common import route as rt_mod
from oversim_tpu.common import wire
from oversim_tpu.core import keys as K
from oversim_tpu.engine.logic import Outbox, select_tree

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32
NS = 1_000_000_000
T_INF = jnp.int64(2**62)
NO_NODE = jnp.int32(-1)
UMAX = jnp.uint32(0xFFFFFFFF)

DEAD, JOINING, READY = 0, 1, 2

# lookup purposes
P_JOIN, P_REFRESH, P_APP, P_SIB = 1, 2, 3, 4


@dataclasses.dataclass(frozen=True)
class KademliaParams:
    """default.ini:185-200 + Kademlia.ned defaults."""

    k: int = 8                    # bucket size
    s: int = 8                    # sibling table size
    num_buckets: int = 32         # B — prefix-length clip (see module doc)
    max_stale: int = 0            # maxStaleCount
    join_delay: float = 10.0      # joinDelay (BaseOverlay)
    sibling_refresh: float = 1000.0   # minSiblingTableRefreshInterval
    bucket_refresh: float = 1000.0    # minBucketRefreshInterval
    redundant_nodes: int = 8      # lookupRedundantNodes
    rpc_timeout: float = 1.5
    # --- routingAdd depth knobs (Kademlia.h:86-107) ---
    replacement_cands: int = 0    # replacementCandidates per bucket
                                  # (0 = enableReplacementCache off)
    replacement_cache_ping: bool = False  # replacementCachePing: ping the
                                  # least-recently-seen bucket entry when a
                                  # candidate enters the cache
    bucket_ping_interval: float = 0.0  # bucketPingInterval (0 = off):
                                  # periodic ping of the oldest-seen
                                  # routing-table entry (NICE-style pings)
    enable_downlists: bool = False  # enableDownlists (Kademlia.cc:1567):
                                  # tell responders about dead nodes they
                                  # returned; receiver pings before evicting
    ping_slots: int = 4           # bounded concurrent maintenance pings
    adaptive_timeouts: bool = False  # optimizeTimeouts (BaseRpc.cc:197-
                                  # 205): RPC timeouts from the
                                  # NeighborCache RTT estimator
                                  # (getNodeTimeout, NeighborCache.cc:802)
                                  # fed by FindNode response RTTs


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KademliaState:
    state: jnp.ndarray      # [N] i32
    sib: jnp.ndarray        # [N, S] i32 sorted by xor distance from own key
    buckets: jnp.ndarray    # [N, B, K] i32
    b_seen: jnp.ndarray     # [N, B, K] i64 — lastSeen (0 = unverified)
    b_stale: jnp.ndarray    # [N, B, K] i32
    b_used: jnp.ndarray     # [N, B] i64 — bucket lastUsage
    refresh_dirty: jnp.ndarray  # [N, B] bool
    t_join: jnp.ndarray     # [N] i64
    t_refresh: jnp.ndarray  # [N] i64 — periodic bucket/sibling refresh tick
    sib_used: jnp.ndarray   # [N] i64 — sibling table lastUsage
    rc_nodes: jnp.ndarray   # [N, B, RC] i32 — replacement cache ring
    rc_pos: jnp.ndarray     # [N, B] i32 — its write cursor
    ping_dst: jnp.ndarray   # [N, Pp] i32 — in-flight maintenance pings
    ping_to: jnp.ndarray    # [N, Pp] i64 — their timeouts
    t_bping: jnp.ndarray    # [N] i64 — periodic bucket-ping timer
    rr: object              # rt_mod.RouteState — R/Kademlia recursive hook
    nc: object              # nc_mod.NcState — RTT cache (adaptive timeouts)
    lk: lk_mod.LookupState
    app: object                # [N, ...] tier-app state (apps/base.py)
    app_glob: object           # simulation-global app state (oracle maps)


class KademliaLogic:
    """Engine logic interface (see engine/logic.py docstring)."""

    def __init__(self, spec: K.KeySpec = K.DEFAULT_SPEC,
                 params: KademliaParams = KademliaParams(),
                 lcfg: lk_mod.LookupConfig | None = None,
                 app=None,
                 mparams: mal_mod.MaliciousParams = mal_mod.MaliciousParams(),
                 rcfg: rt_mod.RouteConfig | None = None):
        """``rcfg`` switches the app data path to R/Kademlia recursive
        routing (Kademlia::recursiveRoutingHook, Kademlia.cc:1022;
        B. Heep, R/Kademlia, ATNAC 2010) — per-hop forwarding over the
        same k-bucket findNode, with the route engine's ACK/reroute
        machinery; mode full/source selects the reply transport."""
        self.key_spec = spec
        self.p = params
        self.lcfg = lcfg or lk_mod.LookupConfig(merge=True)
        self.app = app or KbrTestApp()
        self.mp = mparams
        self.rcfg = rcfg
        # the app's RPC replies follow the call's routing mode
        if rcfg is not None and getattr(self.app, "rcfg", "no") is None:
            self.app.rcfg = rcfg
        self._pow2 = K.pow2_table(spec)

    # -- engine interface ---------------------------------------------------

    def split(self, st: KademliaState):
        return dataclasses.replace(st, app_glob=None), st.app_glob

    def merge(self, node_part: KademliaState, glob):
        return dataclasses.replace(node_part, app_glob=glob)

    def post_step(self, ctx, st: KademliaState, events):
        app, glob = self.app.post_step(ctx, st.app, st.app_glob, events)
        return dataclasses.replace(st, app=app, app_glob=glob)

    def stat_spec(self) -> stats_mod.StatSpec:
        app = self.app.stat_spec()
        return stats_mod.StatSpec(
            scalars=tuple(app["scalars"]) + ("lookup_hops",),
            hists=tuple(app["hists"]),
            counters=tuple(app["counters"]) + (
                "kad_joins", "lookup_success", "lookup_failed",
                "route_dropped"),
        )

    def init(self, rng, n: int) -> KademliaState:
        p = self.p
        return KademliaState(
            state=jnp.zeros((n,), I32),
            sib=jnp.full((n, p.s), NO_NODE, I32),
            buckets=jnp.full((n, p.num_buckets, p.k), NO_NODE, I32),
            b_seen=jnp.zeros((n, p.num_buckets, p.k), I64),
            b_stale=jnp.zeros((n, p.num_buckets, p.k), I32),
            b_used=jnp.zeros((n, p.num_buckets), I64),
            refresh_dirty=jnp.zeros((n, p.num_buckets), bool),
            t_join=jnp.full((n,), T_INF, I64),
            t_refresh=jnp.full((n,), T_INF, I64),
            sib_used=jnp.zeros((n,), I64),
            rc_nodes=jnp.full((n, p.num_buckets, p.replacement_cands),
                              NO_NODE, I32),
            rc_pos=jnp.zeros((n, p.num_buckets), I32),
            ping_dst=jnp.full((n, p.ping_slots), NO_NODE, I32),
            ping_to=jnp.full((n, p.ping_slots), T_INF, I64),
            t_bping=jnp.full((n,), T_INF, I64),
            rr=jax.vmap(lambda _: rt_mod.init(
                self.rcfg or rt_mod.RouteConfig(), self.key_spec.lanes,
                16))(jnp.arange(n)),
            nc=nc_mod.init(n, nc_mod.NcParams(
                capacity=16 if p.adaptive_timeouts else 1)),
            lk=jax.vmap(lambda _: lk_mod.init(self.lcfg, self.key_spec.lanes))(
                jnp.arange(n)),
            app=self.app.init(n),
            app_glob=self.app.glob_init(rng),
        )

    def reset(self, st: KademliaState, clear, join, t_now, rng):
        n = st.state.shape[0]
        glob = st.app_glob
        st = dataclasses.replace(st, app_glob=None)
        fresh = dataclasses.replace(self.init(rng, n), app_glob=None)
        st = select_tree(clear, fresh, st)
        st = dataclasses.replace(st, app_glob=glob)
        jitter = (jax.random.uniform(rng, (n,)) * 0.1 * NS).astype(I64)
        return dataclasses.replace(
            st,
            state=jnp.where(join, JOINING, st.state),
            t_join=jnp.where(join, t_now + jitter, st.t_join))

    def ready_mask(self, st: KademliaState):
        return st.state == READY

    def next_event(self, st: KademliaState):
        joining = st.state == JOINING
        ready = st.state == READY
        t = jnp.where(joining, st.t_join, T_INF)
        t = jnp.minimum(t, jnp.where(ready, st.t_refresh, T_INF))
        t = jnp.minimum(t, jnp.where(ready, self.app.next_event(st.app),
                                     T_INF))
        t = jnp.minimum(t, jax.vmap(lk_mod.next_event)(st.lk))
        t = jnp.minimum(t, jnp.min(st.ping_to, axis=1))
        if self.p.bucket_ping_interval > 0:
            t = jnp.minimum(t, jnp.where(ready, st.t_bping, T_INF))
        if self.rcfg is not None:
            t = jnp.minimum(t, jax.vmap(rt_mod.next_event)(st.rr))
        return t

    # -- key-space helpers (single node slice) -------------------------------

    def _xor_to(self, ctx, slots, key):
        """[C] slots → [C, KL] xor distance of slot keys to ``key``
        (NO_NODE → max distance)."""
        ck = ctx.keys[jnp.maximum(slots, 0)]
        d = ck ^ jnp.broadcast_to(key, ck.shape)
        return jnp.where((slots == NO_NODE)[:, None], UMAX, d)

    def _bucket_index(self, me_key, other_key):
        """Shared-prefix bucket index, clipped to B-1."""
        pl = K.shared_prefix_length(me_key, other_key, self.key_spec)
        return jnp.clip(pl, 0, self.p.num_buckets - 1)

    def _sib_merge(self, ctx, me_key, node_idx, sib, cands, cand_ok):
        """Merge candidate slots into the sibling table.

        Returns (new_sib [S], displaced [S] i32): every node pushed out of
        a previously-full table (NO_NODE padded) — reference routingAdd
        moves verified ex-siblings into their buckets (Kademlia.cc:613
        area); a batch merge can displace several at once.
        """
        s = self.p.s
        c = jnp.concatenate([sib, jnp.where(cand_ok, cands, NO_NODE)])
        # dedupe (keep first occurrence — table entries win over candidates)
        bad = (c == NO_NODE) | (c == node_idx) | K.dup_mask(c)
        c = jnp.where(bad, NO_NODE, c)
        d = self._xor_to(ctx, c, me_key)
        (c_s,) = K.sort_by_distance(d, (c,), approx=True)[1]
        new_sib = c_s[:s]
        # displaced: previously a sibling, no longer one
        was = sib != NO_NODE
        still = jnp.any(sib[:, None] == new_sib[None, :], axis=1)
        disp = jnp.where(was & ~still, sib, NO_NODE)
        return new_sib, disp

    def _bucket_update_batch(self, ctx, st, me_key, cands, alive, now):
        """One-pass batched bucket update — the bucket half of routingAdd
        (Kademlia.cc:432-700) for ALL of a tick's C candidates at once.

        Per-candidate policy, equal to the sequential reference up to
        within-tick ordering: present+alive → lastSeen refresh / stale
        reset; absent → take a free slot, or (alive candidates only)
        evict a stale entry (staleCount > maxStaleCount, highest count
        first); unverified candidates (alive=False, nodes learned from
        FindNodeResponse payloads, Kademlia.cc:1412) fill free slots only
        and never displace.  Alive candidates get slot priority.  The
        whole policy is one candidate sort + one column sort + three
        scatters instead of C unrolled scatter chains (the round-2 tick
        graph was dominated by exactly those chains).

        ``cands`` must be deduplicated by the caller; NO_NODE = disabled.
        """
        p = self.p
        num_b, kk = p.num_buckets, p.k
        c_dim = cands.shape[0]
        en = cands != NO_NODE
        ck = ctx.keys[jnp.maximum(cands, 0)]
        bi = jnp.where(en, self._bucket_index(me_key, ck), num_b)

        # --- presence refresh (alive contacts only) ---
        acand = jnp.where(en & alive, cands, NO_NODE)
        hit = jnp.any(
            st.buckets[:, :, None] == acand[None, None, :], axis=-1) & (
            st.buckets != NO_NODE)
        b_seen = jnp.where(hit, now, st.b_seen)
        b_stale = jnp.where(hit, 0, st.b_stale)
        buckets = st.buckets

        # --- slot assignment for absent candidates ---
        row_c = buckets[jnp.minimum(bi, num_b - 1)]           # [C, K]
        present = jnp.any(row_c == cands[:, None], axis=1)
        need = en & ~present
        # candidates ordered by (bucket, alive-first, arrival order)
        k1 = jnp.where(need, bi, num_b).astype(I32)
        k2 = (~alive).astype(I32)
        k3 = jnp.arange(c_dim, dtype=I32)
        b_s, a_s, idx_s = jax.lax.sort((k1, k2, k3), num_keys=3)  # analysis: allow(sort-call)
        rank = k3 - jnp.searchsorted(b_s, b_s, side="left").astype(I32)
        # per-bucket column order: free columns first, then evictable by
        # stale count descending, then untouchable
        free = buckets == NO_NODE
        evictable = ~free & (b_stale > p.max_stale)
        cls = jnp.where(free, 0, jnp.where(evictable, 1, 2))
        colkey = cls * (1 << 20) - jnp.where(
            evictable, jnp.minimum(b_stale, (1 << 19) - 1), 0)
        order = jnp.argsort(colkey, axis=1).astype(I32)       # [B, K]  # analysis: allow(sort-call)
        free_cnt = jnp.sum(free, axis=1, dtype=I32)           # [B]
        avail_cnt = free_cnt + jnp.sum(evictable, axis=1, dtype=I32)

        bi_c = jnp.minimum(b_s, num_b - 1)
        limit = jnp.where(a_s == 0, avail_cnt[bi_c], free_cnt[bi_c])
        okc = (b_s < num_b) & (rank < limit) & (rank < kk)
        col = order[bi_c, jnp.clip(rank, 0, kk - 1)]
        rows = jnp.where(okc, bi_c, num_b)
        vals = cands[idx_s]
        al_v = a_s == 0
        st = dataclasses.replace(
            st,
            buckets=buckets.at[rows, col].set(vals, mode="drop"),
            b_seen=b_seen.at[rows, col].set(
                jnp.where(al_v, now, jnp.int64(0)), mode="drop"),
            b_stale=b_stale.at[rows, col].set(0, mode="drop"))

        # --- replacement cache (enableReplacementCache, Kademlia.cc:
        # routingAdd full-bucket branch): alive candidates that found no
        # slot enter the bucket's bounded candidate ring; a later
        # eviction promotes one (see _handle_failed).  Ring overwrite
        # replaces the reference's LRU-bounded cache list.
        rc = p.replacement_cands
        if rc:
            rej = (b_s < num_b) & ~okc & al_v
            rej_rank = rank - limit
            pos = (st.rc_pos[bi_c] + jnp.maximum(rej_rank, 0)) % rc
            rrows = jnp.where(rej, bi_c, num_b)
            new_rc = st.rc_nodes.at[rrows, pos].set(vals, mode="drop")
            rej_per_b = jnp.zeros((num_b,), I32).at[rrows].add(
                1, mode="drop")
            st = dataclasses.replace(
                st, rc_nodes=new_rc,
                rc_pos=(st.rc_pos + rej_per_b) % rc)
            # replacementCachePing: give the least-recently-seen entry
            # of each cache-fed bucket a liveness check so stale entries
            # make room (one ping candidate per tick, bounded ping slots)
            if p.replacement_cache_ping:
                fed = jnp.zeros((num_b,), bool).at[rrows].set(
                    True, mode="drop")
                seen_k = jnp.where(
                    (st.buckets != NO_NODE) & fed[:, None],
                    st.b_seen, T_INF)
                flat_i = jnp.argmin(seen_k.reshape(-1))
                cand_p = st.buckets.reshape(-1)[flat_i]
                rc_ping = jnp.where(
                    jnp.any(fed) & (cand_p != NO_NODE), cand_p, NO_NODE)
                return st, rc_ping
        return st, NO_NODE

    def _routing_add_batch(self, ctx, st, me_key, node_idx, cands, alive,
                           now):
        """Batched routingAdd (Kademlia.cc:432) for a tick's whole
        candidate set: one sibling-table merge sort + one batched bucket
        pass.  ``alive`` marks verified contacts (message sources); false
        = unverified learned nodes.  An alive occurrence of a node wins
        over an unverified duplicate."""
        en = (cands != NO_NODE) & (cands != node_idx)
        cands = jnp.where(en, cands, NO_NODE)
        eq = cands[None, :] == cands[:, None]
        alive = jnp.any(eq & (alive & en)[None, :], axis=1) & en
        dup = K.dup_mask(cands)
        en = en & ~dup
        cands = jnp.where(en, cands, NO_NODE)

        new_sib, disp_vec = self._sib_merge(ctx, me_key, node_idx, st.sib,
                                            cands, en)
        st = dataclasses.replace(st, sib=new_sib)
        became_sib = jnp.any(cands[:, None] == new_sib[None, :], axis=1) & en
        # bucket candidates: displaced ex-siblings re-file as verified
        # contacts (reference routingAdd moves them into their buckets,
        # Kademlia.cc:613 area); non-sibling candidates keep their flag.
        # A displaced node that is ALSO a same-tick candidate must enter
        # the bucket once (the bucket pass requires caller-side dedup):
        # drop the disp_vec copy and promote the candidate to verified.
        in_disp = jnp.any(cands[:, None] == disp_vec[None, :], axis=1) & en
        disp_vec = jnp.where(
            jnp.any(disp_vec[:, None] == jnp.where(en, cands, NO_NODE)[None, :],
                    axis=1), NO_NODE, disp_vec)
        bc = jnp.concatenate([disp_vec,
                              jnp.where(became_sib, NO_NODE, cands)])
        ba = jnp.concatenate([jnp.ones(disp_vec.shape, bool),
                              alive | in_disp])
        st, rc_ping = self._bucket_update_batch(ctx, st, me_key, bc, ba,
                                                now)
        return st, rc_ping

    def _promote_from_cache(self, st, evict):
        """Replacement-cache promotion: for each bucket that just lost an
        entry, move one cached candidate into the freed slot (reference
        routingTimeout pulls from the replacement cache).  ``evict``
        [B, K] marks the slots freed this pass."""
        rc = self.p.replacement_cands
        if not rc:
            return st
        have_rc = st.rc_nodes != NO_NODE                       # [B, RC]
        can = jnp.any(evict, axis=1) & jnp.any(have_rc, axis=1)  # [B]
        col_rc = jnp.argmax(have_rc, axis=1)                   # [B]
        col_k = jnp.argmax(evict, axis=1)                      # [B]
        num_b = evict.shape[0]
        promoted = st.rc_nodes[jnp.arange(num_b), col_rc]
        # the ring is not deduplicated: a cached node may have re-entered
        # its bucket (or hold a second ring copy) since it was cached —
        # promotion of an already-present node would break the one-slot-
        # per-node bucket invariant, so such copies are only purged here
        already = jnp.any(st.buckets == promoted[:, None], axis=1)
        rows_any = jnp.where(can, jnp.arange(num_b, dtype=I32), num_b)
        rows = jnp.where(can & ~already,
                         jnp.arange(num_b, dtype=I32), num_b)
        return dataclasses.replace(
            st,
            buckets=st.buckets.at[rows, col_k].set(promoted, mode="drop"),
            b_seen=st.b_seen.at[rows, col_k].set(0, mode="drop"),
            b_stale=st.b_stale.at[rows, col_k].set(0, mode="drop"),
            rc_nodes=st.rc_nodes.at[rows_any, col_rc].set(NO_NODE,
                                                          mode="drop"))

    def _find_node(self, ctx, st, me_key, node_idx, key, rmax):
        """Top-R closest known nodes by XOR distance (Kademlia.cc:1101).

        Returns ([rmax] i32 slots NO_NODE-padded, is_sibling bool)."""
        out, is_sib = self._find_node_batch(ctx, st, me_key, node_idx,
                                            key[None], rmax)
        return out[0], is_sib[0]

    def _find_node_batch(self, ctx, st, me_key, node_idx, keys, rmax):
        """Batched findNode for T target keys at once ([T, KL] → ([T, rmax]
        slots, [T] is_sibling)) — ONE sort over the shared candidate set
        per tick instead of one per unrolled call site.

        findNode: top-R by XOR distance over self ∪ siblings ∪ all buckets
        (Kademlia.cc:1101 walks best bucket → surrounding buckets →
        siblings; same result set).  isSiblingFor: Kademlia.cc:888."""
        p = self.p
        t_dim = keys.shape[0]
        # mask bucket entries that were since promoted into the sibling
        # table (routingAdd can adopt a bucket resident without purging
        # its bucket slot) so the result set never repeats a node
        flat = st.buckets.reshape(-1)
        in_sib = jnp.any(flat[:, None] == st.sib[None, :], axis=1)
        flat = jnp.where(in_sib, NO_NODE, flat)
        cands = jnp.concatenate([node_idx[None], st.sib, flat])    # [C]
        ck = ctx.keys[jnp.maximum(cands, 0)]                       # [C, KL]
        d = ck[None, :, :] ^ keys[:, None, :]                      # [T, C, KL]
        d = jnp.where((cands == NO_NODE)[None, :, None], UMAX, d)
        (c_s,) = K.sort_by_distance(
            d, (jnp.broadcast_to(cands, (t_dim, cands.shape[0])),),
            approx=True)[1]
        ready = st.state == READY
        out = jnp.where(ready, c_s[:, :rmax], NO_NODE)
        r = p.redundant_nodes
        if r < rmax:
            out = out.at[:, r:].set(NO_NODE)

        # isSiblingFor(self, key, numSiblings=1) (Kademlia.cc:888)
        n_sib = jnp.sum((st.sib != NO_NODE).astype(I32))
        full = n_sib >= p.s
        d_me = me_key[None, :] ^ keys                              # [T, KL]
        d_far = self._xor_to(ctx, st.sib[-1:], me_key)             # [1, KL]
        not_ours = full & K.gt(d_me, jnp.broadcast_to(d_far, d_me.shape))
        sk = ctx.keys[jnp.maximum(st.sib, 0)]                      # [S, KL]
        d_sib_key = sk[None, :, :] ^ keys[:, None, :]              # [T, S, KL]
        d_sib_key = jnp.where((st.sib == NO_NODE)[None, :, None], UMAX,
                              d_sib_key)
        closer_sib = jnp.any(
            K.lt(d_sib_key, jnp.broadcast_to(d_me[:, None, :],
                                             d_sib_key.shape)), axis=1)
        is_sib = ready & (n_sib < 1) | (ready & ~not_ours & ~closer_sib)
        return out, is_sib

    def _handle_failed(self, ctx, st, me_key, node_idx, failed):
        """handleFailedNode (Kademlia.cc:979): drop sibling / stale+evict.

        ``failed`` may be a scalar or a [K] batch — the whole tick's
        failure list is folded in one sort + one bucket sweep (each
        occurrence of a node in the batch counts one stale strike, like
        the reference's one call per RPC timeout)."""
        failed = jnp.atleast_1d(jnp.asarray(failed, I32))
        en = jnp.any(failed != NO_NODE)
        # sibling drop + re-sort
        hit = jnp.any(st.sib[:, None] == failed[None, :], axis=-1) & (
            st.sib != NO_NODE)
        sib_masked = jnp.where(hit, NO_NODE, st.sib)
        d = self._xor_to(ctx, sib_masked, me_key)
        (sib_s,) = K.sort_by_distance(d, (sib_masked,), approx=True)[1]
        st = dataclasses.replace(
            st, sib=jnp.where(en, sib_s, st.sib))
        # bucket stale increment (one strike per batch occurrence)
        strikes = jnp.sum(
            st.buckets[..., None] == failed[None, None, :], axis=-1,
            dtype=I32)
        strikes = jnp.where(st.buckets != NO_NODE, strikes, 0)
        stale = st.b_stale + strikes
        evict = (strikes > 0) & (stale > self.p.max_stale)
        st = dataclasses.replace(
            st,
            buckets=jnp.where(evict, NO_NODE, st.buckets),
            b_stale=jnp.where(evict, 0, stale),
            b_seen=jnp.where(evict, 0, st.b_seen))
        return self._promote_from_cache(st, evict)

    def _become_ready(self, ctx, st, en, now, rng):
        p = self.p
        t_bping = st.t_bping
        if p.bucket_ping_interval > 0:
            t_bping = jnp.where(
                en, now + jnp.int64(int(p.bucket_ping_interval * NS)),
                t_bping)
        return dataclasses.replace(
            st,
            state=jnp.where(en, READY, st.state),
            t_join=jnp.where(en, T_INF, st.t_join),
            # immediate bucket refresh pass after join (Kademlia.cc:1043)
            t_refresh=jnp.where(en, now, st.t_refresh),
            # ...and an immediate sibling-table refresh (own-key lookup)
            # so a partially seeded table converges to the true closest
            # set right away instead of after minSiblingTableRefresh
            sib_used=jnp.where(
                en, now - jnp.int64(int(p.sibling_refresh * NS)) - 1,
                st.sib_used),
            t_bping=t_bping,
            app=self.app.on_ready(st.app, en, now, rng))

    # -- the per-node step ---------------------------------------------------

    def step(self, ctx, st, msgs, rng, node_idx, *, outbox_slots, rmax):
        p, lcfg, spec = self.p, self.lcfg, self.key_spec
        ob = Outbox(outbox_slots, spec.lanes, rmax)
        me_key = ctx.keys[node_idx]
        rngs = jax.random.split(rng, 8)
        t0 = ctx.t_start
        t_end = ctx.t_end
        r_in = msgs.valid.shape[0]

        def metric_fn(cand_slots, target):
            return self._xor_to(ctx, cand_slots, target)

        ev = app_base.AppEvents()
        joins_cnt = jnp.int32(0)
        anyfail_cnt = jnp.int32(0)
        lksucc_cnt = jnp.int32(0)
        old_sib = st.sib                     # update() delta base

        # --------------------------------------------- inbox (batched) -----
        # All R inbox slots are consumed in ONE pass per handler class —
        # a masked [R]-batch instead of R unrolled handler chains (the
        # round-2 graph was op-issue-bound on exactly that unrolling).
        # Within-window ordering across the slots is already relaxed by
        # the engine; the batch passes commute the same way.
        v_r = msgs.valid
        t_del_r = msgs.t_deliver

        # FindNodeResponses → lookup engine (one batched pass)
        en_res = v_r & (msgs.kind == wire.FINDNODE_RES)
        if p.adaptive_timeouts:
            # RTT samples from this tick's responses feed the
            # NeighborCache estimator BEFORE the pendings are cleared
            # (NeighborCache::updateNode on every RPC response)
            rtt_src, rtt_s, rtt_ok = lk_mod.response_rtts(
                st.lk, dataclasses.replace(msgs, valid=en_res))
            st = dataclasses.replace(st, nc=nc_mod.feed_response_rtts(
                st.nc, rtt_src, rtt_s, t_del_r, rtt_ok))
        st = dataclasses.replace(st, lk=lk_mod.on_responses(
            st.lk, dataclasses.replace(msgs, valid=en_res), metric_fn, lcfg))

        # batched routingAdd (Kademlia.cc:1027/1419): every message source
        # as a verified contact + every FindNodeResponse payload node as
        # an unverified learn (Kademlia.cc:1412)
        learned = jnp.where(en_res[:, None], msgs.nodes[:, :lcfg.frontier],
                            NO_NODE)                            # [R, F]
        add_cands = jnp.concatenate(
            [jnp.where(v_r, msgs.src, NO_NODE), learned.reshape(-1)])
        add_alive = jnp.concatenate(
            [jnp.ones((r_in,), bool),
             jnp.zeros((learned.size,), bool)])
        now_add = jnp.max(jnp.where(v_r, t_del_r, 0))
        st, rc_ping = self._routing_add_batch(ctx, st, me_key, node_idx,
                                              add_cands, add_alive, now_add)

        # batched findNode + sibling flags for every inbox key: consumed
        # by the FindNodeCall responder below AND (R/Kademlia) by the
        # recursive route pre-pass as its forwarding candidates
        res_b, sib_b = self._find_node_batch(ctx, st, me_key, node_idx,
                                             msgs.key, rmax)

        if self.rcfg is not None:
            # R/Kademlia recursive hook (Kademlia::recursiveRoutingHook,
            # Kademlia.cc:1022; generic loop BaseOverlay.cc:1441-1581):
            # ACK the previous hop, forward or decapsulate — the same
            # pre-pass chord.py runs, driven by k-bucket findNode results
            rcfg = self.rcfg
            st = dataclasses.replace(st, rr=rt_mod.on_acks(
                st.rr, dataclasses.replace(
                    msgs,
                    valid=v_r & (msgs.kind == wire.KBR_ROUTE_ACK))))
            en_sro = v_r & (msgs.kind == wire.KBR_SROUTE)
            deliver_sr = rt_mod.sroute_step(ob, msgs)
            msgs = dataclasses.replace(
                msgs,
                kind=jnp.where(deliver_sr, msgs.d, msgs.kind),
                src=jnp.where(deliver_sr, msgs.c, msgs.src),
                valid=v_r & (~en_sro | deliver_sr))
            v_r = msgs.valid
            en_rt = v_r & (msgs.kind == wire.KBR_ROUTE) & (
                st.state == READY)
            ob.send(en_rt & (msgs.nonce > 0), t_del_r, msgs.src,
                    wire.KBR_ROUTE_ACK, nonce=msgs.nonce,
                    size_b=wire.BASE_CALL_B)
            deliver_rt = en_rt & sib_b
            nxt_v, found_v = jax.vmap(
                rt_mod.pick_next_hop, in_axes=(0, 0, 0, 0, None, 0))(
                res_b, msgs.nodes, msgs.src, msgs.nodes[:, 0], node_idx,
                sib_b)
            fwd = en_rt & ~sib_b & found_v & (msgs.hops < rcfg.hop_max)
            if hasattr(self.app, "forward"):
                # Common API forward() veto (BaseApp.h:214)
                fwd = fwd & ~self.app.forward(st.app, msgs, ctx)
            visited2 = rt_mod.append_visited(msgs.nodes, node_idx, fwd)
            st = dataclasses.replace(st, rr=rt_mod.forward_batch(
                st.rr, ob, fwd, t_del_r, nxt_v, key=msgs.key, inner=msgs.d,
                a=msgs.a, b=msgs.b, c=msgs.c, hops=msgs.hops + 1,
                stamp=msgs.stamp, size_b=msgs.size_b - rcfg.overhead_b,
                visited=visited2, cfg=rcfg))
            routedrop_cnt = jnp.sum((en_rt & ~sib_b & ~fwd).astype(I32))
            msgs = dataclasses.replace(
                msgs,
                kind=jnp.where(deliver_rt, msgs.d, msgs.kind),
                src=jnp.where(deliver_rt, msgs.nodes[:, 0], msgs.src),
                valid=v_r & (~en_rt | deliver_rt))
            v_r = msgs.valid
        else:
            routedrop_cnt = jnp.int32(0)

        # FindNodeCalls → responder
        en_call = v_r & (msgs.kind == wire.FINDNODE_CALL)
        # byzantine switches (common/malicious.py; statically no-op by
        # default).  Only the wire copy is attacked; the honest ``sib_b``
        # feeds the app deliver check below (wrong-node detection)
        if self.mp.active:
            res_atk, sib_atk, respond = jax.vmap(
                lambda rr, ss, rg: mal_mod.attack_findnode(
                    ctx, self.mp, node_idx, rr, ss, rg))(
                res_b, sib_b, jax.random.split(rngs[7], r_in))
        else:
            res_atk, sib_atk, respond = res_b, sib_b, jnp.ones((r_in,), bool)
        ob.send(en_call & respond, t_del_r, msgs.src, wire.FINDNODE_RES,
                key=msgs.key, a=msgs.a, b=msgs.b, c=sib_atk.astype(I32),
                nodes=res_atk,
                size_b=wire.findnode_res_b(p.redundant_nodes))

        # ping (generic liveness; b echoes the caller's generation so
        # verification pongs can be stale-guarded, lookup.on_pongs)
        ob.send(v_r & (msgs.kind == wire.PING_CALL), t_del_r, msgs.src,
                wire.PING_RES, a=msgs.a, b=msgs.b, size_b=wire.BASE_CALL_B)

        # S/Kademlia sibling-verification pongs (lookup engine pings its
        # staged candidate, IterativeLookup.cc:295-340)
        if lcfg.verify_siblings:
            st = dataclasses.replace(st, lk=lk_mod.on_pongs(
                st.lk, dataclasses.replace(
                    msgs, valid=v_r & (msgs.kind == wire.PING_RES)), lcfg))

        # maintenance pings (bucket pings / replacement-cache pings /
        # downlist verification, Kademlia.h bucketPingInterval &
        # replacementCachePing): KAD_PING kinds keep their pongs separate
        # from the lookup engine's verification pings
        ob.send(v_r & (msgs.kind == wire.KAD_PING_CALL), t_del_r, msgs.src,
                wire.KAD_PING_RES, a=msgs.a, size_b=wire.BASE_CALL_B)
        en_kpr = v_r & (msgs.kind == wire.KAD_PING_RES)
        pong_hit = jnp.any(
            st.ping_dst[:, None] == jnp.where(en_kpr, msgs.src,
                                              NO_NODE)[None, :], axis=1)
        st = dataclasses.replace(
            st,
            ping_dst=jnp.where(pong_hit, NO_NODE, st.ping_dst),
            ping_to=jnp.where(pong_hit, T_INF, st.ping_to))

        # downlist receive (KademliaDownlistMessage, Kademlia.cc:1305-
        # 1319): ping each reported-dead node before believing it —
        # queued into the bounded ping table below
        dl_cands = jnp.where(
            v_r & (msgs.kind == wire.KAD_DOWNLIST), msgs.a, NO_NODE)

        # app-owned message kinds (Common API deliver path)
        if hasattr(self.app, "on_msgs"):
            st = dataclasses.replace(st, app=self.app.on_msgs(
                st.app, msgs, ctx, ob, ev, sib_b))
        else:
            for r in range(r_in):
                st = dataclasses.replace(st, app=self.app.on_msg(
                    st.app, msgs.slot(r), ctx, ob, ev, sib_b[r]))

        # ------------------------------------------------------- timers ----
        # join (joinOverlay: lookup own key via bootstrap,
        # Kademlia.cc:1027-1081)
        en_j = (st.state == JOINING) & (st.t_join < t_end)
        now_j = jnp.maximum(st.t_join, t0)
        boot = ctx.sample_ready(rngs[1], node_idx)
        no_join_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_JOIN))
        alone_start = en_j & (boot == NO_NODE)
        st = self._become_ready(ctx, st, alone_start, now_j, rngs[2])
        joins_cnt += alone_start.astype(I32)
        slot, have = lk_mod.free_slot(st.lk)
        start_join = en_j & (boot != NO_NODE) & no_join_lk & have
        seed = jnp.full((lcfg.frontier,), NO_NODE, I32).at[0].set(boot)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_join, slot, P_JOIN, 0, me_key, seed, now_j, lcfg))
        st = dataclasses.replace(st, t_join=jnp.where(
            en_j & ~alone_start,
            now_j + jnp.int64(int(p.join_delay * NS)), st.t_join))

        # periodic refresh tick: mark stale buckets dirty + sibling refresh
        en_r = (st.state == READY) & (st.t_refresh < t_end)
        now_r = jnp.maximum(st.t_refresh, t0)
        refresh_ns = jnp.int64(int(p.bucket_refresh * NS))
        # only buckets for prefixes we can actually populate: any bucket
        # whose index <= index of the furthest sibling (reference refreshes
        # buckets up to routingBucketIndex(siblingTable->back()),
        # Kademlia.cc:1591 area)
        far_sib = st.sib[-1]
        has_sib = far_sib != NO_NODE
        max_bi = jnp.where(
            has_sib,
            self._bucket_index(me_key, ctx.keys[jnp.maximum(far_sib, 0)]),
            -1)
        bi_range = jnp.arange(p.num_buckets, dtype=I32)
        stale_bucket = st.b_used + refresh_ns < now_r
        mark = en_r & (bi_range <= max_bi) & stale_bucket
        st = dataclasses.replace(
            st,
            refresh_dirty=st.refresh_dirty | mark,
            t_refresh=jnp.where(en_r, now_r + refresh_ns, st.t_refresh))
        # sibling-table refresh timing: lookup own key when unused for the
        # interval (start fires below, after the batched findNode)
        sib_stale = en_r & (st.sib_used + jnp.int64(
            int(p.sibling_refresh * NS)) < now_r)

        # ----------------------------------------- maintenance pings ----
        # ping timeouts: unresponsive pinged nodes are failures
        ping_exp = (st.ping_dst != NO_NODE) & (st.ping_to < t_end)
        ping_failed = jnp.where(ping_exp, st.ping_dst, NO_NODE)   # [Pp]
        st = dataclasses.replace(
            st,
            ping_dst=jnp.where(ping_exp, NO_NODE, st.ping_dst),
            ping_to=jnp.where(ping_exp, T_INF, st.ping_to))

        # bucket-ping timer (bucketPingInterval): probe the oldest-seen
        # routing-table entry so silent deaths surface between refreshes
        if p.bucket_ping_interval > 0:
            en_bp = (st.state == READY) & (st.t_bping < t_end)
            now_bp = jnp.maximum(st.t_bping, t0)
            seen_all = jnp.where(st.buckets != NO_NODE, st.b_seen, T_INF)
            flat_bp = jnp.argmin(seen_all.reshape(-1))
            bp_cand = jnp.where(en_bp, st.buckets.reshape(-1)[flat_bp],
                                NO_NODE)
            st = dataclasses.replace(st, t_bping=jnp.where(
                en_bp,
                now_bp + jnp.int64(int(p.bucket_ping_interval * NS)),
                st.t_bping))
        else:
            bp_cand = NO_NODE

        # queue this tick's ping candidates (downlist verifications, the
        # replacement-cache ping, the bucket ping) into free ping slots —
        # the same rank trick as route.forward_batch; overflow lanes drop
        # (retried next downlist/interval)
        ping_cands = jnp.concatenate(
            [dl_cands,
             jnp.stack([jnp.asarray(rc_ping, I32),
                        jnp.asarray(bp_cand, I32)])])            # [R+2]
        # skip nodes already being pinged
        dup_p = jnp.any(
            ping_cands[:, None] == st.ping_dst[None, :], axis=1)
        ping_cands = jnp.where(dup_p | K.dup_mask(ping_cands), NO_NODE,
                               ping_cands)
        en_p = ping_cands != NO_NODE
        lane_rank = jnp.cumsum(en_p.astype(I32)) - 1
        free_p = st.ping_dst == NO_NODE
        slot_rank = jnp.cumsum(free_p.astype(I32)) - 1
        n_free_p = jnp.sum(free_p.astype(I32))
        pp = p.ping_slots
        slot_of_rank = jnp.full((pp,), pp, I32).at[
            jnp.where(free_p, slot_rank, pp)].set(
            jnp.arange(pp, dtype=I32), mode="drop")
        lane_slot = jnp.where(
            en_p & (lane_rank < n_free_p),
            slot_of_rank[jnp.clip(lane_rank, 0, pp - 1)], pp)
        sent_p = lane_slot < pp
        ob.send(sent_p, t0, ping_cands, wire.KAD_PING_CALL,
                size_b=wire.BASE_CALL_B)
        st = dataclasses.replace(
            st,
            ping_dst=st.ping_dst.at[lane_slot].set(ping_cands,
                                                   mode="drop"),
            ping_to=st.ping_to.at[lane_slot].set(
                t0 + jnp.int64(int(p.rpc_timeout * NS)), mode="drop"))

        # app timer
        # graceful-leave: hand app data to the closest sibling and stop
        # firing app tests during the grace window (apps/base.py on_leave)
        st = dataclasses.replace(st, app=app_base.leave_protocol(
            self.app, st.app, ctx, ob, ev, t0, node_idx, st.sib[0],
            st.state == READY))
        en_a = (st.state == READY) & (
            self.app.next_event(st.app) < t_end)
        now_a = jnp.maximum(self.app.next_event(st.app), t0)
        app, req = self.app.on_timer(st.app, en_a, ctx, now_a, rngs[3], ev, node_idx)
        st = dataclasses.replace(st, app=app)

        # bucket-refresh target (pump below): random key with
        # sharedPrefixLength(me, target) == bi —
        # delta = 2^(bits-1-bi) | (rand & (2^(bits-1-bi) - 1)); target=me^delta
        bi_ref = jnp.argmax(st.refresh_dirty).astype(I32)
        jbit = jnp.clip(spec.bits - 1 - bi_ref, 0, spec.bits - 1)
        top = self._pow2[jbit]
        mask = K.sub(top, K.from_int(1, spec), spec)
        rnd = K.random_keys(rngs[5], (), spec)
        target_ref = me_key ^ (top | (rnd & mask))

        # ONE batched findNode for every timer consumer: sibling refresh
        # (own key), the app lookup seed, and the bucket-refresh seed
        seeds3, sib3 = self._find_node_batch(
            ctx, st, me_key, node_idx,
            jnp.stack([me_key, req.key, target_ref]), rmax)
        res0, seed_a, seed_r = seeds3[0], seeds3[1], seeds3[2]
        sib_a = sib3[1]

        # sibling refresh start
        no_sib_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_SIB))
        slot, have = lk_mod.free_slot(st.lk)
        start_sib = sib_stale & no_sib_lk & have & (res0[0] != NO_NODE)
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_sib, slot, P_SIB, 0, me_key,
            res0[:lcfg.frontier], now_r, lcfg))
        st = dataclasses.replace(
            st, sib_used=jnp.where(start_sib, now_r, st.sib_used))
        # local responsibility → full sibling set (top-s of self ∪
        # siblings by XOR distance to the key), matching the responder-side
        # FINDNODE_RES payload so numReplica consumers get the replica set
        local = req.want & sib_a
        loc_cands = jnp.concatenate([node_idx[None], st.sib])
        loc_d = self._xor_to(ctx, loc_cands, req.key)
        (loc_s,) = K.sort_by_distance(loc_d, (loc_cands,), approx=True)[1]
        res_local = loc_s[:lcfg.frontier]
        if res_local.shape[0] < lcfg.frontier:
            res_local = jnp.concatenate([res_local, jnp.full(
                (lcfg.frontier - res_local.shape[0],), NO_NODE, I32)])
        slot, have = lk_mod.free_slot(st.lk)
        if self.rcfg is not None and hasattr(self.app, "route_policy"):
            # R/Kademlia data path: payloads the app declares routable
            # are forwarded hop-by-hop (recursiveRoutingHook at the
            # originator); the rest keep the iterative engine
            routable, inner_a, is_rpc = self.app.route_policy(req.tag)
            route_fire = (req.want & ~sib_a & routable
                          & (seed_a[0] != NO_NODE))
            vis0 = jnp.full((rmax,), NO_NODE, I32).at[0].set(node_idx)
            st = dataclasses.replace(st, rr=rt_mod.forward(
                st.rr, ob, route_fire, now_a, seed_a[0], key=req.key,
                inner=inner_a, a=req.tag, b=jnp.int32(0),
                c=ctx.measuring.astype(I32), hops=jnp.int32(1),
                stamp=now_a, size_b=jnp.int32(100), visited=vis0,
                cfg=self.rcfg))
            if hasattr(self.app, "on_route_fired"):
                st = dataclasses.replace(st, app=self.app.on_route_fired(
                    st.app, route_fire & is_rpc, now_a, req.tag))
            start_app = (req.want & ~sib_a & ~routable & have
                         & (seed_a[0] != NO_NODE))
        else:
            route_fire = jnp.bool_(False)
            start_app = req.want & ~sib_a & have & (seed_a[0] != NO_NODE)
        insta_fail = req.want & ~sib_a & ~start_app & ~route_fire
        st = dataclasses.replace(st, app=self.app.on_lookup_done(
            st.app, app_base.LookupDone(
                en=local | insta_fail, success=local, tag=req.tag,
                target=req.key,
                results=jnp.where(local, res_local, NO_NODE),
                hops=jnp.int32(0), t0=now_a),
            ctx, ob, ev, now_a, node_idx))
        st = dataclasses.replace(st, lk=lk_mod.start(
            st.lk, start_app, slot, P_APP, req.tag, req.key,
            seed_a[:lcfg.frontier], now_a, lcfg))

        # ------------------------------------------------ lookup timeouts --
        new_lk, failed_nodes, failed_prov = lk_mod.on_timeouts(
            st.lk, t_end, t0, lcfg)
        st = dataclasses.replace(st, lk=new_lk)
        # downlists (Kademlia.cc:1543-1585): tell each responder which of
        # the nodes it returned turned out dead; the receiver pings them
        # (KAD_DOWNLIST handler above) before evicting
        if p.enable_downlists:
            en_dl = (failed_nodes != NO_NODE) & (failed_prov != NO_NODE)
            ob.send(en_dl, t0, failed_prov, wire.KAD_DOWNLIST,
                    a=failed_nodes,
                    size_b=wire.BASE_CALL_B + wire.NODEHANDLE_B)
        # one batched repair for the tick's failures: lookup RPC
        # timeouts + maintenance-ping timeouts
        st = self._handle_failed(
            ctx, st, me_key, node_idx,
            jnp.concatenate([failed_nodes, ping_failed]))
        # R/Kademlia: reroute parked route messages around failed hops
        # (the failed hop was just dropped from the tables; a node that
        # became responsible meanwhile self-delivers)
        if self.rcfg is not None:
            new_rr, rt_failed, rt_retry = rt_mod.on_timeouts(
                st.rr, t_end, self.rcfg)
            st = dataclasses.replace(st, rr=new_rr)
            st = self._handle_failed(ctx, st, me_key, node_idx, rt_failed)
            nxt_q, sib_q = self._find_node_batch(
                ctx, st, me_key, node_idx, st.rr.key, rmax)
            nxt_q2, found_q = jax.vmap(
                rt_mod.pick_next_hop, in_axes=(0, 0, 0, 0, None, 0))(
                nxt_q, st.rr.visited, rt_failed,
                st.rr.visited[:, 0], node_idx, sib_q)
            nxt_fin = jnp.where(sib_q, node_idx, nxt_q2)
            ok_q = rt_retry & (sib_q | found_q)
            st = dataclasses.replace(st, rr=rt_mod.reforward_batch(
                st.rr, ob, ok_q, t0, nxt_fin, self.rcfg))
            give_up = rt_retry & ~ok_q
            st = dataclasses.replace(st, rr=rt_mod.drop_slots(
                st.rr, give_up))
            routedrop_cnt += jnp.sum(give_up.astype(I32))

        # ------------------------------------------------- completions -----
        new_lk, comp = lk_mod.take_completions(st.lk, t_end)
        st = dataclasses.replace(st, lk=new_lk)
        taken = comp["taken"]                                   # [L]
        suc_l = comp["success"] & (comp["result"] != NO_NODE)
        pur_l = comp["purpose"]
        comp_hops_ev = (comp["hops"].astype(jnp.float32),
                        taken & comp["success"])
        lksucc_cnt += jnp.sum((taken & suc_l).astype(I32))
        anyfail_cnt += jnp.sum((taken & ~suc_l).astype(I32))

        # join completion → READY.  The reference becomes READY whenever
        # the sibling table is non-empty (lookupFinished,
        # Kademlia.cc:1543) — but its join lookup is exhaustive enough
        # that the table then holds the true closest set.  A node going
        # READY off a 1-2 entry table claims siblinghood for keys it
        # does not own (isSiblingFor: not-full tables accept broadly,
        # Kademlia.cc:888) and black-holes DHT traffic, so the
        # vectorized build requires a SUCCESSFUL own-key lookup or a
        # half-full sibling table before serving.
        # At most one join lookup exists per node (no_join_lk gate above).
        enj = taken & (pur_l == P_JOIN)
        any_j = jnp.any(enj)
        n_sib_j = jnp.sum((st.sib != NO_NODE).astype(I32))
        got = any_j & (jnp.any(enj & suc_l)
                       | (n_sib_j >= min(p.s, 4)))
        joins_cnt += got.astype(I32)
        st = self._become_ready(ctx, st, got, t0, rngs[4])
        # join failed with nothing learned → retry via t_join
        st = dataclasses.replace(st, t_join=jnp.where(
            any_j & ~got, t0 + jnp.int64(int(p.join_delay * NS)),
            st.t_join))

        # bucket refresh completions → clear dirty bits (one scatter)
        enr_l = taken & (pur_l == P_REFRESH)
        rows_r = jnp.where(enr_l, jnp.clip(comp["aux"], 0,
                                           p.num_buckets - 1),
                           p.num_buckets)
        st = dataclasses.replace(
            st,
            refresh_dirty=st.refresh_dirty.at[rows_r].set(
                False, mode="drop"),
            b_used=st.b_used.at[rows_r].set(t0, mode="drop"))

        # app lookups → app completion hook (batched when the app
        # supports it; per-slot fold otherwise)
        ena_l = taken & (pur_l == P_APP)
        if hasattr(self.app, "on_lookup_done_batch"):
            st = dataclasses.replace(st, app=self.app.on_lookup_done_batch(
                st.app, app_base.LookupDone(
                    en=ena_l, success=ena_l & suc_l, tag=comp["aux"],
                    target=comp["target"], results=comp["results"],
                    hops=comp["hops"], t0=comp["t0"]),
                ctx, ob, ev, t0, node_idx))
        else:
            for li in range(lcfg.slots):
                st = dataclasses.replace(st, app=self.app.on_lookup_done(
                    st.app, app_base.LookupDone(
                        en=ena_l[li], success=ena_l[li] & suc_l[li],
                        tag=comp["aux"][li], target=comp["target"][li],
                        results=comp["results"][li], hops=comp["hops"][li],
                        t0=comp["t0"][li]),
                    ctx, ob, ev, t0, node_idx))

        # ------------------------------------------- bucket refresh pump ---
        # target/seed were computed in the batched findNode above; gate on
        # the POST-completion dirty bit so a bucket whose refresh just
        # finished is not immediately re-queried
        dirty_now = st.refresh_dirty[jnp.minimum(bi_ref, p.num_buckets - 1)]
        dirty_any = (st.state == READY) & dirty_now
        no_ref_lk = ~jnp.any(st.lk.active & (st.lk.purpose == P_REFRESH))
        slot, have = lk_mod.free_slot(st.lk)
        start_ref = dirty_any & no_ref_lk & have & (seed_r[0] != NO_NODE)
        # no candidates at all → just clear the bit
        clear_only = dirty_any & no_ref_lk & (seed_r[0] == NO_NODE)
        st = dataclasses.replace(
            st,
            refresh_dirty=jnp.where(clear_only,
                                    st.refresh_dirty.at[bi_ref].set(False),
                                    st.refresh_dirty),
            lk=lk_mod.start(st.lk, start_ref, slot, P_REFRESH, bi_ref,
                            target_ref, seed_r[:lcfg.frontier], t0, lcfg))

        # ------------------------------------------------------- pump ------
        # adaptive per-destination RPC timeouts from the RTT cache
        # (getNodeTimeout, NeighborCache.cc:802; optimizeTimeouts)
        timeout_fn = (nc_mod.adaptive_timeout_fn(st.nc, lcfg.rpc_timeout_ns)
                      if p.adaptive_timeouts else None)
        new_lk, _ = lk_mod.pump(st.lk, ob, ctx, node_idx, t0, rngs[6], lcfg,
                                num_redundant=p.redundant_nodes,
                                timeout_fn=timeout_fn,
                                prox_fn=(nc_mod.prox_fn(st.nc)
                                         if lcfg.prox_aware else None))
        st = dataclasses.replace(st, lk=new_lk)

        # Common API update() (BaseOverlay::callUpdate, BaseOverlay.cc:640
        # → BaseApp::update, BaseApp.h:223): report nodes that entered
        # the sibling set this tick so the app can re-replicate (the
        # DHT's update()-driven maintenance puts)
        if hasattr(self.app, "on_update"):
            new_in = jnp.where(
                (st.sib != NO_NODE)
                & ~jnp.any(st.sib[:, None] == old_sib[None, :], axis=1),
                st.sib, NO_NODE)
            st = dataclasses.replace(st, app=self.app.on_update(
                st.app, st.state == READY, ctx, ob, ev, t0, node_idx,
                new_in,
                sib_keys=ctx.keys[jnp.maximum(st.sib, 0)],
                sib_valid=st.sib != NO_NODE))

        # ------------------------------------------------------ events -----
        events = {
            "c:kad_joins": joins_cnt,
            "c:lookup_success": lksucc_cnt,
            "c:lookup_failed": anyfail_cnt,
            "c:route_dropped": routedrop_cnt,
            "s:lookup_hops": comp_hops_ev,
        }
        ev.finish(events, self.app.hist_map)
        return st, ob, events
